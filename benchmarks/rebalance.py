"""Load-aware placement benchmark (ADR-023): the ``rebalance`` block.

Measures the two halves of the rebalancing-brain story as NUMBERS
(``bench.py --rebalance`` -> REBALANCE_r01.json):

1. **convergence** — three real asyncio-door fleet members with a
   skewed hotspot (every probe bucket of member h0's range spent hot,
   its peers idle: measured imbalance >= 2.0x). The operator door
   (bearer-gated ``/v1/fleet/rebalance``) previews the plan with
   ``dry-run``, ``apply`` executes it over the real wire, and the block
   reports: imbalance before/after, the moves and the wall-clock apply
   window, the per-key admission oracle across the handoff (every
   pre-spent key admits EXACTLY limit tokens total — moved and kept
   alike; anything more is over-admission), client errors during the
   move (target: zero — the FleetClient self-heals over the redirect
   window), and the journal reconstruction (plan + move events under
   ONE correlation id via ``/debug/events?fleet=1``).
2. **off_pin** — rebalance machinery absent == byte-identical: the
   same workload through an in-process fleet routing stack (shared
   ManualClock) with and without the LoadSlab attached must produce
   the SAME decisions in the same order AND the same wire encoding of
   every result frame (sha256 over ``encode_result`` bytes).

Topology mirrors benchmarks/reshard.py: real server processes for the
wire half, the in-process stack for the determinism pin.
"""

from __future__ import annotations

import hashlib
import json
import os
import subprocess
import sys
import threading
import time
import urllib.request
from typing import Dict, List, Optional

import numpy as np

from benchmarks.fleet import (
    REPO,
    _fleet_config_dict,
    _free_port,
    _wait_members,
)

TOKEN = "bench-rebalance"


def _spawn(port: int, http_port: int, cfgpath: str, self_id: str,
           snap: str) -> subprocess.Popen:
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [REPO] + env.get("PYTHONPATH", "").split(os.pathsep))
    env["JAX_PLATFORMS"] = "cpu"
    env["RATELIMITER_TPU_COMPILE_CACHE"] = ""
    # limit 100 / window 600: the admission oracle needs counters that
    # outlive the whole EWMA-settle + apply + verify sequence.
    argv = [sys.executable, "-m", "ratelimiter_tpu.serving",
            "--backend", "sketch", "--limit", "100", "--window", "600",
            "--sketch-width", "8192", "--sub-windows", "6",
            "--max-batch", "4096", "--port", str(port),
            "--http-port", str(http_port),
            "--http-rebalance-token", TOKEN, "--debug-trace",
            # The automatic deployment shape; the long interval keeps
            # the measured cycle under the bench's control (the loop
            # sleeps a full interval before its first cycle, and
            # `apply` runs the IDENTICAL forced cycle).
            "--rebalance", "--rebalance-interval", "300",
            "--fleet-config", cfgpath, "--fleet-self", self_id,
            "--fleet-forward-deadline", "60",
            "--fleet-heartbeat", "0.3", "--fleet-dead-after", "2.0",
            "--snapshot-dir", snap, "--snapshot-interval", "500"]
    return subprocess.Popen(argv, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.DEVNULL, text=True)


def _verb(gateway: str, action: str) -> dict:
    base = f"{gateway}/v1/fleet/rebalance"
    if action == "status":
        url, method = base, "GET"
    else:
        url, method = f"{base}?action={action}", "POST"
    req = urllib.request.Request(
        url, method=method,
        headers={"Authorization": f"Bearer {TOKEN}"})
    with urllib.request.urlopen(req, timeout=120) as resp:
        return json.loads(resp.read().decode())


class _ErrDriver:
    """Light background loadgen counting client-visible ERRORS (not
    denials) while the move is in flight."""

    def __init__(self, fleet: dict):
        from ratelimiter_tpu.serving.client import FleetClient

        self.fc = FleetClient(fleet, call_timeout=120)
        self.decisions = 0
        self.errors: List[str] = []
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def start(self) -> None:
        self._thread.start()

    def _run(self) -> None:
        i = 0
        while not self._stop.is_set():
            i += 1
            try:
                self.fc.allow_n(f"bg:{i % 200}", 1)
                self.decisions += 1
            except Exception as exc:  # noqa: BLE001 — the measurement
                self.errors.append(repr(exc))
            time.sleep(0.005)

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=30)
        self.fc.close()


def _run_convergence(*, log) -> Dict:
    import tempfile

    from ratelimiter_tpu.fleet import FleetMap
    from ratelimiter_tpu.ops.hashing import hash_prefixed_u64
    from ratelimiter_tpu.serving.client import Client, FleetClient

    buckets, n_hosts, limit, spend = 48, 3, 100, 60
    out: Dict = {
        "harness": (f"{n_hosts} asyncio-door fleet members, {buckets} "
                    f"buckets; one probe key per bucket of h0's range "
                    f"spent {spend}/{limit} hot (peers idle); operator "
                    "dry-run -> apply through the bearer door; "
                    "admission oracle + journal reconstruction after "
                    "the wire handoff"),
    }
    with tempfile.TemporaryDirectory() as tmp:
        ports = [_free_port() for _ in range(n_hosts)]
        https = [_free_port() for _ in range(n_hosts)]
        snaps = [os.path.join(tmp, f"snap-{i}") for i in range(n_hosts)]
        fleet = _fleet_config_dict(ports, buckets, snap_dirs=snaps,
                                   http_ports=https)
        cfgpath = os.path.join(tmp, "fleet.json")
        with open(cfgpath, "w", encoding="utf-8") as f:
            json.dump(fleet, f)
        members = [_spawn(ports[i], https[i], cfgpath, f"h{i}", snaps[i])
                   for i in range(n_hosts)]
        driver: Optional[_ErrDriver] = None
        try:
            _wait_members(members)
            gw = f"http://127.0.0.1:{https[0]}"
            out["auto"] = bool(_verb(gw, "status").get("auto"))

            # One probe key per bucket of h0's range [0, 16).
            prefix = "ratelimit"  # the server's default key prefix
            per = buckets // n_hosts
            keys: Dict[int, str] = {}
            for i in range(40000):
                k = f"rb:{i}"
                bkt = int(hash_prefixed_u64([k], prefix)[0] % buckets)
                if bkt < per and bkt not in keys:
                    keys[bkt] = k
                    if len(keys) == per:
                        break
            assert len(keys) == per
            probe = [keys[b] for b in sorted(keys)]
            t0 = time.perf_counter()
            with Client(port=ports[0], timeout=120) as c0:
                for _ in range(spend):
                    rs = c0.allow_batch(probe)
                    assert all(r.allowed for r in rs)
                    time.sleep(0.01)
            out["spend_s"] = round(time.perf_counter() - t0, 3)

            # Wait for the EWMA mass + peer liveness to settle into a
            # plan (each dry-run poll also triggers the load gather).
            t0 = time.perf_counter()
            plan = None
            deadline = time.time() + 90
            while time.time() < deadline:
                got = _verb(gw, "dry-run")
                if got.get("ok") and got["plan"]["moves"]:
                    plan = got["plan"]
                    break
                time.sleep(0.5)
            assert plan is not None, "dry-run never produced a plan"
            out["settle_s"] = round(time.perf_counter() - t0, 3)
            out["imbalance_before"] = plan["imbalance_before"]

            driver = _ErrDriver(fleet)
            driver.start()
            time.sleep(0.3)
            t0 = time.perf_counter()
            applied = _verb(gw, "apply")
            apply_s = time.perf_counter() - t0
            time.sleep(0.5)
            driver.stop()
            assert applied.get("ok"), applied
            moves = applied["plan"]["moves"][:applied["executed"]]
            out["apply"] = {
                "executed": applied["executed"],
                "planned": len(applied["plan"]["moves"]),
                "moves": [{"range": mv["range"], "from": mv["from"],
                           "to": mv["to"]} for mv in moves],
                "wall_s": round(apply_s, 3),
                "imbalance_projected":
                    applied["plan"]["imbalance_projected"],
                "plan_id": applied["plan"]["plan_id"],
            }
            out["client_errors_during_move"] = len(driver.errors)
            out["client_decisions_during_move"] = driver.decisions
            if driver.errors:
                out["first_error"] = driver.errors[0]

            # The new map really owns the moved ranges elsewhere.
            with Client(port=ports[1], timeout=120) as c1:
                m_now = FleetMap.from_dict(c1.fleet_map())
            out["epoch_final"] = m_now.epoch
            for mv in moves:
                lo, hi = mv["range"]
                assert (m_now.owner_table[lo:hi]
                        == m_now.ordinal(mv["to"])).all()

            # Measured imbalance AFTER: the same EWMA view re-summed
            # over the flipped ownership.
            after = _verb(gw, "dry-run")
            out["imbalance_after"] = (
                after["plan"]["imbalance_before"]
                if after.get("ok") and after.get("plan") else None)

            # Admission oracle: every pre-spent probe key — moved and
            # kept — admits exactly limit-spend more, then denies.
            moved_rs = [tuple(mv["range"]) for mv in moves]
            fc = FleetClient(fleet, call_timeout=120)
            oracle_errors = 0
            over = under = exact = 0
            try:
                for bkt, k in sorted(keys.items()):
                    more = 0
                    for _ in range(limit - spend + 5):
                        try:
                            more += bool(fc.allow_n(k, 1).allowed)
                        except Exception:  # noqa: BLE001 — count it
                            oracle_errors += 1
                    if more == limit - spend:
                        exact += 1
                    elif more > limit - spend:
                        over += 1
                    else:
                        under += 1
            finally:
                fc.close()
            moved_buckets = sum(hi - lo for lo, hi in moved_rs)
            out["oracle"] = {
                "keys": len(keys),
                "moved_buckets": moved_buckets,
                "exact": exact,
                "over_admitted_keys": over,
                "under_admitted_keys": under,
                "client_errors": oracle_errors,
            }

            # Journal reconstruction: plan + move events under ONE
            # correlation id through the fleet-merged door.
            with urllib.request.urlopen(
                    f"{gw}/debug/events?fleet=1&category=placement"
                    f"&limit=128", timeout=60) as r:
                evs = json.loads(r.read())["events"]
            plan_evs = [e for e in evs if e["action"] == "plan"]
            move_evs = [e for e in evs if e["action"] == "move"]
            corr = plan_evs[-1]["corr"] if plan_evs else None
            out["journal"] = {
                "plan_events": len(plan_evs),
                "move_events": len(move_evs),
                "corr": corr,
                "one_corr": bool(
                    corr and move_evs
                    and all(e["corr"] == corr for e in move_evs)),
            }
            out["pass"] = bool(
                out["imbalance_before"] >= 2.0
                and out["apply"]["executed"] >= 1
                and (out["imbalance_after"] or 99.0) <= 1.3
                and out["client_errors_during_move"] == 0
                and over == 0 and oracle_errors == 0
                and out["journal"]["one_corr"])
            log(f"rebalance convergence: imbalance "
                f"{out['imbalance_before']:.2f} -> "
                f"{out['imbalance_after']} "
                f"({out['apply']['executed']} moves, "
                f"{out['apply']['wall_s']}s), oracle exact={exact}/"
                f"{len(keys)} over={over}, errors="
                f"{out['client_errors_during_move']}+{oracle_errors}, "
                f"one_corr={out['journal']['one_corr']}")
        finally:
            if driver is not None and driver._thread.is_alive():
                driver.stop()
            for pr in members:
                if pr.poll() is None:
                    pr.terminate()
            for pr in members:
                try:
                    pr.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    pr.kill()
    return out


def _run_off_pin(*, n_requests: int, log) -> Dict:
    """No rebalance machinery == byte-identical decisions AND wire
    frames, pinned over the in-process routing stack."""
    from ratelimiter_tpu import Algorithm, Config, SketchParams
    from ratelimiter_tpu.algorithms.sketch import SketchLimiter
    from ratelimiter_tpu.core.clock import ManualClock
    from ratelimiter_tpu.fleet import FleetCore, FleetForwarder, FleetMap
    from ratelimiter_tpu.fleet.config import FleetHost
    from ratelimiter_tpu.observability.metrics import Registry
    from ratelimiter_tpu.placement import LoadSlab
    from ratelimiter_tpu.serving import protocol

    def run(attach: bool):
        clock = ManualClock(1000.0)
        cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=50,
                     window=60.0,
                     sketch=SketchParams(depth=2, width=1024,
                                         sub_windows=6))
        lim = SketchLimiter(cfg, clock)
        m = FleetMap(buckets=48, hosts=(
            FleetHost(id="solo", host="127.0.0.1", port=1,
                      ranges=((0, 48),)),))
        m.validate()
        core = FleetCore(m, "solo", prefix=lim.config.prefix,
                         registry=Registry())
        if attach:
            core.load_slab = LoadSlab(48)
        fwd = FleetForwarder(lim, core)
        rng = np.random.default_rng(7)
        wire = hashlib.sha256()
        decisions = []
        try:
            for i in range(n_requests):
                k = f"pin:{int(rng.integers(0, 64))}"
                r = fwd.allow_n(k, int(rng.integers(1, 3)))
                decisions.append((k, bool(r.allowed), int(r.remaining),
                                  int(r.limit)))
                wire.update(protocol.encode_result(i & 0xFFFF, r))
                if i % 97 == 0:
                    clock.advance(0.5)
        finally:
            fwd.close()
            lim.close()
        return decisions, wire.hexdigest()

    plain, wire_plain = run(attach=False)
    slabbed, wire_slabbed = run(attach=True)
    identical = plain == slabbed and wire_plain == wire_slabbed
    log(f"rebalance off-pin: decisions_identical={plain == slabbed} "
        f"wire_identical={wire_plain == wire_slabbed} over "
        f"{n_requests} ops")
    return {"requests": n_requests,
            "decisions_identical": plain == slabbed,
            "wire_sha256": wire_plain,
            "wire_identical": wire_plain == wire_slabbed,
            "pass": identical}


def run_rebalance(*, seconds: float = 4.0, log=print) -> Dict:
    """The REBALANCE_r01 block."""
    del seconds  # the phases are event-driven, not time-driven
    return {
        "convergence": _run_convergence(log=log),
        "off_pin": _run_off_pin(n_requests=6000, log=log),
    }
