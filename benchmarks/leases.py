"""Client-embedded quota lease bench (ADR-022) — LEASE_r01.json.

Four phases, each answering one acceptance question:

1. **rate** — client-observed decision rate on lease-eligible hot-key
   traffic, leased vs wire, against ONE real server process through
   the real asyncio door (the loadgen's ``leased`` mode,
   ``benchmarks.e2e._drive_scalar``). The wire side is the honest
   control: same client, same keys, pipelined scalar RTTs. Bar: ≥ 5×.
2. **storm** — the never-over-admit oracle through a seeded revocation
   storm: local spends, wire decisions, revocations with lost pushes,
   kill -9-flavoured abandons, TTL expiries — then every key is
   exhausted and client-observed admissions are checked against the
   frozen-window limit BIT-EXACTLY. This is the structural claim
   (debit-upfront) measured, not argued.
3. **accuracy** — the ADR-016 observatory prices the lease tier: the
   same zipf workload through an undersized sketch with the shadow
   oracle auditing 1/1, leases off vs on (leased spend reaches the
   oracle through the manager's renew/return mirror). Reported as
   false-deny rates with Wilson 95% bounds and their delta.
4. **off_pin** — leases disabled = byte-identical decision stream
   (an idle LeaseManager attached vs a plain limiter, full Result
   equality on a seeded workload).

Published via ``bench.py --leases``.
"""

from __future__ import annotations

import asyncio
from typing import Dict, List, Optional

import numpy as np

from benchmarks.e2e import _drive_scalar, _spawn_server

#: Phase-1 server shape: exact backend (bit-exact ledger), a window
#: too big to refill mid-run, budgets sized so renew top-ups keep the
#: local counters full under a multi-worker spend rate.
_RATE_SERVER_ARGS = [
    "--limit", "2000000000", "--window", "600",
    "--leases", "--lease-ttl", "5",
    "--lease-budget", "2000000", "--lease-max", "4096",
]


class _FakeClock:
    def __init__(self, t: float = 100.0):
        self.t = float(t)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += float(dt)


class _Res:
    """Scalar Result shim for the audit tap (one decision per offer)."""

    __slots__ = ("allowed", "fail_open", "fail_open_slices")

    def __init__(self, allowed: bool):
        self.allowed = np.asarray([bool(allowed)])
        self.fail_open = False
        self.fail_open_slices = None

    def __len__(self) -> int:
        return 1


def _mk_limiter(limit: int, *, backend: str = "exact",
                sketch_width: Optional[int] = None):
    from ratelimiter_tpu import (
        Algorithm,
        Config,
        ManualClock,
        SketchParams,
        create_limiter,
    )

    kw = {}
    if sketch_width is not None:
        kw["sketch"] = SketchParams(depth=2, width=sketch_width,
                                    sub_windows=6,
                                    conservative_update=True)
    cfg = Config(algorithm=Algorithm.TPU_SKETCH, limit=limit,
                 window=60.0, key_prefix="", **kw)
    return create_limiter(cfg, backend=backend,
                          clock=ManualClock(1_700_000_000.0)), cfg


# ------------------------------------------------------------- phase 1

def _run_rate(*, seconds: float, warmup: float, conns: int,
              inflight: int, hot_keys: int, log) -> Dict:
    proc, port = _spawn_server("exact", platform="cpu",
                               extra_args=_RATE_SERVER_ARGS)
    try:
        wire = asyncio.run(_drive_scalar(
            port, seconds=seconds, conns=conns, inflight=inflight,
            n_keys=hot_keys, warmup=warmup, leased=False))
        log(f"leases rate: wire {wire['decisions_per_sec']:,.0f}/s")
        leased = asyncio.run(_drive_scalar(
            port, seconds=seconds, conns=conns, inflight=inflight,
            n_keys=hot_keys, warmup=warmup, leased=True,
            lease_kw={"want": 2_000_000}))
        log(f"leases rate: leased {leased['decisions_per_sec']:,.0f}/s "
            f"(local fraction {leased['local_fraction']})")
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=30)
        except Exception:  # noqa: BLE001
            proc.kill()
    speedup = (leased["decisions_per_sec"] / wire["decisions_per_sec"]
               if wire["decisions_per_sec"] else None)
    return {
        "harness": ("one exact-backend serving process, asyncio door; "
                    "closed-loop scalar allow() on a zipf-hot keyset, "
                    f"{conns} conns x {max(1, inflight)} workers; "
                    "wire control vs enable_leases() on the same "
                    "client (loadgen leased mode)"),
        "wire": wire,
        "leased": leased,
        "speedup": round(speedup, 2) if speedup else None,
        "bar": 5.0,
        "pass": bool(speedup and speedup >= 5.0),
    }


# ------------------------------------------------------------- phase 2

def _run_storm(*, steps: int, log) -> Dict:
    """Seeded storm; the client-observed admission count per key must
    never exceed the frozen-window limit, bit-exactly."""
    import random

    from ratelimiter_tpu.leases import LeaseCache, LeaseManager
    from ratelimiter_tpu.observability import Registry
    from ratelimiter_tpu.serving import protocol as p

    LIMIT, BUDGET = 500, 48
    lim, _cfg = _mk_limiter(LIMIT)
    clk = _FakeClock()
    mgr = LeaseManager(lim, ttl=2.0, default_budget=BUDGET,
                       registry=Registry(), clock=clk)
    cache = LeaseCache(hot_after=2, hot_window=1e9, low_water=0.25,
                       registry=Registry(), clock=clk)
    rng = random.Random(1234)
    keys = [f"storm:{i}" for i in range(3)]
    admitted = {k: 0 for k in keys}
    lost_pushes = delivered_pushes = revocations = abandons = 0

    def push(frame: bytes) -> None:
        nonlocal lost_pushes, delivered_pushes
        if rng.random() < 0.5:           # chaos: the push never lands
            lost_pushes += 1
            return
        delivered_pushes += 1
        _reason, _epoch, ids = p.parse_lease_revoke(
            frame[p.HEADER_SIZE:])
        cache.invalidate_ids(ids)

    def pump() -> None:
        for act in cache.actions():
            if act[0] == "grant":
                _, key, want = act
                cache.on_grant(key, *mgr.grant(cache.client_id, key,
                                               want, push=push))
            else:
                _, key, lease_id, delta, top_up = act
                granted, _lid, top, ttl_s, limit, epoch = mgr.renew(
                    cache.client_id, lease_id, key, delta, top_up)
                cache.on_renew(lease_id, granted, top, ttl_s, limit,
                               epoch)

    for step in range(steps):
        key = rng.choice(keys)
        r = cache.try_acquire(key, 1)
        if r is not None:
            admitted[key] += 1           # local, memory-speed
        else:
            res = lim.allow_n(key, 1)    # wire path
            cache.note_wire(key)
            if res.allowed:
                admitted[key] += 1
        if step % 5 == 4:
            pump()
        if rng.random() < 0.01:          # revocation storm tick
            revocations += 1
            mgr.revoke_key(rng.choice(keys), p.LEASE_REV_MANUAL)
        if rng.random() < 0.005:         # kill -9-flavoured abandon:
            abandons += 1                # local leases dropped, no
            cache.invalidate_ids([])     # return frames ever sent
        if rng.random() < 0.02:
            clk.advance(rng.uniform(0.1, 1.5))

    # Exhaust every key on the wire: the TOTAL a client observed can
    # never pass the limit — and must end exactly exhausted.
    for key in keys:
        guard = 0
        while lim.allow_n(key, 1).allowed:
            admitted[key] += 1
            guard += 1
            assert guard <= LIMIT, "runaway exhaust loop"
        assert not lim.allow_n(key, 1).allowed
    worst = max(admitted.values())
    holds = all(v <= LIMIT for v in admitted.values())
    log(f"leases storm: worst admitted {worst}/{LIMIT}, "
        f"{revocations} revocations ({lost_pushes} pushes lost), "
        f"{abandons} abandons -> bound_holds={holds}")
    mgr.close()
    lim.close()
    return {
        "harness": (f"{steps}-step seeded storm, 3 keys, frozen "
                    "window: local spends + wire decisions + "
                    "revocations with 50% lost pushes + abandoned "
                    "holders + TTL expiry, then full wire exhaust"),
        "limit": LIMIT,
        "admitted_per_key": admitted,
        "worst_admitted": worst,
        "revocations": revocations,
        "pushes_lost": lost_pushes,
        "pushes_delivered": delivered_pushes,
        "abandons": abandons,
        "never_over_admit": holds,
        "pass": holds,
    }


# ------------------------------------------------------------- phase 3

def _run_accuracy(*, n_requests: int, n_keys: int, log) -> Dict:
    """The observatory prices leasing: same seeded zipf workload, same
    undersized sketch geometry, audit sample 1/1 — leases off vs on."""
    from ratelimiter_tpu.leases import LeaseCache, LeaseManager
    from ratelimiter_tpu.observability import Registry, audit

    LIMIT = 60
    rng = np.random.default_rng(7)
    ids = rng.zipf(1.2, size=n_requests) % n_keys

    def run_side(leased: bool) -> Dict:
        lim, cfg = _mk_limiter(LIMIT, backend="sketch",
                               sketch_width=256)
        aud = audit.enable(cfg, sample=1, start=False,
                           registry=Registry())
        mgr = cache = None
        clk = _FakeClock()
        if leased:
            mgr = LeaseManager(lim, ttl=1e6, default_budget=LIMIT // 3,
                               registry=Registry(), clock=clk)
            cache = LeaseCache(hot_after=4, hot_window=1e9,
                               low_water=0.25, registry=Registry(),
                               clock=clk)
        try:
            for step, i in enumerate(ids):
                key = f"acc:{i}"
                if cache is not None:
                    r = cache.try_acquire(key, 1)
                    if r is not None:
                        continue        # mirrored at renew/return
                res = lim.allow_n(key, 1)
                aud.offer_keys([key], np.asarray([1], dtype=np.int64),
                               clk(), _Res(res.allowed))
                if step % 256 == 255:
                    # Keep the tap's bounded queue drained (no worker
                    # thread in this harness) so the sample is the
                    # workload, not the queue capacity.
                    aud.process_pending()
                if cache is not None:
                    cache.note_wire(key)
                    if step % 16 == 15:
                        for act in cache.actions():
                            if act[0] == "grant":
                                _, k, want = act
                                cache.on_grant(k, *mgr.grant(
                                    cache.client_id, k, want))
                            else:
                                _, k, lid, delta, top = act
                                ok, _l, tu, ts, lm, ep = mgr.renew(
                                    cache.client_id, lid, k, delta,
                                    top)
                                cache.on_renew(lid, ok, tu, ts, lm,
                                               ep)
            if cache is not None:
                for _, k, lid, delta in cache.drain():
                    mgr.release(cache.client_id, lid, k, delta)
            aud.process_pending()
            st = aud.status()
            return {
                "samples": st["samples"],
                "false_deny_rate": st["false_deny_rate"],
                "false_deny_wilson95": st["false_deny_wilson95"],
                "false_allow_rate": st["false_allow_rate"],
            }
        finally:
            audit.disable()
            if mgr is not None:
                mgr.close()
            lim.close()

    off = run_side(False)
    on = run_side(True)
    delta = round(on["false_deny_rate"] - off["false_deny_rate"], 8)
    log(f"leases accuracy: false-deny off={off['false_deny_rate']} "
        f"on={on['false_deny_rate']} delta={delta}")
    return {
        "harness": (f"{n_requests} zipf(1.2) decisions over {n_keys} "
                    "keys, undersized d=2 w=256 sketch, shadow oracle "
                    "auditing 1/1; leased side mirrors spend through "
                    "the manager's renew/return reconcile"),
        "leases_off": off,
        "leases_on": on,
        "false_deny_delta": delta,
    }


# ------------------------------------------------------------- phase 4

def _run_off_pin(*, n_requests: int, log) -> Dict:
    """Leases not enabled == byte-identical decisions."""
    from ratelimiter_tpu.leases import LeaseManager
    from ratelimiter_tpu.observability import Registry

    rng = np.random.default_rng(11)
    ops: List[tuple] = [(f"pin:{rng.integers(0, 40)}",
                         int(rng.integers(1, 4)))
                        for _ in range(n_requests)]
    lim_plain, _ = _mk_limiter(200)
    lim_mgr, _ = _mk_limiter(200)
    mgr = LeaseManager(lim_mgr, registry=Registry())  # attached, idle
    identical = True
    for key, n in ops:
        a = lim_plain.allow_n(key, n)
        b = lim_mgr.allow_n(key, n)
        if (a.allowed != b.allowed or a.remaining != b.remaining
                or a.limit != b.limit):
            identical = False
            break
    mgr.close()
    lim_plain.close()
    lim_mgr.close()
    log(f"leases off-pin: identical={identical} over "
        f"{n_requests} ops")
    return {"requests": n_requests, "identical": identical,
            "pass": identical}


def run_leases(*, seconds: float = 4.0, warmup: float = 1.5,
               conns: int = 4, inflight: int = 8, hot_keys: int = 16,
               storm_steps: int = 4000, log=print) -> Dict:
    """The LEASE_r01 block."""
    out: Dict = {
        "rate": _run_rate(seconds=seconds, warmup=warmup, conns=conns,
                          inflight=inflight, hot_keys=hot_keys,
                          log=log),
        "storm": _run_storm(steps=storm_steps, log=log),
        "accuracy": _run_accuracy(n_requests=12_000, n_keys=600,
                                  log=log),
        "off_pin": _run_off_pin(n_requests=600, log=log),
    }
    out["pass"] = bool(out["rate"]["pass"] and out["storm"]["pass"]
                       and out["off_pin"]["pass"])
    return out
