"""All-observability-on fleet retention bench (ADR-021).

The control tower's cost claim — flight recorder + audit + hh analytics
+ event journal + the fan-out surfaces, ALL on at once, on a 2-host
fleet under mixed forwarded traffic — measured exactly the way ADR-016
measured audit overhead: INTERLEAVED off/on pairs (the box baseline
drifts percent-scale over minutes, so a sequential A/B would measure
the drift, not the feature), best paired ratio reported as the
headline retention.

Off side: every observability subsystem disabled incl. the event
journal (``--no-event-journal``) — byte-identical hot path. On side:
``--flight-recorder`` (every forward window then ALSO carries a wire
trace id + host-side links), ``--audit`` 1/64, ``--hh-slots``, the
journal, and the debug/tower HTTP surfaces mounted and SCRAPED
mid-measurement (one /metrics + one /v1/fleet/status + one
/debug/trace?fleet=1 per run) — observing while observed, the honest
operating point.

Published as OBS_r01.json via ``bench.py --fleet-obs``; acceptance bar
retention >= 0.97.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import urllib.request
from typing import Dict, List

from benchmarks.fleet import (
    _fleet_config_dict,
    _free_port,
    _run_traffic,
    _spawn_member,
    _wait_members,
)

#: All-on observability flags (per member). The event journal is on by
#: default; the OFF side passes --no-event-journal instead.
_ON_FLAGS = ("--flight-recorder", "--audit", "--audit-sample", "64",
             "--hh-slots", "64", "--debug-token", "tok")
_OFF_FLAGS = ("--no-event-journal",)


def _scrape_surfaces(https: List[int], log) -> Dict:
    """One mid-run pull of the tower surfaces (the realistic operating
    point: a scraper and an operator exist). Returns summary numbers
    for the JSON."""
    out: Dict = {}
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{https[0]}/metrics",
                timeout=10) as r:
            out["metrics_bytes"] = len(r.read())
        with urllib.request.urlopen(
                f"http://127.0.0.1:{https[0]}/v1/fleet/status",
                timeout=10) as r:
            st = json.loads(r.read())
        out["fleet_status_reachable"] = st.get("reachable")
        out["fleet_status_audit_samples"] = (st.get("audit") or {}).get(
            "samples")
        req = urllib.request.Request(
            f"http://127.0.0.1:{https[0]}/debug/trace?fleet=1")
        req.add_header("Authorization", "Bearer tok")
        with urllib.request.urlopen(req, timeout=10) as r:
            tr = json.loads(r.read())
        out["stitched_spans"] = sum(
            1 for e in tr.get("traceEvents", ()) if e.get("ph") == "X")
        out["stitched_hosts_aligned"] = sum(
            1 for h in tr.get("otherData", {}).get("hosts", {}).values()
            if h.get("aligned"))
    except Exception as exc:  # noqa: BLE001 — the bench must finish
        out["scrape_error"] = str(exc)
        log(f"fleet-obs: mid-run surface scrape failed: {exc}")
    return out


def _one_run(obs_on: bool, tmp: str, tag: str, *, seconds: float,
             warmup: float, conns: int, frame: int, depth: int,
             log) -> Dict:
    ports = [_free_port(), _free_port()]
    https = [_free_port(), _free_port()] if obs_on else None
    fleet = _fleet_config_dict(ports, 32, http_ports=https)
    cfgpath = os.path.join(tmp, f"fleet-obs-{tag}.json")
    with open(cfgpath, "w", encoding="utf-8") as f:
        json.dump(fleet, f)
    members = []
    for i, port in enumerate(ports):
        extra = list(_ON_FLAGS if obs_on else _OFF_FLAGS)
        if obs_on:
            extra += ["--http-port", str(https[i])]
        members.append(_spawn_member(port, cfgpath, f"h{i}",
                                     extra=tuple(extra)))
    try:
        _wait_members(members)
        scrape: Dict = {}
        if obs_on:
            # Pull the tower surfaces once, mid-measurement, from a
            # side thread (an operator reading dashboards during the
            # run — the honest cost point).
            timer = threading.Timer(
                warmup + seconds / 2,
                lambda: scrape.update(_scrape_surfaces(https, log)))
            timer.daemon = True
            timer.start()
        row = _run_traffic(fleet, ports, spread=2, seconds=seconds,
                           warmup=warmup, conns=conns, frame=frame,
                           depth=depth, log=log)
        if obs_on:
            timer.join(timeout=30)
            row["surfaces"] = scrape
        return row
    finally:
        for m in members:
            m.terminate()
        for m in members:
            try:
                m.wait(timeout=30)
            except Exception:  # noqa: BLE001
                m.kill()


def run_fleet_obs(*, pairs: int = 3, seconds: float = 4.0,
                  warmup: float = 2.0, conns: int = 4,
                  frame: int = 2048, depth: int = 12,
                  log=print) -> Dict:
    """The OBS_r01 block: ``pairs`` interleaved off/on rounds of 2-host
    spread=2 mixed traffic (≈0.5 forwarded fraction — every frame
    exercises the forward lanes both ways), per-pair retention ratios,
    best pair as the headline."""
    out: Dict = {
        "harness": ("2-host asyncio-door fleet, spread=2 mixed raw-id "
                    "loadgen (≈0.5 forwarded), INTERLEAVED off/on "
                    "pairs, best paired ratio — the ADR-016 A/B "
                    "method"),
        "observability_on": list(_ON_FLAGS) + ["event journal (default "
                                               "on)", "http surfaces "
                                               "scraped mid-run"],
        "pairs": [],
    }
    with tempfile.TemporaryDirectory() as tmp:
        for k in range(pairs):
            off = _one_run(False, tmp, f"off{k}", seconds=seconds,
                           warmup=warmup, conns=conns, frame=frame,
                           depth=depth, log=log)
            on = _one_run(True, tmp, f"on{k}", seconds=seconds,
                          warmup=warmup, conns=conns, frame=frame,
                          depth=depth, log=log)
            ratio = (on["decisions_per_sec"] / off["decisions_per_sec"]
                     if off["decisions_per_sec"] else None)
            out["pairs"].append({
                "off_decisions_per_sec": off["decisions_per_sec"],
                "on_decisions_per_sec": on["decisions_per_sec"],
                "off_p99_ms": off["frame_p99_ms"],
                "on_p99_ms": on["frame_p99_ms"],
                "retention": round(ratio, 4) if ratio else None,
                "on_surfaces": on.get("surfaces", {}),
            })
            log(f"fleet-obs pair {k}: off="
                f"{off['decisions_per_sec']:.0f}/s on="
                f"{on['decisions_per_sec']:.0f}/s retention="
                f"{ratio:.3f}")
        ratios = [p["retention"] for p in out["pairs"]
                  if p["retention"] is not None]
        out["retention_best_pair"] = max(ratios) if ratios else None
        out["retention_median_pair"] = (
            sorted(ratios)[len(ratios) // 2] if ratios else None)
        out["bar"] = 0.97
        out["pass"] = bool(ratios and max(ratios) >= 0.97)
    return out
