"""The five BASELINE.json benchmark configs, at their literal parameters.

1. SlidingWindowCounter, single key 'user:1', limit=100/min, in-memory
   (CPU ref) — the minimum end-to-end slice, scalar-path latency.
2. TokenBucket + FixedWindow + SlidingWindow, 10k uniform keys,
   single-process CMS vs exact — per-algorithm accuracy + throughput.
3. 1M-key Zipf(1.1) trace, batch=4096, CMS d=4 w=65536, single chip —
   the north-star config AT ITS LITERAL GEOMETRY (VERDICT r2 weak-5
   benched a 16x-wider sketch; this one does not), accuracy measured at
   >= 1 full window of steady state, plus the 4096-ingest serving shape
   and the mega-batch saturation shape.
4. 60x1s sub-windows under bursty on/off load — decay/rotate correctness
   and accuracy through bursts.
5. Multi-tenant 8M-key trace over an 8-device mesh with ICI psum merge —
   run on the CPU virtual mesh in this environment (correctness + relative
   collective cost; NOT a TPU performance claim — labeled as such).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import Dict, List

import numpy as np

from ratelimiter_tpu import Algorithm, Config, ManualClock, SketchParams, create_limiter

T0 = 1_700_000_000.0
T0_US = int(T0) * 1_000_000


def _sync(x):
    np.asarray(x.ravel()[:1] if hasattr(x, "ravel") else x)


# ------------------------------------------------------------- config 1

def config1(log=print) -> Dict:
    """SlidingWindow, one key, limit=100/min, exact in-memory backend."""
    cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=100, window=60.0)
    clock = ManualClock(T0)
    lim = create_limiter(cfg, backend="exact", clock=clock)
    # Correctness of the slice: 100 allowed, then denied, then window rolls.
    allowed = sum(lim.allow("user:1").allowed for _ in range(150))
    assert allowed == 100
    clock.advance(120.0)
    assert lim.allow("user:1").allowed
    # Scalar throughput.
    t0 = time.perf_counter()
    iters = 50_000
    for _ in range(iters):
        lim.allow("user:1")
    dt = time.perf_counter() - t0
    lim.close()
    log("config1 done")
    return {
        "config": 1,
        "setup": "sliding_window single key limit=100/60s exact backend",
        "correct": True,
        "scalar_decisions_per_sec": round(iters / dt, 1),
        "us_per_decision": round(dt / iters * 1e6, 2),
    }


# ------------------------------------------------------------- config 2

def config2(quick: bool = False, log=print) -> List[Dict]:
    """TB + FW + SW at 10k uniform keys: sketch vs exact accuracy and
    batched throughput (host-path, string keys)."""
    out = []
    n_keys, batch = (2000, 1024) if quick else (10_000, 4096)
    steps = 8 if quick else 40
    for algo in (Algorithm.TOKEN_BUCKET, Algorithm.FIXED_WINDOW,
                 Algorithm.SLIDING_WINDOW):
        cfg = Config(algorithm=algo, limit=20, window=10.0,
                     sketch=SketchParams(depth=4, width=65536))
        sk = create_limiter(cfg, backend="sketch", clock=ManualClock(T0))
        ex = create_limiter(cfg, backend="exact", clock=ManualClock(T0))
        rng = np.random.default_rng(3)
        agree = denies_sk = denies_ex = false_deny = false_allow = 0
        t_sk = 0.0
        now = T0
        for s in range(steps):
            now += 0.25
            keys = [f"u:{i}" for i in rng.integers(0, n_keys, size=batch)]
            t0 = time.perf_counter()
            osk = sk.allow_batch(keys, now=now)
            t_sk += time.perf_counter() - t0
            oex = ex.allow_batch(keys, now=now)
            a, b = osk.allowed, oex.allowed
            agree += int((a == b).sum())
            false_deny += int((~a & b).sum())
            false_allow += int((a & ~b).sum())
            denies_sk += int((~a).sum())
            denies_ex += int((~b).sum())
        total = steps * batch
        sk.close()
        ex.close()
        log(f"config2 {algo} done")
        out.append({
            "config": 2,
            "algorithm": str(algo),
            "keys": n_keys,
            "decisions": total,
            "sketch_decisions_per_sec": round(total / t_sk, 1),
            "throughput_note": (
                "host string-key path, one synchronous dispatch per batch "
                "— dispatch-RTT-paced in this environment; accuracy is the "
                "metric here, config 3 measures throughput shapes"),
            "false_deny_rate": round(false_deny / max(total - denies_ex, 1), 6),
            "false_allow_rate": round(false_allow / max(denies_ex, 1), 6),
            "deny_rate_exact": round(denies_ex / total, 4),
        })
    return out


# ------------------------------------------------------------- config 3

def config3(quick: bool = False, log=print) -> Dict:
    """North-star config at its LITERAL geometry: d=4 w=65536, 1M-key
    Zipf(1.1), batch 4096; accuracy at >= 1 window of steady state."""
    import jax
    import jax.numpy as jnp

    from ratelimiter_tpu.evaluation.loadgen import build_bench_chunk
    from ratelimiter_tpu.evaluation.oracle_device import (
        build_eval_chunk,
        build_oracle_rollover,
        init_oracle_state,
    )
    from ratelimiter_tpu.ops import sketch_kernels

    on_accel = jax.devices()[0].platform != "cpu"
    n_keys = 1_000_000 if on_accel else 50_000
    B = (1 << 22) if on_accel else (1 << 15)
    ingest = 4096
    cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=100, window=60.0,
                 max_batch_admission_iters=1,
                 sketch=SketchParams(depth=4, width=65536, sub_windows=60,
                                     conservative_update=True))
    _, sub_us, _, _, _ = sketch_kernels.sketch_geometry(cfg)
    _, _, roll = sketch_kernels.build_steps(cfg)

    # Saturation throughput at the literal geometry.
    chunk = build_bench_chunk(cfg, B, n_keys, 1.1)
    state = roll(sketch_kernels.init_state(cfg), jnp.int64(T0_US // sub_us))
    state, packed, _ = chunk(state, jnp.uint64(0), jnp.int64(T0_US))
    _sync(packed)
    t0 = time.perf_counter()
    n_meas = 2 if quick else 6
    for i in range(1, 1 + n_meas):
        state, packed, _ = chunk(state, jnp.uint64(i * B), jnp.int64(T0_US))
    _sync(packed)
    rps = n_meas * B / (time.perf_counter() - t0)
    del state, packed
    log(f"config3 saturation {rps / 1e6:.1f}M/s")

    # Serving shape: 4096-ingest batches via the lax.scan runner, at two
    # coalescing depths. T=64 is the spec cadence. Two rates per shape:
    # * launch-paced (K=6 chained dispatches, r3-comparable): includes
    #   the per-sync dev-tunnel round trip spread over 6 dispatches —
    #   an environment artifact (production-attached chips pay ~0.1 ms);
    # * steady-state: K sized so the launch share is <10%, i.e. the rate
    #   a continuously pipelined server sustains on the device itself
    #   (ADR-004 addendum: the step is latency-bound at ~266 us; the
    #   measured launch RTT is reported alongside).
    from ratelimiter_tpu.ops.hashing import split_hash, splitmix64

    # Measure the launch round trip once (tiny dispatch + sync).
    _sync((jnp.zeros(8) + 1))
    t0 = time.perf_counter()
    _sync((jnp.zeros(8) + 2))
    rtt_s = time.perf_counter() - t0

    scan = sketch_kernels.build_scan(cfg)
    rng = np.random.default_rng(0)
    serving = {"launch_rtt_ms": round(rtt_s * 1e3, 1)}
    for steps, dt_us in ((64, 400), (512, 50)):
        if quick and steps > 64:
            continue
        ids = rng.zipf(1.1, size=(steps, ingest)).astype(np.uint64)
        h1, h2 = split_hash(splitmix64(ids.reshape(-1)), cfg.sketch.seed)
        h1s = jnp.asarray(h1.reshape(steps, ingest))
        h2s = jnp.asarray(h2.reshape(steps, ingest))
        ns = jnp.ones((steps, ingest), jnp.int32)
        state = roll(sketch_kernels.init_state(cfg), jnp.int64(T0_US // sub_us))
        state, masks, _ = scan(state, h1s, h2s, ns, jnp.int64(T0_US),
                               jnp.int64(dt_us))
        _sync(masks)
        shape_out = {}
        for label, K in (("launch_paced_K6", 2 if quick else 6),
                         ("steady_state", 4 if quick else 48)):
            t0 = time.perf_counter()
            for i in range(K):
                state, masks, _ = scan(
                    state, h1s, h2s, ns,
                    jnp.int64(T0_US + (i + 1) * steps * dt_us),
                    jnp.int64(dt_us))
            _sync(masks)
            scan_s = (time.perf_counter() - t0) / K
            if label == "steady_state":
                # Remove the single sync's amortized share entirely: the
                # remainder is pure device pipeline time.
                scan_s = max(scan_s - rtt_s / K, 1e-9)
            shape_out[label] = {
                "decisions_per_sec": round(steps * ingest / scan_s, 1),
                "dispatch_ms": round(scan_s * 1e3, 2),
                "step_latency_us": round(scan_s / steps * 1e6, 1),
            }
        serving[f"T{steps}"] = shape_out
        del state, masks
        log(f"config3 serving shape T={steps}: "
            f"launch-paced {shape_out['launch_paced_K6']['decisions_per_sec'] / 1e6:.2f}M/s, "
            f"steady {shape_out['steady_state']['decisions_per_sec'] / 1e6:.2f}M/s")
    serving_rps = (serving.get("T64", {}).get("steady_state", {})
                   .get("decisions_per_sec", 0.0))

    # Accuracy at >= 1 full window of steady state (VERDICT r2 weak-4),
    # at TWO offered loads:
    #
    # * saturation (virtual time advances at the measured device rate):
    #   the window then holds ~rps*60 requests — orders of magnitude past
    #   this geometry's capacity (a CMS absorbs roughly limit*w/e ~ 2.4M
    #   in-window requests before collision error swamps the limit), so
    #   the false-deny rate here characterizes OVERLOAD behavior, not the
    #   operating point;
    # * rated load (30K req/s — the reference's own single-instance
    #   sliding-window estimate): the in-window mass (~1.8M) sits inside
    #   the geometry's capacity, which is the regime the d=4 w=65536 spec
    #   is FOR. Wider sketches (bench.py: d=3 w=2^20) hold budget at
    #   device-saturation loads.
    def accuracy_run(rate, chunk_B, max_chunks, target_cov, cfg_run=None):
        cfg_a = cfg if cfg_run is None else cfg_run
        sub_us_a = sketch_kernels.sketch_geometry(cfg_a)[1]
        roll_a = sketch_kernels.build_steps(cfg_a)[2]
        eval_chunk = build_eval_chunk(cfg_a, chunk_B, n_keys, 1.1)
        or_roll = build_oracle_rollover(cfg_a, n_keys)
        states = {"sk": roll_a(sketch_kernels.init_state(cfg_a),
                               jnp.int64(T0_US // sub_us_a)),
                  "or": or_roll(init_oracle_state(cfg_a, n_keys),
                                jnp.int64(T0_US // sub_us_a))}
        acc_chunks = max(2, min(int(target_cov * cfg_a.window * rate / chunk_B),
                                max_chunks))
        period = T0_US // sub_us_a
        acc = []
        ctr = 0
        for i in range(acc_chunks):
            t_virt = T0_US + int((i + 1) * chunk_B / rate * 1e6)
            p = t_virt // sub_us_a
            if p > period:
                states = {"sk": roll_a(states["sk"], jnp.int64(p)),
                          "or": or_roll(states["or"], jnp.int64(p))}
                period = p
            states, stats = eval_chunk(states, jnp.uint64(ctr),
                                       jnp.int64(t_virt))
            acc.append(jnp.stack(stats))
            ctr += chunk_B
        fd, fa, _sk_deny, or_deny = [
            int(x) for x in np.asarray(jnp.sum(jnp.stack(acc), axis=0))]
        total = acc_chunks * chunk_B
        return {
            "offered_rate_per_sec": round(rate, 1),
            "window_coverage": round(total / rate / cfg_a.window, 3),
            "decisions": total,
            "false_deny_rate_vs_oracle": round(fd / max(total - or_deny, 1), 6),
            "false_allow_rate_vs_oracle": round(fa / max(or_deny, 1), 9),
            "oracle_deny_rate": round(or_deny / total, 4),
        }

    acc_sat = accuracy_run(rps, B, 768, 0.1 if quick else 1.25)
    log(f"config3 saturation-accuracy done cov={acc_sat['window_coverage']}")
    # Rated load: sub-window-sized chunks so each stays within one period.
    acc_rated = accuracy_run(30_000.0, 16384, 200, 0.2 if quick else 1.25)
    log(f"config3 rated-accuracy done cov={acc_rated['window_coverage']}")

    # Auto-sized geometry for the SAME saturation load: admitted in-window
    # mass is capped by the keyspace (every key saturates its limit), so
    # size with SketchParams.for_load at the 1% target and re-measure.
    # This is the enforced accuracy envelope the literal geometry lacks
    # (its saturation run above characterizes overload).
    sat_mass = min(rps * cfg.window, n_keys * cfg.limit)
    auto_sketch = SketchParams.for_load(cfg.limit, sat_mass,
                                        active_keys=n_keys,
                                        target_false_deny=0.01)
    cfg_auto = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=cfg.limit,
                      window=cfg.window, max_batch_admission_iters=1,
                      sketch=auto_sketch)
    acc_auto = accuracy_run(rps, B, 768, 0.1 if quick else 1.25,
                            cfg_run=cfg_auto)
    acc_auto["geometry"] = {"depth": auto_sketch.depth,
                            "width": auto_sketch.width,
                            "sized_for_mass": int(sat_mass),
                            "mass_budget": auto_sketch.mass_budget(cfg.limit)}
    log(f"config3 autosized-accuracy done w={auto_sketch.width} "
        f"fd={acc_auto['false_deny_rate_vs_oracle']}")

    return {
        "config": 3,
        "setup": "Zipf(1.1) 1M keys, CMS d=4 w=65536 sub=60 CU, limit=100/60s",
        "n_keys": n_keys,
        "saturation_decisions_per_sec": round(rps, 1),
        "saturation_batch": B,
        "serving_shape": serving,
        "serving_decisions_per_sec": serving_rps,
        "serving_ingest_batch": ingest,
        "accuracy_at_saturation_load": acc_sat,
        "accuracy_at_rated_load": acc_rated,
        "accuracy_at_saturation_autosized": acc_auto,
        "geometry_capacity_note": (
            "The literal d=4 w=65536 geometry's calibrated budget is "
            "2*limit*w ~ 13M admitted in-window requests (~1% false "
            "denies); its saturation run above characterizes overload. "
            "SketchParams.for_load sizes for a target point, and the "
            "limiter warns at runtime when admitted mass exceeds the "
            "geometry's budget (tests/test_geometry.py)."),
        "north_star_decisions_per_sec": 10_000_000,
        "meets_north_star_saturation": rps >= 10_000_000,
        "meets_accuracy_budget_rated": (
            acc_rated["false_deny_rate_vs_oracle"] <= 0.01),
        "meets_accuracy_budget_saturation_autosized": (
            acc_auto["false_deny_rate_vs_oracle"] <= 0.01),
    }


# ------------------------------------------------------------- config 4

def config4(quick: bool = False, log=print) -> Dict:
    """Bursty on/off load against the 60x1s decay ring: the sketch must
    deny during bursts (like the oracle) and fully recover quota after
    idle-off periods — decay correctness under the worst access pattern."""
    import jax.numpy as jnp

    from ratelimiter_tpu.evaluation.oracle_device import (
        build_eval_chunk,
        build_oracle_rollover,
        init_oracle_state,
    )
    from ratelimiter_tpu.ops import sketch_kernels

    n_keys = 10_000 if quick else 100_000
    B = 1 << 14
    cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=50, window=60.0,
                 max_batch_admission_iters=1,
                 sketch=SketchParams(depth=4, width=65536, sub_windows=60))
    _, sub_us, _, _, _ = sketch_kernels.sketch_geometry(cfg)
    roll = sketch_kernels.build_steps(cfg)[2]
    eval_chunk = build_eval_chunk(cfg, B, n_keys, 1.05)
    or_roll = build_oracle_rollover(cfg, n_keys)
    states = {"sk": roll(sketch_kernels.init_state(cfg),
                         jnp.int64(T0_US // sub_us)),
              "or": or_roll(init_oracle_state(cfg, n_keys),
                            jnp.int64(T0_US // sub_us))}
    period = T0_US // sub_us
    ctr = 0
    fd = fa = or_deny = total = 0
    # 90 virtual seconds: 3 s ON (heavy), 7 s OFF, repeating — bursts
    # repeatedly cross sub-window boundaries and decay through the ring.
    seconds = 30 if quick else 90
    for sec in range(seconds):
        t_virt = T0_US + sec * 1_000_000
        p = t_virt // sub_us
        if p > period:
            states = {"sk": roll(states["sk"], jnp.int64(p)),
                      "or": or_roll(states["or"], jnp.int64(p))}
            period = p
        if sec % 10 < 3:  # ON phase
            states, stats = eval_chunk(states, jnp.uint64(ctr),
                                       jnp.int64(t_virt))
            s = [int(x) for x in np.asarray(jnp.stack(stats))]
            fd += s[0]
            fa += s[1]
            or_deny += s[3]
            total += B
            ctr += B
    log("config4 done")
    return {
        "config": 4,
        "setup": "60x1s ring, bursty 3s-on/7s-off load, limit=50/60s",
        "decisions": total,
        "false_deny_rate_vs_oracle": round(fd / max(total - or_deny, 1), 6),
        "false_allow_rate_vs_oracle": round(fa / max(or_deny, 1), 9),
        "oracle_deny_rate": round(or_deny / max(total, 1), 4),
    }


# ------------------------------------------------------------- config 5

_CONFIG5_CHILD = r"""
import json, os, time
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
from ratelimiter_tpu import Algorithm, Config, ManualClock, SketchParams
from ratelimiter_tpu.parallel import MeshSketchLimiter, make_mesh

n_keys = int(os.environ.get("C5_KEYS", "8000000"))
B = int(os.environ.get("C5_BATCH", "8192"))
mesh = make_mesh(n_devices=8)
cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=100, window=60.0,
             max_batch_admission_iters=1,
             sketch=SketchParams(depth=4, width=65536, sub_windows=60))
out = {}
rng = np.random.default_rng(0)
ids = rng.zipf(1.1, size=4 * B).astype(np.uint64) % n_keys
for merge in ("gather", "delta"):
    lim = MeshSketchLimiter(cfg, ManualClock(1.7e9), mesh=mesh, merge=merge)
    r = lim.allow_hashed(ids[:B]); np.asarray(r.allowed[:1])  # compile
    t0 = time.perf_counter()
    for i in range(1, 4):
        r = lim.allow_hashed(ids[i * B:(i + 1) * B])
    np.asarray(r.allowed[:1])
    dt = (time.perf_counter() - t0) / 3
    out[merge] = {"steps_per_sec": round(1 / dt, 2),
                  "decisions_per_sec": round(3 * B / (3 * dt), 1)}
    # exactness probe: hot key over all chips
    hot = lim.allow_batch(["hot"] * 256)
    out[merge]["hot_key_admitted"] = int(hot.allow_count)
    after = lim.allow_batch(["hot"] * 256)
    out[merge]["hot_key_after_converge"] = int(after.allow_count)
    lim.close()
print(json.dumps(out))
"""


def config5(quick: bool = False, log=print) -> Dict:
    """8M-key trace on an 8-device mesh. In this environment the mesh is
    virtual (8 CPU host devices), so the numbers characterize CORRECTNESS
    and the relative gather-vs-delta collective cost — they are not a TPU
    throughput claim (BASELINE config 5's v5e-8 target needs real ICI)."""
    env = dict(os.environ)
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                        " --xla_force_host_platform_device_count=8").strip()
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
        + env.get("PYTHONPATH", "").split(os.pathsep))
    if quick:
        env["C5_KEYS"] = "100000"
        env["C5_BATCH"] = "2048"
    proc = subprocess.run([sys.executable, "-c", _CONFIG5_CHILD], env=env,
                          capture_output=True, text=True, timeout=1200)
    if proc.returncode != 0:
        return {"config": 5, "error": proc.stderr[-2000:]}
    data = json.loads(proc.stdout.strip().splitlines()[-1])
    log("config5 done")
    # Gather is bit-exact (10..limit); delta converges next step.
    gather_ok = data["gather"]["hot_key_admitted"] == 100 and \
        data["gather"]["hot_key_after_converge"] == 0
    delta_ok = (100 <= data["delta"]["hot_key_admitted"] <= 800
                and data["delta"]["hot_key_after_converge"] == 0)
    return {
        "config": 5,
        "setup": "8M-key Zipf over 8-device VIRTUAL CPU mesh (correctness, "
                 "not TPU perf)",
        "gather": data["gather"],
        "delta": data["delta"],
        "gather_exact": gather_ok,
        "delta_within_envelope": delta_ok,
    }


def run_configs(quick: bool = False, log=print) -> List[Dict]:
    out: List[Dict] = [config1(log=log)]
    out.extend(config2(quick=quick, log=log))
    out.append(config3(quick=quick, log=log))
    out.append(config4(quick=quick, log=log))
    out.append(config5(quick=quick, log=log))
    return out
