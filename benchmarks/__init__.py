"""Benchmark suite: the reference's 31-benchmark matrix re-expressed for
the TPU framework, plus the five BASELINE.json configs and an end-to-end
serving benchmark.

Run everything:     python -m benchmarks            (writes RESULTS.json/md)
Quick/CI subset:    python -m benchmarks --quick
One group:          python -m benchmarks --only matrix|configs|e2e

The reference's matrix (``fixedwindow_bench_test.go:26-346``,
``tokenbucket_bench_test.go:26-443``, ``slidingwindow_bench_test.go:26-383``)
measures ns/op of one Allow against miniredis over dimensions
{algorithm, AllowN(1/10/100), parallel, window size, key cardinality,
denied path, fail-open path}. Here the same dimensions exist, but the
unit of work is the *batched dispatch* — the TPU-native hot path — so
cells report decisions/sec and µs/decision at each shape, with the
scalar (single-request) path measured separately as the latency floor.
"""
