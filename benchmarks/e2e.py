"""End-to-end serving benchmark: real server process, real wire protocol,
string keys — the number VERDICT r2 asked for ("including host ingest and
string hashing").

Topology: N pipelined AsyncClient connections drive a spawned
``python -m ratelimiter_tpu.serving`` subprocess; every request carries a
string key (hashed server-side by the native bulk hasher on the batched
path); the server coalesces across connections via the micro-batcher.

Three variants:
* exact backend — pure host path (no device), isolates RPC + batcher cost;
* sketch backend, default platform — the flagship path; NOTE: through the
  dev tunnel a device dispatch pays ~100-200 ms RTT, so this number is
  tunnel-dominated (reported as-is with the RTT alongside — same honesty
  note as bench.py phase C);
* sketch backend, JAX_PLATFORMS=cpu — device path without the tunnel,
  bounding what the host/RPC machinery sustains with a local accelerator.
"""

from __future__ import annotations

import asyncio
import os
import socket
import subprocess
import sys
import time
from typing import Dict, List, Optional

import numpy as np

from ratelimiter_tpu.serving import AsyncClient

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn_server(backend: str, *, platform: Optional[str] = None,
                  max_batch: int = 4096, max_delay_us: float = 500.0,
                  native: bool = False, shards: int = 1,
                  inflight: int = 8, mesh_devices: Optional[int] = None,
                  extra_env: Optional[Dict[str, str]] = None,
                  extra_args: Optional[list] = None):
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [REPO] + env.get("PYTHONPATH", "").split(os.pathsep))
    if platform:
        env["JAX_PLATFORMS"] = platform
    if extra_env:
        env.update(extra_env)
    port = _free_port()
    algo = "sliding_window" if backend == "exact" else "tpu_sketch"
    proc = subprocess.Popen(
        [sys.executable, "-m", "ratelimiter_tpu.serving",
         "--backend", backend, "--algorithm", algo,
         "--limit", "100", "--window", "60",
         "--max-batch", str(max_batch),
         "--max-delay-us", str(max_delay_us),
         "--inflight", str(inflight),
         "--port", str(port)]
        + (["--native"] if native else [])
        + (["--shards", str(shards)] if shards > 1 else [])
        + (["--mesh-devices", str(mesh_devices)]
           if mesh_devices is not None else [])
        + (list(extra_args) if extra_args else []),
        env=env, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
    line = proc.stdout.readline()  # blocks until "serving ..." banner
    if "serving" not in line:
        proc.kill()
        raise RuntimeError(f"server failed to start: {line!r}")
    return proc, port


async def _drive_scalar(port: int, *, seconds: float, conns: int,
                        inflight: int, n_keys: int, warmup: float = 2.0,
                        leased: bool = False,
                        lease_kw: Optional[Dict] = None) -> Dict:
    """The loadgen's ``leased`` mode (ADR-022) and its wire control.

    Closed-loop SCALAR ``allow()`` on a small zipf-hot keyset — the
    traffic shape leases exist for (per-key decisions, maximally
    repeated). ``leased=True`` enables the lease tier on every
    connection first, so decisions for hot keys are answered by the
    in-process cache under real concurrency (the maintenance loop
    renewing budgets while workers spend them); ``leased=False`` is
    the honest control: same client, same keys, every decision a
    pipelined wire RTT. The reported rate is CLIENT-OBSERVED either
    way — what an app embedding the client actually gets."""
    rng = np.random.default_rng(2)
    clients = [await AsyncClient.connect(port=port) for _ in range(conns)]
    caches = []
    if leased:
        from ratelimiter_tpu.observability import Registry

        kw = dict(hot_after=2, hot_window=60.0, low_water=0.5)
        kw.update(lease_kw or {})
        interval = kw.pop("interval", 0.02)
        for c in clients:
            # Own registry per cache: the local-answer counter is
            # registered by NAME, so DEFAULT-registry caches in one
            # process would all read the same (summed) series.
            caches.append(await c.enable_leases(
                interval=interval, registry=Registry(), **kw))
    t_measure = time.perf_counter() + warmup
    stop_at = t_measure + seconds
    counted = 0
    total = 0

    async def worker(c: AsyncClient, wid: int):
        nonlocal counted, total
        ids = rng.zipf(1.1, size=8192) % n_keys
        i = wid * 1291
        while time.perf_counter() < stop_at:
            for _ in range(256):
                await c.allow(f"hot:{ids[i % 8192]}")
                i += 1
            total += 256
            if time.perf_counter() >= t_measure:
                counted += 256
            # A fully-local burst never yields; give the lease
            # maintenance loop (and the other workers) the floor.
            await asyncio.sleep(0)

    await asyncio.gather(*(worker(c, w * conns + k)
                           for k, c in enumerate(clients)
                           for w in range(max(1, inflight))))
    end = time.perf_counter()
    local = sum(int(lc.status()["local_answers"]) for lc in caches)
    for c in clients:
        await c.close()
    span = max(end - t_measure, 1e-9)
    return {
        "mode": "leased" if leased else "wire",
        "decisions_per_sec": round(counted / span, 1),
        "completed": counted,
        "local_answers": local,
        "local_fraction": round(local / total, 4) if total else None,
        "connections": conns,
        "workers_per_conn": max(1, inflight),
        "hot_keys": n_keys,
    }


async def _drive(port: int, *, seconds: float, conns: int, window: int,
                 n_keys: int, warmup: float = 2.0,
                 trace_sample: int = 0) -> Dict:
    """Two passes over a live server:

    1. Throughput: each connection keeps `window` decisions in flight via
       pipelined ALLOW_BATCH frames (the Redis-pipelining analog); the
       first `warmup` seconds absorb jit compiles and are discarded.
    2. Latency: a single connection, ONE scalar request in flight — the
       uncontended per-request RTT (closed-loop saturated latency is just
       Little's law on the queue, so it is measured separately).

    ``trace_sample`` (ADR-014): every Nth frame per connection carries a
    fresh wire trace id and records a client-side "client" span — the
    loadgen half of the flight-recorder story (0 = off).
    """
    from ratelimiter_tpu.observability import tracing

    rng = np.random.default_rng(0)

    # ---- pass 1: saturated throughput via batch frames
    clients = [await AsyncClient.connect(port=port) for _ in range(conns)]
    frame = max(64, window // 4)  # keys per ALLOW_BATCH frame; 4 in flight
    t_measure = time.perf_counter() + warmup
    stop_at = t_measure + seconds
    counted = 0

    async def worker(c: AsyncClient):
        nonlocal counted
        ids = rng.zipf(1.1, size=65536) % n_keys
        i = 0

        async def one():
            nonlocal counted, i
            keys = [f"user:{ids[(i + j) % 65536]}" for j in range(frame)]
            tid = 0
            if trace_sample and (i // frame) % trace_sample == 0 \
                    and tracing.RECORDER is not None:
                tid = tracing.new_trace_id()
            i += frame
            if tid:
                t0 = tracing.now()
                await c.allow_batch(keys, trace_id=tid)
                tracing.record("client", t0, tracing.now(), trace_id=tid,
                               batch=frame)
            else:
                await c.allow_batch(keys)
            if time.perf_counter() >= t_measure:
                counted += frame

        pending = {asyncio.ensure_future(one())
                   for _ in range(max(1, window // frame))}
        while time.perf_counter() < stop_at:
            done, pending = await asyncio.wait(
                pending, return_when=asyncio.FIRST_COMPLETED)
            for d in done:
                d.result()
                if time.perf_counter() < stop_at:
                    pending.add(asyncio.ensure_future(one()))
        if pending:
            await asyncio.gather(*pending, return_exceptions=True)

    await asyncio.gather(*(worker(c) for c in clients))
    end = time.perf_counter()
    for c in clients:
        await c.close()
    span = max(end - t_measure, 1e-9)

    # ---- pass 2: uncontended scalar latency
    c = await AsyncClient.connect(port=port)
    lats: List[float] = []
    for i in range(400):
        t0 = time.perf_counter()
        await c.allow(f"lat:{i % 100}")
        lats.append(time.perf_counter() - t0)
    await c.close()
    lat = np.array(lats[50:])  # drop connection/jit warmup tail

    return {
        "decisions_per_sec": round(counted / span, 1),
        "completed": counted,
        "scalar_p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 2),
        "scalar_p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 2),
        "connections": conns,
        "inflight_per_conn": window,
        "batch_frame": frame,
    }


def _run_variant(name: str, backend: str, *, platform=None, seconds=6.0,
                 conns=4, window=2048, native=False, trace_sample=0,
                 log=print) -> Dict:
    proc, port = _spawn_server(backend, platform=platform, native=native)
    try:
        out = asyncio.run(_drive(port, seconds=seconds, conns=conns,
                                 window=window, n_keys=100_000,
                                 trace_sample=trace_sample))
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()
    out["variant"] = name
    out["backend"] = backend
    log(f"e2e {name}: {out['decisions_per_sec']:.0f}/s "
        f"scalar_p99={out['scalar_p99_ms']}ms")
    return out


def _run_native_loadgen(*, seconds: float, log=print,
                        inflight: int = 8, hashed: bool = False) -> Dict:
    """Native server driven by the native C++ load generator
    (clients/cpp/loadgen.cpp) — removes the Python client from the loop,
    so this is the true server+decide ceiling. ``inflight`` sets the
    server's pipelined dispatch window (1 = the old synchronous path);
    ``hashed`` drives the zero-copy ALLOW_HASHED lane (raw u64 ids,
    device-side hashing, ADR-011) instead of string ALLOW_BATCH frames."""
    import json
    import shutil
    import tempfile

    if shutil.which("g++") is None:
        return {"variant": "native server + native loadgen",
                "error": "no g++"}
    with tempfile.TemporaryDirectory() as td:
        binary = os.path.join(td, "rltpu_loadgen")
        subprocess.run(
            ["g++", "-O2", "-std=c++17",
             os.path.join(REPO, "clients", "cpp", "loadgen.cpp"),
             "-o", binary, "-pthread"],
            check=True, capture_output=True, timeout=180)
        # max_batch 16384: the CPU-device decide costs ~1 us/decision
        # flat, so deeper coalescing amortizes the per-dispatch overhead
        # (r4: C++-side key prefixing + responder-thread encode overlap
        # moved the ceiling from ~300K to ~0.8-1M/s on this harness; the
        # wall is the XLA-CPU step itself, see ADR-003). The pipelined
        # launch/resolve window (ADR-010) overlaps that step with host
        # encode/decode.
        proc, port = _spawn_server("sketch", platform="cpu", native=True,
                                   max_batch=16384, inflight=inflight)
        try:
            out = subprocess.run(
                [binary, "127.0.0.1", str(port), str(seconds), "6", "8",
                 "1024", "100000", "hashed" if hashed else "batch"],
                capture_output=True, text=True, timeout=seconds + 60)
            row = json.loads(out.stdout.strip())
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()
    row["variant"] = ("NATIVE server + NATIVE loadgen, sketch on cpu "
                      "(no Python in the client loop; latency is per "
                      "1024-key frame, not per scalar request)")
    row["connections"] = row.pop("threads")
    row["inflight_per_conn"] = (row.pop("inflight_frames")
                                * row["keys_per_frame"])
    row["server_inflight"] = inflight
    log(f"e2e native+native (inflight={inflight}): "
        f"{row['decisions_per_sec']:.0f}/s")
    return row


def run_shm_ab(*, seconds: float = 4.0, pairs: int = 3,
               threads: int = 4, inflight: int = 8,
               frame_keys: int = 256, loadgen: Optional[str] = None,
               log=print) -> Dict:
    """Transport A/B for the zero-syscall shm wire lane (ADR-025):
    INTERLEAVED paired rounds of tcp-loopback / uds / shm through the
    C++ loadgen's hashed lane against real ``--native --shm`` servers —
    back-to-back rounds see the same box state, so the best paired
    ratio measures the transport's marginal cost, not machine drift
    (the same honesty pattern as the audit overhead A/B). Every row
    carries the loadgen's serialize/wire-write phase means, so the
    JSON shows WHERE the per-frame time went: encoding is
    transport-invariant, the write phase is the lane under test.

    Two servers, both shm-enabled: one TCP (serves the tcp and shm
    rounds — the shm lane upgrades over it) and one UDS (``--listen
    unix:...``). ``frame_keys`` is deliberately smaller than the
    saturation benches' 1024-2048: per-frame wire cost is the
    numerator here, and jumbo frames would hide it behind the device
    decide."""
    import json
    import shutil
    import tempfile

    if shutil.which("g++") is None:
        return {"error": "no g++"}
    td = None
    try:
        if loadgen is None:
            td = tempfile.mkdtemp()
            loadgen = _build_loadgen(td)
        upath = os.path.join(td or tempfile.gettempdir(),
                             f"rltpu-bench-{os.getpid()}.sock")
        tcp_proc, tcp_port = _spawn_server(
            "sketch", platform="cpu", native=True, max_batch=16384,
            inflight=inflight, extra_args=["--shm", "--limit", "1000000"])
        uds_proc = None
        try:
            uds_proc, _ = _spawn_server(
                "sketch", platform="cpu", native=True, max_batch=16384,
                inflight=inflight,
                extra_args=["--shm", "--limit", "1000000",
                            "--listen", f"unix:{upath}"])

            def run(transport: str) -> Dict:
                host = upath if transport == "uds" else "127.0.0.1"
                args = [loadgen, host, str(tcp_port), str(seconds),
                        str(threads), str(inflight), str(frame_keys),
                        "100000", "hashed", "--transport", transport]
                out = subprocess.run(args, capture_output=True, text=True,
                                     timeout=seconds + 90)
                return json.loads(out.stdout.strip())

            rounds = []
            for i in range(max(1, pairs)):
                rd = {t: run(t) for t in ("tcp", "uds", "shm")}
                rounds.append(rd)
                log(f"shm A/B round {i + 1}: "
                    + " ".join(f"{t}={rd[t]['decisions_per_sec']:.0f}/s"
                               f"(wr {rd[t]['wire_write_us_per_frame']:.2f}"
                               "us)" for t in ("tcp", "uds", "shm")))
        finally:
            for proc in (tcp_proc, uds_proc):
                if proc is None:
                    continue
                proc.terminate()
                try:
                    proc.wait(timeout=15)
                except subprocess.TimeoutExpired:
                    proc.kill()
    finally:
        if td is not None:
            import shutil as _sh

            _sh.rmtree(td, ignore_errors=True)

    def best_pair(t: str) -> Dict:
        rd = max(rounds, key=lambda r: (r[t]["decisions_per_sec"]
                                        / max(r["tcp"]["decisions_per_sec"],
                                              1e-9)))
        return {
            "decisions_per_sec": rd[t]["decisions_per_sec"],
            "tcp_decisions_per_sec": rd["tcp"]["decisions_per_sec"],
            "vs_tcp": round(rd[t]["decisions_per_sec"]
                            / max(rd["tcp"]["decisions_per_sec"], 1e-9), 3),
            "frame_p50_ms": rd[t]["frame_p50_ms"],
            "frame_p99_ms": rd[t]["frame_p99_ms"],
        }

    wire = {t: round(min(r[t]["wire_write_us_per_frame"] for r in rounds),
                     3)
            for t in ("tcp", "uds", "shm")}
    return {
        "rounds": rounds,
        "paired_best": {"uds": best_pair("uds"), "shm": best_pair("shm")},
        "wire_write_us_per_frame_best": {
            **wire,
            "tcp_over_shm": round(wire["tcp"] / max(wire["shm"], 1e-9), 2),
        },
        "harness": (
            f"cpp_loadgen hashed lane, {threads} conns x {inflight} "
            f"pipelined {frame_keys}-id frames, interleaved "
            "tcp/uds/shm rounds against two --native --shm sketch-on-cpu "
            "servers (one tcp, one --listen unix:); paired_best is the "
            "round with the best transport/tcp ratio (drift cancels "
            "in-pair); wire_write_us is the loadgen's measured "
            "send-syscall (tcp/uds) or ring-push+doorbell (shm) phase "
            "per frame"),
    }


def _build_loadgen(td: str) -> str:
    binary = os.path.join(td, "rltpu_loadgen")
    subprocess.run(
        ["g++", "-O2", "-std=c++17",
         os.path.join(REPO, "clients", "cpp", "loadgen.cpp"),
         "-o", binary, "-pthread"],
        check=True, capture_output=True, timeout=180)
    return binary


def _net_counters(port: int):
    """(net_syscalls_total, decisions_total) scraped over the wire.

    Syscalls = the engine-maintained recv+writev+wait+wake counters
    (rate_limiter_net_syscalls_total, summed over ``kind``) — the
    numerator of the syscalls-per-decision figure NETENG_r01.json
    reports. The scrape itself rides the same socket path, so its own
    handful of syscalls lands in the delta; at bench volumes (1e5+
    decisions/round) that noise is < 1e-4 of the figure."""
    from ratelimiter_tpu.serving import Client

    c = Client("127.0.0.1", port)
    try:
        text = c.metrics()
        _, _, decisions = c.health()
    finally:
        c.close()
    sys_total = 0.0
    for line in text.splitlines():
        if line.startswith("rate_limiter_net_syscalls_total{"):
            sys_total += float(line.rsplit(" ", 1)[1])
    return sys_total, float(decisions)


def run_conn_sweep(*, seconds: float = 2.5, pairs: int = 2,
                   conns=(16, 64, 256, 512), frame_keys: int = 8,
                   inflight: int = 4, loadgen: Optional[str] = None,
                   log=print) -> Dict:
    """Connection-count sweep for the multi-ring network engine
    (ISSUE-20, ADR-026): INTERLEAVED paired rounds of baseline vs new
    engine at each connection count, C++ loadgen hashed lane, emitting
    per-row throughput, p99, and syscalls-per-decision into
    NETENG_r01.json.

    Two ``--native`` servers stay up for the whole sweep:

    * baseline — ``--net-engine epoll --io-rings 1`` plus
      ``RL_NET_COALESCE=0``, the bench-honesty env knob that restores
      the pre-ISSUE-20 write profile (one send syscall per reply frame,
      one eventfd ding per queued reply) in the SAME binary, so the
      pair measures the engine work and not build drift;
    * engine — ``--net-engine auto`` (best available backend, auto ring
      count), the shipped default.

    ``frame_keys`` is deliberately tiny (8): per-frame wire cost is the
    numerator under test, and jumbo frames would hide it behind the
    device decide (same honesty note as run_shm_ab). Rounds alternate
    baseline/engine back-to-back per connection count so machine drift
    cancels in-pair; syscalls-per-decision is computed from counter
    deltas around each round (engine-maintained counters, not strace)."""
    import json
    import shutil
    import tempfile

    if shutil.which("g++") is None:
        return {"error": "no g++"}
    td = None
    rows: List[Dict] = []
    try:
        if loadgen is None:
            td = tempfile.mkdtemp()
            loadgen = _build_loadgen(td)
        base_proc, base_port = _spawn_server(
            "sketch", platform="cpu", native=True, max_batch=16384,
            inflight=inflight,
            extra_args=["--net-engine", "epoll", "--io-rings", "1",
                        "--limit", "1000000"],
            extra_env={"RL_NET_COALESCE": "0"})
        eng_proc = None
        try:
            eng_proc, eng_port = _spawn_server(
                "sketch", platform="cpu", native=True, max_batch=16384,
                inflight=inflight,
                extra_args=["--net-engine", "auto",
                            "--limit", "1000000"])

            def run(port: int, n_conns: int) -> Dict:
                pre_sys, pre_dec = _net_counters(port)
                out = subprocess.run(
                    [loadgen, "127.0.0.1", str(port), str(seconds),
                     str(n_conns), str(inflight), str(frame_keys),
                     "100000", "hashed"],
                    capture_output=True, text=True, timeout=seconds + 120)
                row = json.loads(out.stdout.strip())
                post_sys, post_dec = _net_counters(port)
                d_dec = max(post_dec - pre_dec, 1.0)
                row["syscalls_per_decision"] = round(
                    (post_sys - pre_sys) / d_dec, 4)
                return row

            for n_conns in conns:
                for i in range(max(1, pairs)):
                    rd = {"conns": n_conns, "round": i,
                          "baseline": run(base_port, n_conns),
                          "engine": run(eng_port, n_conns)}
                    rows.append(rd)
                    log(f"conn-sweep {n_conns}c round {i + 1}: "
                        f"base={rd['baseline']['decisions_per_sec']:.0f}/s"
                        f"({rd['baseline']['syscalls_per_decision']:.3f} "
                        "sys/dec) "
                        f"engine={rd['engine']['decisions_per_sec']:.0f}/s"
                        f"({rd['engine']['syscalls_per_decision']:.3f} "
                        "sys/dec)")
            eng_net = _engine_probe(eng_port)
        finally:
            for proc in (base_proc, eng_proc):
                if proc is None:
                    continue
                proc.terminate()
                try:
                    proc.wait(timeout=15)
                except subprocess.TimeoutExpired:
                    proc.kill()
    finally:
        if td is not None:
            import shutil as _sh

            _sh.rmtree(td, ignore_errors=True)

    def best_pair(n_conns: int) -> Dict:
        cand = [r for r in rows if r["conns"] == n_conns]
        rd = max(cand, key=lambda r: (r["engine"]["decisions_per_sec"]
                                      / max(r["baseline"]
                                            ["decisions_per_sec"], 1e-9)))
        b, e = rd["baseline"], rd["engine"]
        return {
            "baseline_decisions_per_sec": b["decisions_per_sec"],
            "engine_decisions_per_sec": e["decisions_per_sec"],
            "throughput_ratio": round(e["decisions_per_sec"]
                                      / max(b["decisions_per_sec"], 1e-9),
                                      3),
            "baseline_syscalls_per_decision": b["syscalls_per_decision"],
            "engine_syscalls_per_decision": e["syscalls_per_decision"],
            "syscall_cut": round(b["syscalls_per_decision"]
                                 / max(e["syscalls_per_decision"], 1e-9),
                                 2),
            "baseline_frame_p99_ms": b["frame_p99_ms"],
            "engine_frame_p99_ms": e["frame_p99_ms"],
        }

    return {
        "rows": rows,
        "paired_best": {str(n): best_pair(n) for n in conns},
        "engine": eng_net,
        "harness": (
            f"cpp_loadgen hashed lane, {frame_keys}-id frames x "
            f"{inflight} pipelined, interleaved baseline/engine rounds "
            "per connection count against two --native sketch-on-cpu "
            "servers (baseline: --net-engine epoll --io-rings 1 + "
            "RL_NET_COALESCE=0 = pre-ISSUE-20 write-per-frame profile; "
            "engine: --net-engine auto); syscalls_per_decision from "
            "engine counter deltas (rate_limiter_net_syscalls_total) "
            "around each round; paired_best is the round with the best "
            "engine/baseline throughput ratio (drift cancels in-pair)"),
    }


def _engine_probe(port: int) -> Dict:
    """The engine/rings/probe identity of a live server, via /metrics
    (rate_limiter_net_engine_info labels) — recorded in NETENG_r01.json
    so the row says WHICH backend produced it."""
    from ratelimiter_tpu.serving import Client

    c = Client("127.0.0.1", port)
    try:
        text = c.metrics()
    finally:
        c.close()
    for line in text.splitlines():
        if line.startswith("rate_limiter_net_engine_info{"):
            labels = line[line.index("{") + 1:line.index("}")]
            out = {}
            for part in labels.split(","):
                k, _, v = part.partition("=")
                out[k.strip()] = v.strip().strip('"')
            return out
    return {}


def run_mesh_loadgen(n_devices: int, *, seconds: float = 4.0,
                     affine: bool = True, spread: Optional[int] = None,
                     loadgen: Optional[str] = None,
                     platform: Optional[str] = None,
                     router: str = "host",
                     chaos: Optional[str] = None,
                     chaos_slice: int = 1,
                     chaos_after: float = 1.0) -> Dict:
    """One measured point of the slice-parallel serving curve (ADR-012):
    a real ``--backend mesh --native`` server over ``n_devices`` pinned
    slices, driven by the C++ loadgen's zero-copy hashed lane.

    ``spread`` is the slice-spread knob (ADR-013): each connection's ids
    route to a window of that many dispatch shards starting at its home
    shard (splitmix64(id) % n). spread=1 is pure shard-affine traffic —
    the shape a consistent-hash LB produces, frames never fan out;
    spread=n is uniform MIXED traffic — every frame fans out over every
    device and reassembles through the scatter-gather scheduler. When
    ``spread`` is None, ``affine`` selects spread=1 (True) or spread=n
    (False). The server always routes every id itself either way.

    ``--inflight 1`` (synchronous per-shard dispatch): on the CPU mesh
    the jitted step executes synchronously inside launch, so pipelining
    only fragments coalesced batches across window slots; each device's
    dispatcher thread blocking in its own decide IS the parallelism
    (the GIL is released while the device computes).

    ``router="collective"`` (ADR-024) serves the same traffic through
    the collective mesh router: the composite limiter mounts as ONE
    dispatch shard and every frame is one shard_map'd all_to_all step —
    the id generation (and therefore the affine/mixed traffic shape,
    which both routers define by the same ``h64 % n`` owner rule) is
    unchanged, so host and collective rows are directly comparable."""
    import json
    import shutil
    import tempfile

    if shutil.which("g++") is None:
        return {"error": "no g++"}
    if chaos and router == "collective":
        # The slice chaos scenarios need --quarantine, which the
        # collective router refuses (whole-mesh blast radius, ADR-024).
        raise ValueError("chaos scenarios need the host router "
                         "(--quarantine is incompatible with "
                         "router='collective')")
    if spread is None:
        spread = 1 if affine else n_devices
    spread = max(1, min(int(spread), n_devices))
    with tempfile.TemporaryDirectory() as td:
        binary = loadgen or _build_loadgen(td)
        # Chaos-enabled runs (ADR-015): the server arms one scenario
        # mid-traffic and quarantine contains it; the loadgen keeps
        # driving through the fault — fail-open answers count as served
        # (the row reports the degraded-but-serving rate).
        chaos_args = []
        if chaos:
            chaos_args = ["--fail-open", "--quarantine",
                          "--chaos-scenario", chaos,
                          "--chaos-slice", str(chaos_slice),
                          "--chaos-after", str(chaos_after)]
        if router != "host":
            chaos_args = chaos_args + ["--router", router]
        proc, port = _spawn_server(
            "mesh", platform=platform, native=True, max_batch=16384,
            max_delay_us=1000.0, inflight=1, mesh_devices=n_devices,
            extra_args=chaos_args)
        try:
            # 16 conns x 8 x 2048 ids = 262K in flight: enough offered
            # load to keep EIGHT devices' coalescers at max_batch depth
            # (thin queues half-fill the per-device batches and flatten
            # the top of the scaling curve).
            args = [binary, "127.0.0.1", str(port), str(seconds), "16", "8",
                    "2048", "1000000", "hashed", str(n_devices),
                    str(spread)]
            out = subprocess.run(args, capture_output=True, text=True,
                                 timeout=seconds + 120)
            row = json.loads(out.stdout.strip())
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()
    row["n_devices"] = n_devices
    row["router"] = router
    if chaos:
        row["chaos"] = {"scenario": chaos, "victim_slice": chaos_slice,
                        "armed_after_s": chaos_after}
    row["traffic"] = (
        "shard-affine (consistent-hash LB shape)" if spread == 1
        else ("mixed (uniform per-frame fan-out, scatter-gather "
              "coalesced)" if spread >= n_devices
              else f"partially mixed (slice-spread {spread}/{n_devices})"))
    return row


def run_e2e(quick: bool = False, trace_sample: int = 0,
            log=print) -> List[Dict]:
    """``trace_sample=N`` (ADR-014) turns on the loadgen's client-side
    flight recorder and samples every Nth frame per connection with a
    wire trace id: client spans land in the local recorder, and a
    server started with ``--flight-recorder`` attributes its stages to
    the same ids (``python -m benchmarks --only e2e --trace-sample N``)."""
    from ratelimiter_tpu.observability import tracing

    if trace_sample:
        tracing.enable()
    seconds = 2.0 if quick else 6.0
    window = 512 if quick else 2048
    rows = []
    rows.append(_run_variant("host-only (exact backend)", "exact",
                             seconds=seconds, window=window,
                             trace_sample=trace_sample, log=log))
    rows.append(_run_variant("sketch on cpu device", "sketch",
                             platform="cpu", seconds=seconds, window=window,
                             trace_sample=trace_sample, log=log))
    try:
        rows.append(_run_variant(
            "NATIVE server, host-only (exact backend)", "exact",
            seconds=seconds, window=window, native=True,
            trace_sample=trace_sample, log=log))
        rows.append(_run_variant(
            "NATIVE server, sketch on cpu device", "sketch",
            platform="cpu", seconds=seconds, window=window, native=True,
            trace_sample=trace_sample, log=log))
        rows.append(_run_native_loadgen(seconds=seconds, log=log))
    except Exception as exc:  # no compiler -> skip, never fail the suite
        rows.append({"variant": "native server", "error": str(exc)})
    if not quick:
        try:
            rows.append(_run_variant(
                "sketch on default platform (tunnel TPU: RTT-dominated)",
                "sketch", seconds=seconds, window=window, log=log))
        except Exception as exc:  # tunnel flakiness must not kill the suite
            rows.append({"variant": "sketch on default platform",
                         "error": str(exc)})
    if trace_sample and tracing.RECORDER is not None:
        # Surface the sampled client spans so the run proves its own
        # sampling: count + RTT stats across every variant's loadgen.
        summary = tracing.RECORDER.stage_summary().get("client")
        rows.append({"variant": f"loadgen trace sampling (1/{trace_sample} "
                                "frames)",
                     "client_spans": summary or {"count": 0}})
        tracing.disable()
    return rows
