// Closed-loop load generator for the rate-limit service.
//
// The Python e2e driver saturates its own asyncio loop before it
// saturates the native server; this native driver finds the server's
// real ceiling. N threads, one connection each, K pipelined ALLOW_BATCH
// frames of F keys in flight per connection; measures completed
// decisions/s over the timed window (after warmup) and per-frame RTT
// percentiles.
//
// Usage: rltpu_loadgen <host> <port> <seconds> <threads> <inflight>
//                      <keys_per_frame> <n_keys> [mode] [affine_shards]
//                      [spread]
// mode: "batch" (default, string ALLOW_BATCH frames) or "hashed"
// (columnar raw-u64-id ALLOW_HASHED frames — the zero-copy bulk lane,
// ADR-011).
// affine_shards (hashed mode only, default 0 = off): each connection's
// ids are drawn so they route only to a window of `spread` dispatch
// shards starting at the connection's home shard
// (thread % affine_shards) — the slice-spread knob (ADR-013):
//   spread=1 (default)       pure shard-affine traffic, the shape a
//                            consistent-hash LB produces (frames never
//                            fan out; ADR-012's scaling shape);
//   1 < spread < n           partially mixed — each frame fans out over
//                            `spread` devices;
//   spread >= affine_shards  uniform mixed — every frame fans out over
//                            every device (the scatter-gather
//                            scheduler's worst case).
// The server still routes every id itself either way.
// Output: one JSON line.

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "ratelimiter_client.hpp"

namespace {

double now_s() {
  return std::chrono::duration<double>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// splitmix64 finalizer — BIT-IDENTICAL to ops/hashing.splitmix64 and the
// server's router (native/server.cpp): affine mode must agree with the
// door's per-id shard routing or the affinity is silently lost.
inline uint64_t splitmix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ULL;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
  return x ^ (x >> 31);
}

struct Shared {
  std::atomic<uint64_t> completed{0};
  std::atomic<uint64_t> allowed{0};
  double t_measure = 0, t_stop = 0;
  std::mutex lat_mx;
  std::vector<double> latencies;  // frame RTTs inside the window
};

// Raw pipelined driver: hand-rolled frames on one socket (the Client
// class is strictly request/response; pipelining needs direct IO).
void worker(const char* host, int port, int inflight, int frame_keys,
            int n_keys, int wid, bool hashed, int affine, int spread,
            Shared* sh) {
  // The Client class is strictly request/response; pipelining needs
  // direct socket IO, so the frames are hand-rolled here.
  struct addrinfo hints {
  }, *res = nullptr;
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  std::string ps = std::to_string(port);
  if (getaddrinfo(host, ps.c_str(), &hints, &res) != 0) return;
  int fd = socket(res->ai_family, res->ai_socktype, res->ai_protocol);
  if (fd < 0 || connect(fd, res->ai_addr, res->ai_addrlen) != 0) {
    freeaddrinfo(res);
    return;
  }
  freeaddrinfo(res);
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, 1 /*TCP_NODELAY*/, &one, sizeof(one));

  auto send_all = [&](const std::string& b) {
    size_t off = 0;
    while (off < b.size()) {
      ssize_t w = send(fd, b.data() + off, b.size() - off, MSG_NOSIGNAL);
      if (w <= 0) return false;
      off += (size_t)w;
    }
    return true;
  };

  // Pre-encode a rotating set of ALLOW_BATCH frames.
  uint64_t req_id = 1;
  unsigned rng = 12345u + (unsigned)wid * 2654435761u;
  auto make_frame = [&](double* sent_at) {
    std::string body;
    uint32_t count = (uint32_t)frame_keys;
    body.append((char*)&count, 4);
    if (hashed) {
      // Columnar raw-id frame (ADR-011): u64 ids then u32 ns. With
      // affinity, rejection-sample until the id routes to the
      // connection's `spread`-shard window starting at its home shard
      // (spread=1: the consistent-hash-LB traffic shape; expected
      // `affine / spread` draws per id, LCG draws are ~free).
      bool constrain = affine > 0 && spread < affine;
      uint64_t home = (uint64_t)(wid % (affine > 0 ? affine : 1));
      for (int i = 0; i < frame_keys; ++i) {
        uint64_t id64;
        do {
          rng = rng * 1664525u + 1013904223u;
          id64 = rng % (unsigned)n_keys;
        } while (constrain &&
                 (splitmix64(id64) % (uint64_t)affine + (uint64_t)affine -
                  home) % (uint64_t)affine >= (uint64_t)spread);
        body.append((char*)&id64, 8);
      }
      uint32_t n = 1;
      for (int i = 0; i < frame_keys; ++i) body.append((char*)&n, 4);
    } else {
      for (int i = 0; i < frame_keys; ++i) {
        rng = rng * 1664525u + 1013904223u;
        char key[32];
        int klen =
            snprintf(key, sizeof(key), "user:%u", rng % (unsigned)n_keys);
        uint32_t n = 1;
        uint16_t kl = (uint16_t)klen;
        body.append((char*)&n, 4);
        body.append((char*)&kl, 2);
        body.append(key, klen);
      }
    }
    std::string frame;
    uint32_t length = (uint32_t)(1 + 8 + body.size());
    frame.append((char*)&length, 4);
    frame.push_back(
        (char)(hashed ? rltpu::T_ALLOW_HASHED : rltpu::T_ALLOW_BATCH));
    uint64_t id = req_id++;
    frame.append((char*)&id, 8);
    frame += body;
    *sent_at = now_s();
    return frame;
  };

  std::vector<double> sent_at((size_t)inflight + 8, 0.0);
  for (int i = 0; i < inflight; ++i) {
    double t;
    std::string f = make_frame(&t);
    sent_at[(req_id - 1) % sent_at.size()] = t;
    if (!send_all(f)) {
      close(fd);
      return;
    }
  }

  std::string rbuf;
  char tmp[65536];
  std::vector<double> local_lat;
  uint64_t local_completed = 0, local_allowed = 0;
  while (now_s() < sh->t_stop) {
    ssize_t r = recv(fd, tmp, sizeof(tmp), 0);
    if (r <= 0) break;
    rbuf.append(tmp, (size_t)r);
    size_t off = 0;
    while (rbuf.size() - off >= 13) {
      uint32_t length;
      memcpy(&length, rbuf.data() + off, 4);
      if (rbuf.size() - off < 4 + length) break;
      uint8_t type = (uint8_t)rbuf[off + 4];
      uint64_t rid;
      memcpy(&rid, rbuf.data() + off + 5, 8);
      if (type == rltpu::T_RESULT_BATCH || type == rltpu::T_RESULT_HASHED) {
        const char* body = rbuf.data() + off + 13;
        uint32_t count;
        // RESULT_BATCH: i64 limit | u32 count | 25B items.
        // RESULT_HASHED: u8 flags | i64 limit | u32 count | bit mask |
        // columnar i64/f64/f64.
        bool h = type == rltpu::T_RESULT_HASHED;
        memcpy(&count, body + (h ? 9 : 8), 4);
        double t1 = now_s();
        if (t1 >= sh->t_measure) {
          local_completed += count;
          if (h) {
            const uint8_t* bits = (const uint8_t*)body + 13;
            for (uint32_t i = 0; i < count; ++i)
              local_allowed += (bits[i >> 3] >> (i & 7)) & 1;
          } else {
            const char* items = body + 12;
            for (uint32_t i = 0; i < count; ++i)
              local_allowed += (uint8_t)items[i * 25] & 1;
          }
          double t0 = sent_at[rid % sent_at.size()];
          if (t0 > 0) local_lat.push_back(t1 - t0);
        }
        if (now_s() < sh->t_stop) {
          double t;
          std::string f = make_frame(&t);
          sent_at[(req_id - 1) % sent_at.size()] = t;
          if (!send_all(f)) break;
        }
      }
      off += 4 + length;
    }
    if (off) rbuf.erase(0, off);
  }
  close(fd);
  sh->completed.fetch_add(local_completed);
  sh->allowed.fetch_add(local_allowed);
  std::lock_guard<std::mutex> g(sh->lat_mx);
  sh->latencies.insert(sh->latencies.end(), local_lat.begin(),
                       local_lat.end());
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 8 || argc > 11) {
    std::fprintf(stderr,
                 "usage: %s <host> <port> <seconds> <threads> <inflight> "
                 "<keys_per_frame> <n_keys> [batch|hashed] "
                 "[affine_shards] [spread]\n",
                 argv[0]);
    return 2;
  }
  const char* host = argv[1];
  int port = atoi(argv[2]);
  double seconds = atof(argv[3]);
  int threads = atoi(argv[4]);
  int inflight = atoi(argv[5]);
  int frame_keys = atoi(argv[6]);
  int n_keys = atoi(argv[7]);
  bool hashed = argc >= 9 && std::strcmp(argv[8], "hashed") == 0;
  int affine = (argc >= 10 && hashed) ? atoi(argv[9]) : 0;
  int spread = (argc >= 11 && hashed) ? atoi(argv[10]) : 1;
  if (spread < 1) spread = 1;

  Shared sh;
  double warmup = 1.0;
  sh.t_measure = now_s() + warmup;
  sh.t_stop = sh.t_measure + seconds;

  std::vector<std::thread> ts;
  for (int i = 0; i < threads; ++i)
    ts.emplace_back(worker, host, port, inflight, frame_keys, n_keys, i,
                    hashed, affine, spread, &sh);
  for (auto& t : ts) t.join();

  double span = seconds;
  std::vector<double>& lat = sh.latencies;
  std::sort(lat.begin(), lat.end());
  auto pct = [&](double p) {
    if (lat.empty()) return 0.0;
    return lat[std::min(lat.size() - 1, (size_t)(p * lat.size()))] * 1e3;
  };
  std::printf(
      "{\"decisions_per_sec\": %.1f, \"completed\": %llu, "
      "\"allowed\": %llu, \"frame_p50_ms\": %.2f, \"frame_p99_ms\": %.2f, "
      "\"threads\": %d, \"inflight_frames\": %d, \"keys_per_frame\": %d, "
      "\"mode\": \"%s\", \"affine_shards\": %d, \"spread\": %d}\n",
      (double)sh.completed.load() / span,
      (unsigned long long)sh.completed.load(),
      (unsigned long long)sh.allowed.load(), pct(0.50), pct(0.99), threads,
      inflight, frame_keys, hashed ? "hashed" : "batch", affine, spread);
  return 0;
}
