// Closed-loop load generator for the rate-limit service.
//
// The Python e2e driver saturates its own asyncio loop before it
// saturates the native server; this native driver finds the server's
// real ceiling. N threads, one connection each, K pipelined ALLOW_BATCH
// frames of F keys in flight per connection; measures completed
// decisions/s over the timed window (after warmup) and per-frame RTT
// percentiles.
//
// Usage: rltpu_loadgen <host> <port> <seconds> <threads> <inflight>
//                      <keys_per_frame> <n_keys> [mode] [affine_shards]
//                      [spread] [--transport tcp|uds|shm]
// mode: "batch" (default, string ALLOW_BATCH frames) or "hashed"
// (columnar raw-u64-id ALLOW_HASHED frames — the zero-copy bulk lane,
// ADR-011).
// affine_shards (hashed mode only, default 0 = off): each connection's
// ids are drawn so they route only to a window of `spread` dispatch
// shards starting at the connection's home shard
// (thread % affine_shards) — the slice-spread knob (ADR-013):
//   spread=1 (default)       pure shard-affine traffic, the shape a
//                            consistent-hash LB produces (frames never
//                            fan out; ADR-012's scaling shape);
//   1 < spread < n           partially mixed — each frame fans out over
//                            `spread` devices;
//   spread >= affine_shards  uniform mixed — every frame fans out over
//                            every device (the scatter-gather
//                            scheduler's worst case).
// The server still routes every id itself either way.
//
// --transport (ADR-025): "tcp" (default), "uds" (host is a unix socket
// path, "unix:" prefix optional), or "shm" — connect (tcp or uds), then
// T_SHM_HELLO upgrades the connection to shared-memory SPSC rings; the
// SAME frames then move through /dev/shm with zero steady-state
// syscalls. The JSON adds serialize/wire-write phase means so the A/B
// shows where the time went, not just the total.
// Output: one JSON line.

#include <fcntl.h>
#include <poll.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <sys/un.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "../../ratelimiter_tpu/native/shm_ring.h"
#include "ratelimiter_client.hpp"

namespace {

constexpr uint8_t T_SHM_HELLO = 16;
constexpr uint8_t T_SHM_HELLO_R = 141;
constexpr int SHM_SPIN = 4096;

double now_s() {
  return std::chrono::duration<double>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// splitmix64 finalizer — BIT-IDENTICAL to ops/hashing.splitmix64 and the
// server's router (native/server.cpp): affine mode must agree with the
// door's per-id shard routing or the affinity is silently lost.
inline uint64_t splitmix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ULL;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
  return x ^ (x >> 31);
}

struct Shared {
  std::atomic<uint64_t> completed{0};
  std::atomic<uint64_t> allowed{0};
  std::atomic<uint64_t> ser_ns{0};       // serialize phase, timed window
  std::atomic<uint64_t> wire_ns{0};      // wire-write phase, timed window
  std::atomic<uint64_t> timed_frames{0};
  double t_measure = 0, t_stop = 0;
  std::mutex lat_mx;
  std::vector<double> latencies;  // frame RTTs inside the window
};

enum Transport { TR_TCP = 0, TR_UDS = 1, TR_SHM = 2 };

int connect_fd(const char* host, int port, bool uds) {
  if (uds) {
    const char* path = host;
    if (strncmp(path, "unix:", 5) == 0) path += 5;
    int fd = socket(AF_UNIX, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    sockaddr_un a{};
    a.sun_family = AF_UNIX;
    if (strlen(path) >= sizeof(a.sun_path)) {
      close(fd);
      return -1;
    }
    strncpy(a.sun_path, path, sizeof(a.sun_path) - 1);
    if (connect(fd, (sockaddr*)&a, sizeof(a)) != 0) {
      close(fd);
      return -1;
    }
    return fd;
  }
  struct addrinfo hints {
  }, *res = nullptr;
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  std::string ps = std::to_string(port);
  if (getaddrinfo(host, ps.c_str(), &hints, &res) != 0) return -1;
  int fd = socket(res->ai_family, res->ai_socktype, res->ai_protocol);
  if (fd < 0 || connect(fd, res->ai_addr, res->ai_addrlen) != 0) {
    freeaddrinfo(res);
    if (fd >= 0) close(fd);
    return -1;
  }
  freeaddrinfo(res);
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, 1 /*TCP_NODELAY*/, &one, sizeof(one));
  return fd;
}

// Shared-memory lane state (client side: outbound = request ring).
struct ShmLane {
  uint8_t* base = nullptr;
  size_t map_len = 0;
  rlshm::LaneView lane;
  int efd_server = -1, efd_client = -1;

  ~ShmLane() {
    if (efd_server >= 0) close(efd_server);
    if (efd_client >= 0) close(efd_client);
    if (base) munmap(base, map_len);
  }
};

bool recv_exact(int fd, uint8_t* p, size_t n) {
  while (n) {
    ssize_t r = recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= (size_t)r;
  }
  return true;
}

bool send_all_fd(int fd, const char* p, size_t n) {
  while (n) {
    ssize_t w = send(fd, p, n, MSG_NOSIGNAL);
    if (w <= 0) return false;
    p += w;
    n -= (size_t)w;
  }
  return true;
}

// T_SHM_HELLO over the live socket, then map + ctrl-socket fd handoff.
// Mirrors serving/shm.py ClientLane: map the shm file FIRST (the server
// unlinks both paths the moment the ctrl connect lands).
bool shm_upgrade(int fd, ShmLane* L) {
  // hello body is <III>: version | req_ring | rep_ring (0 = default);
  // rid 0 is safe — loadgen data frames start at 1.
  uint8_t frame[25];
  uint32_t length = 1 + 8 + 12;
  uint32_t ver = 1, zero = 0;
  memcpy(frame, &length, 4);
  frame[4] = T_SHM_HELLO;
  memset(frame + 5, 0, 8);
  memcpy(frame + 13, &ver, 4);
  memcpy(frame + 17, &zero, 4);
  memcpy(frame + 21, &zero, 4);
  if (!send_all_fd(fd, (const char*)frame, sizeof(frame))) return false;

  uint8_t hdr[13];
  if (!recv_exact(fd, hdr, 13)) return false;
  memcpy(&length, hdr, 4);
  if (hdr[4] != T_SHM_HELLO_R || length < 9 || length > (1u << 20)) {
    fprintf(stderr, "shm hello rejected (type %u)\n", hdr[4]);
    return false;
  }
  std::vector<uint8_t> body(length - 9);
  if (!recv_exact(fd, body.data(), body.size())) return false;
  if (body.size() < 13 || body[0] != 1) return false;
  uint32_t req_cap, rep_cap;
  memcpy(&req_cap, body.data() + 1, 4);
  memcpy(&rep_cap, body.data() + 5, 4);
  uint16_t splen;
  memcpy(&splen, body.data() + 9, 2);
  if (body.size() < 11u + splen + 2u) return false;
  std::string shm_path((char*)body.data() + 11, splen);
  uint16_t cplen;
  memcpy(&cplen, body.data() + 11 + splen, 2);
  if (body.size() < 13u + splen + cplen) return false;
  std::string ctrl_path((char*)body.data() + 13 + splen, cplen);

  int sfd = open(shm_path.c_str(), O_RDWR);
  if (sfd < 0) return false;
  L->map_len = (size_t)rlshm::total_bytes(req_cap, rep_cap);
  L->base = (uint8_t*)mmap(nullptr, L->map_len, PROT_READ | PROT_WRITE,
                           MAP_SHARED, sfd, 0);
  close(sfd);
  if (L->base == MAP_FAILED) {
    L->base = nullptr;
    return false;
  }
  if (!rlshm::attach(L->base, /*server=*/false, &L->lane)) return false;

  int cfd = socket(AF_UNIX, SOCK_STREAM, 0);
  if (cfd < 0) return false;
  sockaddr_un a{};
  a.sun_family = AF_UNIX;
  strncpy(a.sun_path, ctrl_path.c_str(), sizeof(a.sun_path) - 1);
  if (connect(cfd, (sockaddr*)&a, sizeof(a)) != 0) {
    close(cfd);
    return false;
  }
  // One data byte + SCM_RIGHTS carrying {efd_server, efd_client}.
  char db;
  iovec iov{&db, 1};
  char cbuf[CMSG_SPACE(2 * sizeof(int))];
  msghdr msg{};
  msg.msg_iov = &iov;
  msg.msg_iovlen = 1;
  msg.msg_control = cbuf;
  msg.msg_controllen = sizeof(cbuf);
  ssize_t r = recvmsg(cfd, &msg, 0);
  close(cfd);
  if (r <= 0) return false;
  cmsghdr* cm = CMSG_FIRSTHDR(&msg);
  if (!cm || cm->cmsg_type != SCM_RIGHTS ||
      cm->cmsg_len < CMSG_LEN(2 * sizeof(int)))
    return false;
  int fds[2];
  memcpy(fds, CMSG_DATA(cm), sizeof(fds));
  L->efd_server = fds[0];
  L->efd_client = fds[1];
  return true;
}

void ding(int efd) {
  uint64_t one = 1;
  ssize_t w = write(efd, &one, 8);
  (void)w;
}

void drain_efd(int efd) {
  uint64_t v;
  ssize_t r = read(efd, &v, 8);
  (void)r;
}

// Blocking push onto the request ring: spin, then advertise
// producer_waiting and park on the client doorbell (the server dings it
// after freeing space). Returns false only past the deadline.
bool shm_send(ShmLane* L, const std::string& f, double t_deadline) {
  const uint8_t* p = (const uint8_t*)f.data();
  uint32_t len = (uint32_t)f.size();
  rlshm::Ring& ring = L->lane.outbound;
  bool pushed = ring.try_push(p, len);
  for (int i = 0; !pushed && i < SHM_SPIN; ++i) pushed = ring.try_push(p, len);
  while (!pushed) {
    ring.set_producer_waiting();
    pushed = ring.try_push(p, len);
    if (pushed) {
      ring.clear_producer_waiting();
      break;
    }
    if (now_s() >= t_deadline) {
      ring.clear_producer_waiting();
      return false;
    }
    pollfd pf{L->efd_client, POLLIN, 0};
    poll(&pf, 1, 50);
    if (pf.revents & POLLIN) drain_efd(L->efd_client);
    ring.clear_producer_waiting();
    pushed = ring.try_push(p, len);
  }
  if (ring.consumer_sleeping()) ding(L->efd_server);
  return true;
}

// Pop every available reply record into rbuf; blocks (spin -> doorbell)
// until at least one arrives or the deadline passes. Returns false on a
// torn ring or deadline.
bool shm_recv(ShmLane* L, std::string* rbuf, double t_deadline) {
  rlshm::Ring& ring = L->lane.inbound;
  size_t got = 0;
  for (;;) {
    const uint8_t* payload;
    uint32_t len;
    rlshm::Ring::PopResult pr = ring.pop(&payload, &len);
    if (pr == rlshm::Ring::POP_RECORD) {
      rbuf->append((const char*)payload, len);
      ring.advance(len);
      ++got;
      continue;
    }
    if (pr == rlshm::Ring::POP_TORN) return false;
    if (got) break;  // drained a burst — parse it
    // Empty: spin, then park on the doorbell.
    bool hit = false;
    for (int i = 0; i < SHM_SPIN; ++i) {
      if (!ring.empty()) {
        hit = true;
        break;
      }
    }
    if (hit) continue;
    ring.set_sleeping();
    if (!ring.empty()) {
      ring.clear_sleeping();
      continue;
    }
    if (now_s() >= t_deadline) {
      ring.clear_sleeping();
      return false;
    }
    pollfd pf{L->efd_client, POLLIN, 0};
    poll(&pf, 1, 50);
    ring.clear_sleeping();
    if (pf.revents & POLLIN) drain_efd(L->efd_client);
  }
  // Freed ring space: wake a backpressured server producer.
  if (ring.producer_waiting()) {
    ring.clear_producer_waiting();
    ding(L->efd_server);
  }
  return true;
}

// Raw pipelined driver: hand-rolled frames on one socket or shm lane
// (the Client class is strictly request/response; pipelining needs
// direct IO).
void worker(const char* host, int port, int inflight, int frame_keys,
            int n_keys, int wid, bool hashed, int affine, int spread,
            Transport tr, Shared* sh) {
  bool uds = tr != TR_TCP ? (host[0] == '/' || strncmp(host, "unix:", 5) == 0)
                          : false;
  if (tr == TR_UDS) uds = true;
  int fd = connect_fd(host, port, uds);
  if (fd < 0) return;

  ShmLane shm;
  bool use_shm = tr == TR_SHM;
  if (use_shm && !shm_upgrade(fd, &shm)) {
    close(fd);
    return;
  }

  auto send_all = [&](const std::string& b) {
    return send_all_fd(fd, b.data(), b.size());
  };

  // Pre-encode a rotating set of ALLOW_BATCH frames.
  uint64_t req_id = 1;
  unsigned rng = 12345u + (unsigned)wid * 2654435761u;
  auto make_frame = [&](double* sent_at) {
    std::string body;
    uint32_t count = (uint32_t)frame_keys;
    body.append((char*)&count, 4);
    if (hashed) {
      // Columnar raw-id frame (ADR-011): u64 ids then u32 ns. With
      // affinity, rejection-sample until the id routes to the
      // connection's `spread`-shard window starting at its home shard
      // (spread=1: the consistent-hash-LB traffic shape; expected
      // `affine / spread` draws per id, LCG draws are ~free).
      bool constrain = affine > 0 && spread < affine;
      uint64_t home = (uint64_t)(wid % (affine > 0 ? affine : 1));
      for (int i = 0; i < frame_keys; ++i) {
        uint64_t id64;
        do {
          rng = rng * 1664525u + 1013904223u;
          id64 = rng % (unsigned)n_keys;
        } while (constrain &&
                 (splitmix64(id64) % (uint64_t)affine + (uint64_t)affine -
                  home) % (uint64_t)affine >= (uint64_t)spread);
        body.append((char*)&id64, 8);
      }
      uint32_t n = 1;
      for (int i = 0; i < frame_keys; ++i) body.append((char*)&n, 4);
    } else {
      for (int i = 0; i < frame_keys; ++i) {
        rng = rng * 1664525u + 1013904223u;
        char key[32];
        int klen =
            snprintf(key, sizeof(key), "user:%u", rng % (unsigned)n_keys);
        uint32_t n = 1;
        uint16_t kl = (uint16_t)klen;
        body.append((char*)&n, 4);
        body.append((char*)&kl, 2);
        body.append(key, klen);
      }
    }
    std::string frame;
    uint32_t length = (uint32_t)(1 + 8 + body.size());
    frame.append((char*)&length, 4);
    frame.push_back(
        (char)(hashed ? rltpu::T_ALLOW_HASHED : rltpu::T_ALLOW_BATCH));
    uint64_t id = req_id++;
    frame.append((char*)&id, 8);
    frame += body;
    *sent_at = now_s();
    return frame;
  };

  // Serialize + wire-write phase meters (timed window only): the A/B
  // that matters for the shm lane is WHERE the per-frame time goes —
  // encoding is transport-invariant, the write phase is not.
  uint64_t local_ser_ns = 0, local_wire_ns = 0, local_timed = 0;

  std::vector<double> sent_at((size_t)inflight + 8, 0.0);
  auto store_sent = [&](double t) { sent_at[(req_id - 1) % sent_at.size()] = t; };

  for (int i = 0; i < inflight; ++i) {
    double t;
    std::string f = make_frame(&t);
    store_sent(t);
    bool ok = use_shm ? shm_send(&shm, f, now_s() + 10.0) : send_all(f);
    if (!ok) {
      close(fd);
      return;
    }
  }

  std::string rbuf;
  char tmp[65536];
  std::vector<double> local_lat;
  uint64_t local_completed = 0, local_allowed = 0;
  while (now_s() < sh->t_stop) {
    if (use_shm) {
      if (!shm_recv(&shm, &rbuf, sh->t_stop)) break;
    } else {
      ssize_t r = recv(fd, tmp, sizeof(tmp), 0);
      if (r <= 0) break;
      rbuf.append(tmp, (size_t)r);
    }
    size_t off = 0;
    while (rbuf.size() - off >= 13) {
      uint32_t length;
      memcpy(&length, rbuf.data() + off, 4);
      if (rbuf.size() - off < 4 + length) break;
      uint8_t type = (uint8_t)rbuf[off + 4];
      uint64_t rid;
      memcpy(&rid, rbuf.data() + off + 5, 8);
      if (type == rltpu::T_RESULT_BATCH || type == rltpu::T_RESULT_HASHED) {
        const char* body = rbuf.data() + off + 13;
        uint32_t count;
        // RESULT_BATCH: i64 limit | u32 count | 25B items.
        // RESULT_HASHED: u8 flags | i64 limit | u32 count | bit mask |
        // columnar i64/f64/f64.
        bool h = type == rltpu::T_RESULT_HASHED;
        memcpy(&count, body + (h ? 9 : 8), 4);
        double t1 = now_s();
        bool timed = t1 >= sh->t_measure;
        if (timed) {
          local_completed += count;
          if (h) {
            const uint8_t* bits = (const uint8_t*)body + 13;
            for (uint32_t i = 0; i < count; ++i)
              local_allowed += (bits[i >> 3] >> (i & 7)) & 1;
          } else {
            const char* items = body + 12;
            for (uint32_t i = 0; i < count; ++i)
              local_allowed += (uint8_t)items[i * 25] & 1;
          }
          double t0 = sent_at[rid % sent_at.size()];
          if (t0 > 0) local_lat.push_back(t1 - t0);
        }
        if (now_s() < sh->t_stop) {
          double ts0 = now_s();
          double t;
          std::string f = make_frame(&t);
          double ts1 = now_s();
          store_sent(t);
          bool ok =
              use_shm ? shm_send(&shm, f, sh->t_stop + 5.0) : send_all(f);
          double ts2 = now_s();
          if (timed) {
            local_ser_ns += (uint64_t)((ts1 - ts0) * 1e9);
            local_wire_ns += (uint64_t)((ts2 - ts1) * 1e9);
            ++local_timed;
          }
          if (!ok) break;
        }
      }
      off += 4 + length;
    }
    if (off) rbuf.erase(0, off);
  }
  close(fd);
  sh->completed.fetch_add(local_completed);
  sh->allowed.fetch_add(local_allowed);
  sh->ser_ns.fetch_add(local_ser_ns);
  sh->wire_ns.fetch_add(local_wire_ns);
  sh->timed_frames.fetch_add(local_timed);
  std::lock_guard<std::mutex> g(sh->lat_mx);
  sh->latencies.insert(sh->latencies.end(), local_lat.begin(),
                       local_lat.end());
}

}  // namespace

int main(int argc, char** argv) {
  // Pull --transport out before positional parsing (it can sit anywhere).
  Transport tr = TR_TCP;
  std::vector<char*> pos;
  for (int i = 0; i < argc; ++i) {
    if (i > 0 && std::strcmp(argv[i], "--transport") == 0 && i + 1 < argc) {
      const char* v = argv[++i];
      if (std::strcmp(v, "uds") == 0)
        tr = TR_UDS;
      else if (std::strcmp(v, "shm") == 0)
        tr = TR_SHM;
      else if (std::strcmp(v, "tcp") != 0) {
        std::fprintf(stderr, "unknown transport %s\n", v);
        return 2;
      }
      continue;
    }
    pos.push_back(argv[i]);
  }
  int pargc = (int)pos.size();
  if (pargc < 8 || pargc > 11) {
    std::fprintf(stderr,
                 "usage: %s <host> <port> <seconds> <threads> <inflight> "
                 "<keys_per_frame> <n_keys> [batch|hashed] "
                 "[affine_shards] [spread] [--transport tcp|uds|shm]\n",
                 pos[0]);
    return 2;
  }
  const char* host = pos[1];
  int port = atoi(pos[2]);
  double seconds = atof(pos[3]);
  int threads = atoi(pos[4]);
  int inflight = atoi(pos[5]);
  int frame_keys = atoi(pos[6]);
  int n_keys = atoi(pos[7]);
  bool hashed = pargc >= 9 && std::strcmp(pos[8], "hashed") == 0;
  int affine = (pargc >= 10 && hashed) ? atoi(pos[9]) : 0;
  int spread = (pargc >= 11 && hashed) ? atoi(pos[10]) : 1;
  if (spread < 1) spread = 1;

  Shared sh;
  double warmup = 1.0;
  sh.t_measure = now_s() + warmup;
  sh.t_stop = sh.t_measure + seconds;

  std::vector<std::thread> ts;
  for (int i = 0; i < threads; ++i)
    ts.emplace_back(worker, host, port, inflight, frame_keys, n_keys, i,
                    hashed, affine, spread, tr, &sh);
  for (auto& t : ts) t.join();

  double span = seconds;
  std::vector<double>& lat = sh.latencies;
  std::sort(lat.begin(), lat.end());
  auto pct = [&](double p) {
    if (lat.empty()) return 0.0;
    return lat[std::min(lat.size() - 1, (size_t)(p * lat.size()))] * 1e3;
  };
  uint64_t tf = sh.timed_frames.load();
  double ser_us = tf ? (double)sh.ser_ns.load() / tf / 1e3 : 0.0;
  double wire_us = tf ? (double)sh.wire_ns.load() / tf / 1e3 : 0.0;
  const char* trs = tr == TR_SHM ? "shm" : (tr == TR_UDS ? "uds" : "tcp");
  std::printf(
      "{\"decisions_per_sec\": %.1f, \"completed\": %llu, "
      "\"allowed\": %llu, \"frame_p50_ms\": %.2f, \"frame_p99_ms\": %.2f, "
      "\"threads\": %d, \"inflight_frames\": %d, \"keys_per_frame\": %d, "
      "\"mode\": \"%s\", \"affine_shards\": %d, \"spread\": %d, "
      "\"transport\": \"%s\", \"serialize_us_per_frame\": %.3f, "
      "\"wire_write_us_per_frame\": %.3f}\n",
      (double)sh.completed.load() / span,
      (unsigned long long)sh.completed.load(),
      (unsigned long long)sh.allowed.load(), pct(0.50), pct(0.99), threads,
      inflight, frame_keys, hashed ? "hashed" : "batch", affine, spread, trs,
      ser_us, wire_us);
  return 0;
}
