// Conformance demo/test driver for the C++ client.
//
// Drives a live server through every RPC and prints one status line per
// check; tests/test_cpp_client.py builds this with g++ and asserts the
// output against a real Python server process.
//
// Usage: rltpu_demo <host> <port>

#include <cstdio>
#include <cstdlib>

#include "ratelimiter_client.hpp"

#define CHECK(cond, name)                              \
  do {                                                 \
    if (cond) {                                        \
      std::printf("ok %s\n", name);                    \
    } else {                                           \
      std::printf("FAIL %s\n", name);                  \
      return 1;                                        \
    }                                                  \
  } while (0)

int main(int argc, char** argv) {
  if (argc != 3) {
    std::fprintf(stderr, "usage: %s <host> <port>\n", argv[0]);
    return 2;
  }
  rltpu::Client c(argv[1], static_cast<uint16_t>(std::atoi(argv[2])));

  // Health before traffic.
  auto h = c.health();
  CHECK(h.serving, "health.serving");

  // Scalar allow up to the limit (server started with limit=3).
  auto r1 = c.allow("cpp:user");
  CHECK(r1.allowed && r1.limit == 3 && r1.remaining == 2, "allow#1");
  auto r2 = c.allow_n("cpp:user", 2);
  CHECK(r2.allowed && r2.remaining == 0, "allow_n#2");
  auto r3 = c.allow("cpp:user");
  CHECK(!r3.allowed && r3.retry_after > 0.0, "deny-over-limit");

  // Reset restores quota.
  c.reset("cpp:user");
  CHECK(c.allow("cpp:user").allowed, "reset-restores");

  // Batch frame: duplicates contend in order.
  std::vector<std::string> keys = {"cpp:hot", "cpp:hot", "cpp:hot",
                                   "cpp:hot", "cpp:other"};
  auto batch = c.allow_batch(keys);
  CHECK(batch.size() == 5, "batch-size");
  CHECK(batch[0].allowed && batch[1].allowed && batch[2].allowed &&
            !batch[3].allowed && batch[4].allowed,
        "batch-exactness");

  // Typed errors: n = 0 must raise with the invalid_n code.
  bool raised = false;
  try {
    c.allow_n("cpp:user", 0);
  } catch (const rltpu::RateLimitError& e) {
    raised = (e.code == 1);  // E_INVALID_N
  }
  CHECK(raised, "invalid-n-typed-error");
  // The connection survives an error response.
  CHECK(c.allow("cpp:alive").allowed, "connection-survives-error");

  // Metrics exposition reaches the client.
  auto m = c.metrics();
  CHECK(m.find("rate_limiter_server_batch_size") != std::string::npos,
        "metrics-text");

  auto h2 = c.health();
  CHECK(h2.decisions_total > h.decisions_total, "health-counts");

  std::printf("ALL-OK\n");
  return 0;
}
