// C++ client for the ratelimiter_tpu serving protocol.
//
// The reference plans a client library (pkg/client placeholder,
// ROADMAP.md); this is the native-code counterpart of the Python client
// (ratelimiter_tpu/serving/client.py), speaking the same length-prefixed
// little-endian protocol (serving/protocol.py documents the frames).
//
// Header-only, POSIX sockets, no dependencies:
//
//   #include "ratelimiter_client.hpp"
//   rltpu::Client c("127.0.0.1", 8432);
//   auto r = c.allow("user:1");
//   if (!r.allowed) backoff(r.retry_after);
//
// Thread safety: one Client per thread (or external locking) — same
// contract as the Python blocking client. Errors surface as
// rltpu::RateLimitError with the server's error code preserved, so
// callers can distinguish invalid_n from storage_unavailable.
//
// Build: header-only; demo/test binary via `make cpp-client`.

#pragma once

#include <arpa/inet.h>
#include <netdb.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <unistd.h>

#include <cstdint>
#include <cstring>
#include <stdexcept>
#include <string>
#include <vector>

namespace rltpu {

// Protocol constants (serving/protocol.py).
enum : uint8_t {
  T_ALLOW_N = 1,
  T_RESET = 2,
  T_HEALTH = 3,
  T_METRICS = 4,
  T_ALLOW_BATCH = 5,
  T_ALLOW_HASHED = 11,
  T_RESULT = 129,
  T_OK = 130,
  T_HEALTH_R = 131,
  T_METRICS_R = 132,
  T_RESULT_BATCH = 133,
  T_RESULT_HASHED = 136,
  T_ERROR = 255,
};

struct Result {
  bool allowed = false;
  bool fail_open = false;
  int64_t limit = 0;
  int64_t remaining = 0;
  double retry_after = 0.0;
  double reset_at = 0.0;
};

struct Health {
  bool serving = false;
  double uptime_s = 0.0;
  uint64_t decisions_total = 0;
};

class RateLimitError : public std::runtime_error {
 public:
  RateLimitError(uint16_t code, const std::string& msg)
      : std::runtime_error(msg), code(code) {}
  uint16_t code;  // protocol.py E_* values
};

class ProtocolError : public std::runtime_error {
  using std::runtime_error::runtime_error;
};

class Client {
 public:
  // `host` may be a unix socket path — "unix:/run/rl.sock" or any
  // leading-slash path (port ignored) — for the same-host UDS listener
  // (ADR-025); otherwise it resolves as an IPv4 host.
  Client(const std::string& host, uint16_t port) : req_id_(0) {
    if (host.rfind("unix:", 0) == 0 || (!host.empty() && host[0] == '/')) {
      std::string path = host.rfind("unix:", 0) == 0 ? host.substr(5) : host;
      fd_ = ::socket(AF_UNIX, SOCK_STREAM, 0);
      struct sockaddr_un a {};
      a.sun_family = AF_UNIX;
      if (fd_ < 0 || path.size() >= sizeof(a.sun_path))
        throw ProtocolError("bad unix socket path " + path);
      std::memcpy(a.sun_path, path.c_str(), path.size());
      if (::connect(fd_, reinterpret_cast<sockaddr*>(&a), sizeof(a)) != 0) {
        ::close(fd_);
        throw ProtocolError("connect failed to " + path);
      }
      return;
    }
    struct addrinfo hints{}, *res = nullptr;
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    std::string port_s = std::to_string(port);
    if (getaddrinfo(host.c_str(), port_s.c_str(), &hints, &res) != 0)
      throw ProtocolError("getaddrinfo failed for " + host);
    fd_ = ::socket(res->ai_family, res->ai_socktype, res->ai_protocol);
    if (fd_ < 0 || ::connect(fd_, res->ai_addr, res->ai_addrlen) != 0) {
      freeaddrinfo(res);
      if (fd_ >= 0) ::close(fd_);
      throw ProtocolError("connect failed to " + host + ":" + port_s);
    }
    freeaddrinfo(res);
    int one = 1;
    setsockopt(fd_, IPPROTO_TCP, /*TCP_NODELAY=*/1, &one, sizeof(one));
  }

  ~Client() {
    if (fd_ >= 0) ::close(fd_);
  }
  Client(const Client&) = delete;
  Client& operator=(const Client&) = delete;

  Result allow(const std::string& key) { return allow_n(key, 1); }

  Result allow_n(const std::string& key, uint32_t n) {
    std::vector<uint8_t> body;
    put_u32(body, n);
    put_key(body, key);
    auto [type, resp] = roundtrip(T_ALLOW_N, body);
    if (type != T_RESULT) throw ProtocolError("unexpected response type");
    return parse_result(resp.data(), resp.size());
  }

  // One ALLOW_BATCH frame; results in request order.
  std::vector<Result> allow_batch(const std::vector<std::string>& keys,
                                  const std::vector<uint32_t>* ns = nullptr) {
    std::vector<uint8_t> body;
    put_u32(body, static_cast<uint32_t>(keys.size()));
    for (size_t i = 0; i < keys.size(); ++i) {
      put_u32(body, ns ? (*ns)[i] : 1u);
      put_key(body, keys[i]);
    }
    auto [type, resp] = roundtrip(T_ALLOW_BATCH, body);
    if (type != T_RESULT_BATCH) throw ProtocolError("unexpected response type");
    const uint8_t* p = resp.data();
    size_t len = resp.size();
    if (len < 12) throw ProtocolError("short RESULT_BATCH");
    int64_t limit = get_i64(p);
    uint32_t count = get_u32(p + 8);
    p += 12;
    len -= 12;
    std::vector<Result> out;
    out.reserve(count);
    for (uint32_t i = 0; i < count; ++i) {
      if (len < 25) throw ProtocolError("truncated RESULT_BATCH item");
      Result r;
      r.allowed = p[0] & 1;
      r.fail_open = p[0] & 2;
      r.limit = limit;
      r.remaining = get_i64(p + 1);
      r.retry_after = get_f64(p + 9);
      r.reset_at = get_f64(p + 17);
      out.push_back(r);
      p += 25;
      len -= 25;
    }
    return out;
  }

  void reset(const std::string& key) {
    std::vector<uint8_t> body;
    put_key(body, key);
    auto [type, resp] = roundtrip(T_RESET, body);
    (void)resp;
    if (type != T_OK) throw ProtocolError("unexpected response type");
  }

  Health health() {
    auto [type, resp] = roundtrip(T_HEALTH, {});
    if (type != T_HEALTH_R || resp.size() < 17)
      throw ProtocolError("bad HEALTH response");
    Health h;
    h.serving = resp[0] == 1;
    h.uptime_s = get_f64(resp.data() + 1);
    std::memcpy(&h.decisions_total, resp.data() + 9, 8);
    return h;
  }

  std::string metrics() {
    auto [type, resp] = roundtrip(T_METRICS, {});
    if (type != T_METRICS_R || resp.size() < 4)
      throw ProtocolError("bad METRICS response");
    uint32_t n = get_u32(resp.data());
    return std::string(reinterpret_cast<const char*>(resp.data()) + 4, n);
  }

 private:
  int fd_;
  uint64_t req_id_;

  // ---- little-endian packing helpers (x86/ARM-LE hosts) ----
  static void put_u32(std::vector<uint8_t>& b, uint32_t v) {
    b.insert(b.end(), reinterpret_cast<uint8_t*>(&v),
             reinterpret_cast<uint8_t*>(&v) + 4);
  }
  static void put_u16(std::vector<uint8_t>& b, uint16_t v) {
    b.insert(b.end(), reinterpret_cast<uint8_t*>(&v),
             reinterpret_cast<uint8_t*>(&v) + 2);
  }
  static void put_key(std::vector<uint8_t>& b, const std::string& k) {
    put_u16(b, static_cast<uint16_t>(k.size()));
    b.insert(b.end(), k.begin(), k.end());
  }
  static uint32_t get_u32(const uint8_t* p) {
    uint32_t v;
    std::memcpy(&v, p, 4);
    return v;
  }
  static int64_t get_i64(const uint8_t* p) {
    int64_t v;
    std::memcpy(&v, p, 8);
    return v;
  }
  static double get_f64(const uint8_t* p) {
    double v;
    std::memcpy(&v, p, 8);
    return v;
  }

  void send_all(const uint8_t* p, size_t n) {
    while (n) {
      ssize_t w = ::send(fd_, p, n, 0);
      if (w <= 0) throw ProtocolError("send failed");
      p += w;
      n -= static_cast<size_t>(w);
    }
  }
  void recv_all(uint8_t* p, size_t n) {
    while (n) {
      ssize_t r = ::recv(fd_, p, n, 0);
      if (r <= 0) throw ProtocolError("connection closed by server");
      p += r;
      n -= static_cast<size_t>(r);
    }
  }

  std::pair<uint8_t, std::vector<uint8_t>> roundtrip(
      uint8_t type, const std::vector<uint8_t>& body) {
    uint64_t id = ++req_id_;
    std::vector<uint8_t> frame;
    put_u32(frame, static_cast<uint32_t>(1 + 8 + body.size()));
    frame.push_back(type);
    frame.insert(frame.end(), reinterpret_cast<uint8_t*>(&id),
                 reinterpret_cast<uint8_t*>(&id) + 8);
    frame.insert(frame.end(), body.begin(), body.end());
    send_all(frame.data(), frame.size());

    uint8_t hdr[13];
    recv_all(hdr, 13);
    uint32_t length = get_u32(hdr);
    uint8_t rtype = hdr[4];
    uint64_t rid;
    std::memcpy(&rid, hdr + 5, 8);
    if (length < 9 || length > (1u << 20))
      throw ProtocolError("bad frame length");
    std::vector<uint8_t> resp(length - 9);
    recv_all(resp.data(), resp.size());
    if (rid != id) throw ProtocolError("response id mismatch");
    if (rtype == T_ERROR) {
      if (resp.size() < 4) throw ProtocolError("short ERROR frame");
      uint16_t code, mlen;
      std::memcpy(&code, resp.data(), 2);
      std::memcpy(&mlen, resp.data() + 2, 2);
      throw RateLimitError(
          code, std::string(reinterpret_cast<char*>(resp.data()) + 4, mlen));
    }
    return {rtype, std::move(resp)};
  }

  static Result parse_result(const uint8_t* p, size_t len) {
    if (len < 33) throw ProtocolError("short RESULT frame");
    Result r;
    r.allowed = p[0] & 1;
    r.fail_open = p[0] & 2;
    r.limit = get_i64(p + 1);
    r.remaining = get_i64(p + 9);
    r.retry_after = get_f64(p + 17);
    r.reset_at = get_f64(p + 25);
    return r;
  }
};

}  // namespace rltpu
