"""Multi-ring network engine tests (ISSUE-20, ADR-026).

The native door's wire path is N sharded io rings behind one NetEngine
interface with two backends: portable epoll and raw-syscall io_uring
selected by a startup probe. These tests pin the properties the PR
promises:

* engine PARITY — the reply byte stream is bit-identical across
  backends and ring counts (same pin as tcp==uds==shm in ADR-025);
* the io_uring path NEVER silently skips — when the kernel (or
  seccomp) refuses the probe, the server records an asserted
  downgrade in stats()["net"] and serves on epoll, and the test
  asserts THAT record instead of skipping;
* robustness — kill -9 / RST mid-frame, slow-loris partial frames
  spread across ring shards, one firehose connection cannot starve
  the ring (bounded read budget per wakeup);
* reply coalescing — the writev_frames / writev_calls counters prove
  frames ride vectored writes, and the scatter-gather encoder for
  T_RESULT_BATCH is byte-identical to the joined form by construction.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from contextlib import contextmanager

import pytest

from ratelimiter_tpu import Algorithm, Config, ManualClock, create_limiter
from ratelimiter_tpu.serving import Client
from ratelimiter_tpu.serving import protocol as p
from ratelimiter_tpu.serving.native_server import (
    NativeRateLimitServer,
    native_server_available,
)

needs_native = pytest.mark.skipif(
    not native_server_available(), reason="needs g++ for the native server")


def _mk_limiter(limit=100, window=60.0, backend="exact", **kw):
    clock = ManualClock(1_700_000_000.0)
    cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=limit,
                 window=window, **kw)
    return create_limiter(cfg, backend=backend, clock=clock), clock


@contextmanager
def running_native(limiter, host="127.0.0.1", **kw):
    srv = NativeRateLimitServer(limiter, host, 0, **kw)
    srv.start()
    try:
        yield srv
    finally:
        srv.shutdown()


def _net(srv) -> dict:
    return srv.transport_stats()["net"]


def _assert_engine_record(net: dict, requested: str) -> None:
    """The probe contract: an explicit uring request either runs uring
    (probe passed) or serves on epoll with the refusal RECORDED — the
    caller asserts the record, never skips."""
    assert net["rings"] >= 1
    if requested == "epoll":
        assert net["engine"] == "epoll"
        assert net["uring_probe"] == "off"
        return
    assert net["uring_probe"] in ("pass", "fail")
    if net["uring_probe"] == "pass":
        assert net["engine"] == "uring"
    else:
        assert net["engine"] == "epoll"
        assert net["uring_probe_err"], (
            "a failed probe must say WHY (seccomp/ENOSYS/...)")


def _read_frame(sock: socket.socket) -> bytes:
    hdr = b""
    while len(hdr) < 4:
        d = sock.recv(4 - len(hdr))
        assert d, "unexpected EOF mid-header"
        hdr += d
    (length,) = struct.unpack("<I", hdr)
    body = b""
    while len(body) < length:
        d = sock.recv(length - len(body))
        assert d, "unexpected EOF mid-frame"
        body += d
    return hdr + body


# --------------------------------------------------- engine selection

@needs_native
class TestEngineSelection:
    def test_epoll_single_ring_pre_pr_shape(self):
        """--net-engine epoll --io-rings 1 is the pre-ISSUE-20 wire
        topology: one event loop, no probe run at all."""
        lim, _ = _mk_limiter()
        with running_native(lim, net_engine="epoll", io_rings=1) as srv:
            with Client(port=srv.port) as c:
                assert c.allow("k").allowed
            net = _net(srv)
            assert net == {**net, "engine": "epoll", "rings": 1,
                           "uring_probe": "off"}
            assert net["recv_calls"] > 0 and net["wait_calls"] > 0
        lim.close()

    def test_uring_request_never_silently_skips(self):
        lim, _ = _mk_limiter()
        with running_native(lim, net_engine="uring", io_rings=2) as srv:
            net = _net(srv)
            _assert_engine_record(net, "uring")
            assert net["rings"] == 2
            with Client(port=srv.port) as c:
                assert c.allow("k").allowed
                assert not all(c.allow("k").allowed for _ in range(200))
        lim.close()

    def test_auto_records_probe_result(self):
        lim, _ = _mk_limiter()
        with running_native(lim, net_engine="auto") as srv:
            _assert_engine_record(_net(srv), "auto")
            with Client(port=srv.port) as c:
                assert c.allow("k").allowed
        lim.close()

    def test_invalid_engine_rejected(self):
        lim, _ = _mk_limiter()
        with pytest.raises(ValueError, match="net_engine"):
            NativeRateLimitServer(lim, "127.0.0.1", 0,
                                  net_engine="kqueue")
        lim.close()

    def test_healthz_surface_carries_engine(self):
        lim, _ = _mk_limiter()
        with running_native(lim, net_engine="auto", io_rings=2) as srv:
            st = srv.transport_stats()
            assert st["net"]["rings"] == 2
            assert st["net"]["engine"] in ("epoll", "uring")
        lim.close()


# ------------------------------------------------------- byte parity

@needs_native
class TestEngineParity:
    """Frame-for-frame bit-identical reply streams across backends and
    ring counts, driven lockstep so ordering is deterministic. The
    uring variant runs EVEN when the kernel refuses io_uring — the
    server downgrades with an asserted record (see
    _assert_engine_record), so the parity pin holds on every box with
    zero skips."""

    SCRIPT = None  # built once per run

    @classmethod
    def _script(cls):
        if cls.SCRIPT is None:
            frames = []
            for i in range(12):
                frames.append(p.encode_allow_n(i + 1, f"key{i % 3}", 1))
            frames.append(p.encode_allow_batch(
                100, ["alpha", "beta", "gamma"], [2, 1, 3]))
            frames.append(p.encode_reset(101, "key0"))
            for i in range(6):
                frames.append(p.encode_allow_n(200 + i, "post-reset", 2))
            cls.SCRIPT = frames
        return cls.SCRIPT

    def _reply_stream(self, net_engine: str, io_rings: int) -> tuple:
        lim, _ = _mk_limiter(limit=10)
        try:
            with running_native(lim, net_engine=net_engine,
                                io_rings=io_rings) as srv:
                out = []
                with socket.create_connection(("127.0.0.1", srv.port),
                                              timeout=10) as s:
                    s.settimeout(10)
                    for frame in self._script():
                        s.sendall(frame)
                        out.append(_read_frame(s))
                return b"".join(out), _net(srv)
        finally:
            lim.close()

    def test_reply_bytes_identical_across_engines(self):
        base, base_net = self._reply_stream("epoll", 1)
        assert base_net["engine"] == "epoll"
        multi, _ = self._reply_stream("epoll", 4)
        uring, uring_net = self._reply_stream("uring", 3)
        _assert_engine_record(uring_net, "uring")
        assert multi == base, "ring sharding changed wire bytes"
        assert uring == base, (
            f"io_uring backend changed wire bytes "
            f"(engine={uring_net['engine']})")
        # The pinned stream is not vacuous: allows, denies, a batch
        # result, and an OK all appear.
        assert len(base) > 20 * 13


# -------------------------------------------------------- robustness

@needs_native
class TestRobustness:
    @pytest.mark.parametrize("net_engine", ["epoll", "uring"])
    def test_client_death_mid_frame(self, net_engine):
        """A client dying mid-frame — orderly FIN (kill -9: the kernel
        closes the fd) or hard RST (SO_LINGER 0) — must not wedge the
        ring: the half-frame is dropped with the connection and new
        clients are served."""
        lim, _ = _mk_limiter(limit=100000)
        with running_native(lim, net_engine=net_engine,
                            io_rings=2) as srv:
            frame = p.encode_allow_n(7, "victim", 1)
            # FIN mid-frame.
            s1 = socket.create_connection(("127.0.0.1", srv.port),
                                          timeout=10)
            s1.sendall(frame[:len(frame) // 2])
            s1.close()
            # RST mid-frame.
            s2 = socket.create_connection(("127.0.0.1", srv.port),
                                          timeout=10)
            s2.sendall(frame[:len(frame) // 2])
            s2.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                          struct.pack("ii", 1, 0))
            s2.close()
            deadline = time.time() + 10
            while time.time() < deadline:
                with Client(port=srv.port) as c:
                    if c.allow("survivor").allowed:
                        break
                time.sleep(0.05)
            else:
                pytest.fail("server stopped answering after mid-frame "
                            "client death")
        lim.close()

    def test_slow_loris_across_ring_shards(self):
        """Byte-at-a-time senders spread over 4 rings: every dribbled
        frame is eventually answered, and a well-behaved client on the
        same server stays fast throughout."""
        lim, _ = _mk_limiter(limit=100000)
        with running_native(lim, net_engine="epoll", io_rings=4) as srv:
            results = {}

            def loris(idx: int):
                frame = p.encode_allow_n(idx, f"loris{idx}", 1)
                with socket.create_connection(
                        ("127.0.0.1", srv.port), timeout=15) as s:
                    s.settimeout(15)
                    for b in frame:
                        s.sendall(bytes([b]))
                        time.sleep(0.002)
                    results[idx] = _read_frame(s)

            threads = [threading.Thread(target=loris, args=(i,))
                       for i in range(8)]
            for t in threads:
                t.start()
            # The fast lane stays fast while 8 loris conns dribble.
            t0 = time.time()
            with Client(port=srv.port) as c:
                for _ in range(20):
                    assert c.allow("fast").allowed
            fast_elapsed = time.time() - t0
            for t in threads:
                t.join(timeout=30)
            assert len(results) == 8, "a dribbled frame went unanswered"
            assert fast_elapsed < 5.0, (
                f"well-behaved client stalled {fast_elapsed:.1f}s "
                "behind slow-loris peers")
        lim.close()

    def test_firehose_cannot_starve_the_ring(self):
        """One connection pipelining a huge burst must not starve a
        neighbour pinned to the same ring (per-wakeup read budget)."""
        lim, _ = _mk_limiter(limit=1000000)
        with running_native(lim, net_engine="epoll", io_rings=1,
                            max_batch=4096) as srv:
            hose = socket.create_connection(("127.0.0.1", srv.port),
                                            timeout=10)
            hose.settimeout(10)
            burst = b"".join(p.encode_allow_n(i, "hose", 1)
                             for i in range(2000))
            hose.sendall(burst)
            t0 = time.time()
            with Client(port=srv.port) as c:
                assert c.allow("neighbour").allowed
            assert time.time() - t0 < 5.0, "firehose starved the ring"
            # The hose still gets every reply (nothing dropped).
            got = 0
            buf = b""
            while got < 2000:
                d = hose.recv(1 << 16)
                assert d, "EOF before all firehose replies"
                buf += d
                while len(buf) >= 4:
                    (ln,) = struct.unpack_from("<I", buf)
                    if len(buf) < 4 + ln:
                        break
                    buf = buf[4 + ln:]
                    got += 1
            hose.close()
        lim.close()


# ------------------------------------------------- vectored replies

@needs_native
class TestWritevCoalescing:
    def test_writev_frames_counter_proves_batching(self):
        """Pipelined burst on one connection: every reply frame rides a
        vectored write (writev_frames counts them) and frames outnumber
        sendmsg calls — the batch factor the
        rate_limiter_net_writev_frames metric exports."""
        lim, _ = _mk_limiter(limit=1000000)
        with running_native(lim, net_engine="epoll", io_rings=1,
                            max_batch=512, max_delay=0.005) as srv:
            n = 300
            with socket.create_connection(("127.0.0.1", srv.port),
                                          timeout=10) as s:
                s.settimeout(10)
                s.sendall(b"".join(p.encode_allow_n(i, "burst", 1)
                                   for i in range(n)))
                for _ in range(n):
                    _read_frame(s)
            net = _net(srv)
            assert net["writev_frames"] >= n
            assert net["writev_calls"] >= 1
            assert net["writev_calls"] < net["writev_frames"], (
                "no coalescing happened: every frame paid its own "
                "write syscall")
        lim.close()


class TestBatchViewsEncoder:
    def test_views_join_is_the_single_buffer_frame(self):
        """The scatter-gather T_RESULT_BATCH encoder IS the framing
        source: joining its parts must reproduce encode_result_batch
        byte-for-byte (the asyncio door's writelines path cannot
        drift), and the parts round-trip through the parser."""
        results = [p.Result(allowed=(i % 3 != 0), limit=50,
                            remaining=50 - i, retry_after=0.5 * i,
                            reset_at=1e9 + i, fail_open=(i == 4))
                   for i in range(9)]
        views = p.encode_result_batch_views(41, 50, results)
        assert len(views) == 1 + len(results)
        joined = b"".join(views)
        assert joined == p.encode_result_batch(41, 50, results)
        length, type_, req_id = struct.unpack_from("<IBQ", joined)
        assert type_ == p.T_RESULT_BATCH and req_id == 41
        parsed = p.parse_result_batch(joined[13:])
        assert [r.allowed for r in parsed] == [
            r.allowed for r in results]
        assert [r.fail_open for r in parsed] == [
            r.fail_open for r in results]


# ------------------------------------------------ shm over the rings

@needs_native
class TestShmOverEngines:
    def test_shm_handshake_over_uring(self):
        """The shm ctrl listener and doorbell eventfds ride the owning
        ring on EVERY backend: the full hello → ctrl connect → fd-pass
        handshake and ring traffic must work with the uring engine (or
        its asserted epoll downgrade) exactly as on epoll."""
        lim, _ = _mk_limiter(limit=100000)
        with running_native(lim, shm=True, net_engine="uring",
                            io_rings=2) as srv:
            _assert_engine_record(_net(srv), "uring")
            with Client(port=srv.port, transport="shm") as c:
                for i in range(10):
                    assert c.allow(f"k{i}").allowed
                res = c.allow_batch(["x", "y"], [2, 3])
                assert all(r.allowed for r in res)
            st = srv.transport_stats()
            assert st["connections"]["shm"] == 1
            assert st["shm"]["records_in"] >= 11
        lim.close()
