"""Contract suite instantiated for the sketch backend, plus sketch-specific
behavior (memory constancy, collision direction, sub-window sliding).

The sketch is approximate in general, but with few keys and width 65536 the
contract scenarios have no collisions, so the full exact suite runs unskipped
(exact_admission stays True here; accuracy under load is measured separately
in test_accuracy.py)."""

import numpy as np
import pytest

from tests.contract import ContractTests

from ratelimiter_tpu import (
    Algorithm,
    Config,
    ManualClock,
    SketchParams,
    create_limiter,
)

SKETCH_ALGOS = [Algorithm.SLIDING_WINDOW, Algorithm.FIXED_WINDOW, Algorithm.TPU_SKETCH]


class TestSketchContract(ContractTests):
    backend = "sketch"
    algorithms = SKETCH_ALGOS
    supports_failure_injection = True

    def inject_failure(self, lim) -> None:
        lim.inject_failure()


def make(algo=Algorithm.TPU_SKETCH, limit=100, window=60.0, start=1_700_000_000.0,
         sketch=None, **kw):
    clock = ManualClock(start)
    cfg = Config(algorithm=algo, limit=limit, window=window,
                 sketch=sketch or SketchParams(), **kw)
    return create_limiter(cfg, backend="sketch", clock=clock), clock


class TestSketchBehavior:
    def test_memory_constant_in_keys(self):
        lim, _ = make(sketch=SketchParams(depth=4, width=1024, sub_windows=10))
        before = lim.memory_bytes()
        out = lim.allow_hashed(np.arange(5000, dtype=np.uint64))
        assert out.allow_count == 5000
        assert lim.memory_bytes() == before  # no per-key state at all
        lim.close()

    def test_sub_window_sliding_smooths_burst(self):
        # 60 sub-windows of 1s: a burst at t=59.5 still weighs ~1 at t=60.2
        lim, clock = make(limit=100, window=60.0, start=0.0)
        clock.set(59.5)
        assert lim.allow_n("k", 100).allowed
        clock.set(60.2)
        assert not lim.allow("k").allowed  # old burst still in window
        clock.set(125.0)  # > 2 windows later: fully decayed
        assert lim.allow("k").allowed
        lim.close()

    def test_decay_is_gradual_not_cliff(self):
        # With sliding sub-windows, quota returns progressively as the burst
        # ages out of the window, not all at once at the window boundary.
        lim, clock = make(limit=60, window=60.0, start=0.0)
        clock.set(30.0)
        assert lim.allow_n("k", 60).allowed
        clock.set(89.0)
        r1 = lim.allow_n("k", 60)
        assert not r1.allowed           # t-window=29 < 30: burst still counted
        clock.set(91.5)
        r2 = lim.allow_n("k", 20)
        assert r2.allowed               # burst sub-window aged out of [31.5, 91.5]
        lim.close()

    def test_overestimate_never_over_admits(self):
        # Force heavy collisions (width 16): errors must appear as extra
        # denies, never extra allows.
        lim, _ = make(limit=10, window=10.0,
                      sketch=SketchParams(depth=2, width=16, sub_windows=10))
        h = np.arange(200, dtype=np.uint64)
        out = lim.allow_hashed(h)
        # 200 distinct keys, limit 10 each: without collisions all 200 pass;
        # with collisions some are falsely denied. Over-admission impossible.
        assert out.allow_count <= 200
        per_key_second = lim.allow_hashed(h, ns=np.full(200, 11, dtype=np.int64))
        assert per_key_second.allow_count == 0  # n > limit never admitted
        lim.close()

    def test_reset_errs_toward_allowing(self):
        lim, _ = make(limit=5, window=10.0)
        for _ in range(5):
            assert lim.allow("a").allowed
        assert not lim.allow("a").allowed
        lim.reset("a")
        assert lim.allow("a").allowed
        lim.close()

    def test_prefix_namespaces_sketch(self):
        # Same key under different prefixes must not share counters.
        lim1, c1 = make(limit=3, window=60.0, key_prefix="app1")
        lim2, c2 = make(limit=3, window=60.0, key_prefix="app2")
        for _ in range(3):
            assert lim1.allow("user").allowed
        assert not lim1.allow("user").allowed
        assert lim2.allow("user").allowed  # independent namespace
        lim1.close()
        lim2.close()

    def test_hashed_and_string_paths_agree(self):
        from ratelimiter_tpu.ops.hashing import hash_strings_u64

        lim, _ = make(limit=4, window=60.0, key_prefix="")
        h = hash_strings_u64(["user:7"])
        for _ in range(4):
            assert lim.allow_hashed(h).allow_count == 1
        # Fifth through the string path: same counters, so denied.
        assert not lim.allow("user:7").allowed
        lim.close()

    def test_fixed_window_mode_resets_at_boundary(self):
        lim, clock = make(algo=Algorithm.FIXED_WINDOW, limit=5, window=10.0,
                          start=1000.0)
        assert lim.allow_n("k", 5).allowed
        assert not lim.allow("k").allowed
        clock.set(1010.5)  # next aligned window: full quota, no carryover
        assert lim.allow_n("k", 5).allowed
        lim.close()
