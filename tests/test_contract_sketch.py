"""Contract suite instantiated for the sketch backend, plus sketch-specific
behavior (memory constancy, collision direction, sub-window sliding).

The sketch is approximate in general, but with few keys and width 65536 the
contract scenarios have no collisions, so the full exact suite runs unskipped
(exact_admission stays True here; accuracy under load is measured separately
in test_accuracy.py)."""

import numpy as np
import pytest

from tests.contract import ContractTests

from ratelimiter_tpu import (
    Algorithm,
    Config,
    ManualClock,
    SketchParams,
    create_limiter,
)

class TestSketchContract(ContractTests):
    backend = "sketch"
    supports_failure_injection = True
    supports_window_scale = False  # one shared ring geometry

    def inject_failure(self, lim) -> None:
        lim.inject_failure()


def make(algo=Algorithm.TPU_SKETCH, limit=100, window=60.0, start=1_700_000_000.0,
         sketch=None, **kw):
    clock = ManualClock(start)
    cfg = Config(algorithm=algo, limit=limit, window=window,
                 sketch=sketch or SketchParams(), **kw)
    return create_limiter(cfg, backend="sketch", clock=clock), clock


class TestSketchBehavior:
    def test_memory_constant_in_keys(self):
        lim, _ = make(sketch=SketchParams(depth=4, width=1024, sub_windows=10))
        before = lim.memory_bytes()
        out = lim.allow_hashed(np.arange(5000, dtype=np.uint64))
        assert out.allow_count == 5000
        assert lim.memory_bytes() == before  # no per-key state at all
        lim.close()

    def test_sub_window_sliding_smooths_burst(self):
        # 60 sub-windows of 1s: a burst at t=59.5 still weighs ~1 at t=60.2
        lim, clock = make(limit=100, window=60.0, start=0.0)
        clock.set(59.5)
        assert lim.allow_n("k", 100).allowed
        clock.set(60.2)
        assert not lim.allow("k").allowed  # old burst still in window
        clock.set(125.0)  # > 2 windows later: fully decayed
        assert lim.allow("k").allowed
        lim.close()

    def test_decay_is_gradual_not_cliff(self):
        # With sliding sub-windows, quota returns progressively as the burst
        # ages out of the window, not all at once at the window boundary.
        lim, clock = make(limit=60, window=60.0, start=0.0)
        clock.set(30.0)
        assert lim.allow_n("k", 60).allowed
        clock.set(89.0)
        r1 = lim.allow_n("k", 60)
        assert not r1.allowed           # t-window=29 < 30: burst still counted
        clock.set(91.5)
        r2 = lim.allow_n("k", 20)
        assert r2.allowed               # burst sub-window aged out of [31.5, 91.5]
        lim.close()

    def test_overestimate_never_over_admits(self):
        # Force heavy collisions (width 16): errors must appear as extra
        # denies, never extra allows.
        lim, _ = make(limit=10, window=10.0,
                      sketch=SketchParams(depth=2, width=16, sub_windows=10))
        h = np.arange(200, dtype=np.uint64)
        out = lim.allow_hashed(h)
        # 200 distinct keys, limit 10 each: without collisions all 200 pass;
        # with collisions some are falsely denied. Over-admission impossible.
        assert out.allow_count <= 200
        per_key_second = lim.allow_hashed(h, ns=np.full(200, 11, dtype=np.int64))
        assert per_key_second.allow_count == 0  # n > limit never admitted
        lim.close()

    def test_reset_errs_toward_allowing(self):
        lim, _ = make(limit=5, window=10.0)
        for _ in range(5):
            assert lim.allow("a").allowed
        assert not lim.allow("a").allowed
        lim.reset("a")
        assert lim.allow("a").allowed
        lim.close()

    def test_prefix_namespaces_sketch(self):
        # Same key under different prefixes must not share counters.
        lim1, c1 = make(limit=3, window=60.0, key_prefix="app1")
        lim2, c2 = make(limit=3, window=60.0, key_prefix="app2")
        for _ in range(3):
            assert lim1.allow("user").allowed
        assert not lim1.allow("user").allowed
        assert lim2.allow("user").allowed  # independent namespace
        lim1.close()
        lim2.close()

    def test_hashed_and_string_paths_agree(self):
        from ratelimiter_tpu.ops.hashing import hash_strings_u64

        lim, _ = make(limit=4, window=60.0, key_prefix="")
        h = hash_strings_u64(["user:7"])
        for _ in range(4):
            assert lim.allow_hashed(h).allow_count == 1
        # Fifth through the string path: same counters, so denied.
        assert not lim.allow("user:7").allowed
        lim.close()

    def test_fixed_window_mode_resets_at_boundary(self):
        lim, clock = make(algo=Algorithm.FIXED_WINDOW, limit=5, window=10.0,
                          start=1000.0)
        assert lim.allow_n("k", 5).allowed
        assert not lim.allow("k").allowed
        clock.set(1010.5)  # next aligned window: full quota, no carryover
        assert lim.allow_n("k", 5).allowed
        lim.close()


class TestSketchTokenBucket:
    """Sketched token bucket (ops/bucket_kernels.py): reference TB semantics
    (``tokenbucket.go:23-52``) at constant memory in key cardinality."""

    def test_continuous_refill(self):
        # rate = 10/10s = 1 token/s: after draining, one token back per second.
        lim, clock = make(algo=Algorithm.TOKEN_BUCKET, limit=10, window=10.0)
        assert lim.allow_n("k", 10).allowed
        assert not lim.allow("k").allowed
        clock.advance(1.0)
        assert lim.allow("k").allowed        # exactly 1 token refilled
        assert not lim.allow("k").allowed
        clock.advance(2.5)
        assert lim.allow_n("k", 2).allowed   # 2.5 tokens: 2 whole ones spendable
        assert not lim.allow("k").allowed    # 0.5 left < 1
        lim.close()

    def test_burst_after_idle_capped_at_limit(self):
        lim, clock = make(algo=Algorithm.TOKEN_BUCKET, limit=5, window=1.0)
        assert lim.allow_n("k", 5).allowed
        clock.advance(3600.0)                # idle an hour: cap, not 18000
        assert lim.allow_n("k", 5).allowed
        assert not lim.allow("k").allowed
        lim.close()

    def test_matches_exact_backend_without_collisions(self):
        # With width 65536 and a handful of keys, the sketch holds each key
        # in private cells, and the integer decay is exact: decisions and
        # remaining match the exact oracle step for step.
        clock_s, clock_e = ManualClock(50.0), ManualClock(50.0)
        cfg = Config(algorithm=Algorithm.TOKEN_BUCKET, limit=7, window=3.0)
        sk = create_limiter(cfg, backend="sketch", clock=clock_s)
        ex = create_limiter(cfg, backend="exact", clock=clock_e)
        rng = np.random.default_rng(7)
        for _ in range(60):
            dt = float(rng.uniform(0, 1.5))
            clock_s.advance(dt)
            clock_e.advance(dt)
            key = f"user:{rng.integers(3)}"
            n = int(rng.integers(1, 4))
            rs, re = sk.allow_n(key, n), ex.allow_n(key, n)
            assert rs.allowed == re.allowed
            assert rs.remaining == re.remaining
        sk.close()
        ex.close()

    def test_collisions_only_deny(self):
        # Tiny sketch forces collisions: colliding keys share refill, so
        # errors are extra denies — never extra allows beyond n*limit.
        lim, _ = make(algo=Algorithm.TOKEN_BUCKET, limit=10, window=10.0,
                      sketch=SketchParams(depth=2, width=16))
        h = np.arange(64, dtype=np.uint64)
        out = lim.allow_hashed(h, ns=np.full(64, 10, dtype=np.int64))
        assert out.allow_count <= 64
        # Immediately after, every key's debt estimate >= its true debt:
        # nothing more may be admitted anywhere near the limit.
        again = lim.allow_hashed(h, ns=np.full(64, 10, dtype=np.int64))
        assert again.allow_count == 0
        lim.close()

    def test_retry_after_is_deficit_over_rate(self):
        # rate = 6/60s = 0.1 tokens/s; deficit of 1 token -> 10 s.
        lim, _ = make(algo=Algorithm.TOKEN_BUCKET, limit=6, window=60.0)
        assert lim.allow_n("k", 6).allowed
        res = lim.allow("k")
        assert not res.allowed
        assert res.retry_after == pytest.approx(10.0, abs=1e-5)
        lim.close()

    def test_memory_constant_in_keys(self):
        lim, _ = make(algo=Algorithm.TOKEN_BUCKET, limit=100, window=60.0,
                      sketch=SketchParams(depth=4, width=1024))
        before = lim.memory_bytes()
        out = lim.allow_hashed(np.arange(5000, dtype=np.uint64))
        assert out.allow_count == 5000
        assert lim.memory_bytes() == before
        lim.close()

    def test_windowed_kernels_reject_token_bucket_config(self):
        # Constructing the windowed SketchLimiter machinery with a
        # TOKEN_BUCKET config must raise, not silently build sliding-window
        # semantics; only the factory/SketchTokenBucketLimiter route is legal.
        from ratelimiter_tpu import InvalidConfigError
        from ratelimiter_tpu.ops import sketch_kernels

        cfg = Config(algorithm=Algorithm.TOKEN_BUCKET, limit=5, window=10.0)
        with pytest.raises(InvalidConfigError):
            sketch_kernels.sketch_geometry(cfg)
        with pytest.raises(InvalidConfigError):
            sketch_kernels.build_steps(cfg)

    def test_unweighted_n_greater_than_limit_never_admits(self):
        lim, _ = make(algo=Algorithm.TOKEN_BUCKET, limit=5, window=10.0)
        assert not lim.allow_n("k", 6).allowed
        assert lim.allow_n("k", 5).allowed  # denial consumed nothing
        lim.close()
