"""Contract suite instantiated for the multi-chip mesh backends.

Gather mode gives bit-exact global sequencing, so the FULL exact contract —
including concurrency- and batch-exactness — must hold across an 8-device
mesh, the same bar the single-chip sketch meets. That covers the windowed
algorithms (MeshSketchLimiter) and the token bucket
(MeshTokenBucketLimiter).

Delta mode trades one all_gather for one psum and relaxes ONLY the
within-step cross-chip view: a key hammered from every chip in the same
step can be over-admitted up to n_chips * limit (documented envelope,
docs/ADR/002-mesh-merge-modes.md). Its contract run asserts that envelope
where gather asserts exactness, plus next-step convergence; everything
serialized (scalar calls, concurrency-by-lock) stays exact because state
converges between steps.
"""

import jax
import numpy as np
import pytest

from tests.contract import ContractTests

from ratelimiter_tpu import Algorithm, Config
from ratelimiter_tpu.parallel import (
    MeshSketchLimiter,
    MeshTokenBucketLimiter,
    make_mesh,
)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices")

_MESH = None


def _mesh():
    global _MESH
    if _MESH is None:
        _MESH = make_mesh(n_devices=8)
    return _MESH


def _make_mesh_limiter(config: Config, clock, merge: str):
    cls = (MeshTokenBucketLimiter
           if config.algorithm is Algorithm.TOKEN_BUCKET
           else MeshSketchLimiter)
    return cls(config, clock, mesh=_mesh(), merge=merge)


class TestMeshContract(ContractTests):
    backend = "mesh-sketch-gather"
    supports_window_scale = False
    supports_failure_injection = True

    def make_limiter(self, config: Config, clock):
        return _make_mesh_limiter(config, clock, "gather")

    def inject_failure(self, lim) -> None:
        lim.inject_failure()


class TestMeshDeltaContract(ContractTests):
    """Same suite under merge='delta'. Serialized flows remain exact;
    the one-batch hot-key case asserts the documented staleness envelope
    plus convergence instead of strict in-batch exactness."""

    backend = "mesh-sketch-delta"
    supports_window_scale = False
    strict_batch_order = False
    supports_failure_injection = True
    n_chips = 8

    def make_limiter(self, config: Config, clock):
        return _make_mesh_limiter(config, clock, "delta")

    def inject_failure(self, lim) -> None:
        lim.inject_failure()

    def _assert_hot_batch(self, lim, out, limit: int) -> None:
        b = len(out)
        # Envelope: each chip admits at most `limit` of its own shard
        # within the stale step; convergence denies everything after.
        assert limit <= out.allow_count <= min(b, self.n_chips * limit)
        after = lim.allow_batch(["hot"] * b)
        assert after.allow_count == 0, "delta merge must converge in one step"

    def _assert_admitted(self, count: int, limit: int, sent: int) -> None:
        # Same staleness envelope for the policy-override batches: a key
        # decided on several chips in ONE step can over-admit up to the
        # per-chip sum; converged state denies from the next step on.
        assert count <= min(sent, self.n_chips * limit)


class TestMeshDeltaStalenessEnvelope:
    """VERDICT r2 item 9: the delta envelope under MIXED multi-key traffic,
    not just the single-hot-key case."""

    def _limiter(self, algo=Algorithm.TPU_SKETCH, limit=10, window=60.0):
        from ratelimiter_tpu import ManualClock, SketchParams

        cfg = Config(algorithm=algo, limit=limit, window=window,
                     sketch=SketchParams(depth=4, width=4096, sub_windows=6))
        return _make_mesh_limiter(cfg, ManualClock(1_700_000_000.0), "delta")

    @pytest.mark.parametrize("algo", [Algorithm.TPU_SKETCH,
                                      Algorithm.TOKEN_BUCKET], ids=str)
    def test_mixed_traffic_per_key_envelope(self, algo):
        limit, chips = 10, 8
        lim = self._limiter(algo=algo, limit=limit)
        # Mixed batch: hot (160 dups), warm (24 dups), cold (1 each) —
        # interleaved so every chip's shard sees all classes.
        keys = []
        for i in range(160):
            keys.append("hot")
            if i < 24:
                keys.append("warm")
            if i < 40:
                keys.append(f"cold:{i}")
        out = lim.allow_batch(keys)
        karr = np.array(keys)
        hot_allowed = int(out.allowed[karr == "hot"].sum())
        warm_allowed = int(out.allowed[karr == "warm"].sum())
        cold_allowed = int(out.allowed[np.char.startswith(karr, "cold")].sum())
        # Per-key envelope: >= limit (someone's shard admits a full local
        # quota) and <= n_chips * limit; cold keys all admitted.
        assert limit <= hot_allowed <= chips * limit
        assert limit <= warm_allowed <= min(24, chips * limit)
        assert cold_allowed == 40
        # Convergence: the merged state denies both hot keys next step
        # while cold keys keep their quota.
        nxt = lim.allow_batch(["hot", "warm", "cold:0", "fresh"])
        assert list(nxt.allowed) == [False, False, True, True]
        lim.close()

    def test_staleness_bounded_by_one_step(self):
        """Over-admission never compounds: after ANY step, the merged
        state reflects every chip's writes, so total admission over k
        steps is <= n_chips*limit + 0 (not k * anything)."""
        limit = 10
        lim = self._limiter(limit=limit)
        total = 0
        for _ in range(5):
            out = lim.allow_batch(["hot"] * 64)
            total += out.allow_count
        assert total <= 8 * limit  # all over-admission happened in step 1
        lim.close()
