"""Contract suite instantiated for the multi-chip mesh backend (gather mode).

Gather mode gives bit-exact global sequencing, so the FULL exact contract —
including concurrency- and batch-exactness — must hold across an 8-device
mesh, the same bar the single-chip sketch meets. (Delta mode's relaxed
within-step semantics are covered separately in tests/test_multichip.py.)
"""

import jax
import pytest

from tests.contract import ContractTests
from tests.test_contract_sketch import SKETCH_ALGOS

from ratelimiter_tpu import Config
from ratelimiter_tpu.parallel import MeshSketchLimiter, make_mesh

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices")

_MESH = None


def _mesh():
    global _MESH
    if _MESH is None:
        _MESH = make_mesh(n_devices=8)
    return _MESH


class TestMeshContract(ContractTests):
    backend = "mesh-sketch-gather"
    algorithms = SKETCH_ALGOS
    supports_failure_injection = True

    def make_limiter(self, config: Config, clock):
        return MeshSketchLimiter(config, clock, mesh=_mesh(), merge="gather")

    def inject_failure(self, lim) -> None:
        lim.inject_failure()
