"""kill -9 crash recovery through the real serving binary (ISSUE-2
acceptance criterion; NOT marked slow — this is the tier-1 durability
gate and CI runs it on every push).

A serving subprocess runs with persistence enabled under live traffic,
is SIGKILLed mid-stream, and restarts on the same directory. Asserts:

* counters under-count by at most one snapshot interval of traffic
  (here: everything after the explicitly triggered snapshot — the
  restored consumption is >= the pre-snapshot consumption and <= the
  true total, so errors go toward ALLOWING, never over-denial);
* policy overrides recover EXACTLY via WAL replay (set after the
  snapshot, deleted after the snapshot — both effects survive);
* a fingerprint-mismatched snapshot directory refuses to load with a
  clear error (nonzero exit naming the mismatch).

The exact backend keeps the subprocess JAX-free (instant startup), so
this runs fast enough for the tier-1 lane; the same recovery machinery
is exercised per backend in tests/test_persistence.py.
"""

import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from netutil import free_port

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _env():
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [REPO] + env.get("PYTHONPATH", "").split(os.pathsep))
    env["JAX_PLATFORMS"] = "cpu"
    return env


def _spawn(port, snap_dir, limit=100, extra=()):
    argv = [sys.executable, "-m", "ratelimiter_tpu.serving",
            "--backend", "exact", "--algorithm", "sliding_window",
            "--limit", str(limit), "--window", "600",
            "--port", str(port), "--snapshot-dir", snap_dir,
            # Interval far beyond the test: the explicitly triggered
            # snapshot is deterministically the last one, so "within one
            # snapshot interval of under-count" is exactly "everything
            # after the trigger".
            "--snapshot-interval", "500", "--no-prewarm", *extra]
    return subprocess.Popen(argv, env=_env(), stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)


def _wait_banner(proc, timeout=60):
    t0 = time.time()
    lines = []
    while time.time() - t0 < timeout:
        line = proc.stdout.readline()
        if not line:
            break
        lines.append(line)
        if line.startswith("serving"):
            return lines
    raise AssertionError("server never served:\n" + "".join(lines))


class TestKillNineRecovery:
    def test_kill9_recovers_counters_and_overrides(self, tmp_path):
        from ratelimiter_tpu.serving.client import Client

        snap_dir = str(tmp_path / "durable")
        port = free_port()
        proc = _spawn(port, snap_dir)
        try:
            _wait_banner(proc)
            c = Client(port=port, timeout=60.0)
            # Pre-snapshot state: 30 consumed on "k", override on "vip".
            assert c.allow_n("k", 30).allowed
            c.set_override("vip", 42)
            snap_id, wal_seq, _dur = c.snapshot()
            assert snap_id >= 1 and wal_seq >= 1
            # Crash window: more consumption + override churn, all under
            # live background traffic so the SIGKILL lands mid-stream.
            stop = threading.Event()

            def hammer():
                try:
                    with Client(port=port, timeout=60.0) as hc:
                        i = 0
                        while not stop.is_set():
                            hc.allow(f"bg:{i % 997}")
                            i += 1
                except (ConnectionError, OSError):
                    pass          # the kill severs this stream mid-flight

            t = threading.Thread(target=hammer, daemon=True)
            t.start()
            for _ in range(5):
                assert c.allow_n("k", 10).allowed      # 50 more, lost-able
            c.set_override("vip2", 9)
            assert c.delete_override("vip") is True
            time.sleep(0.2)                            # traffic in flight
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=30)
            stop.set()
            t.join(timeout=10)
            c.close()
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait()

        # Restart on the same directory.
        proc2 = _spawn(port, snap_dir)
        try:
            lines = _wait_banner(proc2)
            assert any("recovery" in ln for ln in lines)
            with Client(port=port, timeout=60.0) as c2:
                # Overrides recover EXACTLY via WAL replay: the one set
                # after the snapshot exists, the one deleted after the
                # snapshot stays deleted.
                assert c2.get_override("vip2") == (9, 1.0)
                assert c2.get_override("vip") is None
                # Counters: consumed >= 30 (snapshot state restored) ...
                assert not c2.allow_n("k", 71).allowed
                # ... and <= 80 (under-count only — the limiter must
                # never think MORE was consumed than actually was).
                assert c2.allow_n("k", 20).allowed
            proc2.send_signal(signal.SIGTERM)
            assert proc2.wait(timeout=30) == 0
        finally:
            if proc2.poll() is None:
                proc2.kill()
                proc2.wait()

    def test_fingerprint_mismatch_refuses_startup(self, tmp_path):
        """Booting on a snapshot directory taken under different flags
        must fail loudly, not silently reinterpret state."""
        from ratelimiter_tpu.serving.client import Client

        snap_dir = str(tmp_path / "durable")
        port = free_port()
        proc = _spawn(port, snap_dir, limit=100)
        try:
            _wait_banner(proc)
            with Client(port=port, timeout=60.0) as c:
                assert c.allow_n("k", 5).allowed
                c.snapshot()
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=30)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait()

        proc2 = _spawn(port, snap_dir, limit=101)      # drifted flag
        out, _ = proc2.communicate(timeout=60)
        assert proc2.returncode != 0
        assert "fingerprint" in out
        assert "limit=100" in out                      # names the original
        assert "move the snapshot directory aside" in out

    def test_wal_only_recovery_without_any_snapshot(self, tmp_path):
        """Crash before the first snapshot: the whole WAL replays onto
        fresh state — overrides still recover exactly."""
        from ratelimiter_tpu.serving.client import Client

        snap_dir = str(tmp_path / "durable")
        port = free_port()
        proc = _spawn(port, snap_dir)
        try:
            _wait_banner(proc)
            with Client(port=port, timeout=60.0) as c:
                c.set_override("vip", 17)
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=30)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait()
        proc2 = _spawn(port, snap_dir)
        try:
            _wait_banner(proc2)
            with Client(port=port, timeout=60.0) as c2:
                assert c2.get_override("vip") == (17, 1.0)
            proc2.send_signal(signal.SIGTERM)
            assert proc2.wait(timeout=30) == 0
        finally:
            if proc2.poll() is None:
                proc2.kill()
                proc2.wait()


class TestSnapshotRpcSurface:
    def test_snapshot_rpc_refused_without_persistence(self):
        """T_SNAPSHOT against a server without --snapshot-dir answers a
        typed error, not a hang or a crash."""
        from ratelimiter_tpu import (
            Algorithm,
            Config,
            InvalidConfigError,
            create_limiter,
        )
        from ratelimiter_tpu.serving.client import Client
        from ratelimiter_tpu.serving.server import RateLimitServer

        import asyncio

        async def run():
            cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=10,
                         window=60.0)
            lim = create_limiter(cfg, backend="exact")
            srv = RateLimitServer(lim, port=0)
            await srv.start()
            try:
                loop = asyncio.get_running_loop()

                def probe():
                    with Client(port=srv.port, timeout=30.0) as c:
                        with pytest.raises(InvalidConfigError,
                                           match="persistence not enabled"):
                            c.snapshot()

                await loop.run_in_executor(None, probe)
            finally:
                await srv.shutdown()
                lim.close()

        asyncio.run(run())
