"""Zero-syscall shared-memory wire lane (ADR-025): transport ladder tests.

Four tiers, mirroring the ISSUE's acceptance bars:

* **UDS listener** — both doors accept ``unix:/path`` binds and the
  binary clients dial them.
* **Shm lane** — T_SHM_HELLO upgrade end-to-end through both doors and
  both Python clients, every request lane, plus the off-by-default pin
  (``--shm`` off answers E_INVALID_CONFIG and the socket wire stays
  byte-identical).
* **Bit-identical pins** — the SAME request frames (trace + deadline
  extensions, batch, hashed, leases) against fresh identical limiters
  over tcp, uds and shm must produce byte-identical reply frames, on
  the asyncio door and the native door. The lane carries the existing
  framing verbatim; nothing re-encodes.
* **Crash safety** — kill -9 mid-record never stalls or corrupts the
  server; ring-full surfaces as the typed RingFullError; record-header
  fuzz (truncate every byte, flip every bit) either raises
  ShmProtocolError or yields bytes — never a hang, never an OOB read;
  lease revocation pushes ride the reply ring.
"""

from __future__ import annotations

import asyncio
import mmap
import os
import signal
import socket
import struct
import subprocess
import sys
import textwrap
import threading
import time
from contextlib import contextmanager

import numpy as np
import pytest

from ratelimiter_tpu import (
    Algorithm,
    Config,
    InvalidConfigError,
    ManualClock,
    SketchParams,
    create_limiter,
)
from ratelimiter_tpu.leases import LeaseManager
from ratelimiter_tpu.observability import Registry
from ratelimiter_tpu.serving import AsyncClient, Client, RateLimitServer
from ratelimiter_tpu.serving import protocol as p
from ratelimiter_tpu.serving import shm as shm_lane
from ratelimiter_tpu.serving.native_server import (
    NativeRateLimitServer,
    native_server_available,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

needs_native = pytest.mark.skipif(
    not native_server_available(), reason="needs g++ for the native server")


class FakeClock:
    def __init__(self, t: float = 100.0):
        self.t = float(t)

    def __call__(self) -> float:
        return self.t


def _mk_limiter(limit=100, window=60.0, backend="exact", **kw):
    clock = ManualClock(1_700_000_000.0)
    cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=limit,
                 window=window, **kw)
    return create_limiter(cfg, backend=backend, clock=clock), clock


def _mk_sketch_limiter(limit=1000):
    clock = ManualClock(1_700_000_000.0)
    cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=limit,
                 window=60.0,
                 sketch=SketchParams(depth=3, width=256, sub_windows=5))
    return create_limiter(cfg, backend="sketch", clock=clock), clock


@contextmanager
def running_server(limiter, host="127.0.0.1", **kw):
    """Asyncio door on a background event loop; yields (server, loop)."""
    loop = asyncio.new_event_loop()
    thread = threading.Thread(target=loop.run_forever, daemon=True)
    thread.start()
    server = RateLimitServer(limiter, host, 0, **kw)
    asyncio.run_coroutine_threadsafe(server.start(), loop).result(timeout=10)
    try:
        yield server, loop
    finally:
        asyncio.run_coroutine_threadsafe(
            server.shutdown(), loop).result(timeout=10)
        loop.call_soon_threadsafe(loop.stop)
        thread.join(timeout=10)
        loop.close()


@contextmanager
def running_native(limiter, host="127.0.0.1", **kw):
    srv = NativeRateLimitServer(limiter, host, 0, **kw)
    srv.start()
    try:
        yield srv
    finally:
        srv.shutdown()


def _wait_until(cond, timeout=10.0, interval=0.01, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {what}")


def _shm_leftovers():
    try:
        return [f for f in os.listdir("/dev/shm") if f.startswith("rltpu-")]
    except FileNotFoundError:
        return []


# -------------------------------------------------------------- uds rung

class TestUdsListener:
    def test_asyncio_door_unix_bind(self, tmp_path):
        lim, _ = _mk_limiter(limit=5)
        path = str(tmp_path / "rl.sock")
        with running_server(lim, host=f"unix:{path}") as (_, _loop):
            with Client(host=f"unix:{path}", transport="uds") as c:
                for i in range(5):
                    assert c.allow("u").allowed
                assert not c.allow("u").allowed
            # Bare path (no "unix:" prefix) also dials.
            with Client(host=path, transport="uds") as c:
                assert c.health()[0]  # serving
        assert not os.path.exists(path)
        lim.close()

    @needs_native
    def test_native_door_unix_bind(self, tmp_path):
        lim, _ = _mk_limiter(limit=5)
        path = str(tmp_path / "rl-native.sock")
        with running_native(lim, host=f"unix:{path}"):
            with Client(host=f"unix:{path}", transport="uds") as c:
                assert c.allow("u").allowed
                res = c.allow_batch(["a", "b", "a"])
                assert [r.allowed for r in res] == [True, True, True]
        assert not os.path.exists(path)
        lim.close()


# -------------------------------------------------------------- shm rung

class TestShmLane:
    def test_asyncio_door_all_request_lanes(self):
        lim, _ = _mk_limiter(limit=10)
        with running_server(lim, shm=True) as (server, _loop):
            with Client(port=server.port, transport="shm") as c:
                assert c.allow("k").allowed
                assert c.allow_n("k", 4).allowed
                res = c.allow_batch(["a", "b", "a"], [1, 1, 1])
                assert [r.allowed for r in res] == [True, True, True]
                # Frame extensions ride the ring unchanged.
                assert c.allow("k", trace_id=0xAB12, deadline=5.0).allowed
                c.reset("k")
                assert c.allow_n("k", 10).allowed
                serving, _uptime, decisions = c.health()
                assert serving and decisions > 0
                assert "rate_limiter" in c.metrics()
            _wait_until(
                lambda: server.transport_stats()["shm"]["lanes_active"] == 0,
                what="lane teardown")
        assert not _shm_leftovers()
        lim.close()

    def test_asyncio_door_async_client_burst(self):
        lim, _ = _mk_limiter(limit=100000)
        with running_server(lim, shm=True) as (server, _loop):
            async def go():
                c = await AsyncClient.connect(
                    port=server.port, transport="shm")
                try:
                    res = await asyncio.gather(
                        *(c.allow(f"k{i % 7}") for i in range(64)))
                    assert all(r.allowed for r in res)
                finally:
                    await c.close()

            asyncio.run(go())
        assert not _shm_leftovers()
        lim.close()

    def test_hashed_lane_over_shm(self):
        lim, _ = _mk_sketch_limiter(limit=1000)
        with running_server(lim, shm=True) as (server, _loop):
            with Client(port=server.port, transport="shm") as c:
                ids = np.arange(1, 9, dtype=np.uint64)
                res = c.allow_hashed(ids)
                assert res.allowed.shape == (8,) and res.allowed.all()
        lim.close()

    def test_shm_off_is_typed_error_and_plain_wire_unchanged(self):
        lim, _ = _mk_limiter(limit=10)
        with running_server(lim) as (server, _loop):  # shm OFF (default)
            with pytest.raises(InvalidConfigError, match="--shm"):
                Client(port=server.port, transport="shm")
            # The rejected hello leaves the plain wire fully usable and
            # byte-identical: a raw allow_n gets the exact encode_result
            # bytes a pre-ADR-025 server would send.
            with socket.create_connection(("127.0.0.1", server.port)) as s:
                s.sendall(p.encode_allow_n(7, "k", 1))
                raw = _recv_frame(s)
            length, type_, rid = p.parse_header(raw[:p.HEADER_SIZE])
            assert (type_, rid) == (p.T_RESULT, 7)
            res = p.parse_result(raw[p.HEADER_SIZE:])
            assert res.allowed and res.limit == 10
            assert raw == p.encode_result(7, res)
        lim.close()

    def test_duplicate_hello_rejected(self):
        lim, _ = _mk_limiter()
        with running_server(lim, shm=True) as (server, _loop):
            with Client(port=server.port, transport="shm") as c:
                # Second hello on the SAME (already upgraded) socket.
                with c._lock:
                    c._sock.sendall(p.encode_shm_hello(99, 0, 0))
                    raw = _recv_frame(c._sock)
                _len, type_, _rid = p.parse_header(raw[:p.HEADER_SIZE])
                assert type_ == p.T_ERROR
                code, msg = p.parse_error(raw[p.HEADER_SIZE:])
                assert code == p.E_INVALID_CONFIG and "already" in msg
        lim.close()

    @needs_native
    def test_native_door_shm_roundtrip(self):
        lim, _ = _mk_limiter(limit=10)
        with running_native(lim, shm=True) as srv:
            with Client(port=srv.port, transport="shm") as c:
                for i in range(10):
                    assert c.allow("k").allowed
                assert not c.allow("k").allowed
                assert c.allow("k", trace_id=0x77, deadline=5.0) is not None
                res = c.allow_batch(["x", "y"], [2, 3])
                assert all(r.allowed for r in res)
            st = srv.transport_stats()
            assert st["connections"]["shm"] == 1
            assert st["shm"]["records_in"] >= 12
        assert not _shm_leftovers()
        lim.close()

    @needs_native
    def test_native_door_shm_off_typed_error(self):
        lim, _ = _mk_limiter()
        with running_native(lim) as srv:
            with pytest.raises(InvalidConfigError, match="--shm"):
                Client(port=srv.port, transport="shm")
            with Client(port=srv.port) as c:  # plain tcp still fine
                assert c.allow("k").allowed
        lim.close()


# ------------------------------------------------- transport observability

class TestTransportObservability:
    def test_stats_and_gauges_track_lanes(self):
        lim, _ = _mk_limiter(limit=100000)
        reg = Registry()
        with running_server(lim, shm=True, registry=reg) as (server, _loop):
            with Client(port=server.port, transport="shm") as c:
                for _ in range(32):
                    assert c.allow("k").allowed
                st = server.transport_stats()
                assert st["connections"]["shm"] == 1
                assert st["shm"]["lanes_active"] == 1
                assert st["shm"]["records_in"] >= 32
                assert st["shm"]["records_out"] >= 32
                assert st["shm"]["rep_ring_highwater_bytes"] > 0
                # A consumer either spun or took the doorbell for every
                # record it claimed; both paths are counted.
                assert (st["shm"]["spin_hits"]
                        + st["shm"]["doorbell_wakes"]) > 0
                text = reg.render()
                for fam in ("rate_limiter_transport_connections",
                            "rate_limiter_shm_lanes_active",
                            "rate_limiter_shm_doorbell_wakes",
                            "rate_limiter_shm_spin_hits",
                            "rate_limiter_shm_ring_full_stalls",
                            "rate_limiter_shm_records",
                            "rate_limiter_shm_ring_used_bytes",
                            "rate_limiter_shm_ring_highwater_bytes"):
                    assert fam in text, fam
            # Counters survive lane retirement (monotonic across
            # disconnects), and the lane gauge returns to zero.
            _wait_until(
                lambda: server.transport_stats()["shm"]["lanes_active"] == 0,
                what="lane retirement")
            assert server.transport_stats()["shm"]["records_in"] >= 32
        lim.close()

    def test_tcp_and_uds_connections_counted(self, tmp_path):
        lim, _ = _mk_limiter()
        with running_server(lim, shm=True) as (server, _loop):
            with Client(port=server.port) as c:
                assert c.allow("k").allowed
            st = server.transport_stats()
            assert st["connections"]["tcp"] >= 1
        path = str(tmp_path / "obs.sock")
        lim2, _ = _mk_limiter()
        with running_server(lim2, host=f"unix:{path}") as (server, _loop):
            with Client(host=f"unix:{path}", transport="uds") as c:
                assert c.allow("k").allowed
            assert server.transport_stats()["connections"]["uds"] >= 1
        lim.close()
        lim2.close()


# ------------------------------------------------------ bit-identical pins

def _recv_frame(sock: socket.socket) -> bytes:
    buf = b""
    while len(buf) < p.HEADER_SIZE:
        chunk = sock.recv(65536)
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    length = struct.unpack_from("<I", buf)[0]
    want = 4 + length
    while len(buf) < want:
        chunk = sock.recv(65536)
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    assert len(buf) == want, "unexpected trailing bytes"
    return buf


def _pin_frames(leases: bool = True) -> list[bytes]:
    """The pinned request sequence: every decision lane plus the trace
    and deadline extensions and (asyncio door only — the native door
    serves leases via the sidecar listener, ADR-022) a lease grant.
    rids are fixed so reply frames compare byte-for-byte across
    transports."""
    ids = np.arange(11, 19, dtype=np.uint64)
    frames = [
        p.encode_allow_n(10, "pin:a", 1),
        p.with_trace(p.encode_allow_n(11, "pin:a", 2), 0xDECAF123),
        p.with_deadline(p.encode_allow_n(12, "pin:b", 1), 5.0),
        p.with_trace(
            p.with_deadline(p.encode_allow_n(13, "pin:b", 1), 2.5),
            0xABCD),
        p.encode_allow_batch(14, ["x", "y", "x"], [1, 2, 3]),
        p.encode_allow_hashed(15, ids),
        p.with_trace(p.encode_allow_hashed(16, ids), 0x5150),
        p.encode_reset(18, "pin:a"),
        p.encode_allow_n(19, "pin:a", 1),
    ]
    if leases:
        frames.insert(7, p.encode_lease_grant(17, 42, "pin:hot", 8, 0))
    return frames


def _roundtrip_socket(sock: socket.socket, frames) -> list[bytes]:
    out = []
    for f in frames:
        sock.sendall(f)
        out.append(_recv_frame(sock))
    return out


def _roundtrip_shm(host: str, port: int, frames) -> list[bytes]:
    """Speak the hello by hand and drive the ClientLane directly so the
    captured replies are the raw ring records, no client post-processing."""
    if host.startswith("unix:"):
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.connect(host[len("unix:"):])
    else:
        sock = socket.create_connection((host, port))
    try:
        sock.sendall(p.encode_shm_hello(1, 0, 0))
        raw = _recv_frame(sock)
        _len, type_, _rid = p.parse_header(raw[:p.HEADER_SIZE])
        assert type_ == p.T_SHM_HELLO_R, "hello refused"
        _rq, _rp, shm_path, ctrl_path = p.parse_shm_hello_r(
            raw[p.HEADER_SIZE:])
        lane = shm_lane.ClientLane(shm_path, ctrl_path)
        try:
            out = []
            for f in frames:
                lane.send_frame(f)
                got = lane.recv_frame(timeout=10.0)
                assert got is not None, "shm reply timeout"
                out.append(got)
            return out
        finally:
            lane.close()
    finally:
        sock.close()


def _fresh_pin_fixture():
    """Identical-state limiter + lease manager for one transport run."""
    lim, _ = _mk_sketch_limiter(limit=1000)
    mgr = LeaseManager(lim, ttl=30.0, default_budget=64,
                       registry=Registry(), clock=FakeClock(100.0))
    return lim, mgr


class TestBitIdenticalPins:
    """The lane carries the EXISTING framing byte-for-byte: the same
    requests against identically-seeded limiters must return identical
    reply bytes whichever rung of the transport ladder carried them."""

    @staticmethod
    def _asyncio_run(transport: str, tmp_path) -> list[bytes]:
        """One capture = one fresh fixture + fresh server, so every
        transport sees IDENTICAL limiter/lease state."""
        frames = _pin_frames()
        lim, mgr = _fresh_pin_fixture()
        host = "127.0.0.1"
        if transport.startswith("uds"):
            host = f"unix:{tmp_path / ('pin-' + transport + '.sock')}"
        try:
            with running_server(lim, host=host, shm=True,
                                leases=mgr) as (server, _loop):
                if transport == "tcp":
                    with socket.create_connection(
                            ("127.0.0.1", server.port)) as s:
                        return _roundtrip_socket(s, frames)
                if transport == "uds":
                    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                    s.connect(host[len("unix:"):])
                    try:
                        return _roundtrip_socket(s, frames)
                    finally:
                        s.close()
                return _roundtrip_shm(host, server.port, frames)
        finally:
            lim.close()

    def test_asyncio_door_tcp_uds_shm_identical(self, tmp_path):
        tcp = self._asyncio_run("tcp", tmp_path)
        uds = self._asyncio_run("uds", tmp_path)
        shm = self._asyncio_run("shm", tmp_path)
        uds_shm = self._asyncio_run("uds+shm", tmp_path)
        assert len(tcp) == len(_pin_frames())
        assert tcp == uds
        assert tcp == shm
        assert tcp == uds_shm

    @needs_native
    def test_native_door_tcp_uds_shm_identical(self, tmp_path):
        frames = _pin_frames(leases=False)

        def native_run(transport, host="127.0.0.1"):
            lim, _mgr = _fresh_pin_fixture()
            try:
                with running_native(lim, host=host, shm=True) as srv:
                    if transport == "tcp":
                        with socket.create_connection(
                                ("127.0.0.1", srv.port)) as s:
                            return _roundtrip_socket(s, frames)
                    if transport == "uds":
                        s = socket.socket(socket.AF_UNIX,
                                          socket.SOCK_STREAM)
                        s.connect(host[len("unix:"):])
                        try:
                            return _roundtrip_socket(s, frames)
                        finally:
                            s.close()
                    return _roundtrip_shm(host, srv.port, frames)
            finally:
                lim.close()

        tcp = native_run("tcp")
        shm = native_run("shm")
        upath = str(tmp_path / "npin.sock")
        uds = native_run("uds", host=f"unix:{upath}")
        assert tcp == shm
        assert tcp == uds

    @needs_native
    def test_doors_agree_with_each_other(self):
        # Cross-door: the native door's bytes == the asyncio door's
        # bytes for the pinned sequence, both over shm (lease frames
        # excluded — the native door hands those to the sidecar).
        frames = _pin_frames(leases=False)
        lim, _mgr = _fresh_pin_fixture()
        with running_server(lim, shm=True) as (server, _loop):
            a = _roundtrip_shm("127.0.0.1", server.port, frames)
        lim.close()
        lim2, _mgr2 = _fresh_pin_fixture()
        with running_native(lim2, shm=True) as srv:
            n = _roundtrip_shm("127.0.0.1", srv.port, frames)
        lim2.close()
        assert a == n


# ------------------------------------------------------------ crash tests

class TestCrashSafety:
    def test_ring_full_is_typed_backpressure(self):
        """Block the server loop, flood a deliberately tiny ring: the
        producer must surface RingFullError (typed, catchable as
        StorageUnavailableError) — never silently drop or deadlock."""
        lim, _ = _mk_limiter(limit=10**9)
        with running_server(lim, shm=True) as (server, loop):
            with Client(port=server.port, transport="shm",
                        shm_ring_bytes=shm_lane.MIN_RING) as c:
                assert c.allow("warm").allowed
                # Wedge the event loop so nothing drains the request
                # ring, then flood it.
                loop.call_soon_threadsafe(time.sleep, 1.5)
                time.sleep(0.05)
                frame = p.encode_allow_n(12345, "x" * 200, 1)
                with pytest.raises(shm_lane.RingFullError):
                    for _ in range(shm_lane.MIN_RING // 64):
                        c._lane.send_frame(frame, timeout=0.2)
                assert c._lane.stats.ring_full_stalls > 0
                # Once the loop resumes the queued frames drain; wait
                # out the wedge, then swallow their replies so the lane
                # is quiet again...
                time.sleep(1.6)
                while c._lane.recv_frame(timeout=0.5) is not None:
                    pass
                # ...and the SAME connection keeps working.
                assert c.allow("after").allowed
        lim.close()

    def test_kill9_mid_write_never_stalls_server(self):
        """A client SIGKILLed half-way through publishing a record (tail
        advanced, commit word garbage) must poison only ITS lane: the
        server drops that connection, keeps serving everyone else, and
        leaves nothing in /dev/shm."""
        lim, _ = _mk_limiter(limit=10**9)
        with running_server(lim, shm=True) as (server, _loop):
            script = textwrap.dedent(f"""
                import os, struct, sys
                sys.path.insert(0, {REPO!r})
                from ratelimiter_tpu.serving.client import Client
                from ratelimiter_tpu.serving import shm as shm_lane
                c = Client("127.0.0.1", {server.port}, transport="shm")
                assert c.allow("warm").allowed
                ring = c._lane.outbound
                tail = ring._tail()
                base = ring._data + (tail & ring._mask)
                # Torn publish: size says 64 bytes, commit word is junk,
                # tail published — exactly what a crash mid-memcpy leaves.
                struct.pack_into("<II", ring._mm, base, 64, 0xDEADBEEF)
                ring._set_tail(tail + 8 + 64)
                shm_lane._ding(c._lane.efd_server)
                print("POISONED", flush=True)
                os.kill(os.getpid(), 9)
            """)
            env = dict(os.environ, JAX_PLATFORMS="cpu")
            proc = subprocess.Popen([sys.executable, "-c", script],
                                    stdout=subprocess.PIPE, env=env,
                                    stderr=subprocess.DEVNULL, text=True)
            try:
                line = proc.stdout.readline()
                assert "POISONED" in line, "victim never armed the record"
                proc.wait(timeout=20)
                assert proc.returncode == -signal.SIGKILL
                # Server survives and retires the poisoned lane...
                _wait_until(
                    lambda: server.transport_stats()["shm"][
                        "lanes_active"] == 0,
                    what="poisoned lane teardown")
                # ...and keeps serving fresh clients on BOTH rungs.
                with Client(port=server.port) as c:
                    assert c.allow("alive").allowed
                with Client(port=server.port, transport="shm") as c:
                    assert c.allow("alive-shm").allowed
            finally:
                if proc.poll() is None:
                    proc.kill()
        assert not _shm_leftovers()
        lim.close()

    @needs_native
    def test_kill9_mid_write_native_door(self):
        lim, _ = _mk_limiter(limit=10**9)
        with running_native(lim, shm=True) as srv:
            script = textwrap.dedent(f"""
                import os, struct, sys
                sys.path.insert(0, {REPO!r})
                from ratelimiter_tpu.serving.client import Client
                from ratelimiter_tpu.serving import shm as shm_lane
                c = Client("127.0.0.1", {srv.port}, transport="shm")
                assert c.allow("warm").allowed
                ring = c._lane.outbound
                tail = ring._tail()
                base = ring._data + (tail & ring._mask)
                struct.pack_into("<II", ring._mm, base, 64, 0xDEADBEEF)
                ring._set_tail(tail + 8 + 64)
                shm_lane._ding(c._lane.efd_server)
                print("POISONED", flush=True)
                os.kill(os.getpid(), 9)
            """)
            env = dict(os.environ, JAX_PLATFORMS="cpu")
            proc = subprocess.Popen([sys.executable, "-c", script],
                                    stdout=subprocess.PIPE, env=env,
                                    stderr=subprocess.DEVNULL, text=True)
            try:
                assert "POISONED" in proc.stdout.readline()
                proc.wait(timeout=20)
                _wait_until(
                    lambda: srv.transport_stats()["shm"][
                        "lanes_active"] == 0,
                    what="poisoned lane teardown (native)")
                with Client(port=srv.port, transport="shm") as c:
                    assert c.allow("alive").allowed
            finally:
                if proc.poll() is None:
                    proc.kill()
        assert not _shm_leftovers()
        lim.close()

    def test_revocation_push_rides_the_reply_ring(self):
        """ADR-022 regression over the new wire: a lease granted over
        shm is revoked by a policy mutation, and the rid-0
        T_LEASE_REVOKE push arrives through the reply RING (the socket
        is liveness-only once upgraded)."""
        lim, _ = _mk_limiter(limit=100000)
        mgr = LeaseManager(lim, ttl=2.0, default_budget=64,
                           registry=Registry())
        with running_server(lim, shm=True, leases=mgr) as (server, _loop):
            with Client(port=server.port, transport="shm") as c:
                cache = c.enable_leases(interval=0.02, hot_after=3,
                                        hot_window=5.0)
                _wait_until(
                    lambda: (c.allow("hot").allowed
                             and cache.status()["leased_keys"] > 0),
                    what="lease grant over shm")
                before = cache.status()["local_answers"]
                for _ in range(16):
                    assert c.allow("hot").allowed
                assert cache.status()["local_answers"] > before
                c.set_override("hot", 50000)
                _wait_until(
                    lambda: cache.status()["leased_keys"] == 0,
                    what="revocation push over the shm reply ring")
                assert c.allow("hot").allowed
        lim.close()


# ------------------------------------------------------- record-level fuzz

def _fresh_ring():
    """An anonymous mapping holding one lane; returns the request ring
    viewed from both roles (same object — SPSC in one process)."""
    cap = shm_lane.MIN_RING
    mm = mmap.mmap(-1, shm_lane.total_bytes(cap, cap))
    shm_lane.init_header(mm, cap, cap)
    req, _rep = shm_lane.attach(mm, server=True)
    return mm, req


class TestRecordFuzz:
    """The consumer's contract under arbitrary corruption: pop() either
    returns bytes or raises ShmProtocolError — it never hangs, never
    reads out of bounds, never silently spins."""

    PAYLOAD = p.encode_allow_n(7, "fuzz-key", 3)

    def test_clean_roundtrip_baseline(self):
        mm, ring = _fresh_ring()
        assert ring.try_push(self.PAYLOAD)
        assert ring.pop() == self.PAYLOAD
        assert ring.pop() is None
        mm.close()

    def test_truncated_publish_every_length(self):
        """Simulate a producer dying after writing only the first i
        bytes of the record region but with tail already published (the
        worst reordering a crash can expose)."""
        rec_len = 8 + shm_lane.align8(len(self.PAYLOAD))
        for cut in range(rec_len):
            mm, ring = _fresh_ring()
            assert ring.try_push(self.PAYLOAD)
            base = ring._data
            keep = bytes(mm[base:base + cut])
            mm[base:base + rec_len] = b"\x00" * rec_len
            mm[base:base + cut] = keep
            try:
                got = ring.pop()
                # A cut past the commit word leaves a committed record;
                # payload bytes may be zeroed but framing never lies
                # about its length.
                if got is not None:
                    assert len(got) == len(self.PAYLOAD)
            except shm_lane.ShmProtocolError:
                pass  # typed poison — the lane dies loudly, by design
            mm.close()

    def test_bitflip_every_header_bit(self):
        for bit in range(64):  # the 8-byte [size|commit] record header
            mm, ring = _fresh_ring()
            assert ring.try_push(self.PAYLOAD)
            off = ring._data + bit // 8
            mm[off] ^= 1 << (bit % 8)
            try:
                got = ring.pop()
                # Only a flip that keeps size^COMMIT_XOR == commit can
                # survive; with both words covering each other that
                # means the record must parse back intact.
                assert got is not None
            except shm_lane.ShmProtocolError:
                pass
            mm.close()

    def test_bitflip_payload_is_framing_safe(self):
        # Payload flips are NOT the ring's job (frames carry their own
        # protocol-level validation) — but they must never break record
        # framing or desync the ring.
        for byte in range(len(self.PAYLOAD)):
            mm, ring = _fresh_ring()
            assert ring.try_push(self.PAYLOAD)
            assert ring.try_push(self.PAYLOAD)  # a second, clean record
            mm[ring._data + 8 + byte] ^= 0xFF
            first = ring.pop()
            assert first is not None and len(first) == len(self.PAYLOAD)
            assert ring.pop() == self.PAYLOAD  # framing stays in step
            mm.close()

    def test_giant_size_rejected_not_overread(self):
        mm, ring = _fresh_ring()
        assert ring.try_push(self.PAYLOAD)
        size = shm_lane.MAX_RING * 4
        struct.pack_into("<II", mm, ring._data, size,
                         size ^ shm_lane.COMMIT_XOR)
        with pytest.raises(shm_lane.ShmProtocolError):
            ring.pop()
        mm.close()

    def test_wrap_pad_fuzz(self):
        # Corrupting a wrap marker's size beyond cap must raise, not
        # send head past tail.
        mm, ring = _fresh_ring()
        assert ring.try_push(self.PAYLOAD)
        struct.pack_into("<II", mm, ring._data, shm_lane.MAX_RING * 8,
                         shm_lane.COMMIT_WRAP)
        with pytest.raises(shm_lane.ShmProtocolError):
            ring.pop()
        mm.close()
