"""Multi-chip mesh tests on the 8-virtual-CPU-device mesh (conftest.py).

This is the miniredis move transplanted (SURVEY.md §4.3): the reference
tests cluster behavior without a cluster by faking Redis in-process; here a
v5e-8 pod is stood in for by 8 XLA host devices, and the very same
shard_map/psum code that runs over ICI runs over the fake mesh.

The core invariant (reference ``interface_test.go:299-335``, transplanted
from 100 goroutines to a mesh): a key with limit L must be admitted at most
L times *globally*, no matter how its traffic is spread over chips.
"""

import numpy as np
import pytest

import jax

from ratelimiter_tpu import Algorithm, Config, ManualClock, SketchParams
from ratelimiter_tpu.algorithms.sketch import SketchLimiter
from ratelimiter_tpu.parallel import MeshSketchLimiter, make_mesh

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices")

T0 = 1_700_000_000.0


def _cfg(**kw):
    base = dict(
        algorithm=Algorithm.SLIDING_WINDOW,
        limit=100,
        window=60.0,
        sketch=SketchParams(depth=2, width=1 << 12, sub_windows=6),
    )
    base.update(kw)
    return Config(**base)


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(n_devices=8)


# ---------------------------------------------------------------- gather


def test_gather_global_exactness_single_key(mesh):
    """256 requests for one key spread over 8 chips, limit 100 -> exactly
    100 global admits in one step (the mesh analog of the reference's
    concurrency-exactness test)."""
    clock = ManualClock(T0)
    lim = MeshSketchLimiter(_cfg(), clock, mesh=mesh, merge="gather")
    out = lim.allow_batch(["hot"] * 256)
    assert out.allow_count == 100
    # And the admitted ones are the *first* 100 in batch order.
    assert bool(np.all(out.allowed[:100])) and not bool(np.any(out.allowed[100:]))


def test_gather_matches_single_chip(mesh):
    """The mesh limiter in gather mode is bit-identical to the single-chip
    limiter on the same trace: same decisions, same evolution."""
    rng = np.random.default_rng(7)
    keys = [f"k{int(i)}" for i in rng.integers(0, 50, size=300)]
    cfg = _cfg(limit=5)

    c1, c2 = ManualClock(T0), ManualClock(T0)
    single = SketchLimiter(cfg, c1)
    meshed = MeshSketchLimiter(cfg, c2, mesh=mesh, merge="gather")
    for lo in range(0, 300, 100):
        batch = keys[lo:lo + 100]
        a = single.allow_batch(batch)
        b = meshed.allow_batch(batch)
        np.testing.assert_array_equal(a.allowed, b.allowed)
        np.testing.assert_array_equal(a.remaining, b.remaining)
        c1.advance(1.0)
        c2.advance(1.0)


def test_gather_never_over_admits_across_steps(mesh):
    clock = ManualClock(T0)
    lim = MeshSketchLimiter(_cfg(limit=40), clock, mesh=mesh, merge="gather")
    total = 0
    for _ in range(5):
        total += lim.allow_batch(["k"] * 16).allow_count
        clock.advance(0.25)
    assert total == 40


# ----------------------------------------------------------------- delta


def test_delta_bounded_staleness_then_convergence(mesh):
    """Delta mode may over-admit within ONE step (each chip sees counts
    that exclude same-step traffic on other chips) but never beyond
    n_chips * limit, and the psum-merged state denies from the next step
    on. This bounded-staleness contract is ADR'd (the analog of the
    reference accepting NTP skew, SURVEY.md §2.4.14)."""
    clock = ManualClock(T0)
    lim = MeshSketchLimiter(_cfg(limit=10), clock, mesh=mesh, merge="delta")
    first = lim.allow_batch(["hot"] * 256)
    # Deterministic: every chip sees est=0 for the fresh key and admits its
    # local limit's worth, so the staleness bound is hit *exactly* —
    # n_chips * limit. A looser assertion would mask a regression where
    # some chip under-admits.
    assert first.allow_count == 8 * 10
    second = lim.allow_batch(["hot"] * 256)
    assert second.allow_count == 0


def test_delta_exact_when_keys_do_not_cross_chips(mesh):
    """Keys confined to one chip's shard see exact semantics in delta mode
    (in-shard sequencing is the single-chip admission kernel)."""
    clock = ManualClock(T0)
    lim = MeshSketchLimiter(_cfg(limit=3), clock, mesh=mesh, merge="delta")
    # 8 chips x 32-slot shards; give each chip its own key, 32 requests.
    keys = []
    for chip in range(8):
        keys.extend([f"chip{chip}"] * 32)
    out = lim.allow_batch(keys)
    for chip in range(8):
        seg = out.allowed[chip * 32:(chip + 1) * 32]
        assert int(seg.sum()) == 3
        assert bool(np.all(seg[:3]))


def test_delta_with_cu_config_never_undercounts(mesh):
    """Conservative update needs a globally-sequenced view, so delta mode
    falls back to vanilla psum-of-increments even when CU is configured
    (sketch_kernels._sketch_step). The merged counts are true sums: even
    per-chip traffic far below the limit must accumulate globally and deny
    from the next step on (the pmax-of-targets design this replaces
    undercounted exactly this case)."""
    clock = ManualClock(T0)
    cfg = _cfg(limit=10,
               sketch=SketchParams(depth=2, width=1 << 12, sub_windows=6,
                                   conservative_update=True))
    lim = MeshSketchLimiter(cfg, clock, mesh=mesh, merge="delta")
    # 64 requests pad to 8 per chip (contiguous shard placement), each chip
    # far under limit=10: all 64 admitted in step 1 (documented staleness),
    # then the psum across all 8 chips sums to 64 >= 10 and denies.
    first = lim.allow_batch(["hot"] * 64)
    assert first.allow_count == 64
    out = lim.allow_batch(["hot"] * 64)
    assert out.allow_count == 0


# ------------------------------------------------------- time + lifecycle


def test_window_expiry_on_mesh_gather(mesh):
    clock = ManualClock(T0)
    lim = MeshSketchLimiter(_cfg(limit=8, window=6.0), clock,
                            mesh=mesh, merge="gather")
    assert lim.allow_batch(["k"] * 16).allow_count == 8
    clock.advance(12.0)  # two full windows: state fully expired
    assert lim.allow_batch(["k"] * 16).allow_count == 8


def test_window_expiry_on_mesh_delta(mesh):
    """Delta mode: drive with scalar calls (batch of 1 lands on one chip,
    so local admission is exact); expiry must fully restore quota."""
    clock = ManualClock(T0)
    lim = MeshSketchLimiter(_cfg(limit=8, window=6.0), clock,
                            mesh=mesh, merge="delta")
    assert lim.allow_n("k", 8).allowed
    assert not lim.allow("k").allowed
    clock.advance(12.0)  # two full windows: state fully expired
    assert lim.allow_n("k", 8).allowed


def test_reset_on_mesh_gather(mesh):
    clock = ManualClock(T0)
    lim = MeshSketchLimiter(_cfg(limit=5), clock, mesh=mesh, merge="gather")
    assert lim.allow_batch(["k"] * 8).allow_count == 5
    lim.reset("k")
    assert lim.allow_batch(["k"] * 8).allow_count == 5


def test_reset_on_mesh_delta(mesh):
    clock = ManualClock(T0)
    lim = MeshSketchLimiter(_cfg(limit=5), clock, mesh=mesh, merge="delta")
    assert lim.allow_n("k", 5).allowed
    assert not lim.allow("k").allowed
    lim.reset("k")
    assert lim.allow_n("k", 5).allowed


def test_scalar_api_on_mesh(mesh):
    clock = ManualClock(T0)
    lim = MeshSketchLimiter(_cfg(limit=2), clock, mesh=mesh)
    assert lim.allow("u").allowed
    assert lim.allow("u").allowed
    r = lim.allow("u")
    assert not r.allowed and r.retry_after > 0
