"""Tiny shared helpers for network-using tests (kept out of conftest so
subprocess-spawning tests can import them by module name too)."""

from __future__ import annotations

import socket


def free_port() -> int:
    """An ephemeral port that was free at probe time (the standard
    bind/close/reuse pattern; any future hardening — SO_REUSEADDR,
    retry-on-race — belongs HERE, not in per-file copies)."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port
