"""Checkpoint/restore: crash-equivalence and staleness-contract tests.

The done-criterion (VERDICT r2 item 3): decide -> snapshot -> fresh
limiter -> restore -> decisions consistent with an uncrashed control
limiter, modulo the documented staleness window (decisions after the
snapshot are lost; the restored limiter errs toward allowing).
"""

import numpy as np
import pytest

from ratelimiter_tpu import (
    Algorithm,
    CheckpointError,
    Config,
    ManualClock,
    SketchParams,
    create_limiter,
)

T0 = 1_700_000_000.0


def pair(algo, backend, limit=10, window=60.0, **kw):
    """(limiter, control) with independent ManualClocks at T0."""
    mk = lambda: create_limiter(
        Config(algorithm=algo, limit=limit, window=window, **kw),
        backend=backend, clock=ManualClock(T0))
    return mk, mk()


BACKEND_ALGOS = [
    ("exact", Algorithm.FIXED_WINDOW),
    ("exact", Algorithm.SLIDING_WINDOW),
    ("exact", Algorithm.TOKEN_BUCKET),
    ("dense", Algorithm.FIXED_WINDOW),
    ("dense", Algorithm.SLIDING_WINDOW),
    ("dense", Algorithm.TOKEN_BUCKET),
    ("sketch", Algorithm.TPU_SKETCH),
    ("sketch", Algorithm.FIXED_WINDOW),
    ("sketch", Algorithm.TOKEN_BUCKET),
]


class TestCrashEquivalence:
    @pytest.mark.parametrize("backend,algo", BACKEND_ALGOS,
                             ids=lambda v: str(v))
    def test_restore_matches_uncrashed_control(self, backend, algo, tmp_path):
        """Same op sequence on (snapshot -> crash -> restore) and on an
        uncrashed control must yield identical decisions."""
        path = str(tmp_path / "snap.npz")
        mk, control = pair(algo, backend, limit=10)
        victim = mk()

        ops1 = [("a", 3), ("b", 7), ("a", 4), ("c", 1)]
        for k, n in ops1:
            assert (victim.allow_n(k, n).allowed
                    == control.allow_n(k, n).allowed)
        victim.save(path)
        victim.close()  # the crash

        restored = mk()
        restored.restore(path)
        # Post-restore decisions must match the control step for step —
        # including denials that depend on pre-crash consumption.
        ops2 = [("a", 4), ("a", 3), ("b", 3), ("b", 1), ("c", 9), ("d", 10)]
        for k, n in ops2:
            rv, rc = restored.allow_n(k, n), control.allow_n(k, n)
            assert rv.allowed == rc.allowed, (k, n)
            assert rv.remaining == rc.remaining, (k, n)
        restored.close()
        control.close()

    @pytest.mark.parametrize("backend,algo", BACKEND_ALGOS,
                             ids=lambda v: str(v))
    def test_elapsed_time_catches_up(self, backend, algo, tmp_path):
        """Restoring a snapshot older than the full history horizon behaves
        like a fresh limiter: quotas fully recovered (window expiry or
        bucket refill), nothing stuck."""
        path = str(tmp_path / "snap.npz")
        clock = ManualClock(T0)
        cfg = Config(algorithm=algo, limit=5, window=10.0)
        lim = create_limiter(cfg, backend=backend, clock=clock)
        assert lim.allow_n("k", 5).allowed
        assert not lim.allow("k").allowed
        lim.save(path)
        lim.close()

        clock2 = ManualClock(T0 + 25.0)  # > 2 windows later
        lim2 = create_limiter(cfg, backend=backend, clock=clock2)
        lim2.restore(path)
        assert lim2.allow_n("k", 5).allowed  # full quota back
        lim2.close()

    def test_lost_tail_errs_toward_allowing(self, tmp_path):
        """Decisions AFTER the snapshot are lost: the restored limiter may
        re-admit them (under-count), never over-deny relative to its own
        snapshot — the documented direction."""
        path = str(tmp_path / "snap.npz")
        cfg = Config(algorithm=Algorithm.TPU_SKETCH, limit=10, window=60.0)
        clock = ManualClock(T0)
        lim = create_limiter(cfg, backend="sketch", clock=clock)
        assert lim.allow_n("k", 4).allowed
        lim.save(path)
        assert lim.allow_n("k", 6).allowed   # after snapshot: lost
        assert not lim.allow("k").allowed
        lim.close()

        lim2 = create_limiter(cfg, backend="sketch", clock=ManualClock(T0))
        lim2.restore(path)
        res = lim2.allow_n("k", 6)
        assert res.allowed               # the lost 6 are re-admittable
        assert not lim2.allow("k").allowed
        lim2.close()


class TestMeshCheckpoint:
    def test_mesh_save_restore_preserves_replication(self, tmp_path):
        """Sharding-preserving restore on the mesh: snapshot a replicated
        state, restore into a fresh mesh limiter, decisions continue with
        the global invariant intact."""
        import jax
        import pytest as _pytest

        if len(jax.devices()) < 8:
            _pytest.skip("needs 8 virtual devices")
        from ratelimiter_tpu.parallel import MeshSketchLimiter, make_mesh

        mesh = make_mesh(n_devices=8)
        path = str(tmp_path / "mesh.npz")
        cfg = Config(algorithm=Algorithm.TPU_SKETCH, limit=10, window=60.0,
                     sketch=SketchParams(depth=2, width=256, sub_windows=6))
        lim = MeshSketchLimiter(cfg, ManualClock(T0), mesh=mesh,
                                merge="gather")
        assert lim.allow_batch(["hot"] * 16).allow_count == 10
        lim.save(path)
        lim.close()

        lim2 = MeshSketchLimiter(cfg, ManualClock(T0), mesh=mesh,
                                 merge="gather")
        lim2.restore(path)
        out = lim2.allow_batch(["hot"] * 16)
        assert out.allow_count == 0          # global history restored
        assert lim2.allow_batch(["cold"] * 4).allow_count == 4
        lim2.close()


class TestValidation:
    def test_config_fingerprint_mismatch(self, tmp_path):
        path = str(tmp_path / "snap.npz")
        c1 = Config(algorithm=Algorithm.TPU_SKETCH, limit=10, window=60.0)
        lim = create_limiter(c1, backend="sketch", clock=ManualClock(T0))
        lim.allow("k")
        lim.save(path)
        lim.close()

        c2 = Config(algorithm=Algorithm.TPU_SKETCH, limit=11, window=60.0)
        lim2 = create_limiter(c2, backend="sketch", clock=ManualClock(T0))
        with pytest.raises(CheckpointError, match="fingerprint"):
            lim2.restore(path)
        lim2.close()

    def test_geometry_change_rejected(self, tmp_path):
        path = str(tmp_path / "snap.npz")
        c1 = Config(algorithm=Algorithm.TPU_SKETCH, limit=10, window=60.0,
                    sketch=SketchParams(depth=2, width=1024))
        lim = create_limiter(c1, backend="sketch", clock=ManualClock(T0))
        lim.save(path)
        lim.close()
        c2 = Config(algorithm=Algorithm.TPU_SKETCH, limit=10, window=60.0,
                    sketch=SketchParams(depth=2, width=2048))
        lim2 = create_limiter(c2, backend="sketch", clock=ManualClock(T0))
        with pytest.raises(CheckpointError, match="fingerprint"):
            lim2.restore(path)
        lim2.close()

    def test_kind_mismatch(self, tmp_path):
        path = str(tmp_path / "snap.npz")
        cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=10, window=60.0)
        create_limiter(cfg, backend="exact", clock=ManualClock(T0)).save(path)
        dense = create_limiter(cfg, backend="dense", clock=ManualClock(T0))
        with pytest.raises(CheckpointError, match="kind"):
            dense.restore(path)
        dense.close()

    def test_not_a_checkpoint(self, tmp_path):
        path = tmp_path / "junk.npz"
        np.savez(path, a=np.arange(3))
        cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=10, window=60.0)
        lim = create_limiter(cfg, backend="exact", clock=ManualClock(T0))
        with pytest.raises(CheckpointError):
            lim.restore(str(path))
        lim.close()

    def test_dense_slot_map_round_trips(self, tmp_path):
        path = str(tmp_path / "snap.npz")
        cfg = Config(algorithm=Algorithm.TOKEN_BUCKET, limit=10, window=60.0)
        clock = ManualClock(T0)
        lim = create_limiter(cfg, backend="dense", clock=clock, capacity=64)
        for i in range(40):
            lim.allow(f"user:{i}")
        assert lim.key_count() == 40
        lim.save(path)
        lim.close()
        lim2 = create_limiter(cfg, backend="dense", clock=ManualClock(T0),
                              capacity=64)
        lim2.restore(path)
        assert lim2.key_count() == 40
        # Slot reuse still works post-restore: new keys claim free slots.
        for i in range(40, 64):
            assert lim2.allow(f"user:{i}").allowed
        lim2.close()

    def test_dense_capacity_mismatch(self, tmp_path):
        path = str(tmp_path / "snap.npz")
        cfg = Config(algorithm=Algorithm.TOKEN_BUCKET, limit=10, window=60.0)
        lim = create_limiter(cfg, backend="dense", clock=ManualClock(T0),
                             capacity=64)
        lim.save(path)
        lim.close()
        lim2 = create_limiter(cfg, backend="dense", clock=ManualClock(T0),
                              capacity=128)
        with pytest.raises(CheckpointError, match="capacity"):
            lim2.restore(path)
        lim2.close()


class TestCrashAtomicSave:
    """ISSUE-2 satellite: save_state is crash-atomic on its own — tmp
    write + fsync(file) + os.replace + fsync(dir). A failure injected
    mid-write must leave the previous snapshot byte-identical and no
    tmp litter behind."""

    def _good_snapshot(self, tmp_path):
        path = str(tmp_path / "snap.npz")
        mk, lim = pair(Algorithm.SLIDING_WINDOW, "exact")
        lim.allow_n("a", 7)
        lim.save(path)
        with open(path, "rb") as f:
            golden = f.read()
        return path, mk, lim, golden

    def test_fsync_failure_mid_write_keeps_old_snapshot(
            self, tmp_path, monkeypatch):
        import os as _os

        path, mk, lim, golden = self._good_snapshot(tmp_path)
        lim.allow_n("a", 1)                       # state changed since

        real_fsync = _os.fsync

        def boom(fd):
            raise OSError(28, "No space left on device")

        monkeypatch.setattr("ratelimiter_tpu.checkpoint.os.fsync", boom)
        with pytest.raises(OSError):
            lim.save(path)
        monkeypatch.setattr("ratelimiter_tpu.checkpoint.os.fsync",
                            real_fsync)
        with open(path, "rb") as f:
            assert f.read() == golden              # old snapshot intact
        assert [p for p in tmp_path.iterdir()
                if ".tmp." in p.name] == []        # no tmp litter
        restored = mk()
        restored.restore(path)                     # and still loadable
        assert not restored.allow_n("a", 4).allowed
        restored.close()
        lim.close()

    def test_replace_failure_keeps_old_snapshot(self, tmp_path,
                                                monkeypatch):
        path, mk, lim, golden = self._good_snapshot(tmp_path)

        def boom(src, dst):
            raise OSError("injected replace failure")

        monkeypatch.setattr("ratelimiter_tpu.checkpoint.os.replace", boom)
        with pytest.raises(OSError, match="injected"):
            lim.save(path)
        monkeypatch.undo()
        with open(path, "rb") as f:
            assert f.read() == golden
        assert [p for p in tmp_path.iterdir()
                if ".tmp." in p.name] == []
        lim.close()


class TestGoldenFingerprint:
    """ISSUE-2 satellite: config_fingerprint is pinned to a golden value.

    Every existing snapshot carries its config's fingerprint; ANY change
    to the hash inputs (renamed/added/removed Config fields, changed
    serialization) strands all of them. If this test fails and the
    change was ACCIDENTAL, fix the code until it passes. If the change
    is INTENTIONAL (a new semantic config field must participate), bump
    checkpoint.FORMAT_VERSION, update the golden values below in the
    same commit, and say in the commit message that existing snapshots
    are invalidated.
    """

    GOLDEN = "9ce0bf0e02550dc074f2925212dccb29"

    def test_golden_value(self):
        from ratelimiter_tpu.checkpoint import config_fingerprint

        cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=100,
                     window=60.0)
        fp = config_fingerprint(cfg)
        assert fp == self.GOLDEN, (
            f"config_fingerprint drifted: {fp} != {self.GOLDEN}. This "
            "STRANDS every existing snapshot (restore refuses on "
            "fingerprint mismatch). If unintentional, revert the Config/"
            "fingerprint change; if intentional, bump FORMAT_VERSION and "
            "update TestGoldenFingerprint.GOLDEN in the same commit.")

    def test_persistence_spec_is_excluded(self):
        """Snapshot cadence is operational, not state geometry: changing
        it must NOT strand snapshots."""
        from ratelimiter_tpu import PersistenceSpec
        from ratelimiter_tpu.checkpoint import config_fingerprint

        base = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=100,
                      window=60.0)
        tuned = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=100,
                       window=60.0,
                       persistence=PersistenceSpec(
                           dir="/elsewhere", snapshot_interval=1.0,
                           retain=9, wal_fsync="never"))
        assert config_fingerprint(base) == config_fingerprint(tuned)

    def test_semantic_fields_do_participate(self):
        from dataclasses import replace

        from ratelimiter_tpu.checkpoint import config_fingerprint

        base = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=100,
                      window=60.0)
        for changed in (replace(base, limit=101),
                        replace(base, window=61.0),
                        replace(base, algorithm=Algorithm.FIXED_WINDOW),
                        replace(base, sketch=SketchParams(width=1 << 17))):
            assert config_fingerprint(changed) != config_fingerprint(base)


class TestBackCompat:
    def test_bucket_checkpoint_without_acc_restores(self, tmp_path):
        """The v0.1 token-bucket snapshot had no `acc` (DCN export
        accumulator): it must restore with a zero accumulator instead of
        failing the key-set check (upgrade path)."""
        mk, lim = pair(Algorithm.TOKEN_BUCKET, "sketch")
        lim.allow_n("k", 7)
        path = str(tmp_path / "old.npz")
        lim.save(path)
        # Rewrite the snapshot as a pre-`acc` release would have laid
        # it out (same meta, `acc` array absent).
        with np.load(path, allow_pickle=False) as z:
            arrays = {k: z[k] for k in z.files if k != "acc"}
        np.savez(path, **arrays)
        lim2 = mk()
        lim2.restore(path)
        # The defaulted accumulator exports nothing stale.
        from ratelimiter_tpu.parallel.dcn import export_debt

        assert export_debt(lim2).sum() == 0
        assert lim2.allow_n("k", 3).allowed        # 7 + 3 = limit
        assert not lim2.allow("k").allowed
        lim.close()
        lim2.close()
