"""Zero-copy hashed wire lane — protocol + both front doors (ADR-011).

T_ALLOW_HASHED carries raw u64 key ids columnar; the server parses them
as np.frombuffer views, stages them with one memcpy, hashes ON DEVICE,
and answers columnar T_RESULT_HASHED (device-packed via pack_wire on the
asyncio door). These tests pin the frame formats, the end-to-end
equivalence with the direct limiter lane, and the error surface on both
doors.
"""

from __future__ import annotations

import asyncio

import numpy as np
import pytest

from ratelimiter_tpu import Algorithm, Config, SketchParams
from ratelimiter_tpu.algorithms.sketch import SketchLimiter
from ratelimiter_tpu.core.clock import ManualClock
from ratelimiter_tpu.core.errors import InvalidConfigError, InvalidNError
from ratelimiter_tpu.core.types import BatchResult
from ratelimiter_tpu.serving import protocol as p

T0 = 1_000_000.0


def _cfg(**kw) -> Config:
    return Config(algorithm=Algorithm.SLIDING_WINDOW, limit=5, window=10.0,
                  sketch=SketchParams(depth=3, width=256, sub_windows=5),
                  **kw)


# ------------------------------------------------------------- protocol


def test_allow_hashed_roundtrip_is_columnar_and_zero_copy():
    ids = np.arange(10, 30, dtype=np.uint64)
    ns = np.arange(1, 21, dtype=np.uint32)
    frame = p.encode_allow_hashed(7, ids, ns)
    length, type_, req_id = p.parse_header(frame[:p.HEADER_SIZE])
    assert (type_, req_id) == (p.T_ALLOW_HASHED, 7)
    body = frame[p.HEADER_SIZE:]
    assert len(body) == length - 9
    got_ids, got_ns = p.parse_allow_hashed(body)
    np.testing.assert_array_equal(got_ids, ids)
    np.testing.assert_array_equal(got_ns, ns)
    # Zero copy: the views alias the body buffer, no materialization.
    assert got_ids.base is not None and not got_ids.flags.writeable


def test_parse_allow_hashed_rejects_malformed():
    with pytest.raises(p.ProtocolError):
        p.parse_allow_hashed(b"\x01")
    body = p._HASHED_HEAD.pack(3) + b"\x00" * 20  # 3 items need 36 B
    with pytest.raises(p.ProtocolError):
        p.parse_allow_hashed(body)


def test_result_hashed_views_are_zero_copy_and_frame_identical():
    """ISSUE-5 satellite (the named ADR-011 residual): the writev-style
    reply builder must frame the three value columns as MEMORYVIEWS
    straight over the device-fetched wire_packed words buffer — buffer
    identity asserted via np.shares_memory — with no intermediate
    per-frame bytes join, and the concatenation of the views must be
    byte-identical to the single-buffer encoder."""
    lim = SketchLimiter(_cfg(), ManualClock(T0))
    ids = np.arange(1, 42, dtype=np.uint64)  # 41 ids: partial mask byte
    res = lim.resolve(lim.launch_ids(ids, wire=True))
    assert res.wire_packed is not None
    _bits, words, _padded = res.wire_packed

    views = p.encode_result_hashed_views(9, res)
    assert len(views) == 4
    # Zero extra copies: every column view aliases the resolve fetch.
    for v in views[1:]:
        assert isinstance(v, memoryview)
        assert np.shares_memory(np.frombuffer(v, dtype=np.uint8), words)
    # And the scatter-gather list is the SAME frame the bytes encoder
    # builds (parseable by the client untouched).
    joined = b"".join(bytes(v) for v in views)
    assert joined == p.encode_result_hashed(9, res)
    parsed = p.parse_result_hashed(joined[p.HEADER_SIZE:])
    np.testing.assert_array_equal(parsed.allowed, res.allowed)
    np.testing.assert_array_equal(parsed.remaining, res.remaining)
    lim.close()


def test_result_hashed_views_fall_back_without_packed_buffers():
    res = BatchResult(
        allowed=np.array([True, False, True]),
        limit=5,
        remaining=np.array([4, 0, 3], dtype=np.int64),
        retry_after=np.array([0.0, 1.5, 0.0]),
        reset_at=np.array([T0 + 10] * 3),
    )
    views = p.encode_result_hashed_views(3, res)
    assert len(views) == 1
    assert bytes(views[0]) == p.encode_result_hashed(3, res)


def test_result_hashed_roundtrip():
    res = BatchResult(
        allowed=np.array([True, False, True, True, False]),
        limit=42,
        remaining=np.array([4, 0, 1, 2, 0], np.int64),
        retry_after=np.array([0.0, 1.5, 0.0, 0.0, 2.25]),
        reset_at=np.full(5, 123.5),
        fail_open=True,
    )
    frame = p.encode_result_hashed(9, res)
    _, type_, req_id = p.parse_header(frame[:p.HEADER_SIZE])
    assert (type_, req_id) == (p.T_RESULT_HASHED, 9)
    back = p.parse_result_hashed(frame[p.HEADER_SIZE:])
    np.testing.assert_array_equal(back.allowed, res.allowed)
    np.testing.assert_array_equal(back.remaining, res.remaining)
    np.testing.assert_array_equal(back.retry_after, res.retry_after)
    np.testing.assert_array_equal(back.reset_at, res.reset_at)
    assert back.limit == 42 and back.fail_open


# ------------------------------------------------------- asyncio door


def _run(coro):
    return asyncio.run(coro)


def test_asyncio_door_hashed_lane_matches_direct():
    from ratelimiter_tpu.serving.client import AsyncClient
    from ratelimiter_tpu.serving.server import run_server

    async def main():
        lim = SketchLimiter(_cfg(), ManualClock(T0))
        oracle = SketchLimiter(_cfg(), ManualClock(T0))
        srv = await run_server(lim, port=0)
        c = await AsyncClient.connect(port=srv.port)
        rng = np.random.default_rng(2)
        try:
            for _ in range(4):
                ids = rng.integers(1, 30, size=50).astype(np.uint64)
                ns = rng.integers(1, 3, size=50).astype(np.uint32)
                got = await c.allow_hashed(ids, ns)
                want = oracle.allow_ids(ids, ns.astype(np.int64))
                np.testing.assert_array_equal(got.allowed, want.allowed)
                np.testing.assert_array_equal(got.remaining, want.remaining)
                np.testing.assert_array_equal(got.retry_after,
                                              want.retry_after)
                np.testing.assert_array_equal(got.reset_at, want.reset_at)
                assert got.limit == want.limit
        finally:
            await c.close()
            await srv.shutdown()
            lim.close()
            oracle.close()

    _run(main())


def test_asyncio_door_hashed_errors_and_empty():
    from ratelimiter_tpu.serving.client import AsyncClient
    from ratelimiter_tpu.serving.server import run_server

    async def main():
        lim = SketchLimiter(_cfg(), ManualClock(T0))
        srv = await run_server(lim, port=0)
        c = await AsyncClient.connect(port=srv.port)
        try:
            empty = await c.allow_hashed(np.zeros(0, np.uint64))
            assert len(empty) == 0
            with pytest.raises(InvalidNError):
                await c.allow_hashed(np.arange(3, dtype=np.uint64),
                                     np.zeros(3, np.uint32))
        finally:
            await c.close()
            await srv.shutdown()
            lim.close()

    _run(main())


def test_asyncio_door_hashed_rejects_non_sketch_backend():
    from ratelimiter_tpu.algorithms.exact import ExactLimiter
    from ratelimiter_tpu.serving.client import AsyncClient
    from ratelimiter_tpu.serving.server import run_server

    async def main():
        lim = ExactLimiter(Config(algorithm=Algorithm.FIXED_WINDOW,
                                  limit=5, window=10.0), ManualClock(T0))
        srv = await run_server(lim, port=0)
        c = await AsyncClient.connect(port=srv.port)
        try:
            with pytest.raises(InvalidConfigError):
                await c.allow_hashed(np.arange(3, dtype=np.uint64))
        finally:
            await c.close()
            await srv.shutdown()
            lim.close()

    _run(main())


def test_hashed_lane_interleaves_with_string_lane():
    """Hashed frames and string traffic share the batcher's pipeline:
    both lanes answer correctly on one connection, and per-key ordering
    within each lane holds."""
    from ratelimiter_tpu.serving.client import AsyncClient
    from ratelimiter_tpu.serving.server import run_server

    async def main():
        lim = SketchLimiter(_cfg(), ManualClock(T0))
        srv = await run_server(lim, port=0)
        c = await AsyncClient.connect(port=srv.port)
        try:
            ids = np.full(3, 99, dtype=np.uint64)
            r1, s1, r2 = await asyncio.gather(
                c.allow_hashed(ids),
                c.allow_n("stringkey", 1),
                c.allow_hashed(ids))
            # limit 5 on one id: 3 + at most 2 more allowed.
            assert int(r1.allowed.sum()) + int(r2.allowed.sum()) == 5
            assert s1.allowed
        finally:
            await c.close()
            await srv.shutdown()
            lim.close()

    _run(main())


# ------------------------------------------------ decorator interposition


def test_circuit_breaker_guards_hashed_lane():
    """The breaker must admit/judge hashed-lane dispatches exactly like
    string batches: hashed failures open it, and while OPEN the hashed
    lane is short-circuited (no device work enqueued) — the review gap
    that motivated the explicit decorator delegation (ADR-011)."""
    from ratelimiter_tpu.observability.decorators import (
        CircuitBreakerDecorator,
    )

    inner = SketchLimiter(_cfg(fail_open=True), ManualClock(T0))
    lim = CircuitBreakerDecorator(inner, failure_threshold=2,
                                  cooldown=60.0)
    try:
        ids = np.arange(1, 9, dtype=np.uint64)
        assert lim.allow_ids(ids).allowed.all()
        inner.inject_failure()
        # Failures through the HASHED lane must trip the breaker.
        for _ in range(2):
            out = lim.allow_ids(ids)
            assert out.fail_open
        assert lim.state == "open"
        inner.heal()
        # While open, hashed launches are short-circuited — no dispatch
        # reaches the backend (its counters must not move).
        before = inner.in_window_admitted_mass()
        t = lim.launch_ids(ids, wire=True)
        out = lim.resolve(t)
        assert out.fail_open
        assert inner.in_window_admitted_mass() == before
    finally:
        lim.close()


def test_metrics_decorator_observes_hashed_lane():
    from ratelimiter_tpu.observability.decorators import MetricsDecorator
    from ratelimiter_tpu.observability.metrics import Registry

    reg = Registry()
    inner = SketchLimiter(_cfg(), ManualClock(T0))
    lim = MetricsDecorator(inner, registry=reg)
    try:
        lim.allow_ids(np.arange(1, 9, dtype=np.uint64))
        text = reg.render()
        assert ('rate_limiter_decisions_allowed_total'
                '{algorithm="sliding_window"} 8') in text
    finally:
        lim.close()


# -------------------------------------------------------- native door


needs_native = pytest.mark.skipif(
    not __import__("ratelimiter_tpu.serving.native_server",
                   fromlist=["native_server_available"]
                   ).native_server_available(),
    reason="native server extension unavailable (no g++)")


@needs_native
@pytest.mark.parametrize("shards", [1, 3])
def test_native_door_hashed_lane_matches_direct(shards):
    from ratelimiter_tpu.ops.hashing import splitmix64
    from ratelimiter_tpu.serving.client import Client
    from ratelimiter_tpu.serving.native_server import NativeRateLimitServer

    lim = SketchLimiter(_cfg())
    srv = NativeRateLimitServer(lim, port=0, shards=shards, inflight=4)
    srv.start()
    c = Client(port=srv.port)
    oracles = [SketchLimiter(_cfg()) for _ in range(shards)]
    try:
        rng = np.random.default_rng(4)
        for _ in range(3):
            ids = rng.integers(1, 40, size=64).astype(np.uint64)
            got = c.allow_hashed(ids)
            assert len(got) == 64
            # Oracle: per-shard replay with the same routing (C++ routes
            # on the finalized hash; shard_of_id is the Python mirror).
            want_allowed = np.zeros(64, bool)
            by_shard = {}
            for i, raw in enumerate(ids.tolist()):
                by_shard.setdefault(srv.shard_of_id(raw), []).append(i)
            fin = splitmix64(ids)
            for sh, idxs in by_shard.items():
                out = oracles[sh].allow_hashed(fin[idxs])
                want_allowed[idxs] = out.allowed
            np.testing.assert_array_equal(got.allowed, want_allowed)
    finally:
        c.close()
        srv.shutdown()
        lim.close()
        for o in oracles:
            o.close()


@needs_native
def test_native_door_hashed_error_surface():
    from ratelimiter_tpu.algorithms.exact import ExactLimiter
    from ratelimiter_tpu.serving.client import Client
    from ratelimiter_tpu.serving.native_server import NativeRateLimitServer

    lim = SketchLimiter(_cfg())
    srv = NativeRateLimitServer(lim, port=0, inflight=4)
    srv.start()
    c = Client(port=srv.port)
    try:
        with pytest.raises(InvalidNError):
            c.allow_hashed(np.arange(3, dtype=np.uint64),
                           np.zeros(3, np.uint32))
        assert len(c.allow_hashed(np.zeros(0, np.uint64))) == 0
    finally:
        c.close()
        srv.shutdown()
        lim.close()

    # A non-sketch backend answers E_INVALID_CONFIG for hashed frames.
    elim = ExactLimiter(Config(algorithm=Algorithm.FIXED_WINDOW, limit=5,
                               window=10.0))
    esrv = NativeRateLimitServer(elim, port=0)
    esrv.start()
    ec = Client(port=esrv.port)
    try:
        with pytest.raises(InvalidConfigError):
            ec.allow_hashed(np.arange(3, dtype=np.uint64))
    finally:
        ec.close()
        esrv.shutdown()
        elim.close()
