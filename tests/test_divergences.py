"""Pins for every deliberate divergence from the reference implementation.

SURVEY.md §7.4.7: where the reference's code contradicts its own documented
contract, this framework follows the contract — each such decision is pinned
here with the reference citation, so the divergence is explicit and tested
rather than accidental.
"""

import pytest

from ratelimiter_tpu import (
    Algorithm,
    Config,
    InvalidKeyError,
    ManualClock,
    create_limiter,
)


def make(algo, **kw):
    clock = ManualClock()
    cfg = Config(algorithm=algo, limit=kw.pop("limit", 10), window=kw.pop("window", 60.0), **kw)
    return create_limiter(cfg, backend="exact", clock=clock), clock


@pytest.mark.parametrize("algo", [Algorithm.FIXED_WINDOW, Algorithm.SLIDING_WINDOW])
def test_denied_allow_n_consumes_nothing_in_windows(algo):
    """Reference FW/SW increment unconditionally before checking
    (``fixedwindow.go:22``, ``slidingwindow.go:24``), so a denied AllowN(5)
    inflates the counter and a following AllowN(2) is wrongly denied —
    violating the documented contract ``interface.go:104-105`` (SURVEY.md
    §2.4.2). We follow the contract: after 9/10 consumed, a denied AllowN(5)
    leaves quota at 9, and AllowN(1) still succeeds."""
    lim, _ = make(algo, limit=10)
    assert lim.allow_n("k", 9).allowed
    assert not lim.allow_n("k", 5).allowed
    res = lim.allow_n("k", 1)
    assert res.allowed  # the reference's FW/SW would deny here
    lim.close()


def test_empty_key_is_validated():
    """Reference defines ErrInvalidKey (``errors.go:13``) and its dormant
    contract suite expects it (``interface_test.go:246-251``), but no code
    path validates keys (SURVEY.md §2.4.11). We validate."""
    lim, _ = make(Algorithm.TOKEN_BUCKET)
    with pytest.raises(InvalidKeyError):
        lim.allow("")
    lim.close()


def test_close_does_not_kill_shared_state():
    """Reference Close() closes the *injected shared* redis client
    (``tokenbucket.go:147-152``), so closing one limiter breaks every other
    limiter sharing it (SURVEY.md §2.4.13). Here close() only invalidates the
    closed limiter."""
    clock = ManualClock()
    cfg = Config(algorithm=Algorithm.FIXED_WINDOW, limit=5, window=60.0)
    a = create_limiter(cfg, backend="exact", clock=clock)
    b = create_limiter(cfg, backend="exact", clock=clock)
    a.close()
    assert b.allow("k").allowed  # unaffected
    b.close()


def test_fw_reset_equivalent_to_current_window_delete():
    """Reference FW Reset deletes only the current window's key
    (``fixedwindow.go:118-128``, §2.4.12). Expired windows can never affect a
    decision, so clearing all state is observationally equivalent — shown
    here: state from an old window has no effect either way."""
    lim, clock = make(Algorithm.FIXED_WINDOW, limit=2, window=10.0)
    clock.set(1000.0)
    lim.allow_n("k", 2)
    clock.set(1015.0)          # old window expired on its own
    assert lim.allow("k").allowed
    lim.reset("k")
    assert lim.allow("k").allowed
    lim.close()


def test_empty_prefix_reachable():
    """SURVEY.md §2.4.8: reference makes the documented empty-prefix behavior
    unreachable. Here Config(key_prefix="") is honored."""
    cfg = Config(algorithm=Algorithm.FIXED_WINDOW, limit=5, window=60.0, key_prefix="")
    assert cfg.with_defaults().format_key("user") == "user"
