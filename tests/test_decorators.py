"""Observability decorators: contract conformance + emitted metrics/logs.

The reference specs the decorators but never builds them
(``docs/ADR/003-decorator-pattern-for-observability.md:44-125``); its
planned test — "decorated limiter passes the same suite" — is realized
here by instantiating the full contract suite over a metrics+logging
decorated exact limiter.
"""

import logging

import numpy as np
import pytest

from tests.contract import ContractTests

from ratelimiter_tpu import Algorithm, Config, ManualClock, create_limiter
from ratelimiter_tpu.observability import (
    LoggingDecorator,
    MetricsDecorator,
    Registry,
)


class TestDecoratedContract(ContractTests):
    """The whole contract suite through a decorator stack — decorators must
    be semantically invisible (ADR/003's composability requirement)."""

    backend = "exact"

    def make_limiter(self, config, clock):
        inner = create_limiter(config, backend="exact", clock=clock)
        return MetricsDecorator(LoggingDecorator(inner), Registry())


def make(algo=Algorithm.SLIDING_WINDOW, limit=5, window=60.0, backend="exact",
         **kw):
    clock = ManualClock(1_700_000_000.0)
    cfg = Config(algorithm=algo, limit=limit, window=window, **kw)
    reg = Registry()
    lim = MetricsDecorator(create_limiter(cfg, backend=backend, clock=clock), reg)
    return lim, reg, clock


class TestMetricsDecorator:
    def test_requests_by_result(self):
        lim, reg, _ = make(limit=2)
        lim.allow("k")
        lim.allow("k")
        lim.allow("k")  # denied
        c = reg.get("rate_limiter_requests_total")
        assert c.value(algorithm="sliding_window", result="allowed") == 2
        assert c.value(algorithm="sliding_window", result="denied") == 1
        assert reg.get("rate_limiter_decisions_allowed_total").value(
            algorithm="sliding_window") == 2
        assert reg.get("rate_limiter_decisions_denied_total").value(
            algorithm="sliding_window") == 1
        lim.close()

    def test_batch_counts_decisions(self):
        lim, reg, _ = make(limit=3)
        out = lim.allow_batch(["a"] * 5)
        assert out.allow_count == 3
        assert reg.get("rate_limiter_decisions_allowed_total").value(
            algorithm="sliding_window") == 3
        assert reg.get("rate_limiter_decisions_denied_total").value(
            algorithm="sliding_window") == 2
        h = reg.get("rate_limiter_batch_size")
        assert h.count() == 1 and h.sum() == 5.0
        lim.close()

    def test_latency_histogram_observes(self):
        lim, reg, _ = make()
        lim.allow("k")
        h = reg.get("rate_limiter_latency_seconds")
        assert h.count(algorithm="sliding_window", op="allow_n") == 1
        assert h.sum(algorithm="sliding_window", op="allow_n") > 0
        lim.close()

    def test_invalid_n_counted_as_error(self):
        from ratelimiter_tpu import InvalidNError

        lim, reg, _ = make()
        with pytest.raises(InvalidNError):
            lim.allow_n("k", 0)
        c = reg.get("rate_limiter_requests_total")
        assert c.value(algorithm="sliding_window", result="error:invalid_n") == 1
        lim.close()

    def test_fail_open_counted_as_storage_error(self):
        lim, reg, _ = make(backend="sketch", algo=Algorithm.TPU_SKETCH,
                           fail_open=True)
        lim.inject_failure()  # __getattr__ pass-through to the sketch backend
        res = lim.allow("k")
        assert res.allowed and res.fail_open
        assert reg.get("rate_limiter_storage_errors_total").value(
            algorithm="tpu_sketch") == 1
        c = reg.get("rate_limiter_requests_total")
        assert c.value(algorithm="tpu_sketch", result="fail_open") == 1
        lim.close()

    def test_fail_closed_counted_as_storage_error(self):
        from ratelimiter_tpu import StorageUnavailableError

        lim, reg, _ = make(backend="sketch", algo=Algorithm.TPU_SKETCH,
                           fail_open=False)
        lim.inject_failure()
        with pytest.raises(StorageUnavailableError):
            lim.allow("k")
        assert reg.get("rate_limiter_storage_errors_total").value(
            algorithm="tpu_sketch") == 1
        lim.close()

    def test_prometheus_rendering(self):
        lim, reg, _ = make(limit=1)
        lim.allow("k")
        lim.allow("k")
        text = reg.render()
        assert "# TYPE rate_limiter_requests_total counter" in text
        assert ('rate_limiter_requests_total{algorithm="sliding_window",'
                'result="allowed"} 1') in text
        assert "# TYPE rate_limiter_latency_seconds histogram" in text
        assert "rate_limiter_latency_seconds_bucket" in text
        assert 'le="+Inf"' in text
        lim.close()


class TestLoggingDecorator:
    def test_decisions_logged_at_debug(self, caplog):
        clock = ManualClock(0.0)
        cfg = Config(algorithm=Algorithm.FIXED_WINDOW, limit=1, window=60.0)
        lim = LoggingDecorator(create_limiter(cfg, clock=clock))
        with caplog.at_level(logging.DEBUG, logger="ratelimiter_tpu"):
            lim.allow("k")
            lim.allow("k")
        msgs = [r.message for r in caplog.records]
        assert any("allowed=True" in s for s in msgs)
        assert any("allowed=False" in s for s in msgs)
        lim.close()

    def test_fail_open_logged_at_warning(self, caplog):
        clock = ManualClock(0.0)
        cfg = Config(algorithm=Algorithm.TPU_SKETCH, limit=5, window=60.0,
                     fail_open=True)
        lim = LoggingDecorator(create_limiter(cfg, backend="sketch", clock=clock))
        lim.inject_failure()
        with caplog.at_level(logging.WARNING, logger="ratelimiter_tpu"):
            lim.allow("k")
        assert any(r.levelno == logging.WARNING and "fail-open" in r.message
                   for r in caplog.records)
        lim.close()

    def test_errors_logged_at_error(self, caplog):
        from ratelimiter_tpu import InvalidNError

        clock = ManualClock(0.0)
        cfg = Config(algorithm=Algorithm.FIXED_WINDOW, limit=1, window=60.0)
        lim = LoggingDecorator(create_limiter(cfg, clock=clock))
        with caplog.at_level(logging.ERROR, logger="ratelimiter_tpu"):
            with pytest.raises(InvalidNError):
                lim.allow_n("k", -1)
        assert any(r.levelno == logging.ERROR for r in caplog.records)
        lim.close()


class TestTracingDecorator:
    def test_contract_preserved_and_capture_writes(self, tmp_path):
        from ratelimiter_tpu.observability import TracingDecorator

        clock = ManualClock(0.0)
        cfg = Config(algorithm=Algorithm.TPU_SKETCH, limit=3, window=60.0)
        lim = TracingDecorator(create_limiter(cfg, backend="sketch",
                                              clock=clock))
        # Semantics unchanged through the annotation wrapper.
        for expect in (True, True, True, False):
            assert lim.allow("k").allowed is expect
        lim.reset("k")
        assert lim.allow("k").allowed
        # capture() produces an xplane trace directory.
        out = str(tmp_path / "trace")
        with lim.capture(out):
            lim.allow_batch(["a", "b", "c"])
        import os

        assert any("plugins" in d or f for d, _, f in os.walk(out)), \
            "profiler capture wrote nothing"
        lim.close()


class TestDecoratorComposition:
    def test_stack_order_is_transparent(self):
        clock = ManualClock(0.0)
        cfg = Config(algorithm=Algorithm.TOKEN_BUCKET, limit=3, window=30.0)
        reg = Registry()
        lim = LoggingDecorator(
            MetricsDecorator(create_limiter(cfg, clock=clock), reg))
        for expect in (True, True, True, False):
            assert lim.allow("k").allowed is expect
        assert reg.get("rate_limiter_decisions_allowed_total").value(
            algorithm="token_bucket") == 3
        lim.close()

    def test_passthrough_extras(self):
        # Backend-specific surface (allow_hashed) stays reachable.
        clock = ManualClock(0.0)
        cfg = Config(algorithm=Algorithm.TPU_SKETCH, limit=100, window=60.0)
        lim = MetricsDecorator(
            create_limiter(cfg, backend="sketch", clock=clock), Registry())
        out = lim.allow_hashed(np.arange(8, dtype=np.uint64))
        assert out.allow_count == 8
        lim.close()
