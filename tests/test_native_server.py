"""Native (C++) front door: the asyncio server's test scenarios against
the epoll implementation — same protocol, same clients, same semantics.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager

import pytest

from ratelimiter_tpu import (
    Algorithm,
    Config,
    InvalidKeyError,
    InvalidNError,
    ManualClock,
    StorageUnavailableError,
    create_limiter,
)
from ratelimiter_tpu.serving import Client
from ratelimiter_tpu.serving.native_server import (
    NativeRateLimitServer,
    native_server_available,
)

pytestmark = pytest.mark.skipif(
    not native_server_available(), reason="needs g++ for the native server")


def _mk_limiter(limit=100, window=60.0, algo=Algorithm.SLIDING_WINDOW,
                backend="exact", **kw):
    clock = ManualClock(1_700_000_000.0)
    cfg = Config(algorithm=algo, limit=limit, window=window, **kw)
    return create_limiter(cfg, backend=backend, clock=clock), clock


@contextmanager
def running(limiter, **kw):
    srv = NativeRateLimitServer(limiter, "127.0.0.1", 0, **kw)
    srv.start()
    try:
        yield srv, srv.port
    finally:
        srv.shutdown()


class TestNativeServer:
    def test_allow_deny_over_the_wire(self):
        lim, _ = _mk_limiter(limit=3)
        with running(lim) as (_, port):
            with Client(port=port) as c:
                for i in range(3):
                    res = c.allow("user:1")
                    assert res.allowed and res.remaining == 2 - i
                res = c.allow("user:1")
                assert not res.allowed and res.retry_after > 0
        lim.close()

    def test_allow_n_and_reset(self):
        lim, _ = _mk_limiter(limit=10)
        with running(lim) as (_, port):
            with Client(port=port) as c:
                assert c.allow_n("k", 10).allowed
                assert not c.allow("k").allowed
                c.reset("k")
                assert c.allow("k").allowed
        lim.close()

    def test_batch_rpc_exactness(self):
        lim, _ = _mk_limiter(limit=3)
        with running(lim) as (_, port):
            with Client(port=port) as c:
                res = c.allow_batch(["h", "h", "h", "h", "x"], [1, 1, 1, 1, 2])
                assert [r.allowed for r in res] == [True, True, True, False,
                                                   True]
                assert res[0].limit == 3
        lim.close()

    def test_validation_errors_typed(self):
        lim, _ = _mk_limiter()
        with running(lim) as (_, port):
            with Client(port=port) as c:
                with pytest.raises(InvalidNError):
                    c.allow_n("k", 0)
                with pytest.raises(InvalidKeyError):
                    c.allow("")
                with pytest.raises(InvalidNError):
                    c.allow_batch(["a", "b"], [1, 0])
                assert c.allow("k").allowed  # connection survives
        lim.close()

    def test_health_and_metrics(self):
        from ratelimiter_tpu.observability import Registry

        lim, _ = _mk_limiter()
        with running(lim, registry=Registry()) as (srv, port):
            with Client(port=port) as c:
                serving, uptime, decisions = c.health()
                assert serving and decisions == 0
                c.allow("k")
                _, _, decisions = c.health()
                assert decisions == 1
                assert "rate_limiter_server_batch_size" in c.metrics()
            assert srv.stats()["decisions_total"] == 1
        lim.close()

    def test_concurrent_clients_global_exactness(self):
        """The flagship invariant through the native batcher: 150
        concurrent requests on a limit-100 key admit exactly 100."""
        lim, _ = _mk_limiter(limit=100)
        with running(lim, max_batch=512, max_delay=2e-3) as (_, port):
            allowed = []
            lock = threading.Lock()

            def worker(count):
                with Client(port=port) as c:
                    mine = [c.allow("hot").allowed for _ in range(count)]
                with lock:
                    allowed.extend(mine)

            threads = [threading.Thread(target=worker, args=(15,))
                       for _ in range(10)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert len(allowed) == 150
            assert sum(allowed) == 100
        lim.close()

    def test_sketch_fast_path_with_prefix(self):
        """Sketch limiters take the no-decode packed-hash path; the key
        prefix must namespace exactly like the string path."""
        lim, _ = _mk_limiter(limit=4, algo=Algorithm.TPU_SKETCH,
                             backend="sketch", key_prefix="app1")
        with running(lim) as (_, port):
            with Client(port=port) as c:
                for _ in range(4):
                    assert c.allow("user:7").allowed
                assert not c.allow("user:7").allowed
        # Same counters as the library path under the same prefix.
        assert not lim.allow("user:7").allowed
        lim.close()

    def test_fail_open_through_native_server(self):
        lim, _ = _mk_limiter(limit=5, algo=Algorithm.TPU_SKETCH,
                             backend="sketch", fail_open=True)
        with running(lim) as (_, port):
            with Client(port=port) as c:
                assert c.allow("k").allowed
                lim.inject_failure()
                res = c.allow("k")
                assert res.allowed and res.fail_open
        lim.close()

    def test_fail_closed_through_native_server(self):
        lim, _ = _mk_limiter(limit=5, algo=Algorithm.TPU_SKETCH,
                             backend="sketch", fail_open=False)
        with running(lim) as (_, port):
            with Client(port=port) as c:
                assert c.allow("k").allowed
                lim.inject_failure()
                with pytest.raises(StorageUnavailableError):
                    c.allow("k")
        lim.close()

    def test_unicode_keys(self):
        lim, _ = _mk_limiter(limit=2)
        with running(lim) as (_, port):
            with Client(port=port) as c:
                assert c.allow("ключ:héllo").allowed
                assert c.allow("ключ:héllo").allowed
                assert not c.allow("ключ:héllo").allowed
        lim.close()

    def test_invalid_utf8_key_rejected(self):
        """The native frame parser validates UTF-8 so both front doors
        accept the same key space (the asyncio server decodes keys; a raw
        bytes key that can't decode must not be silently hashed here,
        since reset() could never name it)."""
        import socket
        import struct

        from ratelimiter_tpu.serving import protocol as p

        lim, _ = _mk_limiter(limit=5, algo=Algorithm.TPU_SKETCH,
                             backend="sketch")
        with running(lim) as (_, port):
            with socket.create_connection(("127.0.0.1", port)) as sk:
                bad = b"\xff\xfekey"          # invalid UTF-8
                body = struct.pack("<IH", 1, len(bad)) + bad
                sk.sendall(struct.pack("<IBQ", 1 + 8 + len(body),
                                       p.T_ALLOW_N, 7) + body)
                hdr = sk.recv(13, socket.MSG_WAITALL)
                length, type_, req_id = p.parse_header(hdr)
                assert type_ == p.T_ERROR and req_id == 7
                rest = sk.recv(length - 9, socket.MSG_WAITALL)
                code, mlen = struct.unpack_from("<HH", rest)
                assert code == p.E_INVALID_KEY
                # Bad in both ways (n=0 AND undecodable key): the key
                # error wins, matching the asyncio server's parse order.
                body = struct.pack("<IH", 0, len(bad)) + bad
                sk.sendall(struct.pack("<IBQ", 1 + 8 + len(body),
                                       p.T_ALLOW_N, 8) + body)
                hdr = sk.recv(13, socket.MSG_WAITALL)
                length, type_, req_id = p.parse_header(hdr)
                rest = sk.recv(length - 9, socket.MSG_WAITALL)
                code, _ = struct.unpack_from("<HH", rest)
                assert req_id == 8 and code == p.E_INVALID_KEY
            # Well-formed keys still work on a fresh connection.
            with Client(port=port) as c:
                assert c.allow("ok").allowed
        lim.close()

    def test_batch_error_precedence_matches_asyncio(self):
        """Cross-pair error precedence parity: a batch frame bad in two
        ways answers the same typed error from either front door (the
        asyncio path validates per pair, key before n, after decoding
        every key at parse time — the native parser mirrors that)."""
        cases = [
            ((["a", ""], [0, 1]), InvalidNError),    # early n=0 beats later empty key
            ((["", "a"], [0, 1]), InvalidKeyError),  # early empty key wins
            ((["a", ""], [1, 0]), InvalidKeyError),  # early empty key beats later n=0
        ]
        lim, _ = _mk_limiter()
        with running(lim) as (_, port):
            with Client(port=port) as c:
                for (keys, ns), exc in cases:
                    with pytest.raises(exc):
                        c.allow_batch(keys, ns)
        lim.close()
        # Same frames through the asyncio server (imported lazily to keep
        # this module native-focused).
        import asyncio as aio

        from ratelimiter_tpu.serving.server import RateLimitServer

        lim2, _ = _mk_limiter()
        loop = aio.new_event_loop()
        t = threading.Thread(target=loop.run_forever, daemon=True)
        t.start()
        srv = RateLimitServer(lim2, "127.0.0.1", 0)
        aio.run_coroutine_threadsafe(srv.start(), loop).result(10)
        try:
            with Client(port=srv.port) as c:
                for (keys, ns), exc in cases:
                    with pytest.raises(exc):
                        c.allow_batch(keys, ns)
        finally:
            aio.run_coroutine_threadsafe(srv.shutdown(), loop).result(10)
            loop.call_soon_threadsafe(loop.stop)
            t.join(timeout=10)
            loop.close()
        lim2.close()

    def test_pipelined_coalescing(self):
        """Many concurrent scalar requests share dispatches (batch-size
        histogram must show multi-request batches)."""
        import asyncio

        from ratelimiter_tpu.observability import Registry
        from ratelimiter_tpu.serving import AsyncClient

        reg = Registry()
        lim, _ = _mk_limiter(limit=100000)
        with running(lim, registry=reg, max_batch=4096,
                     max_delay=5e-3) as (_, port):
            async def burst():
                c = await AsyncClient.connect(port=port)
                res = await c.allow_many([f"k{i % 50}" for i in range(400)])
                await c.close()
                return res

            res = asyncio.run(burst())
            assert all(r.allowed for r in res
                       if not isinstance(r, Exception))
        h = reg.get("rate_limiter_server_batch_size")
        assert h.count() < 400 and h.sum() == 400.0
        lim.close()

    def test_slo_breach_fail_open(self):
        """Dispatch exceeding the SLO answers waiters fail-open while the
        Python decide completes; the breach is counted; the server keeps
        serving afterward."""
        import time

        lim, _ = _mk_limiter(limit=5, fail_open=True)
        slow = _SlowOnce(lim, delay=0.3)
        srv = NativeRateLimitServer(slow, "127.0.0.1", 0,
                                    max_delay=1e-4, dispatch_timeout=0.03)
        srv.start()
        try:
            with Client(port=srv.port) as c:
                t0 = time.perf_counter()
                res = c.allow("k")
                dt = time.perf_counter() - t0
                assert res.allowed and res.fail_open
                assert dt < 0.25  # answered at the SLO, not at 0.3 s
                assert srv.stats()["slo_breaches_total"] == 1
                time.sleep(0.35)  # let the late dispatch land
                res2 = c.allow("k2")  # fast path again, normal result
                assert res2.allowed and not res2.fail_open
        finally:
            srv.shutdown()
        lim.close()

    def test_slo_breach_fail_closed(self):
        import time

        lim, _ = _mk_limiter(limit=5, fail_open=False)
        slow = _SlowOnce(lim, delay=0.3)
        srv = NativeRateLimitServer(slow, "127.0.0.1", 0,
                                    max_delay=1e-4, dispatch_timeout=0.03)
        srv.start()
        try:
            with Client(port=srv.port) as c:
                with pytest.raises(StorageUnavailableError):
                    c.allow("k")
                time.sleep(0.35)
                assert c.allow("k2").allowed
        finally:
            srv.shutdown()
        lim.close()

    def test_graceful_shutdown_drains(self):
        lim, _ = _mk_limiter(limit=10000)
        srv = NativeRateLimitServer(lim, "127.0.0.1", 0, max_delay=20e-3)
        srv.start()
        results = []

        def client_burst():
            with Client(port=srv.port) as c:
                try:
                    results.extend(c.allow(f"k{i}").allowed
                                   for i in range(20))
                except Exception:
                    pass

        t = threading.Thread(target=client_burst)
        t.start()
        import time

        time.sleep(0.02)
        srv.shutdown()
        t.join(timeout=10)
        assert not t.is_alive()
        assert all(results)
        lim.close()


class TestPipelinedDoor:
    """Launch/resolve pipeline through the C++ door (ADR-010): overlap
    must not change decisions, snapshots must quiesce, and fail-open
    stamps must carry the live limit."""

    def test_pipelined_mode_engages_for_sketch(self):
        lim, _ = _mk_limiter(algo=Algorithm.TPU_SKETCH, backend="sketch")
        with running(lim, inflight=8) as (srv, _):
            st = srv.stats()
            assert st["pipelined"] and st["inflight_window"] == 8
        lim.close()

    def test_inflight_one_restores_synchronous_path(self):
        lim, _ = _mk_limiter(algo=Algorithm.TPU_SKETCH, backend="sketch")
        with running(lim, inflight=1) as (srv, port):
            assert not srv.stats()["pipelined"]
            with Client(port=port) as c:
                assert c.allow("k").allowed
        lim.close()

    def test_interleaved_same_key_frames_match_oracle(self):
        """Pipelined ALLOW_BATCH frames with duplicate hot keys decide
        exactly like sequential single dispatches on a fresh limiter —
        sequential semantics survive the in-flight window."""
        import asyncio

        from ratelimiter_tpu.serving import AsyncClient

        lim, _ = _mk_limiter(limit=7, algo=Algorithm.TPU_SKETCH,
                             backend="sketch")
        oracle, _ = _mk_limiter(limit=7, algo=Algorithm.TPU_SKETCH,
                                backend="sketch")
        frames = [["hot", "a", "hot"], ["hot", "hot"], ["b", "hot"],
                  ["hot", "hot", "hot"]]
        with running(lim, inflight=8, max_delay=1e-4) as (_, port):
            async def drive():
                c = await AsyncClient.connect(port=port)
                # All frames in flight on one connection: the io thread
                # parses them in order, so frame order == decide order.
                futs = [asyncio.ensure_future(c.allow_batch(f))
                        for f in frames]
                out = await asyncio.gather(*futs)
                await c.close()
                return [[r.allowed for r in frame] for frame in out]

            got = asyncio.run(drive())
        want = [[bool(a) for a in oracle.allow_batch(f).allowed]
                for f in frames]
        assert got == want
        lim.close()
        oracle.close()

    def test_snapshot_during_pipelined_traffic_is_consistent(self, tmp_path):
        """capture_state under live pipelined load quiesces via the state
        chain's data dependence: the snapshot's counters equal the sum
        of every decision acknowledged before the capture returned."""
        import threading as th

        lim, _ = _mk_limiter(limit=10_000, algo=Algorithm.TPU_SKETCH,
                             backend="sketch")
        path = str(tmp_path / "live.npz")
        with running(lim, inflight=8, max_delay=1e-4) as (_, port):
            stop = th.Event()
            sent = []

            def traffic():
                with Client(port=port) as c:
                    while not stop.is_set():
                        sent.append(sum(
                            r.allowed for r in c.allow_batch(["hot"] * 8)))

            t = th.Thread(target=traffic)
            t.start()
            import time as _t

            _t.sleep(0.05)
            # Sampled BEFORE the capture: every batch acked by now was
            # launched before it, so it MUST appear in the snapshot.
            acked_before = sum(sent)
            lim.save(path)           # mid-flight capture
            stop.set()
            t.join(timeout=10)
            acked_total = sum(sent)
        restored, _ = _mk_limiter(limit=10_000, algo=Algorithm.TPU_SKETCH,
                                  backend="sketch")
        restored.restore(path)
        remaining = int(restored.allow_batch(["hot"]).remaining[0])
        captured = 10_000 - 1 - remaining
        # Quiesce invariant: the capture holds a consistent PREFIX of the
        # launch sequence — at least everything acked before it began,
        # at most everything ever launched (all acked by join).
        assert acked_before <= captured <= acked_total
        lim.close()
        restored.close()

    def test_fail_open_stamps_live_limit_after_update(self):
        """SLO-breach fail-open responses must carry the limit at
        RESPONSE time, not construction time (the old docstring caveat,
        fixed): update_limit through the server wrapper refreshes the
        C++ stamp before any post-update dispatch completes."""
        import time

        lim, _ = _mk_limiter(limit=5, fail_open=True)
        slow = _SlowOnce(lim, delay=0.3)
        srv = NativeRateLimitServer(slow, "127.0.0.1", 0,
                                    max_delay=1e-4, dispatch_timeout=0.03)
        srv.start()
        try:
            srv.update_limit(42)     # before ANY dispatch completes
            with Client(port=srv.port) as c:
                res = c.allow("k")   # breaches the SLO -> fail-open stamp
                assert res.allowed and res.fail_open
                assert res.limit == 42
                time.sleep(0.35)     # let the late dispatch land
        finally:
            srv.shutdown()
        lim.close()

    def test_fail_open_limit_converges_after_direct_update(self):
        """Direct limiter.update_limit (not via the server wrapper) still
        converges: the next completed dispatch refreshes the C++ stamp."""
        import time

        lim, _ = _mk_limiter(limit=5, fail_open=True)
        slow = _SlowOnce(lim, delay=0.0)   # no delay yet
        srv = NativeRateLimitServer(slow, "127.0.0.1", 0,
                                    max_delay=1e-4, dispatch_timeout=0.05)
        srv.start()
        try:
            lim.update_limit(17)
            with Client(port=srv.port) as c:
                c.allow("warm")            # completed dispatch -> refresh
                slow._fired = False
                slow._delay = 0.4          # now breach the SLO
                res = c.allow("k")
                assert res.fail_open and res.limit == 17
                time.sleep(0.45)
        finally:
            srv.shutdown()
        lim.close()


class TestDcnPreScreen:
    """Native door DCN pre-screen (ADVICE r5): an oversized garbage
    stream labeled T_DCN_PUSH must die at the small buffer bound, and
    only a bounded number of connections may hold slab-sized buffers."""

    def _dcn_server(self, secret="s3cret"):
        lim, _ = _mk_limiter(algo=Algorithm.TPU_SKETCH, backend="sketch")
        srv = NativeRateLimitServer(lim, "127.0.0.1", 0, dcn=True,
                                    dcn_secret=secret)
        srv.start()
        return lim, srv

    def test_garbage_dcn_stream_killed_without_buffering(self):
        import socket
        import struct

        from ratelimiter_tpu.serving import protocol as p

        lim, srv = self._dcn_server()
        try:
            with socket.create_connection(("127.0.0.1", srv.port)) as sk:
                # Claim an 80 MiB DCN frame, stream garbage (no RLA
                # magic): the pre-screen must kill the connection within
                # the SMALL buffer bound, never granting slab buffering.
                claimed = 80 << 20
                sk.sendall(struct.pack("<IBQ", claimed, p.T_DCN_PUSH, 1))
                sk.settimeout(10)
                sent = 0
                chunk = b"\x00" * 65536
                dead_after = None
                try:
                    while sent < claimed:
                        sk.sendall(chunk)
                        sent += len(chunk)
                except (BrokenPipeError, ConnectionResetError):
                    dead_after = sent
                assert dead_after is not None, "garbage stream was buffered"
                # The server kills at the first parse (4 bytes of body);
                # the client-side count includes kernel socket buffers
                # and RST propagation slack, so the discriminator is
                # "died well before the claimed size" — the pre-fix
                # server accepted the entire 80 MiB into its rbuf.
                assert dead_after < claimed // 2
            # The server is still healthy.
            with Client(port=srv.port) as c:
                assert c.allow("ok").allowed
        finally:
            srv.shutdown()
        lim.close()

    def test_concurrent_dcn_buffer_grants_bounded(self):
        import socket
        import struct

        from ratelimiter_tpu.serving import protocol as p

        lim, srv = self._dcn_server()
        socks = []
        try:
            # 6 connections each open a magic-valid 8 MiB DCN frame and
            # stall; only max_dcn_conns (4) may hold big buffers — the
            # rest are refused.
            refused = 0
            for i in range(6):
                sk = socket.create_connection(("127.0.0.1", srv.port))
                socks.append(sk)
                hdr = struct.pack("<IBQ", 8 << 20, p.T_DCN_PUSH, 10 + i)
                sk.sendall(hdr + b"RLA2" + b"\x00" * 64)
                sk.settimeout(1.0)
                try:
                    resp = sk.recv(13, socket.MSG_WAITALL)
                    # Refusal surfaces as the typed error frame or an
                    # immediate close; a granted connection just waits
                    # for the rest of the frame (recv times out).
                    if not resp or resp[4] == p.T_ERROR:
                        refused += 1
                except (TimeoutError, socket.timeout):
                    pass                     # granted: no response yet
                except ConnectionResetError:
                    refused += 1
            assert refused == 2
        finally:
            for sk in socks:
                sk.close()
            srv.shutdown()
        lim.close()


class TestShardedServer:
    """Dispatch shards: hash-routed keys, concurrent per-shard limiters,
    split-batch reassembly (the in-process Redis-Cluster analog)."""

    def test_per_key_exactness_across_shards(self):
        lim, _ = _mk_limiter(limit=10, algo=Algorithm.TPU_SKETCH,
                             backend="sketch")
        srv = NativeRateLimitServer(lim, "127.0.0.1", 0, shards=4)
        srv.start()
        try:
            with Client(port=srv.port) as c:
                for i in range(8):                 # keys spread over shards
                    assert c.allow_n(f"k{i}", 10).allowed
                    assert not c.allow(f"k{i}").allowed
        finally:
            srv.shutdown()
        lim.close()

    def test_split_batch_reassembles_in_order(self):
        lim, _ = _mk_limiter(limit=3, algo=Algorithm.TPU_SKETCH,
                             backend="sketch")
        srv = NativeRateLimitServer(lim, "127.0.0.1", 0, shards=4)
        srv.start()
        try:
            with Client(port=srv.port) as c:
                keys = [f"u{i}" for i in range(40)] + ["u0"] * 4
                res = c.allow_batch(keys, [1] * 44)
                assert [r.allowed for r in res[:40]] == [True] * 40
                # The 4 trailing duplicates of u0 share its shard and its
                # in-batch sequencing: 2 more admits, then denial.
                assert [r.allowed for r in res[40:]] == [True, True, False,
                                                         False]
        finally:
            srv.shutdown()
        lim.close()

    def test_reset_routed_to_owning_shard(self):
        lim, _ = _mk_limiter(limit=2, algo=Algorithm.TPU_SKETCH,
                             backend="sketch")
        srv = NativeRateLimitServer(lim, "127.0.0.1", 0, shards=4)
        srv.start()
        try:
            with Client(port=srv.port) as c:
                for i in range(6):
                    key = f"r{i}"
                    assert c.allow_n(key, 2).allowed
                    assert not c.allow(key).allowed
                    c.reset(key)
                    assert c.allow(key).allowed
        finally:
            srv.shutdown()
        lim.close()

    def test_side_door_routes_to_owning_shard(self):
        """decide_one/reset_one (the HTTP gateway's callables) must land
        on the same shard limiter as binary traffic for the same key —
        otherwise one key gets two quotas (ADVICE r4 medium)."""
        lim, _ = _mk_limiter(limit=10, algo=Algorithm.TPU_SKETCH,
                             backend="sketch")
        srv = NativeRateLimitServer(lim, "127.0.0.1", 0, shards=4)
        srv.start()
        try:
            keys = [f"mix{i}" for i in range(8)]
            assert len({srv.shard_of(k) for k in keys}) > 1
            with Client(port=srv.port) as c:
                for k in keys:
                    # Half the quota over the wire, half via the side
                    # door; the 11th request must be denied on BOTH
                    # surfaces (single shared quota).
                    assert c.allow_n(k, 5).allowed
                    assert srv.decide_one(k, 5).allowed
                    assert not c.allow(k).allowed
                    assert not srv.decide_one(k).allowed
                    # Reset via the side door frees the wire path too.
                    srv.reset_one(k)
                    assert c.allow(k).allowed
        finally:
            srv.shutdown()
        lim.close()

    def test_concurrent_clients_sharded_exactness(self):
        lim, _ = _mk_limiter(limit=100, algo=Algorithm.TPU_SKETCH,
                             backend="sketch")
        srv = NativeRateLimitServer(lim, "127.0.0.1", 0, shards=2,
                                    max_batch=512, max_delay=2e-3)
        srv.start()
        try:
            allowed = []
            lock = threading.Lock()

            def worker(count):
                with Client(port=srv.port) as c:
                    mine = [c.allow("hot").allowed for _ in range(count)]
                with lock:
                    allowed.extend(mine)

            threads = [threading.Thread(target=worker, args=(15,))
                       for _ in range(10)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert sum(allowed) == 100             # one shard owns "hot"
        finally:
            srv.shutdown()
        lim.close()

    def test_empty_batch_on_sharded_server(self):
        """count==0 ALLOW_BATCH is valid and must not crash the shard
        router (it indexes keys[0] on the split path)."""
        lim, _ = _mk_limiter(algo=Algorithm.TPU_SKETCH, backend="sketch")
        srv = NativeRateLimitServer(lim, "127.0.0.1", 0, shards=4)
        srv.start()
        try:
            with Client(port=srv.port) as c:
                assert list(c.allow_batch([], [])) == []
                assert c.allow("still-up").allowed
        finally:
            srv.shutdown()
        lim.close()

    def test_non_sketch_backend_rejected_for_shards(self):
        lim, _ = _mk_limiter(backend="exact")
        with pytest.raises(ValueError, match="sketch-family"):
            NativeRateLimitServer(lim, "127.0.0.1", 0, shards=2)
        lim.close()

    def test_slo_conflicts_with_shards(self):
        lim, _ = _mk_limiter()
        with pytest.raises(ValueError, match="shards"):
            NativeRateLimitServer(lim, "127.0.0.1", 0, shards=2,
                                  dispatch_timeout=0.05)
        lim.close()


class _SlowOnce:
    """Delays only the FIRST allow_batch (the SLO-breach fixture; later
    dispatches run fast so the server's recovery is observable)."""

    def __init__(self, inner, delay: float):
        self._inner = inner
        self._delay = delay
        self._fired = False

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def allow_batch(self, keys, ns=None, *, now=None):
        import time

        if not self._fired:
            self._fired = True
            time.sleep(self._delay)
        return self._inner.allow_batch(keys, ns, now=now)
    # allow_hashed intentionally NOT defined: __getattr__ delegation keeps
    # hasattr() capability sniffing truthful for the wrapped backend.


