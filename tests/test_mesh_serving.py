"""Slice-parallel serving tests (ISSUE-5 acceptance, ADR-012).

The mesh backend = one device-pinned single-chip limiter per device,
keys hash-routed to their owning slice, decide path collective-free.
The load-bearing invariant: for the keys a device owns, its decisions
are BIT-IDENTICAL to a single-device limiter fed exactly that traffic —
pinned here per lane (string, pre-hashed, raw-id) and per door
(asyncio + native), plus the durability story (sharded snapshot,
kill -9 recovery, re-bucketing restore across a device-count change —
ADR-018; the full reshard oracle lives in tests/test_reshard.py) and a
loose scaling smoke. CI runs this file in an explicit 8-virtual-device lane
with zero skips allowed (ci.yml).
"""

import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import jax

from ratelimiter_tpu import (
    Algorithm,
    CheckpointError,
    Config,
    ManualClock,
    MeshSpec,
    SketchParams,
    create_limiter,
)
from ratelimiter_tpu.algorithms.sketch import (
    SketchLimiter,
    SketchTokenBucketLimiter,
)
from ratelimiter_tpu.parallel import SlicedMeshLimiter, build_slices

from netutil import free_port

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 4, reason="needs 4 (virtual) devices")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
T0 = 1_700_000_000.0


def _cfg(**kw):
    base = dict(
        algorithm=Algorithm.SLIDING_WINDOW,
        limit=10,
        window=60.0,
        sketch=SketchParams(depth=2, width=1 << 10, sub_windows=6),
    )
    base.update(kw)
    return Config(**base)


# ------------------------------------------------------- routing oracle


class TestSliceOracle:
    def test_string_lane_bit_identical_to_per_slice_oracle(self):
        """Each slice's decisions == a single-device limiter fed ONLY the
        keys that slice owns, bit for bit (allowed/remaining/retry/
        reset). This is the acceptance wording verbatim: collective-free
        routing means a device never sees foreign keys, so its sketch
        evolves exactly like a standalone chip's."""
        cfg = _cfg(limit=5)
        mesh = SlicedMeshLimiter(cfg, ManualClock(T0), n_devices=4)
        rng = np.random.default_rng(3)
        keys = [f"k{int(i)}" for i in rng.integers(0, 60, size=240)]
        out = mesh.allow_batch(keys)

        owners = mesh.owner_of_hash(mesh._hash(keys))
        for dev in range(4):
            idx = np.flatnonzero(owners == dev)
            if not idx.size:
                continue
            oracle = SketchLimiter(cfg, ManualClock(T0))
            ref = oracle.allow_batch([keys[i] for i in idx])
            np.testing.assert_array_equal(out.allowed[idx], ref.allowed)
            np.testing.assert_array_equal(out.remaining[idx], ref.remaining)
            np.testing.assert_array_equal(out.retry_after[idx],
                                          ref.retry_after)
            np.testing.assert_array_equal(out.reset_at[idx], ref.reset_at)
            oracle.close()
        mesh.close()

    def test_raw_id_lane_bit_identical_to_per_slice_oracle(self):
        cfg = _cfg(limit=3)
        mesh = SlicedMeshLimiter(cfg, ManualClock(T0), n_devices=4)
        rng = np.random.default_rng(5)
        ids = rng.integers(1, 1 << 40, size=300, dtype=np.uint64)
        out = mesh.allow_ids(ids)

        owners = mesh.owner_of_id(ids)
        for dev in range(4):
            idx = np.flatnonzero(owners == dev)
            if not idx.size:
                continue
            oracle = SketchLimiter(cfg, ManualClock(T0))
            ref = oracle.allow_ids(ids[idx])
            np.testing.assert_array_equal(out.allowed[idx], ref.allowed)
            np.testing.assert_array_equal(out.remaining[idx], ref.remaining)
            oracle.close()
        mesh.close()

    def test_same_key_sequencing_survives_the_split(self):
        """A hot key's requests inside one frame land on its slice in
        frame order (the stable-sort partition), so exactly `limit` are
        admitted and they are the FIRST `limit` occurrences."""
        mesh = SlicedMeshLimiter(_cfg(limit=7), ManualClock(T0), n_devices=4)
        keys = []
        for i in range(40):
            keys.append("hot")
            keys.append(f"cold{i}")
        out = mesh.allow_batch(keys)
        hot = out.allowed[0::2]
        assert hot.sum() == 7
        assert bool(np.all(hot[:7])) and not bool(np.any(hot[7:]))
        mesh.close()

    def test_scalar_and_reset_route_to_owner(self):
        clock = ManualClock(T0)
        mesh = SlicedMeshLimiter(_cfg(limit=2), clock, n_devices=4)
        assert mesh.allow("one").allowed
        assert mesh.allow("one").allowed
        assert not mesh.allow("one").allowed
        mesh.reset("one")
        assert mesh.allow("one").allowed
        mesh.close()


# -------------------------------------------------- pipelined dispatch


class TestMeshPipeline:
    def test_launch_resolve_matches_sync_and_is_idempotent(self):
        cfg = _cfg(limit=5)
        c1, c2 = ManualClock(T0), ManualClock(T0)
        a = SlicedMeshLimiter(cfg, c1, n_devices=4)
        b = SlicedMeshLimiter(cfg, c2, n_devices=4)
        rng = np.random.default_rng(11)
        frames = [[f"k{int(i)}" for i in rng.integers(0, 30, size=64)]
                  for _ in range(4)]
        tickets = [a.launch_batch(f) for f in frames]
        outs_pipe = [a.resolve(t) for t in tickets]
        outs_sync = [b.allow_batch(f) for f in frames]
        for p, s in zip(outs_pipe, outs_sync):
            np.testing.assert_array_equal(p.allowed, s.allowed)
            np.testing.assert_array_equal(p.remaining, s.remaining)
        # idempotent resolve
        again = a.resolve(tickets[0])
        assert again is outs_pipe[0]
        a.close()
        b.close()

    def test_single_owner_wire_frame_passes_device_packed_buffers(self):
        """A frame fully owned by one slice keeps the zero-copy
        wire_packed buffers (the composite must not strip them), and a
        MIXED wire frame reassembles packed buffers through the index
        maps (ADR-013 scatter-back) — the wire encoder frames either
        from packed columns, never by re-packing per row."""
        mesh = SlicedMeshLimiter(_cfg(), ManualClock(T0), n_devices=4)
        ids = np.arange(1, 4000, dtype=np.uint64)
        owners = mesh.owner_of_id(ids)
        mine = ids[owners == 2][:64]
        res = mesh.resolve(mesh.launch_ids(mine, wire=True))
        assert res.wire_packed is not None
        # A mixed frame reassembles the packed form host-side via the
        # scatter-back: buffers present and bit-consistent with the
        # row-level columns.
        res2 = mesh.resolve(mesh.launch_ids(ids[:64], wire=True))
        assert res2.wire_packed is not None
        bits, words, padded = res2.wire_packed
        b = len(res2)
        np.testing.assert_array_equal(
            np.unpackbits(bits, bitorder="little")[:b].astype(bool),
            res2.allowed)
        np.testing.assert_array_equal(words[:b], res2.remaining)
        np.testing.assert_array_equal(
            words[padded:padded + b].view(np.float64), res2.retry_after)
        np.testing.assert_array_equal(
            words[2 * padded:2 * padded + b].view(np.float64),
            res2.reset_at)
        mesh.close()

    def test_fail_open_split_frame_ors_the_flag(self):
        mesh = SlicedMeshLimiter(_cfg(fail_open=True), ManualClock(T0),
                                 n_devices=4)
        ids = np.arange(1, 200, dtype=np.uint64)
        # Break ONE slice: its sub-frame fails open; the whole frame's
        # flag must say so (same contract as the native door's
        # multi-shard joins).
        mesh.slices[1].inject_failure()
        out = mesh.allow_ids(ids)
        assert out.fail_open
        owners = mesh.owner_of_id(ids)
        assert bool(np.all(out.allowed[owners == 1]))
        mesh.heal()
        mesh.close()


# ------------------------------------------------------- control plane


class TestMeshControlPlane:
    def test_policy_overrides_apply_everywhere_and_decide(self):
        clock = ManualClock(T0)
        mesh = SlicedMeshLimiter(_cfg(limit=2), clock, n_devices=4)
        mesh.set_override("vip", 6)
        assert mesh.get_override("vip").limit == 6
        out = mesh.allow_batch(["vip"] * 8)
        assert out.allow_count == 6
        assert mesh.delete_override("vip") is True
        assert mesh.get_override("vip") is None
        assert mesh.override_count() == 0
        mesh.close()

    def test_update_limit_and_window_reach_every_slice(self):
        clock = ManualClock(T0)
        mesh = SlicedMeshLimiter(_cfg(limit=2), clock, n_devices=4)
        mesh.update_limit(4)
        assert mesh.config.limit == 4
        for s in mesh.slices:
            assert s.config.limit == 4
        out = mesh.allow_batch(["w"] * 6)
        assert out.allow_count == 4
        mesh.update_window(30.0)
        assert mesh.config.window == 30.0
        for s in mesh.slices:
            assert s.config.window == 30.0
        mesh.close()

    def test_token_bucket_mesh_refill(self):
        clock = ManualClock(T0)
        cfg = Config(algorithm=Algorithm.TOKEN_BUCKET, limit=10, window=10.0,
                     sketch=SketchParams(depth=2, width=256))
        mesh = create_limiter(cfg, backend="mesh", clock=clock, n_devices=4)
        assert isinstance(mesh.slices[0], SketchTokenBucketLimiter)
        out = mesh.allow_batch(["hot"] * 16)
        assert out.allow_count == 10
        clock.advance(2.0)
        out = mesh.allow_batch(["hot"] * 4)
        assert out.allow_count == 2
        mesh.close()

    def test_factory_and_mesh_spec(self):
        from dataclasses import replace

        cfg = replace(_cfg(), mesh=MeshSpec(devices=2))
        mesh = create_limiter(cfg, backend="mesh", clock=ManualClock(T0))
        assert mesh.n_slices == 2
        mesh.close()


# --------------------------------------------------- durability × mesh


class TestMeshCheckpoint:
    def test_capture_restore_roundtrip(self, tmp_path):
        clock = ManualClock(T0)
        cfg = _cfg(limit=4)
        mesh = SlicedMeshLimiter(cfg, clock, n_devices=4)
        keys = [f"k{i}" for i in range(40)]
        mesh.allow_batch(keys)
        mesh.set_override("vip", 9)
        path = str(tmp_path / "mesh.npz")
        mesh.save(path)

        fresh = SlicedMeshLimiter(cfg, ManualClock(T0), n_devices=4)
        fresh.restore(path)
        # Restored counters: the consumed quota stands on every slice.
        a = mesh.allow_batch(keys)
        b = fresh.allow_batch(keys)
        np.testing.assert_array_equal(a.allowed, b.allowed)
        assert fresh.get_override("vip").limit == 9
        mesh.close()
        fresh.close()

    def test_restore_rebuckets_device_count_change(self, tmp_path):
        """A snapshot taken at another slice count RE-BUCKETS onto this
        mesh (ADR-018; the pre-PR-11 refusal is gone): overrides exact,
        counters carried, never over-admitting vs the source — the full
        oracle lives in tests/test_reshard.py. restore_slice still
        refuses (one slice cannot re-bucket in place)."""
        cfg = _cfg(limit=4)
        clock = ManualClock(T0)
        mesh = SlicedMeshLimiter(cfg, clock, n_devices=4)
        keys = [f"k{i}" for i in range(40)]
        mesh.allow_batch(keys)
        mesh.set_override("vip", 9)
        path = str(tmp_path / "mesh4.npz")
        mesh.save(path)
        src = mesh.allow_batch(keys)
        mesh.close()
        other = SlicedMeshLimiter(cfg, ManualClock(T0), n_devices=2)
        other.restore(path)
        assert other.get_override("vip").limit == 9
        got = other.allow_batch(keys)
        assert not (got.allowed & ~src.allowed).any()
        other.close()
        with pytest.raises(CheckpointError, match="rebucket"):
            third = SlicedMeshLimiter(cfg, ManualClock(T0), n_devices=2)
            try:
                third.restore_slice(path, 0)
            finally:
                third.close()


def _env():
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [REPO] + env.get("PYTHONPATH", "").split(os.pathsep))
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    return env


def _spawn_mesh(port, snap_dir, mesh_devices=2, extra=()):
    argv = [sys.executable, "-m", "ratelimiter_tpu.serving",
            "--backend", "mesh", "--mesh-devices", str(mesh_devices),
            "--limit", "100", "--window", "600",
            "--sketch-depth", "4", "--sketch-width", "8192",
            "--sub-windows", "6",
            "--port", str(port), "--snapshot-dir", snap_dir,
            "--snapshot-interval", "500", "--no-prewarm", *extra]
    return subprocess.Popen(argv, env=_env(), stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)


def _wait_banner(proc, timeout=120):
    t0 = time.time()
    lines = []
    while time.time() - t0 < timeout:
        line = proc.stdout.readline()
        if not line:
            break
        lines.append(line)
        if line.startswith("serving"):
            return lines
    raise AssertionError("server never served:\n" + "".join(lines))


class TestMeshKillNine:
    def test_kill9_recovers_sharded_state_same_device_count(self, tmp_path):
        """A mesh-backed server snapshots its sliced state, dies by
        SIGKILL under live traffic, and restores onto the SAME device
        count: overrides recover exactly via WAL replay, counters are
        bounded (restored >= pre-snapshot consumption, <= true total —
        under-count only, the fail-toward-allowing direction)."""
        from ratelimiter_tpu.serving.client import Client

        snap_dir = str(tmp_path / "mesh-durable")
        port = free_port()
        proc = _spawn_mesh(port, snap_dir)
        try:
            _wait_banner(proc)
            c = Client(port=port, timeout=120.0)
            assert c.allow_n("k", 30).allowed
            c.set_override("vip", 42)
            snap_id, wal_seq, _dur = c.snapshot()
            assert snap_id >= 1 and wal_seq >= 1
            stop = threading.Event()

            def hammer():
                try:
                    with Client(port=port, timeout=120.0) as hc:
                        i = 0
                        while not stop.is_set():
                            hc.allow(f"bg:{i % 97}")
                            i += 1
                except (ConnectionError, OSError):
                    pass
            t = threading.Thread(target=hammer, daemon=True)
            t.start()
            for _ in range(5):
                assert c.allow_n("k", 10).allowed
            c.set_override("vip2", 9)
            assert c.delete_override("vip") is True
            time.sleep(0.2)
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=30)
            stop.set()
            t.join(timeout=10)
            c.close()
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait()

        proc2 = _spawn_mesh(port, snap_dir)
        try:
            lines = _wait_banner(proc2)
            assert any("recovery" in ln for ln in lines)
            with Client(port=port, timeout=120.0) as c2:
                assert c2.get_override("vip2") == (9, 1.0)
                assert c2.get_override("vip") is None
                # >= 30 consumed (snapshot restored the owning slice) ...
                assert not c2.allow_n("k", 71).allowed
                # ... and <= 80 (under-count only).
                assert c2.allow_n("k", 20).allowed
            proc2.send_signal(signal.SIGTERM)
            rc = proc2.wait(timeout=30)
            # Graceful exit is rc 0; the XLA CPU client very rarely
            # crashes in its own atexit teardown AFTER the server has
            # fully drained + snapshotted (every correctness assertion
            # above already passed). Both observed flavors of that
            # teardown crash are tolerated — SIGABRT (the common one)
            # and SIGSEGV (seen once under full-suite load, PR 9: the
            # same XLA-CPU destructor class, after the final snapshot
            # line had already been emitted). The JAX-free exact-backend
            # kill -9 test (test_durability_crash.py) pins rc == 0 for
            # the serving stack itself, so widening this gate does not
            # mask a real shutdown regression — the durability
            # assertions above are the test's contract, not the XLA
            # destructor's exit code.
            assert rc in (0, -signal.SIGABRT, -signal.SIGSEGV), (
                f"shutdown rc={rc}:\n{proc2.stdout.read()}")
        finally:
            if proc2.poll() is None:
                proc2.kill()
                proc2.wait()

    @pytest.mark.slow
    def test_device_count_change_rebuckets_on_restart(self, tmp_path):
        """Restarting a mesh snapshot directory under a DIFFERENT device
        count RE-BUCKETS the key-routed state onto the new geometry
        (ADR-018; pre-PR-11 this refused): the server boots, logs the
        re-bucketing warning, and serves with the restored counters —
        the consumed quota stands across the resize. Slow lane (two
        server boots); the mesh CI lane runs it unfiltered."""
        from ratelimiter_tpu.serving.client import Client

        snap_dir = str(tmp_path / "mesh-resize")
        port = free_port()
        proc = _spawn_mesh(port, snap_dir)
        try:
            _wait_banner(proc)
            with Client(port=port, timeout=120.0) as c:
                # Consume the whole default limit (100) on one key.
                assert c.allow_n("k", 100).allowed
                c.snapshot()
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=30)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait()

        port2 = free_port()
        proc2 = _spawn_mesh(port2, snap_dir, mesh_devices=4)
        try:
            lines = _wait_banner(proc2)
            assert any("re-bucketing" in ln for ln in lines), lines
            with Client(port=port2, timeout=120.0) as c2:
                # The re-bucketed state still carries the consumed
                # quota: the key stays denied (never over-admits).
                assert not c2.allow_n("k", 1).allowed
        finally:
            if proc2.poll() is None:
                proc2.kill()
                proc2.wait()


# ----------------------------------------------------------- both doors


class TestMeshDoors:
    def test_asyncio_door_serves_all_three_lanes(self):
        import asyncio

        from ratelimiter_tpu.serving.client import AsyncClient
        from ratelimiter_tpu.serving.server import RateLimitServer

        cfg = _cfg(limit=5)
        oracle_cfg = cfg

        async def main():
            lim = SlicedMeshLimiter(cfg, n_devices=4)
            srv = RateLimitServer(lim, max_delay=1e-4)
            await srv.start()
            c = await AsyncClient.connect(port=srv.port)
            outs = await asyncio.gather(*[c.allow("hot") for _ in range(8)])
            assert sum(o.allowed for o in outs) == 5
            res = await c.allow_batch([f"b{i}" for i in range(64)])
            assert len(res) == 64
            ids = np.arange(1, 257, dtype=np.uint64)
            br = await c.allow_hashed(ids)
            direct = SlicedMeshLimiter(oracle_cfg, n_devices=4)
            np.testing.assert_array_equal(br.allowed,
                                          direct.allow_ids(ids).allowed)
            direct.close()
            await c.close()
            await srv.shutdown()
            lim.close()

        asyncio.run(main())

    def test_native_door_mounts_slices_as_shards(self):
        from ratelimiter_tpu.serving.native_server import (
            NativeRateLimitServer,
            native_server_available,
        )
        if not native_server_available():
            pytest.skip("no compiler for the native front door")
        from ratelimiter_tpu.serving.client import Client

        cfg = _cfg(limit=5)
        slices = build_slices(cfg, n_devices=4)
        srv = NativeRateLimitServer(slices[0], shards=4,
                                    shard_limiters=slices, max_delay=1e-4)
        srv.start()
        try:
            with Client(port=srv.port, timeout=60.0) as c:
                assert sum(c.allow("hot").allowed for _ in range(8)) == 5
                ids = np.arange(1, 1025, dtype=np.uint64)
                br = c.allow_hashed(ids)
                direct = SlicedMeshLimiter(cfg, n_devices=4)
                np.testing.assert_array_equal(
                    br.allowed, direct.allow_ids(ids).allowed)
                direct.close()
            st = srv.stats()
            assert st["num_shards"] == 4
            assert sum(st["shard_decisions"]) == st["decisions_total"]
            assert all(v > 0 for v in st["shard_decisions"]), \
                "per-device routing left a device idle"
        finally:
            srv.shutdown(close_limiters=False)
            for s in slices:
                s.close()

    def test_dcn_peer_gate_accepts_mesh_rejects_host_backends(self):
        """ISSUE-5 satellite: the --dcn-peer argparse gate must accept
        --backend mesh (slices export over DCN) and keep refusing
        non-sketch-family backends."""
        env = _env()
        # exact: refused before any server starts (fast, JAX-free).
        proc = subprocess.run(
            [sys.executable, "-m", "ratelimiter_tpu.serving",
             "--backend", "exact", "--algorithm", "sliding_window",
             "--dcn-peer", "127.0.0.1:1", "--port", str(free_port())],
            env=env, capture_output=True, text=True, timeout=120)
        assert proc.returncode != 0
        assert "sketch-family" in proc.stderr
        # mesh: passes the gate and serves (banner appears).
        port = free_port()
        srv = subprocess.Popen(
            [sys.executable, "-m", "ratelimiter_tpu.serving",
             "--backend", "mesh", "--mesh-devices", "2",
             "--sketch-depth", "2", "--sketch-width", "1024",
             "--sub-windows", "6", "--no-prewarm",
             "--dcn-peer", "127.0.0.1:1", "--port", str(port)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        try:
            _wait_banner(srv)
        finally:
            srv.terminate()
            srv.wait(timeout=30)

    def test_mesh_devices_flag_needs_mesh_backend(self):
        env = _env()
        proc = subprocess.run(
            [sys.executable, "-m", "ratelimiter_tpu.serving",
             "--backend", "sketch", "--mesh-devices", "2",
             "--port", str(free_port())],
            env=env, capture_output=True, text=True, timeout=120)
        assert proc.returncode != 0
        assert "--mesh-devices needs --backend mesh" in proc.stderr


# -------------------------------------------------------- scaling smoke


class TestScalingSmoke:
    def test_throughput_scales_with_devices(self):
        """Loose-ratio scaling smoke (the full curve is bench.py
        --mesh-devices; this guards the mechanism, not the magnitude):
        4 device slices driven concurrently must beat 1 on a big enough
        box, and must NEVER collapse below it anywhere."""
        sys.path.insert(0, REPO)
        from bench import measure_mesh_step_rate

        kw = dict(seconds=0.8, batch=4096, window=2,
                  depth=2, width=1 << 12, sub_windows=6)
        r1 = measure_mesh_step_rate(1, **kw)
        r4 = measure_mesh_step_rate(4, **kw)
        if (os.cpu_count() or 1) >= 8:
            assert r4 >= 1.3 * r1, (r1, r4)
        else:
            # Tiny CI boxes cannot parallelize 4 devices; only guard
            # against collapse.
            assert r4 >= 0.7 * r1, (r1, r4)
