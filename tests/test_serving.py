"""Serving tier: protocol, micro-batcher, and server integration tests.

The integration tier mirrors the reference's miniredis-based tests
(SURVEY.md §4.2): a real server speaking the real wire protocol over real
sockets, in-process so tests control time and failure injection. The
headline test is the VERDICT r2 "done" criterion: many concurrent clients
through a live server, limit-L key admits exactly L globally.
"""

from __future__ import annotations

import asyncio
import threading
import time
from contextlib import contextmanager

import pytest

from ratelimiter_tpu import (
    Algorithm,
    Config,
    InvalidNError,
    ManualClock,
    StorageUnavailableError,
    create_limiter,
)
from ratelimiter_tpu.core.types import Result
from ratelimiter_tpu.observability import Registry
from ratelimiter_tpu.serving import AsyncClient, Client, MicroBatcher, RateLimitServer
from ratelimiter_tpu.serving import protocol as p


# --------------------------------------------------------------- protocol

class TestProtocol:
    def test_allow_n_roundtrip(self):
        frame = p.encode_allow_n(42, "user:1", 7)
        length, type_, rid = p.parse_header(frame[:p.HEADER_SIZE])
        assert (type_, rid) == (p.T_ALLOW_N, 42)
        key, n = p.parse_allow_n(frame[p.HEADER_SIZE:])
        assert (key, n) == ("user:1", 7)

    def test_result_roundtrip(self):
        res = Result(allowed=True, limit=100, remaining=3, retry_after=0.0,
                     reset_at=1234.5, fail_open=True)
        frame = p.encode_result(9, res)
        body = frame[p.HEADER_SIZE:]
        back = p.parse_result(body)
        assert back == res

    def test_error_roundtrip_maps_exception(self):
        frame = p.encode_error(1, p.E_INVALID_N, "n must be positive")
        code, msg = p.parse_error(frame[p.HEADER_SIZE:])
        exc = p.exception_for(code, msg)
        assert isinstance(exc, InvalidNError)

    def test_unicode_keys(self):
        frame = p.encode_allow_n(1, "ключ:héllo", 1)
        key, _ = p.parse_allow_n(frame[p.HEADER_SIZE:])
        assert key == "ключ:héllo"

    def test_bad_length_rejected(self):
        import struct

        bad = struct.pack("<IBQ", 2 ** 24, p.T_ALLOW_N, 1)
        with pytest.raises(p.ProtocolError):
            p.parse_header(bad)


# ---------------------------------------------------------------- batcher

def _mk_limiter(limit=100, window=60.0, algo=Algorithm.SLIDING_WINDOW,
                backend="exact", **kw):
    clock = ManualClock(1_700_000_000.0)
    cfg = Config(algorithm=algo, limit=limit, window=window, **kw)
    return create_limiter(cfg, backend=backend, clock=clock), clock


class TestMicroBatcher:
    def test_coalesces_concurrent_submits(self):
        lim, _ = _mk_limiter(limit=100)
        reg = Registry()
        batcher = MicroBatcher(lim, max_batch=64, max_delay=5e-3, registry=reg)

        async def go():
            results = await asyncio.gather(
                *(batcher.submit(f"k{i % 4}") for i in range(32)))
            await batcher.drain()
            return results

        results = asyncio.run(go())
        assert all(r.allowed for r in results)
        h = reg.get("rate_limiter_server_batch_size")
        # All 32 submits landed within one coalescing window -> one dispatch.
        assert h.count() == 1 and h.sum() == 32.0
        batcher.close()
        lim.close()

    def test_flushes_at_max_batch(self):
        lim, _ = _mk_limiter(limit=1000)
        reg = Registry()
        batcher = MicroBatcher(lim, max_batch=8, max_delay=10.0, registry=reg)

        async def go():
            return await asyncio.gather(*(batcher.submit(f"k{i}")
                                          for i in range(8)))

        results = asyncio.run(go())  # returns despite the 10s max_delay
        assert len(results) == 8
        assert reg.get("rate_limiter_server_batch_size").sum() == 8.0
        batcher.close()
        lim.close()

    def test_exactness_through_batching(self):
        lim, _ = _mk_limiter(limit=10)
        batcher = MicroBatcher(lim, max_batch=256, max_delay=2e-3)

        async def go():
            return await asyncio.gather(
                *(batcher.submit("hot") for _ in range(40)))

        results = asyncio.run(go())
        assert sum(r.allowed for r in results) == 10
        batcher.close()
        lim.close()

    def test_threadsafe_decide_many_single_dispatch_in_order(self):
        """Satellite pin (gRPC AllowBatch path, transport-free): the bulk
        bridge submits the WHOLE frame before waiting, so N items cost
        O(1) coalesced dispatches — and results come back in request
        order even with duplicate keys."""
        import threading

        from ratelimiter_tpu.serving.__main__ import (
            make_threadsafe_decide_many,
        )

        lim, _ = _mk_limiter(limit=2)
        dispatches = []
        inner_allow_batch = lim.allow_batch

        def counting_allow_batch(keys, ns=None, **kw):
            dispatches.append(len(keys))
            return inner_allow_batch(keys, ns, **kw)

        lim.allow_batch = counting_allow_batch
        reg = Registry()
        batcher = MicroBatcher(lim, max_batch=4096, max_delay=2e-3,
                               registry=reg)

        async def go():
            loop = asyncio.get_running_loop()
            decide_many = make_threadsafe_decide_many(batcher, loop)
            pairs = [("a", 1), ("b", 1), ("a", 1), ("b", 1), ("a", 1)]
            # decide_many blocks, so it runs on a worker thread exactly
            # like a gRPC handler does.
            results = await loop.run_in_executor(None, decide_many, pairs)
            await batcher.drain()
            return results

        results = asyncio.run(go())
        # One dispatch for the whole 5-item frame.
        assert dispatches == [5]
        # Order preserved: per-key greedy in frame order at limit=2.
        assert [r.allowed for r in results] == [True, True, True, True, False]
        batcher.close()
        lim.close()

    def test_validation_rejected_before_batching(self):
        lim, _ = _mk_limiter()
        batcher = MicroBatcher(lim, max_batch=8, max_delay=1e-3)

        async def go():
            with pytest.raises(InvalidNError):
                await batcher.submit("k", 0)
            with pytest.raises(Exception):
                await batcher.submit("", 1)

        asyncio.run(go())
        batcher.close()
        lim.close()

    def test_slo_breach_fail_open(self):
        lim, _ = _mk_limiter(limit=5, fail_open=True)
        slow = _SlowLimiter(lim, delay=0.2)
        batcher = MicroBatcher(slow, max_batch=4, max_delay=1e-4,
                               dispatch_timeout=0.02)

        async def go():
            t0 = time.perf_counter()
            res = await batcher.submit("k")
            dt = time.perf_counter() - t0
            await batcher.drain()
            return res, dt

        res, dt = asyncio.run(go())
        assert res.allowed and res.fail_open
        assert dt < 0.15  # answered at SLO, not at dispatch completion
        batcher.close()
        lim.close()

    def test_slo_breach_fail_closed(self):
        lim, _ = _mk_limiter(limit=5, fail_open=False)
        slow = _SlowLimiter(lim, delay=0.2)
        batcher = MicroBatcher(slow, max_batch=4, max_delay=1e-4,
                               dispatch_timeout=0.02)

        async def go():
            with pytest.raises(StorageUnavailableError):
                await batcher.submit("k")
            await batcher.drain()

        asyncio.run(go())
        batcher.close()
        lim.close()


class _SlowLimiter:
    """Wraps a limiter, delaying allow_batch — the SLO-breach fixture."""

    def __init__(self, inner, delay: float):
        self._inner = inner
        self._delay = delay

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def allow_batch(self, keys, ns=None, *, now=None):
        time.sleep(self._delay)
        return self._inner.allow_batch(keys, ns, now=now)


# ----------------------------------------------------------------- server

@contextmanager
def running_server(limiter, **kw):
    """A live server on a background event loop; yields (server, port)."""
    loop = asyncio.new_event_loop()
    thread = threading.Thread(target=loop.run_forever, daemon=True)
    thread.start()
    server = RateLimitServer(limiter, "127.0.0.1", 0, **kw)
    asyncio.run_coroutine_threadsafe(server.start(), loop).result(timeout=10)
    try:
        yield server, server.port, loop
    finally:
        asyncio.run_coroutine_threadsafe(server.shutdown(), loop).result(timeout=10)
        loop.call_soon_threadsafe(loop.stop)
        thread.join(timeout=10)
        loop.close()


class TestServerIntegration:
    def test_allow_deny_over_the_wire(self):
        lim, _ = _mk_limiter(limit=3)
        with running_server(lim) as (_, port, _loop):
            with Client(port=port) as c:
                for i in range(3):
                    res = c.allow("user:1")
                    assert res.allowed and res.remaining == 2 - i
                res = c.allow("user:1")
                assert not res.allowed and res.retry_after > 0
        lim.close()

    def test_allow_n_and_reset(self):
        lim, _ = _mk_limiter(limit=10)
        with running_server(lim) as (_, port, _loop):
            with Client(port=port) as c:
                assert c.allow_n("k", 10).allowed
                assert not c.allow("k").allowed
                c.reset("k")
                assert c.allow("k").allowed
        lim.close()

    def test_invalid_n_comes_back_as_typed_error(self):
        lim, _ = _mk_limiter()
        with running_server(lim) as (_, port, _loop):
            with Client(port=port) as c:
                with pytest.raises(InvalidNError):
                    c.allow_n("k", 0)
                # Connection still usable after an error response.
                assert c.allow("k").allowed
        lim.close()

    def test_health_and_metrics(self):
        lim, _ = _mk_limiter()
        reg = Registry()
        with running_server(lim, registry=reg) as (_, port, _loop):
            with Client(port=port) as c:
                serving, uptime, decisions = c.health()
                assert serving and uptime >= 0 and decisions == 0
                c.allow("k")
                _, _, decisions = c.health()
                assert decisions == 1
                text = c.metrics()
                assert "rate_limiter_server_batch_size" in text
        lim.close()

    def test_concurrent_clients_global_exactness(self):
        """VERDICT r2 done-criterion: many concurrent clients, one hot key,
        limit L -> exactly L allowed globally (exact backend; the batcher
        coalesces across connections and the in-batch sequencing keeps the
        serialized-Lua semantics)."""
        lim, _ = _mk_limiter(limit=100)
        with running_server(lim, max_batch=512, max_delay=2e-3) as (srv, port, _loop):
            allowed = []
            lock = threading.Lock()

            def worker(count: int):
                with Client(port=port) as c:
                    mine = [c.allow("hot").allowed for _ in range(count)]
                with lock:
                    allowed.extend(mine)

            threads = [threading.Thread(target=worker, args=(15,))
                       for _ in range(10)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert len(allowed) == 150
            assert sum(allowed) == 100
        lim.close()

    def test_pipelined_client_coalesces_into_batches(self):
        lim, _ = _mk_limiter(limit=5000)
        reg = Registry()
        with running_server(lim, max_batch=4096, max_delay=5e-3,
                            registry=reg) as (_, port, loop):
            async def burst():
                c = await AsyncClient.connect(port=port)
                results = await c.allow_many([f"k{i % 50}" for i in range(500)])
                await c.close()
                return results

            results = asyncio.run_coroutine_threadsafe(
                burst(), loop).result(timeout=30)
            assert all(isinstance(r, Result) and r.allowed for r in results)
        h = reg.get("rate_limiter_server_batch_size")
        assert h.count() < 500, "pipelined requests must share dispatches"
        assert h.sum() == 500.0
        lim.close()

    def test_allow_batch_rpc(self):
        """One ALLOW_BATCH frame: results in order, in-frame exactness
        preserved (duplicates contend through the shared batcher)."""
        lim, _ = _mk_limiter(limit=3)
        with running_server(lim) as (_, port, loop):
            async def go():
                c = await AsyncClient.connect(port=port)
                res = await c.allow_batch(["h", "h", "h", "h", "x"],
                                          [1, 1, 1, 1, 2])
                await c.close()
                return res

            res = asyncio.run_coroutine_threadsafe(go(), loop).result(timeout=30)
            assert [r.allowed for r in res] == [True, True, True, False, True]
            assert res[0].limit == 3
        # Sync client path too.
        lim2, _ = _mk_limiter(limit=2)
        with running_server(lim2) as (_, port, _loop):
            with Client(port=port) as c:
                res = c.allow_batch(["a", "a", "a"])
                assert [r.allowed for r in res] == [True, True, False]
        lim.close()
        lim2.close()

    def test_allow_batch_rpc_validation_error(self):
        lim, _ = _mk_limiter()
        with running_server(lim) as (_, port, _loop):
            with Client(port=port) as c:
                with pytest.raises(InvalidNError):
                    c.allow_batch(["a", "b"], [1, 0])
                assert c.allow("a").allowed  # connection survives
        lim.close()

    def test_allow_batch_invalid_frame_consumes_no_quota(self):
        """A frame rejected mid-validation must queue NOTHING: earlier
        pairs in the frame would otherwise consume quota with no reader
        of their futures (whole-frame atomicity of validation)."""
        lim, _ = _mk_limiter(limit=2)
        with running_server(lim) as (_, port, _loop):
            with Client(port=port) as c:
                with pytest.raises(InvalidNError):
                    c.allow_batch(["a", "a", "b"], [1, 1, 0])
                # "a" was listed twice before the invalid pair; if those
                # had been queued, only 0 allowances would remain here.
                res = c.allow_batch(["a", "a"])
                assert [r.allowed for r in res] == [True, True]
        lim.close()

    def test_invalid_utf8_key_rejected_same_as_native(self):
        """Parity with the native front door: undecodable key bytes on
        ALLOW_N and RESET come back as E_INVALID_KEY error frames (never
        E_INTERNAL, never a silent hang)."""
        import socket
        import struct

        lim, _ = _mk_limiter()
        with running_server(lim) as (_, port, _loop):
            with socket.create_connection(("127.0.0.1", port)) as sk:
                bad = b"\xff\xfekey"
                body = struct.pack("<IH", 1, len(bad)) + bad
                sk.sendall(struct.pack("<IBQ", 1 + 8 + len(body),
                                       p.T_ALLOW_N, 3) + body)
                body = struct.pack("<H", len(bad)) + bad
                sk.sendall(struct.pack("<IBQ", 1 + 8 + len(body),
                                       p.T_RESET, 4) + body)
                for _ in range(2):
                    hdr = sk.recv(13, socket.MSG_WAITALL)
                    length, type_, req_id = p.parse_header(hdr)
                    rest = sk.recv(length - 9, socket.MSG_WAITALL)
                    assert type_ == p.T_ERROR and req_id in (3, 4)
                    code, _ = struct.unpack_from("<HH", rest)
                    assert code == p.E_INVALID_KEY, (req_id, code)
        lim.close()

    def test_fail_open_through_the_server(self):
        lim, _ = _mk_limiter(limit=5, algo=Algorithm.TPU_SKETCH,
                             backend="sketch", fail_open=True)
        with running_server(lim) as (_, port, _loop):
            with Client(port=port) as c:
                assert c.allow("k").allowed
                lim.inject_failure()
                res = c.allow("k")
                assert res.allowed and res.fail_open
        lim.close()

    def test_fail_closed_through_the_server(self):
        lim, _ = _mk_limiter(limit=5, algo=Algorithm.TPU_SKETCH,
                             backend="sketch", fail_open=False)
        with running_server(lim) as (_, port, _loop):
            with Client(port=port) as c:
                assert c.allow("k").allowed
                lim.inject_failure()
                with pytest.raises(StorageUnavailableError):
                    c.allow("k")
        lim.close()

    def test_graceful_shutdown_answers_inflight(self):
        lim, _ = _mk_limiter(limit=1000)
        loop = asyncio.new_event_loop()
        thread = threading.Thread(target=loop.run_forever, daemon=True)
        thread.start()
        server = RateLimitServer(lim, "127.0.0.1", 0, max_batch=512,
                                 max_delay=50e-3)
        asyncio.run_coroutine_threadsafe(server.start(), loop).result(timeout=10)
        port = server.port

        results = []

        def client_burst():
            with Client(port=port) as c:
                results.extend(c.allow(f"k{i}").allowed for i in range(20))

        t = threading.Thread(target=client_burst)
        t.start()
        time.sleep(0.01)  # let some requests queue inside the 50ms window
        asyncio.run_coroutine_threadsafe(server.shutdown(), loop).result(timeout=10)
        t.join(timeout=10)
        assert not t.is_alive()
        # Every request that reached the server before shutdown got a real
        # answer (drain flushes the queue rather than dropping it).
        assert all(results)
        loop.call_soon_threadsafe(loop.stop)
        thread.join(timeout=10)
        loop.close()
        lim.close()


class TestServerBinary:
    def test_cli_serves_and_shuts_down_cleanly(self, tmp_path):
        """Spawn the real binary (python -m ratelimiter_tpu.serving), drive
        it over TCP, SIGTERM it, assert clean exit — the reference's
        cmd/server TODO list, end to end."""
        import os
        import signal as sig
        import socket
        import subprocess
        import sys

        env = dict(os.environ)
        env["PYTHONPATH"] = os.pathsep.join(
            [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
            + env.get("PYTHONPATH", "").split(os.pathsep))
        # Pick a free port up front.
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        proc = subprocess.Popen(
            [sys.executable, "-m", "ratelimiter_tpu.serving",
             "--backend", "exact", "--algorithm", "fixed_window",
             "--limit", "2", "--window", "60", "--port", str(port)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        try:
            line = proc.stdout.readline()
            assert "serving" in line, line
            with Client(port=port, timeout=10.0) as c:
                assert c.allow("k").allowed
                assert c.allow("k").allowed
                assert not c.allow("k").allowed
                serving, _, decisions = c.health()
                assert serving and decisions == 3
            proc.send_signal(sig.SIGTERM)
            assert proc.wait(timeout=15) == 0
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait()

    def test_decorator_stack_flags(self):
        """--circuit-breaker / --log-decisions / --trace / --no-metrics
        build the documented stack (breaker judged from real calls,
        metrics outside it, logging outermost)."""
        from ratelimiter_tpu import Algorithm, Config, create_limiter
        from ratelimiter_tpu.observability import (
            CircuitBreakerDecorator,
            LoggingDecorator,
            MetricsDecorator,
            TracingDecorator,
        )
        from ratelimiter_tpu.serving.__main__ import (
            build_limiter_stack,
            build_parser,
        )

        ap = build_parser()
        cfg = Config(algorithm=Algorithm.FIXED_WINDOW, limit=5, window=60.0)

        args = ap.parse_args(["--circuit-breaker", "--log-decisions",
                              "--trace", "--breaker-threshold", "2",
                              "--breaker-cooldown", "3.5"])
        stack = build_limiter_stack(create_limiter(cfg, backend="exact"), args)
        assert isinstance(stack, LoggingDecorator)
        assert isinstance(stack.inner, MetricsDecorator)
        assert isinstance(stack.inner.inner, CircuitBreakerDecorator)
        assert stack.inner.inner.failure_threshold == 2
        assert stack.inner.inner.cooldown == 3.5
        assert isinstance(stack.inner.inner.inner, TracingDecorator)
        assert stack.allow("k").allowed  # stack actually serves decisions
        stack.close()

        from ratelimiter_tpu.observability.decorators import LimiterDecorator

        args = ap.parse_args(["--no-metrics"])
        bare = build_limiter_stack(create_limiter(cfg, backend="exact"), args)
        assert not isinstance(bare, LimiterDecorator)
        bare.close()

    def test_cli_circuit_breaker_flag_serves(self):
        """The shipped binary accepts --circuit-breaker and still answers
        decisions (the breaker is transparent on a healthy backend)."""
        import os
        import signal as sig
        import socket
        import subprocess
        import sys

        env = dict(os.environ)
        env["PYTHONPATH"] = os.pathsep.join(
            [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
            + env.get("PYTHONPATH", "").split(os.pathsep))
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        proc = subprocess.Popen(
            [sys.executable, "-m", "ratelimiter_tpu.serving",
             "--backend", "exact", "--algorithm", "sliding_window",
             "--limit", "3", "--window", "60", "--port", str(port),
             "--circuit-breaker", "--breaker-threshold", "2"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        try:
            line = proc.stdout.readline()
            assert "serving" in line, line
            with Client(port=port, timeout=10.0) as c:
                assert c.allow("k").allowed
                assert not c.allow_n("k", 5).allowed
            proc.send_signal(sig.SIGTERM)
            assert proc.wait(timeout=15) == 0
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait()
