"""Flight-recorder tracing subsystem (ADR-014) + metrics satellites.

Covers, per ISSUE 7:

* recorder mechanics: ring wraparound, Chrome-trace/Perfetto dump shape;
* span-tree completeness oracle: one MIXED mesh frame through EACH front
  door yields a connected trace (client span -> door stages -> per-slice
  dispatch -> device), with monotone timestamps and no same-stage
  overlap per thread;
* wire propagation: the flagged trace-id extension survives client ->
  server on both doors (and the DCN envelope), HTTP carries
  ``traceparent``;
* tracing-off = zero-overhead smoke: RECORDER is None by default and
  decisions are identical with the recorder on vs off;
* metrics.py satellites: label-value escaping per the Prometheus spec,
  locked reads, the bisect bucket scan, OpenMetrics exemplars;
* the /debug/trace and /debug/profile endpoints' trust boundary.
"""

from __future__ import annotations

import asyncio
import json
import threading
import urllib.request

import numpy as np
import pytest

from ratelimiter_tpu import (
    Algorithm,
    Config,
    ManualClock,
    SketchParams,
    create_limiter,
)
from ratelimiter_tpu.observability import metrics as m
from ratelimiter_tpu.observability import tracing
from ratelimiter_tpu.parallel import SlicedMeshLimiter
from ratelimiter_tpu.serving import protocol as p
from ratelimiter_tpu.serving.client import AsyncClient, Client
from ratelimiter_tpu.serving.http_gateway import HttpGateway
from ratelimiter_tpu.serving.native_server import (
    NativeRateLimitServer,
    native_server_available,
)
from ratelimiter_tpu.serving.server import RateLimitServer

T0 = 1_700_000_000.0


@pytest.fixture
def recorder():
    """Fresh process recorder per test; always off afterwards so the
    rest of the suite keeps the zero-overhead default."""
    tracing.disable()
    rec = tracing.enable(1024)
    try:
        yield rec
    finally:
        tracing.disable()


def _sketch_cfg(**kw):
    return Config(algorithm=Algorithm.SLIDING_WINDOW, limit=100,
                  window=60.0,
                  sketch=SketchParams(depth=2, width=2048, sub_windows=8),
                  **kw)


# ---------------------------------------------------------------- recorder


class TestRecorder:
    def test_record_and_dump(self, recorder):
        t0 = tracing.now()
        recorder.record("io", t0, t0 + 1000, trace_id=7, shard=3, batch=5)
        spans = recorder.dump()
        assert len(spans) == 1
        s = spans[0]
        assert s["stage"] == "io" and s["trace_id"] == 7
        assert s["shard"] == 3 and s["batch"] == 5
        assert s["t_end_ns"] - s["t_start_ns"] == 1000

    def test_ring_wraparound_keeps_latest(self, recorder):
        cap = recorder.capacity
        base = tracing.now()
        for i in range(cap + 40):
            recorder.record("io", base + i, base + i + 1, trace_id=i + 1)
        spans = [s for s in recorder.dump() if s["stage"] == "io"]
        assert len(spans) == cap
        # The oldest 40 fell off; what remains is the newest cap records
        # in monotone order.
        ids = [s["trace_id"] for s in spans]
        assert ids == list(range(41, cap + 41))

    def test_per_thread_rings_no_interleave_corruption(self, recorder):
        def worker(k):
            for i in range(500):
                t = tracing.now()
                recorder.record("launch", t, t + 1, trace_id=k)

        threads = [threading.Thread(target=worker, args=(k,))
                   for k in (1, 2, 3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        spans = recorder.dump()
        per = {k: sum(1 for s in spans if s["trace_id"] == k)
               for k in (1, 2, 3)}
        assert per == {1: 500, 2: 500, 3: 500}

    def test_chrome_trace_is_json_with_events(self, recorder):
        t0 = tracing.now()
        recorder.record("device", t0, t0 + 5000, trace_id=9, batch=2)
        payload = recorder.chrome_trace()
        text = json.dumps(payload)          # Perfetto-loadable JSON
        back = json.loads(text)
        ev = back["traceEvents"][0]
        assert ev["ph"] == "X" and ev["name"] == "device"
        assert ev["args"]["trace_id"] == f"{9:016x}"
        assert ev["dur"] == pytest.approx(5.0)

    def test_off_by_default_and_module_record_noop(self):
        tracing.disable()
        assert tracing.RECORDER is None
        # Guarded module-level record is a no-op, not an error.
        tracing.record("io", 0, 1, trace_id=1)

    def test_stage_summary(self, recorder):
        t0 = tracing.now()
        for i in range(10):
            recorder.record("encode", t0, t0 + 10_000)
        summary = recorder.stage_summary()
        assert summary["encode"]["count"] == 10
        assert summary["encode"]["mean_us"] == pytest.approx(10.0)


class TestTraceparent:
    def test_parse_roundtrip(self):
        tid = tracing.new_trace_id()
        hdr = tracing.format_traceparent(tid)
        assert tracing.parse_traceparent(hdr) == tid

    def test_parse_garbage(self):
        assert tracing.parse_traceparent(None) == 0
        assert tracing.parse_traceparent("") == 0
        assert tracing.parse_traceparent("00-zz-yy-01") == 0
        assert tracing.parse_traceparent("nonsense") == 0


# ------------------------------------------------------------ wire framing


class TestWireTraceExtension:
    def test_with_trace_split_trace_roundtrip(self):
        frame = p.encode_allow_n(17, "user:1", 2)
        tid = tracing.new_trace_id()
        traced = p.with_trace(frame, tid)
        length, type_, req_id = p.parse_header(traced[:p.HEADER_SIZE])
        assert type_ == p.T_ALLOW_N | p.TRACE_FLAG and req_id == 17
        base, got_tid, body = p.split_trace(
            type_, traced[p.HEADER_SIZE:])
        assert base == p.T_ALLOW_N and got_tid == tid
        key, n = p.parse_allow_n(body)
        assert key == "user:1" and n == 2

    def test_untraced_passthrough(self):
        frame = p.encode_allow_n(1, "k", 1)
        _, type_, _ = p.parse_header(frame[:p.HEADER_SIZE])
        base, tid, body = p.split_trace(type_, frame[p.HEADER_SIZE:])
        assert base == p.T_ALLOW_N and tid == 0
        assert body == frame[p.HEADER_SIZE:]

    def test_response_types_cannot_carry_trace(self):
        ok = p.encode_ok(1)
        with pytest.raises(p.ProtocolError):
            p.with_trace(ok, 5)

    def test_traced_dcn_push_keeps_cap_and_hmac(self):
        # The trace prefix rides OUTSIDE the HMAC envelope: verification
        # is unchanged and the DCN size cap still applies to the base
        # type.
        delta = np.ones((2, 4), dtype=np.int64)
        frame = p.encode_dcn_debt(3, delta, secret="s3", sender=9,
                                  seq=123)
        traced = p.with_trace(frame, 77)
        length, type_, _ = p.parse_header(traced[:p.HEADER_SIZE],
                                          allow_dcn=True)
        base, tid, body = p.split_trace(type_, traced[p.HEADER_SIZE:])
        assert base == p.T_DCN_PUSH and tid == 77
        payload = p.unwrap_dcn_auth(body, "s3")
        kind, got, _ = p.parse_dcn(payload, 2, 4, 0)
        assert kind == p.DCN_KIND_DEBT
        np.testing.assert_array_equal(got, delta)


# ----------------------------------------------------- span-tree oracles


def _assert_span_tree(spans, tid, *, want_stages, n_slices=None):
    """The completeness oracle: every wanted stage present under the
    trace id, timestamps monotone (t_end >= t_start), same-stage spans
    non-overlapping per thread, and per-slice spans (when present)
    contained in the frame's device window."""
    mine = [s for s in spans if s["trace_id"] == tid]
    stages = {s["stage"] for s in mine}
    missing = set(want_stages) - stages
    assert not missing, f"stages missing from the trace: {missing}"
    for s in mine:
        assert s["t_end_ns"] >= s["t_start_ns"], s
    # Same-stage spans must not overlap within one thread (each thread's
    # pipeline processes one frame's stage at a time).
    by = {}
    for s in mine:
        by.setdefault((s["thread"], s["stage"]), []).append(s)
    for (_, stage), group in by.items():
        group.sort(key=lambda s: s["t_start_ns"])
        for a, b in zip(group, group[1:]):
            assert a["t_end_ns"] <= b["t_start_ns"], (
                f"overlapping {stage} spans in one thread")
    if n_slices is not None:
        slices = [s for s in mine if s["stage"] == "slice"]
        assert len({s["shard"] for s in slices}) == n_slices
        device = [s for s in mine if s["stage"] == "device"]
        assert device, "no device span to parent the slices"
        lo = min(d["t_start_ns"] for d in device)
        hi = max(d["t_end_ns"] for d in device)
        for s in slices:
            assert lo <= s["t_start_ns"] and s["t_end_ns"] <= hi, (
                "slice span escapes the frame's device window")


class TestAsyncioDoorSpanTree:
    def test_mixed_mesh_frame_traced_end_to_end(self, recorder):
        """One mixed frame through the asyncio door on a 2-slice mesh:
        client span -> io -> coalesce/queue/launch -> device -> barrier +
        per-slice spans -> resolve -> encode, all under ONE wire-
        propagated trace id."""
        cfg = _sketch_cfg()
        mesh = SlicedMeshLimiter(cfg, n_devices=2)

        async def run():
            srv = RateLimitServer(mesh, max_batch=4096, max_delay=200e-6)
            await srv.start()
            c = await AsyncClient.connect(srv.host, srv.port)
            tid = tracing.new_trace_id()
            # Raw ids chosen to fan out over BOTH slices (uniform ids
            # split ~evenly under splitmix64 % 2).
            ids = np.arange(1, 257, dtype=np.uint64)
            t0 = tracing.now()
            out = await c.allow_hashed(ids, trace_id=tid)
            tracing.record("client", t0, tracing.now(), trace_id=tid,
                           batch=len(out))
            assert len(out) == 256 and out.allowed.all()
            await c.close()
            await srv.shutdown()
            return tid

        tid = asyncio.run(run())
        spans = recorder.dump()
        _assert_span_tree(
            spans, tid,
            want_stages=("client", "io", "coalesce", "queue", "launch",
                         "device", "barrier", "slice", "resolve",
                         "encode"),
            n_slices=2)
        # The client span must enclose the whole server-side pipeline.
        mine = [s for s in spans if s["trace_id"] == tid]
        client = next(s for s in mine if s["stage"] == "client")
        for s in mine:
            if s["stage"] != "client":
                assert client["t_start_ns"] <= s["t_start_ns"]
                assert s["t_end_ns"] <= client["t_end_ns"]
        mesh.close()

    def test_string_lane_traced(self, recorder):
        lim = create_limiter(_sketch_cfg(), backend="sketch")

        async def run():
            srv = RateLimitServer(lim, max_batch=64, max_delay=200e-6)
            await srv.start()
            c = await AsyncClient.connect(srv.host, srv.port)
            tid = tracing.new_trace_id()
            res = await c.allow_n("user:1", 1, trace_id=tid)
            assert res.allowed
            await c.close()
            await srv.shutdown()
            return tid

        tid = asyncio.run(run())
        _assert_span_tree(recorder.dump(), tid,
                          want_stages=("io", "coalesce", "launch",
                                       "device", "resolve", "encode"))
        lim.close()


@pytest.mark.skipif(not native_server_available(),
                    reason="needs g++ for the native server")
class TestNativeDoorSpanTree:
    def test_mixed_mesh_frame_traced_end_to_end(self, recorder):
        """One mixed hashed frame through the NATIVE door with the mesh
        slices mounted as dispatch shards (1 shard == 1 device,
        ADR-012): the ABI 9 spans callback yields io -> dispatch ->
        device -> complete per touched shard, under the wire trace id."""
        from ratelimiter_tpu.parallel.limiter import build_slices

        slices = build_slices(_sketch_cfg(), n_devices=2)
        srv = NativeRateLimitServer(slices[0], "127.0.0.1", 0,
                                    max_batch=4096, max_delay=200e-6,
                                    shard_limiters=list(slices))
        srv.start()
        try:
            with Client(port=srv.port) as c:
                tid = tracing.new_trace_id()
                t0 = tracing.now()
                out = c.allow_hashed(np.arange(1, 257, dtype=np.uint64),
                                     trace_id=tid)
                tracing.record("client", t0, tracing.now(), trace_id=tid,
                               batch=len(out))
                assert len(out) == 256 and out.allowed.all()
                # stats() surfaces the cumulative per-stage aggregates
                # (ABI 9).
                st = srv.stats()
                assert st["stage_ns"]["batches"] > 0
                assert st["stage_ns"]["device"] > 0
        finally:
            srv.shutdown()
        spans = recorder.dump()
        _assert_span_tree(spans, tid,
                          want_stages=("client", "io", "dispatch",
                                       "device", "complete"))
        # Both shards (= devices) dispatched under this trace id.
        mine = [s for s in spans if s["trace_id"] == tid]
        assert {s["shard"] for s in mine
                if s["stage"] == "device"} == {0, 1}
        client = next(s for s in mine if s["stage"] == "client")
        for s in mine:
            if s["stage"] != "client":
                assert client["t_start_ns"] <= s["t_start_ns"]
                assert s["t_end_ns"] <= client["t_end_ns"]

    def test_string_lane_traced(self, recorder):
        lim = create_limiter(_sketch_cfg(), backend="sketch")
        srv = NativeRateLimitServer(lim, "127.0.0.1", 0, max_batch=64,
                                    max_delay=200e-6)
        srv.start()
        try:
            with Client(port=srv.port) as c:
                tid = tracing.new_trace_id()
                res = c.allow_n("user:1", 1, trace_id=tid)
                assert res.allowed
                res2 = c.allow_batch(["a", "b"], [1, 1], trace_id=tid)
                assert all(r.allowed for r in res2)
        finally:
            srv.shutdown()
        lim.close()
        _assert_span_tree(recorder.dump(), tid,
                          want_stages=("io", "dispatch", "device",
                                       "complete"))


# --------------------------------------------------- zero-overhead smoke


class TestZeroOverhead:
    def test_decisions_identical_recorder_on_vs_off(self):
        """Tracing must never change behavior: same traffic, recorder on
        vs off, byte-identical decision stream."""
        def run(enable: bool):
            tracing.disable()
            if enable:
                tracing.enable(1024)
            try:
                lim = create_limiter(
                    _sketch_cfg(), backend="sketch",
                    clock=ManualClock(T0))

                async def drive():
                    srv = RateLimitServer(lim, max_batch=32,
                                          max_delay=100e-6)
                    await srv.start()
                    c = await AsyncClient.connect(srv.host, srv.port)
                    out = []
                    ids = np.arange(1, 65, dtype=np.uint64)
                    for i in range(8):
                        br = await c.allow_hashed(
                            ids, trace_id=(i + 1) if enable else 0)
                        out.append(br.allowed.copy())
                        rs = await c.allow_batch(
                            [f"u:{j}" for j in range(16)],
                            trace_id=(i + 1) if enable else 0)
                        out.append(np.array([r.allowed for r in rs]))
                    await c.close()
                    await srv.shutdown()
                    return np.concatenate(out)

                got = asyncio.run(drive())
                lim.close()
                return got
            finally:
                tracing.disable()

        off = run(False)
        on = run(True)
        np.testing.assert_array_equal(off, on)

    def test_recorder_on_throughput_smoke(self):
        """Pinned throughput smoke for the acceptance bar (recorder ON
        within 3% of OFF on the standard bench). The claim guarded here
        is structural — spans are stamped per *dispatch*, never per
        decision, at clock-read cost — so the CI margin is loose (1.5x)
        to absorb shared-runner scheduler noise; the tight 3% A/B is a
        bench measurement (``bench.py`` with/without ``--trace``,
        recorded in ADR-014)."""
        import time as _time

        from ratelimiter_tpu.serving.batcher import MicroBatcher

        def run(enable: bool) -> float:
            tracing.disable()
            if enable:
                tracing.enable(4096)
            try:
                lim = create_limiter(_sketch_cfg(), backend="sketch")
                ids = np.arange(1, 2049, dtype=np.uint64)
                ns = np.ones(len(ids), dtype=np.int64)

                async def drive() -> float:
                    b = MicroBatcher(lim, max_batch=4096,
                                     max_delay=50e-6,
                                     registry=m.Registry())
                    await b.submit_hashed_nowait(ids, ns)  # warm/compile
                    t0 = _time.perf_counter()
                    for i in range(20):
                        await b.submit_hashed_nowait(
                            ids, ns, trace_id=(i + 1) if enable else 0)
                    dt = _time.perf_counter() - t0
                    await b.drain()
                    b.close()
                    return dt

                # Best of 3 rounds: the per-round minimum is the
                # noise-robust estimator for "cost of the code path".
                best = min(asyncio.run(drive()) for _ in range(3))
                lim.close()
                return best
            finally:
                tracing.disable()

        off = run(False)
        on = run(True)
        assert on <= off * 1.5, (
            f"recorder-on hot path regressed: {on:.4f}s vs {off:.4f}s "
            "for 20 traced 2048-id dispatches")

    def test_hot_path_defaults_off(self):
        tracing.disable()
        assert tracing.RECORDER is None
        from ratelimiter_tpu.serving.batcher import MicroBatcher
        lim = create_limiter(_sketch_cfg(), backend="sketch",
                             clock=ManualClock(T0))

        async def drive():
            b = MicroBatcher(lim, max_batch=16, registry=m.Registry())
            fut = b.submit_nowait("k", 1)
            res = await fut
            await b.drain()
            b.close()
            return res

        res = asyncio.run(drive())
        assert res.allowed
        assert tracing.RECORDER is None
        lim.close()


# --------------------------------------------------- metrics satellites


class TestMetricsSatellites:
    def test_label_value_escaping(self):
        reg = m.Registry()
        c = reg.counter("t_total", "h")
        evil = 'a"b\\c\nd'
        c.inc(key=evil)
        text = reg.render()
        line = next(ln for ln in text.splitlines()
                    if ln.startswith("t_total{"))
        assert line == 't_total{key="a\\"b\\\\c\\nd"} 1'
        # The exposition must stay one-sample-per-line: no raw newline
        # leaked into the body.
        assert 'a"b' not in text

    def test_histogram_bisect_matches_linear_reference(self):
        buckets = m.LATENCY_BUCKETS
        h = m.Histogram("h_seconds", "h", buckets)
        rng = np.random.default_rng(0)
        values = list(rng.uniform(0, 3.0, size=500))
        values += list(buckets)  # exact boundary values: `<=` semantics

        def linear_bucket(v):
            for i, ub in enumerate(buckets):
                if v <= ub:
                    return i
            return len(buckets)

        want = [0] * (len(buckets) + 1)
        for v in values:
            h.observe(v)
            want[linear_bucket(v)] += 1
        got = h._counts[()]
        assert got[:-1] == want[:-1] and got[-1] == want[-1]
        assert h.count() == len(values)
        assert h.sum() == pytest.approx(sum(values))

    def test_locked_reads_race_free(self):
        c = m.Counter("race_total", "h")
        g = m.Gauge("race_g", "h")
        h = m.Histogram("race_seconds", "h")
        stop = threading.Event()

        def writer():
            i = 0
            while not stop.is_set():
                c.inc(key=f"k{i % 50}")
                g.inc(key=f"k{i % 50}")
                h.observe(0.01)
                i += 1

        t = threading.Thread(target=writer)
        t.start()
        try:
            for _ in range(2000):
                c.value(key="k1")
                g.value(key="k1")
                h.count()
                h.sum()
        finally:
            stop.set()
            t.join()

    def test_openmetrics_exemplars(self):
        reg = m.Registry()
        h = reg.histogram("lat_seconds", "h")
        h.observe(0.003, exemplar="00000000000000ab", stage="device")
        h.observe(0.004, stage="device")  # unsampled: no exemplar update
        # Past every bucket bound -> the +Inf overflow bucket keeps its
        # exemplar too (the slowest observations are the ones worth a
        # trace id).
        h.observe(99.0, exemplar="00000000000000cd", stage="device")
        classic = reg.render()
        assert "# {" not in classic       # classic text has no exemplars
        om = reg.render_openmetrics()
        assert '# {trace_id="00000000000000ab"} 0.003' in om
        assert '# {trace_id="00000000000000cd"} 99' in om
        inf_line = next(l for l in om.splitlines()
                        if 'le="+Inf"' in l and "lat_seconds" in l)
        assert "00000000000000cd" in inf_line
        assert om.rstrip().endswith("# EOF")

    def test_openmetrics_counter_family_name(self):
        """OpenMetrics counter families must be named WITHOUT the
        `_total` suffix in HELP/TYPE while the sample keeps it —
        `# TYPE x_total counter` fails Prometheus's strict OM parser
        and drops the whole scrape."""
        reg = m.Registry()
        c = reg.counter("req_total", "requests")
        c.inc(door="binary")
        classic = reg.render()
        assert "# TYPE req_total counter" in classic
        assert 'req_total{door="binary"} 1' in classic
        om = reg.render_openmetrics()
        assert "# TYPE req counter" in om
        assert "# TYPE req_total" not in om
        assert 'req_total{door="binary"} 1' in om

    def test_stage_histograms_via_collect_hook(self):
        reg = m.Registry()
        tracing.disable()
        rec = tracing.enable(256, registry=reg)
        try:
            t0 = tracing.now()
            rec.record("device", t0, t0 + 2_000_000, trace_id=0xAB)
            text = reg.render_openmetrics()
            assert "rate_limiter_stage_seconds" in text
            assert 'stage="device"' in text
            assert f'trace_id="{0xAB:016x}"' in text
            # Scrape again: the cursor advanced, counts must not double.
            text2 = reg.render()
            line = next(
                ln for ln in text2.splitlines()
                if ln.startswith("rate_limiter_stage_seconds_count"))
            assert line.endswith(" 1")
        finally:
            tracing.disable()


# ------------------------------------------------------- debug endpoints


class TestDebugEndpoints:
    def _get(self, port, path, token=None, timeout=10):
        req = urllib.request.Request(f"http://127.0.0.1:{port}{path}")
        if token:
            req.add_header("Authorization", f"Bearer {token}")
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return resp.status, json.loads(resp.read().decode())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read().decode())

    def test_debug_trace_gating_and_dump(self, recorder):
        lim = create_limiter(_sketch_cfg(), backend="sketch",
                             clock=ManualClock(T0))
        gw = HttpGateway(lambda key, n: lim.allow_n(key, n), lim.reset,
                         enable_debug=True, debug_token="s3cr3t")
        gw.start()
        try:
            t0 = tracing.now()
            recorder.record("device", t0, t0 + 1000, trace_id=5)
            code, _ = self._get(gw.port, "/debug/trace")
            assert code == 403                       # bearer required
            code, body = self._get(gw.port, "/debug/trace", token="s3cr3t")
            assert code == 200 and body["enabled"]
            assert any(ev["name"] == "device"
                       for ev in body["traceEvents"])
        finally:
            gw.shutdown()
            lim.close()

    def test_debug_disabled_by_default(self):
        lim = create_limiter(_sketch_cfg(), backend="sketch",
                             clock=ManualClock(T0))
        gw = HttpGateway(lambda key, n: lim.allow_n(key, n), lim.reset)
        gw.start()
        try:
            code, _ = self._get(gw.port, "/debug/trace")
            assert code == 403
            code, _ = self._get(gw.port, "/debug/profile?seconds=0.1")
            assert code == 403
        finally:
            gw.shutdown()
            lim.close()

    @pytest.mark.slow
    def test_debug_profile_capture(self, recorder):
        # Slow lane: the generous ceiling below is real — late in a
        # full-suite run this single test has been MEASURED at 120 s
        # (TSL profiler-server init), a seventh of the tier-1 budget.
        # The tracing CI lane runs it unfiltered in a fresh process,
        # where the init is seconds.
        lim = create_limiter(_sketch_cfg(), backend="sketch",
                             clock=ManualClock(T0))
        gw = HttpGateway(lambda key, n: lim.allow_n(key, n), lim.reset,
                         enable_debug=True)
        gw.start()
        try:
            # The process's FIRST capture pays several seconds of
            # profiler-server init on top of the capture window — and
            # late in a full-suite run (hundreds of live threads, a
            # loaded box) that init has been observed past 90 s, so the
            # ceiling is generous: this asserts the endpoint WORKS, not
            # how fast TSL brings up its profiler server.
            code, body = self._get(gw.port, "/debug/profile?seconds=0.2",
                                   timeout=300)
            # 503 = profiler unavailable on this platform (reported, not
            # crashed); 200 = capture artifacts on disk.
            assert code in (200, 503)
            if code == 200:
                assert body["ok"] and body["files"]
        finally:
            gw.shutdown()
            lim.close()

    def test_traceparent_reaches_trace_aware_decide(self, recorder):
        lim = create_limiter(_sketch_cfg(), backend="sketch",
                             clock=ManualClock(T0))
        seen = {}

        def decide(key, n, trace_id=0):
            seen["tid"] = trace_id
            return lim.allow_n(key, n)

        gw = HttpGateway(decide, lim.reset)
        gw.start()
        try:
            tid = tracing.new_trace_id()
            req = urllib.request.Request(
                f"http://127.0.0.1:{gw.port}/v1/allow?key=u1")
            req.add_header("traceparent", tracing.format_traceparent(tid))
            with urllib.request.urlopen(req, timeout=10) as resp:
                assert resp.status == 200
                assert resp.headers["traceparent"]
            assert seen["tid"] == tid
            spans = recorder.dump()
            assert any(s["stage"] == "http" and s["trace_id"] == tid
                       for s in spans)
        finally:
            gw.shutdown()
            lim.close()

    def test_metrics_openmetrics_negotiation(self, recorder):
        reg = m.Registry()
        h = reg.histogram("neg_seconds", "h")
        h.observe(0.001, exemplar="ff")
        lim = create_limiter(_sketch_cfg(), backend="sketch",
                             clock=ManualClock(T0))
        gw = HttpGateway(lambda key, n: lim.allow_n(key, n), lim.reset,
                         metrics_render=reg.render)
        gw.start()
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{gw.port}/metrics")
            with urllib.request.urlopen(req, timeout=10) as resp:
                assert "# EOF" not in resp.read().decode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{gw.port}/metrics")
            req.add_header("Accept", "application/openmetrics-text")
            with urllib.request.urlopen(req, timeout=10) as resp:
                text = resp.read().decode()
                assert "openmetrics-text" in resp.headers["Content-Type"]
                assert text.rstrip().endswith("# EOF")
                assert 'trace_id="ff"' in text
        finally:
            gw.shutdown()
            lim.close()


# ----------------------------------------------------- bench integration


class TestBenchTrace:
    def test_loadgen_trace_sampling(self):
        """The e2e loadgen's trace_sample knob (`python -m benchmarks
        --only e2e --trace-sample N`): sampled frames carry wire trace
        ids and land client spans in the local recorder. The server is
        IN-PROCESS here, so its spans share the loadgen's rings — size
        the ring past the scalar-latency pass's span volume or the
        early client spans wrap away (in the real subprocess loadgen
        the client process records only its own spans)."""
        from benchmarks.e2e import _drive

        tracing.disable()
        rec = tracing.enable(1 << 14)
        lim = create_limiter(_sketch_cfg(), backend="sketch")
        try:
            async def run():
                srv = RateLimitServer(lim, max_batch=256,
                                      max_delay=200e-6)
                await srv.start()
                try:
                    return await _drive(srv.port, seconds=0.3, conns=1,
                                        window=64, n_keys=100,
                                        warmup=0.0, trace_sample=1)
                finally:
                    await srv.shutdown()

            out = asyncio.run(run())
            assert out["completed"] > 0
            clients = [s for s in rec.dump() if s["stage"] == "client"]
            assert clients, "no sampled client spans recorded"
            assert all(s["trace_id"] for s in clients)
        finally:
            tracing.disable()
            lim.close()

    def test_stage_breakdown_smoke(self):
        """bench.py --trace block: tiny run, every expected stage key
        present and the hot stages populated."""
        import bench

        tracing.disable()
        out = bench.measure_stage_breakdown(seconds=0.3, batch=256,
                                            width=1 << 11)
        assert tracing.RECORDER is None      # restored the off default
        for stage in ("io", "route", "queue", "coalesce", "launch",
                      "device", "resolve", "encode"):
            assert stage in out["stage_us"]
        assert out["decisions"] > 0
        assert out["stage_us"]["device"] > 0
        assert out["stage_spans"]["io"] > 0
