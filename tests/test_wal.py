"""Write-ahead log: framing, rotation, pruning, torn-tail recovery.

The load-bearing property (ISSUE-2 satellite): recovery NEVER raises on
a damaged log and replays exactly the intact prefix — fuzzed here by
truncating a valid log at every byte offset and by flipping bytes.
"""

import os

import pytest

from ratelimiter_tpu.persistence import wal as w


def fill(log, n, start=0):
    for i in range(start, start + n):
        log.append(w.REC_POLICY_SET,
                   {"key": f"user:{i}", "limit": 10 + i, "window_scale": 1.0})


class TestAppendReplay:
    def test_round_trip(self, tmp_path):
        log = w.WriteAheadLog(str(tmp_path))
        fill(log, 5)
        log.append(w.REC_RESET, {"key": "gone"})
        log.close()
        recs = list(w.replay(str(tmp_path)))
        assert [r.seq for r in recs] == [1, 2, 3, 4, 5, 6]
        assert recs[0].payload == {"key": "user:0", "limit": 10,
                                   "window_scale": 1.0}
        assert recs[-1].type == w.REC_RESET

    def test_after_seq_filters(self, tmp_path):
        log = w.WriteAheadLog(str(tmp_path))
        fill(log, 10)
        log.close()
        assert [r.seq for r in w.replay(str(tmp_path), after_seq=7)] == [8, 9, 10]
        assert list(w.replay(str(tmp_path), after_seq=10)) == []

    def test_empty_and_missing_dir(self, tmp_path):
        assert list(w.replay(str(tmp_path / "nope"))) == []
        w.WriteAheadLog(str(tmp_path)).close()
        assert list(w.replay(str(tmp_path))) == []

    def test_reopen_continues_seq(self, tmp_path):
        log = w.WriteAheadLog(str(tmp_path))
        fill(log, 3)
        log.close()
        log2 = w.WriteAheadLog(str(tmp_path))
        assert log2.last_seq == 3
        assert log2.append(w.REC_RESET, {"key": "k"}) == 4
        log2.close()
        assert [r.seq for r in w.replay(str(tmp_path))] == [1, 2, 3, 4]

    def test_fsync_policies(self, tmp_path):
        for policy in ("always", "interval", "never"):
            d = tmp_path / policy
            log = w.WriteAheadLog(str(d), fsync=policy)
            fill(log, 3)
            log.close()
            assert len(list(w.replay(str(d)))) == 3


class TestRotationPrune:
    def test_rotation_by_size(self, tmp_path):
        log = w.WriteAheadLog(str(tmp_path), max_bytes=256)
        fill(log, 20)
        log.close()
        segs = w.segment_files(str(tmp_path))
        assert len(segs) > 1
        # Segment names carry their first seq; replay crosses boundaries.
        assert [r.seq for r in w.replay(str(tmp_path))] == list(range(1, 21))

    def test_prune_below_watermark(self, tmp_path):
        log = w.WriteAheadLog(str(tmp_path), max_bytes=256)
        fill(log, 30)
        removed = log.prune(upto_seq=15)
        assert removed > 0
        # Everything past the watermark survives; the active segment stays.
        seqs = [r.seq for r in w.replay(str(tmp_path), after_seq=15)]
        assert seqs == list(range(16, 31))
        log.append(w.REC_RESET, {"key": "k"})
        log.close()
        assert [r.seq for r in w.replay(str(tmp_path), after_seq=15)][-1] == 31

    def test_prune_never_removes_active(self, tmp_path):
        log = w.WriteAheadLog(str(tmp_path))
        fill(log, 5)
        assert log.prune(upto_seq=5) == 0
        log.close()
        assert len(list(w.replay(str(tmp_path)))) == 5


class TestTornTail:
    """ISSUE-2 satellite: truncate a valid log at EVERY byte offset —
    recovery must never raise and must replay exactly the records whose
    full frame survived."""

    def _log_bytes(self, tmp_path, n=8):
        log = w.WriteAheadLog(str(tmp_path / "orig"))
        fill(log, n)
        log.close()
        (seg,) = [p for _, p in w.segment_files(str(tmp_path / "orig"))]
        with open(seg, "rb") as f:
            buf = f.read()
        # Frame boundaries, from the scanner itself (trusted: round-trip
        # test above pins it against append).
        recs, valid = w._scan_buffer(buf, 0)
        assert len(recs) == n and valid == len(buf)
        return buf

    def test_truncate_every_offset(self, tmp_path):
        buf = self._log_bytes(tmp_path)
        boundaries = []
        off = 0
        while off < len(buf):
            _, length, _, _ = w._HEAD.unpack_from(buf, off)
            off += w._HEAD.size + length
            boundaries.append(off)
        d = tmp_path / "t"
        os.makedirs(d, exist_ok=True)
        seg = str(d / "wal-00000000000000000001.log")
        for cut in range(len(buf) + 1):
            with open(seg, "wb") as f:
                f.write(buf[:cut])
            recs = list(w.replay(str(d)))           # must never raise
            expect = sum(b <= cut for b in boundaries)
            assert len(recs) == expect, f"cut at {cut}"
            assert [r.seq for r in recs] == list(range(1, expect + 1))

    def test_flipped_byte_stops_at_prefix(self, tmp_path):
        buf = self._log_bytes(tmp_path, n=4)
        d = tmp_path / "f"
        os.makedirs(d, exist_ok=True)
        seg = str(d / "wal-00000000000000000001.log")
        # Corrupt one byte inside the third record's payload: records 1-2
        # replay, 3+ do not (CRC catches it).
        recs, _ = w._scan_buffer(buf, 0)
        off = 0
        for _ in range(2):
            _, length, _, _ = w._HEAD.unpack_from(buf, off)
            off += w._HEAD.size + length
        bad = bytearray(buf)
        bad[off + w._HEAD.size + 2] ^= 0xFF
        with open(seg, "wb") as f:
            f.write(bytes(bad))
        assert [r.seq for r in w.replay(str(d))] == [1, 2]

    def test_reopen_truncates_torn_tail(self, tmp_path):
        """Appends after a torn tail land after the valid prefix — the
        garbage is cut off, not appended past."""
        log = w.WriteAheadLog(str(tmp_path))
        fill(log, 3)
        log.close()
        (seg,) = [p for _, p in w.segment_files(str(tmp_path))]
        size = os.path.getsize(seg)
        with open(seg, "rb+") as f:
            f.truncate(size - 5)                    # tear record 3
        log2 = w.WriteAheadLog(str(tmp_path))
        assert log2.last_seq == 2
        assert log2.append(w.REC_RESET, {"key": "k"}) == 3
        log2.close()
        recs = list(w.replay(str(tmp_path)))
        assert [(r.seq, r.type) for r in recs][-1] == (3, w.REC_RESET)
        assert len(recs) == 3

    def test_oversized_length_field_rejected(self, tmp_path):
        log = w.WriteAheadLog(str(tmp_path))
        fill(log, 2)
        log.close()
        (seg,) = [p for _, p in w.segment_files(str(tmp_path))]
        with open(seg, "ab") as f:
            f.write(w._HEAD.pack(0, w.MAX_PAYLOAD + 1, 3, w.REC_RESET))
        assert [r.seq for r in w.replay(str(tmp_path))] == [1, 2]


class TestSegmentGaps:
    def test_missing_middle_segment_stops_replay(self, tmp_path):
        """A pruned-from-the-middle (i.e. damaged) log must not replay
        later mutations against missing earlier ones."""
        log = w.WriteAheadLog(str(tmp_path), max_bytes=256)
        fill(log, 30)
        log.close()
        segs = w.segment_files(str(tmp_path))
        assert len(segs) >= 3
        os.unlink(segs[1][1])
        recs = list(w.replay(str(tmp_path)))
        # Only the first segment's records replay.
        assert recs and recs[-1].seq == segs[1][0] - 1

    def test_pruned_prefix_is_fine(self, tmp_path):
        """Segments pruned from the FRONT (below a snapshot watermark)
        are the normal case: replay starts at the first kept segment."""
        log = w.WriteAheadLog(str(tmp_path), max_bytes=256)
        fill(log, 30)
        log.close()
        segs = w.segment_files(str(tmp_path))
        os.unlink(segs[0][1])
        recs = list(w.replay(str(tmp_path)))
        assert recs[0].seq == segs[1][0]
        assert recs[-1].seq == 30


class TestValidation:
    def test_bad_fsync_policy(self, tmp_path):
        with pytest.raises(ValueError):
            w.WriteAheadLog(str(tmp_path), fsync="sometimes")


class TestSingleWriter:
    def test_second_writer_refused_while_first_lives(self, tmp_path):
        """Two live writers interleave frames and clobber the manifest:
        the second open must fail loudly, and release-on-close must let
        a successor in (flock also releases on kill -9)."""
        from ratelimiter_tpu.core.errors import CheckpointError

        log = w.WriteAheadLog(str(tmp_path))
        fill(log, 2)
        with pytest.raises(CheckpointError, match="exactly one writer"):
            w.WriteAheadLog(str(tmp_path))
        log.close()
        log2 = w.WriteAheadLog(str(tmp_path))       # lock released
        assert log2.last_seq == 2
        log2.close()


class TestMidHistoryDamage:
    """A torn record anywhere but the active tail means replay() can
    never reach later records: the WRITER must refuse to open (acking
    appends it can never replay would silently lose them), while
    replay() itself stays never-raise and yields the intact prefix."""

    def _damaged_dir(self, tmp_path):
        log = w.WriteAheadLog(str(tmp_path), max_bytes=256)
        fill(log, 30)
        log.close()
        segs = w.segment_files(str(tmp_path))
        assert len(segs) >= 3
        with open(segs[0][1], "rb+") as f:
            f.seek(10)
            b = f.read(1)
            f.seek(10)
            f.write(bytes([b[0] ^ 0xFF]))
        return segs

    def test_writer_refuses_mid_history_tear(self, tmp_path):
        from ratelimiter_tpu.core.errors import CheckpointError

        self._damaged_dir(tmp_path)
        with pytest.raises(CheckpointError, match="mid-history"):
            w.WriteAheadLog(str(tmp_path))

    def test_replay_still_never_raises(self, tmp_path):
        self._damaged_dir(tmp_path)
        recs = list(w.replay(str(tmp_path)))
        assert [r.seq for r in recs] == []          # tear at record 1

    def test_writer_refuses_segment_gap(self, tmp_path):
        import os as _os

        from ratelimiter_tpu.core.errors import CheckpointError

        log = w.WriteAheadLog(str(tmp_path), max_bytes=256)
        fill(log, 30)
        log.close()
        segs = w.segment_files(str(tmp_path))
        _os.unlink(segs[1][1])
        with pytest.raises(CheckpointError, match="gap"):
            w.WriteAheadLog(str(tmp_path))
