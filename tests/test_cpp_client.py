"""Native C++ client conformance: build with g++, drive a real server.

The reference's planned client library (pkg/client) exists here twice —
Python (serving/client.py) and native C++ (clients/cpp/). This test is
the native half's conformance gate: compile the demo driver and run its
checks against a live Python server subprocess over real sockets.
"""

import os
import shutil
import signal
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CPP_DIR = os.path.join(REPO, "clients", "cpp")


@pytest.mark.skipif(shutil.which("g++") is None, reason="needs g++")
def test_cpp_loadgen_builds_and_drives(tmp_path):
    """The native load generator compiles and completes a short run
    against a live native server."""
    from ratelimiter_tpu import Algorithm, Config, create_limiter
    from ratelimiter_tpu.serving.native_server import (
        NativeRateLimitServer,
        native_server_available,
    )

    if not native_server_available():
        pytest.skip("native server extension unavailable")
    binary = str(tmp_path / "rltpu_loadgen")
    subprocess.run(
        ["g++", "-O2", "-std=c++17", "-Wall", "-Werror",
         os.path.join(CPP_DIR, "loadgen.cpp"), "-o", binary, "-pthread"],
        check=True, capture_output=True, timeout=120)
    lim = create_limiter(Config(algorithm=Algorithm.SLIDING_WINDOW,
                                limit=10_000, window=60.0), backend="exact")
    srv = NativeRateLimitServer(lim, "127.0.0.1", 0)
    srv.start()
    try:
        out = subprocess.run(
            [binary, "127.0.0.1", str(srv.port), "1", "2", "4", "64", "1000"],
            capture_output=True, text=True, timeout=30)
        assert out.returncode == 0, out.stderr
        import json

        row = json.loads(out.stdout.strip())
        assert row["completed"] > 0
        assert row["decisions_per_sec"] > 0
    finally:
        srv.shutdown()
        lim.close()


@pytest.mark.skipif(shutil.which("g++") is None, reason="needs g++")
def test_cpp_client_conformance(tmp_path):
    binary = str(tmp_path / "rltpu_demo")
    subprocess.run(
        ["g++", "-O2", "-std=c++17", "-Wall", "-Werror",
         os.path.join(CPP_DIR, "demo.cpp"), "-o", binary],
        check=True, capture_output=True, timeout=120)

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [REPO] + env.get("PYTHONPATH", "").split(os.pathsep))
    server = subprocess.Popen(
        [sys.executable, "-m", "ratelimiter_tpu.serving",
         "--backend", "exact", "--algorithm", "fixed_window",
         "--limit", "3", "--window", "60", "--port", str(port)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    try:
        banner = server.stdout.readline()
        assert "serving" in banner, banner
        out = subprocess.run([binary, "127.0.0.1", str(port)],
                             capture_output=True, text=True, timeout=60)
        assert out.returncode == 0, out.stdout + out.stderr
        assert "ALL-OK" in out.stdout
        assert "FAIL" not in out.stdout
        server.send_signal(signal.SIGTERM)
        assert server.wait(timeout=15) == 0
    finally:
        if server.poll() is None:
            server.kill()
            server.wait()
