"""Cross-slice scatter-gather scheduler tests (ISSUE-6 acceptance, ADR-013).

Mixed frames — frames whose keys span several device slices — used to
fork-join across every device queue and collapsed 16x under load
(MULTICHIP_r06). The scheduler fixes that with (1) ragged per-device
sub-framing with ONE completion barrier per frame, (2) cross-slice
launch coalescing (many clients' frames merge into one padded dispatch
per device per batching window, never overshooting the largest
prewarmed pad shape), and (3) completion batching + extended BatchJoin
reassembly in the native door. The load-bearing invariant is unchanged
from ADR-012: coalescing changes the BATCHING, never the DECISIONS —
pinned here bit-for-bit against single-device oracles per key lane,
along with snapshot-during-coalesce quiescence, fail-open OR-folding
over reassembled frames, the debt-slab visibility surface riding the
same mesh lane, and a pinned coalescer-not-slower CPU smoke. CI runs
this file in the explicit 8-virtual-device mesh lane with zero skips
allowed (ci.yml).
"""

from __future__ import annotations

import asyncio
import time

import numpy as np
import pytest

import jax

from ratelimiter_tpu import (
    Algorithm,
    Config,
    ManualClock,
    SketchParams,
    create_limiter,
)
from ratelimiter_tpu.algorithms.sketch import SketchLimiter
from ratelimiter_tpu.observability import MetricsDecorator, Registry
from ratelimiter_tpu.parallel import SlicedMeshLimiter
from ratelimiter_tpu.serving import MicroBatcher

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 4, reason="needs 4 (virtual) devices")

T0 = 1_700_000_000.0


def _cfg(**kw):
    base = dict(
        algorithm=Algorithm.SLIDING_WINDOW,
        limit=10,
        window=60.0,
        sketch=SketchParams(depth=2, width=1 << 10, sub_windows=6),
    )
    base.update(kw)
    return Config(**base)


def _run(coro):
    return asyncio.run(coro)


def _coalesce(lim, frames, *, max_batch=1 << 15):
    """Drive one coalescing window through the MicroBatcher: every frame
    submitted in the same loop tick lands in one window (max_delay gives
    the timer no chance to fire in between) and the batcher answers each
    from its row range of the single window dispatch."""
    async def drive():
        b = MicroBatcher(lim, max_batch=max_batch, max_delay=5e-3,
                         inflight=4, registry=Registry())
        futs = [b.submit_hashed_nowait(ids, ns) for ids, ns in frames]
        out = await asyncio.gather(*futs)
        await b.drain()
        b.close()
        return out

    return _run(drive())


# ------------------------------------------------------- ordering oracle


class TestCoalescedOrderingOracle:
    def test_mixed_frames_bit_identical_to_per_slice_oracle(self):
        """Several clients' MIXED frames coalesced into one window must
        decide exactly like single-device limiters fed each slice's ids
        in arrival order — the acceptance wording verbatim: coalescing
        merges dispatches, the per-key decision stream is untouched
        (allowed, remaining, retry_after, reset_at — all bit-identical).
        """
        cfg = _cfg(limit=5)
        mesh = SlicedMeshLimiter(cfg, ManualClock(T0), n_devices=4)
        rng = np.random.default_rng(7)
        frames = []
        for _ in range(6):
            ids = rng.integers(1, 1 << 40, size=96, dtype=np.uint64)
            ns = np.ones(96, dtype=np.int64)
            frames.append((ids, ns))
        outs = _coalesce(mesh, frames)
        assert all(len(o) == 96 for o in outs)

        # Oracle: the window in arrival order, partitioned by owner.
        window_ids = np.concatenate([f[0] for f in frames])
        owners = mesh.owner_of_id(window_ids)
        allowed = np.concatenate([o.allowed for o in outs])
        remaining = np.concatenate([o.remaining for o in outs])
        retry = np.concatenate([o.retry_after for o in outs])
        reset = np.concatenate([o.reset_at for o in outs])
        for dev in range(4):
            idx = np.flatnonzero(owners == dev)
            if not idx.size:
                continue
            oracle = SketchLimiter(cfg, ManualClock(T0))
            ref = oracle.allow_ids(window_ids[idx])
            np.testing.assert_array_equal(allowed[idx], ref.allowed)
            np.testing.assert_array_equal(remaining[idx], ref.remaining)
            np.testing.assert_array_equal(retry[idx], ref.retry_after)
            np.testing.assert_array_equal(reset[idx], ref.reset_at)
            oracle.close()
        mesh.close()

    def test_interleaved_same_key_across_coalesced_frames(self):
        """A hot id recurring across the window's frames is sequenced in
        ARRIVAL order: exactly `limit` admits, and they are the FIRST
        `limit` occurrences counted across frame boundaries — in-window
        segment ordering decides duplicates exactly as sequential
        per-frame dispatches would (ADR-013)."""
        cfg = _cfg(limit=7)
        mesh = SlicedMeshLimiter(cfg, ManualClock(T0), n_devices=4)
        hot = np.uint64(0xBEEF)
        rng = np.random.default_rng(13)
        frames = []
        for _ in range(5):
            ids = rng.integers(1, 1 << 40, size=32, dtype=np.uint64)
            ids[0::8] = hot  # 4 occurrences per frame, 20 in the window
            frames.append((ids, np.ones(32, dtype=np.int64)))
        outs = _coalesce(mesh, frames)
        hot_decisions = np.concatenate(
            [o.allowed[f[0] == hot] for o, f in zip(outs, frames)])
        assert hot_decisions.sum() == 7
        assert bool(np.all(hot_decisions[:7]))
        assert not bool(np.any(hot_decisions[7:]))
        mesh.close()

    def test_row_view_slices_are_views_with_wire_offsets(self):
        """BatchResult.rows hands back numpy VIEWS over the window result
        (no copies on the scatter-back path) and re-bases the packed wire
        buffers by row offset so the encoder can frame the sub-range from
        the same device-fetched words buffer."""
        mesh = SlicedMeshLimiter(_cfg(), ManualClock(T0), n_devices=4)
        ids = np.arange(1, 257, dtype=np.uint64)
        res = mesh.resolve(mesh.launch_ids(ids, wire=True))
        assert res.wire_packed is not None
        win = res.rows(64, 128)
        assert win.remaining.base is not None  # a view, not a copy
        np.testing.assert_array_equal(win.allowed, res.allowed[64:192])
        bits, words, padded, off = win.wire_packed
        assert off == 64 and words is res.wire_packed[1]
        # And a nested slice accumulates the offset.
        sub = win.rows(8, 16)
        assert sub.wire_packed[3] == 72
        np.testing.assert_array_equal(sub.allowed, res.allowed[72:88])
        mesh.close()

    def test_coalescer_never_dispatches_past_largest_prewarmed_pad(self):
        """A window concatenation must never exceed 2*max_batch — the
        largest pad shape _prewarm compiles (the lone-oversized-frame
        allowance). An oversized frame arriving over a non-empty window
        flushes the window FIRST and then dispatches alone; otherwise
        coalescing would pad past every prewarmed shape and land an XLA
        compile on the hot path — the exact r06 collapse mode ADR-013
        exists to prevent. Arrival-order sequencing must survive the
        early flush (the two dispatches run FIFO on the launch
        executor), pinned against the per-slice oracle."""
        cfg = _cfg(limit=5)
        mesh = SlicedMeshLimiter(cfg, ManualClock(T0), n_devices=4)
        max_batch = 64
        rng = np.random.default_rng(23)
        hot = np.uint64(0xF00D)
        small = rng.integers(1, 1 << 40, size=40, dtype=np.uint64)
        big = rng.integers(1, 1 << 40, size=100, dtype=np.uint64)
        small[:4] = hot
        big[:4] = hot  # duplicates straddle the flush boundary

        async def drive():
            b = MicroBatcher(mesh, max_batch=max_batch, max_delay=5e-3,
                             inflight=4, registry=Registry())
            sizes = []
            orig = b._dispatch_hashed

            async def spy(ids, ns, fut, trace_id=0):
                sizes.append(int(ids.shape[0]))
                await orig(ids, ns, fut, trace_id)

            b._dispatch_hashed = spy
            futs = [b.submit_hashed_nowait(
                        ids, np.ones(ids.shape[0], dtype=np.int64))
                    for ids in (small, big)]
            outs = await asyncio.gather(*futs)
            await b.drain()
            b.close()
            return outs, sizes

        outs, sizes = _run(drive())
        assert sizes == [40, 100]  # flushed apart, neither concatenated
        assert max(sizes) <= 2 * max_batch
        # Decisions still sequence in arrival order across the flush.
        window_ids = np.concatenate([small, big])
        owners = mesh.owner_of_id(window_ids)
        allowed = np.concatenate([o.allowed for o in outs])
        for dev in range(4):
            idx = np.flatnonzero(owners == dev)
            if not idx.size:
                continue
            oracle = SketchLimiter(cfg, ManualClock(T0))
            ref = oracle.allow_ids(window_ids[idx])
            np.testing.assert_array_equal(allowed[idx], ref.allowed)
            oracle.close()
        hot_decisions = allowed[window_ids == hot]
        assert hot_decisions.sum() == 5 and bool(np.all(hot_decisions[:5]))
        mesh.close()

    def test_lone_oversized_frame_carved_into_prewarmed_segments(self):
        """A SINGLE hashed frame larger than 2*max_batch (the wire
        protocol admits up to ~87K ids regardless of --max-batch) must
        not dispatch whole — it would pad past every prewarmed shape
        and pay the XLA compile on the hot path. The asyncio door
        mirrors the native dispatcher's carve: max_batch segments
        dispatched in order through the FIFO executors, reassembled
        host-side. Decisions stay bit-identical to the per-slice oracle
        fed the frame in order (same-key sequencing crosses segment
        boundaries), and the merged result still encodes as one
        RESULT_HASHED frame via the packbits path."""
        cfg = _cfg(limit=5)
        mesh = SlicedMeshLimiter(cfg, ManualClock(T0), n_devices=4)
        max_batch = 64
        rng = np.random.default_rng(29)
        hot = np.uint64(0xCAFE)
        big = rng.integers(1, 1 << 40, size=300, dtype=np.uint64)
        big[0::30] = hot  # 10 occurrences, straddling segment cuts

        async def drive():
            b = MicroBatcher(mesh, max_batch=max_batch, max_delay=5e-3,
                             inflight=4, registry=Registry())
            sizes = []
            orig = b._dispatch_hashed

            async def spy(ids, ns, fut, trace_id=0):
                sizes.append(int(ids.shape[0]))
                await orig(ids, ns, fut, trace_id)

            b._dispatch_hashed = spy
            fut = b.submit_hashed_nowait(
                big, np.ones(big.shape[0], dtype=np.int64))
            out = await fut
            await b.drain()
            b.close()
            return out, sizes

        out, sizes = _run(drive())
        assert sizes == [64, 64, 64, 64, 44]  # carved at max_batch
        assert len(out) == 300 and not out.fail_open
        owners = mesh.owner_of_id(big)
        for dev in range(4):
            idx = np.flatnonzero(owners == dev)
            if not idx.size:
                continue
            oracle = SketchLimiter(cfg, ManualClock(T0))
            ref = oracle.allow_ids(big[idx])
            np.testing.assert_array_equal(out.allowed[idx], ref.allowed)
            np.testing.assert_array_equal(out.remaining[idx], ref.remaining)
            oracle.close()
        hot_decisions = out.allowed[big == hot]
        assert hot_decisions.sum() == 5 and bool(np.all(hot_decisions[:5]))
        # The reassembled result has no device-packed buffers; the wire
        # encoder's packbits fallback must still frame it losslessly.
        from ratelimiter_tpu.serving import protocol

        assert out.wire_packed is None
        frame = protocol.encode_result_hashed(9, out)
        rt = protocol.parse_result_hashed(frame[protocol.HEADER_SIZE:])
        np.testing.assert_array_equal(rt.allowed, out.allowed)
        np.testing.assert_array_equal(rt.remaining, out.remaining)
        mesh.close()


# ------------------------------------------- snapshot-during-coalesce


class TestSnapshotDuringCoalesce:
    def test_capture_quiesces_inflight_coalesced_windows(self, tmp_path):
        """capture_state while coalesced windows are in flight must
        reflect EVERY launched window (quiescence by data dependence on
        the donated state chain, PR 2/3 contract): restoring the
        snapshot reproduces the post-launch counters exactly."""
        cfg = _cfg(limit=10)
        mesh = SlicedMeshLimiter(cfg, ManualClock(T0), n_devices=4)
        hot = np.full(4, 0xF00D, dtype=np.uint64)
        # Two coalesced windows (multi-frame concatenations) in flight.
        t1 = mesh.launch_ids(np.concatenate([hot, hot]))
        t2 = mesh.launch_ids(hot)
        path = str(tmp_path / "mid.npz")
        mesh.save(path)  # capture with both windows un-resolved
        assert mesh.resolve(t1).allowed.tolist() == [True] * 8
        assert mesh.resolve(t2).allowed.tolist() == [True, True, False,
                                                     False]
        restored = SlicedMeshLimiter(cfg, ManualClock(T0), n_devices=4)
        restored.restore(path)
        # 12 units offered in the snapshot, limit 10: nothing left.
        out = restored.allow_ids(hot)
        assert out.allowed.tolist() == [False] * 4
        mesh.close()
        restored.close()


# ----------------------------------------------------- fail-open folding


class TestFailOpenFolding:
    def test_window_or_folds_over_reassembled_frames(self):
        """A coalesced window containing a failed-open sub-frame answers
        EVERY frame of the window with fail_open=True — the conservative
        window-OR (a frame coalesced with a failed-open neighbor cannot
        prove its own answers weren't fabricated), the same OR-folding
        contract as the native door's multi-shard hashed joins."""
        cfg = _cfg(fail_open=True)
        mesh = SlicedMeshLimiter(cfg, ManualClock(T0), n_devices=4)
        all_ids = np.arange(1, 4096, dtype=np.uint64)
        owners = mesh.owner_of_id(all_ids)
        broken, healthy = 1, 2
        mesh.slices[broken].inject_failure()
        frames = [
            # Frame A never touches the broken slice...
            (all_ids[owners == healthy][:48],
             np.ones(48, dtype=np.int64)),
            # ...frame B does.
            (all_ids[owners == broken][:48],
             np.ones(48, dtype=np.int64)),
        ]
        outs = _coalesce(mesh, frames)
        assert outs[1].fail_open
        assert bool(np.all(outs[1].allowed))  # fabricated allows
        assert outs[0].fail_open, \
            "window OR must reach every reassembled frame"
        mesh.heal()
        mesh.close()

    def test_healthy_window_does_not_or_spuriously(self):
        mesh = SlicedMeshLimiter(_cfg(fail_open=True), ManualClock(T0),
                                 n_devices=4)
        frames = [(np.arange(1 + 64 * i, 65 + 64 * i, dtype=np.uint64),
                   np.ones(64, dtype=np.int64)) for i in range(3)]
        outs = _coalesce(mesh, frames)
        assert not any(o.fail_open for o in outs)
        mesh.close()


# --------------------------------------------- native door segmentation


class TestNativeDoorSegmentation:
    def test_oversized_hashed_frame_segments_and_reassembles(self):
        """The C++ dispatcher must cut a coalesced run BEFORE crossing
        max_batch (the r06 collapse was overshooting runs padding to an
        un-prewarmed shape) — a hashed frame far larger than max_batch is
        carved into max_batch-sized segments, dispatched separately, and
        reassembled through the extended BatchJoin into ONE reply frame
        whose decisions are bit-identical to the single-device oracle."""
        from ratelimiter_tpu.serving.client import Client
        from ratelimiter_tpu.serving.native_server import (
            NativeRateLimitServer,
            native_server_available,
        )
        if not native_server_available():
            pytest.skip("no compiler for the native front door")

        cfg = _cfg(limit=5)
        lim = SketchLimiter(cfg, ManualClock(T0))
        srv = NativeRateLimitServer(lim, max_batch=64, max_delay=1e-4)
        srv.start()
        try:
            rng = np.random.default_rng(23)
            ids = rng.integers(1, 1 << 40, size=300, dtype=np.uint64)
            ids[0::10] = np.uint64(0xCAFE)  # hot id spanning segments
            with Client(port=srv.port, timeout=60.0) as c:
                br = c.allow_hashed(ids)
            assert len(br) == 300  # one reply frame, original order
            # The oracle mirrors the carve: sequential max_batch-sized
            # dispatches (segmentation IS sequential dispatch of the
            # segments — CU collision writes are per-dispatch, so a
            # single 300-id oracle batch would be a different, coarser
            # granularity, not what the scheduler promises).
            oracle = SketchLimiter(cfg, ManualClock(T0))
            refs = [oracle.allow_ids(ids[s:s + 64])
                    for s in range(0, 300, 64)]
            ref_allowed = np.concatenate([r.allowed for r in refs])
            ref_remaining = np.concatenate([r.remaining for r in refs])
            np.testing.assert_array_equal(br.allowed, ref_allowed)
            np.testing.assert_array_equal(br.remaining, ref_remaining)
            # Same-key sequencing across the segment boundaries: the
            # first 5 hot occurrences (and only those) were admitted.
            hot = br.allowed[0::10]
            assert hot.sum() == 5 and bool(np.all(hot[:5]))
            oracle.close()
        finally:
            srv.shutdown()

    def test_oversized_string_frame_segments_and_reassembles(self):
        """The STRING lane gets the same carve (the wire protocol admits
        T_ALLOW_BATCH frames up to ~174K short keys regardless of
        --max-batch, and prewarm only covers one pad shape past it): a
        lone oversized string frame opening a run is carved into
        max_batch segments riding the shard-split BatchJoin deposit
        path, answered as ONE T_RESULT_BATCH frame bit-identical to the
        oracle dispatched segment-sequentially."""
        from ratelimiter_tpu.serving.client import Client
        from ratelimiter_tpu.serving.native_server import (
            NativeRateLimitServer,
            native_server_available,
        )
        if not native_server_available():
            pytest.skip("no compiler for the native front door")

        cfg = _cfg(limit=5)
        lim = SketchLimiter(cfg, ManualClock(T0))
        srv = NativeRateLimitServer(lim, max_batch=64, max_delay=1e-4)
        srv.start()
        try:
            keys = [f"key-{i}" for i in range(300)]
            for i in range(0, 300, 10):
                keys[i] = "hot-key"  # 30 occurrences spanning segments
            with Client(port=srv.port, timeout=60.0) as c:
                out = c.allow_batch(keys)
            assert len(out) == 300  # one reply frame, original order
            oracle = SketchLimiter(cfg, ManualClock(T0))
            refs = []
            for s in range(0, 300, 64):
                refs.extend(oracle.allow_batch(keys[s:s + 64]).results())
            assert [r.allowed for r in out] == [r.allowed for r in refs]
            assert ([r.remaining for r in out]
                    == [r.remaining for r in refs])
            hot = [out[i].allowed for i in range(0, 300, 10)]
            assert sum(hot) == 5 and all(hot[:5])
            oracle.close()
        finally:
            srv.shutdown()

    def test_many_small_frames_coalesce_through_native_door(self):
        """Many clients' small hashed frames ride one server: decisions
        per frame equal the oracle fed the same ids in submission order
        (the in-C++ coalescer merges them; reassembly must keep each
        frame's rows intact)."""
        from ratelimiter_tpu.serving.client import Client
        from ratelimiter_tpu.serving.native_server import (
            NativeRateLimitServer,
            native_server_available,
        )
        if not native_server_available():
            pytest.skip("no compiler for the native front door")

        cfg = _cfg(limit=1 << 20)
        lim = SketchLimiter(cfg, ManualClock(T0))
        srv = NativeRateLimitServer(lim, max_batch=256, max_delay=2e-3)
        srv.start()
        try:
            rng = np.random.default_rng(31)
            frames = [rng.integers(1, 1 << 40, size=32, dtype=np.uint64)
                      for _ in range(16)]
            with Client(port=srv.port, timeout=60.0) as c:
                outs = [c.allow_hashed(f) for f in frames]
            for f, o in zip(frames, outs):
                assert len(o) == len(f)
                assert bool(np.all(o.allowed))
        finally:
            srv.shutdown()


# ------------------------------------------------- debt-slab visibility


class TestDebtSlabGauge:
    def test_gauges_scrape_per_slice_and_healthz_aggregates(self):
        """The debt-slab occupancy/collision surface (ROADMAP item 5:
        strict gating doesn't transfer to the continuously-decaying debt
        slab, visibility does) rides the mesh lane: a token-bucket mesh
        exports one gauge series per device slice via the scrape-time
        collect hook — never on the decide path — and /healthz
        aggregates worst-unit occupancy across slices."""
        from ratelimiter_tpu.serving.__main__ import _debt_slab_health

        clock = ManualClock(T0)
        cfg = Config(algorithm=Algorithm.TOKEN_BUCKET, limit=50,
                     window=10.0,
                     sketch=SketchParams(depth=3, width=256))
        mesh = create_limiter(cfg, backend="mesh", clock=clock, n_devices=4)
        reg = Registry()
        dec = MetricsDecorator(mesh, registry=reg)
        rng = np.random.default_rng(41)
        dec.allow_ids(rng.integers(1, 1 << 40, size=512, dtype=np.uint64),
                      np.full(512, 30, dtype=np.int64))

        text = reg.render()  # the scrape runs the collect hook
        occ = reg.get("rate_limiter_debt_slab_occupancy")
        assert occ is not None
        per_slice = [occ.value(shard="0", slice=str(i)) for i in range(4)]
        assert any(v > 0 for v in per_slice), per_slice
        assert "rate_limiter_debt_slab_collision_probability" in text

        h = _debt_slab_health([dec])
        assert h["debt_slab"]["units"] == 4
        assert h["debt_slab"]["occupancy"] == pytest.approx(
            max(per_slice), abs=1e-9)
        assert 0.0 <= h["debt_slab"]["collision_p"] <= 1.0
        # Idle long enough and the decayed slab reads empty again — the
        # gauge tracks EFFECTIVE debt, not stale stored cells.
        clock.advance(3600.0)
        assert _debt_slab_health([dec])["debt_slab"]["occupancy"] == 0.0
        mesh.close()

    def test_windowed_sketch_has_no_debt_slab(self):
        from ratelimiter_tpu.serving.__main__ import _debt_slab_health

        lim = SketchLimiter(_cfg(), ManualClock(T0))
        assert _debt_slab_health([lim]) == {}
        lim.close()


# -------------------------------------------------------- pinned smoke


class TestCoalescerSmoke:
    def test_coalesced_window_not_slower_than_fork_join_on_cpu(self):
        """Pinned throughput smoke: dispatching K mixed frames as ONE
        coalesced window (single partition + per-device sub-dispatch +
        one barrier + rows() scatter-back) must not be slower than K
        fork-join dispatches on the CPU harness. The margin absorbs
        shared-box scheduler noise — the claim guarded is 'coalescing is
        at worst free', the measured win on this image is ~Kx fewer
        per-device dispatches."""
        cfg = _cfg(limit=1 << 20)
        mesh = SlicedMeshLimiter(cfg, ManualClock(T0), n_devices=4)
        rng = np.random.default_rng(3)
        frames = [rng.integers(1, 1 << 40, size=256, dtype=np.uint64)
                  for _ in range(8)]
        window = np.concatenate(frames)
        mesh.allow_ids(window)  # compile both pad shapes
        mesh.allow_ids(frames[0])
        reps = 6

        t0 = time.perf_counter()
        for _ in range(reps):
            for f in frames:
                mesh.resolve(mesh.launch_ids(f))
        fork_join_s = time.perf_counter() - t0

        t0 = time.perf_counter()
        for _ in range(reps):
            res = mesh.resolve(mesh.launch_ids(window))
            off = 0
            for f in frames:
                res.rows(off, len(f))
                off += len(f)
        coalesced_s = time.perf_counter() - t0

        assert coalesced_s <= fork_join_s * 1.5, (
            f"coalescer regressed: window {coalesced_s:.4f}s vs "
            f"fork-join {fork_join_s:.4f}s over {reps} windows")
        mesh.close()
