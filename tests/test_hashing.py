"""Native bulk hasher: C++ / NumPy twin / scalar reference agreement.

The string-hash algorithm is defined by hasher.cpp and must be produced
bit-identically by three implementations (C extension, vectorized NumPy
fallback, and the scalar Python reference below). Any drift between them
would silently re-key every sketch, so the cross-check is exhaustive over
length classes (0..40 bytes: empty-lane, sub-lane, exact-lane, multi-lane)
and non-ASCII packing.
"""

from __future__ import annotations

import numpy as np
import pytest

from ratelimiter_tpu import native
from ratelimiter_tpu.native.fallback import hash_packed_numpy
from ratelimiter_tpu.ops.hashing import hash_strings_u64, split_hash

M64 = (1 << 64) - 1
_P1 = 0x9E3779B185EBCA87
_P2 = 0xC2B2AE3D27D4EB4F
_P3 = 0x165667B19E3779F9


def _rotl(x: int, r: int) -> int:
    return ((x << r) | (x >> (64 - r))) & M64


def _fmix(x: int) -> int:
    x ^= x >> 30
    x = (x * 0xBF58476D1CE4E5B9) & M64
    x ^= x >> 27
    x = (x * 0x94D049BB133111EB) & M64
    x ^= x >> 31
    return x


def scalar_reference(key: str, seed: int = native.DEFAULT_SEED) -> int:
    """Straight-line transcription of hasher.cpp's per-key loop."""
    data = key.encode("utf-8")
    h = (seed ^ ((len(data) * _P1) & M64)) & M64
    for i in range(0, len(data) - len(data) % 8, 8):
        lane = int.from_bytes(data[i:i + 8], "little")
        h = (_rotl(h ^ ((lane * _P1) & M64), 27) * _P2 + _P3) & M64
    rem = len(data) % 8
    if rem:
        lane = int.from_bytes(data[len(data) - rem:] + b"\0" * (8 - rem),
                              "little")
        h = (_rotl(h ^ ((lane * _P1) & M64), 27) * _P2 + _P3) & M64
    return _fmix(h)


KEYS = (
    ["a", "ab", "abcdefg", "abcdefgh", "abcdefghi", "user:1", "tenant:42:api",
     "x" * 15, "x" * 16, "x" * 17, "x" * 39, "x" * 40,
     "ключ", "键值", "🔑" * 3, "mixedascii-ключ-tail"]
    + [f"user:{i}" for i in range(50)]
)


def test_numpy_twin_matches_scalar_reference():
    got = hash_packed_numpy(*native.pack_keys(KEYS), seed=native.DEFAULT_SEED)
    want = np.array([scalar_reference(k) for k in KEYS], dtype=np.uint64)
    np.testing.assert_array_equal(got, want)


def test_native_matches_scalar_reference():
    if not native.native_available():
        pytest.skip("C extension not built and no compiler available")
    got = native.hash_packed(*native.pack_keys(KEYS))
    want = np.array([scalar_reference(k) for k in KEYS], dtype=np.uint64)
    np.testing.assert_array_equal(got, want)


def test_native_and_numpy_agree_on_fuzz():
    rng = np.random.default_rng(11)
    keys = ["".join(chr(rng.integers(33, 127)) for _ in range(rng.integers(1, 33)))
            for _ in range(2000)]
    packed = native.pack_keys(keys)
    via_numpy = hash_packed_numpy(*packed, seed=native.DEFAULT_SEED)
    if native.native_available():
        via_c = native.hash_packed(*packed)
        np.testing.assert_array_equal(via_c, via_numpy)
    # determinism across a re-pack
    np.testing.assert_array_equal(
        native.bulk_hash_u64(keys), via_numpy)


def test_pack_keys_non_ascii_fallback_is_exact():
    keys = ["plain", "ключ", "ab", "🔑x", ""]
    buf, offsets, lengths = native.pack_keys(keys)
    for i, k in enumerate(keys):
        enc = k.encode("utf-8")
        assert lengths[i] == len(enc)
        got = bytes(buf[offsets[i]:offsets[i] + lengths[i]])
        assert got == enc


def test_no_collisions_at_100k_distinct_keys():
    keys = [f"user:{i}:resource:{i % 97}" for i in range(100_000)]
    h = hash_strings_u64(keys)
    assert len(np.unique(h)) == len(keys)


def test_split_hash_halves_are_odd_stride_and_seeded():
    h = hash_strings_u64([f"k{i}" for i in range(64)])
    h1a, h2a = split_hash(h, seed=1)
    h1b, h2b = split_hash(h, seed=2)
    assert np.all(h2a % 2 == 1)
    assert not np.array_equal(h1a, h1b)  # per-limiter remix


def test_empty_batch():
    assert native.bulk_hash_u64([]).shape == (0,)
