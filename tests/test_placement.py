"""Load-aware placement (ADR-023): load accounting, the deterministic
planner, sub-range map moves, the rebalance controller, and the event
journal's file spill.

Pinned invariants:

* the planner is a PURE function — same (map, load, liveness, frozen,
  knobs, seed) → byte-identical plan, so every member plans alone and
  only donors execute (no leader election);
* ``move_ranges`` sub-range splits keep the exact-cover invariant and
  leave whole-unit moves byte-identical to the pre-split semantics;
* a multi-move rebalance NEVER over-admits vs the single-host oracle on
  the moved ranges, including under chaos kill-during-handoff at every
  injected phase (the handoff's abort-anywhere contract, inherited);
* the load slab is observation-only: decisions with the slab attached
  are identical to decisions without it (the rebalance-off pin);
* an alive-but-unreachable peer's missing load block SKIPS the cycle
  (plans are never made on a guess);
* the journal's file spill replays across restart, survives torn tail
  writes, and stays bounded.
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

from ratelimiter_tpu.chaos import injector as chaos_injector
from ratelimiter_tpu.core.clock import ManualClock
from ratelimiter_tpu.core.errors import InvalidConfigError
from ratelimiter_tpu.fleet.config import affine_map
from ratelimiter_tpu.observability import events
from ratelimiter_tpu.observability.events import EventJournal
from ratelimiter_tpu.observability.metrics import Registry
from ratelimiter_tpu.placement import (
    LoadSlab,
    PlannerKnobs,
    RebalanceController,
    merge_placement,
    plan_moves,
)

jax = pytest.importorskip("jax")

from tests.test_elastic import _Host, _make_fleet, _owned_key  # noqa: E402,F401


def _map3(buckets=48):
    return affine_map([("127.0.0.1", 7001), ("127.0.0.1", 7002),
                       ("127.0.0.1", 7003)], buckets=buckets)


def _hot(buckets, hot_lo, hot_hi, hot=100.0, base=1.0):
    rate = np.full(buckets, base, dtype=np.float64)
    rate[hot_lo:hot_hi] = hot
    return rate


# ---------------------------------------------------------------------------
# FleetMap.move_ranges sub-range splits


class TestMoveRangesSplit:
    def test_whole_unit_move_semantics_unchanged(self):
        m = _map3()
        m2 = m.move_ranges(m.host("h0").ranges, "h0", "h1")
        assert m2.epoch == m.epoch + 1
        assert m2.host("h0").ranges == ()
        # Union WITHOUT coalescing — the pre-split pin (test_elastic
        # depends on tuple identity of the receiver's ranges).
        assert m2.host("h1").ranges == tuple(
            sorted(set(m.host("h1").ranges) | set(m.host("h0").ranges)))
        m2.validate()

    def test_sub_range_split_keeps_left_and_right_pieces(self):
        m = _map3()  # h0 owns [0, 16)
        m2 = m.move_ranges([(4, 9)], "h0", "h2")
        assert m2.host("h0").ranges == ((0, 4), (9, 16))
        assert (4, 9) in m2.host("h2").ranges
        assert m2.epoch == m.epoch + 1
        m2.validate()
        assert (m2.owner_table[4:9] == m2.ordinal("h2")).all()
        assert (m2.owner_table[0:4] == m2.ordinal("h0")).all()
        assert (m2.owner_table[9:16] == m2.ordinal("h0")).all()

    def test_split_at_range_edges_drops_empty_pieces(self):
        m = _map3()
        left = m.move_ranges([(0, 5)], "h0", "h1")
        assert left.host("h0").ranges == ((5, 16),)
        right = m.move_ranges([(10, 16)], "h0", "h1")
        assert right.host("h0").ranges == ((0, 10),)
        left.validate()
        right.validate()

    def test_chained_splits_compose(self):
        m = _map3()
        m2 = m.move_ranges([(2, 4)], "h0", "h1")
        m3 = m2.move_ranges([(10, 12)], "h0", "h2")
        assert m3.host("h0").ranges == ((0, 2), (4, 10), (12, 16))
        m3.validate()

    def test_straddling_and_unowned_moves_rejected(self):
        m = _map3()  # h0: [0,16) h1: [16,32)
        with pytest.raises(InvalidConfigError, match="straddling"):
            m.move_ranges([(12, 20)], "h0", "h2")
        with pytest.raises(InvalidConfigError):
            m.move_ranges([(20, 24)], "h0", "h2")  # h1's range
        with pytest.raises(InvalidConfigError):
            m.move_ranges([(0, 64)], "h0", "h1")  # outside the map


# ---------------------------------------------------------------------------
# Load accounting


class TestLoadSlab:
    def test_note_accumulates_and_drains_rates(self):
        mono = [0.0]
        slab = LoadSlab(8, ewma_halflife_s=1.0, min_drain_s=0.1,
                        clock=lambda: mono[0])
        slab.note(np.array([0, 0, 1, 5], dtype=np.int64),
                  np.array([True, True, False, True]))
        slab.note_one(0, True)
        slab.note_one(1, False)
        mono[0] = 1.0
        snap = slab.snapshot()
        assert snap["decide_total"] == 4
        assert snap["forward_total"] == 2
        # Bucket 0: three decides over 1s at halflife 1 → EWMA picks up
        # alpha * 3/s = 1.5; bucket 1: two forwards → 1.0.
        assert snap["decide_rate"][0] == pytest.approx(1.5, abs=0.01)
        assert snap["forward_rate"][1] == pytest.approx(1.0, abs=0.01)
        assert slab.rates()[5] > 0.0

    def test_all_local_and_all_foreign_fast_paths(self):
        slab = LoadSlab(4)
        slab.note(np.array([0, 1], dtype=np.int64),
                  np.array([True, True]))
        slab.note(np.array([2, 3], dtype=np.int64),
                  np.array([False, False]))
        snap = slab.snapshot()
        assert snap["decide_total"] == 2
        assert snap["forward_total"] == 2

    def test_metrics_families_export(self):
        reg = Registry()
        slab = LoadSlab(4, registry=reg)
        slab.note_one(0, True)
        slab.note_one(1, False)
        text = reg.render()
        assert "rate_limiter_placement_decide_mass_total 1" in text
        assert "rate_limiter_placement_forward_mass_total 1" in text

    def test_merge_counts_each_decision_once_and_reports_gaps(self):
        mono = [0.0]
        slabs = {h: LoadSlab(4, ewma_halflife_s=1.0, min_drain_s=0.1,
                             clock=lambda: mono[0])
                 for h in ("a", "b")}
        slabs["a"].note_one(0, True)
        slabs["a"].note_one(1, False)   # a forwarded it...
        slabs["b"].note_one(1, True)    # ...b decided it.
        mono[0] = 1.0
        merged = merge_placement({h: s.snapshot()
                                  for h, s in slabs.items()})
        assert merged["gaps"] == []
        assert merged["hosts"]["a"]["decide_total"] == 1
        assert merged["hosts"]["b"]["decide_total"] == 1
        # The forwarded row counts decide-mass ONCE (at b).
        total = sum(h["decide_total"] for h in merged["hosts"].values())
        assert total == 2
        gappy = merge_placement({"a": slabs["a"].snapshot(), "c": None})
        assert gappy["gaps"] == ["c"]


# ---------------------------------------------------------------------------
# Planner


class TestPlanner:
    def test_same_inputs_byte_identical_plan(self):
        m = _map3()
        rate = _hot(48, 0, 8)
        alive = {"h0", "h1", "h2"}
        dumps = [json.dumps(plan_moves(m, rate, alive=alive,
                                       frozen={40}, seed=7).to_dict(),
                            sort_keys=True)
                 for _ in range(3)]
        assert dumps[0] == dumps[1] == dumps[2]
        # Any input change changes the plan id.
        other = plan_moves(m, rate, alive=alive, frozen={40}, seed=8)
        assert other.plan_id != plan_moves(
            m, rate, alive=alive, frozen={40}, seed=7).plan_id

    def test_hotspot_plan_reduces_imbalance_below_target(self):
        m = _map3()
        rate = _hot(48, 0, 8)
        p = plan_moves(m, rate, alive={"h0", "h1", "h2"})
        assert p.imbalance_before >= 2.0
        assert p.moves and p.reason == "planned"
        assert p.imbalance_projected <= p.knobs["target_ratio"]
        assert all(mv["from"] == "h0" for mv in p.moves)
        assert len(p.moves) <= p.knobs["max_moves"]
        # corr is the plan id (one correlation id per plan).
        assert f"{p.corr:016x}" == p.plan_id

    def test_within_band_and_single_host_do_not_plan(self):
        m = _map3()
        flat = np.ones(48)
        p = plan_moves(m, flat, alive={"h0", "h1", "h2"})
        assert p.reason == "within-band" and not p.moves
        solo = plan_moves(m, _hot(48, 0, 8), alive={"h0"})
        assert solo.reason == "single-host" and not solo.moves

    def test_dead_hosts_never_donate_or_receive(self):
        m = _map3()
        rate = _hot(48, 0, 8)
        p = plan_moves(m, rate, alive={"h0", "h1"})  # h2 dead
        assert p.moves
        assert all(mv["to"] != "h2" and mv["from"] != "h2"
                   for mv in p.moves)

    def test_fully_frozen_donor_cannot_plan(self):
        m = _map3()
        rate = _hot(48, 0, 8)
        p = plan_moves(m, rate, alive={"h0", "h1", "h2"},
                       frozen=set(range(0, 16)))
        assert not p.moves
        assert p.reason == "cooldown"

    def test_single_hot_bucket_over_cap_is_still_movable(self):
        """A lone unfrozen bucket hotter than want*overshoot is still a
        candidate window — there is no smaller move, and starving it
        would pin the hotspot to its donor forever."""
        m = _map3()
        rate = np.full(48, 10.0)
        rate[3] = 200.0
        rate[16:] = 1.0
        frozen = set(range(0, 16)) - {3}
        p = plan_moves(m, rate, alive={"h0", "h1", "h2"},
                       frozen=frozen)
        assert p.moves
        assert p.moves[0]["from"] == "h0"
        assert p.moves[0]["range"] == [3, 4]

    def test_plan_applies_on_real_map_transitions(self):
        """Each planned move is a legal move_ranges transition from the
        previous one — the executor replays them verbatim."""
        m = _map3()
        p = plan_moves(m, _hot(48, 0, 8), alive={"h0", "h1", "h2"})
        work = m
        for mv in p.moves:
            lo, hi = mv["range"]
            work = work.move_ranges([(lo, hi)], mv["from"], mv["to"])
        work.validate()
        assert work.epoch == m.epoch + len(p.moves)


# ---------------------------------------------------------------------------
# Rebalance controller over the in-process fleet harness


def _attach_placement(hosts, mono, buckets=48, **ctl_kw):
    """Wire a LoadSlab + RebalanceController per in-process host; peers'
    load rides a direct healthz-shaped fetch (the tower seam)."""
    knobs = ctl_kw.pop("knobs", None) or PlannerKnobs(
        min_residency_s=600.0)
    for h in hosts.values():
        h.core.load_slab = LoadSlab(buckets, ewma_halflife_s=1.0,
                                    min_drain_s=0.05,
                                    clock=lambda: mono[0])

    def make_fetch(self_name):
        def fetch():
            return {n: {"placement": p.core.load_slab.snapshot()}
                    for n, p in hosts.items() if n != self_name}
        return fetch

    return {name: RebalanceController(
                h.core, h.membership, h.core.load_slab,
                interval=999.0, knobs=knobs, move_wait=5.0,
                fetch_peer_health=make_fetch(name),
                clock=lambda: mono[0], **ctl_kw)
            for name, h in hosts.items()}


def _seed_load(hosts, mono, owner, hot_buckets, n=400):
    """Deterministic synthetic hotspot: ``n`` decisions on each hot
    bucket at its owner, then one manual-time step so the EWMA drains
    into non-zero rates. The manual clock stays FIXED afterwards, so
    every later gather sees the identical load vector (determinism)."""
    slab = hosts[owner].core.load_slab
    for b in hot_buckets:
        slab.note(np.full(n, b, dtype=np.int64),
                  np.ones(n, dtype=bool))
    mono[0] += 2.0
    for h in hosts.values():
        h.core.load_slab.snapshot()  # drain at the new time


class TestRebalanceController:
    def test_cycle_moves_hotspot_and_journals_one_corr(self, tmp_path):
        clock = ManualClock(1000.0)
        m, hosts = _make_fleet(tmp_path, ["a", "b", "c"], clock)
        mono = [100.0]
        ctls = _attach_placement(hosts, mono)
        events.enable(capacity=256)
        try:
            _seed_load(hosts, mono, "a", [4, 5, 6])

            # Every member plans identically from the same view...
            plans = {n: c.dry_run()["plan"]["plan_id"]
                     for n, c in ctls.items()}
            assert len(set(plans.values())) == 1
            # ...but only the donor executes.
            out_b = ctls["b"].run_cycle()
            assert out_b["ok"] and out_b["executed"] == 0
            out = ctls["a"].run_cycle()
            assert out["ok"] and out["executed"] >= 1
            new_map = hosts["a"].core.map
            assert new_map.epoch > m.epoch
            moved = [tuple(mv["range"])
                     for mv in out["plan"]["moves"][:out["executed"]]]
            hot_owner = {h.id for h in new_map.hosts
                         if any(lo <= 4 < hi for lo, hi in h.ranges)}
            assert hot_owner != {"a"}  # the hotspot moved off the donor
            assert any(lo <= 4 < hi for lo, hi in moved)
            # Moved buckets are frozen (min-residency): an immediate
            # replan refuses to touch them.
            assert ctls["a"].frozen_now()
            st = ctls["a"].status()
            assert st["moves_ok"] == out["executed"]
            assert st["moves_failed"] == 0

            # Journal: the plan + every move share ONE correlation id.
            evs = events.get().tail(category="placement")["events"]
            by_action = {}
            for e in evs:
                by_action.setdefault(e["action"], []).append(e)
            assert by_action["plan"], evs
            corr = by_action["plan"][-1]["corr"]
            assert corr
            assert all(e["corr"] == corr for e in by_action["move"])
            assert corr == by_action["plan"][-1]["payload"]["plan_id"]
        finally:
            events.disable()
            for h in list(hosts.values()):
                h.close()

    def test_never_over_admission_under_chaos_at_every_phase(
            self, tmp_path):
        """The acceptance invariant: a multi-move rebalance with
        kill-during-handoff chaos at every phase never admits more than
        the single-host oracle for keys on the moved ranges. An aborted
        handoff leaves ownership and epoch unchanged (journaled as
        move-failed, pace backed off) and the next cycle replans from
        the real map; the completed move CONTINUES the counters on the
        receiver."""
        clock = ManualClock(1000.0)
        m, hosts = _make_fleet(tmp_path, ["a", "b", "c"], clock)
        mono = [100.0]
        ctls = _attach_placement(hosts, mono)
        inj = chaos_injector.install(seed=5)
        events.enable(capacity=256)
        try:
            a = hosts["a"]
            _seed_load(hosts, mono, "a", [4, 5, 6])
            # Determinism IS the coordination: the dry-run preview is
            # exactly the plan every later cycle will execute (the
            # manual clock is pinned, so the load view cannot drift).
            plan = ctls["a"].dry_run()["plan"]
            assert plan["moves"]
            lo, hi = plan["moves"][0]["range"]
            to_id = plan["moves"][0]["to"]
            # A real key on the first moved range, spent BEFORE the
            # rebalance starts.
            bmap = a.core.map
            key = next(
                f"o:{i}" for i in range(2000)
                if lo <= int(bmap.bucket_of_hash(
                    a.core.hash_keys([f"o:{i}"]))[0]) < hi)
            limit = a.cfg.limit  # 20
            spent = 15
            for _ in range(spent):
                assert a.fwd.allow_n(key, 1).allowed

            for phase in ("capture", "restore", "flip"):
                inj.abort_handoff(phase=phase, count=1)
                out = ctls["a"].run_cycle()
                # The move failed; ownership and epoch are unchanged.
                assert a.core.map.epoch == m.epoch
                assert a.core.map.host("a").ranges == m.host("a").ranges
                assert out["executed"] == 0
                # AIMD: every failure backs the pace off.
                assert ctls["a"].pace > 1.0
            assert ctls["a"].moves_failed == 3
            evs = events.get().tail(category="placement")["events"]
            assert sum(1 for e in evs
                       if e["action"] == "move-failed") == 3

            # Chaos cleared: the same plan now completes.
            inj.clear()
            out = ctls["a"].run_cycle()
            assert out["executed"] >= 1
            assert out["plan"]["plan_id"] == plan["plan_id"]
            new_map = a.core.map
            assert new_map.epoch > m.epoch
            assert new_map.ordinal(to_id) == int(new_map.owner_table[
                int(bmap.bucket_of_hash(a.core.hash_keys([key]))[0])])
            # Oracle: the receiver CONTINUES the window — exactly
            # limit - spent further admissions, then denials. Total
            # admissions across the move == the single-host oracle's.
            recv = hosts[to_id]
            seq = [recv.fwd.allow_n(key, 1).allowed
                   for _ in range(limit - spent + 3)]
            assert seq == [True] * (limit - spent) + [False] * 3
        finally:
            chaos_injector.uninstall()
            events.disable()
            for h in list(hosts.values()):
                h.close()

    def test_alive_but_unreachable_peer_skips_cycle(self, tmp_path):
        clock = ManualClock(1000.0)
        m, hosts = _make_fleet(tmp_path, ["a", "b"], clock)
        mono = [100.0]
        ctls = _attach_placement(hosts, mono)
        try:
            _seed_load(hosts, mono, "a", [4, 5, 6])
            # b is alive (membership) but its health fetch fails.
            ctls["a"].fetch_peer_health = lambda: {"b": None}
            out = ctls["a"].run_cycle()
            assert not out["ok"] and out["reason"] == "load-gap"
            assert out["gaps"] == ["b"]
            assert hosts["a"].core.map.epoch == m.epoch  # nothing moved
            assert "load-gap" in ctls["a"].status()["last_skip"]
        finally:
            for h in list(hosts.values()):
                h.close()

    def test_observatory_veto_halts_plan_and_backs_off(self, tmp_path):
        clock = ManualClock(1000.0)
        m, hosts = _make_fleet(tmp_path, ["a", "b", "c"], clock)
        mono = [100.0]
        burn = [0.0]
        ctls = _attach_placement(
            hosts, mono,
            slo_status=lambda: {"windows": {"300s": {
                "burn_rate": burn[0]}}})
        events.enable(capacity=128)
        try:
            _seed_load(hosts, mono, "a", [4, 5, 6])
            burn[0] = 5.0  # over the 2.0 abort bar
            out = ctls["a"].run_cycle()
            assert out["executed"] == 0
            assert hosts["a"].core.map.epoch == m.epoch
            assert ctls["a"].vetoes == 1
            assert ctls["a"].pace == 2.0
            evs = events.get().tail(category="placement")["events"]
            veto = [e for e in evs if e["action"] == "move-vetoed"]
            assert veto and veto[-1]["payload"]["burn_rate"] == 5.0
            # Signal clears: the move goes through and pace recovers.
            burn[0] = 0.0
            out = ctls["a"].run_cycle()
            assert out["executed"] >= 1
            assert ctls["a"].pace < 2.0
        finally:
            events.disable()
            for h in list(hosts.values()):
                h.close()

    def test_operator_abort_holds_until_apply(self, tmp_path):
        clock = ManualClock(1000.0)
        m, hosts = _make_fleet(tmp_path, ["a", "b", "c"], clock)
        mono = [100.0]
        ctls = _attach_placement(hosts, mono)
        events.enable(capacity=128)
        try:
            _seed_load(hosts, mono, "a", [4, 5, 6])
            got = ctls["a"].abort()
            assert got["ok"] and got["held"]
            out = ctls["a"].run_cycle()
            assert out.get("state") == "held"
            assert hosts["a"].core.map.epoch == m.epoch
            evs = events.get().tail(category="placement")["events"]
            assert any(e["action"] == "abort"
                       and e["actor"] == "operator" for e in evs)
            # apply clears the hold and runs a full cycle now.
            out = ctls["a"].apply()
            assert out["ok"] and out["executed"] >= 1
            assert hosts["a"].core.map.epoch > m.epoch
        finally:
            events.disable()
            for h in list(hosts.values()):
                h.close()

    def test_controller_metric_families_export(self, tmp_path):
        clock = ManualClock(1000.0)
        m, hosts = _make_fleet(tmp_path, ["a", "b"], clock)
        mono = [100.0]
        reg = Registry()
        _attach_placement(hosts, mono, registry=reg)
        try:
            text = reg.render()
            for fam in ("rate_limiter_placement_imbalance",
                        "rate_limiter_placement_pace",
                        "rate_limiter_placement_plans_total",
                        "rate_limiter_placement_moves_total",
                        "rate_limiter_placement_vetoes_total"):
                assert f"# TYPE {fam}" in text, fam
        finally:
            for h in list(hosts.values()):
                h.close()


# ---------------------------------------------------------------------------
# The rebalance-off pin: the slab observes, never decides


class TestPlacementOffPin:
    def test_decisions_identical_with_and_without_slab(self, tmp_path):
        """Two identical fleets, one with load slabs attached (always-on
        for fleet members), one without: the same workload produces the
        SAME decisions in the same order — the slab is pure observation
        (and with --rebalance off nothing ever moves)."""
        keys = [f"pin:{i}" for i in range(40)]

        def run(sub, attach):
            clock = ManualClock(1000.0)
            m, hosts = _make_fleet(tmp_path / sub, ["a", "b"], clock)
            if attach:
                for h in hosts.values():
                    h.core.load_slab = LoadSlab(48)
            try:
                out = []
                for _ in range(3):
                    for k in keys:
                        owner = hosts["a" if int(
                            hosts["a"].core.owners_of_hash(
                                hosts["a"].core.hash_keys([k]))[0]
                        ) == 0 else "b"]
                        r = owner.fwd.allow_n(k, 1)
                        out.append((k, bool(r.allowed),
                                    int(r.remaining), int(r.limit)))
                return out
            finally:
                for h in list(hosts.values()):
                    h.close()

        plain = run("plain", attach=False)
        slabbed = run("slabbed", attach=True)
        assert plain == slabbed

    def test_slab_sees_the_routed_traffic(self, tmp_path):
        clock = ManualClock(1000.0)
        m, hosts = _make_fleet(tmp_path, ["a", "b"], clock)
        for h in hosts.values():
            h.core.load_slab = LoadSlab(48)
        try:
            a = hosts["a"]
            key = _owned_key(a.core, 0)
            for _ in range(5):
                a.fwd.allow_n(key, 1)
            snap = a.core.load_slab.snapshot()
            assert snap["decide_total"] >= 5
            # A foreign key goes through the same routing chokepoint:
            # its bucket lands in FORWARD mass at the sender, whether or
            # not the forward itself succeeds (no wire in this harness).
            b_key = _owned_key(a.core, 1)
            before = a.core.load_slab.snapshot()["forward_total"]
            try:
                a.fwd.allow_n(b_key, 1)
            except Exception:  # noqa: BLE001
                pass
            after = a.core.load_slab.snapshot()["forward_total"]
            assert after == before + 1
        finally:
            for h in list(hosts.values()):
                h.close()


# ---------------------------------------------------------------------------
# Event journal file spill (satellite: --event-journal-dir)


class TestEventJournalSpill:
    def test_spill_replays_across_restart(self, tmp_path):
        d = str(tmp_path / "journal")
        j = EventJournal(64, host="m1", spill_dir=d)
        for i in range(5):
            j.record("policy", "set-override", actor="test",
                     payload={"i": i})
        j.close()
        # A new journal (a restarted process) replays the tail.
        j2 = EventJournal(64, host="m1", spill_dir=d)
        got = j2.tail()["events"]
        assert len(got) == 5
        assert [e["payload"]["i"] for e in got] == list(range(5))
        assert all(e["replayed"] for e in got)
        # Replayed events are re-sequenced monotonically and new events
        # continue the sequence.
        seqs = [e["seq"] for e in got]
        assert seqs == sorted(seqs)
        j2.record("policy", "reset", actor="test")
        assert j2.tail()["events"][-1]["seq"] == seqs[-1] + 1
        assert j2.status()["spill"]["replayed"] == 5
        j2.close()

    def test_torn_tail_write_is_skipped(self, tmp_path):
        d = str(tmp_path / "journal")
        j = EventJournal(64, spill_dir=d)
        j.record("policy", "reset")
        j.record("policy", "reset")
        j.close()
        segs = sorted(n for n in os.listdir(d)
                      if n.startswith("events-"))
        with open(os.path.join(d, segs[-1]), "a",
                  encoding="utf-8") as f:
            f.write('{"category": "policy", "action": "trunc')  # kill -9
        j2 = EventJournal(64, spill_dir=d)
        assert len(j2.tail()["events"]) == 2  # torn line dropped
        j2.close()

    def test_segments_rotate_and_stay_bounded(self, tmp_path):
        d = str(tmp_path / "journal")
        j = EventJournal(4096, spill_dir=d, spill_segment_bytes=4096,
                         spill_segments=3)
        for i in range(400):
            j.record("policy", "reset", payload={"pad": "x" * 64,
                                                 "i": i})
        segs = [n for n in os.listdir(d) if n.startswith("events-")]
        assert 1 <= len(segs) <= 3
        j.close()
        # Restart replays only what the bounded segments still hold —
        # the newest events, oldest-first.
        j2 = EventJournal(4096, spill_dir=d, spill_segments=3)
        got = j2.tail(limit=4096)["events"]
        assert got
        idx = [e["payload"]["i"] for e in got]
        assert idx == sorted(idx)
        assert idx[-1] == 399
        j2.close()

    def test_ring_capacity_bounds_replay(self, tmp_path):
        d = str(tmp_path / "journal")
        j = EventJournal(4096, spill_dir=d)
        for i in range(100):
            j.record("policy", "reset", payload={"i": i})
        j.close()
        j2 = EventJournal(16, spill_dir=d)
        got = j2.tail(limit=4096)["events"]
        assert len(got) == 16
        assert got[-1]["payload"]["i"] == 99  # newest kept
        j2.close()

    def test_spill_dir_failure_never_breaks_recording(self, tmp_path):
        f = tmp_path / "not-a-dir"
        f.write_text("x")
        j = EventJournal(64, spill_dir=str(f))  # open fails, counted
        j.record("policy", "reset")
        assert len(j.tail()["events"]) == 1
        assert j.status()["spill"]["errors"] >= 1
        j.close()


# ---------------------------------------------------------------------------
# Slow: full rebalance over real server processes + the operator CLI


def _fleet_config_http(tmp_path, pa, pb, ha, hb, snap_a, snap_b):
    """2-member fleet map with DECLARED http gateways (the tower needs
    them to fetch peers' /healthz placement blocks)."""
    d = {"buckets": 32, "epoch": 1, "hosts": [
        {"id": "a", "host": "127.0.0.1", "port": pa, "http": ha,
         "ranges": [[0, 16]], "successor": "b", "snapshot_dir": snap_a},
        {"id": "b", "host": "127.0.0.1", "port": pb, "http": hb,
         "ranges": [[16, 32]], "successor": "a", "snapshot_dir": snap_b},
    ]}
    path = str(tmp_path / "fleet.json")
    with open(path, "w", encoding="utf-8") as f:
        json.dump(d, f)
    return path, d


def _rebalance_cli(gateway, action, token="swordfish"):
    """Drive tools/fleet_rebalance.py exactly as an operator would."""
    import subprocess
    import sys

    from tests.test_elastic import REPO

    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "fleet_rebalance.py"),
         gateway, action, "--token", token],
        capture_output=True, text=True, timeout=120)
    try:
        return json.loads(out.stdout)
    except ValueError:
        raise AssertionError(
            f"fleet_rebalance {action} emitted no JSON:\n"
            f"stdout={out.stdout!r}\nstderr={out.stderr!r}")


@pytest.mark.slow
class TestRebalanceProcesses:
    def test_operator_rebalance_over_wire_continues_counters(
            self, tmp_path):
        """Skewed load on member a, operator dry-run → apply through
        the bearer-gated HTTP door (via tools/fleet_rebalance.py), a
        real over-the-wire handoff, and the oracle check: every probe
        key admits EXACTLY limit tokens across the move — moved and
        unmoved alike — with zero client errors."""
        import time as _time
        import urllib.request

        from ratelimiter_tpu.fleet import FleetMap
        from ratelimiter_tpu.ops.hashing import hash_prefixed_u64
        from ratelimiter_tpu.serving.client import Client, FleetClient
        from tests.netutil import free_port
        from tests.test_elastic import _spawn_member, _wait_banner

        pa, pb = free_port(), free_port()
        ha, hb = free_port(), free_port()
        snap_a = str(tmp_path / "sa")
        snap_b = str(tmp_path / "sb")
        cfgpath, fleet_d = _fleet_config_http(tmp_path, pa, pb, ha, hb,
                                              snap_a, snap_b)
        extras = lambda hp: ("--http-port", str(hp),  # noqa: E731
                             "--http-rebalance-token", "swordfish",
                             "--debug-trace")
        a = _spawn_member(pa, cfgpath, "a", snap_a, extra=extras(ha))
        b = _spawn_member(pb, cfgpath, "b", snap_b, extra=extras(hb))
        procs = [a, b]
        try:
            _wait_banner(a)
            _wait_banner(b)
            gw_a = f"http://127.0.0.1:{ha}"

            # One probe key per bucket of a's range [0, 16): the limit
            # is 100 (the member flags), spend 60 up front.
            prefix = "ratelimit"  # the server's default key prefix
            keys = {}
            for i in range(20000):
                k = f"rb:{i}"
                bkt = int(hash_prefixed_u64([k], prefix)[0] % 32)
                if bkt < 16 and bkt not in keys:
                    keys[bkt] = k
                    if len(keys) == 16:
                        break
            assert len(keys) == 16
            probe = [keys[b_] for b_ in sorted(keys)]
            with Client(port=pa, timeout=120) as ca:
                for _ in range(60):
                    rs = ca.allow_batch(probe)
                    assert all(r.allowed for r in rs)
                    _time.sleep(0.01)

            # Operator status door answers through the CLI.
            st = _rebalance_cli(gw_a, "status")
            assert st["ok"] and st["auto"] is False

            # Wait for the EWMA to drain + membership to see b, then
            # the dry-run previews a plan with moves (imbalance 2.0x —
            # all load on a, none on b).
            deadline = _time.time() + 60
            plan = None
            while _time.time() < deadline:
                got = _rebalance_cli(gw_a, "dry-run")
                if got.get("ok") and got["plan"]["moves"]:
                    plan = got["plan"]
                    break
                _time.sleep(0.5)
            assert plan is not None, "dry-run never produced moves"
            assert plan["imbalance_before"] >= 2.0
            assert all(mv["from"] == "a" for mv in plan["moves"])

            # Apply executes the donor's moves over the real wire.
            out = _rebalance_cli(gw_a, "apply")
            assert out["ok"], out
            executed = out["executed"]
            assert executed >= 1
            moved = [tuple(mv["range"])
                     for mv in out["plan"]["moves"][:executed]]
            with Client(port=pb, timeout=120) as cb:
                m_now = FleetMap.from_dict(cb.fleet_map())
            assert m_now.epoch >= 2
            for lo, hi in moved:
                assert (m_now.owner_table[lo:hi]
                        == m_now.ordinal("b")).all()
            # Projected imbalance actually landed under the trigger.
            assert out["plan"]["imbalance_projected"] <= 1.4

            # Oracle: EVERY probe key — on moved and unmoved buckets —
            # admits exactly 40 more (100 - 60), then denies. More
            # would be over-admission across the handoff; the client
            # follows the new map (zero errors).
            fc = FleetClient(fleet_d, call_timeout=120)
            try:
                for bkt, k in sorted(keys.items()):
                    more = sum(fc.allow_n(k, 1).allowed
                               for _ in range(45))
                    was_moved = any(lo <= bkt < hi for lo, hi in moved)
                    assert more == 40, (
                        f"bucket {bkt} "
                        f"({'moved' if was_moved else 'kept'}) "
                        f"admitted 60+{more} of 100")
            finally:
                fc.close()

            # The journal (fleet-merged door): plan + move events under
            # ONE correlation id.
            with urllib.request.urlopen(
                    f"{gw_a}/debug/events?fleet=1&category=placement"
                    f"&limit=64", timeout=60) as r:
                evs = json.loads(r.read())["events"]
            plans = [e for e in evs if e["action"] == "plan"]
            moves = [e for e in evs if e["action"] == "move"]
            assert plans and moves
            corr = plans[-1]["corr"]
            assert corr and all(e["corr"] == corr for e in moves)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.wait(timeout=30)
