"""Durability subsystem: snapshotter, recovery, manager semantics
(ratelimiter_tpu/persistence/, docs/ADR/009).

The crash-window contract under test: policy overrides and dynamic
config updates recover EXACTLY (WAL); decision counters recover to the
newest snapshot (bounded under-count). The kill -9 integration test
(tests/test_durability_crash.py) exercises the same contract through a
real serving subprocess.
"""

import json
import os
import time

import pytest

from ratelimiter_tpu import (
    Algorithm,
    CheckpointError,
    Config,
    ManualClock,
    PersistenceSpec,
    create_limiter,
)
from ratelimiter_tpu.persistence import (
    PersistenceManager,
    read_manifest,
)
from ratelimiter_tpu.persistence import wal as walmod

T0 = 1_700_000_000.0


def mk_cfg(d, algo=Algorithm.SLIDING_WINDOW, **pkw):
    return Config(algorithm=algo, limit=10, window=60.0,
                  persistence=PersistenceSpec(dir=str(d),
                                              snapshot_interval=1000.0,
                                              **pkw))


def boot(d, backend="exact", algo=Algorithm.SLIDING_WINDOW, **pkw):
    """(manager, wrapped limiter) recovered from directory d."""
    cfg = mk_cfg(d, algo, **pkw)
    mgr = PersistenceManager(cfg.persistence)
    lim = mgr.wrap(create_limiter(cfg, backend=backend,
                                  clock=ManualClock(T0)))
    mgr.attach([lim])
    mgr.recover()
    return mgr, lim


class TestCrashRecovery:
    @pytest.mark.parametrize("backend", ["exact", "dense", "sketch"])
    def test_counters_recover_to_snapshot_overrides_exactly(
            self, backend, tmp_path):
        mgr, lim = boot(tmp_path, backend)
        assert lim.allow_n("a", 4).allowed
        lim.set_override("vip", 7)
        mgr.snapshot_now()
        assert lim.allow_n("a", 3).allowed      # crash window: lost
        lim.set_override("vip2", 9)             # crash window: WAL-exact
        lim.delete_override("vip")              # crash window: WAL-exact
        mgr.wal.close()                         # kill -9 (no final snapshot)

        mgr2, lim2 = boot(tmp_path, backend)
        assert lim2.get_override("vip") is None
        assert lim2.get_override("vip2").limit == 9
        # Counters: >= 4 consumed (snapshot), <= 7 consumed (real total).
        assert not lim2.allow_n("a", 7).allowed
        assert lim2.allow_n("a", 3).allowed
        mgr2.stop(final_snapshot=False)
        lim2.close()
        lim.close()

    def test_no_snapshot_full_wal_replay(self, tmp_path):
        mgr, lim = boot(tmp_path)
        lim.set_override("vip", 5)
        lim.update_limit(20)
        mgr.wal.close()

        mgr2, lim2 = boot(tmp_path)
        assert lim2.get_override("vip").limit == 5
        assert lim2.config.limit == 20
        assert mgr2.report.snapshot_id is None
        assert mgr2.report.replayed == 2
        mgr2.stop(final_snapshot=False)
        lim2.close()
        lim.close()

    def test_update_window_replays(self, tmp_path):
        mgr, lim = boot(tmp_path)
        lim.update_window(30.0)
        mgr.wal.close()
        mgr2, lim2 = boot(tmp_path)
        assert lim2.config.window == 30.0
        mgr2.stop(final_snapshot=False)
        lim2.close()
        lim.close()

    def test_graceful_stop_loses_nothing(self, tmp_path):
        mgr, lim = boot(tmp_path)
        assert lim.allow_n("a", 9).allowed
        mgr.stop()                              # final snapshot
        lim.close()
        mgr2, lim2 = boot(tmp_path)
        assert not lim2.allow_n("a", 2).allowed  # 9 consumed survived
        mgr2.stop(final_snapshot=False)
        lim2.close()

    def test_replayed_mutations_are_not_relogged(self, tmp_path):
        mgr, lim = boot(tmp_path)
        lim.set_override("vip", 5)
        assert mgr.wal.last_seq == 1
        mgr.wal.close()
        mgr2, lim2 = boot(tmp_path)
        assert mgr2.report.replayed == 1
        assert mgr2.wal.last_seq == 1           # replay appended nothing
        mgr2.stop(final_snapshot=False)
        lim2.close()
        lim.close()

    def test_decisions_are_not_logged(self, tmp_path):
        mgr, lim = boot(tmp_path)
        for i in range(50):
            lim.allow(f"k{i}")
        lim.allow_batch(["a", "b", "c"])
        assert mgr.wal.last_seq == 0
        mgr.stop(final_snapshot=False)
        lim.close()

    def test_noop_delete_is_not_logged(self, tmp_path):
        mgr, lim = boot(tmp_path)
        assert lim.delete_override("ghost") is False
        assert mgr.wal.last_seq == 0
        mgr.stop(final_snapshot=False)
        lim.close()


class TestSnapshotter:
    def test_background_interval_snapshots(self, tmp_path):
        cfg = mk_cfg(tmp_path)
        mgr = PersistenceManager(
            PersistenceSpec(dir=str(tmp_path), snapshot_interval=0.1))
        lim = mgr.wrap(create_limiter(cfg, backend="exact",
                                      clock=ManualClock(T0)))
        mgr.attach([lim])
        mgr.start()
        deadline = time.time() + 10
        while time.time() < deadline:
            if (read_manifest(str(tmp_path)) or {}).get("snapshots"):
                break
            time.sleep(0.02)
        mgr.stop(final_snapshot=False)
        assert (read_manifest(str(tmp_path)) or {}).get("snapshots"), \
            "background thread never snapshotted"
        lim.close()

    def test_mutation_count_trigger(self, tmp_path):
        mgr = PersistenceManager(PersistenceSpec(
            dir=str(tmp_path), snapshot_interval=1000.0,
            snapshot_after_mutations=3))
        lim = mgr.wrap(create_limiter(mk_cfg(tmp_path), backend="exact",
                                      clock=ManualClock(T0)))
        mgr.attach([lim])
        mgr.start()
        for i in range(3):
            lim.set_override(f"vip{i}", 5)
        deadline = time.time() + 10
        while time.time() < deadline:
            m = read_manifest(str(tmp_path))
            if m and m["snapshots"]:
                break
            time.sleep(0.02)
        mgr.stop(final_snapshot=False)
        m = read_manifest(str(tmp_path))
        assert m and m["snapshots"], "mutation trigger never fired"
        lim.close()

    def test_retention_prunes_snapshots_and_wal(self, tmp_path):
        mgr = PersistenceManager(PersistenceSpec(
            dir=str(tmp_path), snapshot_interval=1000.0, retain=2,
            wal_max_bytes=4096))
        lim = mgr.wrap(create_limiter(mk_cfg(tmp_path), backend="exact",
                                      clock=ManualClock(T0)))
        mgr.attach([lim])
        for round_ in range(4):
            for i in range(40):
                lim.set_override(f"vip{round_}:{i}", 5)
            mgr.snapshot_now()
        m = read_manifest(str(tmp_path))
        assert len(m["snapshots"]) == 2
        snaps_on_disk = [f for f in os.listdir(tmp_path)
                         if f.startswith("snap-")]
        assert len(snaps_on_disk) == 2
        # WAL segments wholly below the oldest retained watermark are gone.
        oldest = min(e["wal_seq"] for e in m["snapshots"])
        first_seg = walmod.segment_files(str(tmp_path))[0][0]
        remaining = list(walmod.replay(str(tmp_path)))
        if remaining:
            assert remaining[-1].seq == 160
        assert first_seg > 1 or oldest < 4096 // 60
        mgr.stop(final_snapshot=False)
        lim.close()
        # The pruned directory still recovers cleanly.
        mgr2, lim2 = boot(tmp_path)
        assert lim2.get_override("vip3:39").limit == 5
        mgr2.stop(final_snapshot=False)
        lim2.close()

    def test_watermark_sampled_before_capture(self, tmp_path):
        """The manifest watermark never exceeds a seq the snapshot might
        miss: a mutation landing mid-snapshot replays (idempotently)."""
        mgr, lim = boot(tmp_path)
        lim.set_override("vip", 5)
        entry = mgr.snapshot_now()
        assert entry["wal_seq"] == mgr.wal.last_seq == 1
        mgr.stop(final_snapshot=False)
        lim.close()

    def test_snapshot_failure_leaves_disk_state(self, tmp_path,
                                                monkeypatch):
        mgr, lim = boot(tmp_path)
        lim.allow_n("a", 4)
        good = mgr.snapshot_now()
        calls = {"n": 0}
        orig = lim.inner.capture_state

        def boom():
            calls["n"] += 1
            raise RuntimeError("capture exploded")

        monkeypatch.setattr(lim.inner, "capture_state", boom)
        with pytest.raises(RuntimeError):
            mgr.snapshot_now()
        assert calls["n"] == 1
        m = read_manifest(str(tmp_path))
        assert [e["id"] for e in m["snapshots"]] == [good["id"]]
        monkeypatch.setattr(lim.inner, "capture_state", orig)
        mgr.stop(final_snapshot=False)
        lim.close()

    def test_status_fields(self, tmp_path):
        mgr, lim = boot(tmp_path)
        st = mgr.status()
        assert st["persistence"] is True and st["wal_seq"] == 0
        mgr.snapshot_now()
        st = mgr.status()
        assert st["last_snapshot_id"] == 1
        assert "last_snapshot_age_s" in st
        assert "last_snapshot_duration_s" in st
        assert "recovered" in st
        mgr.stop(final_snapshot=False)
        lim.close()

    def test_metrics_emitted(self, tmp_path):
        from ratelimiter_tpu.observability.metrics import Registry

        reg = Registry()
        mgr = PersistenceManager(PersistenceSpec(
            dir=str(tmp_path), snapshot_interval=1000.0), registry=reg)
        lim = mgr.wrap(create_limiter(mk_cfg(tmp_path), backend="exact",
                                      clock=ManualClock(T0)))
        mgr.attach([lim])
        lim.set_override("vip", 5)
        mgr.snapshot_now()
        text = reg.render()
        assert "rate_limiter_snapshots_total 1" in text
        assert "rate_limiter_wal_records_total 1" in text
        assert "rate_limiter_wal_seq 1" in text
        assert "rate_limiter_snapshot_duration_seconds_count 1" in text
        assert "rate_limiter_last_snapshot_timestamp_seconds" in text
        mgr.stop(final_snapshot=False)
        lim.close()


class TestRecoveryValidation:
    def test_fingerprint_mismatch_refuses_with_clear_error(self, tmp_path):
        """ISSUE-2 acceptance: a fingerprint-mismatched snapshot directory
        refuses to load, naming the config it was taken under."""
        mgr, lim = boot(tmp_path)
        lim.allow("a")
        mgr.snapshot_now()
        mgr.stop(final_snapshot=False)
        lim.close()

        cfg2 = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=99,
                      window=60.0,
                      persistence=PersistenceSpec(dir=str(tmp_path)))
        mgr2 = PersistenceManager(cfg2.persistence)
        lim2 = mgr2.wrap(create_limiter(cfg2, backend="exact",
                                        clock=ManualClock(T0)))
        mgr2.attach([lim2])
        with pytest.raises(CheckpointError) as ei:
            mgr2.recover()
        msg = str(ei.value)
        assert "fingerprint" in msg and "limit=10" in msg
        assert "move the snapshot directory aside" in msg
        mgr2.stop(final_snapshot=False)
        lim2.close()

    def test_corrupt_newest_falls_back_to_older(self, tmp_path):
        mgr, lim = boot(tmp_path)
        lim.allow_n("a", 4)
        first = mgr.snapshot_now()
        lim.allow_n("a", 3)
        second = mgr.snapshot_now()
        mgr.stop(final_snapshot=False)
        lim.close()
        # Corrupt the newest snapshot file (torn write simulation —
        # normally impossible thanks to write_atomic, but disks rot).
        newest = os.path.join(str(tmp_path), second["files"][0])
        with open(newest, "wb") as f:
            f.write(b"not an npz")
        mgr2, lim2 = boot(tmp_path)
        assert mgr2.report.snapshot_id == first["id"]
        # Older snapshot: only 4 consumed.
        assert lim2.allow_n("a", 6).allowed
        mgr2.stop(final_snapshot=False)
        lim2.close()

    def test_shard_count_mismatch_refuses(self, tmp_path):
        cfg = mk_cfg(tmp_path)
        mgr = PersistenceManager(cfg.persistence)
        lims = [mgr.wrap(create_limiter(cfg, backend="exact",
                                        clock=ManualClock(T0)))
                for _ in range(2)]
        mgr.attach(lims, shard_of=lambda k: hash(k) % 2)
        mgr.snapshot_now()
        mgr.stop(final_snapshot=False)
        for lim in lims:
            lim.close()
        mgr2, lim2 = (None, None)
        cfg2 = mk_cfg(tmp_path)
        mgr2 = PersistenceManager(cfg2.persistence)
        lim2 = mgr2.wrap(create_limiter(cfg2, backend="exact",
                                        clock=ManualClock(T0)))
        mgr2.attach([lim2])
        with pytest.raises(CheckpointError, match="--shards 2"):
            mgr2.recover()
        mgr2.stop(final_snapshot=False)
        lim2.close()

    def test_partial_shard_restore_refuses_wal_replay(self, tmp_path):
        """If NO retained entry restores fully but some shard already
        took a partial entry's state, recovery refuses instead of
        replaying the WAL over mixed shard state."""
        cfg = mk_cfg(tmp_path)
        mgr = PersistenceManager(cfg.persistence)
        lims = [mgr.wrap(create_limiter(cfg, backend="exact",
                                        clock=ManualClock(T0)))
                for _ in range(2)]
        mgr.attach(lims, shard_of=lambda k: 0)
        entry = mgr.snapshot_now()
        mgr.stop(final_snapshot=False)
        for lim in lims:
            lim.close()
        # Shard 0's file stays good; shard 1's is garbage -> the (only)
        # entry restores shard 0 then fails.
        with open(os.path.join(str(tmp_path), entry["files"][1]), "wb") as f:
            f.write(b"rotten")
        cfg2 = mk_cfg(tmp_path)
        mgr2 = PersistenceManager(cfg2.persistence)
        lims2 = [mgr2.wrap(create_limiter(cfg2, backend="exact",
                                          clock=ManualClock(T0)))
                 for _ in range(2)]
        mgr2.attach(lims2, shard_of=lambda k: 0)
        with pytest.raises(CheckpointError, match="mixed state"):
            mgr2.recover()
        mgr2.stop(final_snapshot=False)
        for lim in lims2:
            lim.close()

    def test_second_manager_on_live_directory_refused(self, tmp_path):
        """Single-writer guard surfaces through the manager: a
        double-started process fails loudly at construction."""
        mgr, lim = boot(tmp_path)
        with pytest.raises(CheckpointError, match="exactly one writer"):
            PersistenceManager(mk_cfg(tmp_path).persistence)
        mgr.stop(final_snapshot=False)
        lim.close()

    def test_unreadable_manifest_refuses(self, tmp_path):
        with open(tmp_path / "manifest.json", "w") as f:
            f.write("{broken")
        with pytest.raises(CheckpointError, match="manifest"):
            boot(tmp_path)

    def test_sharded_reset_replays_to_owning_shard_only(self, tmp_path):
        cfg = mk_cfg(tmp_path)
        mgr = PersistenceManager(cfg.persistence)
        lims = [mgr.wrap(create_limiter(cfg, backend="exact",
                                        clock=ManualClock(T0)))
                for _ in range(2)]
        shard_of = lambda k: 1  # noqa: E731 — every key owned by shard 1
        mgr.attach(lims, shard_of=shard_of)
        lims[1].allow_n("k", 10)
        mgr.snapshot_now()
        lims[1].reset("k")
        mgr.wal.close()

        cfg2 = mk_cfg(tmp_path)
        mgr2 = PersistenceManager(cfg2.persistence)
        lims2 = [mgr2.wrap(create_limiter(cfg2, backend="exact",
                                          clock=ManualClock(T0)))
                 for _ in range(2)]
        mgr2.attach(lims2, shard_of=shard_of)
        rep = mgr2.recover()
        assert rep.replayed == 1
        assert lims2[1].allow_n("k", 10).allowed   # reset landed
        mgr2.stop(final_snapshot=False)
        for lim in lims + lims2:
            lim.close()


class TestManifest:
    def test_manifest_is_valid_json_with_watermarks(self, tmp_path):
        mgr, lim = boot(tmp_path)
        lim.set_override("vip", 5)
        mgr.snapshot_now()
        with open(tmp_path / "manifest.json") as f:
            m = json.load(f)
        (entry,) = m["snapshots"]
        assert entry["wal_seq"] == 1
        assert entry["config"]["limit"] == 10
        assert entry["files"] == ["snap-00000001-000.npz"]
        mgr.stop(final_snapshot=False)
        lim.close()

    def test_snapshot_ids_continue_across_restarts(self, tmp_path):
        mgr, lim = boot(tmp_path)
        mgr.snapshot_now()
        mgr.stop(final_snapshot=False)
        lim.close()
        mgr2, lim2 = boot(tmp_path)
        entry = mgr2.snapshot_now()
        assert entry["id"] == 2
        mgr2.stop(final_snapshot=False)
        lim2.close()
