"""Fleet control tower tests (ADR-021).

Three pillars:

* **Event journal** — bounded ring semantics, cursor pagination,
  category filters, the module seam (off = no-op), and the emit sites
  (controller tighten/relax with signal snapshots, quarantine
  transitions).
* **Mergeable rollup** — the tower's pure merge functions pinned
  against hand-computed merges (summed tallies + recomputed Wilson,
  token-joined top-K, pooled SLO burn, per-scope hierarchy
  aggregation), plus composition with unreachable members.
* **Cross-host trace stitching** — the satellite regression: forwarded
  fragments used to be invisible on the receiving host's recorder (no
  TRACE_FLAG anywhere in fleet/). A REAL two-member hop (FleetForwarder
  + a real asyncio peer server over TCP) must now produce receiver-side
  spans under a window-level wire id LINKED to the client frame's trace
  id, and the merged timeline must read as ONE trace id across the hop.

The slow lane adds the full two-process composition: two server
binaries, a traced frame across the hop, /debug/trace?fleet=1,
/v1/fleet/status vs an offline merge, and /debug/events?fleet=1.
"""

from __future__ import annotations

import asyncio
import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from netutil import free_port

from ratelimiter_tpu import Algorithm, Config, SketchParams
from ratelimiter_tpu.core.clock import ManualClock
from ratelimiter_tpu.evaluation.compare import wilson_interval
from ratelimiter_tpu.fleet import FleetCore, FleetForwarder, FleetMap
from ratelimiter_tpu.fleet import tower
from ratelimiter_tpu.observability import events, tracing
from ratelimiter_tpu.serving import protocol as p
from ratelimiter_tpu.serving.client import Client


def _cfg(limit=20, window=600.0, **kw):
    return Config(algorithm=Algorithm.TPU_SKETCH, limit=limit,
                  window=window,
                  sketch=SketchParams(depth=4, width=4096, sub_windows=6),
                  **kw)


def _map(hosts_spec, buckets=32):
    hosts = []
    for spec in hosts_spec:
        hid, port, (lo, hi) = spec[:3]
        h = {"id": hid, "host": "127.0.0.1", "port": port,
             "ranges": [[lo, hi]]}
        if len(spec) > 3:
            h.update(spec[3])
        hosts.append(h)
    return FleetMap.from_dict(
        {"buckets": buckets, "epoch": 1, "hosts": hosts})


def _server_on_thread(limiter):
    from ratelimiter_tpu.serving import RateLimitServer

    loop = asyncio.new_event_loop()
    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()
    srv = RateLimitServer(limiter, "127.0.0.1", 0)
    asyncio.run_coroutine_threadsafe(srv.start(), loop).result(10)
    return srv, loop, t


def _stop(srv, loop, t):
    asyncio.run_coroutine_threadsafe(srv.shutdown(), loop).result(10)
    loop.call_soon_threadsafe(loop.stop)
    t.join(timeout=10)
    loop.close()


@pytest.fixture
def journal():
    j = events.enable(128, host="test")
    yield j
    events.disable()


@pytest.fixture
def recorder():
    rec = tracing.enable(4096)
    yield rec
    tracing.disable()


# ===================================================================
#                         event journal
# ===================================================================


class TestEventJournal:
    def test_record_read_pagination(self, journal):
        for i in range(10):
            journal.record("policy", f"a{i}", actor="t")
        page = journal.read(after=0, limit=4)
        assert [e["action"] for e in page["events"]] == \
            ["a0", "a1", "a2", "a3"]
        assert page["cursor"] == 4
        assert page["truncated"] is False
        page2 = journal.read(after=page["cursor"], limit=100)
        assert [e["action"] for e in page2["events"]] == \
            [f"a{i}" for i in range(4, 10)]
        assert page2["cursor"] == 10
        # Past the end: empty page, cursor stays.
        page3 = journal.read(after=10)
        assert page3["events"] == [] and page3["cursor"] == 10

    def test_ring_bound_and_truncation_flag(self):
        j = events.EventJournal(16)
        for i in range(40):
            j.record("policy", f"a{i}")
        page = j.read(after=0)
        assert len(page["events"]) == 16
        assert page["events"][0]["action"] == "a24"
        assert page["truncated"] is True       # history before seq 25 gone
        assert j.read(after=24)["truncated"] is False

    def test_category_filter_and_tail(self, journal):
        journal.record("policy", "p1")
        journal.record("controller", "tighten")
        journal.record("policy", "p2")
        page = journal.read(after=0, category="policy")
        assert [e["action"] for e in page["events"]] == ["p1", "p2"]
        tail = journal.tail(2)
        assert [e["action"] for e in tail["events"]] == \
            ["tighten", "p2"]
        assert journal.tail(5, category="controller")["events"][0][
            "action"] == "tighten"

    def test_event_shape(self, journal):
        journal.record("handoff", "send", actor="h1", corr=0xDEAD,
                       severity="warning", payload={"ranges": [[0, 4]]})
        e = journal.read(after=0)["events"][0]
        assert e["category"] == "handoff" and e["actor"] == "h1"
        assert e["corr"] == f"{0xDEAD:016x}"
        assert e["severity"] == "warning"
        assert e["payload"] == {"ranges": [[0, 4]]}
        assert e["ts"] > 1e9 and e["mono_ns"] > 0

    def test_seam_off_is_noop(self):
        assert events.JOURNAL is None
        events.emit("policy", "set-override")  # must not raise

    def test_emit_reaches_journal(self, journal):
        events.emit("quarantine", "probing", actor="slice1")
        assert journal.read(after=0)["events"][0]["action"] == "probing"


class TestControllerEvents:
    """The acceptance bar: a tighten must be reconstructable from the
    journal ALONE — cause, signal snapshot, correlation id."""

    class _StubHier:
        def __init__(self, tenants, glob):
            self.tenants, self.glob = tenants, glob
            self.moves = []

        def hierarchy_stats(self):
            return {"tenants": {n: dict(t)
                                for n, t in self.tenants.items()},
                    "global": dict(self.glob)}

        def set_effective(self, scope, v):
            self.moves.append((scope, v))
            if scope in self.tenants:
                self.tenants[scope]["effective"] = v
            else:
                self.glob["effective"] = v
            return v

        def hierarchy_payload(self):
            return {}

        def effective_limits(self):
            return {}

    def _storm(self):
        # att: 90/95 of global mass on a 1/4 fair weight share —
        # share > hot_share(2.0) x fair(0.25), the hot-tenant trigger.
        return self._StubHier(
            {"att": {"in_window": 90, "effective": 1000,
                     "ceiling": 1000, "weight": 1},
             "vic": {"in_window": 4, "effective": 1000,
                     "ceiling": 1000, "weight": 3}},
            {"in_window": 95, "effective": 100, "ceiling": 100})

    def test_tighten_event_carries_cause_snapshot_corr(self, journal):
        from ratelimiter_tpu.hierarchy.controller import AIMDController

        hier = self._storm()
        ctl = AIMDController(hier, interval=999)
        moved = ctl.tick(now=100.0)
        assert moved == {"att": 700}
        evs = journal.read(after=0, category="controller")["events"]
        assert len(evs) == 1
        e = evs[0]
        assert e["action"] == "tighten" and e["actor"] == "att"
        assert e["severity"] == "warning"
        assert len(e["corr"]) == 16 and e["corr"] != "0" * 16
        pl = e["payload"]
        # Reconstructable: cause + old/new + the full signal snapshot.
        assert pl["cause"] == "hot-tenant"
        assert pl["old"] == 1000 and pl["new"] == 700
        assert pl["global_mass"] == 95
        assert pl["global_effective"] == 100
        assert pl["saturated"] is True
        assert pl["hot_tenants"] == ["att"]
        assert pl["in_window"] == 90
        assert "burn_rate" in pl and "false_deny_wilson_high" in pl

    def test_veto_event(self, journal):
        from ratelimiter_tpu.hierarchy.controller import AIMDController

        hier = self._storm()
        ctl = AIMDController(
            hier,
            audit_status=lambda: {"false_deny_wilson95": [0.2, 0.5]})
        moved = ctl.tick(now=100.0)
        assert moved == {}           # vetoed — no tighten happened
        evs = journal.read(after=0, category="controller")["events"]
        assert [e["action"] for e in evs] == ["tighten-vetoed"]
        assert evs[0]["payload"]["false_deny_wilson_high"] == 0.5

    def test_relax_event(self, journal):
        from ratelimiter_tpu.hierarchy.controller import AIMDController

        hier = self._StubHier(
            {"t": {"in_window": 1, "effective": 500, "ceiling": 1000,
                   "weight": 1}},
            {"in_window": 0, "effective": 100, "ceiling": 100})
        ctl = AIMDController(hier)
        moved = ctl.tick(now=100.0)
        assert moved["t"] == 550
        evs = journal.read(after=0, category="controller")["events"]
        assert evs[0]["action"] == "relax"
        assert evs[0]["payload"]["old"] == 500
        assert evs[0]["payload"]["new"] == 550


class TestQuarantineEvents:
    def test_transitions_journaled(self, journal):
        from ratelimiter_tpu.observability.metrics import Registry
        from ratelimiter_tpu.parallel.quarantine import QuarantineManager

        qm = QuarantineManager(2, registry=Registry())
        qm.force(1)
        qm.clear(1)
        evs = journal.read(after=0, category="quarantine")["events"]
        assert [(e["action"], e["actor"]) for e in evs] == \
            [("quarantined", "slice1"), ("healthy", "slice1")]
        assert evs[0]["severity"] == "warning"
        assert evs[1]["payload"]["from"] == "quarantined"


# ===================================================================
#                      mergeable fleet rollup
# ===================================================================


class TestMergeAudit:
    def test_sum_and_recomputed_wilson(self):
        blocks = {
            "h0": {"sample": 1, "samples": 1000, "oracle_allows": 900,
                   "false_denies": 9, "false_allows": 1,
                   "fail_open_samples": 2, "dropped_decisions": 5,
                   "oracle_errors": 0},
            "h1": {"sample": 1, "samples": 500, "oracle_allows": 400,
                   "false_denies": 1, "false_allows": 0,
                   "fail_open_samples": 0, "dropped_decisions": 0,
                   "oracle_errors": 1},
        }
        m = tower.merge_audit(blocks)
        assert m["samples"] == 1500
        assert m["oracle_allows"] == 1300
        assert m["false_denies"] == 10
        assert m["oracle_denies"] == 200
        # Rates + Wilson RECOMPUTED over merged counts — the offline
        # hand merge, not an average of member rates.
        assert m["false_deny_rate"] == round(10 / 1300, 8)
        lo, hi = wilson_interval(10, 1300)
        assert m["false_deny_wilson95"] == [round(lo, 8), round(hi, 8)]
        lo, hi = wilson_interval(1, 200)
        assert m["false_allow_wilson95"] == [round(lo, 10),
                                             round(hi, 10)]
        assert m["per_host"]["h1"]["false_denies"] == 1

    def test_empty(self):
        assert tower.merge_audit({}) == {}


class TestMergeConsumers:
    def test_token_join_and_rerank(self):
        blocks = {
            "h0": {"slots": 16, "occupied": 2, "tracked_mass": 100,
                   "top": [{"consumer": "aa", "in_window": 60},
                           {"consumer": "bb", "in_window": 40}]},
            "h1": {"slots": 16, "occupied": 2, "tracked_mass": 100,
                   "top": [{"consumer": "cc", "in_window": 70},
                           {"consumer": "aa", "in_window": 30}]},
        }
        m = tower.merge_consumers(blocks, k=2)
        assert m["tracked_mass"] == 200
        # aa = 60+30 = 90 beats cc = 70: the token join changes the
        # ranking vs any single member's view.
        assert [r["consumer"] for r in m["top"]] == ["aa", "cc"]
        assert m["top"][0]["in_window"] == 90
        assert m["top"][0]["hosts"] == {"h0": 60, "h1": 30}
        assert m["top"][0]["share"] == round(90 / 200, 6)


class TestMergeSlo:
    def test_pooled_counts_not_averaged_ratios(self):
        blocks = {
            # Idle member: perfect, tiny traffic.
            "h0": {"objective": 0.999, "windows": {"300s": {
                "span_s": 300, "spans": 10, "spans_slow": 0,
                "decisions": 10, "decisions_bad": 0,
                "burn_rate": 0.0}}},
            # Burning member: 10% bad on heavy traffic.
            "h1": {"objective": 0.999, "windows": {"300s": {
                "span_s": 300, "spans": 1000, "spans_slow": 0,
                "decisions": 990, "decisions_bad": 99,
                "burn_rate": 100.0}}},
        }
        m = tower.merge_slo(blocks)
        row = m["windows"]["300s"]
        assert row["decisions"] == 1000 and row["decisions_bad"] == 99
        # Pooled fraction 99/1000, NOT the (0 + 0.1)/2 average.
        assert row["availability_bad_fraction"] == round(99 / 1000, 6)
        assert row["burn_rate"] == round((99 / 1000) / 0.001, 3)
        assert row["per_host_burn"] == {"h0": 0.0, "h1": 100.0}


class TestMergeHierarchy:
    def test_mass_sums_limits_min(self):
        blocks = {
            "h0": {"tenants": {"t": {"in_window": 30, "effective": 100,
                                     "ceiling": 1000, "weight": 2}},
                   "global": {"in_window": 50, "effective": 500,
                              "ceiling": 500}},
            "h1": {"tenants": {"t": {"in_window": 20, "effective": 70,
                                     "ceiling": 1000, "weight": 2}},
                   "global": {"in_window": 10, "effective": 500,
                              "ceiling": 500}},
        }
        m = tower.merge_hierarchy(blocks)
        t = m["tenants"]["t"]
        assert t["in_window"] == 50
        assert t["effective"] == 70          # the binding constraint
        assert t["per_host_in_window"] == {"h0": 30, "h1": 20}
        assert t["per_host_effective"] == {"h0": 100, "h1": 70}
        assert m["global"]["in_window"] == 60


class TestMergedStatus:
    def test_unreachable_member_is_a_named_gap(self):
        members = {
            "h0": {"serving": True, "decisions_total": 10,
                   "fleet": {"epoch": 3, "owned_ranges": [[0, 16]]},
                   "audit": {"sample": 1, "samples": 10,
                             "oracle_allows": 9, "false_denies": 0,
                             "false_allows": 0}},
            "h1": None,
        }
        m = tower.merged_status(members)
        assert m["members"] == 2 and m["reachable"] == 1
        assert m["hosts"]["h1"] == {"reachable": False}
        assert m["epoch"] == 3 and m["epoch_converged"] is True
        assert m["audit"]["samples"] == 10

    def test_epoch_split_flagged(self):
        members = {
            "h0": {"fleet": {"epoch": 3}},
            "h1": {"fleet": {"epoch": 4}},
        }
        m = tower.merged_status(members)
        assert m["epoch"] == 4 and m["epoch_converged"] is False


class TestMergeTraces:
    def _payload(self, spans, links=(), threads=None):
        return {"traceEvents": [
            {"name": s["stage"], "cat": "ratelimiter", "ph": "X",
             "ts": s["ts"], "dur": s.get("dur", 1.0), "pid": 1,
             "tid": s.get("tid", 7),
             "args": {"trace_id": s["trace_id"]}} for s in spans],
            "otherData": {"links": list(links),
                          "threads": threads or {}}}

    def test_offset_alignment_and_host_lanes(self):
        a = self._payload([{"stage": "io", "ts": 100.0,
                            "trace_id": "aa" * 8}])
        b = self._payload([{"stage": "device", "ts": 5000.0,
                            "trace_id": "bb" * 8}],
                          threads={"7": "worker"})
        merged = tower.merge_traces(
            {"h0": a, "h1": b}, {"h0": 0, "h1": -4_000_000_000}, "h0")
        spans = [e for e in merged["traceEvents"] if e["ph"] == "X"]
        by_host = {e["args"]["host"]: e for e in spans}
        assert by_host["h0"]["ts"] == 100.0
        # -4s offset: 5000us - 4_000_000us.
        assert by_host["h1"]["ts"] == pytest.approx(5000.0 - 4e6)
        assert by_host["h0"]["pid"] != by_host["h1"]["pid"]
        # Perfetto process/thread metadata for the host lanes.
        metas = [e for e in merged["traceEvents"] if e["ph"] == "M"]
        names = {(e["name"], e["args"].get("name")) for e in metas}
        assert ("process_name", "h0") in names
        assert ("process_name", "h1") in names
        assert ("thread_name", "worker") in names
        assert merged["otherData"]["hosts"]["h1"]["aligned"] is True

    def test_single_parent_window_rewrites_to_client_id(self):
        T, W = "11" * 8, "22" * 8
        a = self._payload([{"stage": "io", "ts": 1.0, "trace_id": T},
                           {"stage": "forward", "ts": 2.0,
                            "trace_id": W}],
                          links=[{"parent": T, "child": W, "t_ns": 0}])
        b = self._payload([{"stage": "device", "ts": 3.0,
                            "trace_id": W}])
        merged = tower.merge_traces({"h0": a, "h1": b},
                                    {"h0": 0, "h1": 0}, "h0")
        spans = [e for e in merged["traceEvents"] if e["ph"] == "X"]
        # ONE trace id across the hop: the receiver's window-id spans
        # (and the sender's forward span) renamed to the client id,
        # window id preserved as an arg.
        for e in spans:
            assert e["args"]["trace_id"] == T
        dev = next(e for e in spans if e["name"] == "device")
        assert dev["args"]["window_id"] == W

    def test_multi_parent_window_gets_synthetic_parent(self):
        t1, t2, w = "11" * 8, "33" * 8, "22" * 8
        a = self._payload([], links=[
            {"parent": t1, "child": w, "t_ns": 0},
            {"parent": t2, "child": w, "t_ns": 0}])
        b = self._payload([{"stage": "device", "ts": 3.0,
                            "trace_id": w}])
        merged = tower.merge_traces({"h0": a, "h1": b},
                                    {"h0": 0, "h1": 0}, "h0")
        dev = [e for e in merged["traceEvents"] if e["ph"] == "X"][0]
        # PR-14 residual closed: the window's spans rename to a
        # SYNTHETIC parent id derived from the full parent set (a
        # by-id filter now groups the receiver's spans under one id
        # instead of leaving them stranded on the window id), while
        # the window id and the parent list stay queryable in args.
        assert dev["args"]["trace_id"] == tower.synthetic_parent_id(
            [t1, t2])
        assert dev["args"]["trace_id"] != w
        assert dev["args"]["window_id"] == w
        assert dev["args"]["trace_parents"] == sorted([t1, t2])

    def test_synthetic_parent_id_is_order_invariant_and_16_hex(self):
        t1, t2 = "11" * 8, "33" * 8
        sid = tower.synthetic_parent_id([t2, t1])
        assert sid == tower.synthetic_parent_id([t1, t2])
        assert len(sid) == 16
        int(sid, 16)  # well-formed hex, same shape as real trace ids
        # Different coalitions -> different synthetic ids.
        assert sid != tower.synthetic_parent_id([t1, "55" * 8])


class TestOfflineStitchParityPin:
    """tools/fleet_trace.py --offline must reproduce the server-side
    fan-out byte-for-byte given the same dumps and clock offsets: both
    paths call tower.merge_traces, so any divergence is assembly
    plumbing (ref choice, offset lookup, unreachable handling) — the
    exact class of bug this pin exists to catch. Covers the
    single-parent rewrite and the multi-parent window in one timeline."""

    def _tool(self):
        import importlib.util

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "fleet_trace_tool", os.path.join(repo, "tools",
                                             "fleet_trace.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def _payloads(self):
        t1, t2, w_single, w_multi = "11" * 8, "33" * 8, "22" * 8, "44" * 8
        a = {"traceEvents": [
                {"name": "io", "cat": "ratelimiter", "ph": "X",
                 "ts": 100.0, "dur": 2.0, "pid": 1, "tid": 7,
                 "args": {"trace_id": t1}},
                {"name": "forward", "cat": "ratelimiter", "ph": "X",
                 "ts": 101.0, "dur": 5.0, "pid": 1, "tid": 7,
                 "args": {"trace_id": w_single}}],
             "otherData": {"links": [
                 {"parent": t1, "child": w_single, "t_ns": 0},
                 {"parent": t1, "child": w_multi, "t_ns": 0},
                 {"parent": t2, "child": w_multi, "t_ns": 1}],
                 "threads": {"7": "io-worker"}}}
        b = {"traceEvents": [
                {"name": "device", "cat": "ratelimiter", "ph": "X",
                 "ts": 5000.0, "dur": 3.0, "pid": 1, "tid": 9,
                 "args": {"trace_id": w_single}},
                {"name": "device", "cat": "ratelimiter", "ph": "X",
                 "ts": 5010.0, "dur": 3.0, "pid": 1, "tid": 9,
                 "args": {"trace_id": w_multi}}],
             "otherData": {"links": [], "threads": {"9": "dispatch"}}}
        return a, b

    def test_offline_equals_server_side_merge(self, monkeypatch):
        import copy

        a, b = self._payloads()
        off_b = -4_000_000_000
        health = {"fleet": {
            "self": "a",
            "peers": {"b": {"mono_offset_ns": off_b}},
            "hosts": {
                "a": {"addr": "127.0.0.1:9001", "http": 8434},
                "b": {"addr": "127.0.0.1:9002", "http": 8435},
            }}}
        urls = []

        def fake_fetch(url, bearer=None, timeout=10.0):
            urls.append(url)
            if url.endswith("/healthz"):
                return copy.deepcopy(health)
            if "8434" in url:
                return copy.deepcopy(a)
            if "8435" in url:
                return copy.deepcopy(b)
            raise AssertionError(f"unexpected fetch {url}")

        monkeypatch.setattr(tower, "fetch_json", fake_fetch)
        tool = self._tool()
        offline = tool.stitched_offline("http://127.0.0.1:8434", "tok",
                                        10.0)
        # The server-side stitch on the SAME inputs: exactly what
        # ControlTower.fleet_trace hands to merge_traces.
        server_side = tower.merge_traces(
            {"a": copy.deepcopy(a), "b": copy.deepcopy(b)},
            {"a": 0, "b": off_b}, "a")
        assert json.dumps(offline, sort_keys=True) == json.dumps(
            server_side, sort_keys=True)
        # The pin is only meaningful if the hard cases are present:
        spans = [e for e in offline["traceEvents"] if e["ph"] == "X"]
        dev_single = next(e for e in spans if e["name"] == "device"
                          and "trace_parents" not in e["args"])
        assert dev_single["args"]["trace_id"] == "11" * 8   # rewritten
        assert dev_single["args"]["window_id"] == "22" * 8
        assert dev_single["ts"] == pytest.approx(5000.0 + off_b / 1e3)
        dev_multi = next(e for e in spans if e["name"] == "device"
                         and "trace_parents" in e["args"])
        assert dev_multi["args"]["trace_id"] == tower.synthetic_parent_id(
            ["11" * 8, "33" * 8])
        assert dev_multi["args"]["window_id"] == "44" * 8
        assert dev_multi["args"]["trace_parents"] == sorted(
            ["11" * 8, "33" * 8])

    def test_offline_unreachable_peer_is_a_named_gap_in_both(
            self, monkeypatch):
        import copy

        a, _ = self._payloads()
        health = {"fleet": {
            "self": "a",
            "peers": {},
            "hosts": {
                "a": {"addr": "127.0.0.1:9001", "http": 8434},
                "b": {"addr": "127.0.0.1:9002", "http": 8435},
            }}}

        def fake_fetch(url, bearer=None, timeout=10.0):
            if url.endswith("/healthz"):
                return copy.deepcopy(health)
            if "8434" in url:
                return copy.deepcopy(a)
            raise urllib.error.URLError("connection refused")

        monkeypatch.setattr(tower, "fetch_json", fake_fetch)
        tool = self._tool()
        offline = tool.stitched_offline("http://127.0.0.1:8434", None,
                                        10.0)
        server_side = tower.merge_traces(
            {"a": copy.deepcopy(a), "b": None}, {"a": 0, "b": None}, "a")
        assert json.dumps(offline, sort_keys=True) == json.dumps(
            server_side, sort_keys=True)
        hb = offline["otherData"]["hosts"]["b"]
        assert hb["reachable"] is False and hb["aligned"] is False


class TestMergeEvents:
    def test_host_tag_alignment_and_sort(self):
        pages = {
            "h0": {"events": [{"seq": 1, "ts": 100.0, "mono_ns": 50,
                               "category": "policy", "action": "x"}]},
            "h1": {"events": [{"seq": 9, "ts": 99.0, "mono_ns": 10,
                               "category": "handoff", "action": "y"}]},
            "h2": None,
        }
        m = tower.merge_events(pages, {"h0": 0, "h1": 1000, "h2": None},
                               "h0")
        assert [e["host"] for e in m["events"]] == ["h1", "h0"]  # by ts
        assert m["events"][0]["mono_aligned_ns"] == 1010
        assert m["hosts"]["h2"] == {"reachable": False,
                                    "aligned": False}


# ===================================================================
#       cross-host trace stitching over a REAL two-member hop
# ===================================================================


class TestForwardLaneTraceRegression:
    """Satellite 1: forward lanes used to STRIP trace context — a
    traced client frame's forwarded fragments were invisible on the
    receiving host's recorder. Pins, across a real TCP hop to a real
    asyncio peer server: (a) the wire window carries a TRACE_FLAG
    window id, so the receiver records io/device spans under it;
    (b) the sender links the client frame's id to the window id;
    (c) a 'forward' span wraps the hop on the sender; (d) decisions
    stay bit-identical with tracing on."""

    def _fleet(self, clock, limit=20, **core_kw):
        from ratelimiter_tpu.algorithms.sketch import SketchLimiter
        from ratelimiter_tpu.observability.metrics import Registry

        cfg = _cfg(limit=limit)
        lim_a = SketchLimiter(cfg, clock)
        lim_b = SketchLimiter(cfg, clock)
        srv, loop, t = _server_on_thread(lim_b)
        m = _map([("a", 1, (0, 16)), ("b", srv.port, (16, 32))])
        core = FleetCore(m, "a", prefix=cfg.prefix,
                         forward_deadline=30.0, registry=Registry(),
                         **core_kw)
        fwd = FleetForwarder(lim_a, core)
        return cfg, fwd, core, (srv, loop, t)

    def test_traced_frame_crosses_the_hop(self, recorder):
        clock = ManualClock(1000.0)
        cfg, fwd, core, server = self._fleet(clock)
        srv, loop, t = server
        try:
            ids = np.arange(1, 41, dtype=np.uint64)
            owners = core.owners_of_ids(ids)
            assert (owners == 1).any() and (owners == 0).any()
            T = tracing.new_trace_id()
            # The batcher sets the current-trace context around the
            # launch (recorder-on only); drive the same seam directly.
            tracing.set_current(T)
            try:
                out = fwd.allow_ids(ids)
            finally:
                tracing.set_current(0)
            assert len(out) == 40
            # (b) sender linked the client id to a fresh window id.
            links = recorder.links()
            wids = [ln["child"] for ln in links
                    if ln["parent"] == f"{T:016x}"]
            assert len(wids) == 1
            W = wids[0]
            spans = recorder.dump()
            stages_under_w = {s["stage"] for s in spans
                              if f"{s['trace_id']:016x}" == W}
            # (a) receiver-side spans recorded under the window id (the
            # peer server runs in-process, so its rings are ours): its
            # io span at minimum, and (c) the sender's forward span.
            assert "io" in stages_under_w
            assert "forward" in stages_under_w
            # (d) bit-identical to an un-traced oracle run.
            from ratelimiter_tpu.algorithms.sketch import SketchLimiter

            oa, ob = SketchLimiter(cfg, clock), SketchLimiter(cfg, clock)
            want = np.zeros(40, dtype=bool)
            for host, oracle in ((0, oa), (1, ob)):
                pos = np.nonzero(owners == host)[0]
                if pos.shape[0]:
                    want[pos] = oracle.allow_ids(ids[pos]).allowed
            np.testing.assert_array_equal(out.allowed, want)
            for lim in (oa, ob):
                lim.close()
        finally:
            fwd.close()
            _stop(srv, loop, t)

    def test_recorder_off_no_trace_flag_on_wire(self):
        """Tracing off: the lane must not stamp TRACE_FLAG (wire bytes
        stay the PR 12 shape; window ids only exist under a recorder)."""
        assert tracing.RECORDER is None
        seen = []
        orig = p.with_trace

        def spy(frame, tid):
            seen.append(tid)
            return orig(frame, tid)

        clock = ManualClock(1000.0)
        cfg, fwd, core, server = self._fleet(clock)
        srv, loop, t = server
        try:
            p.with_trace = spy
            ids = np.arange(1, 41, dtype=np.uint64)
            fwd.allow_ids(ids)
            assert seen == []
        finally:
            p.with_trace = orig
            fwd.close()
            _stop(srv, loop, t)

    def test_untraced_frames_under_recorder_still_get_window_ids(
            self, recorder):
        """An UNSAMPLED frame (trace id 0) forwarded while the recorder
        runs still rides a window id — the receiver's spans stay
        joinable to the hop — but no parent link is recorded."""
        clock = ManualClock(1000.0)
        cfg, fwd, core, server = self._fleet(clock)
        srv, loop, t = server
        try:
            ids = np.arange(1, 41, dtype=np.uint64)
            fwd.allow_ids(ids)
            assert recorder.links() == []
            assert any(s["stage"] == "forward"
                       for s in recorder.dump())
        finally:
            fwd.close()
            _stop(srv, loop, t)


# ===================================================================
#                two-process control-tower composition
# ===================================================================


def _spawn_member(port, http_port, cfgpath, self_id, extra=()):
    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = os.pathsep.join(
        [repo] + env.get("PYTHONPATH", "").split(os.pathsep))
    env["JAX_PLATFORMS"] = "cpu"
    argv = [sys.executable, "-m", "ratelimiter_tpu.serving",
            "--backend", "sketch", "--sketch-depth", "2",
            "--sketch-width", "1024", "--sub-windows", "6",
            "--limit", "100", "--window", "60", "--max-batch", "256",
            "--no-prewarm", "--port", str(port),
            "--http-port", str(http_port),
            "--fleet-config", cfgpath, "--fleet-self", self_id,
            "--fleet-heartbeat", "0.2", "--fleet-dead-after", "30",
            "--fleet-forward-deadline", "20",
            "--flight-recorder", "--debug-token", "tok",
            "--audit", "--audit-sample", "1", "--hh-slots", "16",
            "--http-policy-token", "ptok",
            *extra]
    return subprocess.Popen(argv, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.DEVNULL, text=True)


def _get(url, token=None, timeout=10):
    req = urllib.request.Request(url)
    if token:
        req.add_header("Authorization", f"Bearer {token}")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _post(url, token=None, timeout=10):
    req = urllib.request.Request(url, method="POST")
    if token:
        req.add_header("Authorization", f"Bearer {token}")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


@pytest.mark.slow
class TestTwoMemberControlTower:
    """The acceptance scenario end to end, on two REAL server binaries:
    a traced client frame crosses the forwarding hop and appears on ONE
    stitched timeline under one trace id; /v1/fleet/status matches an
    offline merge of the members' tallies; a policy mutation on one
    member is readable from the other's /debug/events?fleet=1."""

    def _start_fleet(self, tmp_path):
        ports = [free_port(), free_port()]
        https = [free_port(), free_port()]
        fleet = {"buckets": 32, "epoch": 1, "hosts": [
            {"id": "h0", "host": "127.0.0.1", "port": ports[0],
             "http": https[0], "ranges": [[0, 16]]},
            {"id": "h1", "host": "127.0.0.1", "port": ports[1],
             "http": https[1], "ranges": [[16, 32]]},
        ]}
        cfgpath = os.path.join(tmp_path, "fleet.json")
        with open(cfgpath, "w", encoding="utf-8") as f:
            json.dump(fleet, f)
        procs = [_spawn_member(ports[i], https[i], cfgpath, f"h{i}")
                 for i in range(2)]
        for proc in procs:
            line = proc.stdout.readline()
            if "serving" not in line:
                for pr in procs:
                    pr.kill()
                raise RuntimeError(f"member failed to start: {line!r}")
        return procs, ports, https

    def test_control_tower_end_to_end(self, tmp_path):
        procs, ports, https = self._start_fleet(str(tmp_path))
        try:
            # Traffic: raw-id frames from h0, half the ids owned by h1
            # (forwarded), one frame traced.
            c = Client(port=ports[0])
            T = tracing.new_trace_id()
            ids = np.arange(1, 201, dtype=np.uint64)
            c.allow_hashed(ids, trace_id=T)
            c.allow_hashed(ids + 500)
            # Hot ids (repeated hits) so the hh side tables promote
            # consumers on BOTH members — the top-K merge then has
            # real mass to join. Promotion threshold is
            # limit x hh_promote_fraction (= 50 here), so ~60 allowed
            # hits per id, still under the limit of 100.
            hot = np.repeat(np.arange(1, 9, dtype=np.uint64), 10)
            for _ in range(6):
                c.allow_hashed(hot)
            c.close()
            # Let heartbeats measure clock offsets (>= 2 cycles each
            # way) and the auditors drain.
            time.sleep(1.5)

            # ---------------- stitched fleet trace
            merged = _get(f"http://127.0.0.1:{https[0]}/debug/trace"
                          f"?fleet=1", token="tok")
            hosts_meta = merged["otherData"]["hosts"]
            assert set(hosts_meta) == {"h0", "h1"}
            assert all(h["reachable"] for h in hosts_meta.values())
            assert all(h["aligned"] for h in hosts_meta.values())
            spans = [e for e in merged["traceEvents"]
                     if e.get("ph") == "X"]
            t_hex = f"{T:016x}"
            t_spans = [e for e in spans
                       if e["args"].get("trace_id") == t_hex]
            t_hosts = {e["args"]["host"] for e in t_spans}
            t_stages = {e["name"] for e in t_spans}
            # ONE trace id across the forwarding hop: sender io +
            # forward-lane wire span on h0, dispatch/device on h1.
            assert {"h0", "h1"} <= t_hosts
            assert "io" in t_stages and "forward" in t_stages
            assert "device" in t_stages
            h1_stages = {e["name"] for e in t_spans
                         if e["args"]["host"] == "h1"}
            assert "device" in h1_stages
            # The hop's spans carry the wire window id for joining.
            assert any("window_id" in e["args"] for e in t_spans)

            # ---------------- merged fleet status vs offline merge
            health = [
                _get(f"http://127.0.0.1:{hp}/healthz") for hp in https]
            st = _get(f"http://127.0.0.1:{https[1]}/v1/fleet/status")
            assert st["reachable"] == 2 and st["epoch_converged"]
            # Audit tallies: merged == sum of the members' own tallies,
            # Wilson recomputed over the merged n (hand merge here —
            # independent of the tower's merge code path inputs).
            fd = sum(h["audit"]["false_denies"] for h in health)
            oa = sum(h["audit"]["oracle_allows"] for h in health)
            n = sum(h["audit"]["samples"] for h in health)
            assert st["audit"]["samples"] == n > 0
            assert st["audit"]["false_denies"] == fd
            lo, hi = wilson_interval(fd, oa)
            assert st["audit"]["false_deny_wilson95"] == [
                round(lo, 8), round(hi, 8)]
            # Top-K: merged == offline token-join of the members' tops
            # (masses per token must agree exactly; ordering among
            # equal masses is unconstrained).
            by_tok = {}
            for h in health:
                for row in h.get("consumers", {}).get("top", ()):
                    by_tok[row["consumer"]] = by_tok.get(
                        row["consumer"], 0) + row["in_window"]
            assert by_tok, "hh promotion produced no consumers"
            got_top = {r["consumer"]: r["in_window"]
                       for r in st["consumers"]["top"]}
            assert got_top, "merged rollup dropped the consumers"
            # Every merged row's mass is exactly the offline token sum…
            assert all(by_tok.get(t) == m for t, m in got_top.items())
            # …and the merged rows are the offline merge's top masses.
            want_sorted = sorted(by_tok.values(), reverse=True)
            assert sorted(got_top.values(), reverse=True) == \
                want_sorted[:len(got_top)]
            # Member identity mirrored into the rollup rows.
            assert st["hosts"]["h0"]["member"]["door"] == "asyncio"
            assert st["hosts"]["h0"]["member"]["backend"] == "sketch"
            assert st["hosts"]["h0"]["member"]["fleet_epoch"] == 1

            # ---------------- fleet event journal
            # Mutate policy on h1; read it from h0's fleet merge.
            _post(f"http://127.0.0.1:{https[1]}/v1/policy"
                  f"?key=vip&limit=500", token="ptok")
            evs = _get(f"http://127.0.0.1:{https[0]}/debug/events"
                       f"?fleet=1&category=policy", token="tok")
            mine = [e for e in evs["events"]
                    if e["action"] == "set-override"]
            assert mine and mine[-1]["host"] == "h1"
            assert mine[-1]["payload"]["limit"] == 500
            assert "key_hash" in mine[-1]["payload"]
            assert "vip" not in json.dumps(mine[-1])   # PII boundary

            # ---------------- member_info gauge + healthz mirror
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{https[0]}/metrics",
                    timeout=10) as r:
                metrics = r.read().decode()
            assert "rate_limiter_member_info{" in metrics
            info_line = next(ln for ln in metrics.splitlines()
                             if ln.startswith(
                                 "rate_limiter_member_info{"))
            assert 'id="h0"' in info_line
            assert 'backend="sketch"' in info_line
            assert 'fleet_epoch="1"' in info_line
            assert 'door="asyncio"' in info_line
            assert health[0]["member"]["self"] == "h0"
            assert health[0]["member"]["abi"] == "py"
            # The gate holds fleet-wide: no token, no trace.
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(f"http://127.0.0.1:{https[0]}/debug/trace?fleet=1")
            assert ei.value.code == 403

            # ---------------- the operator CLIs (thin wrappers, but
            # their arg/IO plumbing is what an incident relies on)
            repo = os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))
            env = dict(os.environ)
            env["PYTHONPATH"] = os.pathsep.join(
                [repo] + env.get("PYTHONPATH", "").split(os.pathsep))
            trace_out = os.path.join(str(tmp_path), "trace.json")
            r = subprocess.run(
                [sys.executable, os.path.join(repo, "tools",
                                              "fleet_trace.py"),
                 f"http://127.0.0.1:{https[0]}", "--token", "tok",
                 "-o", trace_out],
                env=env, capture_output=True, text=True, timeout=60)
            assert r.returncode == 0, r.stdout + r.stderr
            assert "trace ids crossing hosts" in r.stdout
            with open(trace_out, encoding="utf-8") as f:
                assert json.load(f)["traceEvents"]
            r = subprocess.run(
                [sys.executable, os.path.join(repo, "tools",
                                              "fleet_status.py"),
                 f"http://127.0.0.1:{https[1]}", "--offline"],
                env=env, capture_output=True, text=True, timeout=60)
            assert r.returncode == 0, r.stdout + r.stderr
            assert "2/2 members reachable" in r.stdout
            assert "audit (merged over" in r.stdout
        finally:
            for pr in procs:
                pr.terminate()
            for pr in procs:
                try:
                    pr.wait(timeout=15)
                except subprocess.TimeoutExpired:
                    pr.kill()
