"""Pipelined serving hot path (ADR-010): launch/resolve dispatch API.

The load-bearing invariant: sequential per-key semantics SURVIVE overlap.
With up to N dispatches in flight, every decision must equal what the old
launch→block→serialize path would have produced — state threading via
donated buffers (each launch consumes the previous launch's state) is
what carries the ordering, and these tests pin it against the
single-dispatch oracle decision-for-decision. Plus: snapshots taken while
dispatches are in flight must capture a consistent (fully applied) state,
the staging-buffer pool must actually recycle, and the pipelined path
must not be slower than the synchronous one on the CPU harness (the
pinned smoke CI runs).
"""

from __future__ import annotations

import asyncio
import time

import numpy as np
import pytest

from ratelimiter_tpu import (
    Algorithm,
    Config,
    ManualClock,
    SketchParams,
    StorageUnavailableError,
    create_limiter,
)
from ratelimiter_tpu.observability import MetricsDecorator, Registry
from ratelimiter_tpu.serving import MicroBatcher

T0 = 1_700_000_000.0


def _mk(limit=5, algo=Algorithm.SLIDING_WINDOW, backend="sketch", **kw):
    cfg = Config(algorithm=algo, limit=limit, window=60.0,
                 sketch=SketchParams(depth=3, width=512, sub_windows=6),
                 **kw)
    return create_limiter(cfg, backend=backend, clock=ManualClock(T0))


# ------------------------------------------------------ limiter-level API

class TestLaunchResolve:
    def test_interleaved_same_key_matches_single_dispatch_oracle(self):
        """K batches of the same hot key launched back to back WITHOUT
        resolving in between must decide exactly like the synchronous
        path: the 6th unit request on a limit-5 key is denied no matter
        which in-flight window it rode in."""
        lim, oracle = _mk(limit=5), _mk(limit=5)
        frames = [["hot", "hot"], ["hot", "cold"], ["hot", "hot"],
                  ["cold", "hot"]]
        tickets = [lim.launch_batch(f) for f in frames]     # all in flight
        piped = [lim.resolve(t).allowed.tolist() for t in tickets]
        want = [oracle.allow_batch(f).allowed.tolist() for f in frames]
        assert piped == want
        lim.close()
        oracle.close()

    def test_resolve_order_does_not_matter(self):
        """Resolving newest-first returns the same per-ticket decisions:
        ordering lives in the device-side state chain, not in the resolve
        calls."""
        lim, oracle = _mk(limit=3), _mk(limit=3)
        frames = [["k"], ["k"], ["k"], ["k"], ["k"]]
        tickets = [lim.launch_batch(f) for f in frames]
        for t in reversed(tickets):
            lim.resolve(t)
        got = [bool(t.result.allowed[0]) for t in tickets]
        want = [bool(oracle.allow_batch(f).allowed[0]) for f in frames]
        assert got == want == [True, True, True, False, False]
        lim.close()
        oracle.close()

    def test_resolve_is_idempotent(self):
        lim = _mk()
        t = lim.launch_batch(["a"])
        first = lim.resolve(t)
        assert lim.resolve(t) is first
        lim.close()

    def test_token_bucket_pipelined_matches_oracle(self):
        lim = _mk(limit=4, algo=Algorithm.TOKEN_BUCKET)
        oracle = _mk(limit=4, algo=Algorithm.TOKEN_BUCKET)
        frames = [["k", "k"], ["k", "k"], ["k"]]
        tickets = [lim.launch_batch(f) for f in frames]
        got = [lim.resolve(t).allowed.tolist() for t in tickets]
        want = [oracle.allow_batch(f).allowed.tolist() for f in frames]
        assert got == want
        # Device-computed retry matches too (finish kernel parity).
        t_deny = lim.launch_batch(["k"])
        o_deny = oracle.allow_batch(["k"])
        r = lim.resolve(t_deny)
        assert r.retry_after[0] == pytest.approx(o_deny.retry_after[0])
        assert r.reset_at[0] == pytest.approx(o_deny.reset_at[0])
        lim.close()
        oracle.close()

    def test_device_side_retry_reset_match_legacy_values(self):
        """The finish kernels moved retry/reset math onto the device; the
        values must be bit-identical in meaning to the host formulas:
        retry = time to window reset for denied, 0 for allowed."""
        lim = _mk(limit=2)
        out = lim.resolve(lim.launch_batch(["x", "x", "x"]))
        assert out.allowed.tolist() == [True, True, False]
        assert out.retry_after[0] == 0.0 and out.retry_after[1] == 0.0
        assert out.retry_after[2] == pytest.approx(60.0 - (T0 % 60.0))
        assert np.all(out.reset_at == out.reset_at[0])
        assert out.remaining.dtype == np.int64
        lim.close()

    def test_staging_buffers_recycle(self):
        """Launch→resolve→launch at one batch shape reuses the SAME
        staging arrays (the per-dispatch np.zeros allocations are gone);
        overlapping launches get distinct buffers."""
        lim = _mk(limit=1000)
        t1 = lim.launch_batch(["a", "b"])
        ids_first = [id(a) for a in t1.slot]
        t2 = lim.launch_batch(["c", "d"])       # in flight with t1
        ids_second = [id(a) for a in t2.slot]
        assert ids_second != ids_first
        lim.resolve(t1)
        lim.resolve(t2)
        t3 = lim.launch_batch(["e", "f"])       # recycled from the pool
        assert [id(a) for a in t3.slot] in (ids_first, ids_second)
        lim.resolve(t3)
        lim.close()

    def test_launch_fail_open_and_fail_closed(self):
        lim = _mk(limit=5, fail_open=True)
        lim.resolve(lim.launch_batch(["warm", "up"]))   # seed the pool
        pool = sum(len(v) for v in lim._staging.values())
        lim.inject_failure()
        for _ in range(3):
            t = lim.launch_batch(["x", "y"])
            out = lim.resolve(t)
            assert out.fail_open and out.allowed.all()
        # Failed launches must return their staging slot to the pool —
        # a leak here re-introduces the per-dispatch allocations under
        # exactly the failure windows fail-open exists for.
        assert sum(len(v) for v in lim._staging.values()) == pool
        assert lim._inflight_mass == 0
        lim.heal()
        lim.close()

        lim2 = _mk(limit=5, fail_open=False)
        lim2.inject_failure()
        with pytest.raises(StorageUnavailableError):
            lim2.launch_batch(["x"])
        lim2.close()

    def test_exact_backend_pre_resolves(self):
        """Backends without an async device path answer at launch via the
        base fallback, so callers can use one API everywhere."""
        lim, _ = ( _mk(limit=2, backend="exact"), None)
        assert lim.pipelined is False
        t = lim.launch_batch(["k", "k", "k"])
        assert t.resolved
        assert lim.resolve(t).allowed.tolist() == [True, True, False]
        lim.close()

    def test_decorated_limiter_routes_launch_to_backend(self):
        """A decorator stack must delegate launch_batch to the backend's
        real pipelined path (not the base eager fallback) and observe the
        batch once, at resolve."""
        reg = Registry()
        lim = MetricsDecorator(_mk(limit=3), registry=reg)
        assert lim.pipelined is True
        t = lim.launch_batch(["k", "k", "k", "k"])
        assert not t.resolved                    # genuinely deferred
        out = lim.resolve(t)
        assert out.allowed.tolist() == [True, True, True, False]
        assert reg.get("rate_limiter_requests_total").value(
            algorithm="sliding_window", result="mixed") == 4.0
        lim.close()


    def test_strict_overload_gate_counts_inflight_mass(self):
        """overload_policy='strict' must not be dilutable by the
        pipeline: launched-but-unresolved mass counts against the
        accuracy budget at full offered weight, so a deep in-flight
        window cannot slip inflight*max_batch admissions past the gate
        before any resolve lands."""
        cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=5,
                     window=60.0,
                     sketch=SketchParams(depth=3, width=256, sub_windows=6,
                                         overload_policy="strict"))
        lim = create_limiter(cfg, backend="sketch",
                             clock=ManualClock(T0))
        budget = lim.mass_budget            # 2560 at this geometry
        n = budget // 2 + 1
        t1 = lim.launch_batch([f"a{i}" for i in range(n)])
        t2 = lim.launch_batch([f"b{i}" for i in range(n)])
        # Neither resolved yet: in-flight offered mass 2n > budget, so
        # the NEXT launch must deny-all without dispatching.
        t3 = lim.launch_batch(["c"])
        assert t3.resolved and not t3.result.allowed.any()
        assert lim.overload_periods >= 1
        # The legitimately launched work still resolves normally.
        assert lim.resolve(t1).allowed.all()
        assert lim.resolve(t2).allowed.all()
        # In-flight pessimism fully replaced by confirmed mass (no leak).
        assert lim._inflight_mass == 0
        assert lim.in_window_admitted_mass() == 2 * n
        lim.close()


# ------------------------------------------------------- snapshot quiesce

class TestSnapshotDuringInflight:
    def test_capture_waits_for_inflight_launches(self, tmp_path):
        """capture_state while dispatches are in flight must quiesce the
        pipeline: the data dependence on the donated state chain means
        the captured arrays reflect EVERY launched step. Restoring the
        snapshot into a fresh limiter reproduces the post-launch
        counters exactly."""
        lim = _mk(limit=10)
        t1 = lim.launch_batch(["hot"] * 4)
        t2 = lim.launch_batch(["hot"] * 4)
        path = str(tmp_path / "mid.npz")
        lim.save(path)                       # capture with both in flight
        # The tickets still resolve correctly after the capture.
        assert lim.resolve(t1).allowed.tolist() == [True] * 4
        assert lim.resolve(t2).allowed.tolist() == [True] * 4

        restored = _mk(limit=10)
        restored.restore(path)
        # 8 units consumed in the snapshot: exactly 2 admits left.
        out = restored.allow_batch(["hot"] * 4)
        assert out.allowed.tolist() == [True, True, False, False]
        lim.close()
        restored.close()


# --------------------------------------------------- pipelined MicroBatcher

def _run(coro):
    return asyncio.run(coro)


class TestPipelinedBatcher:
    def test_interleaved_frames_match_oracle(self):
        """Same-key frames submitted through the pipelined micro-batcher
        (inflight=4) decide exactly like sequential single dispatches on
        a fresh limiter — coalescing and overlap change the batching, not
        the decisions."""
        lim, oracle = _mk(limit=7), _mk(limit=7)
        frames = [["hot", "a", "hot"], ["hot", "hot"], ["b", "hot"],
                  ["hot", "hot", "hot"]]

        async def drive():
            b = MicroBatcher(lim, max_batch=4096, max_delay=1e-3,
                             inflight=4, registry=Registry())
            assert b._pipelined
            futs = []
            for f in frames:
                futs.extend(b.submit_many_nowait((k, 1) for k in f))
            res = await asyncio.gather(*futs)
            await b.drain()
            b.close()
            return [r.allowed for r in res]

        got = _run(drive())
        want = [r.allowed
                for f in frames for r in oracle.allow_batch(f).results()]
        assert got == want
        lim.close()
        oracle.close()

    def test_inflight_gauge_and_phase_histograms(self):
        reg = Registry()
        lim = _mk(limit=100000)

        async def drive():
            b = MicroBatcher(lim, max_batch=64, max_delay=1e-4,
                             inflight=4, registry=reg)
            futs = [b.submit_nowait(f"k{i}") for i in range(256)]
            await asyncio.gather(*futs)
            await b.drain()
            b.close()

        _run(drive())
        assert reg.get("rate_limiter_pipeline_launch_seconds").count() >= 4
        assert reg.get("rate_limiter_pipeline_resolve_seconds").count() >= 4
        # Every launch resolved: the gauge is back to zero.
        assert reg.get("rate_limiter_pipeline_inflight").value() == 0.0
        lim.close()

    def test_non_pipelined_backend_uses_legacy_path(self):
        lim, _ = _mk(limit=3, backend="exact"), None

        async def drive():
            b = MicroBatcher(lim, max_batch=16, max_delay=1e-4,
                             inflight=8, registry=Registry())
            assert not b._pipelined
            out = await asyncio.gather(*[b.submit_nowait("k")
                                         for _ in range(5)])
            await b.drain()
            b.close()
            return [r.allowed for r in out]

        assert _run(drive()) == [True, True, True, False, False]
        lim.close()

    def test_slo_disables_pipelining(self):
        """Pipelining and the dispatch SLO are mutually exclusive (same
        rule as the native door): a launch blocked on a full window sits
        outside any wait_for, so its waiters could hang past the SLO."""
        lim = _mk(limit=10)
        b = MicroBatcher(lim, dispatch_timeout=0.5, inflight=8,
                         registry=Registry())
        assert not b._pipelined
        b.close()
        lim.close()

    def test_adaptive_rearm_triggers_on_mark_crossing(self):
        """Batch frames jump the queue depth by whole frames; the
        adaptive re-arm must fire on threshold CROSSINGS, not exact
        matches (a 20-deep frame hops straight over the depth-8 and
        depth-16 marks)."""
        lim = _mk(limit=100000)

        async def drive():
            b = MicroBatcher(lim, max_batch=64, max_delay=50e-3,
                             inflight=4, registry=Registry())
            assert b._adaptive_marks == [8, 16, 32, 48]
            futs = b.submit_many_nowait((f"k{i}", 1) for i in range(4))
            assert b._armed_depth == 4            # initial arm
            futs += b.submit_many_nowait((f"j{i}", 1) for i in range(20))
            # The second frame jumped the depth 4 -> 24, CROSSING the 8
            # and 16 marks without ever equalling one: the timer must
            # have been re-armed (armed_depth tracked the crossing).
            assert b._armed_depth == 24
            res = await asyncio.gather(*futs)
            await b.drain()
            b.close()
            return res

        assert all(r.allowed for r in _run(drive()))
        lim.close()

    def test_adaptive_delay_keeps_decisions_exact(self):
        """The queue-depth-aware timer re-arm must not drop or duplicate
        a request: N submissions crossing several adaptive marks all
        resolve, and a limit-L key admits exactly L."""
        lim = _mk(limit=50)

        async def drive():
            b = MicroBatcher(lim, max_batch=64, max_delay=5e-3,
                             inflight=4, adaptive_delay=True,
                             registry=Registry())
            futs = [b.submit_nowait("hot") for _ in range(120)]
            res = await asyncio.gather(*futs)
            await b.drain()
            b.close()
            return res

        res = _run(drive())
        assert len(res) == 120 and sum(r.allowed for r in res) == 50
        lim.close()


# ----------------------------------------------------- pinned smoke (CI)

class TestPipelineSmoke:
    def test_pipelined_not_slower_than_sync_on_cpu(self):
        """Pinned throughput smoke: the pipelined launch/resolve path
        (window 8) must not be slower than the synchronous path on the
        CPU harness. The margin absorbs scheduler noise on shared CI
        boxes — the claim guarded is 'pipelining is free when overlap
        buys nothing', not a speedup."""
        from ratelimiter_tpu.ops.hashing import splitmix64

        lim = _mk(limit=1 << 20)
        rng = np.random.default_rng(0)
        h = splitmix64(rng.integers(1, 1 << 40, size=512, dtype=np.uint64))
        reps = 60
        lim.allow_hashed(h, now=T0)                      # compile

        t0 = time.perf_counter()
        for i in range(reps):
            lim.allow_hashed(h, now=T0 + i * 1e-3)
        sync_s = time.perf_counter() - t0

        window: list = []
        t0 = time.perf_counter()
        for i in range(reps):
            if len(window) >= 8:
                lim.resolve(window.pop(0))
            window.append(lim.launch_hashed(h, now=T0 + (reps + i) * 1e-3))
        while window:
            lim.resolve(window.pop(0))
        piped_s = time.perf_counter() - t0

        assert piped_s <= sync_s * 1.5, (
            f"pipelined path regressed: {piped_s:.4f}s vs sync "
            f"{sync_s:.4f}s over {reps} dispatches")
        lim.close()
