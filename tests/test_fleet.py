"""Fleet tier tests (ADR-017): ownership map, consistent-hash routing,
cross-host forwarding, typed redirects, membership + per-range failover.

The correctness bar mirrors the mesh serving tier's (ADR-012): fleet
decisions must be BIT-IDENTICAL to a single-host oracle fed each host's
owned rows in arrival order — under affine routing, under mis-routed
(server-side forwarded) traffic, and after failover — including same-key
ordering across a forwarding hop. Deterministic halves run fully
in-process on a ManualClock; process-level halves spawn real servers
through both front doors.
"""

from __future__ import annotations

import asyncio
import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from netutil import free_port

from ratelimiter_tpu import Algorithm, Config, SketchParams
from ratelimiter_tpu.core.clock import ManualClock
from ratelimiter_tpu.core.errors import (
    InvalidConfigError,
    NotOwnerError,
    StorageUnavailableError,
)
from ratelimiter_tpu.fleet import (
    FleetCore,
    FleetForwarder,
    FleetMap,
    FleetMembership,
    affine_map,
)
from ratelimiter_tpu.ops.hashing import splitmix64, splitmix64_inv
from ratelimiter_tpu.serving import protocol as p

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cfg(limit=20, window=600.0, **kw):
    return Config(algorithm=Algorithm.TPU_SKETCH, limit=limit,
                  window=window,
                  sketch=SketchParams(depth=4, width=4096, sub_windows=6),
                  **kw)


def _two_host_map(port_a=1, port_b=2, buckets=32):
    return FleetMap.from_dict({
        "buckets": buckets, "epoch": 1, "hosts": [
            {"id": "a", "host": "127.0.0.1", "port": port_a,
             "ranges": [[0, buckets // 2]], "successor": "b"},
            {"id": "b", "host": "127.0.0.1", "port": port_b,
             "ranges": [[buckets // 2, buckets]], "successor": "a"},
        ]})


# ===================================================================
#                             unit layer
# ===================================================================


class TestFleetMap:
    def test_round_trip_and_owner_table(self):
        m = _two_host_map()
        m2 = FleetMap.from_dict(m.to_dict())
        assert m2 == m
        t = m.owner_table
        assert t.shape == (32,)
        assert (t[:16] == 0).all() and (t[16:] == 1).all()
        h = np.arange(100, dtype=np.uint64)
        assert (m.owner_of_hash(h) == t[h % 32]).all()

    def test_validation_rejects_holes_and_overlaps(self):
        with pytest.raises(InvalidConfigError, match="uncovered"):
            FleetMap.from_dict({"buckets": 8, "hosts": [
                {"id": "a", "host": "h", "port": 1, "ranges": [[0, 4]]}]})
        with pytest.raises(InvalidConfigError, match="doubly-owned"):
            FleetMap.from_dict({"buckets": 8, "hosts": [
                {"id": "a", "host": "h", "port": 1, "ranges": [[0, 6]]},
                {"id": "b", "host": "h", "port": 2, "ranges": [[4, 8]]}]})
        with pytest.raises(InvalidConfigError, match="unknown successor"):
            FleetMap.from_dict({"buckets": 8, "hosts": [
                {"id": "a", "host": "h", "port": 1, "ranges": [[0, 8]],
                 "successor": "ghost"}]})
        with pytest.raises(InvalidConfigError, match="own successor"):
            FleetMap.from_dict({"buckets": 8, "hosts": [
                {"id": "a", "host": "h", "port": 1, "ranges": [[0, 8]],
                 "successor": "a"}]})

    def test_reassign_moves_ranges_and_bumps_epoch(self):
        m = _two_host_map()
        m2 = m.reassign("a", "b")
        assert m2.epoch == m.epoch + 1
        assert m2.host("a").ranges == ()
        assert m2.owned_buckets("b") == 32
        # Dead host keeps identity (rejoin is an operator action).
        assert m2.host("a").host == "127.0.0.1"
        # Idempotent on an already-empty host.
        assert m2.reassign("a", "b") is m2

    def test_affine_map_shape(self):
        m = affine_map([("h", 1), ("h", 2), ("h", 3)])
        assert m.buckets == 48
        assert sum(m.owned_buckets(h.id) for h in m.hosts) == 48
        assert m.host("h0").successor == "h1"
        assert m.host("h2").successor == "h0"


class TestSplitmixInverse:
    def test_round_trip_fuzz(self):
        rng = np.random.default_rng(7)
        x = rng.integers(0, 1 << 64, size=200_000, dtype=np.uint64)
        assert (splitmix64_inv(splitmix64(x)) == x).all()
        assert (splitmix64(splitmix64_inv(x)) == x).all()

    def test_edge_values(self):
        edges = np.array([0, 1, (1 << 64) - 1, 0x9E3779B97F4A7C15],
                         dtype=np.uint64)
        assert (splitmix64_inv(splitmix64(edges)) == edges).all()


class TestNotOwnerProtocol:
    def test_format_parse_round_trip(self):
        msg = p.format_not_owner(3, "b@10.0.0.2:9001", 7, 64)
        assert p.parse_not_owner(msg) == {
            "bucket": 3, "owner": "b@10.0.0.2:9001", "epoch": 7,
            "buckets": 64}
        assert p.parse_not_owner("storage unavailable") is None
        assert p.parse_not_owner("not owner: garbage") is None

    def test_exception_for_builds_typed_redirect(self):
        msg = p.format_not_owner(1, "b@h:2", 9, 8)
        exc = p.exception_for(p.E_NOT_OWNER, msg)
        assert isinstance(exc, NotOwnerError)
        assert exc.owner == "b@h:2" and exc.epoch == 9
        assert p.code_for(exc) == p.E_NOT_OWNER


class TestFleetFrames:
    def test_fleet_map_frame_round_trip(self):
        m = _two_host_map()
        frame = p.encode_fleet_map_r(5, m.to_dict())
        length, type_, rid = p.parse_header(frame[:p.HEADER_SIZE])
        assert type_ == p.T_FLEET_MAP_R and rid == 5
        assert FleetMap.from_dict(
            p.parse_fleet_map_r(frame[p.HEADER_SIZE:])) == m

    def test_announce_rides_authenticated_dcn(self):
        from ratelimiter_tpu.serving.dcn_peer import merge_push_payload

        payload = {"kind": "announce", "from": "a",
                   "map": _two_host_map().to_dict()}
        frame = p.encode_dcn_fleet(1, payload, secret="s", sender=7,
                                   seq=10_000_000_000_000_000)
        body = frame[p.HEADER_SIZE:]
        got = []
        guard = p.DcnReplayGuard(time_fn=lambda: 1e10)
        merge_push_payload([], body, "s", guard, got.append)
        assert got == [payload]
        # Replay of the same sequence is rejected before dispatch.
        with pytest.raises(InvalidConfigError, match="replayed"):
            merge_push_payload([], body, "s", guard, got.append)
        # Wrong secret never reaches the membership.
        with pytest.raises(InvalidConfigError, match="auth tag"):
            merge_push_payload([], body, "wrong", None, got.append)
        assert len(got) == 1

    def test_fleet_frame_without_membership_is_typed_error(self):
        from ratelimiter_tpu.serving.dcn_peer import merge_push_payload

        frame = p.encode_dcn_fleet(1, {"kind": "announce", "from": "x",
                                       "map": {}})
        with pytest.raises(InvalidConfigError, match="not a fleet member"):
            merge_push_payload([], frame[p.HEADER_SIZE:], None, None, None)


class TestFleetCoreSplit:
    def _core(self, forward=True):
        from ratelimiter_tpu.observability.metrics import Registry

        return FleetCore(_two_host_map(), "a", prefix="ratelimit",
                         forward=forward, registry=Registry())

    def test_split_partitions_and_preserves_order(self):
        core = self._core()
        h = np.arange(200, dtype=np.uint64)
        owners = core.owners_of_hash(h)
        local, adopted, foreign = core.split(h, owners)
        assert adopted.shape[0] == 0
        assert set(local.tolist()) == set(
            np.nonzero(owners == 0)[0].tolist())
        assert list(foreign) == [1]
        pos = foreign[1]
        # Frame order preserved within the forwarded group.
        assert (np.diff(pos) > 0).all()
        assert (owners[pos] == 1).all()

    def test_all_local_fast_path(self):
        core = self._core()
        h = np.arange(500, dtype=np.uint64)
        owners = core.owners_of_hash(h)
        mine = h[owners == 0]
        assert core.all_local(core.owners_of_hash(mine))
        assert not core.all_local(owners)

    def test_redirect_error_names_owner_and_epoch(self):
        core = self._core(forward=False)
        h = np.arange(64, dtype=np.uint64)
        with pytest.raises(NotOwnerError) as ei:
            core.check_frame_owned(h)
        assert ei.value.epoch == 1
        assert "b@127.0.0.1:2" in str(ei.value)

    def test_forward_queue_bound(self):
        """A slow/unresponsive peer cannot buffer unbounded: once
        ``forward_queue`` fragments are outstanding, the next submit's
        future carries the typed overflow error IMMEDIATELY (ADR-019:
        the lane never raises at submit so sibling connections' rows
        still decide; the overflow rows answer per policy)."""
        import socket

        sink = socket.socket()
        sink.bind(("127.0.0.1", 0))
        sink.listen(8)  # accepts, never answers
        port = sink.getsockname()[1]
        m = FleetMap.from_dict({
            "buckets": 4, "hosts": [
                {"id": "a", "host": "127.0.0.1", "port": 1,
                 "ranges": [[0, 2]]},
                {"id": "b", "host": "127.0.0.1", "port": port,
                 "ranges": [[2, 4]]}]})
        from ratelimiter_tpu.observability.metrics import Registry

        core = FleetCore(m, "a", forward_deadline=5.0, forward_queue=1,
                         registry=Registry())
        try:
            # First fragment is in flight against the silent peer, the
            # second fills the outstanding allowance, the third
            # overflows without waiting on the peer.
            core.forward_ids(1, np.asarray([2], np.uint64),
                             np.asarray([1]))
            time.sleep(0.2)
            core.forward_ids(1, np.asarray([2], np.uint64),
                             np.asarray([1]))
            fut = core.forward_ids(1, np.asarray([2], np.uint64),
                                   np.asarray([1]))
            with pytest.raises(StorageUnavailableError, match="full"):
                fut.result(timeout=1.0)
        finally:
            core.close()
            sink.close()


# ===================================================================
#             deterministic in-process fleet (ManualClock)
# ===================================================================


def _server_on_thread(limiter, fleet=None, fleet_announce=None):
    from ratelimiter_tpu.serving import RateLimitServer

    loop = asyncio.new_event_loop()
    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()
    srv = RateLimitServer(limiter, "127.0.0.1", 0, dcn=True,
                          fleet=fleet, fleet_announce=fleet_announce)
    asyncio.run_coroutine_threadsafe(srv.start(), loop).result(10)
    return srv, loop, t


def _stop(srv, loop, t):
    asyncio.run_coroutine_threadsafe(srv.shutdown(), loop).result(10)
    loop.call_soon_threadsafe(loop.stop)
    t.join(timeout=10)
    loop.close()


class TestInProcessFleetOracle:
    """Host A = FleetForwarder over a local slice; host B = a REAL
    asyncio server on a background loop. One shared ManualClock makes
    every decision deterministic, so the oracle comparison is
    bit-identical, not statistical."""

    def _fleet(self, clock, limit=20):
        from ratelimiter_tpu.algorithms.sketch import SketchLimiter
        from ratelimiter_tpu.observability.metrics import Registry

        cfg = _cfg(limit=limit)
        lim_a = SketchLimiter(cfg, clock)
        lim_b = SketchLimiter(cfg, clock)
        srv, loop, t = _server_on_thread(lim_b)
        m = _two_host_map(port_b=srv.port)
        core = FleetCore(m, "a", prefix=cfg.prefix,
                         forward_deadline=30.0, registry=Registry())
        fwd = FleetForwarder(lim_a, core)
        oracle_a = SketchLimiter(cfg, clock)
        oracle_b = SketchLimiter(cfg, clock)
        return cfg, fwd, core, (srv, loop, t), (oracle_a, oracle_b)

    def test_mixed_string_frames_bit_identical_to_oracle(self):
        clock = ManualClock(1000.0)
        cfg, fwd, core, server, (oa, ob) = self._fleet(clock)
        srv, loop, t = server
        try:
            rng = np.random.default_rng(3)
            keys_pool = [f"user:{i}" for i in range(40)]
            for frame_i in range(12):
                keys = [keys_pool[j] for j in
                        rng.integers(0, 40, size=25)]
                ns = rng.integers(1, 3, size=25).tolist()
                got = fwd.allow_batch(keys, ns)
                # Oracle: each host's owned rows, in frame order.
                owners = core.owners_of_hash(core.hash_keys(keys))
                want_allowed = np.zeros(25, dtype=bool)
                want_remaining = np.zeros(25, dtype=np.int64)
                for host, oracle in ((0, oa), (1, ob)):
                    pos = np.nonzero(owners == host)[0]
                    if not pos.shape[0]:
                        continue
                    out = oracle.allow_batch([keys[i] for i in pos],
                                             [ns[i] for i in pos])
                    want_allowed[pos] = out.allowed
                    want_remaining[pos] = out.remaining
                np.testing.assert_array_equal(got.allowed, want_allowed)
                np.testing.assert_array_equal(got.remaining,
                                              want_remaining)
                if frame_i == 7:
                    clock.advance(cfg.window / 6)  # cross a sub-window
        finally:
            fwd.close()
            _stop(srv, loop, t)

    def test_raw_id_frames_bit_identical_to_oracle(self):
        clock = ManualClock(1000.0)
        cfg, fwd, core, server, (oa, ob) = self._fleet(clock, limit=10)
        srv, loop, t = server
        try:
            rng = np.random.default_rng(5)
            for _ in range(8):
                ids = rng.integers(0, 64, size=100).astype(np.uint64)
                got = fwd.allow_ids(ids)
                owners = core.owners_of_ids(ids)
                want_allowed = np.zeros(100, dtype=bool)
                want_remaining = np.zeros(100, dtype=np.int64)
                for host, oracle in ((0, oa), (1, ob)):
                    pos = np.nonzero(owners == host)[0]
                    if not pos.shape[0]:
                        continue
                    out = oracle.allow_ids(ids[pos])
                    want_allowed[pos] = out.allowed
                    want_remaining[pos] = out.remaining
                np.testing.assert_array_equal(got.allowed, want_allowed)
                np.testing.assert_array_equal(got.remaining,
                                              want_remaining)
        finally:
            fwd.close()
            _stop(srv, loop, t)

    def test_same_key_ordering_across_forwarding_hop(self):
        """A key owned by host B, driven ONLY through host A's
        forwarder: the first `limit` units are allowed, every later one
        denied, and remaining decreases strictly in send order — the
        per-peer FIFO channel preserves cross-host sequencing."""
        clock = ManualClock(1000.0)
        cfg, fwd, core, server, _ = self._fleet(clock, limit=10)
        srv, loop, t = server
        try:
            key = next(f"k:{i}" for i in range(100)
                       if int(core.owners_of_hash(
                           core.hash_keys([f"k:{i}"]))[0]) == 1)
            seq = [fwd.allow_n(key, 1) for _ in range(15)]
            assert [r.allowed for r in seq] == [True] * 10 + [False] * 5
            assert [r.remaining for r in seq[:10]] == list(range(9, -1, -1))
        finally:
            fwd.close()
            _stop(srv, loop, t)

    def test_forward_failure_degrades_per_policy(self):
        from ratelimiter_tpu.algorithms.sketch import SketchLimiter
        from ratelimiter_tpu.observability.metrics import Registry

        clock = ManualClock(1000.0)
        dead = free_port()
        # fail-open: foreign rows answer fail_open allowances.
        cfg = _cfg(limit=10, fail_open=True)
        lim = SketchLimiter(cfg, clock)
        core = FleetCore(_two_host_map(port_b=dead), "a",
                         prefix=cfg.prefix, forward_deadline=0.3,
                         registry=Registry())
        fwd = FleetForwarder(lim, core)
        try:
            ids = np.arange(40, dtype=np.uint64)
            out = fwd.allow_ids(ids)
            foreign = core.owners_of_ids(ids) == 1
            assert out.fail_open
            assert out.allowed[foreign].all()
        finally:
            fwd.close()
        # fail-closed: the frame errors (typed).
        cfg2 = _cfg(limit=10, fail_open=False)
        lim2 = SketchLimiter(cfg2, clock)
        core2 = FleetCore(_two_host_map(port_b=dead), "a",
                          prefix=cfg2.prefix, forward_deadline=0.3,
                          registry=Registry())
        fwd2 = FleetForwarder(lim2, core2)
        try:
            with pytest.raises(StorageUnavailableError):
                fwd2.allow_ids(np.arange(40, dtype=np.uint64))
        finally:
            fwd2.close()

    def test_redirect_only_door_answers_typed_not_owner(self):
        """A fleet server with forwarding OFF answers foreign frames
        with E_NOT_OWNER at the door — parsed back into the typed
        exception by the client."""
        from ratelimiter_tpu.algorithms.sketch import SketchLimiter
        from ratelimiter_tpu.observability.metrics import Registry
        from ratelimiter_tpu.serving.client import Client

        clock = ManualClock(1000.0)
        cfg = _cfg()
        lim_b = SketchLimiter(cfg, clock)
        m = _two_host_map()
        core_b = FleetCore(m, "b", prefix=cfg.prefix, forward=False,
                           registry=Registry())
        srv, loop, t = _server_on_thread(
            FleetForwarder(lim_b, core_b), fleet=core_b)
        try:
            with Client(port=srv.port, timeout=10) as c:
                # A key owned by host a, sent to host b.
                key = next(f"k:{i}" for i in range(100)
                           if int(core_b.owners_of_hash(
                               core_b.hash_keys([f"k:{i}"]))[0]) == 0)
                with pytest.raises(NotOwnerError) as ei:
                    c.allow(key)
                assert ei.value.epoch == 1
                assert "a@" in str(ei.value)
                # And the map is fetchable for re-routing.
                assert FleetMap.from_dict(c.fleet_map()).epoch == 1
        finally:
            _stop(srv, loop, t)


class TestFleetClientFanOut:
    def test_failed_legs_retry_only_and_repartition(self, monkeypatch):
        """The fan-out retry contract (review hardening): a failed leg
        refreshes the map ONCE and retries ONLY its rows, re-partitioned
        under the fresh owner table — successful legs are never re-sent
        (a whole-frame retry would double-charge healthy owners)."""
        from ratelimiter_tpu.serving.client import FleetClient

        fc = FleetClient(_two_host_map().to_dict())
        owners = np.array([0] * 5 + [1] * 5)
        state = {"refreshed": False}
        calls = []

        def owners_of(rows):
            got = owners[rows]
            if state["refreshed"]:
                # Epoch 2: host 1's rows failed over to host 0.
                got = np.zeros_like(got)
            return got

        def call(o, rows):
            calls.append((o, tuple(rows.tolist()), state["refreshed"]))
            if o == 1 and not state["refreshed"]:
                raise ConnectionError("down")
            return [("ok", int(i)) for i in rows]

        monkeypatch.setattr(
            fc, "_refresh_from_error",
            lambda exc: state.update(refreshed=True) or True)
        try:
            parts = fc._fan_out_rows(10, owners_of, call)
        finally:
            fc.close()
        answered = sorted(i for rows, out in parts
                          for i in rows.tolist())
        assert answered == list(range(10))
        # Host 0's original leg sent exactly once, pre-refresh.
        first_leg = [c for c in calls if c[1] == (0, 1, 2, 3, 4)]
        assert first_leg == [(0, (0, 1, 2, 3, 4), False)]
        # The failed rows re-sent once, to the NEW owner, post-refresh.
        assert (0, (5, 6, 7, 8, 9), True) in calls
        assert len(calls) == 3  # no whole-frame resend

    def test_bounded_retry_raises_after_second_failure(self, monkeypatch):
        from ratelimiter_tpu.serving.client import FleetClient

        fc = FleetClient(_two_host_map().to_dict())
        monkeypatch.setattr(fc, "_refresh_from_error", lambda exc: True)

        def call(o, rows):
            raise ConnectionError("forever down")

        try:
            with pytest.raises(ConnectionError):
                fc._fan_out_rows(4, lambda rows: np.zeros(len(rows),
                                                          dtype=np.int64),
                                 call)
        finally:
            fc.close()

    def test_async_fleet_client_routes_and_merges(self):
        """AsyncFleetClient end to end against two REAL in-process
        asyncio servers on one ManualClock: affine fan-out, request
        order, and the hashed-lane merge."""
        from ratelimiter_tpu.algorithms.sketch import SketchLimiter
        from ratelimiter_tpu.serving.client import AsyncFleetClient

        clock = ManualClock(1000.0)
        cfg = _cfg(limit=5)
        lim_a, lim_b = SketchLimiter(cfg, clock), SketchLimiter(cfg, clock)
        sa = _server_on_thread(lim_a)
        sb = _server_on_thread(lim_b)

        async def drive():
            m = _two_host_map(port_a=sa[0].port, port_b=sb[0].port)
            fc = await AsyncFleetClient.connect(m.to_dict())
            try:
                keys = [f"user:{i}" for i in range(30)]
                res = await fc.allow_batch(keys)
                assert all(r.allowed for r in res)
                # Same frame again x4: each key at 5/5 after this.
                for _ in range(4):
                    res = await fc.allow_batch(keys)
                res = await fc.allow_batch(keys)
                assert not any(r.allowed for r in res)  # all exhausted
                out = await fc.allow_hashed(
                    np.arange(100, dtype=np.uint64))
                assert len(out) == 100 and out.allowed.all()
            finally:
                await fc.close()

        try:
            asyncio.run(drive())
        finally:
            _stop(*sa)
            _stop(*sb)


class TestMembershipAndFailover:
    def _core(self, self_id, m=None):
        from ratelimiter_tpu.observability.metrics import Registry

        return FleetCore(m or _two_host_map(), self_id,
                         prefix="ratelimit", registry=Registry())

    def test_announce_refreshes_liveness_and_adopts_higher_epoch(self):
        from ratelimiter_tpu.observability.metrics import Registry

        core = self._core("a")
        mem = FleetMembership(core, heartbeat=10, dead_after=10,
                              registry=Registry())
        m2 = core.map.reassign("b", "a")  # epoch 2
        mem.handle_announce({"from": "b", "map": m2.to_dict()})
        assert core.map.epoch == 2
        st = mem.status()
        assert st["peers"]["b"]["alive"]
        assert st["peers"]["b"]["epoch"] == 2
        # An older epoch never rolls the map back.
        mem.handle_announce({"from": "b",
                             "map": _two_host_map().to_dict()})
        assert core.map.epoch == 2

    def test_silent_peer_fails_over_to_successor_with_restore(self):
        """Kill detection + adoption, fully in-process: b stops hearing
        a, declares it dead, adopts its ranges onto a restored standby
        unit at epoch+1, and serves a's keys from it."""
        from ratelimiter_tpu.algorithms.sketch import SketchLimiter
        from ratelimiter_tpu.observability.metrics import Registry

        clock = ManualClock(1000.0)
        cfg = _cfg(limit=10)
        core = self._core("b")
        lim_b = SketchLimiter(cfg, clock)
        fwd = FleetForwarder(lim_b, core)
        adopted_unit = SketchLimiter(cfg, clock)
        # Pre-consume on the standby — stands in for snapshot restore.
        key_a = next(f"k:{i}" for i in range(100)
                     if int(core.owners_of_hash(
                         core.hash_keys([f"k:{i}"]))[0]) == 0)
        adopted_unit.allow_n(key_a, 7)
        adopted = []

        def adopt(dead):
            adopted.append(dead.id)
            return adopted_unit

        mem = FleetMembership(core, heartbeat=10, dead_after=0.2,
                              adopt_fn=adopt, registry=Registry())
        try:
            mem.handle_announce({"from": "a",
                                 "map": core.map.to_dict()})
            time.sleep(0.35)
            mem._check_dead()
            assert adopted == ["a"]
            assert core.map.epoch == 2
            assert core.map.owned_buckets("b") == 32
            assert not mem.status()["peers"]["a"]["alive"]
            # Adopted keys decide on the RESTORED unit: 7 of 10 already
            # consumed, so only 3 more single units pass.
            seq = [fwd.allow_n(key_a, 1) for _ in range(5)]
            assert [r.allowed for r in seq] == [True] * 3 + [False] * 2
        finally:
            mem.stop()
            fwd.close()

    def test_forward_failure_classifier_feeds_death(self):
        from ratelimiter_tpu.observability.metrics import Registry

        core = self._core("b")
        mem = FleetMembership(core, heartbeat=10, dead_after=1000,
                              failure_threshold=2, registry=Registry())
        try:
            mem.handle_announce({"from": "a", "map": core.map.to_dict()})
            # Caller errors never count...
            mem.note_peer_failure("a", InvalidConfigError("nope"))
            mem._check_dead()
            assert mem.status()["peers"]["a"]["alive"]
            # ...backend faults do.
            mem.note_peer_failure("a", ConnectionError("down"))
            mem.note_peer_failure("a", TimeoutError("slow"))
            mem._check_dead()
            assert not mem.status()["peers"]["a"]["alive"]
            assert core.map.epoch == 2  # b was a's successor
        finally:
            mem.stop()


# ===================================================================
#                      real server processes
# ===================================================================


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.pathsep.join(
        [REPO] + env.get("PYTHONPATH", "").split(os.pathsep))
    return env


def _spawn_fleet_member(port, cfgpath, self_id, *, snap=None,
                        native=False, extra=()):
    argv = [sys.executable, "-m", "ratelimiter_tpu.serving",
            "--backend", "sketch", "--limit", "100", "--window", "600",
            "--sketch-width", "8192", "--sub-windows", "6",
            "--port", str(port), "--no-prewarm",
            "--fleet-config", cfgpath, "--fleet-self", self_id,
            "--fleet-forward-deadline", "60",
            "--fleet-heartbeat", "0.3", "--fleet-dead-after", "1.5"]
    if snap:
        argv += ["--snapshot-dir", snap, "--snapshot-interval", "500"]
    if native:
        argv.append("--native")
    argv += list(extra)
    proc = subprocess.Popen(argv, env=_env(), stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    return proc


def _wait_banner(proc, timeout=180):
    t0 = time.time()
    lines = []
    while time.time() - t0 < timeout:
        line = proc.stdout.readline()
        if not line:
            break
        lines.append(line)
        if line.startswith("serving"):
            return lines
    raise AssertionError("fleet member never served:\n" + "".join(lines))


def _fleet_config(tmp_path, pa, pb, *, snap_a=None, snap_b=None):
    d = {"buckets": 32, "epoch": 1, "hosts": [
        {"id": "a", "host": "127.0.0.1", "port": pa,
         "ranges": [[0, 16]], "successor": "b",
         **({"snapshot_dir": snap_a} if snap_a else {})},
        {"id": "b", "host": "127.0.0.1", "port": pb,
         "ranges": [[16, 32]], "successor": "a",
         **({"snapshot_dir": snap_b} if snap_b else {})},
    ]}
    path = str(tmp_path / "fleet.json")
    with open(path, "w", encoding="utf-8") as f:
        json.dump(d, f)
    return path, d


class TestFleetProcesses:
    @pytest.mark.slow
    def test_two_hosts_affine_forwarded_and_cross_door_quota(self,
                                                             tmp_path):
        """Two real asyncio fleet members: affine FleetClient routing,
        dumb-LB mis-routing (server-side forwarding), one key's quota
        counted ONCE across hosts, and same-key ordering across the
        forwarding hop."""
        from ratelimiter_tpu.serving.client import Client, FleetClient

        pa, pb = free_port(), free_port()
        cfgpath, fleet_d = _fleet_config(tmp_path, pa, pb)
        a = _spawn_fleet_member(pa, cfgpath, "a")
        b = _spawn_fleet_member(pb, cfgpath, "b")
        try:
            _wait_banner(a)
            _wait_banner(b)
            fc = FleetClient(fleet_d)
            owner_of = (lambda k: int(
                fc.map.owner_of_hash(fc._hash([k]))[0]))
            ca = Client(port=pa, timeout=120)
            cb = Client(port=pb, timeout=120)
            # Warm scalar pad shapes with keys each host OWNS.
            ca.allow(next(f"w:{i}" for i in range(99)
                          if owner_of(f"w:{i}") == 0))
            cb.allow(next(f"w:{i}" for i in range(99)
                          if owner_of(f"w:{i}") == 1))
            # Affine fan-out: whole frame served, request order kept.
            res = fc.allow_batch([f"user:{i}" for i in range(200)])
            assert sum(r.allowed for r in res) == 200
            # Dumb LB: every row at host a; foreign rows FORWARD.
            res2 = ca.allow_batch([f"fwd:{i}" for i in range(200)])
            assert sum(r.allowed for r in res2) == 200
            # Raw-id lane through the fleet client.
            out = fc.allow_hashed(np.arange(1000, dtype=np.uint64))
            assert int(out.allowed.sum()) == 1000
            # One key's quota counts ONCE regardless of entry door.
            n_ok = sum((ca if i % 2 == 0 else cb).allow_n(
                "shared:key", 1).allowed for i in range(120))
            assert n_ok == 100
            # Same-key ordering across the hop: first 100 allowed, in
            # order, then denies.
            k2 = "ord:key"
            non_owner = cb if owner_of(k2) == 0 else ca
            seq = [non_owner.allow_n(k2, 1) for _ in range(110)]
            assert [r.allowed for r in seq] == [True] * 100 + [False] * 10
            assert [r.remaining for r in seq[:100]] == list(
                range(99, -1, -1))
            # /healthz-equivalent map fetch names both hosts.
            m = FleetMap.from_dict(ca.fleet_map())
            assert {h.id for h in m.hosts} == {"a", "b"}
            fc.close()
            ca.close()
            cb.close()
        finally:
            for pr in (a, b):
                if pr.poll() is None:
                    pr.terminate()
            a.wait(timeout=30)
            b.wait(timeout=30)

    @pytest.mark.slow
    def test_kill9_failover_restores_range_to_successor(self, tmp_path):
        """Kill -9 one host mid-traffic: the successor detects death,
        restores the range from the dead host's newest snapshot + WAL
        suffix, bumps the epoch, and serves — overrides exact, counters
        within one snapshot interval, FleetClient self-heals off the
        refreshed map.

        Slow lane (the CI fleet lane runs it unfiltered, zero skips):
        the tier-1 budget keeps the DETERMINISTIC in-process failover
        coverage (TestMembershipAndFailover) instead of this
        wall-clock-bound two-process flavor."""
        from ratelimiter_tpu.serving.client import Client, FleetClient

        pa, pb = free_port(), free_port()
        snap_a = str(tmp_path / "snap-a")
        snap_b = str(tmp_path / "snap-b")
        cfgpath, fleet_d = _fleet_config(tmp_path, pa, pb,
                                         snap_a=snap_a, snap_b=snap_b)
        a = _spawn_fleet_member(pa, cfgpath, "a", snap=snap_a)
        b = _spawn_fleet_member(pb, cfgpath, "b", snap=snap_b)
        try:
            _wait_banner(a)
            _wait_banner(b)
            fc = FleetClient(fleet_d)
            owner_of = (lambda k: int(
                fc.map.owner_of_hash(fc._hash([k]))[0]))
            ka = next(f"k:{i}" for i in range(99)
                      if owner_of(f"k:{i}") == 0)
            ca = Client(port=pa, timeout=120)
            assert ca.allow_n(ka, 30).allowed
            ca.set_override("vip", 42)
            snap_id, _, _ = ca.snapshot()
            assert snap_id >= 1
            # Post-snapshot decisions: lost on kill -9, bounded by one
            # interval, under-counting only.
            for _ in range(5):
                ca.allow_n(ka, 2)
            t_kill = time.time()
            a.send_signal(signal.SIGKILL)
            a.wait(timeout=30)
            # Drive until the survivor owns + serves the range.
            recovered_at = None
            deadline = time.time() + 90
            while time.time() < deadline:
                try:
                    fc.allow_n(ka, 1)
                    recovered_at = time.time()
                    break
                except Exception:
                    time.sleep(0.2)
            assert recovered_at is not None, "range never failed over"
            window = recovered_at - t_kill
            assert window < 60, f"failover took {window:.1f}s"
            assert fc.map.epoch == 2
            # Overrides exact (WAL replay into the standby unit).
            with Client(port=pb, timeout=120) as cb:
                assert cb.get_override("vip") == (42, 1.0)
            # Counters within one interval: >= 30 consumed (snapshot),
            # <= 41 (true total incl. the probe) — under-count only.
            assert fc.allow_n(ka, 59).allowed     # 30+1+59 <= 100
            assert not fc.allow_n(ka, 50).allowed  # would pass 100
            fc.close()
            ca.close()
        finally:
            for pr in (a, b):
                if pr.poll() is None:
                    pr.terminate()
            b.wait(timeout=30)

    @pytest.mark.slow
    def test_native_door_fleet_forwarding(self, tmp_path):
        """Mixed-door fleet (a = C++ native door, b = asyncio door):
        the native bridge forwards foreign string AND raw-id rows, and
        a key's quota counts once across doors."""
        import shutil

        if shutil.which("g++") is None:
            pytest.skip("no g++: native front door unavailable")
        from ratelimiter_tpu.serving.client import Client, FleetClient

        pa, pb = free_port(), free_port()
        cfgpath, fleet_d = _fleet_config(tmp_path, pa, pb)
        a = _spawn_fleet_member(pa, cfgpath, "a", native=True)
        b = _spawn_fleet_member(pb, cfgpath, "b")
        try:
            _wait_banner(a)
            _wait_banner(b)
            fc = FleetClient(fleet_d)
            owner_of = (lambda k: int(
                fc.map.owner_of_hash(fc._hash([k]))[0]))
            ca = Client(port=pa, timeout=120)
            cb = Client(port=pb, timeout=120)
            ca.allow(next(f"w:{i}" for i in range(99)
                          if owner_of(f"w:{i}") == 0))
            cb.allow(next(f"w:{i}" for i in range(99)
                          if owner_of(f"w:{i}") == 1))
            # Mis-routed strings at the NATIVE door forward correctly.
            res = ca.allow_batch([f"user:{i}" for i in range(100)])
            assert sum(r.allowed for r in res) == 100
            # Mis-routed raw ids at the native door.
            out = ca.allow_hashed(np.arange(500, dtype=np.uint64))
            assert int(out.allowed.sum()) == 500
            # Cross-door single-quota checks, string and hashed lanes.
            n_ok = sum((ca if i % 2 == 0 else cb).allow_n(
                "shared:k2", 1).allowed for i in range(120))
            assert n_ok == 100
            hot = np.full(120, 7777, dtype=np.uint64)
            total = (int(ca.allow_hashed(hot[:60]).allowed.sum())
                     + int(cb.allow_hashed(hot[60:]).allowed.sum()))
            assert total == 100
            fc.close()
            ca.close()
            cb.close()
        finally:
            for pr in (a, b):
                if pr.poll() is None:
                    pr.terminate()
            a.wait(timeout=30)
            b.wait(timeout=30)
