"""Coalesced columnar forward-lane tests (ADR-019).

The correctness bar mirrors the ADR-013 scatter-gather scheduler's, one
level up: rows forwarded through the coalesced peer lanes must decide
BIT-IDENTICALLY to the same rows arriving directly at their owner, with
same-key send order preserved under (a) cross-frame coalescing into one
wire window, (b) pipelined multi-frame links, and (c) multi-connection
peers (per-key connection affinity). Failure attribution is window-
scoped: one failed coalesced wire frame degrades exactly its member
rows' frames. Routing is owner-scoped: a frame opens lanes only to the
owners of its rows.
"""

from __future__ import annotations

import asyncio
import socket
import struct
import threading
import time

import numpy as np
import pytest

from netutil import free_port

from ratelimiter_tpu import Algorithm, Config, SketchParams
from ratelimiter_tpu.core.clock import ManualClock
from ratelimiter_tpu.core.types import BatchResult
from ratelimiter_tpu.fleet import (
    FleetCore,
    FleetForwarder,
    FleetMap,
)
from ratelimiter_tpu.ops.hashing import splitmix64
from ratelimiter_tpu.serving import protocol as p


def _cfg(limit=20, window=600.0, **kw):
    return Config(algorithm=Algorithm.TPU_SKETCH, limit=limit,
                  window=window,
                  sketch=SketchParams(depth=4, width=4096, sub_windows=6),
                  **kw)


def _map(hosts_spec, buckets=32):
    """hosts_spec: [(id, port, (lo, hi), extra_dict?), ...]"""
    hosts = []
    for spec in hosts_spec:
        hid, port, (lo, hi) = spec[:3]
        h = {"id": hid, "host": "127.0.0.1", "port": port,
             "ranges": [[lo, hi]]}
        if len(spec) > 3:
            h.update(spec[3])
        hosts.append(h)
    return FleetMap.from_dict(
        {"buckets": buckets, "epoch": 1, "hosts": hosts})


def _server_on_thread(limiter):
    from ratelimiter_tpu.serving import RateLimitServer

    loop = asyncio.new_event_loop()
    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()
    srv = RateLimitServer(limiter, "127.0.0.1", 0)
    asyncio.run_coroutine_threadsafe(srv.start(), loop).result(10)
    return srv, loop, t


def _stop(srv, loop, t):
    asyncio.run_coroutine_threadsafe(srv.shutdown(), loop).result(10)
    loop.call_soon_threadsafe(loop.stop)
    t.join(timeout=10)
    loop.close()


# ===================================================================
#                       protocol-level units
# ===================================================================


class TestForwardFlag:
    def test_with_forward_round_trip(self):
        frame = p.encode_allow_hashed(7, np.arange(4, dtype=np.uint64))
        flagged = p.with_forward(p.with_deadline(frame, 1.5))
        _, type_, req_id = struct.unpack_from("<IBQ", flagged)
        assert req_id == 7
        assert type_ & p.FORWARD_FLAG
        base, trace_id, budget, body = p.split_request(
            type_, flagged[p.HEADER_SIZE:])
        base, fwd = p.split_forward(base)
        assert fwd and base == p.T_ALLOW_HASHED
        assert trace_id == 0 and abs(budget - 1.5) < 1e-9
        ids, ns = p.parse_allow_hashed(body)
        assert (ids == np.arange(4)).all()

    def test_split_forward_passthrough(self):
        assert p.split_forward(p.T_ALLOW_HASHED) == (p.T_ALLOW_HASHED,
                                                     False)
        # Response types never carry the hint.
        assert p.split_forward(p.T_RESULT_HASHED) == (p.T_RESULT_HASHED,
                                                      False)

    def test_double_flag_rejected(self):
        frame = p.with_forward(
            p.encode_allow_hashed(1, np.arange(2, dtype=np.uint64)))
        with pytest.raises(p.ProtocolError):
            p.with_forward(frame)


class TestColumnarBatchParse:
    def test_matches_scalar_parse(self):
        from ratelimiter_tpu.core.types import Result

        results = [
            Result(True, 100, 42, 0.0, 123.5),
            Result(False, 100, 0, 2.5, 124.0),
            Result(True, 100, 7, 0.0, 125.0, fail_open=True),
        ]
        body = p.encode_result_batch(9, 100, results)[p.HEADER_SIZE:]
        want = p.parse_result_batch(body)
        got = p.parse_result_batch_columnar(body)
        assert isinstance(got, BatchResult)
        assert got.limit == 100 and len(got) == 3
        assert got.fail_open  # any row's flag ORs
        for i, r in enumerate(want):
            assert bool(got.allowed[i]) == r.allowed
            assert int(got.remaining[i]) == r.remaining
            assert float(got.retry_after[i]) == r.retry_after
            assert float(got.reset_at[i]) == r.reset_at

    def test_bad_body_rejected(self):
        with pytest.raises(p.ProtocolError):
            p.parse_result_batch_columnar(b"\x00" * 13)


class TestScatterMergeVectorized:
    def test_list_leg_merges_columnar(self):
        from ratelimiter_tpu.core.types import Result
        from ratelimiter_tpu.fleet.forwarder import scatter_merge

        legs = [
            (np.array([0, 2]), [Result(True, 100, 5, 0.0, 10.0),
                                Result(False, 200, 0, 1.5, 11.0)]),
            (np.array([1]), BatchResult(
                allowed=np.array([True]), limit=100,
                remaining=np.array([9], dtype=np.int64),
                retry_after=np.array([0.0]),
                reset_at=np.array([12.0]))),
        ]
        out = scatter_merge(3, 100, legs)
        assert out.allowed.tolist() == [True, True, False]
        assert out.remaining.tolist() == [5, 9, 0]
        assert out.retry_after.tolist() == [0.0, 0.0, 1.5]
        assert not out.fail_open
        # The 200-limit row materialized per-row limits.
        assert out.limits is not None
        assert out.limits.tolist() == [100, 100, 200]

    def test_list_leg_fail_open_ors(self):
        from ratelimiter_tpu.core.types import Result
        from ratelimiter_tpu.fleet.forwarder import scatter_merge

        out = scatter_merge(1, 10, [
            (None, [Result(True, 10, 0, 0.0, 1.0, fail_open=True)])])
        assert out.fail_open


class TestFleetMapShards:
    def test_shards_round_trip_and_validation(self):
        m = _map([("a", 1, (0, 16)),
                  ("b", 2, (16, 32), {"shards": 4})])
        assert m.hosts[0].shards == 1
        assert m.hosts[1].shards == 4
        d = m.to_dict()
        assert "shards" not in d["hosts"][0]
        assert d["hosts"][1]["shards"] == 4
        assert FleetMap.from_dict(d) == m
        with pytest.raises(Exception, match="shards"):
            _map([("a", 1, (0, 32), {"shards": 0})])


# ===================================================================
#            deterministic in-process lanes (ManualClock)
# ===================================================================


class TestCoalescedOrderingOracle:
    """Host A = FleetForwarder over a local slice; host B = a REAL
    asyncio server. Frames launch PIPELINED (several in flight before
    the first resolve) so their foreign fragments genuinely coalesce
    into shared wire windows; decisions must stay bit-identical to the
    oracle fed each host's rows in send order."""

    def _fleet(self, clock, limit=20, **core_kw):
        from ratelimiter_tpu.algorithms.sketch import SketchLimiter
        from ratelimiter_tpu.observability.metrics import Registry

        cfg = _cfg(limit=limit)
        lim_a = SketchLimiter(cfg, clock)
        lim_b = SketchLimiter(cfg, clock)
        srv, loop, t = _server_on_thread(lim_b)
        m = _map([("a", 1, (0, 16)), ("b", srv.port, (16, 32))])
        core = FleetCore(m, "a", prefix=cfg.prefix,
                         forward_deadline=30.0, registry=Registry(),
                         **core_kw)
        fwd = FleetForwarder(lim_a, core)
        oracle_a = SketchLimiter(cfg, clock)
        oracle_b = SketchLimiter(cfg, clock)
        return cfg, fwd, core, (srv, loop, t), (oracle_a, oracle_b)

    def _drive_pipelined(self, fwd, core, frames):
        """Launch every frame, then resolve in launch order — foreign
        fragments of frames 2..k queue behind frame 1's window and
        coalesce (forward_inflight bounds wire frames in flight)."""
        tickets = [fwd.launch_ids(ids) for ids in frames]
        return [fwd.resolve(t) for t in tickets]

    def test_interleaved_frames_coalesce_bit_identical(self):
        clock = ManualClock(1000.0)
        cfg, fwd, core, server, (oa, ob) = self._fleet(
            clock, limit=10, forward_inflight=1)
        srv, loop, t = server
        try:
            rng = np.random.default_rng(11)
            # A hot id owned by B, present in EVERY frame, plus noise:
            # send order across frames must be its decision order.
            hot = next(i for i in range(1, 200)
                       if int(core.owners_of_ids(
                           np.asarray([i], np.uint64))[0]) == 1)
            frames = []
            for k in range(10):
                ids = rng.integers(0, 64, size=30).astype(np.uint64)
                ids[5] = hot
                ids[17] = hot
                frames.append(ids)
            outs = self._drive_pipelined(fwd, core, frames)
            # Oracle: each host's rows, frame by frame, in send order.
            hot_remaining = []
            for ids, got in zip(frames, outs):
                owners = core.owners_of_ids(ids)
                want_allowed = np.zeros(len(ids), dtype=bool)
                want_remaining = np.zeros(len(ids), dtype=np.int64)
                for host, oracle in ((0, oa), (1, ob)):
                    pos = np.nonzero(owners == host)[0]
                    if not pos.shape[0]:
                        continue
                    out = oracle.allow_ids(ids[pos])
                    want_allowed[pos] = out.allowed
                    want_remaining[pos] = out.remaining
                np.testing.assert_array_equal(got.allowed, want_allowed)
                np.testing.assert_array_equal(got.remaining,
                                              want_remaining)
                hot_remaining.extend(
                    got.remaining[ids == hot].tolist())
            # The hot key's trajectory is strictly non-increasing —
            # send order survived the coalesced hop.
            assert hot_remaining == sorted(hot_remaining, reverse=True)
            # And coalescing actually happened: 10 frames' fragments
            # crossed in fewer wire windows.
            lane = core.lane(1)
            assert 0 < lane.wire_frames < 10
            assert lane.wire_rows == sum(
                int((core.owners_of_ids(ids) == 1).sum())
                for ids in frames)
        finally:
            fwd.close()
            _stop(srv, loop, t)

    def test_multi_connection_affinity_preserves_order(self):
        clock = ManualClock(1000.0)
        cfg, fwd, core, server, (oa, ob) = self._fleet(
            clock, limit=10, forward_inflight=2, forward_conns=3)
        srv, loop, t = server
        try:
            rng = np.random.default_rng(3)
            frames = [rng.integers(0, 48, size=40).astype(np.uint64)
                      for _ in range(8)]
            outs = self._drive_pipelined(fwd, core, frames)
            per_id_remaining: dict = {}
            for ids, got in zip(frames, outs):
                owners = core.owners_of_ids(ids)
                want_allowed = np.zeros(len(ids), dtype=bool)
                want_remaining = np.zeros(len(ids), dtype=np.int64)
                for host, oracle in ((0, oa), (1, ob)):
                    pos = np.nonzero(owners == host)[0]
                    if not pos.shape[0]:
                        continue
                    out = oracle.allow_ids(ids[pos])
                    want_allowed[pos] = out.allowed
                    want_remaining[pos] = out.remaining
                np.testing.assert_array_equal(got.allowed, want_allowed)
                np.testing.assert_array_equal(got.remaining,
                                              want_remaining)
                for i, rid in enumerate(ids.tolist()):
                    per_id_remaining.setdefault(rid, []).append(
                        int(got.remaining[i]))
            for rid, seq in per_id_remaining.items():
                assert seq == sorted(seq, reverse=True), rid
        finally:
            fwd.close()
            _stop(srv, loop, t)

    def test_string_frames_hash_forward_columnar(self):
        """Single-shard receiver: string rows ride the columnar lane
        (wire_frames counts coalesced hashed windows) and stay
        bit-identical — including a policy-overridden key, whose
        override the receiver resolves from the finalized hash."""
        clock = ManualClock(1000.0)
        cfg, fwd, core, server, (oa, ob) = self._fleet(clock, limit=5)
        srv, loop, t = server
        try:
            keys = [f"user:{i}" for i in range(30)]
            vip = next(k for k in keys if int(core.owners_of_hash(
                core.hash_keys([k]))[0]) == 1)
            # Override at the OWNER (lim_b inside the server) and on
            # the oracle twin.
            srv_lim = srv.batcher.limiter
            srv_lim.set_override(vip, 2)
            ob.set_override(vip, 2)
            for _ in range(4):
                got = fwd.allow_batch(keys)
                owners = core.owners_of_hash(core.hash_keys(keys))
                want_allowed = np.zeros(len(keys), dtype=bool)
                for host, oracle in ((0, oa), (1, ob)):
                    pos = np.nonzero(owners == host)[0]
                    out = oracle.allow_batch([keys[i] for i in pos])
                    want_allowed[pos] = out.allowed
                np.testing.assert_array_equal(got.allowed, want_allowed)
            # Columnar lane used for the string rows:
            assert core.lane(1).wire_frames > 0
        finally:
            fwd.close()
            _stop(srv, loop, t)

    def test_multi_shard_peer_gets_strings(self):
        """A peer declaring shards > 1 must receive STRING rows as
        strings (FNV routing contract): the columnar window counter
        stays at zero while decisions remain bit-identical."""
        clock = ManualClock(1000.0)
        from ratelimiter_tpu.algorithms.sketch import SketchLimiter
        from ratelimiter_tpu.observability.metrics import Registry

        cfg = _cfg(limit=8)
        lim_a = SketchLimiter(cfg, clock)
        lim_b = SketchLimiter(cfg, clock)
        srv, loop, t = _server_on_thread(lim_b)
        m = _map([("a", 1, (0, 16)),
                  ("b", srv.port, (16, 32), {"shards": 2})])
        core = FleetCore(m, "a", prefix=cfg.prefix,
                         forward_deadline=30.0, registry=Registry())
        fwd = FleetForwarder(lim_a, core)
        ob = SketchLimiter(cfg, clock)
        oa = SketchLimiter(cfg, clock)
        try:
            keys = [f"k:{i}" for i in range(40)]
            got = fwd.allow_batch(keys, [2] * 40)
            owners = core.owners_of_hash(core.hash_keys(keys))
            want_allowed = np.zeros(40, dtype=bool)
            for host, oracle in ((0, oa), (1, ob)):
                pos = np.nonzero(owners == host)[0]
                out = oracle.allow_batch([keys[i] for i in pos],
                                         [2] * len(pos))
                want_allowed[pos] = out.allowed
            np.testing.assert_array_equal(got.allowed, want_allowed)
            assert core.lane(1).wire_frames == 0  # string fallback
            assert not core.peer_columnar(1)
            # Raw-id frames still ride the columnar lane regardless.
            fwd.allow_ids(np.arange(64, dtype=np.uint64))
            assert core.lane(1).wire_frames > 0
        finally:
            fwd.close()
            _stop(srv, loop, t)


class _OneShotPeer:
    """Fake peer: answers the FIRST hashed window correctly (allow-all)
    after ``reply_delay`` seconds — long enough for later fragments to
    queue behind the in-flight bound and coalesce — then reads the
    second window and closes cold: one failed coalesced wire frame."""

    def __init__(self, reply_delay: float = 0.5):
        self.reply_delay = reply_delay
        self.sock = socket.socket()
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(4)
        self.port = self.sock.getsockname()[1]
        self.windows: list = []
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()

    def _recv_frame(self, conn):
        buf = b""
        while len(buf) < p.HEADER_SIZE:
            chunk = conn.recv(65536)
            if not chunk:
                return None, None, None
            buf += chunk
        length, type_, req_id = p.parse_header(buf[:p.HEADER_SIZE])
        body = buf[p.HEADER_SIZE:]
        while len(body) < length - 9:
            chunk = conn.recv(65536)
            if not chunk:
                return None, None, None
            body += chunk
        return type_, req_id, body

    def _run(self):
        conn, _ = self.sock.accept()
        try:
            type_, req_id, body = self._recv_frame(conn)
            if type_ is None:
                return
            base, _, _, body = p.split_request(type_, body)
            base, fwd = p.split_forward(base)
            assert base == p.T_ALLOW_HASHED and fwd
            ids, ns = p.parse_allow_hashed(body)
            b = int(ids.shape[0])
            self.windows.append(b)
            # Hold the reply so later fragments coalesce behind the
            # sender's in-flight bound.
            time.sleep(self.reply_delay)
            res = BatchResult(
                allowed=np.ones(b, dtype=bool), limit=99,
                remaining=np.full(b, 7, dtype=np.int64),
                retry_after=np.zeros(b), reset_at=np.full(b, 5.0))
            conn.sendall(p.encode_result_hashed(req_id, res))
            # Read the second (coalesced) window, record it, then die
            # without answering.
            type2, _, body2 = self._recv_frame(conn)
            if type2 is not None:
                base2, _, _, body2 = p.split_request(type2, body2)
                base2, _ = p.split_forward(base2)
                ids2, _ = p.parse_allow_hashed(body2)
                self.windows.append(int(ids2.shape[0]))
        finally:
            conn.close()
            self.sock.close()


class TestWindowFailureAttribution:
    def test_failed_wire_frame_degrades_only_its_members(self):
        """inflight=1 forces frames 2+3 to coalesce into window 2;
        the peer answers window 1 and kills the connection. Frame 1
        must carry REAL results; frames 2 and 3 degrade fail-open;
        nothing else is touched."""
        from ratelimiter_tpu.algorithms.sketch import SketchLimiter
        from ratelimiter_tpu.observability.metrics import Registry

        clock = ManualClock(1000.0)
        peer = _OneShotPeer()
        cfg = _cfg(limit=10, fail_open=True)
        lim = SketchLimiter(cfg, clock)
        m = _map([("a", 1, (0, 16)), ("b", peer.port, (16, 32))])
        core = FleetCore(m, "a", prefix=cfg.prefix,
                         forward_deadline=2.0, forward_inflight=1,
                         registry=Registry())
        fwd = FleetForwarder(lim, core)
        try:
            foreign = np.array(
                [i for i in range(1, 400)
                 if int(core.owners_of_ids(
                     np.asarray([i], np.uint64))[0]) == 1][:30],
                dtype=np.uint64)
            t1 = fwd.launch_ids(foreign[:10])
            # Give window 1 a moment to fly alone; 2+3 then share
            # window 2 behind the in-flight bound.
            deadline = time.time() + 5
            while not peer.windows and time.time() < deadline:
                time.sleep(0.01)
            assert peer.windows == [10]
            t2 = fwd.launch_ids(foreign[10:20])
            t3 = fwd.launch_ids(foreign[20:30])
            r1 = fwd.resolve(t1)
            r2 = fwd.resolve(t2)
            r3 = fwd.resolve(t3)
            # Window 1's members: REAL peer answers.
            assert not r1.fail_open
            assert (r1.remaining == 7).all()
            # Frames 2 and 3 genuinely shared ONE wire window:
            assert peer.windows == [10, 20]
            # Window 2's members: degraded fail-open, attributed only
            # to them.
            assert r2.fail_open and r3.fail_open
            assert r2.allowed.all() and r3.allowed.all()
            assert (r2.remaining == 0).all()
            assert int(core._c_degraded.total()) == 20
        finally:
            fwd.close()

    def test_dead_owner_degrades_only_its_rows(self):
        """3-host frame: rows owned by a live peer answer REAL, rows
        owned by a dead peer degrade, local rows decide locally — the
        per-job attribution of one frame's split."""
        from ratelimiter_tpu.algorithms.sketch import SketchLimiter
        from ratelimiter_tpu.observability.metrics import Registry

        clock = ManualClock(1000.0)
        cfg = _cfg(limit=10, fail_open=True)
        lim_a = SketchLimiter(cfg, clock)
        lim_b = SketchLimiter(cfg, clock)
        srv, loop, t = _server_on_thread(lim_b)
        dead = free_port()
        m = _map([("a", 1, (0, 11)), ("b", srv.port, (11, 22)),
                  ("c", dead, (22, 32))])
        core = FleetCore(m, "a", prefix=cfg.prefix,
                         forward_deadline=0.5, registry=Registry())
        fwd = FleetForwarder(lim_a, core)
        ob = SketchLimiter(cfg, clock)
        try:
            ids = np.arange(1, 120, dtype=np.uint64)
            out = fwd.allow_ids(ids)
            owners = core.owners_of_ids(ids)
            live = owners == 1
            deadrows = owners == 2
            # Live-peer rows bit-identical to the oracle:
            want = ob.allow_ids(ids[live])
            np.testing.assert_array_equal(out.allowed[live],
                                          want.allowed)
            np.testing.assert_array_equal(out.remaining[live],
                                          want.remaining)
            # Dead-peer rows: fail-open allowances (remaining 0).
            assert out.allowed[deadrows].all()
            assert (out.remaining[deadrows] == 0).all()
            assert out.fail_open
            assert int(core._c_degraded.total()) == int(deadrows.sum())
        finally:
            fwd.close()
            _stop(srv, loop, t)


class TestFourHostRouting:
    def test_frame_contacts_only_owners_of_its_rows(self):
        """4-host map, one live peer (c): frames whose rows are owned
        only by {a, c} must open a lane to c alone — the routed fleet
        talks O(owners-touched), not O(N^2)."""
        from ratelimiter_tpu.algorithms.sketch import SketchLimiter
        from ratelimiter_tpu.observability.metrics import Registry

        clock = ManualClock(1000.0)
        cfg = _cfg(limit=10)
        lim_a = SketchLimiter(cfg, clock)
        lim_c = SketchLimiter(cfg, clock)
        srv, loop, t = _server_on_thread(lim_c)
        m = _map([("a", 1, (0, 8)), ("b", free_port(), (8, 16)),
                  ("c", srv.port, (16, 24)), ("d", free_port(), (24, 32))])
        core = FleetCore(m, "a", prefix=cfg.prefix,
                         forward_deadline=30.0, registry=Registry())
        fwd = FleetForwarder(lim_a, core)
        oc = SketchLimiter(cfg, clock)
        oa = SketchLimiter(cfg, clock)
        try:
            pool = np.array(
                [i for i in range(1, 2000)
                 if int(core.owners_of_ids(
                     np.asarray([i], np.uint64))[0]) in (0, 2)][:120],
                dtype=np.uint64)
            assert pool.shape[0] == 120
            for k in range(3):
                ids = pool[k * 40:(k + 1) * 40]
                got = fwd.allow_ids(ids)
                owners = core.owners_of_ids(ids)
                want_allowed = np.zeros(40, dtype=bool)
                want_remaining = np.zeros(40, dtype=np.int64)
                for host, oracle in ((0, oa), (2, oc)):
                    pos = np.nonzero(owners == host)[0]
                    if not pos.shape[0]:
                        continue
                    out = oracle.allow_ids(ids[pos])
                    want_allowed[pos] = out.allowed
                    want_remaining[pos] = out.remaining
                np.testing.assert_array_equal(got.allowed, want_allowed)
                np.testing.assert_array_equal(got.remaining,
                                              want_remaining)
            # Only c's lane exists; b and d were never contacted.
            assert set(core._lanes.keys()) == {2}
            assert core.lane(2).wire_frames > 0
        finally:
            fwd.close()
            _stop(srv, loop, t)


class TestBatcherForwardLaneSeparation:
    def test_standalone_never_coalesces_with_client_window(self):
        """A FORWARD_FLAG frame must dispatch in its own window: the
        limiter sees two launches, not one concatenation — while two
        standalone frames DO coalesce with each other."""
        from ratelimiter_tpu.algorithms.sketch import SketchLimiter
        from ratelimiter_tpu.serving.batcher import MicroBatcher

        clock = ManualClock(1000.0)
        lim = SketchLimiter(_cfg(limit=50), clock)
        sizes = []
        orig = lim.launch_ids

        def spy(ids, ns=None, *, now=None, wire=False):
            sizes.append(int(np.asarray(ids).shape[0]))
            return orig(ids, ns, now=now, wire=wire)

        lim.launch_ids = spy

        async def drive():
            b = MicroBatcher(lim, max_batch=1024, max_delay=0.05,
                             inflight=2)
            f1 = b.submit_hashed_nowait(
                np.arange(10, dtype=np.uint64),
                np.ones(10, dtype=np.uint32))
            f2 = b.submit_hashed_nowait(
                np.arange(100, 120, dtype=np.uint64),
                np.ones(20, dtype=np.uint32), standalone=True)
            f3 = b.submit_hashed_nowait(
                np.arange(200, 230, dtype=np.uint64),
                np.ones(30, dtype=np.uint32), standalone=True)
            out = [await f for f in (f1, f2, f3)]
            await b.drain()
            return out

        r1, r2, r3 = asyncio.run(drive())
        assert len(r1) == 10 and len(r2) == 20 and len(r3) == 30
        # One client window (10) and ONE coalesced forward window (50)
        # — never a 60-row concatenation of the two classes.
        assert sorted(sizes) == [10, 50]


class TestForwardJobsApi:
    def test_submit_failure_yields_prefailed_future_not_raise(self):
        """forward_jobs never raises: sibling connections' rows still
        decide when one submit overflows (the jobs carry the error)."""
        from ratelimiter_tpu.observability.metrics import Registry

        core = FleetCore(_map([("a", 1, (0, 16)),
                               ("b", free_port(), (16, 32))]),
                         "a", forward_deadline=0.2, registry=Registry())
        core.close()  # lane submits now fail
        h = np.arange(8, dtype=np.uint64)
        jobs = core.forward_jobs(1, np.arange(8), splitmix64(h),
                                 np.ones(8, dtype=np.int64))
        assert jobs
        for pos, fut in jobs:
            assert fut.exception(timeout=1) is not None
