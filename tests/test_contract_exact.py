"""Contract suite instantiated for the exact backend (the oracle)."""

from tests.contract import ContractTests


class TestExactContract(ContractTests):
    backend = "exact"
