"""Fused Pallas kernels vs the jnp reference — BIT-IDENTICAL (ADR-011).

The ``kernels`` knob is an execution choice, not a semantic one: a
limiter built with ``kernels="pallas"`` (interpret mode on this CPU CI —
same numerics as a compiled TPU kernel) must produce exactly the same
decisions, remaining, retry and reset as ``kernels="jnp"``, decision for
decision, across sub-window rollovers, policy overrides, conservative
and vanilla updates, the token-bucket variant, and the lax.scan path.
Any drift here would make the knob silently re-shape admissions — these
tests are the contract that keeps ``kernels`` out of the checkpoint
fingerprint.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import pytest

from ratelimiter_tpu import Algorithm, Config, SketchParams
from ratelimiter_tpu.algorithms.sketch import (
    SketchLimiter,
    SketchTokenBucketLimiter,
)
from ratelimiter_tpu.core.clock import ManualClock
from ratelimiter_tpu.core.errors import InvalidConfigError

T0 = 1_000_000.0


def _cfg(kernels: str, *, algo=Algorithm.SLIDING_WINDOW, cu=True,
         limit=7, hh=0) -> Config:
    return Config(
        algorithm=algo, limit=limit, window=6.0,
        sketch=SketchParams(depth=3, width=128, sub_windows=6,
                            conservative_update=cu, hh_slots=hh,
                            kernels=kernels))


def _pair(kernels_cfg: Config):
    cls = (SketchTokenBucketLimiter
           if kernels_cfg.algorithm is Algorithm.TOKEN_BUCKET
           else SketchLimiter)
    jnp_cfg = dataclasses.replace(
        kernels_cfg,
        sketch=dataclasses.replace(kernels_cfg.sketch, kernels="jnp"))
    return (cls(kernels_cfg, ManualClock(T0)), cls(jnp_cfg, ManualClock(T0)))


def _assert_same(a, b):
    np.testing.assert_array_equal(np.asarray(a.allowed),
                                  np.asarray(b.allowed))
    np.testing.assert_array_equal(np.asarray(a.remaining),
                                  np.asarray(b.remaining))
    np.testing.assert_array_equal(np.asarray(a.retry_after),
                                  np.asarray(b.retry_after))
    np.testing.assert_array_equal(np.asarray(a.reset_at),
                                  np.asarray(b.reset_at))


def _drive(lp, lj, *, steps=14, batch=48, n_keys=24, seed=0,
           advance=0.75):
    """Drive both limiters with the same Zipf-ish trace across several
    sub-window rollovers (sub-window = 1 s; advance 0.75 s/step crosses
    boundaries at the same virtual instants for both) and compare every
    field of every batch bit-exactly."""
    rng = np.random.default_rng(seed)
    for step in range(steps):
        ids = rng.integers(1, n_keys, size=batch).astype(np.uint64)
        ns = rng.integers(1, 3, size=batch).astype(np.int64)
        rp = lp.allow_ids(ids, ns)
        rj = lj.allow_ids(ids, ns)
        _assert_same(rp, rj)
        lp.clock.advance(advance)
        lj.clock.advance(advance)


@pytest.mark.parametrize("cu", [True, False])
@pytest.mark.parametrize("algo", [Algorithm.SLIDING_WINDOW,
                                  Algorithm.FIXED_WINDOW])
def test_windowed_parity_across_rollovers(algo, cu):
    lp, lj = _pair(_cfg("pallas", algo=algo, cu=cu))
    try:
        _drive(lp, lj)
    finally:
        lp.close()
        lj.close()


def test_token_bucket_parity():
    lp, lj = _pair(_cfg("pallas", algo=Algorithm.TOKEN_BUCKET))
    try:
        _drive(lp, lj, advance=0.4)
    finally:
        lp.close()
        lj.close()


def test_policy_override_parity():
    lp, lj = _pair(_cfg("pallas"))
    try:
        for lim in (lp, lj):
            lim.set_override("whale", 50)
            lim.set_override("guppy", 2)
        keys = (["whale"] * 20 + ["guppy"] * 6 + ["plain"] * 10) * 2
        for _ in range(6):
            rp = lp.allow_batch(keys)
            rj = lj.allow_batch(keys)
            _assert_same(rp, rj)
            if rp.limits is None:
                assert rj.limits is None
            else:
                np.testing.assert_array_equal(rp.limits, rj.limits)
            lp.clock.advance(0.9)
            lj.clock.advance(0.9)
    finally:
        lp.close()
        lj.close()


def test_scan_path_parity():
    """build_scan honors the kernels knob: a pallas-kernel scan equals
    the jnp-kernel scan bit for bit (packed masks AND final state)."""
    import jax.numpy as jnp

    from ratelimiter_tpu.ops import sketch_kernels as sk

    T0_US = 1_700_000_000 * 1_000_000
    cfgs = {k: Config(algorithm=Algorithm.SLIDING_WINDOW, limit=9,
                      window=6.0,
                      sketch=SketchParams(depth=3, width=64, sub_windows=6,
                                          kernels=k))
            for k in ("pallas", "jnp")}
    rng = np.random.default_rng(5)
    T, B = 4, 16
    h1 = rng.integers(0, 2 ** 32, size=(T, B), dtype=np.uint32)
    h2 = rng.integers(0, 2 ** 32, size=(T, B), dtype=np.uint32) | 1
    ns = np.ones((T, B), np.int32)
    outs = {}
    for k, cfg in cfgs.items():
        _, sub, _, _, _ = sk.sketch_geometry(cfg)
        _, _, roll = sk.build_steps(cfg)
        st = roll(sk.init_state(cfg), jnp.int64(T0_US // sub))
        scan = sk.build_scan(cfg)
        st, packed, denies = scan(st, jnp.asarray(h1), jnp.asarray(h2),
                                  jnp.asarray(ns), jnp.int64(T0_US),
                                  jnp.int64(1000))
        outs[k] = (np.asarray(packed), np.asarray(denies),
                   {kk: np.asarray(v) for kk, v in st.items()})
    np.testing.assert_array_equal(outs["pallas"][0], outs["jnp"][0])
    np.testing.assert_array_equal(outs["pallas"][1], outs["jnp"][1])
    for kk in outs["jnp"][2]:
        np.testing.assert_array_equal(outs["pallas"][2][kk],
                                      outs["jnp"][2][kk])


def test_reset_parity_after_mixed_traffic():
    lp, lj = _pair(_cfg("pallas"))
    try:
        keys = ["a"] * 6 + ["b"] * 3
        for lim in (lp, lj):
            lim.allow_batch(keys)
            lim.reset("a")
        rp = lp.allow_batch(keys)
        rj = lj.allow_batch(keys)
        _assert_same(rp, rj)
    finally:
        lp.close()
        lj.close()


def test_auto_resolves_jnp_off_tpu():
    from ratelimiter_tpu.ops import pallas_sketch

    cfg = _cfg("auto")
    assert pallas_sketch.resolve_kernels(cfg) == "jnp"  # CPU backend


def test_pallas_rejects_hh_side_table():
    from ratelimiter_tpu.ops import pallas_sketch

    cfg = _cfg("pallas", hh=64)
    with pytest.raises(InvalidConfigError):
        pallas_sketch.resolve_kernels(cfg)
    # auto with hh falls back silently (the side table is a supported
    # configuration; the fused kernels just don't cover it).
    assert pallas_sketch.resolve_kernels(_cfg("auto", hh=64)) == "jnp"


def test_kernels_knob_validated():
    with pytest.raises(InvalidConfigError):
        _cfg("mosaic").validate()
    _cfg("pallas").validate()
    _cfg("jnp").validate()


def test_kernels_knob_excluded_from_fingerprint():
    from ratelimiter_tpu.checkpoint import config_fingerprint

    assert (config_fingerprint(_cfg("pallas"))
            == config_fingerprint(_cfg("jnp"))
            == config_fingerprint(_cfg("auto")))
