"""Contract suite instantiated for the dense device backend, plus
dense-specific behavior (slot capacity, recycling, fault injection)."""

import pytest

from tests.contract import ContractTests

from ratelimiter_tpu import (
    Algorithm,
    Config,
    DenseParams,
    ManualClock,
    StorageUnavailableError,
    create_limiter,
)


class TestDenseContract(ContractTests):
    backend = "dense"
    supports_failure_injection = True

    def inject_failure(self, lim) -> None:
        lim.inject_failure()


def make(algo=Algorithm.FIXED_WINDOW, limit=5, window=60.0, capacity=8, **kw):
    clock = ManualClock()
    cfg = Config(algorithm=algo, limit=limit, window=window,
                 dense=DenseParams(capacity=capacity), **kw)
    return create_limiter(cfg, backend="dense", clock=clock), clock


class TestDenseSlots:
    def test_capacity_exhaustion_fail_closed(self):
        lim, _ = make(capacity=2)
        lim.allow("a")
        lim.allow("b")
        with pytest.raises(StorageUnavailableError):
            lim.allow("c")
        lim.close()

    def test_capacity_exhaustion_fail_open(self):
        lim, _ = make(capacity=2, fail_open=True)
        lim.allow("a")
        lim.allow("b")
        res = lim.allow("c")
        assert res.allowed and res.fail_open
        lim.close()

    def test_prune_recycles_slots(self):
        lim, clock = make(capacity=2, window=10.0)
        lim.allow("a")
        lim.allow("b")
        clock.advance(21.0)  # 2x window -> TTL horizon
        lim.allow("c")       # forces prune of a/b instead of failing
        assert lim.key_count() == 1
        lim.close()

    def test_recycled_slot_state_is_fresh(self):
        lim, clock = make(algo=Algorithm.TOKEN_BUCKET, limit=3, capacity=1,
                          window=10.0)
        assert lim.allow_n("a", 3).allowed      # drain a's bucket
        clock.advance(21.0)
        assert lim.allow_n("b", 3).allowed      # b reuses a's slot, starts full
        lim.close()

    def test_reset_frees_slot(self):
        lim, _ = make(capacity=1)
        lim.allow("a")
        lim.reset("a")
        assert lim.allow("b").allowed  # slot available again
        lim.close()

    def test_heal_after_injected_failure(self):
        lim, _ = make(fail_open=True)
        lim.inject_failure()
        assert lim.allow("k").fail_open
        lim.heal()
        assert not lim.allow("k").fail_open
        lim.close()

    def test_large_batch_padding(self):
        lim, _ = make(capacity=64, limit=100)
        keys = [f"k{i % 50}" for i in range(100)]  # non-power-of-two batch
        out = lim.allow_batch(keys)
        assert out.allow_count == 100
        lim.close()
