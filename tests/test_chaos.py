"""Tier-1 chaos suite (ADR-015): failure domains proven by fault
injection.

Acceptance contract (ISSUE 8): with one slice killed mid-traffic at
n=8, healthy slices' decisions are BIT-IDENTICAL to a no-fault oracle;
the dead slice's range answers per the configured fail-open/fail-closed
policy within one deadline budget; after probe recovery + snapshot
restore the slice serves exact overrides and counters within one
snapshot interval; and with the injection seam disabled the hot path is
byte-identical. Every scenario is seeded-deterministic so failures
replay.
"""

import asyncio
import os
import sys
import time

import jax
import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ratelimiter_tpu import Algorithm, Config, MeshSpec, SketchParams, chaos
from ratelimiter_tpu.chaos.injector import ChaosInjector, SliceFault
from ratelimiter_tpu.core.errors import (
    DeadlineExceededError,
    InvalidKeyError,
    StorageUnavailableError,
)
from ratelimiter_tpu.parallel.limiter import SlicedMeshLimiter, build_slices
from ratelimiter_tpu.parallel.quarantine import (
    QuarantineManager,
    SliceGuard,
    classify_failure,
)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices")

N = 8
T0 = 1_700_000_000.0


def _cfg(devices: int = N, **kw):
    # Acceptance scenarios (kill-mid-traffic oracle + both doors) run at
    # the full n=8; unit-scoped scenarios run at n=4 — the mechanics are
    # identical and each composite costs 8 limiter builds worth of
    # compile otherwise (tier-1 wall-clock budget).
    base = dict(
        algorithm=Algorithm.SLIDING_WINDOW,
        limit=10,
        window=60.0,
        fail_open=True,
        sketch=SketchParams(depth=2, width=1 << 10, sub_windows=6),
        mesh=MeshSpec(devices=devices, quarantine=True, slice_deadline=5.0,
                      probe_interval=0.05),
    )
    base.update(kw)
    return Config(**base)


@pytest.fixture(autouse=True)
def _chaos_clean():
    yield
    chaos.uninstall()


def _warm(lim, ids):
    lim.allow_ids(ids, now=T0 - 1.0)


# ------------------------------------------------------------ classifier


class TestClassifier:
    def test_backend_faults_quarantine(self):
        assert classify_failure(StorageUnavailableError("x"))
        assert classify_failure(SliceFault("x"))
        assert classify_failure(DeadlineExceededError("x"))
        assert classify_failure(TimeoutError())
        assert classify_failure(OSError())
        assert classify_failure(RuntimeError("xla fell over"))

    def test_caller_errors_do_not(self):
        from ratelimiter_tpu.core.errors import (
            CheckpointError,
            ClosedError,
            InvalidConfigError,
            InvalidNError,
        )

        for exc in (InvalidKeyError("k"), InvalidNError("n"),
                    InvalidConfigError("c"), ClosedError("z"),
                    CheckpointError("cp"), NotImplementedError(),
                    TypeError()):
            assert not classify_failure(exc), exc


# ------------------------------------------------- kill-a-slice (direct)


class TestKillSlice:
    def test_healthy_ranges_bit_identical_to_no_fault_oracle(self):
        """The acceptance oracle: same id traffic through a faulted
        quarantine-mesh and a fault-free QUARANTINE-OFF mesh — rows
        owned by healthy slices must match bit for bit (incl. frames
        decided mid-fault), and the guard layer itself must be
        decision-transparent."""
        cfg = _cfg()
        lim = SlicedMeshLimiter(cfg)
        oracle = SlicedMeshLimiter(_cfg(mesh=MeshSpec(devices=N)))
        victim = 2
        rng = np.random.default_rng(7)
        frames = [rng.integers(1, 1 << 40, size=256, dtype=np.uint64)
                  for _ in range(6)]
        _warm(lim, frames[0])
        _warm(oracle, frames[0])
        inj = chaos.install(seed=1)
        try:
            got, want = [], []
            for i, ids in enumerate(frames):
                if i == 3:  # mid-traffic kill
                    inj.fail_slice(victim)
                now = T0 + i * 0.25
                got.append(lim.allow_ids(ids, now=now))
                want.append(oracle.allow_ids(ids, now=now))
            owners = lim.owner_of_id(np.concatenate(frames))
            got_allowed = np.concatenate([g.allowed for g in got])
            want_allowed = np.concatenate([w.allowed for w in want])
            got_rem = np.concatenate([g.remaining for g in got])
            want_rem = np.concatenate([w.remaining for w in want])
            healthy = owners != victim
            np.testing.assert_array_equal(got_allowed[healthy],
                                          want_allowed[healthy])
            np.testing.assert_array_equal(got_rem[healthy],
                                          want_rem[healthy])
            # Post-kill victim rows: fail-open allowances, flagged.
            post = np.concatenate(
                [np.full(256, i >= 3) for i in range(6)])
            vict_rows = got_allowed[(owners == victim) & post]
            assert vict_rows.size and vict_rows.all()
            assert any(g.fail_open for g in got[3:])
            assert not any(g.fail_open for g in got[:3])
            assert lim.quarantine.state(victim) != "healthy"
        finally:
            lim.close()
            oracle.close()

    def test_fail_closed_range_errors_with_slice_attribution(self):
        cfg = _cfg(devices=4, fail_open=False)
        lim = SlicedMeshLimiter(cfg)
        ids = np.arange(1, 257, dtype=np.uint64)
        _warm(lim, ids)
        inj = chaos.install(seed=2)
        inj.fail_slice(1)
        try:
            with pytest.raises(StorageUnavailableError) as ei:
                lim.allow_ids(ids, now=T0)
            assert getattr(ei.value, "slice_index", None) == 1
        finally:
            lim.close()

    def test_caller_errors_pass_through_without_quarantine(self):
        lim = SlicedMeshLimiter(_cfg(devices=4))
        try:
            with pytest.raises(InvalidKeyError):
                lim.allow_n("", 1)
            assert lim.quarantine.quarantined() == []
        finally:
            lim.close()

    def test_scalar_path_degrades_too(self):
        lim = SlicedMeshLimiter(_cfg(devices=4))
        # Find a key owned by slice 2, then kill slice 2.
        key = next(f"k{i}" for i in range(200)
                   if lim.owner_of_key(f"k{i}") == 2)
        lim.allow_n(key, 1, now=T0)
        inj = chaos.install(seed=3)
        inj.fail_slice(2)
        try:
            res = lim.allow_n(key, 1, now=T0 + 0.1)
            assert res.allowed and res.fail_open
            assert res.limit == lim.config.limit
        finally:
            lim.close()


# ------------------------------------------- slow/wedged slice deadlines


class TestSliceDeadline:
    def test_wedged_slice_answers_within_one_deadline_budget(self):
        deadline = 0.3
        cfg = _cfg(mesh=MeshSpec(devices=4, quarantine=True,
                                 slice_deadline=deadline,
                                 probe_interval=30.0))
        lim = SlicedMeshLimiter(cfg)
        ids = np.arange(1, 513, dtype=np.uint64)
        _warm(lim, ids)
        victim = int(lim.owner_of_id(ids[:1])[0])
        inj = chaos.install(seed=4)
        inj.wedge_slice(victim)
        try:
            t0 = time.perf_counter()
            out = lim.allow_ids(ids, now=T0)
            elapsed = time.perf_counter() - t0
            # One deadline budget + bookkeeping slack — never the
            # multi-second hang the pre-ADR-015 barrier would take.
            assert elapsed < deadline * 2 + 1.0, elapsed
            assert out.fail_open
            assert lim.quarantine.state(victim) != "healthy"
            # Subsequent frames skip the wedged slice entirely (fast).
            t1 = time.perf_counter()
            out2 = lim.allow_ids(ids, now=T0 + 0.1)
            assert time.perf_counter() - t1 < deadline
            assert out2.fail_open
        finally:
            inj.clear_slice(victim)
            lim.close()

    def test_slow_slice_quarantines_then_recovers(self):
        deadline = 0.15
        cfg = _cfg(mesh=MeshSpec(devices=4, quarantine=True,
                                 slice_deadline=deadline,
                                 probe_interval=0.05))
        lim = SlicedMeshLimiter(cfg)
        ids = np.arange(1, 257, dtype=np.uint64)
        _warm(lim, ids)
        victim = int(lim.owner_of_id(ids[:1])[0])
        inj = chaos.install(seed=5)
        inj.delay_slice(victim, 4 * deadline)
        try:
            out = lim.allow_ids(ids, now=T0)
            assert out.fail_open
            assert lim.quarantine.state(victim) != "healthy"
            inj.clear_slice(victim)
            deadline_at = time.time() + 30.0
            while (lim.quarantine.state(victim) != "healthy"
                   and time.time() < deadline_at):
                lim.quarantine.probe_now(victim)
                time.sleep(0.02)
            assert lim.quarantine.state(victim) == "healthy"
            out3 = lim.allow_ids(ids, now=T0 + 1.0)
            assert not out3.fail_open
        finally:
            lim.close()


# ------------------------------------- probe recovery + snapshot restore


class TestRecoveryRestore:
    def test_recovery_restores_snapshot_plus_wal_suffix(self, tmp_path):
        """Restore-before-rejoin: after a kill + heal, the victim slice
        serves EXACT overrides (snapshot + WAL replay) and counters
        within one snapshot interval."""
        from ratelimiter_tpu import PersistenceSpec
        from ratelimiter_tpu.observability.metrics import Registry
        from ratelimiter_tpu.persistence import PersistenceManager

        cfg = _cfg(devices=4,
                   persistence=PersistenceSpec(dir=str(tmp_path),
                                               snapshot_interval=3600.0))
        lim = SlicedMeshLimiter(cfg)
        mgr = PersistenceManager(cfg.persistence, registry=Registry())
        top = mgr.wrap(lim)
        mgr.attach([top])
        lim.quarantine.restore_fn = mgr.slice_restorer()
        victim = 3
        vkey = next(f"u{i}" for i in range(300)
                    if lim.owner_of_key(f"u{i}") == victim)
        try:
            top.set_override(vkey, 77)           # pre-snapshot override
            for i in range(8):                   # consume quota
                top.allow_n(vkey, 1, now=T0 + i * 0.01)
            mgr.snapshot_now()
            top.set_override(f"{vkey}:wal", 55)  # WAL-suffix override
            inj = chaos.install(seed=6)
            inj.fail_slice(victim)
            out = top.allow_n(vkey, 1, now=T0 + 1.0)
            assert out.fail_open
            assert lim.quarantine.state(victim) != "healthy"
            # More WAL mutations while degraded (write-all still lands).
            top.set_override(f"{vkey}:during", 33)
            inj.clear_slice(victim)
            assert lim.quarantine.probe_now(victim)
            assert lim.quarantine.state(victim) == "healthy"
            # Overrides exact after restore + WAL suffix.
            assert lim.get_override(vkey).limit == 77
            assert lim.get_override(f"{vkey}:wal").limit == 55
            assert lim.get_override(f"{vkey}:during").limit == 33
            # Counters within one snapshot interval: the 8 pre-snapshot
            # units are restored, so the next 2 exhaust the 77-override
            # far from fresh — remaining must reflect restored usage.
            res = top.allow_n(vkey, 1, now=T0 + 2.0)
            assert res.allowed and not res.fail_open
            assert res.remaining <= 77 - 9
        finally:
            mgr.stop(final_snapshot=False)
            top.close()

    def test_probe_failure_reopens_and_restore_failure_blocks_rejoin(self):
        lim = SlicedMeshLimiter(_cfg(mesh=MeshSpec(
            devices=4, quarantine=True, slice_deadline=1.0,
            probe_interval=0.01)))
        ids = np.arange(1, 65, dtype=np.uint64)
        _warm(lim, ids)
        victim = int(lim.owner_of_id(ids[:1])[0])
        inj = chaos.install(seed=7)
        inj.fail_slice(victim)
        try:
            lim.allow_ids(ids, now=T0)
            # Probe while the fault is still armed: must re-open.
            assert not lim.quarantine.probe_now(victim)
            assert lim.quarantine.state(victim) == "quarantined"
            # Heal the device but make restore fail: stays quarantined
            # (restore-before-rejoin is an invariant, not best-effort).
            inj.clear_slice(victim)
            calls = []

            def bad_restore(idx):
                calls.append(idx)
                raise RuntimeError("restore target unavailable")

            lim.quarantine.restore_fn = bad_restore
            assert not lim.quarantine.probe_now(victim)
            assert calls == [victim]
            assert lim.quarantine.state(victim) == "quarantined"
            lim.quarantine.restore_fn = None
            assert lim.quarantine.probe_now(victim)
        finally:
            lim.close()


# --------------------------------------------- breaker scoping satellite


class TestBreakerScoping:
    def test_single_slice_fault_storm_leaves_other_ranges_admitting(self):
        from ratelimiter_tpu.observability import CircuitBreakerDecorator
        from ratelimiter_tpu.observability.metrics import Registry

        lim = SlicedMeshLimiter(_cfg(devices=4))
        breaker = CircuitBreakerDecorator(lim, failure_threshold=3,
                                          cooldown=60.0,
                                          registry=Registry())
        ids = np.arange(1, 513, dtype=np.uint64)
        _warm(lim, ids)
        victim = 2
        inj = chaos.install(seed=8)
        inj.fail_slice(victim)
        try:
            for i in range(10):  # a storm: 10 consecutive failed frames
                out = breaker.allow_ids(ids, now=T0 + i * 0.01)
                assert out.fail_open
            # The whole-keyspace breaker must NOT have tripped...
            assert breaker.state == "closed"
            # ...while the victim's scoped state did.
            assert breaker.sub_state(victim, now=T0 + 1.0) == "open"
            # Other ranges still reach the backend and decide exactly —
            # a frame not touching the victim is NOT fail-open.
            owners = lim.owner_of_id(ids)
            healthy_ids = np.ascontiguousarray(ids[owners != victim])
            res = breaker.allow_ids(healthy_ids, now=T0 + 2.0)
            assert not res.fail_open
        finally:
            lim.close()

    def test_unattributed_failures_still_trip_globally(self):
        from ratelimiter_tpu.observability import CircuitBreakerDecorator
        from ratelimiter_tpu.observability.metrics import Registry

        lim = SlicedMeshLimiter(_cfg(devices=4,
                                     mesh=MeshSpec(devices=4)))
        breaker = CircuitBreakerDecorator(lim, failure_threshold=2,
                                          cooldown=60.0,
                                          registry=Registry())
        try:
            for s in lim.slices:
                s.inject_failure(StorageUnavailableError("backend down"))
            out1 = breaker.allow_batch(["a", "b"], now=T0)
            out2 = breaker.allow_batch(["c", "d"], now=T0 + 0.01)
            assert out1.fail_open and out2.fail_open
            assert breaker.state == "open"
        finally:
            lim.close()


# --------------------------------------------------- e2e through the doors


#: Shared door-test traffic (both doors drive IDENTICAL frames, so ONE
#: no-fault oracle trace serves both — an 8-slice composite's compiles
#: are the suite's dominant cost).
_DOOR_VICTIM = 2
_DOOR_FRAMES = [np.random.default_rng(11).integers(
    1, 1 << 40, size=(6, 512), dtype=np.uint64)[i] for i in range(6)]
_DOOR_ORACLE: dict = {}


def _door_oracle():
    """(owners over all frames, per-frame no-fault BatchResults,
    owners of frames[0]) — computed once, replayed for both doors."""
    if not _DOOR_ORACLE:
        oracle = SlicedMeshLimiter(_cfg(limit=1000,
                                        mesh=MeshSpec(devices=N)))
        try:
            _warm(oracle, _DOOR_FRAMES[0])
            want = [oracle.allow_ids(ids) for ids in _DOOR_FRAMES]
            _DOOR_ORACLE.update(
                owners=oracle.owner_of_id(np.concatenate(_DOOR_FRAMES)),
                want_allowed=np.concatenate([w.allowed for w in want]),
                frame0_owners=oracle.owner_of_id(_DOOR_FRAMES[0]))
        finally:
            oracle.close()
    return _DOOR_ORACLE


class TestChaosAsyncioDoor:
    def test_kill_slice_mid_traffic_end_to_end(self):
        from ratelimiter_tpu.serving.client import AsyncClient
        from ratelimiter_tpu.serving.server import RateLimitServer

        cfg = _cfg(limit=1000)
        orc = _door_oracle()
        victim = _DOOR_VICTIM
        frames = _DOOR_FRAMES

        async def main():
            lim = SlicedMeshLimiter(cfg)
            _warm(lim, frames[0])
            srv = RateLimitServer(lim, max_delay=1e-4)
            await srv.start()
            c = await AsyncClient.connect(port=srv.port)
            inj = chaos.install(seed=12)
            got = []
            t_frame = []
            for i, ids in enumerate(frames):
                if i == 3:
                    inj.fail_slice(victim)
                t0 = time.perf_counter()
                got.append(await c.allow_hashed(ids, deadline=30.0))
                t_frame.append(time.perf_counter() - t0)
            # Healthy-owned rows bit-identical to the no-fault oracle,
            # through the real wire (coalesced T_RESULT_HASHED frames).
            owners = orc["owners"]
            got_allowed = np.concatenate([g.allowed for g in got])
            healthy = owners != victim
            np.testing.assert_array_equal(got_allowed[healthy],
                                          orc["want_allowed"][healthy])
            # Satellite 3: a quarantined slice's rows in the coalesced
            # hashed frame carry the batch fail_open flag with LIVE
            # limit/window values.
            assert all(g.fail_open for g in got[3:])
            assert not any(g.fail_open for g in got[:3])
            lim.update_limit(777)
            post = await c.allow_hashed(frames[0])
            assert post.fail_open
            assert post.limit == 777
            vmask = orc["frame0_owners"] == victim
            now = time.time()
            resets = np.asarray(post.reset_at)[vmask]
            assert np.all(resets > now - 5.0)
            assert np.all(resets < now + float(cfg.window) + 5.0)
            # No multi-second p99: every frame within a deadline-ish
            # budget (kill faults fail fast; bound generously for CI).
            assert max(t_frame[3:]) < 5.0, t_frame
            await c.close()
            await srv.shutdown()
            lim.close()

        asyncio.run(main())


class TestChaosNativeDoor:
    def test_kill_slice_mid_traffic_end_to_end(self):
        from ratelimiter_tpu.serving.native_server import (
            NativeRateLimitServer,
            native_server_available,
        )
        if not native_server_available():
            pytest.skip("no compiler for the native front door")
        from ratelimiter_tpu.serving.client import Client

        cfg = _cfg(limit=1000, mesh=MeshSpec(devices=N))
        slices = build_slices(cfg)
        qmgr = QuarantineManager(len(slices), clock=slices[0].clock,
                                 probe_interval=30.0)
        guards = [SliceGuard(s, i, qmgr, deadline=5.0)
                  for i, s in enumerate(slices)]
        srv = NativeRateLimitServer(guards[0], shard_limiters=guards,
                                    max_delay=1e-4)
        srv.start()
        qmgr.on_state_change = (
            lambda i, st: srv.set_shard_health(i, st != "healthy"))
        orc = _door_oracle()
        victim = _DOOR_VICTIM
        frames = _DOOR_FRAMES
        inj = chaos.install(seed=14)
        try:
            with Client(port=srv.port, timeout=120.0) as c:
                got = []
                for i, ids in enumerate(frames):
                    if i == 3:
                        inj.fail_slice(victim)
                    got.append(c.allow_hashed(ids, deadline=60.0))
                owners = orc["owners"]
                got_allowed = np.concatenate([g.allowed for g in got])
                healthy = owners != victim
                np.testing.assert_array_equal(got_allowed[healthy],
                                              orc["want_allowed"][healthy])
                assert all(g.fail_open for g in got[3:])
                assert not any(g.fail_open for g in got[:3])
                # Live limit/window in degraded rows after an update
                # through the server (satellite 3, native half).
                srv.update_limit(888)
                post = c.allow_hashed(frames[0])
                assert post.fail_open and post.limit == 888
                st = srv.stats()
                assert st["shard_quarantined"][victim] == 1
                assert sum(st["shard_quarantined"]) == 1
        finally:
            chaos.uninstall()
            srv.shutdown(close_limiters=False)
            for g in guards:
                g.close()


# ----------------------------------------------------------- DCN chaos


class TestDcnChaos:
    def _pusher_pair(self, secret=None):
        from ratelimiter_tpu import ManualClock, create_limiter
        from ratelimiter_tpu.serving.dcn_peer import DcnPusher
        from ratelimiter_tpu.serving.server import RateLimitServer

        cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=10,
                     window=60.0,
                     sketch=SketchParams(depth=2, width=1 << 10,
                                         sub_windows=4))
        # Virtual time on the SENDER: the pusher's export cadence reads
        # the limiter's clock, so a wall clock would roll the whole ring
        # past the test's traffic before the first export.
        sender = create_limiter(cfg, backend="sketch",
                                clock=ManualClock(T0))
        receiver = create_limiter(cfg, backend="sketch")
        return cfg, sender, receiver, DcnPusher, RateLimitServer

    def test_partition_drops_frames_and_clears(self):
        cfg, sender, receiver, DcnPusher, RateLimitServer = \
            self._pusher_pair()

        async def main():
            srv = RateLimitServer(receiver, dcn=True)
            await srv.start()
            push = DcnPusher(sender, [("127.0.0.1", srv.port)],
                             interval=3600.0)
            loop = asyncio.get_running_loop()
            try:
                sender.allow_batch([f"k{i}" for i in range(64)], now=T0)
                # Roll the window forward so a completed sub-window slab
                # exists to export (the pusher syncs to the sender's
                # manual clock).
                sender.clock.set(T0 + 31.0)
                sender.allow_batch(["roll"], now=T0 + 31.0)
                inj = chaos.install(seed=21)
                inj.partition_dcn(1.0)
                delivered = await loop.run_in_executor(
                    None, push.sync_once)
                assert delivered == 0
                assert inj.dcn_dropped >= 1
                assert push.pushes_failed >= 1
                # Partition heals: the next cycle retries the slabs
                # (per-peer watermarks) and delivers.
                inj.clear()
                delivered2 = await loop.run_in_executor(
                    None, push.sync_once)
                assert delivered2 >= 1
            finally:
                push.stop()
                await srv.shutdown()

        asyncio.run(main())
        sender.close()
        receiver.close()

    def test_corruption_rejected_by_hmac_no_mass_merged(self):
        cfg, sender, receiver, DcnPusher, RateLimitServer = \
            self._pusher_pair(secret="s3cret")

        async def main():
            srv = RateLimitServer(receiver, dcn=True, dcn_secret="s3cret")
            await srv.start()
            push = DcnPusher(sender, [("127.0.0.1", srv.port)],
                             interval=3600.0, secret="s3cret")
            loop = asyncio.get_running_loop()
            try:
                sender.allow_batch([f"c{i}" for i in range(64)], now=T0)
                sender.clock.set(T0 + 31.0)
                sender.allow_batch(["roll"], now=T0 + 31.0)
                before = int(receiver.in_window_admitted_mass())
                inj = chaos.install(seed=22)
                inj.corrupt_dcn(1.0)
                delivered = await loop.run_in_executor(
                    None, push.sync_once)
                assert delivered == 0
                assert inj.dcn_corrupted >= 1
                # The corrupted push must merge NOTHING (HMAC covers the
                # body, and the flip landed inside it).
                assert int(receiver.in_window_admitted_mass()) == before
            finally:
                push.stop()
                await srv.shutdown()

        asyncio.run(main())
        sender.close()
        receiver.close()


# ------------------------------------------------- snapshot-stall chaos


class TestSnapshotStall:
    def test_stalled_snapshot_thread_never_blocks_decisions(self, tmp_path):
        from ratelimiter_tpu import PersistenceSpec, create_limiter
        from ratelimiter_tpu.observability.metrics import Registry
        from ratelimiter_tpu.persistence import PersistenceManager

        cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=100,
                     window=60.0,
                     sketch=SketchParams(depth=2, width=1 << 10,
                                         sub_windows=4),
                     persistence=PersistenceSpec(dir=str(tmp_path),
                                                 snapshot_interval=3600.0))
        lim = create_limiter(cfg, backend="sketch")
        mgr = PersistenceManager(cfg.persistence, registry=Registry())
        top = mgr.wrap(lim)
        mgr.attach([top])
        ids = np.arange(1, 257, dtype=np.uint64)
        lim.allow_hashed(ids, now=T0)
        inj = chaos.install(seed=31)
        inj.stall_snapshot(1.0)
        import threading

        t = threading.Thread(target=mgr.snapshot_now, daemon=True)
        t.start()
        time.sleep(0.1)  # snapshot thread is now inside the stall
        t0 = time.perf_counter()
        lim.allow_hashed(ids, now=T0 + 0.5)
        decide_s = time.perf_counter() - t0
        t.join(timeout=30)
        assert not t.is_alive()
        assert inj.snapshot_stalls == 1
        # The stall happened BEFORE capture takes the lock: decisions
        # during it must not pay the stall.
        assert decide_s < 0.5, decide_s
        mgr.stop(final_snapshot=False)
        top.close()


# ---------------------------------------------- deadline shedding (doors)


class TestDeadlineShedding:
    def test_asyncio_door_sheds_expired_work_per_policy(self):
        from ratelimiter_tpu import create_limiter
        from ratelimiter_tpu.serving import protocol as p
        from ratelimiter_tpu.serving.client import AsyncClient
        from ratelimiter_tpu.serving.server import RateLimitServer

        cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=10,
                     window=60.0, fail_open=True,
                     sketch=SketchParams(depth=2, width=1 << 10,
                                         sub_windows=4))

        async def main():
            lim = create_limiter(cfg, backend="sketch")
            srv = RateLimitServer(lim)
            await srv.start()
            c = await AsyncClient.connect(port=srv.port)
            # Expired-on-arrival frame (raw, the client refuses to send
            # one): fail-open policy answers an allowance stamped
            # fail_open — no dispatch slot burned.
            raw = p.with_deadline(p.encode_allow_n(50, "k", 1), -1.0)
            _, body = await c._request_once(raw, 50)
            res = p.parse_result(body)
            assert res.allowed and res.fail_open
            # Hashed frame, same contract.
            ids = np.arange(1, 65, dtype=np.uint64)
            raw = p.with_deadline(p.encode_allow_hashed(51, ids), 0.0)
            t, body = await c._request_once(raw, 51)
            assert t == p.T_RESULT_HASHED
            br = p.parse_result_hashed(body)
            assert br.fail_open and bool(np.all(br.allowed))
            # A generous deadline passes through untouched.
            live = await c.allow_n("k2", 1, deadline=30.0)
            assert not live.fail_open
            await c.close()
            await srv.shutdown()
            lim.close()

        asyncio.run(main())

    def test_asyncio_door_fail_closed_sheds_with_typed_error(self):
        from ratelimiter_tpu import create_limiter
        from ratelimiter_tpu.serving import protocol as p
        from ratelimiter_tpu.serving.client import AsyncClient
        from ratelimiter_tpu.serving.server import RateLimitServer

        cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=10,
                     window=60.0, fail_open=False,
                     sketch=SketchParams(depth=2, width=1 << 10,
                                         sub_windows=4))

        async def main():
            lim = create_limiter(cfg, backend="sketch")
            srv = RateLimitServer(lim)
            await srv.start()
            c = await AsyncClient.connect(port=srv.port)
            raw = p.with_deadline(p.encode_allow_n(60, "k", 1), -1.0)
            with pytest.raises(DeadlineExceededError):
                await c._request_once(raw, 60)
            await c.close()
            await srv.shutdown()
            lim.close()

        asyncio.run(main())

    def test_native_door_sheds_and_counts(self):
        from ratelimiter_tpu import create_limiter
        from ratelimiter_tpu.serving import protocol as p
        from ratelimiter_tpu.serving.client import Client
        from ratelimiter_tpu.serving.native_server import (
            NativeRateLimitServer,
            native_server_available,
        )
        if not native_server_available():
            pytest.skip("no compiler for the native front door")

        cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=10,
                     window=60.0, fail_open=True,
                     sketch=SketchParams(depth=2, width=1 << 10,
                                         sub_windows=4))
        lim = create_limiter(cfg, backend="sketch")
        srv = NativeRateLimitServer(lim)
        srv.start()
        try:
            import socket as sk_mod

            raw = p.with_deadline(p.encode_allow_n(70, "k", 1), -1.0)
            sk = sk_mod.create_connection(("127.0.0.1", srv.port))
            sk.sendall(raw)
            buf = b""
            while len(buf) < 13:
                buf += sk.recv(65536)
            length, type_, rid = p.parse_header(buf[:13])
            while len(buf) < 4 + length:
                buf += sk.recv(65536)
            assert rid == 70
            res = p.parse_result(buf[13:])
            assert res.allowed and res.fail_open
            sk.close()
            assert srv.stats()["deadline_shed_total"] == 1
            # Live frames unaffected (and the shed counter stays put).
            with Client(port=srv.port, timeout=60.0) as c:
                out = c.allow("k2", deadline=30.0)
                assert not out.fail_open
            assert srv.stats()["deadline_shed_total"] == 1
        finally:
            srv.shutdown(close_limiters=False)
            lim.close()


# ----------------------------------------------- determinism + zero-cost


class TestHarnessProperties:
    def test_seeded_determinism_replays_exactly(self):
        a = ChaosInjector(seed=99)
        b = ChaosInjector(seed=99)
        a.partition_dcn(0.5)
        a.corrupt_dcn(0.5)
        b.partition_dcn(0.5)
        b.corrupt_dcn(0.5)
        frame = b"x" * 64
        seq_a = [a.dcn_frame(frame) for _ in range(64)]
        seq_b = [b.dcn_frame(frame) for _ in range(64)]
        assert seq_a == seq_b
        c = ChaosInjector(seed=100)
        c.partition_dcn(0.5)
        c.corrupt_dcn(0.5)
        assert [c.dcn_frame(frame) for _ in range(64)] != seq_a

    def test_chaos_off_decisions_byte_identical(self):
        """Seam disabled (no injector installed): the quarantine-guarded
        mesh decides byte-identically to the unguarded one."""
        assert chaos.INJECTOR is None
        guarded = SlicedMeshLimiter(_cfg(devices=4))
        plain = SlicedMeshLimiter(_cfg(devices=4,
                                       mesh=MeshSpec(devices=4)))
        rng = np.random.default_rng(17)
        try:
            for i in range(4):
                ids = rng.integers(1, 1 << 40, size=512, dtype=np.uint64)
                now = T0 + i * 0.2
                g = guarded.allow_ids(ids, now=now)
                p = plain.allow_ids(ids, now=now)
                np.testing.assert_array_equal(g.allowed, p.allowed)
                np.testing.assert_array_equal(g.remaining, p.remaining)
                np.testing.assert_array_equal(g.retry_after, p.retry_after)
                np.testing.assert_array_equal(g.reset_at, p.reset_at)
                assert g.fail_open == p.fail_open
        finally:
            guarded.close()
            plain.close()
