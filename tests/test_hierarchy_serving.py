"""Hierarchical cascades through the real serving doors (ADR-020) plus
the fleet-migrate operator surface (the ADR-018 residual).

In-process gateway tests pin the /v1/tenants and /v1/fleet/migrate
endpoint contracts (opt-in, bearer gating, CRUD). Server-binary tests
prove the cascade through BOTH front doors of a real
``python -m ratelimiter_tpu.serving`` process — the wire protocol is
UNCHANGED (tenant scope derives on device from the key), decisions over
HTTP and the binary protocol share one cascade, and the AIMD controller
runs off the hot path — and through the mesh backend (per-slice share
enforcement on a 2-slice deployment).
"""

from __future__ import annotations

import json
import os
import signal as sig
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from netutil import free_port

from ratelimiter_tpu import (
    Algorithm,
    Config,
    HierarchySpec,
    ManualClock,
    create_limiter,
)
from ratelimiter_tpu.core.config import SketchParams
from ratelimiter_tpu.serving.http_gateway import HttpGateway

T0 = 1_700_000_000.0
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _req(url, method="GET", token=None):
    req = urllib.request.Request(url, method=method)
    if token:
        req.add_header("Authorization", f"Bearer {token}")
    with urllib.request.urlopen(req) as r:
        return r.status, json.loads(r.read())


def _env():
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [REPO] + env.get("PYTHONPATH", "").split(os.pathsep))
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    return env


def _wait_banner(proc, timeout=180):
    t0 = time.time()
    lines = []
    while time.time() - t0 < timeout:
        line = proc.stdout.readline()
        if not line:
            break
        lines.append(line)
        if line.startswith("serving"):
            return lines
    raise AssertionError("server never came up:\n" + "".join(lines))


# ----------------------------------------------------- gateway endpoints


def _hier_limiter():
    cfg = Config(
        algorithm=Algorithm.SLIDING_WINDOW, limit=100, window=60.0,
        sketch=SketchParams(depth=2, width=1 << 12, sub_windows=4),
        hierarchy=HierarchySpec(tenants=4, global_limit=50))
    return create_limiter(cfg, backend="sketch", clock=ManualClock(T0))


class TestTenantsEndpoint:
    def _gw(self, **kw):
        lim = _hier_limiter()
        gw = HttpGateway(lambda k, n: lim.allow_n(k, n), lim.reset,
                         tenants=lim, **kw)
        gw.start()
        return gw, lim

    def test_disabled_by_default(self):
        gw, lim = self._gw()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _req(f"http://127.0.0.1:{gw.port}/v1/tenants")
            assert ei.value.code == 403
        finally:
            gw.shutdown()
            lim.close()

    def test_token_gating_and_crud(self):
        gw, lim = self._gw(enable_tenants=True, tenants_token="tok")
        base = f"http://127.0.0.1:{gw.port}/v1/tenants"
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _req(base)  # no token
            assert ei.value.code == 403
            with pytest.raises(urllib.error.HTTPError) as ei:
                _req(base, token="wrong")
            assert ei.value.code == 403

            st, out = _req(f"{base}?name=gold&limit=30&weight=3&floor=6",
                           method="POST", token="tok")
            assert st == 200 and out["tid"] == 1 and out["weight"] == 3
            st, out = _req(f"{base}?assign=k1&tenant=gold",
                           method="POST", token="tok")
            assert st == 200
            assert lim.tenant_of("k1") == "gold"
            st, out = _req(f"{base}?effective=gold&limit=12",
                           method="POST", token="tok")
            assert out["effective"] == 12
            assert lim.effective_limits()["gold"] == 12
            st, out = _req(f"{base}?global_limit=40", method="POST",
                           token="tok")
            assert st == 200
            st, out = _req(base, token="tok")
            assert out["tenants"]["gold"]["ceiling"] == 30
            assert out["effective"]["gold"] == 12
            st, out = _req(f"{base}?unassign=k1", method="POST",
                           token="tok")
            assert out["unassigned"] is True
            st, out = _req(f"{base}?name=gold", method="DELETE",
                           token="tok")
            assert out["deleted"] is True
        finally:
            gw.shutdown()
            lim.close()


class TestMigrateEndpoint:
    def test_unwired_or_tokenless_is_403(self):
        lim = _hier_limiter()
        calls = []
        for kw in ({}, {"fleet_migrate": lambda r, t, w: calls.append(1)},
                   {"migrate_token": "tok"}):
            gw = HttpGateway(lambda k, n: lim.allow_n(k, n), lim.reset,
                             **kw)
            gw.start()
            try:
                with pytest.raises(urllib.error.HTTPError) as ei:
                    _req(f"http://127.0.0.1:{gw.port}/v1/fleet/migrate"
                         f"?to=b&ranges=0:4", method="POST", token="tok")
                assert ei.value.code == 403
            finally:
                gw.shutdown()
        assert not calls
        lim.close()

    def test_wired_migrate_contract(self):
        lim = _hier_limiter()
        calls = []

        def migrate(ranges, to, wait):
            calls.append((ranges, to, wait))
            return {"ok": to == "b", "epoch": 2, "to": to,
                    "ranges": [list(r) for r in ranges]}

        gw = HttpGateway(lambda k, n: lim.allow_n(k, n), lim.reset,
                         fleet_migrate=migrate, migrate_token="tok")
        gw.start()
        base = f"http://127.0.0.1:{gw.port}/v1/fleet/migrate"
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _req(f"{base}?to=b&ranges=0:4", method="POST")
            assert ei.value.code == 403          # bad token
            with pytest.raises(urllib.error.HTTPError) as ei:
                _req(f"{base}?to=b&ranges=0:4", token="tok")  # GET
            assert ei.value.code == 405
            with pytest.raises(urllib.error.HTTPError) as ei:
                _req(f"{base}?to=b&ranges=nope", method="POST",
                     token="tok")
            assert ei.value.code == 400
            st, out = _req(f"{base}?to=b&ranges=0:4,8:12&wait=3",
                           method="POST", token="tok")
            assert st == 200 and out["ok"] and out["epoch"] == 2
            assert calls[-1] == ([(0, 4), (8, 12)], "b", 3.0)
            with pytest.raises(urllib.error.HTTPError) as ei:
                _req(f"{base}?to=c&ranges=0:4", method="POST",
                     token="tok")
            assert ei.value.code == 504          # migrate reports not-ok
        finally:
            gw.shutdown()
            lim.close()

    def test_cli_wrapper(self):
        """tools/fleet_migrate.py drives the endpoint end to end: exit 0
        + the donor's JSON on success, exit 1 + the gateway's error body
        (not a traceback) on a bad token, and client-side range
        validation refuses before any request is made."""
        lim = _hier_limiter()

        def migrate(ranges, to, wait):
            return {"ok": True, "epoch": 3, "to": to,
                    "ranges": [list(r) for r in ranges]}

        gw = HttpGateway(lambda k, n: lim.allow_n(k, n), lim.reset,
                         fleet_migrate=migrate, migrate_token="tok")
        gw.start()
        script = os.path.join(REPO, "tools", "fleet_migrate.py")
        base = f"http://127.0.0.1:{gw.port}"

        def run(*extra):
            return subprocess.run(
                [sys.executable, script, base, "--to", "b:9433"] +
                list(extra), env=_env(), capture_output=True, text=True,
                timeout=60)

        try:
            out = run("--ranges", "0:4,8:12", "--wait", "3",
                      "--token", "tok")
            assert out.returncode == 0, out.stderr
            body = json.loads(out.stdout)
            assert body["epoch"] == 3 and body["ranges"] == [[0, 4],
                                                             [8, 12]]
            out = run("--ranges", "0:4", "--token", "wrong")
            assert out.returncode == 1
            body = json.loads(out.stdout)
            assert body["http_status"] == 403 and "token" in body["error"]
            out = run("--ranges", "4:4", "--token", "tok")
            assert out.returncode != 0 and "empty range" in out.stderr
        finally:
            gw.shutdown()
            lim.close()


# ------------------------------------------------------- real server doors


def _spawn(extra, *, http_port, port, backend="sketch"):
    argv = [sys.executable, "-m", "ratelimiter_tpu.serving",
            "--backend", backend, "--algorithm", "sliding_window",
            "--limit", "1000", "--window", "60",
            "--sketch-width", "4096", "--sub-windows", "4",
            "--port", str(port), "--http-port", str(http_port),
            "--no-prewarm"] + list(extra)
    return subprocess.Popen(argv, env=_env(), stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)


class TestServerBinaryHierarchy:
    def test_cascade_both_doors_and_controller(self):
        """One real server, both doors: the tenant cap set at boot binds
        decisions arriving over HTTP AND the binary protocol (shared
        cascade, wire protocol unchanged — no tenant field anywhere),
        /v1/tenants manages it live, /healthz carries the hierarchy
        block with AIMD controller counters."""
        from ratelimiter_tpu.serving import Client

        port, http_port = free_port(), free_port()
        proc = _spawn(
            ["--tenants", "4", "--global-limit", "100",
             "--tenant", "gold=5:3:2", "--assign", "g1=gold",
             "--assign", "g2=gold",
             "--controller", "--controller-interval", "0.05",
             "--http-tenants-token", "tok"],
            http_port=http_port, port=port)
        try:
            _wait_banner(proc)
            base = f"http://127.0.0.1:{http_port}"
            # Wire unchanged: a plain allow, no tenant anything.
            st, out = _req(f"{base}/v1/allow?key=g1")
            assert st == 200
            # Binary door shares the same cascade: gold has 5/window
            # across BOTH doors and BOTH its keys.
            with Client(port=port, timeout=30.0) as c:
                got = sum(c.allow("g2").allowed for _ in range(6))
            assert got == 4  # 1 (HTTP) + 4 = gold's 5
            with pytest.raises(urllib.error.HTTPError) as ei:
                _req(f"{base}/v1/allow?key=g1")
            assert ei.value.code == 429
            # Unassigned keys ride the default tenant, not gold's cap.
            st, _ = _req(f"{base}/v1/allow?key=other")
            assert st == 200
            # Live management over /v1/tenants: raise gold's ceiling.
            st, _ = _req(f"{base}/v1/tenants?name=gold&limit=50",
                         method="POST", token="tok")
            assert st == 200
            st, _ = _req(f"{base}/v1/allow?key=g1")
            assert st == 200
            # /healthz hierarchy block + controller counters.
            time.sleep(0.3)
            st, h = _req(f"{base}/healthz")
            hier = h["hierarchy"]
            assert hier["tenants"]["gold"]["ceiling"] == 50
            assert hier["tenants"]["gold"]["in_window"] >= 5
            assert hier["controller"]["ticks"] > 0
            proc.send_signal(sig.SIGTERM)
            assert proc.wait(timeout=30) == 0
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait()

    def test_flag_validation(self):
        """--controller/--tenant/--assign without --tenants refuse at
        boot; --tenants on a non-sketch backend refuses."""
        for extra, backend in ((["--controller"], "sketch"),
                               (["--tenant", "a=5"], "sketch"),
                               (["--assign", "k=a"], "sketch"),
                               (["--tenants", "4"], "exact")):
            argv = [sys.executable, "-m", "ratelimiter_tpu.serving",
                    "--backend", backend, "--limit", "10",
                    "--window", "60", "--port", str(free_port()),
                    "--no-prewarm"] + extra
            out = subprocess.run(argv, env=_env(), capture_output=True,
                                 text=True, timeout=120)
            assert out.returncode != 0
            assert "--tenants" in out.stderr


class _DoorAdapter:
    """The abuse-scenario drivers (evaluation/scenarios.py) program
    against the limiter surface; this adapter satisfies it THROUGH the
    real doors of a server process — allow/allow_batch ride the binary
    protocol (or HTTP when no client is given), hierarchy stats and
    effective limits come from /healthz. Scenario clocks are real time
    here (a fresh server's window is already fresh), so `advance` is a
    no-op."""

    class _Batch:
        def __init__(self, allowed):
            self.allowed = allowed

    def __init__(self, http_base, client=None):
        self.http_base = http_base
        self.client = client

    def advance(self, _seconds):     # the scenario drivers' clock hook
        pass

    def allow(self, key):
        try:
            _req(f"{self.http_base}/v1/allow?key={key}")
            return type("R", (), {"allowed": True})()
        except urllib.error.HTTPError as e:
            assert e.code == 429
            return type("R", (), {"allowed": False})()

    def allow_batch(self, keys):
        if self.client is not None:
            rows = self.client.allow_batch(keys)
            return self._Batch([bool(r.allowed) for r in rows])
        return self._Batch([self.allow(k).allowed for k in keys])

    def hierarchy_stats(self):
        _, h = _req(f"{self.http_base}/healthz")
        return h["hierarchy"]

    def effective_limits(self):
        st = self.hierarchy_stats()
        out = {name: int(t["effective"])
               for name, t in st["tenants"].items()}
        out["global"] = int(st["global"]["effective"])
        return out


class TestAbuseScenariosThroughDoors:
    def test_rotating_key_contained_via_both_doors(self):
        """The rotating-key attacker through a REAL server, frames
        alternating between the binary and HTTP doors: fresh keys every
        frame never hit a per-key limit or the hh table, yet the
        default-tenant ceiling contains the aggregate while the stable
        legit tenant keeps serving — one shared cascade behind both
        doors."""
        from ratelimiter_tpu.evaluation import scenarios as sc
        from ratelimiter_tpu.serving import Client

        port, http_port = free_port(), free_port()
        args = ["--tenants", "4", "--global-limit", "10000",
                "--default-tenant-limit", "200",
                "--tenant", "legit=10000:4"]
        for i in range(16):
            args += ["--assign", f"legit{i}=legit"]
        proc = _spawn(args, http_port=http_port, port=port)
        try:
            _wait_banner(proc)
            base = f"http://127.0.0.1:{http_port}"
            with Client(port=port, timeout=30.0) as c:
                binary = _DoorAdapter(base, client=c)
                http = _DoorAdapter(base)

                class Alternating(_DoorAdapter):
                    def __init__(self):
                        super().__init__(base, client=c)
                        self._n = 0

                    def allow_batch(self, keys):
                        door = binary if self._n % 2 == 0 else http
                        self._n += 1
                        return door.allow_batch(keys)

                res = sc.run_rotating_key(Alternating(), Alternating(),
                                          batch=128, frames=6)
            out = res.as_dict()
            assert out["contained"] is True
            assert out["legit_allow_rate"] == 1.0
            assert out["attacker_admitted"] <= 200   # default ceiling
            assert out["attacker_admit_rate"] < 0.5
            proc.send_signal(sig.SIGTERM)
            assert proc.wait(timeout=30) == 0
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait()

    def test_thundering_herd_fair_split_through_door(self):
        """The synchronized window-rollover herd through a real
        server's binary door: the global scope clips the surge to its
        limit and the admitted mass splits by tenant weight (1:2:5),
        measured off /healthz — fair sharing arbitrated on device, not
        by the test."""
        from ratelimiter_tpu.evaluation import scenarios as sc
        from ratelimiter_tpu.serving import Client

        weights = {"small": 1, "mid": 2, "big": 5}
        port, http_port = free_port(), free_port()
        args = ["--tenants", "4", "--global-limit", "96"]
        for name, w in weights.items():
            args += ["--tenant", f"{name}=10000:{w}"]
            for i in range(16):
                args += ["--assign", f"{name}_k{i}={name}"]
        proc = _spawn(args, http_port=http_port, port=port)
        try:
            _wait_banner(proc)
            base = f"http://127.0.0.1:{http_port}"
            with Client(port=port, timeout=30.0) as c:
                door = _DoorAdapter(base, client=c)
                res = sc.run_thundering_herd(
                    door, door, tenants=weights, keys_per_tenant=16,
                    bursts_per_key=4)
            out = res.as_dict()
            # The warmup decision consumed 1 of global 96; the shares
            # floor(95 * w / 8) are deterministic: 11 / 23 / 59.
            assert out["admitted"] == 93
            assert out["per_tenant_admitted"] == {"big": 59, "mid": 23,
                                                  "small": 11}
            proc.send_signal(sig.SIGTERM)
            assert proc.wait(timeout=30) == 0
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait()


class TestServerMeshHierarchy:
    def test_mesh_backend_cascade_through_door(self):
        """--backend mesh + --tenants: per-slice share enforcement
        (global 20 over 2 slices → each slice admits its 10-share) on a
        real server, decisions through the HTTP door."""
        port, http_port = free_port(), free_port()
        proc = _spawn(
            ["--tenants", "4", "--global-limit", "20",
             "--mesh-devices", "2"],
            http_port=http_port, port=port, backend="mesh")
        try:
            _wait_banner(proc)
            base = f"http://127.0.0.1:{http_port}"
            allowed = 0
            for i in range(60):
                try:
                    st, _ = _req(f"{base}/v1/allow?key=mk{i}")
                    allowed += int(st == 200)
                except urllib.error.HTTPError as e:
                    assert e.code == 429
            assert allowed == 20
            st, h = _req(f"{base}/healthz")
            hier = h["hierarchy"]
            assert hier["divisor"] == 2
            assert hier["global"]["in_window"] == 20
            proc.send_signal(sig.SIGTERM)
            assert proc.wait(timeout=30) == 0
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait()
