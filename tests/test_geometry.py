"""Accuracy-envelope enforcement: load-aware geometry sizing
(SketchParams.for_load), the calibrated mass budget, and the runtime
undersized-geometry watchdog (VERDICT r3 item 3; the reference sizes its
backend explicitly, docs/ADR/001:183-187)."""

import logging

import pytest

from netutil import free_port

from ratelimiter_tpu import (
    Algorithm,
    Config,
    InvalidConfigError,
    ManualClock,
    SketchParams,
    create_limiter,
)


class TestForLoad:
    def test_width_scales_with_mass(self):
        small = SketchParams.for_load(100, 1_000_000)
        big = SketchParams.for_load(100, 100_000_000)
        assert big.width > small.width
        # Powers of two, valid geometries.
        small.validate()
        big.validate()

    def test_width_scales_inversely_with_limit(self):
        tight = SketchParams.for_load(10, 10_000_000)
        loose = SketchParams.for_load(10_000, 10_000_000)
        assert tight.width > loose.width

    def test_stricter_target_needs_more_width(self):
        lax = SketchParams.for_load(100, 50_000_000, target_false_deny=0.01)
        strict = SketchParams.for_load(100, 50_000_000,
                                       target_false_deny=0.0001)
        assert strict.width > lax.width

    def test_budget_roundtrip(self):
        """A geometry sized for mass M at the 1% target has a budget that
        admits M (the watchdog must not cry wolf at the design point)."""
        for mass in (1e5, 1e7, 2.4e8):
            p = SketchParams.for_load(100, mass, target_false_deny=0.01)
            assert p.mass_budget(100) >= mass

    def test_config3_literal_geometry_is_declared_undersized(self):
        """The BASELINE config-3 literal geometry (d=4 w=65536) measured
        46.6% false denies at saturation (RESULTS_r03). Its budget must
        declare saturation mass (~100M admitted) far out of envelope."""
        literal = SketchParams(depth=4, width=65536)
        assert literal.mass_budget(100) < 100_000_000 / 5

    def test_memory_gate(self):
        with pytest.raises(InvalidConfigError, match="max_state_bytes"):
            SketchParams.for_load(1, 10 ** 12,
                                  max_state_bytes=64 << 20)

    def test_active_keys_floor(self):
        """Occupancy regime: width floors at one cell per active key even
        when the mass curve alone would allow less (the measured 1M-key
        2^19-cell false-deny excursion, config.py class comment)."""
        mass_only = SketchParams.for_load(100, 1_000_000)
        floored = SketchParams.for_load(100, 1_000_000,
                                        active_keys=1_000_000)
        assert floored.width >= 1_000_000
        assert floored.width > mass_only.width

    def test_safety_and_validation(self):
        wide = SketchParams.for_load(100, 1_000_000, safety=8.0)
        base = SketchParams.for_load(100, 1_000_000)
        assert wide.width > base.width
        with pytest.raises(InvalidConfigError):
            SketchParams.for_load(0, 1000)
        with pytest.raises(InvalidConfigError):
            SketchParams.for_load(100, 0)
        with pytest.raises(InvalidConfigError):
            SketchParams.for_load(100, 1000, target_false_deny=0.9)
        with pytest.raises(InvalidConfigError):
            SketchParams.for_load(100, 1000, depth=2)


class TestMassWatchdog:
    def _lim(self, width=16, limit=5, sub_windows=6, window=6.0):
        cfg = Config(algorithm=Algorithm.TPU_SKETCH, limit=limit,
                     window=window, max_batch_admission_iters=1,
                     sketch=SketchParams(depth=3, width=width,
                                         sub_windows=sub_windows))
        return create_limiter(cfg, backend="sketch",
                              clock=ManualClock(1_700_000_000.0))

    def test_overload_warns_once_per_subwindow(self, caplog):
        lim = self._lim()
        budget = lim.mass_budget           # 2 * 5 * 16 = 160
        assert budget == 160
        with caplog.at_level(logging.WARNING, logger="ratelimiter_tpu"):
            # Admitted mass: distinct keys, 1 req each -> all allowed.
            for start in (0, 200):
                lim.allow_batch([f"k{start + i}" for i in range(200)])
        warnings = [r for r in caplog.records
                    if "geometry undersized" in r.message]
        assert len(warnings) == 1          # same sub-window: warned once
        assert lim.overload_periods == 1
        assert lim.in_window_admitted_mass() > budget
        # A later sub-window still overloaded -> warns again.
        lim.clock.advance(1.1)
        with caplog.at_level(logging.WARNING, logger="ratelimiter_tpu"):
            lim.allow_batch([f"j{i}" for i in range(200)])
        warnings = [r for r in caplog.records
                    if "geometry undersized" in r.message]
        assert len(warnings) == 2
        lim.close()

    def test_mass_expires_with_the_window(self):
        lim = self._lim()
        lim.allow_batch([f"k{i}" for i in range(100)])
        assert lim.in_window_admitted_mass() == 100
        lim.clock.advance(7.0)             # > window: all periods pruned
        lim.allow("fresh")
        assert lim.in_window_admitted_mass() == 1
        lim.close()

    def test_within_budget_never_warns(self, caplog):
        lim = self._lim(width=1024)        # budget 10240
        with caplog.at_level(logging.WARNING, logger="ratelimiter_tpu"):
            for _ in range(3):
                lim.allow_batch([f"k{i}" for i in range(300)])
        assert not [r for r in caplog.records
                    if "geometry undersized" in r.message]
        assert lim.overload_periods == 0
        lim.close()

    def test_denied_requests_do_not_count(self):
        lim = self._lim(width=64, limit=3)
        for _ in range(10):
            lim.allow("hot")
        # Only the 3 admitted decisions contribute mass.
        assert lim.in_window_admitted_mass() == 3
        lim.close()

    def test_token_bucket_excluded(self):
        cfg = Config(algorithm=Algorithm.TOKEN_BUCKET, limit=5, window=6.0,
                     sketch=SketchParams(depth=3, width=16))
        lim = create_limiter(cfg, backend="sketch",
                             clock=ManualClock(1_700_000_000.0))
        for i in range(50):
            lim.allow(f"k{i}")             # must not touch the watchdog
        lim.close()

    def test_budget_follows_dynamic_limit(self):
        lim = self._lim(width=64, limit=5)
        assert lim.mass_budget == 2 * 5 * 64
        lim.update_limit(50)
        assert lim.mass_budget == 2 * 50 * 64
        lim.close()


class TestStrictOverloadPolicy:
    """overload_policy="strict": a mis-sized geometry surfaces in
    DECISIONS (bounded extra denies), not just logs (VERDICT r4 weak 6 /
    next-round item 8)."""

    def _lim(self, policy="strict", width=16, limit=5):
        cfg = Config(algorithm=Algorithm.TPU_SKETCH, limit=limit,
                     window=6.0, max_batch_admission_iters=1,
                     sketch=SketchParams(depth=3, width=width, sub_windows=6,
                                         overload_policy=policy))
        return create_limiter(cfg, backend="sketch",
                              clock=ManualClock(1_700_000_000.0))

    def test_over_budget_rejects_new_admissions(self):
        lim = self._lim()
        budget = lim.mass_budget                       # 160
        out = lim.allow_batch([f"k{i}" for i in range(200)])
        assert int(out.allowed.sum()) == 200           # filled the budget
        out = lim.allow_batch([f"m{i}" for i in range(10)])
        assert int(out.allowed.sum()) == 0             # strict: reject all
        assert (out.retry_after > 0).all()
        assert lim.overload_periods >= 1
        # Mass did NOT grow past the overload point.
        assert lim.in_window_admitted_mass() == 200 > budget
        lim.close()

    def test_recovers_as_history_expires(self):
        lim = self._lim()
        lim.allow_batch([f"k{i}" for i in range(200)])
        assert int(lim.allow("x").allowed) == 0
        lim.clock.advance(7.0)                         # full window passes
        assert lim.allow("x").allowed                  # budget clear again
        lim.close()

    def test_warn_policy_keeps_admitting(self):
        lim = self._lim(policy="warn")
        lim.allow_batch([f"k{i}" for i in range(200)])
        out = lim.allow_batch([f"m{i}" for i in range(10)])
        assert int(out.allowed.sum()) == 10            # degraded, serving
        lim.close()

    def test_invalid_policy_rejected(self):
        with pytest.raises(InvalidConfigError, match="overload_policy"):
            SketchParams(overload_policy="explode").validate()

    def test_metrics_gauges_exported(self):
        from ratelimiter_tpu.observability import MetricsDecorator, Registry

        reg = Registry()
        lim = MetricsDecorator(self._lim(policy="warn"), registry=reg)
        lim.allow_batch([f"k{i}" for i in range(200)])
        text = reg.render()
        assert 'rate_limiter_sketch_overload_periods{shard="0"} 1' in text
        assert ('rate_limiter_sketch_in_window_admitted_mass{shard="0"} 200'
                in text)
        assert 'rate_limiter_sketch_mass_budget{shard="0"} 160' in text
        lim.close()

    def test_healthz_surfaces_overload(self):
        """The server binary's /healthz carries the envelope fields."""
        import json
        import os
        import signal as sig
        import socket
        import subprocess
        import sys
        import urllib.request

        env = dict(os.environ)
        env["PYTHONPATH"] = os.pathsep.join(
            [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
            + env.get("PYTHONPATH", "").split(os.pathsep))
        env["JAX_PLATFORMS"] = "cpu"


        port, http_port = free_port(), free_port()
        proc = subprocess.Popen(
            [sys.executable, "-m", "ratelimiter_tpu.serving",
             "--backend", "sketch", "--algorithm", "sliding_window",
             "--limit", "5", "--window", "60",
             "--sketch-depth", "3", "--sketch-width", "16",
             "--no-prewarm", "--port", str(port),
             "--http-port", str(http_port)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        try:
            for _ in range(10):
                line = proc.stdout.readline()
                if line.startswith("serving"):
                    break
            from ratelimiter_tpu.serving import Client

            with Client(port=port, timeout=30.0) as c:
                c.allow_batch([f"k{i}" for i in range(200)], [1] * 200)
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{http_port}/healthz") as r:
                health = json.loads(r.read())
            assert health["overload_periods"] >= 1
            assert health["in_window_admitted_mass"] > health["mass_budget"]
            assert health["overload_policy"] == "warn"
            proc.send_signal(sig.SIGTERM)
            assert proc.wait(timeout=15) == 0
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait()
