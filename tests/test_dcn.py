"""DCN tier: cross-pod completed-slab exchange (parallel/dcn.py)."""

import pytest

from ratelimiter_tpu import (
    Algorithm,
    Config,
    InvalidConfigError,
    ManualClock,
    SketchParams,
    create_limiter,
)
from ratelimiter_tpu.parallel.dcn import (
    DcnMirrorGroup,
    export_completed,
    merge_completed,
)

T0 = 1_700_000_000.0


def pod(limit=10, window=6.0, sub_windows=6, width=4096, start=T0):
    clock = ManualClock(start)
    cfg = Config(algorithm=Algorithm.TPU_SKETCH, limit=limit, window=window,
                 sketch=SketchParams(depth=4, width=width,
                                     sub_windows=sub_windows))
    return create_limiter(cfg, backend="sketch", clock=clock), clock


class TestExportMerge:
    def test_export_only_completed_periods(self):
        lim, clock = pod()
        lim.allow_n("k", 3)                      # current sub-window: not done
        periods, slabs, _last = export_completed(lim, -(1 << 62))
        assert periods.shape[0] == 0
        clock.advance(1.0)
        lim.allow("k")                           # rolls the period over
        periods, slabs, _last = export_completed(lim, -(1 << 62))
        assert periods.shape[0] == 1
        assert slabs[0].sum() >= 3 * 4           # 3 requests x depth cells
        lim.close()

    def test_merge_makes_foreign_traffic_visible(self):
        a, ca = pod()
        b, cb = pod()
        assert a.allow_n("k", 10).allowed        # pod A: key exhausted
        ca.advance(1.0)
        cb.advance(1.0)
        a.allow("warm")                          # roll A's period
        b.allow("warm")                          # roll B's period too
        assert b.allow_n("k", 10).allowed        # B hasn't heard about A yet
        periods, slabs, _last = export_completed(a, -(1 << 62))
        assert merge_completed(b, periods, slabs)[0] == 1
        # B now sees A's 10 on top of its own 10: hard deny.
        assert not b.allow("k").allowed
        a.close()
        b.close()

    def test_incomplete_foreign_periods_dropped(self):
        a, ca = pod()
        b, _cb = pod()
        a.allow_n("k", 5)
        ca.advance(1.0)
        a.allow("warm")                          # A completed period; B did not
        periods, slabs, _last = export_completed(a, -(1 << 62))
        assert merge_completed(b, periods, slabs)[0] == 0  # b still at period 0
        a.close()
        b.close()

    def test_token_bucket_rejected(self):
        clock = ManualClock(T0)
        cfg = Config(algorithm=Algorithm.TOKEN_BUCKET, limit=10, window=10.0)
        tb = create_limiter(cfg, backend="sketch", clock=clock)
        with pytest.raises(InvalidConfigError):
            export_completed(tb, 0)
        tb.close()

    def test_hh_traffic_exported_as_cms_mass(self):
        """Promoted keys' private counts are folded back into CMS form
        at export (via the owner's captured (h1, h2) pair), so heavy
        hitters — precisely the keys whose traffic matters cross-pod —
        are visible to peers (VERDICT r4 item 4; r3 refused hh+DCN)."""

        def hh_pod():
            clock = ManualClock(T0)
            cfg = Config(algorithm=Algorithm.TPU_SKETCH, limit=10,
                         window=6.0,
                         sketch=SketchParams(depth=3, width=256,
                                             sub_windows=6, hh_slots=16,
                                             hh_promote_fraction=0.2))
            return create_limiter(cfg, backend="sketch", clock=clock), clock

        a, ca = hh_pod()
        b, cb = hh_pod()
        # Promote "hot" on A (crosses 0.2*10=2 estimate), then consume
        # most of its quota IN the side table.
        for _ in range(3):
            assert a.allow("hot").allowed
        import numpy as np

        assert int(np.asarray(a._state["hh_owner"]).astype(bool).sum()) >= 1
        for _ in range(6):
            a.allow("hot")                       # 9/10 consumed on A
        ca.advance(1.0)
        cb.advance(1.0)
        a.allow("warm")
        b.allow("warm")
        periods, slabs, _last = export_completed(a, -(1 << 62))
        assert merge_completed(b, periods, slabs)[0] >= 1
        # B sees A's 9 (side-table counts included): 2 more at most.
        assert b.allow("hot").allowed
        assert not b.allow_n("hot", 2).allowed
        a.close()
        b.close()

    def test_hh_export_does_not_double_count(self):
        """Round-tripping pods with hh enabled must not echo or double:
        after A->B and B->A, A's view of its own key equals true global
        consumption."""

        def hh_pod():
            clock = ManualClock(T0)
            cfg = Config(algorithm=Algorithm.TPU_SKETCH, limit=10,
                         window=6.0,
                         sketch=SketchParams(depth=3, width=256,
                                             sub_windows=6, hh_slots=16,
                                             hh_promote_fraction=0.2))
            return create_limiter(cfg, backend="sketch", clock=clock), clock

        a, ca = hh_pod()
        b, cb = hh_pod()
        for _ in range(4):
            assert a.allow("hot").allowed        # promoted + 4 consumed
        ca.advance(1.0)
        cb.advance(1.0)
        a.allow("warm")
        b.allow("warm")
        group = DcnMirrorGroup([a, b])
        group.sync()
        group.sync()                             # second sync: nothing new
        # Global consumption of "hot" is 4: A may take exactly 6 more.
        assert a.allow_n("hot", 6).allowed
        assert not a.allow("hot").allowed
        a.close()
        b.close()

    def test_negative_foreign_cells_clamped(self):
        """A corrupt/malicious payload with negative cells must not erase
        local history (limit bypass); negatives clamp to 0 on merge."""
        import numpy as np

        a, ca = pod(limit=10)
        b, cb = pod(limit=10)
        b.allow_n("k", 10)
        ca.advance(1.0)
        cb.advance(1.0)
        a.allow("warm")
        b.allow("warm")
        periods, slabs, _ = export_completed(a, -(1 << 62))
        evil = -np.abs(slabs) - 1_000_000        # all-negative forgery
        merge_completed(b, periods, evil)
        assert not b.allow("k").allowed          # history intact
        a.close()
        b.close()


def bucket_pod(limit=10, window=10.0, width=4096, start=T0):
    clock = ManualClock(start)
    cfg = Config(algorithm=Algorithm.TOKEN_BUCKET, limit=limit,
                 window=window,
                 sketch=SketchParams(depth=4, width=width))
    return create_limiter(cfg, backend="sketch", clock=clock), clock


class TestBucketExchange:
    def test_export_carries_local_increments_once(self):
        from ratelimiter_tpu.parallel.dcn import export_debt, merge_debt

        a, _ca = bucket_pod()
        a.allow_n("k", 4)
        delta = export_debt(a)
        assert delta.sum() == 4 * 1_000_000 * 4  # 4 tokens x depth rows
        # Snapshot-and-zero: nothing new -> empty export.
        assert export_debt(a).sum() == 0
        a.close()

    def test_merge_makes_foreign_debt_visible(self):
        from ratelimiter_tpu.parallel.dcn import export_debt, merge_debt

        a, _ca = bucket_pod()
        b, _cb = bucket_pod()
        assert a.allow_n("k", 10).allowed        # A: bucket drained
        assert merge_debt(b, export_debt(a)) > 0
        res = b.allow("k")                       # B sees the full debt
        assert not res.allowed and res.retry_after > 0
        a.close()
        b.close()

    def test_merged_debt_drains_at_refill_rate(self):
        from ratelimiter_tpu.parallel.dcn import export_debt, merge_debt

        a, _ca = bucket_pod(limit=10, window=10.0)   # 1 token/s refill
        b, cb = bucket_pod(limit=10, window=10.0)
        a.allow_n("k", 10)
        merge_debt(b, export_debt(a))
        assert not b.allow("k").allowed
        cb.advance(2.1)                          # ~2 tokens refilled
        assert b.allow_n("k", 2).allowed
        assert not b.allow("k").allowed
        a.close()
        b.close()

    def test_error_direction_never_over_admits_globally_after_sync(self):
        """Post-sync, the group's total admission for one key cannot
        exceed limit + what each pod admitted pre-sync (the documented
        envelope); once synced, everyone denies."""
        from ratelimiter_tpu.parallel.dcn import export_debt, merge_debt

        pods = [bucket_pod(limit=10) for _ in range(3)]
        total = sum(p.allow_batch(["hot"] * 12).allow_count
                    for p, _ in pods)
        assert 10 <= total <= 30                 # pre-sync envelope
        deltas = [export_debt(p) for p, _ in pods]
        for i, (p, _) in enumerate(pods):
            for j, d in enumerate(deltas):
                if i != j:
                    merge_debt(p, d)
        for p, _ in pods:
            assert not p.allow("hot").allowed
            p.close()

    def test_negative_debt_delta_clamped(self):
        """A forged negative delta must not erase real debt."""
        import numpy as np

        from ratelimiter_tpu.parallel.dcn import merge_debt

        a, _ = bucket_pod(limit=10)
        a.allow_n("k", 10)
        evil = np.full(tuple(a._state["debt"].shape), -(1 << 60),
                       dtype=np.int64)
        assert merge_debt(a, evil) == 0          # clamps to all-zero
        assert not a.allow("k").allowed
        a.close()

    def test_reset_not_exported(self):
        """Reset forgives local debt but must not emit a negative delta
        (which could over-admit remotely)."""
        from ratelimiter_tpu.parallel.dcn import export_debt

        a, _ca = bucket_pod()
        a.allow_n("k", 10)
        a.reset("k")
        assert a.allow("k").allowed              # local recovery
        delta = export_debt(a)
        assert (delta >= 0).all()
        # The original 10 + the post-reset 1 are both real local traffic.
        assert delta.sum() >= 10 * 1_000_000 * 4
        a.close()

    def test_mirror_group_bucket_mode(self):
        from ratelimiter_tpu.parallel.dcn import DcnMirrorGroup

        (a, _ca), (b, _cb) = bucket_pod(), bucket_pod()
        group = DcnMirrorGroup([a, b])
        a.allow_n("k", 6)
        b.allow_n("k", 4)
        assert group.sync() > 0
        # Global view on both: 10 of 10 consumed.
        assert not a.allow("k").allowed
        assert not b.allow("k").allowed
        assert group.sync() == 0                 # nothing new
        a.close()
        b.close()

    def test_mixed_family_rejected(self):
        from ratelimiter_tpu.parallel.dcn import DcnMirrorGroup

        (a, _), (w, _) = bucket_pod(), pod()
        with pytest.raises(InvalidConfigError):
            DcnMirrorGroup([a, w])
        a.close()
        w.close()


class TestMirrorGroup:
    def test_cross_pod_convergence_and_envelope(self):
        """Over-admission bounded by n_pods*limit per (sub-window+sync);
        after sync every pod denies — the documented DCN contract."""
        pods = [pod(limit=10) for _ in range(3)]
        group = DcnMirrorGroup([p for p, _ in pods])
        total = 0
        for p, _ in pods:
            out = p.allow_batch(["hot"] * 12)
            total += out.allow_count
        assert 10 <= total <= 3 * 10             # pre-sync envelope
        for _, c in pods:
            c.advance(1.0)
        for p, _ in pods:
            p.allow("warm")                      # complete the sub-window
        group.sync()
        for p, _ in pods:
            assert not p.allow("hot").allowed    # global history visible
        # Expiry needs no coordination: everything ages out everywhere.
        for _, c in pods:
            c.advance(15.0)                      # > 2 windows
        for p, _ in pods:
            assert p.allow("hot").allowed
            p.close()

    def test_no_double_counting_across_cycles(self):
        """Repeated syncs must not re-apply the same slabs (exports carry
        only local traffic, tracked per pod)."""
        (a, ca), (b, cb) = pod(limit=10), pod(limit=10)
        group = DcnMirrorGroup([a, b])
        a.allow_n("k", 4)
        ca.advance(1.0)
        cb.advance(1.0)
        a.allow("warm")
        b.allow("warm")
        assert group.sync() == 1
        assert group.sync() == 0                 # nothing new: no re-apply
        # b sees exactly 4 consumed: 6 remain under the global view.
        assert b.allow_n("k", 6).allowed
        assert not b.allow("k").allowed
        a.close()
        b.close()

    def test_mixed_geometry_rejected(self):
        (a, _), (b, _) = pod(limit=10), pod(limit=11)
        with pytest.raises(InvalidConfigError):
            DcnMirrorGroup([a, b])
        a.close()
        b.close()

    def test_sync_during_stale_ring_replaces_expired_slots(self):
        """A pod idle for a full ring wrap accepts fresh foreign slabs
        into slots still holding ancient periods."""
        (a, ca), (b, cb) = pod(limit=10), pod(limit=10)
        group = DcnMirrorGroup([a, b])
        # Both pods advance far (ring wraps), then traffic on A only.
        for c in (ca, cb):
            c.advance(100.0)
        a.allow_n("k", 10)
        b.allow("other")
        ca.advance(1.0)
        cb.advance(1.0)
        a.allow("warm")
        b.allow("warm")
        group.sync()
        assert not b.allow("k").allowed
        a.close()
        b.close()
