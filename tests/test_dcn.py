"""DCN tier: cross-pod completed-slab exchange (parallel/dcn.py)."""

import pytest

from ratelimiter_tpu import (
    Algorithm,
    Config,
    InvalidConfigError,
    ManualClock,
    SketchParams,
    create_limiter,
)
from ratelimiter_tpu.parallel.dcn import (
    DcnMirrorGroup,
    export_completed,
    merge_completed,
)

T0 = 1_700_000_000.0


def pod(limit=10, window=6.0, sub_windows=6, width=4096, start=T0):
    clock = ManualClock(start)
    cfg = Config(algorithm=Algorithm.TPU_SKETCH, limit=limit, window=window,
                 sketch=SketchParams(depth=4, width=width,
                                     sub_windows=sub_windows))
    return create_limiter(cfg, backend="sketch", clock=clock), clock


class TestExportMerge:
    def test_export_only_completed_periods(self):
        lim, clock = pod()
        lim.allow_n("k", 3)                      # current sub-window: not done
        periods, slabs, _last = export_completed(lim, -(1 << 62))
        assert periods.shape[0] == 0
        clock.advance(1.0)
        lim.allow("k")                           # rolls the period over
        periods, slabs, _last = export_completed(lim, -(1 << 62))
        assert periods.shape[0] == 1
        assert slabs[0].sum() >= 3 * 4           # 3 requests x depth cells
        lim.close()

    def test_merge_makes_foreign_traffic_visible(self):
        a, ca = pod()
        b, cb = pod()
        assert a.allow_n("k", 10).allowed        # pod A: key exhausted
        ca.advance(1.0)
        cb.advance(1.0)
        a.allow("warm")                          # roll A's period
        b.allow("warm")                          # roll B's period too
        assert b.allow_n("k", 10).allowed        # B hasn't heard about A yet
        periods, slabs, _last = export_completed(a, -(1 << 62))
        assert merge_completed(b, periods, slabs)[0] == 1
        # B now sees A's 10 on top of its own 10: hard deny.
        assert not b.allow("k").allowed
        a.close()
        b.close()

    def test_incomplete_foreign_periods_dropped(self):
        a, ca = pod()
        b, _cb = pod()
        a.allow_n("k", 5)
        ca.advance(1.0)
        a.allow("warm")                          # A completed period; B did not
        periods, slabs, _last = export_completed(a, -(1 << 62))
        assert merge_completed(b, periods, slabs)[0] == 0  # b still at period 0
        a.close()
        b.close()

    def test_token_bucket_rejected(self):
        clock = ManualClock(T0)
        cfg = Config(algorithm=Algorithm.TOKEN_BUCKET, limit=10, window=10.0)
        tb = create_limiter(cfg, backend="sketch", clock=clock)
        with pytest.raises(InvalidConfigError):
            export_completed(tb, 0)
        tb.close()


class TestMirrorGroup:
    def test_cross_pod_convergence_and_envelope(self):
        """Over-admission bounded by n_pods*limit per (sub-window+sync);
        after sync every pod denies — the documented DCN contract."""
        pods = [pod(limit=10) for _ in range(3)]
        group = DcnMirrorGroup([p for p, _ in pods])
        total = 0
        for p, _ in pods:
            out = p.allow_batch(["hot"] * 12)
            total += out.allow_count
        assert 10 <= total <= 3 * 10             # pre-sync envelope
        for _, c in pods:
            c.advance(1.0)
        for p, _ in pods:
            p.allow("warm")                      # complete the sub-window
        group.sync()
        for p, _ in pods:
            assert not p.allow("hot").allowed    # global history visible
        # Expiry needs no coordination: everything ages out everywhere.
        for _, c in pods:
            c.advance(15.0)                      # > 2 windows
        for p, _ in pods:
            assert p.allow("hot").allowed
            p.close()

    def test_no_double_counting_across_cycles(self):
        """Repeated syncs must not re-apply the same slabs (exports carry
        only local traffic, tracked per pod)."""
        (a, ca), (b, cb) = pod(limit=10), pod(limit=10)
        group = DcnMirrorGroup([a, b])
        a.allow_n("k", 4)
        ca.advance(1.0)
        cb.advance(1.0)
        a.allow("warm")
        b.allow("warm")
        assert group.sync() == 1
        assert group.sync() == 0                 # nothing new: no re-apply
        # b sees exactly 4 consumed: 6 remain under the global view.
        assert b.allow_n("k", 6).allowed
        assert not b.allow("k").allowed
        a.close()
        b.close()

    def test_mixed_geometry_rejected(self):
        (a, _), (b, _) = pod(limit=10), pod(limit=11)
        with pytest.raises(InvalidConfigError):
            DcnMirrorGroup([a, b])
        a.close()
        b.close()

    def test_sync_during_stale_ring_replaces_expired_slots(self):
        """A pod idle for a full ring wrap accepts fresh foreign slabs
        into slots still holding ancient periods."""
        (a, ca), (b, cb) = pod(limit=10), pod(limit=10)
        group = DcnMirrorGroup([a, b])
        # Both pods advance far (ring wraps), then traffic on A only.
        for c in (ca, cb):
            c.advance(100.0)
        a.allow_n("k", 10)
        b.allow("other")
        ca.advance(1.0)
        cb.advance(1.0)
        a.allow("warm")
        b.allow("warm")
        group.sync()
        assert not b.allow("k").allowed
        a.close()
        b.close()
