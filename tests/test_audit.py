"""Live accuracy observatory (ADR-016) — tier-1 suite.

Covers, per ISSUE 9:

* the shared three-way engine: Wilson intervals, tally arithmetic, the
  inlined windowed host oracle fuzz-pinned IDENTICAL to ExactLimiter
  (the exact==dense parity chain then reaches the device oracle), and
  the CMS-vs-semantic split on a deliberately colliding sketch;
* the auditor core: hash-coherent sampling (a key is always or never
  audited, across lanes), per-slice attribution, fail-open exclusion
  (degraded ranges attributed, not averaged away), drop-and-count under
  a full queue, shadow failures contained;
* audit-off = byte-identical hot path (pinned on the asyncio door), and
  audit-ON decisions also byte-identical (the tap is passive);
* both doors' taps end to end: the auditor's tally equals an offline
  recomputation of the same decisions at sample=1;
* chaos integration: a quarantined slice's fail-open rows are counted
  per slice and never pollute the accuracy rates;
* the SLO burn-rate tracker (windows, axes, gauges, fallback source);
* top-K consumer analytics off the hh side table (limiter surface,
  MetricsDecorator gauges, /healthz merge);
* LoggingDecorator satellites (key redaction, fail_open_slices);
* GET /debug/audit trust boundary and the combined /healthz envelope
  with mesh + quarantine + audit all enabled (the composition no test
  exercised before);
* the bench's live_accuracy smoke (agreement machinery runs tiny).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import subprocess
import sys
import urllib.request

import numpy as np
import pytest

from ratelimiter_tpu import (
    Algorithm,
    Config,
    ManualClock,
    SketchParams,
    create_limiter,
)
from ratelimiter_tpu.algorithms.exact import ExactLimiter
from ratelimiter_tpu.core.types import BatchResult
from ratelimiter_tpu.evaluation.compare import (
    ShadowComparator,
    ThreeWayTally,
    wilson_interval,
)
from ratelimiter_tpu.observability import audit
from ratelimiter_tpu.observability import metrics as m
from ratelimiter_tpu.observability.decorators import (
    LoggingDecorator,
    MetricsDecorator,
)
from ratelimiter_tpu.observability.slo import SloBurnTracker
from ratelimiter_tpu.ops.hashing import splitmix64
from ratelimiter_tpu.serving.batcher import MicroBatcher
from ratelimiter_tpu.serving.client import AsyncClient, Client
from ratelimiter_tpu.serving.http_gateway import HttpGateway
from ratelimiter_tpu.serving.native_server import (
    NativeRateLimitServer,
    native_server_available,
)
from ratelimiter_tpu.serving.server import RateLimitServer

T0 = 1_700_000_000.0


@pytest.fixture(autouse=True)
def _audit_off():
    """Every test starts and ends with the module seam clear — the
    zero-overhead default the rest of the suite relies on."""
    audit.disable()
    yield
    audit.disable()


def _cfg(limit=100, width=1 << 12, depth=2, sub_windows=8, **kw):
    return Config(algorithm=Algorithm.SLIDING_WINDOW, limit=limit,
                  window=60.0, key_prefix="",
                  sketch=SketchParams(depth=depth, width=width,
                                      sub_windows=sub_windows), **kw)


def _batch_result(allowed, *, fail_open=False, limit=100):
    allowed = np.asarray(allowed, dtype=bool)
    b = allowed.shape[0]
    return BatchResult(allowed=allowed, limit=limit,
                       remaining=np.zeros(b, np.int64),
                       retry_after=np.zeros(b, np.float64),
                       reset_at=np.zeros(b, np.float64),
                       fail_open=fail_open)


# ------------------------------------------------------------ the engine


class TestWilson:
    def test_contains_point_estimate(self):
        for k, n in [(0, 10), (1, 100), (50, 100), (99, 100)]:
            lo, hi = wilson_interval(k, n)
            assert lo <= k / n <= hi

    def test_no_evidence(self):
        assert wilson_interval(0, 0) == (0.0, 1.0)

    def test_clamped_and_ordered(self):
        for k, n in [(0, 5), (5, 5), (3, 7)]:
            lo, hi = wilson_interval(k, n)
            assert 0.0 <= lo <= hi <= 1.0

    def test_narrows_with_n(self):
        lo1, hi1 = wilson_interval(1, 100)
        lo2, hi2 = wilson_interval(100, 10_000)
        assert (hi2 - lo2) < (hi1 - lo1)


class TestTally:
    def test_counts(self):
        t = ThreeWayTally()
        live = np.array([True, False, False, True])
        twin = np.array([True, True, False, True])
        oracle = np.array([True, True, True, False])
        t.add(live, twin, oracle)
        assert t.requests == 4
        assert t.oracle_allows == 3
        assert t.false_denies_vs_oracle == 2   # idx 1, 2
        assert t.false_allows_vs_oracle == 1   # idx 3
        assert t.cms_false_denies_vs_twin == 1  # idx 1
        assert t.semantic_disagreements == 2   # idx 2, 3
        assert t.false_deny_rate == 2 / 3

    def test_twinless(self):
        t = ThreeWayTally()
        t.add(np.array([True]), None, np.array([False]))
        assert t.false_allows_vs_oracle == 1
        assert t.cms_false_denies_vs_twin == 0


class TestOracleParity:
    """The inlined windowed oracle must be bit-identical to ExactLimiter
    (which is itself pinned bit-identical to the dense device oracle by
    tests/test_cross_backend.py)."""

    @pytest.mark.parametrize("algo", [Algorithm.SLIDING_WINDOW,
                                      Algorithm.FIXED_WINDOW])
    @pytest.mark.parametrize("seed", [0, 1])
    def test_fuzz_vs_exact(self, algo, seed):
        cfg = Config(algorithm=algo, limit=7, window=3.0, key_prefix="",
                     sketch=SketchParams(depth=1, width=1 << 14,
                                         sub_windows=6))
        comp = ShadowComparator(cfg, include_twin=False)
        ex = ExactLimiter(Config(algorithm=algo, limit=7, window=3.0,
                                 key_prefix=""))
        rng = np.random.default_rng(seed)
        t = T0
        try:
            for _ in range(100):
                b = int(rng.integers(1, 24))
                h = rng.integers(1, 40, size=b).astype(np.uint64)
                ns = rng.integers(1, 3, size=b).astype(np.int64)
                # Includes idle gaps > window (both-expired resets) and
                # sub-window steps (weighted boundary math).
                t += float(rng.random() * 1.7)
                fast, _ = comp.decide(h, ns, t)
                exp = ex.allow_batch([f"k{int(x)}" for x in h],
                                     [int(n) for n in ns], now=t).allowed
                assert np.array_equal(fast, exp)
        finally:
            comp.close()
            ex.close()

    def test_prune_preserves_semantics(self):
        """Sweeping fully-stale entries is invisible: a key idle past
        one window decides identically whether its entry was pruned or
        kept."""
        cfg = _cfg(limit=3)
        comp = ShadowComparator(cfg, include_twin=False,
                                oracle_capacity=1024)
        h = np.array([42], dtype=np.uint64)
        comp.decide(h, np.array([3]), T0)       # key at its limit
        denied, _ = comp.decide(h, np.array([1]), T0 + 1.0)
        assert not denied[0]
        # Force the sweep: flood with > 4*cap distinct fresh keys two
        # windows later, then the idle key must decide as fresh.
        later = T0 + 200.0
        comp.decide(np.arange(1000, 6000, dtype=np.uint64),
                    None, later)
        assert len(comp._sw_state) < 6000 + 2   # stale swept
        fresh, _ = comp.decide(h, np.array([1]), later)
        assert fresh[0]
        comp.close()

    def test_cms_split_on_colliding_sketch(self):
        """A deliberately tiny sketch produces false denies that the
        collision-free twin attributes to CMS error, not semantics."""
        cfg = Config(algorithm=Algorithm.TPU_SKETCH, limit=20,
                     window=60.0, key_prefix="",
                     sketch=SketchParams(depth=1, width=64,
                                         sub_windows=6))
        from ratelimiter_tpu.algorithms.sketch import SketchLimiter

        lim = SketchLimiter(cfg, ManualClock(T0))
        comp = ShadowComparator(cfg, include_twin=True,
                                twin_width=1 << 16)
        rng = np.random.default_rng(0)
        h = splitmix64(rng.integers(0, 2000, size=6000,
                                    dtype=np.uint64))
        for i in range(0, 6000, 512):
            now = T0 + i / 2000.0
            live = lim.allow_hashed(h[i:i + 512], now=now).allowed
            comp.observe(h[i:i + 512], None, now, live)
        t = comp.tally
        assert t.false_denies_vs_oracle > 0
        # The split attributes (nearly) all of it to collisions.
        assert t.cms_false_denies_vs_twin > 0
        assert t.cms_false_denies_vs_twin >= t.false_denies_vs_oracle / 2
        lim.close()
        comp.close()


# ------------------------------------------------------------ the auditor


class TestAuditorCore:
    def make(self, **kw):
        kw.setdefault("start", False)
        kw.setdefault("include_twin", False)
        return audit.ShadowAuditor(_cfg(), **kw)

    def test_hash_coherent_sampling(self):
        """A key is ALWAYS or NEVER audited: two frames containing the
        same keys contribute the same audited subset, and it matches
        the documented rule."""
        aud = self.make(sample=8)
        h = np.arange(1, 513, dtype=np.uint64) * np.uint64(0x9E3779B9)
        res = _batch_result(np.ones(512, bool))
        aud.offer_hashed(h, None, T0, res)
        aud.process_pending()
        first = aud.status()["samples"]
        expected = int(((h >> np.uint64(61)) == 0).sum())
        assert first == expected > 0
        aud.offer_hashed(h, None, T0 + 1.0, res)
        aud.process_pending()
        assert aud.status()["samples"] == 2 * first
        aud.close()

    def test_lane_coherence_ids_vs_hashed(self):
        """The raw-id lane finalizes with splitmix64 before sampling —
        the same subset as a pre-finalized offer of splitmix64(ids)."""
        aud = self.make(sample=4)
        ids = np.arange(100, 400, dtype=np.uint64)
        res = _batch_result(np.ones(300, bool))
        aud.offer_ids(ids, None, T0, res)
        aud.process_pending()
        via_ids = aud.status()["samples"]
        aud2 = self.make(sample=4)
        aud2.offer_hashed(splitmix64(ids), None, T0, res)
        aud2.process_pending()
        assert aud2.status()["samples"] == via_ids > 0
        aud.close()
        aud2.close()

    def test_string_lane_applies_prefix(self):
        """offer_keys hashes with the limiter's prefix rule, so the
        audited decisions line up with what the backend decided."""
        cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=5,
                     window=60.0, key_prefix="rl",
                     sketch=SketchParams(depth=2, width=1 << 12,
                                         sub_windows=8))
        aud = audit.ShadowAuditor(cfg, sample=1, start=False,
                                  include_twin=False)
        lim = create_limiter(cfg, backend="sketch", clock=ManualClock(T0))
        keys = [f"user:{i}" for i in range(32)]
        out = lim.allow_batch(keys, now=T0)
        aud.offer_keys(keys, None, T0, out)
        aud.process_pending()
        st = aud.status()
        assert st["samples"] == 32
        assert st["false_denies"] == 0 and st["false_allows"] == 0
        lim.close()
        aud.close()

    def test_per_slice_attribution(self):
        """Mismatches land on the slice the key routes to
        (h64 % n_slices — the SlicedMeshLimiter router)."""
        aud = self.make(sample=1, n_slices=4)
        h = np.arange(1, 65, dtype=np.uint64)
        # Live DENIES everything; the oracle allows (fresh keys) — 64
        # false denies attributed per slice.
        res = _batch_result(np.zeros(64, bool))
        aud.offer_hashed(h, None, T0, res)
        aud.process_pending()
        st = aud.status()
        assert st["false_denies"] == 64
        per = st["per_slice"]
        assert set(per) == {"0", "1", "2", "3"}
        for s, d in per.items():
            exp = int((h % np.uint64(4) == np.uint64(int(s))).sum())
            assert d["samples"] == exp
            assert d["false_denies"] == exp
        aud.close()

    def test_fail_open_attributed_not_averaged(self):
        """Fail-open rows are excluded from the rates and counted on
        the named slices only; un-named slices' rows still compare."""
        aud = self.make(sample=1, n_slices=4)
        h = np.arange(1, 65, dtype=np.uint64)
        res = _batch_result(np.ones(64, bool), fail_open=True)
        res.fail_open_slices = [1]
        aud.offer_hashed(h, None, T0, res)
        aud.process_pending()
        st = aud.status()
        on_victim = int((h % np.uint64(4) == np.uint64(1)).sum())
        assert st["fail_open_samples"] == on_victim
        assert st["per_slice"]["1"]["fail_open_samples"] == on_victim
        assert st["per_slice"]["1"]["samples"] == 0
        # Healthy slices' rows were compared normally (fresh keys,
        # allowed == oracle) — no false counts anywhere.
        assert st["samples"] == 64 - on_victim
        assert st["false_denies"] == 0 and st["false_allows"] == 0
        aud.close()

    def test_unattributed_fail_open_excludes_frame(self):
        aud = self.make(sample=1, n_slices=2)
        res = _batch_result(np.ones(16, bool), fail_open=True)
        aud.offer_hashed(np.arange(1, 17, dtype=np.uint64), None, T0, res)
        aud.process_pending()
        st = aud.status()
        assert st["fail_open_samples"] == 16
        assert st["samples"] == 0
        aud.close()

    def test_drop_and_count_never_blocks(self):
        aud = self.make(sample=1, queue_depth=2)
        res = _batch_result(np.ones(8, bool))
        for _ in range(10):
            aud.offer_hashed(np.arange(8, dtype=np.uint64), None, T0, res)
        assert aud.dropped_frames == 8
        assert aud.dropped_decisions == 64
        assert len(aud._q) == 2
        aud.process_pending()
        assert aud.status()["dropped_decisions"] == 64
        aud.close()

    def test_shadow_failure_contained(self, monkeypatch):
        """A shadow-leg crash is counted and dropped — it must never
        propagate toward serving."""
        aud = self.make(sample=1)
        monkeypatch.setattr(aud._comparator, "decide",
                            lambda *a, **k: 1 / 0)
        aud.offer_hashed(np.arange(4, dtype=np.uint64), None, T0,
                         _batch_result(np.ones(4, bool)))
        aud.process_pending()   # must not raise
        assert aud.oracle_errors == 1
        assert aud.status()["samples"] == 0
        aud.close()

    def test_live_config_update_rebaselines_shadow(self):
        """A runtime update_limit on the audited backend must not turn
        every allow between the old and new limit into a permanent
        false-allow reading: the worker follows live_config and
        re-baselines the shadow legs."""
        cfg = _cfg(limit=5)
        lim = create_limiter(cfg, backend="sketch", clock=ManualClock(T0))
        aud = audit.ShadowAuditor(cfg, sample=1, start=False,
                                  include_twin=False,
                                  live_config=lambda: lim.config)
        h = np.full(12, 77, dtype=np.uint64)
        out = lim.allow_hashed(h, now=T0)        # 5 allowed, 7 denied
        aud.offer_hashed(h, None, T0, out)
        aud.process_pending()
        assert aud.status()["false_allows"] == 0
        lim.update_limit(12)
        out2 = lim.allow_hashed(h, now=T0 + 1.0)  # 7 more allowed
        assert int(out2.allowed.sum()) == 7
        aud.offer_hashed(h, None, T0 + 1.0, out2)
        aud.process_pending()
        st = aud.status()
        # Without the re-baseline the oracle (still at limit 5) would
        # score those 7 allows as false allows.
        assert st["false_allows"] == 0
        assert st["false_denies"] == 0
        aud.close()
        lim.close()

    def test_scalar_result_normalized(self):
        """decide_one-style taps carry a scalar Result."""
        from ratelimiter_tpu.core.types import allowed_result

        aud = self.make(sample=1)
        aud.offer_keys(["k"], [1], T0, allowed_result(10, 9, T0 + 60))
        aud.process_pending()
        assert aud.status()["samples"] == 1
        aud.close()

    def test_registry_gauges(self):
        reg = m.Registry()
        aud = audit.ShadowAuditor(_cfg(), sample=1, n_slices=2,
                                  start=False, include_twin=False,
                                  registry=reg)
        res = _batch_result(np.zeros(8, bool))   # all false denies
        aud.offer_hashed(np.arange(1, 9, dtype=np.uint64), None, T0, res)
        aud.process_pending()
        text = reg.render()
        assert "rate_limiter_audit_false_deny_rate 1" in text
        assert "rate_limiter_audit_samples 8" in text
        assert 'rate_limiter_audit_slice_false_denies{slice="0"}' in text
        aud.close()
        # close() unhooks: a later render must not poke the auditor.
        reg.render()

    def test_enable_disable_seam(self):
        assert audit.AUDITOR is None
        a = audit.enable(_cfg(), sample=4, include_twin=False)
        assert audit.get() is a
        audit.disable()
        assert audit.AUDITOR is None


# ------------------------------------------- hot path + asyncio door tap


class TestAsyncioDoor:
    def _drive(self, *, enable_audit: bool, sample: int = 1):
        """One seeded trace through the real asyncio door; returns
        (decisions, audit status or None)."""
        cfg = _cfg(limit=5, width=1 << 11)

        async def run():
            clock = ManualClock(T0)
            lim = create_limiter(cfg, backend="sketch", clock=clock)
            srv = RateLimitServer(lim, max_batch=256, max_delay=50e-6)
            await srv.start()
            auditor = None
            if enable_audit:
                auditor = audit.enable(cfg, sample=sample, n_slices=1,
                                       include_twin=False)
            c = await AsyncClient.connect(srv.host, srv.port)
            rng = np.random.default_rng(0)
            ids = rng.integers(0, 200, size=1024).astype(np.uint64)
            allowed = []
            for i in range(0, 1024, 256):
                clock.set(T0 + i / 500.0)
                out = await c.allow_hashed(ids[i:i + 256])
                allowed.append(np.asarray(out.allowed))
            # String lane too (the client returns per-request Results).
            out = await c.allow_batch([f"u{i}" for i in range(64)])
            allowed.append(np.array([r.allowed for r in out]))
            await c.close()
            await srv.shutdown()
            lim.close()
            st = None
            if auditor is not None:
                assert auditor.flush(timeout=20)
                st = auditor.status()
                audit.disable()
            return np.concatenate(allowed), st

        return asyncio.run(run())

    def test_audit_off_is_default_and_byte_identical(self):
        assert audit.AUDITOR is None
        base, st = self._drive(enable_audit=False)
        assert st is None
        on, st_on = self._drive(enable_audit=True)
        # The tap is passive: decisions byte-identical with audit on.
        assert np.array_equal(base, on)
        assert st_on["samples"] > 0

    def test_tally_matches_offline_recomputation(self):
        """sample=1: the auditor's tally equals recomputing the same
        decisions offline against a fresh engine — the door tap loses
        nothing and invents nothing."""
        cfg = _cfg(limit=5, width=1 << 11)

        async def run():
            clock = ManualClock(T0)
            lim = create_limiter(cfg, backend="sketch", clock=clock)
            srv = RateLimitServer(lim, max_batch=256, max_delay=50e-6)
            await srv.start()
            auditor = audit.enable(cfg, sample=1, include_twin=False)
            c = await AsyncClient.connect(srv.host, srv.port)
            rng = np.random.default_rng(1)
            ids = rng.integers(0, 64, size=1024).astype(np.uint64)
            frames = []
            for i in range(0, 1024, 256):
                now = T0 + i / 400.0
                clock.set(now)
                out = await c.allow_hashed(ids[i:i + 256])
                frames.append((ids[i:i + 256], now,
                               np.asarray(out.allowed)))
            await c.close()
            await srv.shutdown()
            lim.close()
            assert auditor.flush(timeout=20)
            st = auditor.status()
            audit.disable()
            return frames, st

        frames, st = asyncio.run(run())
        comp = ShadowComparator(cfg, include_twin=False)
        for ids, now, allowed in frames:
            comp.observe(splitmix64(ids), None, now, allowed)
        t = comp.tally
        comp.close()
        assert st["samples"] == t.requests
        assert st["false_denies"] == t.false_denies_vs_oracle
        assert st["false_allows"] == t.false_allows_vs_oracle
        assert st["oracle_allows"] == t.oracle_allows
        # The tight trace over 64 hot keys at limit=5 actually denies —
        # the comparison above is not vacuous.
        assert t.oracle_allows < t.requests

    def test_slo_breach_frames_late_tapped(self):
        """A frame answered by SLO-breach policy still CONSUMES sketch
        mass via the shielded dispatch — its eventual device result is
        mirrored into the tap, so audited keys' shadow timelines have
        no holes (which would read as false denies later)."""
        import time as _time

        cfg = _cfg(limit=100, fail_open=True)

        async def run():
            lim = create_limiter(cfg, backend="sketch",
                                 clock=ManualClock(T0))
            real_allow = lim.allow_ids

            def slow_allow(ids, ns=None, *, now=None):
                _time.sleep(0.15)       # past the 50 ms SLO
                return real_allow(ids, ns, now=now)

            lim.allow_ids = slow_allow
            b = MicroBatcher(lim, max_batch=64, max_delay=1e-4,
                             dispatch_timeout=0.05)
            auditor = audit.enable(cfg, sample=1, include_twin=False)
            fut = b.submit_hashed_nowait(
                np.arange(8, dtype=np.uint64), np.ones(8, np.int64))
            out = await fut
            assert out.fail_open          # answered by breach policy
            await b.drain()
            b.close()                     # joins the executor: the
            #                               shielded call has landed
            await asyncio.sleep(0.05)     # let its done-callback run
            lim.close()
            assert auditor.flush(timeout=10)
            st = auditor.status()
            audit.disable()
            return st

        st = asyncio.run(run())
        # The REAL device decisions (not the fabricated fail-open
        # answers) reached the shadow oracle.
        assert st["samples"] == 8
        assert st["fail_open_samples"] == 0
        assert st["false_denies"] == 0 and st["false_allows"] == 0

    def test_batcher_tap_without_server(self):
        """The MicroBatcher itself taps (both lanes) — pinned without
        the socket layer."""
        cfg = _cfg(limit=100)

        async def run():
            lim = create_limiter(cfg, backend="sketch",
                                 clock=ManualClock(T0))
            b = MicroBatcher(lim, max_batch=64, max_delay=1e-4)
            auditor = audit.enable(cfg, sample=1, include_twin=False)
            await b.submit("alice", 1)
            fut = b.submit_hashed_nowait(
                np.arange(8, dtype=np.uint64), np.ones(8, np.int64))
            await fut
            await b.drain()
            b.close()
            lim.close()
            assert auditor.flush(timeout=10)
            st = auditor.status()
            audit.disable()
            return st

        st = asyncio.run(run())
        assert st["samples"] == 9
        assert st["audited_frames"] == 2


# --------------------------------------------------------- native door


@pytest.mark.skipif(not native_server_available(),
                    reason="native server extension unavailable (no g++)")
class TestNativeDoor:
    def test_pipelined_hashed_tap(self):
        cfg = _cfg(limit=1000, width=1 << 12)
        lim = create_limiter(cfg, backend="sketch")
        srv = NativeRateLimitServer(lim, max_batch=512, inflight=4)
        auditor = audit.enable(cfg, sample=1, include_twin=False)
        try:
            srv.start()
            c = Client(port=srv.port)
            ids = np.arange(1, 65, dtype=np.uint64)
            out = c.allow_hashed(ids)
            assert len(out.allowed) == 64
            # String lane through the same door.
            c.allow_batch([f"u{i}" for i in range(32)])
            c.close()
            assert auditor.flush(timeout=20)
            st = auditor.status()
            assert st["samples"] == 64 + 32
            assert st["false_denies"] == 0 and st["false_allows"] == 0
            # Native taps attribute by dispatch shard.
            assert set(st["per_slice"]) == {"0"}
        finally:
            audit.disable()
            srv.shutdown()
            lim.close()

    def test_decide_one_tap(self):
        cfg = _cfg(limit=10)
        lim = create_limiter(cfg, backend="sketch")
        srv = NativeRateLimitServer(lim, max_batch=64, inflight=1)
        auditor = audit.enable(cfg, sample=1, include_twin=False)
        try:
            srv.start()
            res = srv.decide_one("gateway-user", 1)
            assert res.allowed
            assert auditor.flush(timeout=10)
            assert auditor.status()["samples"] == 1
        finally:
            audit.disable()
            srv.shutdown()
            lim.close()


# ------------------------------------------------------ chaos integration


class TestChaosIntegration:
    def test_quarantined_slice_attributed(self):
        """With a slice killed under chaos, its fail-open rows land in
        fail_open_samples on THAT slice; healthy ranges' accuracy stays
        clean — degraded ranges attributed, not averaged away."""
        jax = pytest.importorskip("jax")
        if len(jax.devices()) < 2:
            pytest.skip("needs 2 virtual devices")
        from ratelimiter_tpu import MeshSpec, chaos as chaos_pkg
        from ratelimiter_tpu.parallel.limiter import SlicedMeshLimiter

        victim = 1
        cfg = Config(
            algorithm=Algorithm.SLIDING_WINDOW, limit=1000, window=60.0,
            fail_open=True, key_prefix="",
            sketch=SketchParams(depth=2, width=1 << 12, sub_windows=4),
            mesh=MeshSpec(devices=2, quarantine=True,
                          slice_deadline=0.2, probe_interval=30.0))
        lim = SlicedMeshLimiter(cfg)
        aud = audit.ShadowAuditor(cfg, sample=1, n_slices=2, start=False,
                                  include_twin=False)
        ids = np.arange(1024, dtype=np.uint64)
        lim.allow_ids(ids)          # warm every slice + guard warm gates
        inj = chaos_pkg.install(seed=7)
        try:
            inj.fail_slice(victim)
            now = lim.clock.now()
            for _ in range(3):
                out = lim.allow_ids(ids)
                aud.offer_ids(ids, None, now, out)
            aud.process_pending()
            st = aud.status()
            owners = lim.owner_of_id(ids)
            per_fault = int((owners == victim).sum())
            assert st["fail_open_samples"] == 3 * per_fault
            assert st["per_slice"][str(victim)]["fail_open_samples"] == \
                3 * per_fault
            # The healthy slice was compared normally and stayed clean
            # (limit is high; no real denies in this trace).
            assert st["false_denies"] == 0
            assert st["false_allows"] == 0
            assert st["per_slice"]["0"]["fail_open_samples"] == 0
            assert st["per_slice"]["0"]["samples"] == 3 * int(
                (owners == 0).sum())
        finally:
            chaos_pkg.uninstall()
            aud.close()
            lim.close()


# ------------------------------------------------------------- SLO burn


class _FakeTime:
    def __init__(self):
        self.t = 1000.0

    def monotonic(self):
        return self.t


class TestSloBurnTracker:
    def test_burn_rate_windows(self, monkeypatch):
        reg = m.Registry()
        fake = _FakeTime()
        monkeypatch.setattr("ratelimiter_tpu.observability.slo.time", fake)
        hist = reg.histogram("rate_limiter_stage_seconds")
        shed = reg.counter("rate_limiter_server_deadline_shed_total")
        req = reg.counter("rate_limiter_requests_total")
        tr = SloBurnTracker(reg, objective=0.99, latency_target=0.01,
                            stage="device", windows=(60.0,))
        tr.sample()                            # zero baseline
        fake.t += 61.0
        for _ in range(99):                    # the window's traffic
            hist.observe(0.001, stage="device")
            req.inc(result="allowed")
        hist.observe(0.5, stage="device")      # one slow span
        req.inc(result="allowed")
        shed.inc(1)                            # one shed decision
        st = tr.status()
        row = st["windows"]["60s"]
        # latency axis: 1 slow / 100 spans this window = 1% bad = burn
        # 1.0 at a 1% budget; availability: 1 shed / 101 ~= 0.99%.
        assert row["latency_bad_fraction"] == pytest.approx(0.01)
        assert row["availability_bad_fraction"] == pytest.approx(1 / 101,
                                                                 abs=1e-4)
        assert row["burn_rate"] == pytest.approx(1.0, abs=0.05)
        assert row["span_s"] == pytest.approx(61.0)
        assert st["latency_target_effective_s"] <= 0.01

    def test_slo_breach_counts_decisions_not_frames(self, monkeypatch):
        """One breached frame fails-open a WHOLE batch: the availability
        axis consumes the decision-unit breach counter, so a full
        latency outage burns ~1.0, not ~1/batch_size."""
        reg = m.Registry()
        fake = _FakeTime()
        monkeypatch.setattr("ratelimiter_tpu.observability.slo.time", fake)
        breach_dec = reg.counter(
            "rate_limiter_server_slo_breach_decisions_total")
        tr = SloBurnTracker(reg, objective=0.99, windows=(60.0,))
        tr.sample()
        fake.t += 61.0
        breach_dec.inc(4096)     # one breached 4096-decision frame
        st = tr.status()
        assert st["windows"]["60s"]["availability_bad_fraction"] == 1.0

    def test_fallback_to_dispatch_histogram(self):
        reg = m.Registry()
        disp = reg.histogram("rate_limiter_server_dispatch_seconds")
        disp.observe(0.2)
        tr = SloBurnTracker(reg, latency_target=0.05)
        st = tr.status()
        assert st["spans_observed"] == 1

    def test_gauges_on_collect(self, monkeypatch):
        reg = m.Registry()
        fake = _FakeTime()
        monkeypatch.setattr("ratelimiter_tpu.observability.slo.time", fake)
        hist = reg.histogram("rate_limiter_stage_seconds")
        tr = SloBurnTracker(reg, windows=(30.0,))
        tr.attach()
        hist.observe(1.0, stage="device")
        fake.t += 31.0
        text = reg.render()
        assert "rate_limiter_slo_burn_rate" in text
        tr.detach()

    def test_bad_objective_rejected(self):
        with pytest.raises(ValueError):
            SloBurnTracker(m.Registry(), objective=1.0)


# -------------------------------------------------------- top consumers


class TestTopConsumers:
    def _hot_limiter(self):
        cfg = Config(algorithm=Algorithm.TPU_SKETCH, limit=1000,
                     window=60.0, key_prefix="", max_batch_admission_iters=4,
                     sketch=SketchParams(depth=2, width=256, sub_windows=6,
                                         hh_slots=16,
                                         hh_promote_fraction=0.01))
        clock = ManualClock(T0)
        return create_limiter(cfg, backend="sketch", clock=clock), clock

    def test_consumer_stats_ordering(self):
        lim, _ = self._hot_limiter()
        for _ in range(40):
            lim.allow("whale")
        for _ in range(25):
            lim.allow("dolphin")
        st = lim.consumer_stats(k=5)
        assert st["slots"] == 16
        assert st["occupied"] >= 2
        top = st["top"]
        assert len(top) >= 2
        # The side table counts a promoted key's traffic from its claim
        # point (promotion threshold = 1% of limit = 10 here), so the
        # whale tracks ~30 of its 40 requests and stays ranked first.
        assert top[0]["in_window"] > top[1]["in_window"] > 0
        assert top[0]["in_window"] >= 25
        assert top[0]["share"] > top[1]["share"]
        # Identities are hash tokens, never raw keys.
        assert all(len(r["consumer"]) == 16 for r in top)
        lim.close()

    def test_no_hh_table(self):
        lim = create_limiter(_cfg(), backend="sketch",
                             clock=ManualClock(T0))
        assert lim.consumer_stats() == {"slots": 0, "occupied": 0,
                                        "top": []}
        assert lim.has_hh is False
        lim.close()

    def test_metrics_decorator_exports_topk(self):
        lim, clock = self._hot_limiter()
        reg = m.Registry()
        dec = MetricsDecorator(lim, reg)
        for _ in range(30):
            dec.allow("whale")
        text = reg.render()
        assert 'rate_limiter_top_consumer_mass{rank="1"' in text
        assert "rate_limiter_hh_tracked_consumers" in text
        gauge = reg.get("rate_limiter_top_consumer_mass")
        assert gauge.value(rank="1", shard="0", slice="0") > 0
        # Vacated ranks drop to 0 on the next scrape — no phantom
        # heavy hitters frozen at their last mass.
        assert gauge.value(rank="5", shard="0", slice="0") == 0.0
        clock.advance(120.0)               # whole window rolls off
        dec.allow("minnow")                # advance the sketch's period
        text = reg.render()
        assert gauge.value(rank="1", shard="0", slice="0") == 0.0
        dec.close()

    def test_healthz_merge(self):
        from ratelimiter_tpu.serving.__main__ import _consumers_health

        lim, _ = self._hot_limiter()
        for _ in range(30):
            lim.allow("whale")
        block = _consumers_health([lim])
        assert block["consumers"]["occupied"] >= 1
        # Counted from the promotion point (threshold 10 of 30 allows).
        assert block["consumers"]["top"][0]["in_window"] >= 15
        assert "slice" in block["consumers"]["top"][0]
        lim.close()
        # No hh table -> no block at all (healthz stays lean).
        lim2 = create_limiter(_cfg(), backend="sketch")
        assert _consumers_health([lim2]) == {}
        lim2.close()


# --------------------------------------------------- logging satellites


class TestLoggingSatellites:
    def _limiter(self, **kw):
        return create_limiter(_cfg(limit=5), backend="exact",
                              clock=ManualClock(T0), **kw)

    def test_redact_keys(self, caplog):
        lim = LoggingDecorator(self._limiter(), redact_keys=True)
        with caplog.at_level(logging.DEBUG, logger="ratelimiter_tpu"):
            lim.allow("alice@example.com")
            lim.reset("alice@example.com")
        text = "\n".join(r.message for r in caplog.records)
        assert "alice@example.com" not in text
        assert "key#" in text
        # Stable: the same key always logs the same token.
        tokens = {w for w in text.split() if w.startswith("key=key#")}
        assert len(tokens) == 1
        lim.close()

    def test_raw_keys_by_default(self, caplog):
        lim = LoggingDecorator(self._limiter())
        with caplog.at_level(logging.DEBUG, logger="ratelimiter_tpu"):
            lim.allow("bob")
        assert any("key=bob" in r.message for r in caplog.records)
        lim.close()

    def test_fail_open_names_slices(self, caplog):
        """A slice-attributed fail-open WARNING carries the slice list
        so the degraded-range line is actionable."""
        inner = self._limiter()

        class _Inner(LoggingDecorator):
            pass

        dec = LoggingDecorator(inner)
        out = _batch_result(np.ones(4, bool), fail_open=True)
        out.fail_open_slices = [2, 0]
        with caplog.at_level(logging.WARNING, logger="ratelimiter_tpu"):
            dec._observe_batch("allow_batch", out, None, 0.001)
        msg = caplog.records[-1].message
        assert "fail-open" in msg and "fail_open_slices=[0, 2]" in msg
        dec.close()

    def test_scalar_fail_open_names_slices(self, caplog):
        from ratelimiter_tpu.core.types import fail_open_result

        class FailOpenInner:
            config = _cfg(fail_open=True)

            def allow_n(self, key, n, *, now=None):
                res = fail_open_result(10, T0 + 60)
                object.__setattr__(res, "fail_open_slices", [3])
                return res

            def close(self):
                pass

        inner = create_limiter(_cfg(fail_open=True), backend="exact")
        dec = LoggingDecorator(inner, redact_keys=True)
        dec.inner = FailOpenInner()
        with caplog.at_level(logging.WARNING, logger="ratelimiter_tpu"):
            dec.allow_n("whale", 1)
        msg = caplog.records[-1].message
        assert "fail_open_slices=[3]" in msg and "whale" not in msg
        inner.close()


# ------------------------------------------------------- debug endpoint


class TestDebugAuditEndpoint:
    def _gateway(self, **kw):
        return HttpGateway(lambda key, n: (_ for _ in ()).throw(
            AssertionError("decide unused")), lambda k: None, **kw)

    def _get(self, port, path, token=None):
        req = urllib.request.Request(f"http://127.0.0.1:{port}{path}")
        if token:
            req.add_header("Authorization", f"Bearer {token}")
        try:
            with urllib.request.urlopen(req, timeout=10) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    def test_not_wired_is_403(self):
        gw = self._gateway()
        gw.start()
        try:
            code, body = self._get(gw.port, "/debug/audit")
            assert code == 403 and "not enabled" in body["error"]
        finally:
            gw.shutdown()

    def test_bearer_gate_and_payload(self):
        payload = {"enabled": True, "false_deny_rate": 0.0,
                   "slo": {"windows": {}}}
        gw = self._gateway(audit_status=lambda: payload,
                           audit_token="s3cret")
        gw.start()
        try:
            code, _ = self._get(gw.port, "/debug/audit")
            assert code == 403
            code, body = self._get(gw.port, "/debug/audit", token="s3cret")
            assert code == 200 and body["enabled"] is True
        finally:
            gw.shutdown()


# ------------------------------------- combined /healthz composition


class TestHealthzComposition:
    """Satellite 4: no test exercised the FULL envelope with mesh +
    quarantine + audit (+ hh analytics + SLO) enabled at once — a real
    server subprocess proves the composition end to end."""

    def _spawn(self):
        env = dict(os.environ)
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env["PYTHONPATH"] = os.pathsep.join(
            [repo] + env.get("PYTHONPATH", "").split(os.pathsep))
        env["JAX_PLATFORMS"] = "cpu"
        from tests.netutil import free_port

        port, http_port = free_port(), free_port()
        proc = subprocess.Popen(
            [sys.executable, "-m", "ratelimiter_tpu.serving",
             "--backend", "mesh", "--mesh-devices", "2", "--quarantine",
             "--audit", "--audit-sample", "1", "--audit-token", "tok",
             "--hh-slots", "16",
             "--sketch-depth", "2", "--sketch-width", "1024",
             "--sub-windows", "6", "--limit", "100", "--window", "60",
             "--max-batch", "256", "--no-prewarm", "--fail-open",
             "--port", str(port), "--http-port", str(http_port)],
            env=env, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True)
        line = proc.stdout.readline()
        if "serving" not in line:
            proc.kill()
            raise RuntimeError(f"server failed to start: {line!r}")
        return proc, port, http_port

    def test_full_envelope(self):
        proc, port, http_port = self._spawn()
        try:
            c = Client(port=port)
            c.allow_hashed(np.arange(1, 65, dtype=np.uint64))
            c.allow_batch([f"user:{i}" for i in range(32)])
            c.close()
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{http_port}/healthz",
                    timeout=10) as r:
                health = json.loads(r.read())
            # The composed envelope: every subsystem reports.
            assert health["serving"] is True
            assert "quarantine" in health
            assert health["audit"]["sample"] == 1
            assert "slo" in health and "windows" in health["slo"]
            assert "overload_periods" in health     # accuracy envelope
            assert "consumers" in health            # hh analytics
            # /debug/audit: gated, then the full observatory payload.
            req = urllib.request.Request(
                f"http://127.0.0.1:{http_port}/debug/audit")
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=10)
            assert ei.value.code == 403
            req.add_header("Authorization", "Bearer tok")
            with urllib.request.urlopen(req, timeout=10) as r:
                dbg = json.loads(r.read())
            assert dbg["enabled"] is True
            assert dbg["samples"] >= 0
            assert "per_slice" in dbg and "slo" in dbg
            # /metrics carries the audit gauge families.
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{http_port}/metrics",
                    timeout=10) as r:
                metrics = r.read().decode()
            assert "rate_limiter_audit_false_deny_rate" in metrics
            assert "rate_limiter_slo_burn_rate" in metrics
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()


# ----------------------------------------------------------- bench smoke


class TestBenchSmoke:
    def test_live_accuracy_block(self):
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        from bench import measure_live_accuracy

        out = measure_live_accuracy(
            n_keys=800, n_requests=3000, batch=512, sample=4,
            width=1 << 9, sub_windows=12, measure_overhead=False,
            twin_width=1 << 14)
        assert out["door_decisions_match_offline"] is True
        assert out["agreement_within_wilson95"] is True
        assert out["live"]["samples"] > 0
        lo, hi = out["live"]["false_deny_wilson95"]
        assert 0.0 <= lo <= hi <= 1.0
        # The module seam is clean afterwards (bench disables it).
        assert audit.AUDITOR is None
