"""Heavy-hitter exact side table (SketchParams.hh_slots): promotion,
additive estimates, eviction, reset, and mesh parity. The design notes
live in ops/sketch_kernels._sketch_step; measured accuracy impact is
documented in ROADMAP.md (neutral under conservative update, aimed at
the vanilla-update regimes such as the mesh delta merge)."""

import numpy as np
import pytest

from ratelimiter_tpu import (
    Algorithm,
    Config,
    InvalidConfigError,
    ManualClock,
    SketchParams,
    create_limiter,
)

T0 = 1_700_000_000.0


def make(limit=10, window=6.0, hh_slots=16, frac=0.5, cu=True, **kw):
    cfg = Config(algorithm=Algorithm.TPU_SKETCH, limit=limit, window=window,
                 max_batch_admission_iters=4,
                 sketch=SketchParams(depth=2, width=64, sub_windows=6,
                                     hh_slots=hh_slots,
                                     hh_promote_fraction=frac,
                                     conservative_update=cu), **kw)
    clock = ManualClock(T0)
    return create_limiter(cfg, backend="sketch", clock=clock), clock


class TestHHSemantics:
    def test_exactness_across_promotion(self):
        """A hot key admits exactly `limit`, with promotion happening
        mid-stream (no quota reset, no double count)."""
        lim, _ = make(limit=10)
        assert sum(lim.allow("hot").allowed for _ in range(25)) == 10
        owners = np.asarray(lim._state["hh_owner"])
        assert np.count_nonzero(owners) == 1     # promoted
        lim.close()

    def test_window_slide_recovers_quota(self):
        lim, clock = make(limit=10)
        for _ in range(15):
            lim.allow("hot")
        clock.advance(7.0)                        # full window elapsed
        assert sum(lim.allow("hot").allowed for _ in range(15)) == 10
        lim.close()

    def test_boundary_weighting_survives_promotion(self):
        """Sub-window-resolution sliding semantics hold through the side
        table: mass consumed at t=0 stays full-weight until its
        sub-window becomes the boundary (one full window later), then
        fades by the overlap fraction."""
        lim, clock = make(limit=10, window=6.0)   # 6 x 1 s sub-windows
        assert lim.allow_n("hot", 10).allowed
        assert not lim.allow("hot").allowed
        clock.advance(3.5)                        # still fully in window
        assert not lim.allow("hot").allowed
        clock.advance(3.0)                        # t=6.5: boundary frac 0.5
        got = sum(lim.allow("hot").allowed for _ in range(10))
        assert 2 <= got <= 8                      # partial, never full
        lim.close()

    def test_reset_clears_promoted_key(self):
        lim, _ = make(limit=10)
        lim.allow_n("hot", 10)
        assert not lim.allow("hot").allowed
        lim.reset("hot")
        assert lim.allow("hot").allowed
        lim.close()

    def test_idle_owner_evicted_and_slot_reusable(self):
        lim, clock = make(limit=10)
        for _ in range(12):
            lim.allow("hot")                      # promote "hot"
        assert np.count_nonzero(np.asarray(lim._state["hh_owner"])) == 1
        # Idle a full window + rollovers: slot reclaimed.
        for step in range(8):
            clock.advance(1.0)
            lim.allow(f"tick{step}")              # drives rollovers
        assert np.count_nonzero(np.asarray(lim._state["hh_owner"])) <= 1
        # And the evicted key starts fresh (its history expired anyway).
        assert lim.allow("hot").allowed
        lim.close()

    def test_batch_duplicates_sequenced_through_hh(self):
        lim, _ = make(limit=10)
        for _ in range(3):
            lim.allow("h")                        # promote with count 3
        out = lim.allow_batch(["h"] * 12)
        assert int(np.sum(out.allowed)) == 7      # 10 - 3 already used
        lim.close()

    def test_unpromoted_keys_unaffected(self):
        """Cold keys below the threshold run pure sketch semantics."""
        lim, _ = make(limit=10, frac=1.0)
        out = lim.allow_batch([f"c{i}" for i in range(30)])
        assert out.allow_count == 30
        assert np.count_nonzero(np.asarray(lim._state["hh_owner"])) == 0
        lim.close()

    def test_vanilla_update_mode_works(self):
        lim, _ = make(limit=10, cu=False)
        assert sum(lim.allow("hot").allowed for _ in range(25)) == 10
        lim.close()

    def test_checkpoint_roundtrip_with_hh_state(self, tmp_path):
        lim, clock = make(limit=10)
        for _ in range(12):
            lim.allow("hot")
        path = str(tmp_path / "hh.npz")
        lim.save(path)
        lim2, _ = make(limit=10)
        lim2.restore(path)
        assert not lim2.allow("hot").allowed      # promoted state survived
        np.testing.assert_array_equal(
            np.asarray(lim._state["hh_owner"]),
            np.asarray(lim2._state["hh_owner"]))
        lim.close()
        lim2.close()

    def test_validation(self):
        with pytest.raises(InvalidConfigError):
            SketchParams(hh_slots=17).validate()       # not a power of two
        with pytest.raises(InvalidConfigError):
            SketchParams(hh_slots=8).validate()        # below minimum
        with pytest.raises(InvalidConfigError):
            SketchParams(hh_promote_fraction=0.0).validate()


class TestHHMesh:
    @pytest.fixture()
    def mesh(self):
        import jax

        from ratelimiter_tpu.parallel import make_mesh

        if len(jax.devices()) < 2:
            pytest.skip("needs a multi-device (CPU) mesh")
        return make_mesh()

    def test_mesh_gather_exactness_with_hh(self, mesh):
        """Gather mode is strictly exact with hh enabled: one hot key,
        limit L, exactly L admitted; promotion state replicated."""
        from ratelimiter_tpu.parallel import MeshSketchLimiter

        cfg = Config(algorithm=Algorithm.TPU_SKETCH, limit=10, window=6.0,
                     max_batch_admission_iters=4,
                     sketch=SketchParams(depth=2, width=64, sub_windows=6,
                                         hh_slots=16,
                                         hh_promote_fraction=0.5))
        clock = ManualClock(T0)
        lim = MeshSketchLimiter(cfg, mesh=mesh, merge="gather", clock=clock)
        out = lim.allow_batch(["hot"] * 32)
        assert out.allow_count == 10
        assert np.count_nonzero(np.asarray(lim._state["hh_owner"])) == 1
        assert lim.allow_batch(["hot"] * 8).allow_count == 0
        lim.close()

    def test_mesh_delta_bounded_staleness_with_hh(self, mesh):
        """Delta mode keeps its documented envelope with hh enabled:
        per-step over-admission bounded by n_chips x limit, convergence
        after the psum; promotion (pmax'd claims) stays replicated."""
        import jax

        from ratelimiter_tpu.parallel import MeshSketchLimiter

        n_chips = len(jax.devices())
        cfg = Config(algorithm=Algorithm.TPU_SKETCH, limit=10, window=6.0,
                     max_batch_admission_iters=4,
                     sketch=SketchParams(depth=2, width=64, sub_windows=6,
                                         hh_slots=16,
                                         hh_promote_fraction=0.5))
        clock = ManualClock(T0)
        lim = MeshSketchLimiter(cfg, mesh=mesh, merge="delta", clock=clock)
        first = lim.allow_batch(["hot"] * 32).allow_count
        assert 10 <= first <= min(32, n_chips * 10)
        # Merged state visible: everyone denies now (and the hot key,
        # far past the threshold, claims its slot identically everywhere).
        assert lim.allow_batch(["hot"] * 16).allow_count == 0
        assert np.count_nonzero(np.asarray(lim._state["hh_owner"])) == 1
        lim.close()
