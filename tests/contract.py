"""Interface conformance suite — instantiated for every backend.

The reference defines an equivalent suite (``interface_test.go:11-28``,
``RunAllTests``) but never instantiates it (SURVEY.md §4.2.5); notably its
AllowN-atomicity case (``interface_test.go:154-167``) would fail against the
reference's own FixedWindow/SlidingWindow. Here the suite runs for each
backend x algorithm via pytest class inheritance, and the atomicity case is
law (SURVEY.md §2.4.2 resolution).

Subclasses set ``backend`` and override ``make_limiter`` /
``inject_failure`` as needed.
"""

from __future__ import annotations

import threading

import numpy as np
import pytest

from ratelimiter_tpu import (
    Algorithm,
    ClosedError,
    Config,
    InvalidKeyError,
    InvalidNError,
    ManualClock,
    create_limiter,
)

ALGORITHMS = [Algorithm.TOKEN_BUCKET, Algorithm.SLIDING_WINDOW,
              Algorithm.FIXED_WINDOW, Algorithm.TPU_SKETCH]


class ContractTests:
    backend: str = "exact"
    #: algorithms this backend supports (overridden by sketch backend)
    algorithms = ALGORITHMS
    supports_failure_injection = False
    #: exact backends admit exactly `limit`; approximate (sketch) backends may
    #: under-admit, never over-admit — they set exact_admission = False.
    exact_admission = True

    def make_limiter(self, config: Config, clock) -> object:
        return create_limiter(config, backend=self.backend, clock=clock)

    def make(self, algorithm, limit=100, window=60.0, **kw):
        clock = ManualClock()
        cfg = Config(algorithm=algorithm, limit=limit, window=window, **kw)
        return self.make_limiter(cfg, clock), clock

    @pytest.fixture(params=ALGORITHMS, ids=str)
    def algo(self, request):
        if request.param not in self.algorithms:
            pytest.skip(f"{self.backend} backend does not support {request.param}")
        return request.param

    # ----------------------------------------------------------- basic allow

    def test_allow_under_limit(self, algo):
        lim, _ = self.make(algo, limit=10)
        for i in range(10):
            res = lim.allow("user:1")
            assert res.allowed, f"request {i} should be allowed"
            assert res.limit == 10
        lim.close()

    def test_allow_over_limit_denies(self, algo):
        lim, _ = self.make(algo, limit=5)
        for _ in range(5):
            assert lim.allow("k").allowed
        res = lim.allow("k")
        assert not res.allowed
        assert res.remaining == 0
        assert res.retry_after > 0
        lim.close()

    def test_remaining_decrements(self, algo):
        lim, _ = self.make(algo, limit=10)
        remainings = [lim.allow("k").remaining for _ in range(10)]
        assert remainings == list(range(9, -1, -1))
        lim.close()

    def test_keys_independent(self, algo):
        lim, _ = self.make(algo, limit=3)
        for _ in range(3):
            assert lim.allow("a").allowed
        assert not lim.allow("a").allowed
        assert lim.allow("b").allowed
        lim.close()

    # ----------------------------------------------------------- allow_n

    def test_allow_n_consumes_n(self, algo):
        lim, _ = self.make(algo, limit=10)
        res = lim.allow_n("k", 7)
        assert res.allowed and res.remaining == 3
        res = lim.allow_n("k", 3)
        assert res.allowed and res.remaining == 0
        assert not lim.allow("k").allowed
        lim.close()

    def test_allow_n_all_or_nothing(self, algo):
        """The case the reference's dormant suite encodes and its FW/SW code
        fails (``interface_test.go:154-167``): a denied AllowN must consume
        nothing, so a smaller AllowN succeeds right after."""
        lim, _ = self.make(algo, limit=5)
        assert lim.allow_n("k", 3).allowed
        assert not lim.allow_n("k", 5).allowed  # only 2 left
        assert lim.allow_n("k", 2).allowed      # denial consumed nothing
        lim.close()

    def test_allow_n_invalid(self, algo):
        lim, _ = self.make(algo)
        with pytest.raises(InvalidNError):
            lim.allow_n("k", 0)
        with pytest.raises(InvalidNError):
            lim.allow_n("k", -3)
        lim.close()

    # ----------------------------------------------------------- validation

    def test_empty_key_rejected(self, algo):
        lim, _ = self.make(algo)
        with pytest.raises(InvalidKeyError):
            lim.allow("")
        with pytest.raises(InvalidKeyError):
            lim.reset("")
        lim.close()

    def test_closed_raises(self, algo):
        lim, _ = self.make(algo)
        lim.close()
        with pytest.raises(ClosedError):
            lim.allow("k")
        lim.close()  # idempotent

    # ----------------------------------------------------------- reset

    def test_reset_restores_quota(self, algo):
        lim, _ = self.make(algo, limit=3)
        for _ in range(3):
            assert lim.allow("k").allowed
        assert not lim.allow("k").allowed
        lim.reset("k")
        assert lim.allow("k").allowed
        lim.close()

    # ----------------------------------------------------------- time

    def test_window_expiry_restores_quota(self, algo):
        lim, clock = self.make(algo, limit=4, window=10.0)
        for _ in range(4):
            assert lim.allow("k").allowed
        assert not lim.allow("k").allowed
        # Two full windows clears even sliding-window history (and fully
        # refills a token bucket).
        clock.advance(20.0)
        assert lim.allow("k").allowed
        lim.close()

    def test_retry_after_bounded_by_window(self, algo):
        lim, _ = self.make(algo, limit=2, window=30.0)
        lim.allow_n("k", 2)
        res = lim.allow("k")
        assert not res.allowed
        assert 0 < res.retry_after <= 30.0
        lim.close()

    # ----------------------------------------------------------- concurrency

    def test_concurrency_exactness(self, algo):
        """Reference ``interface_test.go:279-336``: N concurrent unit requests
        against limit=N admit exactly N (no over-admission; exact backends
        also never under-admit)."""
        lim, _ = self.make(algo, limit=100)
        allowed = []
        lock = threading.Lock()

        def worker():
            res = lim.allow("shared")
            with lock:
                allowed.append(res.allowed)

        threads = [threading.Thread(target=worker) for _ in range(150)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        n_allowed = sum(allowed)
        if self.exact_admission:
            assert n_allowed == 100
        else:
            assert n_allowed <= 100
        lim.close()

    # ----------------------------------------------------------- batch

    def test_batch_exactness_duplicate_key(self, algo):
        """Batch analog of concurrency exactness (SURVEY.md §4.3): one batch
        with 150 unit requests for one key, limit 100 -> exactly the first
        100 allowed. Relaxed-consistency backends override
        _assert_hot_batch with their documented envelope."""
        lim, _ = self.make(algo, limit=100)
        out = lim.allow_batch(["hot"] * 150)
        self._assert_hot_batch(lim, out, limit=100)
        lim.close()

    def _assert_hot_batch(self, lim, out, limit: int) -> None:
        if self.exact_admission:
            assert out.allow_count == limit
            assert bool(np.all(out.allowed[:limit]))
            assert not bool(np.any(out.allowed[limit:]))
        else:
            assert out.allow_count <= limit

    def test_batch_matches_sequential(self, algo):
        """allow_batch == sequential allow_n in batch order (exact backends)."""
        if not self.exact_admission:
            pytest.skip("approximate backend")
        keys = ["a", "b", "a", "c", "a", "b"]
        ns = [3, 2, 4, 1, 2, 6]
        lim1, _ = self.make(algo, limit=7)
        out = lim1.allow_batch(keys, ns)
        lim2, _ = self.make(algo, limit=7)
        seq = [lim2.allow_n(k, n) for k, n in zip(keys, ns)]
        assert list(out.allowed) == [r.allowed for r in seq]
        assert list(out.remaining) == [r.remaining for r in seq]
        lim1.close()
        lim2.close()

    def test_batch_mixed_keys(self, algo):
        lim, _ = self.make(algo, limit=2)
        out = lim.allow_batch(["x", "y", "x", "y", "x"])
        if self.exact_admission:
            assert list(out.allowed) == [True, True, True, True, False]
        lim.close()

    # ----------------------------------------------------------- failure

    def test_fail_open(self, algo):
        if not self.supports_failure_injection:
            pytest.skip("backend has no failure mode to inject")
        lim, _ = self.make(algo, limit=5, fail_open=True)
        self.inject_failure(lim)
        res = lim.allow("k")
        assert res.allowed and res.fail_open
        lim.close()

    def test_fail_closed(self, algo):
        if not self.supports_failure_injection:
            pytest.skip("backend has no failure mode to inject")
        from ratelimiter_tpu import StorageUnavailableError

        lim, _ = self.make(algo, limit=5, fail_open=False)
        self.inject_failure(lim)
        with pytest.raises(StorageUnavailableError):
            lim.allow("k")
        lim.close()

    def inject_failure(self, lim) -> None:
        raise NotImplementedError
