"""Interface conformance suite — instantiated for every backend.

The reference defines an equivalent suite (``interface_test.go:11-28``,
``RunAllTests``) but never instantiates it (SURVEY.md §4.2.5); notably its
AllowN-atomicity case (``interface_test.go:154-167``) would fail against the
reference's own FixedWindow/SlidingWindow. Here the suite runs for each
backend x algorithm via pytest class inheritance, and the atomicity case is
law (SURVEY.md §2.4.2 resolution).

Subclasses set ``backend`` and override ``make_limiter`` /
``inject_failure`` as needed.
"""

from __future__ import annotations

import threading

import numpy as np
import pytest

from ratelimiter_tpu import (
    Algorithm,
    ClosedError,
    Config,
    InvalidKeyError,
    InvalidNError,
    ManualClock,
    create_limiter,
)

ALGORITHMS = [Algorithm.TOKEN_BUCKET, Algorithm.SLIDING_WINDOW,
              Algorithm.FIXED_WINDOW, Algorithm.TPU_SKETCH]


class ContractTests:
    backend: str = "exact"
    #: algorithms this backend supports (overridden by sketch backend)
    algorithms = ALGORITHMS
    supports_failure_injection = False
    #: exact backends admit exactly `limit`; approximate (sketch) backends may
    #: under-admit, never over-admit — they set exact_admission = False.
    exact_admission = True
    #: per-key window_scale overrides need per-key window grids; sketch
    #: backends share one ring geometry and set this False.
    supports_window_scale = True
    #: relaxed-consistency backends (mesh delta) cannot pin exact in-batch
    #: allow/deny positions and set this False.
    strict_batch_order = True

    def make_limiter(self, config: Config, clock) -> object:
        return create_limiter(config, backend=self.backend, clock=clock)

    def make(self, algorithm, limit=100, window=60.0, **kw):
        clock = ManualClock()
        cfg = Config(algorithm=algorithm, limit=limit, window=window, **kw)
        return self.make_limiter(cfg, clock), clock

    @pytest.fixture(params=ALGORITHMS, ids=str)
    def algo(self, request):
        if request.param not in self.algorithms:
            pytest.skip(f"{self.backend} backend does not support {request.param}")
        return request.param

    # ----------------------------------------------------------- basic allow

    def test_allow_under_limit(self, algo):
        lim, _ = self.make(algo, limit=10)
        for i in range(10):
            res = lim.allow("user:1")
            assert res.allowed, f"request {i} should be allowed"
            assert res.limit == 10
        lim.close()

    def test_allow_over_limit_denies(self, algo):
        lim, _ = self.make(algo, limit=5)
        for _ in range(5):
            assert lim.allow("k").allowed
        res = lim.allow("k")
        assert not res.allowed
        assert res.remaining == 0
        assert res.retry_after > 0
        lim.close()

    def test_remaining_decrements(self, algo):
        lim, _ = self.make(algo, limit=10)
        remainings = [lim.allow("k").remaining for _ in range(10)]
        assert remainings == list(range(9, -1, -1))
        lim.close()

    def test_keys_independent(self, algo):
        lim, _ = self.make(algo, limit=3)
        for _ in range(3):
            assert lim.allow("a").allowed
        assert not lim.allow("a").allowed
        assert lim.allow("b").allowed
        lim.close()

    # ----------------------------------------------------------- allow_n

    def test_allow_n_consumes_n(self, algo):
        lim, _ = self.make(algo, limit=10)
        res = lim.allow_n("k", 7)
        assert res.allowed and res.remaining == 3
        res = lim.allow_n("k", 3)
        assert res.allowed and res.remaining == 0
        assert not lim.allow("k").allowed
        lim.close()

    def test_allow_n_all_or_nothing(self, algo):
        """The case the reference's dormant suite encodes and its FW/SW code
        fails (``interface_test.go:154-167``): a denied AllowN must consume
        nothing, so a smaller AllowN succeeds right after."""
        lim, _ = self.make(algo, limit=5)
        assert lim.allow_n("k", 3).allowed
        assert not lim.allow_n("k", 5).allowed  # only 2 left
        assert lim.allow_n("k", 2).allowed      # denial consumed nothing
        lim.close()

    def test_allow_n_invalid(self, algo):
        lim, _ = self.make(algo)
        with pytest.raises(InvalidNError):
            lim.allow_n("k", 0)
        with pytest.raises(InvalidNError):
            lim.allow_n("k", -3)
        lim.close()

    # ----------------------------------------------------------- validation

    def test_empty_key_rejected(self, algo):
        lim, _ = self.make(algo)
        with pytest.raises(InvalidKeyError):
            lim.allow("")
        with pytest.raises(InvalidKeyError):
            lim.reset("")
        lim.close()

    def test_closed_raises(self, algo):
        lim, _ = self.make(algo)
        lim.close()
        with pytest.raises(ClosedError):
            lim.allow("k")
        lim.close()  # idempotent

    # ----------------------------------------------------------- reset

    def test_reset_restores_quota(self, algo):
        lim, _ = self.make(algo, limit=3)
        for _ in range(3):
            assert lim.allow("k").allowed
        assert not lim.allow("k").allowed
        lim.reset("k")
        assert lim.allow("k").allowed
        lim.close()

    # ----------------------------------------------------------- time

    def test_window_expiry_restores_quota(self, algo):
        lim, clock = self.make(algo, limit=4, window=10.0)
        for _ in range(4):
            assert lim.allow("k").allowed
        assert not lim.allow("k").allowed
        # Two full windows clears even sliding-window history (and fully
        # refills a token bucket).
        clock.advance(20.0)
        assert lim.allow("k").allowed
        lim.close()

    def test_retry_after_bounded_by_window(self, algo):
        lim, _ = self.make(algo, limit=2, window=30.0)
        lim.allow_n("k", 2)
        res = lim.allow("k")
        assert not res.allowed
        assert 0 < res.retry_after <= 30.0
        lim.close()

    # ----------------------------------------------------------- concurrency

    def test_concurrency_exactness(self, algo):
        """Reference ``interface_test.go:279-336``: N concurrent unit requests
        against limit=N admit exactly N (no over-admission; exact backends
        also never under-admit)."""
        lim, _ = self.make(algo, limit=100)
        allowed = []
        lock = threading.Lock()

        def worker():
            res = lim.allow("shared")
            with lock:
                allowed.append(res.allowed)

        threads = [threading.Thread(target=worker) for _ in range(150)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        n_allowed = sum(allowed)
        if self.exact_admission:
            assert n_allowed == 100
        else:
            assert n_allowed <= 100
        lim.close()

    # ----------------------------------------------------------- batch

    def test_batch_exactness_duplicate_key(self, algo):
        """Batch analog of concurrency exactness (SURVEY.md §4.3): one batch
        with 150 unit requests for one key, limit 100 -> exactly the first
        100 allowed. Relaxed-consistency backends override
        _assert_hot_batch with their documented envelope."""
        lim, _ = self.make(algo, limit=100)
        out = lim.allow_batch(["hot"] * 150)
        self._assert_hot_batch(lim, out, limit=100)
        lim.close()

    def _assert_hot_batch(self, lim, out, limit: int) -> None:
        if self.exact_admission:
            assert out.allow_count == limit
            assert bool(np.all(out.allowed[:limit]))
            assert not bool(np.any(out.allowed[limit:]))
        else:
            assert out.allow_count <= limit

    def test_batch_matches_sequential(self, algo):
        """allow_batch == sequential allow_n in batch order (exact backends)."""
        if not self.exact_admission:
            pytest.skip("approximate backend")
        keys = ["a", "b", "a", "c", "a", "b"]
        ns = [3, 2, 4, 1, 2, 6]
        lim1, _ = self.make(algo, limit=7)
        out = lim1.allow_batch(keys, ns)
        lim2, _ = self.make(algo, limit=7)
        seq = [lim2.allow_n(k, n) for k, n in zip(keys, ns)]
        assert list(out.allowed) == [r.allowed for r in seq]
        assert list(out.remaining) == [r.remaining for r in seq]
        lim1.close()
        lim2.close()

    def test_batch_mixed_keys(self, algo):
        lim, _ = self.make(algo, limit=2)
        out = lim.allow_batch(["x", "y", "x", "y", "x"])
        if self.exact_admission:
            assert list(out.allowed) == [True, True, True, True, False]
        lim.close()

    # --------------------------------------------- policy overrides (tiers)

    def _assert_admitted(self, count: int, limit: int, sent: int) -> None:
        """Admission-count envelope for one fresh key decided in one batch.
        Exact backends: exactly min(limit, sent); approximate backends:
        never more; relaxed-consistency backends (mesh delta) override."""
        if self.exact_admission:
            assert count == min(limit, sent)
        else:
            assert count <= min(limit, sent)

    def test_override_mixed_batch_single_dispatch(self, algo):
        """The policy-engine acceptance shape: ONE batch mixing default and
        overridden keys, every key decided against ITS OWN limit (the
        override resolves inside the same fused step — no per-key host
        dispatch on device backends)."""
        lim, _ = self.make(algo, limit=4)
        lim.set_override("vip", 10)
        out = lim.allow_batch(["vip"] * 12 + ["std"] * 6)
        self._assert_admitted(int(np.sum(out.allowed[:12])), 10, 12)
        self._assert_admitted(int(np.sum(out.allowed[12:])), 4, 6)
        lim.close()

    def test_override_interleaved_order(self, algo):
        """Interleaving default/override keys in one frame keeps per-key
        in-batch sequencing: each key's first `its-limit` requests win."""
        lim, _ = self.make(algo, limit=2)
        lim.set_override("v", 3)
        out = lim.allow_batch(["v", "d", "v", "d", "v", "d", "v", "d"])
        if self.exact_admission and self.strict_batch_order:
            assert list(out.allowed) == [True, True, True, True,
                                         True, False, False, False]
        lim.close()

    def test_override_lowers_limit(self, algo):
        lim, _ = self.make(algo, limit=10)
        lim.set_override("cheap", 2)
        out = lim.allow_batch(["cheap"] * 5)
        self._assert_admitted(out.allow_count, 2, 5)
        self._assert_admitted(lim.allow_batch(["normal"] * 10).allow_count,
                              10, 10)
        lim.close()

    def test_override_result_reports_key_limit(self, algo):
        """Result.limit (and with it X-RateLimit-Limit) is the KEY's
        effective limit, not the config default."""
        lim, _ = self.make(algo, limit=4)
        lim.set_override("vip", 9)
        assert lim.allow("vip").limit == 9
        assert lim.allow("std").limit == 4
        assert lim.allow_batch(["vip", "std"]).results()[0].limit == 9
        lim.close()

    def test_override_get_delete_roundtrip(self, algo):
        lim, _ = self.make(algo, limit=4)
        assert lim.get_override("vip") is None
        ov = lim.set_override("vip", 8)
        assert ov.limit == 8 and lim.get_override("vip").limit == 8
        assert lim.override_count() == 1
        assert dict(lim.list_overrides())["vip"].limit == 8
        assert lim.delete_override("vip") is True
        assert lim.delete_override("vip") is False
        assert lim.get_override("vip") is None
        # Back on the default tier.
        self._assert_admitted(lim.allow_batch(["vip"] * 6).allow_count, 4, 6)
        lim.close()

    def test_override_window_scale(self, algo):
        """Window-scaled keys expire on their OWN grid: a 1/4-window key
        regains quota while default keys are still inside their window.
        (Token bucket: the scale shortens time-to-full the same way.)"""
        if not self.supports_window_scale:
            from ratelimiter_tpu import InvalidConfigError

            lim, _ = self.make(algo, limit=4)
            with pytest.raises(InvalidConfigError):
                lim.set_override("fast", window_scale=0.25)
            lim.close()
            return
        lim, clock = self.make(algo, limit=4, window=40.0)
        lim.set_override("fast", window_scale=0.25)     # 10s window
        assert lim.allow_batch(["fast"] * 4).allow_count == 4
        assert lim.allow_batch(["slow"] * 4).allow_count == 4
        clock.advance(21.0)   # > 2 fast windows, < 1 slow window
        assert lim.allow_batch(["fast"] * 4).allow_count == 4
        slow = lim.allow_batch(["slow"] * 4).allow_count
        if algo is Algorithm.TOKEN_BUCKET:
            assert slow == 2  # continuous refill: 21s * 4/40s = 2.1
        else:
            assert slow == 0
        lim.close()

    def test_override_invalid_rejected(self, algo):
        from ratelimiter_tpu import InvalidConfigError

        lim, _ = self.make(algo)
        with pytest.raises(InvalidConfigError):
            lim.set_override("k", 0)
        with pytest.raises(InvalidConfigError):
            lim.set_override("k", -5)
        with pytest.raises(InvalidConfigError):
            lim.set_override("k", window_scale=0.0)
        from ratelimiter_tpu import InvalidKeyError

        with pytest.raises(InvalidKeyError):
            lim.set_override("", 5)
        assert lim.override_count() == 0
        lim.close()

    # ----------------------------------------------------------- failure

    def test_fail_open(self, algo):
        if not self.supports_failure_injection:
            pytest.skip("backend has no failure mode to inject")
        lim, _ = self.make(algo, limit=5, fail_open=True)
        self.inject_failure(lim)
        res = lim.allow("k")
        assert res.allowed and res.fail_open
        lim.close()

    def test_fail_closed(self, algo):
        if not self.supports_failure_injection:
            pytest.skip("backend has no failure mode to inject")
        from ratelimiter_tpu import StorageUnavailableError

        lim, _ = self.make(algo, limit=5, fail_open=False)
        self.inject_failure(lim)
        with pytest.raises(StorageUnavailableError):
            lim.allow("k")
        lim.close()

    def inject_failure(self, lim) -> None:
        raise NotImplementedError
