"""Smoke test for the runnable deployment artifact (deployments/ —
VERDICT r4 item 9): two native pods with DCN + HTTP come up, serve
shared-quota decisions over HTTP, and converge cross-pod."""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import time
import urllib.error
import urllib.request

import pytest

from netutil import free_port

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "deployments", "two_pod_local.sh")


@pytest.mark.slow
def test_two_pod_local_script():
    if shutil.which("bash") is None or shutil.which("curl") is None:
        pytest.skip("needs bash + curl")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # The suite's conftest forces an 8-virtual-device CPU topology, which
    # makes the pods' jit compiles miss the persistent cache; the pods
    # are single-device servers, so give them the plain topology and skip
    # prewarm (smoke speed, not serving latency, matters here).
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count"))
    env["PREWARM"] = "0"
    # Fixed ports so the test can reach the pods.
    http_a, http_b = free_port(), free_port()
    env.update({"HTTP_A": str(http_a), "HTTP_B": str(http_b),
                "PORT_A": str(free_port()), "PORT_B": str(free_port())})
    proc = subprocess.Popen(["bash", SCRIPT, "120"], env=env,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    try:
        # Wait for both gateways (the script itself waits too; this
        # bounds the test independently of its echo output).
        deadline = time.time() + 90
        for port in (http_a, http_b):
            while True:
                try:
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{port}/healthz",
                            timeout=2) as r:
                        assert json.loads(r.read())["serving"]
                    break
                except Exception:
                    if time.time() > deadline:
                        raise AssertionError(
                            f"gateway :{port} never came up")
                    time.sleep(0.5)
        # Drain a key on pod A over HTTP (limit 100 in the script).
        with urllib.request.urlopen(
                f"http://127.0.0.1:{http_a}/v1/allow?key=user:42&n=100"
                ) as r:
            assert r.status == 200
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{http_a}/v1/allow?key=user:42")
            raise AssertionError("pod A should 429")
        except urllib.error.HTTPError as e:
            assert e.code == 429
        # Pod B hears about it within ~2 DCN cycles (probe budget 30 <
        # limit 100, so denial proves convergence).
        converged = False
        for _ in range(30):
            time.sleep(1.0)
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{http_b}/v1/allow?key=user:42")
            except urllib.error.HTTPError as e:
                assert e.code == 429
                converged = True
                break
        assert converged, "pods never converged over DCN"
        proc.terminate()
        assert proc.wait(timeout=30) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
