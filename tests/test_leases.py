"""Client-embedded quota leases (ADR-022): protocol, manager, cache,
safety oracles, chaos drills, and both-door integration.

The safety headline is debit-upfront: a grant admits the WHOLE budget
through the limiter's decide path before a token reaches the client, so
no client behaviour — spends, crashes, lost revocations, kill -9 — can
push global admissions past the limit. The oracle tests here pin that
bit-exactly; the documented failure side (unused budget reads as
consumed) is asserted too, in the mass-retention checks.

Deliberately grpc-free: the CI lease lane runs this module with zero
skips on a plain CPU box (the native-door class compiles the C++ door,
which the image carries).
"""

from __future__ import annotations

import asyncio
import json
import os
import random
import signal
import subprocess
import sys
import threading
import time
from contextlib import contextmanager
from pathlib import Path

import pytest

from ratelimiter_tpu import (
    Algorithm,
    Config,
    ManualClock,
    create_limiter,
)
from ratelimiter_tpu.leases import LeaseCache, LeaseListener, LeaseManager
from ratelimiter_tpu.observability import Registry, events
from ratelimiter_tpu.serving import AsyncClient, Client, RateLimitServer
from ratelimiter_tpu.serving import protocol as p

REPO_ROOT = Path(__file__).resolve().parent.parent


def _mk_limiter(limit=1000, window=60.0, algo=Algorithm.TPU_SKETCH,
                backend="exact", **kw):
    clock = ManualClock(1_700_000_000.0)
    cfg = Config(algorithm=algo, limit=limit, window=window, **kw)
    return create_limiter(cfg, backend=backend, clock=clock), clock


class FakeClock:
    """Mutable monotonic stand-in for the lease manager/cache clocks."""

    def __init__(self, t: float = 100.0):
        self.t = float(t)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += float(dt)


def _mk_manager(limit=1000, ttl=2.0, budget=64, **kw):
    lim, _ = _mk_limiter(limit=limit)
    clk = FakeClock()
    reg = Registry()
    mgr = LeaseManager(lim, ttl=ttl, default_budget=budget,
                       registry=reg, clock=clk, **kw)
    return mgr, lim, clk, reg


@contextmanager
def running_server(limiter, **kw):
    """A live asyncio-door server on a background loop."""
    loop = asyncio.new_event_loop()
    thread = threading.Thread(target=loop.run_forever, daemon=True)
    thread.start()
    server = RateLimitServer(limiter, "127.0.0.1", 0, **kw)
    asyncio.run_coroutine_threadsafe(server.start(), loop).result(timeout=10)
    try:
        yield server, server.port, loop
    finally:
        asyncio.run_coroutine_threadsafe(server.shutdown(),
                                         loop).result(timeout=10)
        loop.call_soon_threadsafe(loop.stop)
        thread.join(timeout=10)
        loop.close()


def _wait_until(cond, timeout=10.0, interval=0.01, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {what}")


# --------------------------------------------------------------- protocol

class TestLeaseProtocol:
    def test_grant_roundtrip(self):
        frame = p.encode_lease_grant(7, 0xABCD, "user:1", 128, 1.5)
        length, type_, rid = p.parse_header(frame[:p.HEADER_SIZE])
        assert (type_, rid) == (p.T_LEASE_GRANT, 7)
        assert length == len(frame) - 4
        client, key, want, ttl_want = p.parse_lease_grant(
            frame[p.HEADER_SIZE:])
        assert (client, key, want, ttl_want) == (0xABCD, "user:1", 128, 1.5)

    def test_renew_roundtrip(self):
        frame = p.encode_lease_renew(8, 3, 99, "ключ:héllo", 17, 32)
        _, type_, rid = p.parse_header(frame[:p.HEADER_SIZE])
        assert (type_, rid) == (p.T_LEASE_RENEW, 8)
        out = p.parse_lease_renew(frame[p.HEADER_SIZE:])
        assert out == (3, 99, "ключ:héllo", 17, 32)

    def test_return_roundtrip(self):
        frame = p.encode_lease_return(9, 4, 100, "k", 63)
        _, type_, _ = p.parse_header(frame[:p.HEADER_SIZE])
        assert type_ == p.T_LEASE_RETURN
        assert p.parse_lease_return(frame[p.HEADER_SIZE:]) == (4, 100,
                                                               "k", 63)

    def test_lease_r_roundtrip(self):
        frame = p.encode_lease_r(5, True, 42, 256, 2.0, 1000, epoch=3)
        _, type_, rid = p.parse_header(frame[:p.HEADER_SIZE])
        assert (type_, rid) == (p.T_LEASE_R, 5)
        out = p.parse_lease_r(frame[p.HEADER_SIZE:])
        assert out == (True, 42, 256, 2.0, 1000, 3)
        refuse = p.encode_lease_r(6, False, 0, 0, 0.0, 0)
        assert p.parse_lease_r(refuse[p.HEADER_SIZE:])[0] is False

    def test_revoke_push_roundtrip(self):
        frame = p.encode_lease_revoke(p.LEASE_REV_POLICY, 2, [1, 5, 9])
        _, type_, rid = p.parse_header(frame[:p.HEADER_SIZE])
        assert (type_, rid) == (p.T_LEASE_REVOKE, 0)  # unsolicited push
        reason, epoch, ids = p.parse_lease_revoke(frame[p.HEADER_SIZE:])
        assert (reason, epoch, ids) == (p.LEASE_REV_POLICY, 2, [1, 5, 9])
        # Empty id list = revoke-all form.
        allf = p.encode_lease_revoke(p.LEASE_REV_SHUTDOWN, 0, [])
        assert p.parse_lease_revoke(allf[p.HEADER_SIZE:]) == (
            p.LEASE_REV_SHUTDOWN, 0, [])

    def test_revoke_truncated_rejected(self):
        frame = p.encode_lease_revoke(p.LEASE_REV_LIMIT, 1, [1, 2])
        with pytest.raises(p.ProtocolError):
            p.parse_lease_revoke(frame[p.HEADER_SIZE:-3])

    def test_dcn_lease_envelope_roundtrip(self):
        payload = {"scope": "key", "key_hash": "ab" * 8,
                   "reason": "policy", "epoch": 4}
        frame = p.encode_dcn_lease(1, payload)
        _, type_, _ = p.parse_header(frame[:p.HEADER_SIZE])
        assert type_ == p.T_DCN_PUSH
        body = frame[p.HEADER_SIZE:]
        assert body[0] == p.DCN_KIND_LEASE
        assert p.parse_dcn_lease(body[1:]) == payload

    def test_dcn_lease_auth_tamper_rejected(self):
        from ratelimiter_tpu.core.errors import InvalidConfigError

        payload = {"scope": "all", "reason": "limit", "epoch": 1}
        frame = p.encode_dcn_lease(2, payload, "s3cret", sender=7, seq=1)
        body = frame[p.HEADER_SIZE:]
        inner = p.unwrap_dcn_auth(body, "s3cret")
        assert inner[0] == p.DCN_KIND_LEASE
        assert p.parse_dcn_lease(inner[1:]) == payload
        # One flipped payload byte must fail the HMAC.
        bad = bytearray(body)
        bad[-1] ^= 0x01
        with pytest.raises(InvalidConfigError):
            p.unwrap_dcn_auth(bytes(bad), "s3cret")
        # Unauthenticated frame at a secret-requiring receiver: rejected.
        plain = p.encode_dcn_lease(3, payload)
        with pytest.raises(InvalidConfigError):
            p.unwrap_dcn_auth(plain[p.HEADER_SIZE:], "s3cret")


# ---------------------------------------------------------------- manager

class TestLeaseManager:
    def test_grant_debits_budget_upfront(self):
        mgr, lim, _, _ = _mk_manager(limit=1000, budget=256)
        ok, lease_id, budget, ttl, limit, _ = mgr.grant(1, "k", 256)
        assert ok and lease_id == 1 and budget == 256 and limit == 1000
        assert ttl == pytest.approx(2.0)
        # The window has already been charged the WHOLE budget.
        assert lim.allow_n("k", 744).allowed
        assert not lim.allow_n("k", 1).allowed

    def test_grant_refused_when_window_cannot_cover(self):
        mgr, lim, _, _ = _mk_manager(limit=100, budget=64)
        assert lim.allow_n("k", 80).allowed
        ok, _, _, _, _, _ = mgr.grant(1, "k", 64)
        assert not ok
        # A refused grant consumed nothing.
        assert lim.allow_n("k", 20).allowed

    def test_want_clamped_to_max_budget(self):
        mgr, _, _, _ = _mk_manager(limit=100000, max_budget=512)
        ok, _, budget, _, _, _ = mgr.grant(1, "k", 10**9)
        assert ok and budget == 512

    def test_max_leases_capacity(self):
        mgr, _, _, _ = _mk_manager(max_leases=1)
        assert mgr.grant(1, "a")[0]
        assert not mgr.grant(2, "b")[0]

    def test_renew_extends_and_tops_up(self):
        mgr, _, clk, _ = _mk_manager(ttl=2.0, budget=64)
        _, lease_id, _, _, _, _ = mgr.grant(1, "k", 64)
        clk.advance(1.5)
        ok, _, top_up, ttl, limit, _ = mgr.renew(1, lease_id, "k", 10, 32)
        assert ok and top_up == 32 and ttl == pytest.approx(2.0)
        assert limit == 1000
        # The renew pushed the deadline out: 1.5s later it's still live.
        clk.advance(1.5)
        assert mgr.renew(1, lease_id, "k", 0, 0)[0]

    def test_renew_refused_wrong_client_or_unknown(self):
        mgr, _, _, _ = _mk_manager()
        _, lease_id, _, _, _, _ = mgr.grant(1, "k")
        assert not mgr.renew(2, lease_id, "k", 0, 0)[0]   # not the holder
        assert not mgr.renew(1, lease_id + 7, "k", 0, 0)[0]  # unknown

    def test_release_counts_returned_not_recredited(self):
        mgr, lim, _, reg = _mk_manager(limit=1000, budget=100)
        _, lease_id, _, _, _, _ = mgr.grant(1, "k", 100)
        ok, *_ = mgr.release(1, lease_id, "k", 40)
        assert not ok  # RETURN always answers granted=False
        c = reg.get("rate_limiter_lease_tokens_total")
        assert c.value(flow="returned") == 60.0
        assert c.value(flow="consumed") == 40.0
        # Returned budget stays charged: only 900 tokens remain.
        assert lim.allow_n("k", 900).allowed
        assert not lim.allow_n("k", 1).allowed

    def test_ttl_sweep_expires_silent_holder(self):
        mgr, _, clk, reg = _mk_manager(ttl=2.0)
        _, lease_id, _, _, _, _ = mgr.grant(1, "k")
        clk.advance(2.5)
        mgr.grant(2, "other")  # any entry point sweeps first
        assert reg.get("rate_limiter_lease_expired_total").value() == 1.0
        assert not mgr.renew(1, lease_id, "k", 0, 0)[0]

    def test_revoke_key_tombstones_until_ttl(self):
        mgr, _, _, _ = _mk_manager()
        _, lease_id, _, _, _, _ = mgr.grant(1, "k")
        assert mgr.revoke_key("k", p.LEASE_REV_POLICY) == 1
        # A raced renew gets a clean refusal, not unknown-lease noise.
        assert not mgr.renew(1, lease_id, "k", 5, 0)[0]
        # The key itself stays leasable (fresh debit, fresh grant).
        assert mgr.grant(1, "k")[0]

    def test_revoke_pushes_frame_through_grant_connection(self):
        mgr, _, _, _ = _mk_manager()
        frames = []
        _, lease_id, _, _, _, _ = mgr.grant(1, "k", push=frames.append)
        assert mgr.revoke_key("k", p.LEASE_REV_CONTROLLER) == 1
        assert len(frames) == 1
        reason, _, ids = p.parse_lease_revoke(frames[0][p.HEADER_SIZE:])
        assert reason == p.LEASE_REV_CONTROLLER and ids == [lease_id]

    def test_push_error_counts_failure_ttl_bounds_holder(self):
        mgr, _, _, reg = _mk_manager()

        def broken(_frame):
            raise ConnectionError("holder is gone")

        mgr.grant(1, "k", push=broken)
        assert mgr.revoke_all(p.LEASE_REV_MANUAL) == 1
        assert reg.get(
            "rate_limiter_lease_push_failures_total").value() == 1.0

    def test_epoch_bump_revokes_moved_keys(self):
        epoch = [1]
        lim, _ = _mk_limiter()
        clk = FakeClock()
        reg = Registry()
        mgr = LeaseManager(lim, registry=reg, clock=clk,
                           epoch_fn=lambda: epoch[0],
                           owns_fn=lambda key: key == "stays")
        frames = []
        mgr.grant(1, "stays", push=frames.append)
        _, moved_id, _, _, _, _ = mgr.grant(1, "moves", push=frames.append)
        epoch[0] = 2
        assert mgr.check_epoch() == 1
        assert mgr.status()["epoch"] == 2
        reason, ep, ids = p.parse_lease_revoke(frames[-1][p.HEADER_SIZE:])
        assert (reason, ep, ids) == (p.LEASE_REV_EPOCH, 2, [moved_id])
        assert mgr.renew(1, moved_id, "moves", 0, 0)[0] is False

    def test_gossip_emitted_and_applied_by_peer(self):
        sent = []
        mgr_a, _, _, _ = _mk_manager()
        mgr_a.gossip = sent.append
        mgr_b, _, _, _ = _mk_manager()
        mgr_b.grant(9, "k")
        mgr_a.grant(1, "k")
        mgr_a.revoke_key("k", p.LEASE_REV_POLICY)
        assert sent and sent[0]["scope"] == "key"
        assert sent[0]["reason"] == "policy"
        # Same config => same consumer-token hashing on the peer.
        assert mgr_b.on_gossip(sent[0]) == 1
        sent.clear()
        mgr_a.grant(1, "k2")
        mgr_a.revoke_all(p.LEASE_REV_LIMIT)
        assert sent and sent[0]["scope"] == "all"
        mgr_b.grant(9, "k3")
        assert mgr_b.on_gossip(sent[0]) == 1
        # Peer-origin revocations must NOT re-gossip (no storms).
        captured = []
        mgr_b.gossip = captured.append
        mgr_b.grant(9, "k4")
        mgr_b.on_gossip({"scope": "all", "reason": "limit", "epoch": 0})
        assert captured == []

    def test_require_hot_nominates_from_hh_table(self):
        lim, _ = _mk_limiter()
        clk = FakeClock()
        mgr = LeaseManager(lim, require_hot=True, hot_k=4,
                           registry=Registry(), clock=clk)
        hot_token = mgr._consumer_token("hot")

        class HotStats:
            def consumer_stats(self, k):
                return {"top": [{"consumer": hot_token}]}

        # No hh side table at all -> nothing is eligible.
        assert not mgr.grant(1, "hot")[0]
        lim.consumer_stats = HotStats().consumer_stats
        assert mgr.eligible("hot")
        assert not mgr.eligible("cold")
        assert mgr.grant(1, "hot")[0]
        assert not mgr.grant(1, "cold")[0]

    def test_snapshot_restore_roundtrip(self):
        mgr, _, clk, _ = _mk_manager(ttl=4.0)
        _, id_a, _, _, _, _ = mgr.grant(11, "a", 32)
        _, id_b, _, _, _, _ = mgr.grant(22, "b", 64)
        mgr.revoke_key("b")
        arrays, meta = mgr.snapshot_arrays()
        assert len(arrays["lease_id"]) == 2
        lim2, _ = _mk_limiter()
        mgr2 = LeaseManager(lim2, ttl=4.0, registry=Registry(), clock=clk)
        assert mgr2.restore_arrays(arrays, meta) == 2
        st = mgr2.status()
        assert st["active"] == 1 and st["tombstoned"] == 1
        # The restored limiter was NOT touched: restore neither re-debits
        # nor re-credits — the mass rides the LIMITER's own snapshot.
        assert lim2.allow_n("probe", 1000).allowed
        # A surviving holder renews by id (the frame re-carries the key).
        assert mgr2.renew(11, id_a, "a", 3, 0)[0]
        assert not mgr2.renew(22, id_b, "b", 0, 0)[0]  # tombstone held
        # New ids never collide with restored ones.
        _, id_c, _, _, _, _ = mgr2.grant(33, "c")
        assert id_c > max(id_a, id_b)

    def test_journal_events_on_grant_and_revoke(self):
        events.enable(capacity=64)
        try:
            mgr, _, _, _ = _mk_manager()
            raw_key = "user:super-secret-raw-key"
            mgr.grant(1, raw_key)
            mgr.revoke_key(raw_key, p.LEASE_REV_POLICY)
            evs = events.get().tail(category="lease")["events"]
            actions = [e["action"] for e in evs]
            assert "grant" in actions and "revoke" in actions
            rev = next(e for e in evs if e["action"] == "revoke")
            assert rev["payload"]["reason"] == "policy"
            assert rev["severity"] == "warning"
            # PII boundary: hashed key tokens only, never raw keys.
            assert raw_key not in json.dumps(evs)
        finally:
            events.disable()


# ------------------------------------------------------------ lease cache

class TestLeaseCache:
    def _cache(self, **kw):
        clk = FakeClock()
        kw.setdefault("registry", Registry())
        kw.setdefault("client_id", 7)
        return LeaseCache(clock=clk, **kw), clk

    def test_local_answer_decrements_budget(self):
        cache, clk = self._cache()
        cache.on_grant("k", True, 1, 10, 2.0, 100, 0)
        res = cache.try_acquire("k", 3)
        assert res.allowed and res.remaining == 7 and res.limit == 100
        assert cache.status()["local_answers"] == 1

    def test_exhausted_falls_back_to_wire(self):
        cache, _ = self._cache()
        cache.on_grant("k", True, 1, 2, 2.0, 100, 0)
        assert cache.try_acquire("k") is not None
        assert cache.try_acquire("k") is not None
        assert cache.try_acquire("k") is None  # budget gone -> wire

    def test_expired_lease_dies_client_side(self):
        cache, clk = self._cache()
        cache.on_grant("k", True, 1, 10, 2.0, 100, 0)
        clk.advance(2.5)
        assert cache.try_acquire("k") is None
        assert cache.status()["leased_keys"] == 0

    def test_hot_detection_requests_grant(self):
        cache, _ = self._cache(hot_after=3, hot_window=1.0)
        for _ in range(3):
            cache.note_wire("k")
        acts = cache.actions()
        assert ("grant", "k", 0) in acts
        # Pending: no duplicate request on the next tick.
        assert cache.actions() == []

    def test_consumed_delta_exactly_once(self):
        cache, _ = self._cache()
        cache.on_grant("k", True, 1, 10, 2.0, 100, 0)
        for _ in range(4):
            cache.try_acquire("k")
        acts = cache.actions()
        renews = [a for a in acts if a[0] == "renew"]
        assert len(renews) == 1 and renews[0][3] == 4
        # Send failed -> the delta is re-credited for the NEXT renew.
        cache.renew_failed(1, renews[0][3])
        acts2 = cache.actions()
        assert [a for a in acts2 if a[0] == "renew"][0][3] == 4
        # Send succeeded but REFUSED -> lease dies, delta NOT re-credited
        # (the server already reconciled it).
        cache.on_renew(1, False, 0, 0.0, 0, 0)
        assert cache.status()["leased_keys"] == 0

    def test_invalidate_ids_and_epoch(self):
        cache, _ = self._cache()
        cache.on_grant("a", True, 1, 10, 2.0, 100, 1)
        cache.on_grant("b", True, 2, 10, 2.0, 100, 1)
        assert cache.invalidate_ids([2]) == 1
        assert cache.try_acquire("b") is None
        assert cache.try_acquire("a") is not None
        # Empty list drops EVERYTHING (revoke-all push form).
        assert cache.invalidate_ids([]) == 1
        cache.on_grant("c", True, 3, 10, 2.0, 100, 1)
        assert cache.on_epoch(2) == 1  # older-epoch lease retired
        assert cache.status()["leased_keys"] == 0

    def test_drain_returns_all(self):
        cache, _ = self._cache()
        cache.on_grant("a", True, 1, 10, 2.0, 100, 0)
        cache.try_acquire("a")
        rows = cache.drain()
        assert rows == [("return", "a", 1, 1)]
        assert cache.try_acquire("a") is None


# --------------------------------------------------- never-over-admit oracle

class TestNeverOverAdmitOracle:
    def test_storm_never_exceeds_limit(self):
        """Seeded storm of grants, local spends, renews, revocations,
        lost pushes, and abandons: client-observed admissions per key
        can NEVER exceed the limit — bit-exactly, because every local
        token was debited through the window upfront."""
        LIMIT = 500
        lim, _ = _mk_limiter(limit=LIMIT)
        clk = FakeClock()
        mgr = LeaseManager(lim, ttl=3.0, default_budget=16,
                           registry=Registry(), clock=clk)
        cache = LeaseCache(client_id=7, hot_after=2, hot_window=10.0,
                           registry=Registry(), clock=clk)
        rng = random.Random(42)
        keys = ["alpha", "beta", "gamma"]
        admitted = {k: 0 for k in keys}

        def drive():
            for act in cache.actions():
                if act[0] == "grant":
                    _, key, want = act
                    out = mgr.grant(cache.client_id, key, want,
                                    push=None)
                    cache.on_grant(key, out[0], out[1], out[2], out[3],
                                   out[4], out[5])
                else:
                    _, key, lease_id, delta, top_up = act
                    out = mgr.renew(cache.client_id, lease_id, key,
                                    delta, top_up)
                    cache.on_renew(lease_id, out[0], out[2], out[3],
                                   out[4], out[5])

        for step in range(4000):
            key = rng.choice(keys)
            res = cache.try_acquire(key)
            if res is not None:
                admitted[key] += 1
            else:
                r = lim.allow_n(key, 1)
                if r.allowed:
                    admitted[key] += 1
                cache.note_wire(key)
            if step % 7 == 0:
                drive()
            roll = rng.random()
            if roll < 0.01:
                # Revocation storm tick; half the pushes get "lost"
                # (the cache never hears — TTL bounds it instead).
                victim = rng.choice(keys)
                ids = [i for i, k in list(cache._by_id.items())
                       if k == victim]
                mgr.revoke_key(victim, p.LEASE_REV_POLICY)
                if rng.random() < 0.5:
                    cache.invalidate_ids(ids)
            elif roll < 0.02:
                # kill -9 flavored abandon: local state vanishes,
                # server-side grant expires by TTL.
                cache.invalidate_ids([])
            elif roll < 0.1:
                clk.advance(rng.random())
        # The manual limiter clock never advanced: one frozen window.
        for k in keys:
            assert admitted[k] <= LIMIT, (k, admitted[k])
        # Exhaust each key: once the window is spent, neither path
        # admits — and the totals pin AT the limit, not past it.
        for k in keys:
            for _ in range(3 * LIMIT):
                res = cache.try_acquire(k)
                if res is None:
                    res = lim.allow_n(k, 1)
                if res.allowed:
                    admitted[k] += 1
            assert admitted[k] <= LIMIT, (k, admitted[k])
            assert not lim.allow_n(k, 1).allowed

    def test_budget_grants_plus_wire_bounded_by_limit(self):
        """Token-flow ledger: granted budgets + direct wire admissions
        never exceed the window, even when every grant is abandoned."""
        LIMIT = 300
        lim, _ = _mk_limiter(limit=LIMIT)
        clk = FakeClock()
        reg = Registry()
        mgr = LeaseManager(lim, ttl=1.0, default_budget=50,
                           registry=reg, clock=clk)
        rng = random.Random(7)
        wire = 0
        for i in range(40):
            if rng.random() < 0.5:
                mgr.grant(i, "k", 50)     # may be refused when spent
                clk.advance(1.1)          # holder dies; budget lost
            else:
                if lim.allow_n("k", 5).allowed:
                    wire += 5
        granted = int(reg.get("rate_limiter_lease_tokens_total")
                      .value(flow="granted"))
        assert granted + wire <= LIMIT
        assert not lim.allow_n("k", LIMIT).allowed


# ------------------------------------------------------- revocation chaos

class TestRevocationChaos:
    def test_lost_push_is_counted_journaled_and_ttl_bounded(self):
        """Full DCN partition during a revocation storm: pushes drop,
        the failure is counted and journaled, and the holder's cache
        keeps answering ONLY until the TTL — never past it."""
        from ratelimiter_tpu import chaos

        inj = chaos.install(seed=11)
        inj.partition_dcn(1.0)
        events.enable(capacity=64)
        try:
            lim, _ = _mk_limiter()
            clk = FakeClock()
            reg = Registry()
            mgr = LeaseManager(lim, ttl=2.0, default_budget=32,
                               registry=reg, clock=clk)
            cache = LeaseCache(client_id=3, registry=Registry(),
                               clock=clk)
            delivered = []
            out = mgr.grant(3, "k", 32, push=delivered.append)
            cache.on_grant("k", out[0], out[1], out[2], out[3], out[4],
                           out[5])
            assert mgr.revoke_key("k", p.LEASE_REV_POLICY) == 1
            # The push was chaos-dropped, counted, and journaled.
            assert delivered == []
            assert inj.dcn_dropped == 1
            assert reg.get(
                "rate_limiter_lease_push_failures_total").value() == 1.0
            evs = events.get().tail(category="lease")["events"]
            assert any(e["action"] == "revoke" for e in evs)
            # The holder never heard: it keeps answering locally...
            assert cache.try_acquire("k") is not None
            # ...but ONLY until the TTL, the pinned staleness bound.
            clk.advance(2.1)
            assert cache.try_acquire("k") is None
            # And the server refuses the holder's next renew cleanly.
            assert not mgr.renew(3, out[1], "k", 1, 0)[0]
        finally:
            events.disable()
            chaos.uninstall()

    def test_corrupted_push_parses_as_garbage_not_over_admission(self):
        """Bit-flip corruption on the push frame: whatever the client
        does with the garbage (drop it, revoke a wrong id), admissions
        stay bounded — the budget was debited long before."""
        from ratelimiter_tpu import chaos

        inj = chaos.install(seed=13)
        inj.corrupt_dcn(1.0)
        try:
            mgr, _, clk, _ = _mk_manager(ttl=2.0)
            got = []
            out = mgr.grant(5, "k", push=got.append)
            mgr.revoke_key("k", p.LEASE_REV_MANUAL)
            assert inj.dcn_corrupted == 1 and len(got) == 1
            clean = p.encode_lease_revoke(p.LEASE_REV_MANUAL, 0,
                                          [out[1]])
            assert got[0] != clean  # the wire really was corrupted
            # Server state is already revoked regardless of delivery.
            assert not mgr.renew(5, out[1], "k", 0, 0)[0]
        finally:
            chaos.uninstall()


# ----------------------------------------------------- asyncio door (e2e)

class TestAsyncioDoorLeases:
    def test_client_lease_lifecycle_and_policy_revocation(self):
        lim, _ = _mk_limiter(limit=100000)
        mgr = LeaseManager(lim, ttl=2.0, default_budget=64,
                           registry=Registry())
        with running_server(lim, leases=mgr) as (_, port, _loop):
            with Client(port=port) as c:
                cache = c.enable_leases(interval=0.02, hot_after=3,
                                        hot_window=5.0)
                _wait_until(
                    lambda: (c.allow("hot").allowed
                             and cache.status()["leased_keys"] > 0),
                    what="lease grant")
                before = cache.status()["local_answers"]
                for _ in range(64 // 2):
                    assert c.allow("hot").allowed
                assert cache.status()["local_answers"] > before
                assert mgr.status()["active"] >= 1
                # A policy mutation through the door revokes; the push
                # rides the granting connection back to THIS client.
                c.set_override("hot", 50000)
                _wait_until(
                    lambda: cache.status()["leased_keys"] == 0,
                    what="revocation push to reach the cache")
                # Wire path still serves the key afterwards.
                assert c.allow("hot").allowed

    def test_shutdown_revokes_all(self):
        lim, _ = _mk_limiter(limit=100000)
        mgr = LeaseManager(lim, ttl=30.0, default_budget=16,
                           registry=Registry())
        with running_server(lim, leases=mgr) as (_, port, _loop):
            with Client(port=port) as c:
                cache = c.enable_leases(interval=0.02, hot_after=2,
                                        hot_window=5.0)
                _wait_until(
                    lambda: (c.allow("k").allowed
                             and cache.status()["leased_keys"] > 0),
                    what="lease grant")
        # Server shutdown pushed revoke-all before closing.
        assert mgr.status()["active"] == 0

    def test_async_client_leases(self):
        lim, _ = _mk_limiter(limit=100000)
        mgr = LeaseManager(lim, ttl=2.0, default_budget=64,
                           registry=Registry())

        async def go():
            server = RateLimitServer(lim, "127.0.0.1", 0, leases=mgr)
            await server.start()
            c = await AsyncClient.connect(port=server.port)
            try:
                cache = await c.enable_leases(interval=0.02, hot_after=3,
                                              hot_window=5.0)
                deadline = time.monotonic() + 10
                while time.monotonic() < deadline:
                    assert (await c.allow("hot")).allowed
                    if cache.status()["leased_keys"]:
                        break
                    await asyncio.sleep(0.005)
                assert cache.status()["leased_keys"] == 1
                before = cache.status()["local_answers"]
                for _ in range(20):
                    assert (await c.allow("hot")).allowed
                assert cache.status()["local_answers"] > before
            finally:
                await c.close()
                await server.shutdown()

        asyncio.run(go())
        assert mgr.status()["active"] == 0  # close() returned the lease


# ------------------------------------------------------ native door (e2e)

class TestNativeDoorLeases:
    pytestmark = pytest.mark.skipif(
        not __import__(
            "ratelimiter_tpu.serving.native_server",
            fromlist=["native_server_available"],
        ).native_server_available(),
        reason="needs g++ for the native server")

    def test_lease_sidecar_next_to_native_door(self):
        from ratelimiter_tpu.serving.native_server import (
            NativeRateLimitServer,
        )

        lim, _ = _mk_limiter(limit=100000)
        mgr = LeaseManager(lim, ttl=2.0, default_budget=64,
                           registry=Registry())
        listener = LeaseListener(mgr, "127.0.0.1", 0)
        listener.start()
        srv = NativeRateLimitServer(lim, "127.0.0.1", 0)
        srv.start()
        try:
            with Client(port=srv.port) as c:
                cache = c.enable_leases(lease_port=listener.port,
                                        interval=0.02, hot_after=3,
                                        hot_window=5.0)
                _wait_until(
                    lambda: (c.allow("hot").allowed
                             and cache.status()["leased_keys"] > 0),
                    what="lease grant via the sidecar listener")
                before = cache.status()["local_answers"]
                for _ in range(20):
                    assert c.allow("hot").allowed
                assert cache.status()["local_answers"] > before
                # Revocation pushes ride the sidecar connection too.
                mgr.revoke_all(p.LEASE_REV_MANUAL)
                _wait_until(
                    lambda: cache.status()["leased_keys"] == 0,
                    what="revocation push via the sidecar")
        finally:
            srv.shutdown()
            listener.close()


# ------------------------------------------------- kill -9 mass retention

_HOLDER_SCRIPT = """
import sys, time
from ratelimiter_tpu.serving import Client

port = int(sys.argv[1])
c = Client(port=port)
cache = c.enable_leases(interval=0.02, hot_after=1, hot_window=60.0,
                        low_water=0.0)
# Exactly ONE wire decision seeds the hot detector; the grant follows
# on a driver tick without further wire debits.
assert c.allow("hh").allowed
deadline = time.monotonic() + 30
while time.monotonic() < deadline:
    if cache.status()["leased_keys"]:
        break
    time.sleep(0.01)
else:
    sys.exit(3)
for _ in range(5):
    assert c.allow("hh").allowed  # local answers, no wire debit
print("LEASED", flush=True)
time.sleep(600)  # hold the lease until kill -9
"""


class TestKillNineHolder:
    def test_killed_holder_budget_expires_and_mass_stays(self, tmp_path):
        """kill -9 a lease-holding client process: the grant expires
        server-side, its unused budget reads as consumed (bit-exact
        mass retention), and a checkpoint restore does not resurrect
        the mass."""
        LIMIT, BUDGET = 200, 64
        lim, _ = _mk_limiter(limit=LIMIT)
        mgr = LeaseManager(lim, ttl=1.0, default_budget=BUDGET,
                           registry=Registry())
        script = tmp_path / "holder.py"
        script.write_text(_HOLDER_SCRIPT, encoding="utf-8")
        env = dict(os.environ,
                   PYTHONPATH=str(REPO_ROOT),
                   PYTHONUNBUFFERED="1",
                   JAX_PLATFORMS="cpu")
        with running_server(lim, leases=mgr) as (_, port, _loop):
            proc = subprocess.Popen(
                [sys.executable, str(script), str(port)],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                env=env, text=True)
            try:
                # jax/absl may chat on the merged stream before the
                # holder's own banner — scan for it.
                lines = []
                deadline = time.monotonic() + 120
                while time.monotonic() < deadline:
                    line = proc.stdout.readline()
                    if not line:
                        break
                    lines.append(line)
                    if "LEASED" in line:
                        break
                assert any("LEASED" in ln for ln in lines), (
                    f"holder never leased: {lines!r}")
                assert mgr.status()["active"] == 1
                # Snapshot the grant table while the holder is alive.
                arrays, meta = mgr.snapshot_arrays()
                os.kill(proc.pid, signal.SIGKILL)
                proc.wait(timeout=30)
                # No renewals arrive; the TTL sweep expires the grant.
                _wait_until(
                    lambda: (mgr.grant(99, "sweep-probe")[0] or True)
                    and mgr.status()["expired_total"] >= 1,
                    what="server-side lease expiry")
                # The holder's grant is gone; its key holds no leases.
                assert "hh" not in mgr._by_key
            finally:
                if proc.poll() is None:
                    proc.kill()
                proc.wait(timeout=10)
        # Mass retention, bit-exact: the holder debited 1 wire decision
        # + a 64-token grant; the probe key is separate. Kill -9 does
        # NOT refund the 59 unspent tokens.
        assert lim.allow_n("hh", LIMIT - BUDGET - 1).allowed
        assert not lim.allow_n("hh", 1).allowed
        # Restore the sidecar into a FRESH process: the grant table
        # comes back, the limiter is untouched (no resurrection, no
        # double debit — mass rides the limiter's own snapshot).
        lim2, _ = _mk_limiter(limit=LIMIT)
        mgr2 = LeaseManager(lim2, ttl=1.0, registry=Registry())
        assert mgr2.restore_arrays(arrays, meta) == 1
        assert lim2.allow_n("probe", LIMIT).allowed
        lim2.close()
        lim.close()


# ----------------------------------------------- leases-off identity pin

class TestLeasesOffPin:
    def test_manager_attachment_is_decision_invisible(self):
        """Leases off (manager constructed, zero grants): the decision
        stream is byte-identical to a limiter that never heard of
        leases — the pinned no-regression contract."""
        rng = random.Random(1234)
        workload = [(f"k{rng.randrange(8)}", rng.randrange(1, 4))
                    for _ in range(600)]
        lim_plain, _ = _mk_limiter(limit=100)
        lim_leased, _ = _mk_limiter(limit=100)
        LeaseManager(lim_leased, registry=Registry())  # attached, idle
        got_plain = [lim_plain.allow_n(k, n).allowed for k, n in workload]
        got_leased = [lim_leased.allow_n(k, n) for k, n in workload]
        assert got_plain == [r.allowed for r in got_leased]
        # Full-result equality, not just the bitmap.
        lim_a, _ = _mk_limiter(limit=100)
        lim_b, _ = _mk_limiter(limit=100)
        LeaseManager(lim_b, registry=Registry())
        for k, n in workload[:100]:
            assert lim_a.allow_n(k, n) == lim_b.allow_n(k, n)


# ----------------------------------------------------------- audit mirror

class TestAuditMirror:
    def test_reconcile_offers_leased_admissions_to_auditor(self):
        from ratelimiter_tpu.observability import audit

        cfg = Config(algorithm=Algorithm.TPU_SKETCH, limit=1000,
                     window=60.0)
        aud = audit.enable(cfg, sample=1, start=False,
                           registry=Registry())
        try:
            lim, _ = _mk_limiter(limit=1000)
            mgr = LeaseManager(lim, registry=Registry(),
                               clock=FakeClock())
            _, lease_id, _, _, _, _ = mgr.grant(1, "k", 64)
            mgr.renew(1, lease_id, "k", 10, 0)
            aud.process_pending()
            st = aud.status()
            assert st["samples"] >= 1
        finally:
            audit.disable()


# ------------------------------------------------------------ fleet client

class TestFleetClientLeases:
    def test_fleet_client_leases_route_to_owner(self):
        """FleetClient over two live members, each with its own lease
        manager: hot keys lease from their OWNER, answer locally, and an
        epoch bump retires stale leases client-side."""
        lim_a, _ = _mk_limiter(limit=100000)
        lim_b, _ = _mk_limiter(limit=100000)
        mgr_a = LeaseManager(lim_a, ttl=2.0, default_budget=64,
                             registry=Registry())
        mgr_b = LeaseManager(lim_b, ttl=2.0, default_budget=64,
                             registry=Registry())
        from ratelimiter_tpu.serving.client import FleetClient

        with running_server(lim_a, leases=mgr_a) as (_, pa, _l1), \
                running_server(lim_b, leases=mgr_b) as (_, pb, _l2):
            d = {"buckets": 32, "epoch": 1, "hosts": [
                {"id": "a", "host": "127.0.0.1", "port": pa,
                 "ranges": [[0, 16]], "successor": "b"},
                {"id": "b", "host": "127.0.0.1", "port": pb,
                 "ranges": [[16, 32]], "successor": "a"},
            ]}
            fc = FleetClient(d, map_max_age=None)
            try:
                cache = fc.enable_leases(interval=0.02, hot_after=3,
                                         hot_window=5.0)
                # One key per owner, so BOTH members grant.
                owner_of = (lambda k: int(
                    fc.map.owner_of_hash(fc._hash([k]))[0]))
                key_a = next(f"k:{i}" for i in range(99)
                             if owner_of(f"k:{i}") == 0)
                key_b = next(f"k:{i}" for i in range(99)
                             if owner_of(f"k:{i}") == 1)
                _wait_until(
                    lambda: (fc.allow(key_a).allowed
                             and fc.allow(key_b).allowed
                             and cache.status()["leased_keys"] == 2),
                    what="leases from both owners")
                assert mgr_a.status()["active"] == 1
                assert mgr_b.status()["active"] == 1
                before = cache.status()["local_answers"]
                for _ in range(20):
                    assert fc.allow(key_a).allowed
                    assert fc.allow(key_b).allowed
                assert cache.status()["local_answers"] >= before + 30
                # Fleet epoch bump: stale-epoch leases retire locally.
                assert cache.on_epoch(2) == 2
                assert cache.status()["leased_keys"] == 0
            finally:
                fc.close()
        lim_a.close()
        lim_b.close()
