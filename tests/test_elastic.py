"""Elastic fleet lifecycle (ADR-018): live range migration, adopted-unit
durability, automatic rejoin give-back, graceful departure, and the
chaos scenarios that break them mid-flight.

The in-process tests build real FleetCore/FleetForwarder/FleetMembership
stacks per host with a patched frame transport (payload-level protocol,
deterministic ManualClock) — the same shape TestInProcessFleetOracle
uses; the wire itself is covered by the slow two-process tests below and
in tests/test_fleet.py.

Pinned invariants:

* a migrated range's counters CONTINUE on the receiver (capture ->
  WAL-suffix replay -> flip; overrides exact, loss bounded by the
  handoff window, under-count only);
* exactly ONE owner per bucket range per epoch, under kill/abort at
  every injected handoff phase;
* the adopted-range standby rides the successor's own snapshot cycle
  (the ADR-017 declared leftover): original owner dies -> successor
  adopts -> successor snapshots -> successor dies -> ITS successor
  restores the adopted overrides exactly;
* a returning host gets its ranges back automatically (auto rejoin)
  with the state the successor accumulated while covering for it.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from ratelimiter_tpu import (
    Algorithm,
    Config,
    PersistenceSpec,
    SketchParams,
)
from ratelimiter_tpu.chaos import injector as chaos_injector
from ratelimiter_tpu.core.clock import ManualClock
from ratelimiter_tpu.fleet import (
    FleetCore,
    FleetForwarder,
    FleetMap,
    FleetMembership,
    build_standby,
)
from ratelimiter_tpu.fleet.config import FleetHost
from ratelimiter_tpu.observability.metrics import Registry
from ratelimiter_tpu.persistence import PersistenceManager
from tests.netutil import free_port

jax = pytest.importorskip("jax")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cfg(dir_=None, limit=20):
    return Config(algorithm=Algorithm.SLIDING_WINDOW, limit=limit,
                  window=600.0,
                  sketch=SketchParams(depth=2, width=1024, sub_windows=6),
                  persistence=PersistenceSpec(dir=dir_))


class _Host:
    """One in-process fleet member: persistence + core + forwarder +
    membership, with frame delivery patched to direct calls."""

    def __init__(self, name, fleet_map, clock, tmp_path, hosts):
        self.name = name
        self.clock = clock
        self.dir = str(tmp_path / f"snap-{name}")
        cfg = _cfg(self.dir)
        self.persist = PersistenceManager(cfg.persistence)
        from ratelimiter_tpu.algorithms.sketch import SketchLimiter

        self.limiter = self.persist.wrap(SketchLimiter(cfg, clock))
        self.cfg = self.limiter.config
        self.core = FleetCore(fleet_map, name, prefix=self.cfg.prefix,
                              registry=Registry())
        self.fwd = FleetForwarder(self.limiter, self.core)
        self.persist.attach([self.limiter])
        self.persist.recover()
        self.hosts = hosts

        def restore_fn(payload):
            dir_ = payload.get("snapshot_dir")
            if not dir_:
                return None
            return build_standby(self.cfg, dir_,
                                 origin=payload.get("origin"),
                                 clock=clock)

        def adopt_fn(dead):
            if dead.snapshot_dir:
                return build_standby(self.cfg, dead.snapshot_dir,
                                     clock=clock)
            from ratelimiter_tpu import create_limiter

            return create_limiter(self.cfg, backend="sketch",
                                  clock=clock)

        self.membership = FleetMembership(
            self.core, heartbeat=0.1, dead_after=0.5,
            adopt_fn=adopt_fn,
            snapshot_fn=self.persist.snapshot_now,
            handoff_restore_fn=restore_fn,
            on_adopt=lambda o, u, r: self.persist.add_aux_unit(o, u, r),
            on_release=self.persist.remove_aux_unit,
            registry=Registry())
        self.membership._push_frame = self._push

    def _push(self, host, payload):
        peer = self.hosts.get(host.id)
        if peer is None:
            raise ConnectionError(f"peer {host.id} down")
        if payload.get("kind") == "handoff":
            # Synchronous for test determinism (production runs it on a
            # handoff thread off the receive path).
            peer.membership._handle_handoff(payload)
        else:
            peer.membership.handle_announce(payload)

    def kill(self):
        """kill -9: drop off the transport; no final snapshot, no
        graceful close. The one divergence from a real SIGKILL is that
        the OS would release the WAL flock at process exit — emulate
        that by closing the log fd, nothing else."""
        self.hosts.pop(self.name, None)
        self._killed = True
        self.persist.wal.close()

    def close(self):
        self.hosts.pop(self.name, None)
        self.fwd.close()
        if not getattr(self, "_killed", False):
            self.persist.stop(final_snapshot=False)


def _make_fleet(tmp_path, names, clock, buckets=48):
    per = buckets // len(names)
    hosts_spec = []
    for i, n in enumerate(names):
        lo = i * per
        hi = buckets if i == len(names) - 1 else (i + 1) * per
        hosts_spec.append(FleetHost(
            id=n, host="127.0.0.1", port=i + 1, ranges=((lo, hi),),
            successor=names[(i + 1) % len(names)],
            snapshot_dir=str(tmp_path / f"snap-{n}")))
    m = FleetMap(buckets=buckets, hosts=tuple(hosts_spec))
    m.validate()
    hosts: dict = {}
    for n in names:
        hosts[n] = _Host(n, m, clock, tmp_path, hosts)
    return m, hosts


def _owned_key(core, ordinal, prefix="k"):
    return next(f"{prefix}:{i}" for i in range(500)
                if int(core.owners_of_hash(
                    core.hash_keys([f"{prefix}:{i}"]))[0]) == ordinal)


def _rejoin_and_wait(membership, epoch, timeout=10.0):
    """Kick the give-back (it runs on its own thread so the heartbeat
    keeps beating) and wait for the flip to land."""
    membership._maybe_rejoin()
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if membership.core.map.epoch >= epoch:
            return
        time.sleep(0.02)
    raise AssertionError(
        f"rejoin flip to epoch {epoch} never landed "
        f"(at {membership.core.map.epoch})")


class TestLiveMigration:
    def test_counters_and_overrides_continue_on_receiver(self, tmp_path):
        clock = ManualClock(1000.0)
        m, hosts = _make_fleet(tmp_path, ["a", "b"], clock)
        a, b = hosts["a"], hosts["b"]
        try:
            hot = _owned_key(a.core, 0)
            vip = _owned_key(a.core, 0, "vip")
            for _ in range(15):
                a.fwd.allow_n(hot, 1)
            a.fwd.set_override(vip, 7)
            ranges = m.host("a").ranges
            assert a.membership.migrate_ranges(ranges, "b", wait=2.0)
            assert a.core.map.epoch == 2
            assert b.core.map.epoch == 2
            assert b.core.map.host("b").ranges == tuple(
                sorted(set(m.host("b").ranges) | set(ranges)))
            # The receiver CONTINUES the sequence: 5 of 20 left.
            seq = [b.fwd.allow_n(hot, 1) for _ in range(7)]
            assert [r.allowed for r in seq] == [True] * 5 + [False] * 2
            assert b.fwd.get_override(vip).limit == 7
            assert b.membership.handoffs == 1
        finally:
            a.close()
            b.close()

    def test_departure_hands_everything_to_successor(self, tmp_path):
        clock = ManualClock(1000.0)
        m, hosts = _make_fleet(tmp_path, ["a", "b"], clock)
        a, b = hosts["a"], hosts["b"]
        try:
            hot = _owned_key(a.core, 0)
            for _ in range(20):
                a.fwd.allow_n(hot, 1)
            assert a.membership.depart(wait=2.0)
            assert b.core.map.owned_buckets("b") == m.buckets
            assert a.core.map.host("a").ranges == ()
            # b serves the departed range with the restored counters.
            assert not b.fwd.allow_n(hot, 1).allowed
        finally:
            a.close()
            b.close()

    def test_unrelated_epoch_bump_does_not_confirm_flip(self, tmp_path):
        """Flip confirmation is ownership-level: an unrelated epoch
        bump landing during the wait (a failover elsewhere) must not
        make migrate_ranges report success for a move whose handoff
        never reached the receiver."""
        from dataclasses import replace as _replace

        clock = ManualClock(1000.0)
        m, hosts = _make_fleet(tmp_path, ["a", "b"], clock)
        a, b = hosts["a"], hosts["b"]
        try:
            a.membership._push_frame = lambda host, payload: None  # dropped
            bumped = _replace(m, epoch=m.epoch + 1)

            def bump_soon():
                time.sleep(0.1)
                a.membership.handle_announce(
                    {"kind": "announce", "from": "b",
                     "map": bumped.to_dict()})

            t = threading.Thread(target=bump_soon, daemon=True)
            t.start()
            assert not a.membership.migrate_ranges(
                m.host("a").ranges, "b", wait=0.5)
            t.join(timeout=5)
            # Epoch moved, ownership did not — and a still serves.
            assert a.core.map.epoch == 2
            assert a.core.map.host("a").ranges == m.host("a").ranges
        finally:
            a.close()
            b.close()

    def test_equal_epoch_conflict_converges_on_canonical_winner(
            self, tmp_path):
        """Two uncoordinated movers can mint the SAME epoch: every
        member adopts the deterministic canonical winner regardless of
        arrival order, so the fleet converges instead of splitting."""
        clock = ManualClock(1000.0)
        m, hosts = _make_fleet(tmp_path, ["a", "b"], clock)
        a, b = hosts["a"], hosts["b"]
        try:
            m1 = m.move_ranges(m.host("a").ranges, "a", "b")
            m2 = m.move_ranges(m.host("b").ranges, "b", "a")
            assert m1.epoch == m2.epoch == m.epoch + 1
            winner = min((m1, m2), key=lambda x: x.canonical_key())
            for host_obj, first, second in ((a, m1, m2), (b, m2, m1)):
                host_obj.membership.handle_announce(
                    {"kind": "announce", "from": "x",
                     "map": first.to_dict()})
                host_obj.membership.handle_announce(
                    {"kind": "announce", "from": "y",
                     "map": second.to_dict()})
            assert a.core.map.to_dict() == winner.to_dict()
            assert b.core.map.to_dict() == winner.to_dict()
        finally:
            a.close()
            b.close()

    def test_restore_failure_aborts_live_handoff(self, tmp_path):
        """Unlike dead-owner failover (fresh state beats no service), a
        LIVE move whose standby restore fails ABORTS before the epoch
        bump: the giver still holds the exact counters, so flipping to
        fresh state would hand every moved key a full quota for
        nothing."""
        clock = ManualClock(1000.0)
        m, hosts = _make_fleet(tmp_path, ["a", "b"], clock)
        a, b = hosts["a"], hosts["b"]
        try:

            def broken_restore(payload):
                raise RuntimeError("snapshot volume blip")

            b.membership.handoff_restore_fn = broken_restore
            assert not a.membership.migrate_ranges(
                m.host("a").ranges, "b", wait=0.3)
            assert a.core.map.epoch == 1
            assert b.core.map.epoch == 1
            assert a.core.map.host("a").ranges == m.host("a").ranges
        finally:
            a.close()
            b.close()

    def test_depart_with_no_live_peer_keeps_ownership(self, tmp_path):
        clock = ManualClock(1000.0)
        m, hosts = _make_fleet(tmp_path, ["a", "b"], clock)
        a, b = hosts["a"], hosts["b"]
        try:
            b.kill()
            a.membership._dead.add("b")
            assert not a.membership.depart(wait=0.2)
            assert a.core.map.host("a").ranges == m.host("a").ranges
        finally:
            a.close()
            b.close()


class TestAdoptedUnitDurability:
    def test_second_failure_restores_adopted_overrides_exactly(
            self, tmp_path):
        """The ADR-017 declared leftover, now closed: A dies -> B
        adopts -> B snapshots (aux rides its own cycle) -> B dies ->
        C restores from B's dir and still has A's overrides exactly
        and A's counters (within one snapshot interval)."""
        clock = ManualClock(1000.0)
        m, hosts = _make_fleet(tmp_path, ["a", "b", "c"], clock)
        a, b, c = hosts["a"], hosts["b"], hosts["c"]
        try:
            hot = _owned_key(a.core, 0)
            vip = _owned_key(a.core, 0, "vip")
            for _ in range(20):
                a.fwd.allow_n(hot, 1)
            a.fwd.set_override(vip, 11)
            a.persist.snapshot_now()
            a.kill()
            # B (a's successor) fails the range over.
            b.membership._dead.add("a")
            b.membership._maybe_failover(b.core.map.host("a"))
            assert b.core.map.epoch == 2
            assert not b.fwd.allow_n(hot, 1).allowed
            assert b.fwd.get_override(vip).limit == 11
            # Snapshot-age the successor: the aux unit must ride.
            entry = b.persist.snapshot_now()
            assert any(x["origin"] == "a" for x in entry.get("aux", []))
            # kill -9 the successor; C restores B's dir (own + aux).
            b.kill()
            unit = build_standby(c.cfg, b.dir, clock=clock)
            try:
                assert unit.get_override(vip).limit == 11
                assert not unit.allow_n(hot, 1).allowed
            finally:
                unit.close()
        finally:
            for h in (a, b, c):
                h.close()

    def test_release_removes_aux_from_snapshot_cycle(self, tmp_path):
        clock = ManualClock(1000.0)
        m, hosts = _make_fleet(tmp_path, ["a", "b"], clock)
        a, b = hosts["a"], hosts["b"]
        try:
            a.persist.snapshot_now()
            a.kill()
            b.membership._dead.add("a")
            b.membership._maybe_failover(b.core.map.host("a"))
            assert any(x["origin"] == "a" for x in
                       b.persist.snapshot_now().get("aux", []))
            # A rejoins; after the give-back the aux entry stops.
            a2 = _Host("a", b.core.map, clock, tmp_path, hosts)
            hosts["a"] = a2
            b.membership.handle_announce(
                {"kind": "announce", "from": "a",
                 "map": a2.core.map.to_dict()})
            _rejoin_and_wait(b.membership, 3)
            assert not b.persist.snapshot_now().get("aux", [])
            a2.close()
        finally:
            for h in (a, b):
                h.close()


class TestMeshPeerStandby:
    def test_mesh_combined_snapshot_rebuckets_onto_standby(self,
                                                           tmp_path):
        """A sliced-mesh peer's combined snapshot cannot restore a
        single-unit standby directly; build_standby re-buckets it (the
        1-slice conservative union) instead of adopting fresh state —
        counters continue, overrides exact."""
        from ratelimiter_tpu.parallel.limiter import SlicedMeshLimiter

        clock = ManualClock(1000.0)
        d = str(tmp_path / "mesh-peer")
        cfg = _cfg(d)
        pm = PersistenceManager(cfg.persistence)
        mesh = pm.wrap(SlicedMeshLimiter(cfg, clock, n_devices=4))
        cfg = mesh.config
        pm.attach([mesh])
        pm.recover()
        try:
            for _ in range(20):
                mesh.allow_n("hot", 1)
            mesh.set_override("vip", 3)
            pm.snapshot_now()
            unit = build_standby(cfg, d, clock=clock)
            try:
                assert not unit.allow_n("hot", 1).allowed
                assert unit.get_override("vip").limit == 3
            finally:
                unit.close()
        finally:
            pm.stop(final_snapshot=False)
            mesh.close()


class TestRejoin:
    def test_returning_host_takes_ranges_back_with_state(self, tmp_path):
        clock = ManualClock(1000.0)
        m, hosts = _make_fleet(tmp_path, ["a", "b"], clock)
        a, b = hosts["a"], hosts["b"]
        try:
            hot = _owned_key(a.core, 0)
            vip = _owned_key(a.core, 0, "vip")
            for _ in range(12):
                a.fwd.allow_n(hot, 1)
            a.fwd.set_override(vip, 5)
            a.persist.snapshot_now()
            a.kill()
            b.membership._dead.add("a")
            b.membership._maybe_failover(b.core.map.host("a"))
            # B keeps charging the range while covering.
            for _ in range(8):
                b.fwd.allow_n(hot, 1)
            # A restarts fresh and announces; B hands the ranges back.
            a2 = _Host("a", b.core.map, clock, tmp_path, hosts)
            hosts["a"] = a2
            b.membership.handle_announce(
                {"kind": "announce", "from": "a",
                 "map": a2.core.map.to_dict()})
            assert "a" in b.membership._rejoin_pending
            _rejoin_and_wait(b.membership, 3)
            assert b.core.map.epoch == 3
            assert a2.core.map.epoch == 3
            assert a2.core.map.host("a").ranges == m.host("a").ranges
            assert b.core.status()["adopted_buckets"] == 0
            assert b.membership.rejoins == 1
            # A serves with the ACCUMULATED state (12 + 8 = at limit).
            assert not a2.fwd.allow_n(hot, 1).allowed
            assert a2.fwd.get_override(vip).limit == 5
            # Exactly one owner: B no longer serves the range locally.
            owners = a2.core.map.owner_table
            for lo, hi in m.host("a").ranges:
                assert (owners[lo:hi] == a2.core.map.ordinal("a")).all()
            a2.close()
        finally:
            for h in (a, b):
                h.close()

    def test_manual_rejoin_mode_never_hands_back(self, tmp_path):
        clock = ManualClock(1000.0)
        m, hosts = _make_fleet(tmp_path, ["a", "b"], clock)
        a, b = hosts["a"], hosts["b"]
        b.membership.auto_rejoin = False
        try:
            a.persist.snapshot_now()
            a.kill()
            b.membership._dead.add("a")
            b.membership._maybe_failover(b.core.map.host("a"))
            b.membership.handle_announce(
                {"kind": "announce", "from": "a",
                 "map": a.core.map.to_dict()})
            assert "a" not in b.membership._rejoin_pending
            b.membership._maybe_rejoin()
            assert b.core.map.epoch == 2  # unchanged: operator's call
        finally:
            for h in (a, b):
                h.close()


class TestHandoffChaos:
    def test_kill_during_handoff_leaves_exactly_one_owner(self,
                                                          tmp_path):
        """Abort at EVERY injected phase: the flip is only ever
        published by the receiver after its restore, so a death at any
        point leaves the sender the single owner at the old epoch."""
        clock = ManualClock(1000.0)
        m, hosts = _make_fleet(tmp_path, ["a", "b"], clock)
        a, b = hosts["a"], hosts["b"]
        inj = chaos_injector.install(seed=3)
        try:
            for phase in ("capture", "restore", "flip"):
                inj.abort_handoff(phase=phase, count=1)
                if phase == "capture":
                    # Sender-side abort surfaces to the caller.
                    with pytest.raises(chaos_injector.SliceFault):
                        a.membership.migrate_ranges(
                            m.host("a").ranges, "b", wait=0.2)
                else:
                    assert not a.membership.migrate_ranges(
                        m.host("a").ranges, "b", wait=0.2)
                assert a.core.map.epoch == 1
                assert b.core.map.epoch == 1
                assert a.core.map.host("a").ranges == m.host("a").ranges
                assert b.core.map.host("a").ranges == m.host("a").ranges
            assert inj.handoff_aborts == 3
            # Chaos cleared: the same move now completes.
            inj.clear()
            assert a.membership.migrate_ranges(m.host("a").ranges, "b",
                                               wait=2.0)
            assert b.core.map.epoch == 2
        finally:
            chaos_injector.uninstall()
            a.close()
            b.close()

    def test_migration_stall_keeps_old_owner_serving(self, tmp_path):
        clock = ManualClock(1000.0)
        m, hosts = _make_fleet(tmp_path, ["a", "b"], clock)
        a, b = hosts["a"], hosts["b"]
        inj = chaos_injector.install(seed=3)
        chaos_injector.scenario("migration-stall", inj, seconds=0.3)
        try:
            hot = _owned_key(a.core, 0)
            done = threading.Event()

            def move():
                a.membership.migrate_ranges(m.host("a").ranges, "b",
                                            wait=5.0)
                done.set()

            t = threading.Thread(target=move, daemon=True)
            t0 = time.monotonic()
            t.start()
            # During the stall the OLD owner still answers (epoch 1).
            time.sleep(0.1)
            assert a.core.map.epoch == 1
            assert a.fwd.allow_n(hot, 1).allowed
            assert done.wait(10.0)
            assert time.monotonic() - t0 >= 0.3
            assert inj.handoff_stalls == 1
            assert b.core.map.epoch == 2
        finally:
            chaos_injector.uninstall()
            a.close()
            b.close()

    def test_scenario_vocabulary_and_seeded_determinism(self):
        inj = chaos_injector.ChaosInjector(seed=9)
        for name in ("migration-stall", "kill-during-handoff",
                     "rejoin-storm"):
            chaos_injector.scenario(name, inj)
        with pytest.raises(ValueError):
            chaos_injector.scenario("no-such-scenario", inj)
        # rejoin-storm = seeded announce dropping: two injectors with
        # the same seed drop the SAME frame pattern (replay pin).
        frames = [bytes([13] * 20 + [i]) for i in range(64)]
        patterns = []
        for _ in range(2):
            x = chaos_injector.ChaosInjector(seed=21)
            chaos_injector.scenario("rejoin-storm", x)
            patterns.append([x.dcn_frame(f) is None for f in frames])
            assert any(patterns[-1]) and not all(patterns[-1])
        assert patterns[0] == patterns[1]


def _fleet_config(tmp_path, pa, pb, snap_a, snap_b):
    d = {"buckets": 32, "epoch": 1, "hosts": [
        {"id": "a", "host": "127.0.0.1", "port": pa,
         "ranges": [[0, 16]], "successor": "b", "snapshot_dir": snap_a},
        {"id": "b", "host": "127.0.0.1", "port": pb,
         "ranges": [[16, 32]], "successor": "a", "snapshot_dir": snap_b},
    ]}
    path = str(tmp_path / "fleet.json")
    with open(path, "w", encoding="utf-8") as f:
        json.dump(d, f)
    return path, d


def _spawn_member(port, cfgpath, self_id, snap, extra=()):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # The suite's kill -9 tests can tear entries in the SHARED
    # persistent jit cache, and a handoff compiles new shapes
    # mid-serving — concurrent/torn cache reads abort XLA-CPU
    # (observed SIGSEGV/SIGABRT ~10%). Fleet members here compile
    # privately instead.
    env["RATELIMITER_TPU_COMPILE_CACHE"] = ""
    env["PYTHONPATH"] = os.pathsep.join(
        [REPO] + env.get("PYTHONPATH", "").split(os.pathsep))
    argv = [sys.executable, "-m", "ratelimiter_tpu.serving",
            "--backend", "sketch", "--limit", "100", "--window", "600",
            "--sketch-width", "8192", "--sub-windows", "6",
            "--port", str(port), "--no-prewarm", "--inflight", "8",
            "--fleet-config", cfgpath, "--fleet-self", self_id,
            "--fleet-forward-deadline", "60",
            "--fleet-heartbeat", "0.3", "--fleet-dead-after", "1.5",
            "--snapshot-dir", snap, "--snapshot-interval", "500",
            *extra]
    return subprocess.Popen(argv, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)


def _wait_banner(proc, timeout=180):
    t0 = time.time()
    lines = []
    while time.time() - t0 < timeout:
        line = proc.stdout.readline()
        if not line:
            break
        lines.append(line)
        if line.startswith("serving"):
            return lines
    raise AssertionError("member never served:\n" + "".join(lines))


@pytest.mark.slow
class TestRollingRestartProcesses:
    def test_rolling_restart_zero_client_errors_and_rejoin(self,
                                                           tmp_path):
        """The satellite-4 drain contract over real processes: SIGTERM
        one member of a 2-host fleet under live FleetClient traffic
        with a deep --inflight window. The departure announce moves
        ownership BEFORE the socket closes, every outstanding request
        resolves, the member exits 0, no client request errors; the
        restarted member then gets its ranges back (auto rejoin)."""
        from ratelimiter_tpu.serving.client import FleetClient

        pa, pb = free_port(), free_port()
        snap_a = str(tmp_path / "sa")
        snap_b = str(tmp_path / "sb")
        cfgpath, fleet_d = _fleet_config(tmp_path, pa, pb, snap_a,
                                         snap_b)
        a = _spawn_member(pa, cfgpath, "a", snap_a)
        b = _spawn_member(pb, cfgpath, "b", snap_b)
        procs = [a, b]
        try:
            _wait_banner(a)
            _wait_banner(b)
            fc = FleetClient(fleet_d, call_timeout=120)
            errors = []
            counts = {"n": 0}
            stop = threading.Event()
            keys = [f"roll:{i}" for i in range(512)]

            def drive():
                i = 0
                while not stop.is_set():
                    frame = [keys[(i * 7 + j) % 512] for j in range(64)]
                    i += 1
                    try:
                        fc.allow_batch(frame)
                        counts["n"] += 64
                    except Exception as exc:  # noqa: BLE001 — counted
                        errors.append(repr(exc))

            t = threading.Thread(target=drive, daemon=True)
            t.start()
            time.sleep(2.0)
            # ---- rolling restart of member a
            a.send_signal(signal.SIGTERM)
            assert a.wait(timeout=120) == 0, "member a exited non-zero"
            time.sleep(1.0)
            served_during = counts["n"]
            assert served_during > 0
            # b owns everything after the departure announce.
            from ratelimiter_tpu.serving.client import Client

            with Client(port=pb, timeout=120) as cb:
                m_now = FleetMap.from_dict(cb.fleet_map())
            assert m_now.epoch >= 2
            assert m_now.owned_buckets("b") == 32, m_now.to_dict()
            # ---- member a returns; auto rejoin hands its ranges back
            a = _spawn_member(pa, cfgpath, "a", snap_a)
            procs[0] = a
            _wait_banner(a)
            deadline = time.time() + 60
            got_back = False
            while time.time() < deadline:
                with Client(port=pb, timeout=120) as cb:
                    m_now = FleetMap.from_dict(cb.fleet_map())
                if m_now.host("a").ranges:
                    got_back = True
                    break
                time.sleep(0.3)
            assert got_back, "rejoin never handed the ranges back"
            time.sleep(1.5)
            stop.set()
            t.join(timeout=30)
            fc.close()
            assert not errors, (
                f"{len(errors)} client error(s) during the rolling "
                f"restart; first: {errors[0]}")
            assert counts["n"] > served_during, \
                "no traffic served after the restart"
        finally:
            stop.set()
            for pr in procs:
                if pr.poll() is None:
                    pr.terminate()
            for pr in procs:
                try:
                    pr.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    pr.kill()
