"""HTTP interop gateway (serving/http_gateway.py): the reference's
flagship example surface — 429 + X-RateLimit-* headers, 503 on backend
failure, /healthz, /metrics — plus the server-binary integration
(VERDICT r3 item 6; reference docs/EXAMPLES.md:44-57)."""

from __future__ import annotations

import json
import urllib.error
import urllib.request

import pytest

from netutil import free_port

from ratelimiter_tpu import (
    Algorithm,
    Config,
    ManualClock,
    create_limiter,
)
from ratelimiter_tpu.observability import MetricsDecorator, Registry
from ratelimiter_tpu.serving.http_gateway import HttpGateway, gateway_for_limiter

T0 = 1_700_000_000.0


@pytest.fixture()
def gw():
    clock = ManualClock(T0)
    cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=3, window=60.0,
                 fail_open=False)
    lim = create_limiter(cfg, backend="exact", clock=clock)
    reg = Registry()
    lim = MetricsDecorator(lim, registry=reg)
    gateway = HttpGateway(lambda k, n: lim.allow_n(k, n), lim.reset,
                          metrics_render=reg.render)
    gateway.start()
    yield gateway, lim, clock
    gateway.shutdown()
    lim.close()


def _get(url):
    with urllib.request.urlopen(url) as r:
        return r.status, dict(r.headers), json.loads(r.read())


class TestHttpGateway:
    def test_allow_with_headers_then_429(self, gw):
        gateway, _, _ = gw
        base = f"http://127.0.0.1:{gateway.port}"
        for i in range(3):
            status, headers, body = _get(f"{base}/v1/allow?key=u1")
            assert status == 200 and body["allowed"]
            assert headers["X-RateLimit-Limit"] == "3"
            assert headers["X-RateLimit-Remaining"] == str(2 - i)
            assert int(headers["X-RateLimit-Reset"]) >= int(T0)
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(f"{base}/v1/allow?key=u1")
        e = ei.value
        assert e.code == 429
        assert e.headers["X-RateLimit-Remaining"] == "0"
        assert int(e.headers["Retry-After"]) >= 1
        body = json.loads(e.read())
        assert body["allowed"] is False and body["retry_after"] > 0

    def test_allow_n_and_header_key(self, gw):
        gateway, _, _ = gw
        base = f"http://127.0.0.1:{gateway.port}"
        status, headers, _ = _get(f"{base}/v1/allow?key=u2&n=3")
        assert status == 200 and headers["X-RateLimit-Remaining"] == "0"
        req = urllib.request.Request(f"{base}/v1/allow",
                                     headers={"X-User-ID": "u3"})
        with urllib.request.urlopen(req) as r:
            assert r.status == 200

    def test_reset_roundtrip(self, gw):
        gateway, _, _ = gw
        base = f"http://127.0.0.1:{gateway.port}"
        _get(f"{base}/v1/allow?key=u4&n=3")
        urllib.request.urlopen(urllib.request.Request(
            f"{base}/v1/reset?key=u4", method="POST"))
        status, _, body = _get(f"{base}/v1/allow?key=u4")
        assert status == 200 and body["allowed"]

    def test_validation_errors_are_400(self, gw):
        gateway, _, _ = gw
        base = f"http://127.0.0.1:{gateway.port}"
        for url in (f"{base}/v1/allow",                # no key anywhere
                    f"{base}/v1/allow?key=u5&n=0",     # bad n
                    f"{base}/v1/allow?key=u5&n=abc"):  # unparsable n
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(url)
            assert ei.value.code == 400

    def test_backend_failure_is_503(self, gw):
        gateway, lim, _ = gw
        inner = lim.inner
        inner.inject_failure()
        base = f"http://127.0.0.1:{gateway.port}"
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(f"{base}/v1/allow?key=u6")
        assert ei.value.code == 503
        inner.heal()
        status, _, _ = _get(f"{base}/v1/allow?key=u6")
        assert status == 200

    def test_healthz_metrics_and_404(self, gw):
        gateway, _, _ = gw
        base = f"http://127.0.0.1:{gateway.port}"
        status, _, body = _get(f"{base}/healthz")
        assert status == 200 and body["serving"]
        _get(f"{base}/v1/allow?key=u7")
        with urllib.request.urlopen(f"{base}/metrics") as r:
            assert "rate_limiter" in r.read().decode()
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(f"{base}/nope")
        assert ei.value.code == 404

    def test_reset_disabled_is_403(self):
        """ADVICE r4: /v1/reset is a quota-erase lever on a curl-able
        surface; a gateway built with enable_reset=False refuses it."""
        cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=2, window=60.0)
        lim = create_limiter(cfg, backend="exact", clock=ManualClock(T0))
        gw = HttpGateway(lambda k, n: lim.allow_n(k, n), lim.reset,
                         enable_reset=False)
        gw.start()
        try:
            base = f"http://127.0.0.1:{gw.port}"
            _get(f"{base}/v1/allow?key=g&n=2")
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(urllib.request.Request(
                    f"{base}/v1/reset?key=g", method="POST"))
            assert ei.value.code == 403
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(f"{base}/v1/allow?key=g")   # quota NOT erased
            assert ei.value.code == 429
        finally:
            gw.shutdown()
            lim.close()

    def test_reset_token_gating(self):
        cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=2, window=60.0)
        lim = create_limiter(cfg, backend="exact", clock=ManualClock(T0))
        gw = HttpGateway(lambda k, n: lim.allow_n(k, n), lim.reset,
                         reset_token="tok123")
        gw.start()
        try:
            base = f"http://127.0.0.1:{gw.port}"
            _get(f"{base}/v1/allow?key=g&n=2")
            # No token / wrong token -> 403, quota intact.
            for hdrs in ({}, {"Authorization": "Bearer nope"}):
                with pytest.raises(urllib.error.HTTPError) as ei:
                    urllib.request.urlopen(urllib.request.Request(
                        f"{base}/v1/reset?key=g", method="POST",
                        headers=hdrs))
                assert ei.value.code == 403
            # Bearer header works.
            urllib.request.urlopen(urllib.request.Request(
                f"{base}/v1/reset?key=g", method="POST",
                headers={"Authorization": "Bearer tok123"}))
            _get(f"{base}/v1/allow?key=g&n=2")
            # Regression: a ?token= query parameter must NOT authorize —
            # query strings land in access logs, proxies, and Referer
            # headers (tokens are header-only now).
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(urllib.request.Request(
                    f"{base}/v1/reset?key=g&token=tok123", method="POST"))
            assert ei.value.code == 403
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(f"{base}/v1/allow?key=g")   # quota intact
            assert ei.value.code == 429
        finally:
            gw.shutdown()
            lim.close()

    def test_policy_endpoint_disabled_by_default(self):
        cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=2, window=60.0)
        lim = create_limiter(cfg, backend="exact", clock=ManualClock(T0))
        gw = gateway_for_limiter(lim)   # no enable_policy
        gw.start()
        try:
            base = f"http://127.0.0.1:{gw.port}"
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(urllib.request.Request(
                    f"{base}/v1/policy?key=k&limit=9", method="POST"))
            assert ei.value.code == 403
        finally:
            gw.shutdown()
            lim.close()

    def test_policy_endpoint_crud_and_token_gating(self):
        cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=2, window=60.0)
        lim = create_limiter(cfg, backend="exact", clock=ManualClock(T0))
        gw = gateway_for_limiter(lim, enable_policy=True, policy_token="pt")
        gw.start()

        def req(method, path, token=None):
            return urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{gw.port}{path}", method=method,
                headers=({"Authorization": f"Bearer {token}"}
                         if token else {})))

        try:
            # No token / query token -> 403 (header-only, like reset).
            for path in ("/v1/policy?key=v&limit=9",
                         "/v1/policy?key=v&limit=9&token=pt"):
                with pytest.raises(urllib.error.HTTPError) as ei:
                    req("POST", path)
                assert ei.value.code == 403
            with req("POST", "/v1/policy?key=v&limit=9", token="pt") as r:
                body = json.loads(r.read())
                assert body["limit"] == 9 and body["window_scale"] == 1.0
            with req("GET", "/v1/policy?key=v", token="pt") as r:
                assert json.loads(r.read())["limit"] == 9
            # The override changes live decisions + headers.
            status, headers, body = _get(
                f"http://127.0.0.1:{gw.port}/v1/allow?key=v")
            assert status == 200 and headers["X-RateLimit-Limit"] == "9"
            # Invalid override -> 400, not 500.
            with pytest.raises(urllib.error.HTTPError) as ei:
                req("POST", "/v1/policy?key=v&limit=-3", token="pt")
            assert ei.value.code == 400
            with req("DELETE", "/v1/policy?key=v", token="pt") as r:
                assert json.loads(r.read())["deleted"] is True
            with pytest.raises(urllib.error.HTTPError) as ei:
                req("GET", "/v1/policy?key=v", token="pt")
            assert ei.value.code == 404
        finally:
            gw.shutdown()
            lim.close()

    def test_gateway_for_limiter_convenience(self):
        cfg = Config(algorithm=Algorithm.FIXED_WINDOW, limit=2, window=60.0)
        lim = create_limiter(cfg, backend="exact", clock=ManualClock(T0))
        gw = gateway_for_limiter(lim)
        gw.start()
        try:
            base = f"http://127.0.0.1:{gw.port}"
            assert _get(f"{base}/v1/allow?key=k")[0] == 200
        finally:
            gw.shutdown()
            lim.close()


class TestServerBinaryHttp:
    def test_http_alongside_binary_protocol(self):
        """--http-port on the real binary: both protocols serve the SAME
        limiter (quota consumed over HTTP is gone over the binary
        protocol too)."""
        import os
        import signal as sig
        import socket
        import subprocess
        import sys

        from ratelimiter_tpu.serving import Client

        env = dict(os.environ)
        env["PYTHONPATH"] = os.pathsep.join(
            [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
            + env.get("PYTHONPATH", "").split(os.pathsep))


        port, http_port = free_port(), free_port()
        proc = subprocess.Popen(
            [sys.executable, "-m", "ratelimiter_tpu.serving",
             "--backend", "exact", "--algorithm", "sliding_window",
             "--limit", "2", "--window", "60", "--port", str(port),
             "--http-port", str(http_port)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        try:
            # Skip log lines (e.g. "listening on ...") until the banner.
            for _ in range(10):
                line = proc.stdout.readline()
                if line.startswith("serving"):
                    break
            assert "http:" in line, line
            base = f"http://127.0.0.1:{http_port}"
            status, _, _ = _get(f"{base}/v1/allow?key=shared")
            assert status == 200
            with Client(port=port, timeout=10.0) as c:
                assert c.allow("shared").allowed     # 2 of 2 used now
                assert not c.allow("shared").allowed
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(f"{base}/v1/allow?key=shared")
            assert ei.value.code == 429
            proc.send_signal(sig.SIGTERM)
            assert proc.wait(timeout=15) == 0
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait()

    def test_policy_override_against_running_binary(self):
        """The tentpole acceptance shape end to end: a per-key override
        set over HTTP against the real binary (sketch backend) changes
        THAT key's admission decisions while other keys stay on the
        default limit; occupancy shows up on /healthz and /metrics."""
        import os
        import signal as sig
        import subprocess
        import sys

        env = dict(os.environ)
        env["PYTHONPATH"] = os.pathsep.join(
            [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
            + env.get("PYTHONPATH", "").split(os.pathsep))
        port, http_port = free_port(), free_port()
        proc = subprocess.Popen(
            [sys.executable, "-m", "ratelimiter_tpu.serving",
             "--backend", "sketch", "--algorithm", "tpu_sketch",
             "--limit", "3", "--window", "60", "--port", str(port),
             "--http-port", str(http_port), "--max-batch", "64",
             "--http-policy-token", "pt", "--no-prewarm"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        try:
            for _ in range(10):
                line = proc.stdout.readline()
                if line.startswith("serving"):
                    break
            assert "http:" in line, line
            base = f"http://127.0.0.1:{http_port}"
            urllib.request.urlopen(urllib.request.Request(
                f"{base}/v1/policy?key=vip&limit=7", method="POST",
                headers={"Authorization": "Bearer pt"}))
            vip = [_get(f"{base}/v1/allow?key=vip") for _ in range(7)]
            assert all(s == 200 for s, _, _ in vip)
            assert vip[0][1]["X-RateLimit-Limit"] == "7"
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(f"{base}/v1/allow?key=vip")       # 8th denied
            assert ei.value.code == 429
            # Default keys stay at limit 3.
            std = [_get(f"{base}/v1/allow?key=std") for _ in range(3)]
            assert all(s == 200 for s, _, _ in std)
            assert std[0][1]["X-RateLimit-Limit"] == "3"
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(f"{base}/v1/allow?key=std")
            assert ei.value.code == 429
            # Observability: occupancy on /healthz and /metrics.
            status, _, health = _get(f"{base}/healthz")
            assert status == 200 and health["policy_overrides"] == 1
            with urllib.request.urlopen(f"{base}/metrics") as r:
                assert "rate_limiter_policy_overrides 1" in r.read().decode()
            proc.send_signal(sig.SIGTERM)
            assert proc.wait(timeout=15) == 0
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait()


class TestSnapshotEndpoint:
    """POST /v1/snapshot: the durability trigger — wired only when the
    embedding runs persistence, bearer-gated header-only like reset."""

    def _gw(self, snapshot=None, token=None):
        clock = ManualClock(T0)
        cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=3,
                     window=60.0)
        lim = create_limiter(cfg, backend="exact", clock=clock)
        gateway = HttpGateway(lambda k, n: lim.allow_n(k, n), lim.reset,
                              snapshot=snapshot, snapshot_token=token)
        gateway.start()
        return gateway, lim

    def _post(self, url, headers=None):
        req = urllib.request.Request(url, method="POST",
                                     headers=headers or {})
        with urllib.request.urlopen(req) as r:
            return r.status, json.loads(r.read())

    def test_unwired_gateway_answers_403(self):
        gateway, lim = self._gw()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                self._post(f"http://127.0.0.1:{gateway.port}/v1/snapshot")
            assert ei.value.code == 403
            assert "not enabled" in json.loads(ei.value.read())["error"]
        finally:
            gateway.shutdown()
            lim.close()

    def test_trigger_and_token_gate(self):
        calls = []

        def snapshot():
            calls.append(1)
            return {"id": 3, "wal_seq": 17, "duration_s": 0.01}

        gateway, lim = self._gw(snapshot=snapshot, token="st")
        base = f"http://127.0.0.1:{gateway.port}"
        try:
            # No token / wrong token / query-string token: all 403, the
            # trigger never fires.
            for hdrs in ({}, {"Authorization": "Bearer nope"}):
                with pytest.raises(urllib.error.HTTPError) as ei:
                    self._post(f"{base}/v1/snapshot", hdrs)
                assert ei.value.code == 403
            with pytest.raises(urllib.error.HTTPError):
                self._post(f"{base}/v1/snapshot?token=st")
            assert calls == []
            status, body = self._post(
                f"{base}/v1/snapshot",
                {"Authorization": "Bearer st"})
            assert status == 200 and body["ok"] is True
            assert body["snapshot_id"] == 3 and body["wal_seq"] == 17
            assert calls == [1]
            # GET is not a trigger (POST only).
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(f"{base}/v1/snapshot")
            assert ei.value.code == 404
        finally:
            gateway.shutdown()
            lim.close()
