"""Config validation / defaults / key formatting.

Mirrors reference ``config_test.go`` (397 LoC of tables — SURVEY.md §4.1) in
pytest-parametrized form, plus pins for this repo's deliberate divergences.
"""

import dataclasses

import pytest

from ratelimiter_tpu import (
    Algorithm,
    Config,
    DEFAULT_PREFIX,
    InvalidConfigError,
    SketchParams,
)
from ratelimiter_tpu.core.config import MAX_WINDOW_SECONDS


def cfg(**kw):
    base = dict(algorithm=Algorithm.FIXED_WINDOW, limit=100, window=60.0)
    base.update(kw)
    return Config(**base)


class TestValidate:
    def test_valid(self):
        cfg().validate()

    @pytest.mark.parametrize("limit", [0, -1, -100])
    def test_nonpositive_limit(self, limit):
        with pytest.raises(InvalidConfigError, match="limit"):
            cfg(limit=limit).validate()

    @pytest.mark.parametrize("limit", [1.5, "10", None, True])
    def test_non_integer_limit(self, limit):
        with pytest.raises(InvalidConfigError, match="limit"):
            cfg(limit=limit).validate()

    def test_window_too_small(self):
        # Reference bound: >= 1ms (config.go:31-47)
        with pytest.raises(InvalidConfigError, match="1ms"):
            cfg(window=0.0005).validate()
        cfg(window=0.001).validate()

    def test_window_too_large(self):
        # Reference bound: <= 365 days
        with pytest.raises(InvalidConfigError, match="365"):
            cfg(window=MAX_WINDOW_SECONDS + 1).validate()
        cfg(window=MAX_WINDOW_SECONDS).validate()

    def test_invalid_algorithm(self):
        with pytest.raises(InvalidConfigError, match="algorithm"):
            cfg(algorithm="token_bucket").validate()  # must be the enum

    @pytest.mark.parametrize("algo", list(Algorithm))
    def test_all_algorithms_valid(self, algo):
        cfg(algorithm=algo).validate()

    def test_sketch_width_power_of_two(self):
        with pytest.raises(InvalidConfigError, match="power of two"):
            cfg(sketch=SketchParams(width=1000)).validate()

    def test_sketch_depth_bounds(self):
        with pytest.raises(InvalidConfigError, match="depth"):
            cfg(sketch=SketchParams(depth=0)).validate()


class TestDefaults:
    def test_default_prefix_applied(self):
        c = cfg().with_defaults()
        assert c.key_prefix == DEFAULT_PREFIX

    def test_with_defaults_non_mutating(self):
        # Reference WithDefaults returns a copy (config.go:54-67)
        c = cfg()
        c2 = c.with_defaults()
        assert c.key_prefix is None and c2.key_prefix == DEFAULT_PREFIX

    def test_explicit_prefix_kept(self):
        c = cfg(key_prefix="myapp").with_defaults()
        assert c.key_prefix == "myapp"

    def test_empty_prefix_reachable(self):
        """Deliberate divergence (SURVEY.md §2.4.8): in the reference, empty
        prefix is documented but unreachable (WithDefaults re-instates the
        default). Here "" survives defaulting and means no prefix."""
        c = cfg(key_prefix="").with_defaults()
        assert c.key_prefix == ""
        assert c.format_key("user:1") == "user:1"

    def test_frozen(self):
        with pytest.raises(dataclasses.FrozenInstanceError):
            cfg().limit = 5  # type: ignore[misc]


class TestFormatKey:
    def test_default(self):
        assert cfg().format_key("user:1") == "ratelimit:user:1"

    def test_custom_prefix(self):
        assert cfg(key_prefix="app").format_key("k") == "app:k"

    def test_window_suffix(self):
        # FW/SW key schema: prefix:key:windowStart (fixedwindow.go:139-141)
        assert cfg().format_key("k", 1700000000) == "ratelimit:k:1700000000"

    def test_refill_rate(self):
        # rate = limit / window (tokenbucket.go:155-157)
        assert cfg(limit=120, window=60.0).refill_rate == pytest.approx(2.0)
