"""Every example script runs clean (the reference ships an empty
examples/ placeholder; ours are executable and CI-gated)."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = sorted(
    f for f in os.listdir(os.path.join(REPO, "examples"))
    if f.endswith(".py"))


@pytest.mark.parametrize("script", EXAMPLES)
def test_example_runs(script):
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [REPO] + env.get("PYTHONPATH", "").split(os.pathsep))
    # Examples inherit the test env's CPU/8-device setup (conftest.py).
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", script)],
        env=env, capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "OK" in out.stdout or "SKIP" in out.stdout, out.stdout
