"""Every example script runs clean (the reference ships an empty
examples/ placeholder; ours are executable and CI-gated)."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
#: Examples that spawn server subprocesses (each pays a full JAX boot
#: per process) — slow lane to respect the 870 s tier-1 budget; their
#: CI lanes run them explicitly (ci.yml: 11/12 ride the mesh lane, 15
#: the fleet lane, 16 the resharding lane, 18 the fleet-observability
#: lane).
SLOW_EXAMPLES = {"11_mesh_serving.py", "12_mixed_traffic.py",
                 "13_tracing.py", "14_accuracy_observatory.py",
                 "15_fleet.py", "16_elastic.py",
                 "18_control_tower.py"}
EXAMPLES = sorted(
    f for f in os.listdir(os.path.join(REPO, "examples"))
    if f.endswith(".py"))


@pytest.mark.parametrize(
    "script",
    [pytest.param(f, marks=[pytest.mark.slow] if f in SLOW_EXAMPLES
                  else []) for f in EXAMPLES])
def test_example_runs(script):
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [REPO] + env.get("PYTHONPATH", "").split(os.pathsep))
    # Examples inherit the test env's CPU/8-device setup (conftest.py).
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", script)],
        env=env, capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "OK" in out.stdout or "SKIP" in out.stdout, out.stdout
