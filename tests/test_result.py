"""Result constructors (reference ``result_test.go`` — but here the
constructors are live code used by every backend, not dead scaffolding)."""

import numpy as np

from ratelimiter_tpu.core.types import (
    BatchResult,
    allowed_result,
    batch_fail_open,
    denied_result,
    fail_open_result,
)


def test_allowed():
    r = allowed_result(limit=100, remaining=42, reset_at=123.0)
    assert r.allowed and r.limit == 100 and r.remaining == 42
    assert r.retry_after == 0.0 and r.reset_at == 123.0 and not r.fail_open


def test_allowed_clamps_remaining():
    assert allowed_result(10, -3, 0.0).remaining == 0


def test_denied():
    r = denied_result(limit=10, remaining=0, retry_after=5.5, reset_at=99.0)
    assert not r.allowed and r.retry_after == 5.5


def test_denied_clamps():
    r = denied_result(10, -1, -2.0, 0.0)
    assert r.remaining == 0 and r.retry_after == 0.0


def test_fail_open():
    r = fail_open_result(limit=7, reset_at=50.0)
    assert r.allowed and r.fail_open and r.remaining == 0


def test_batch_result_scalarizes():
    b = BatchResult(
        allowed=np.array([True, False]),
        limit=5,
        remaining=np.array([4, 0]),
        retry_after=np.array([0.0, 3.0]),
        reset_at=np.array([10.0, 10.0]),
    )
    assert len(b) == 2 and b.allow_count == 1
    r1 = b.result(1)
    assert not r1.allowed and r1.retry_after == 3.0 and r1.limit == 5
    assert [r.allowed for r in b.results()] == [True, False]


def test_batch_fail_open():
    b = batch_fail_open(3, limit=9, reset_at=1.0)
    assert b.fail_open and b.allow_count == 3
    assert b.result(0).fail_open
