"""gRPC adapter (serving/grpc_server.py) for the checked-in proto
contract (api/proto/ratelimiter.proto) — the reference's planned L5
surface (its ``docs/ARCHITECTURE.md`` gRPC service). Skips when the
optional grpcio runtime (or protoc) is absent."""

from __future__ import annotations

import pytest

from netutil import free_port

grpc = pytest.importorskip("grpc")

from ratelimiter_tpu import (  # noqa: E402
    Algorithm,
    Config,
    ManualClock,
    create_limiter,
)
from ratelimiter_tpu.core.types import Result  # noqa: E402
from ratelimiter_tpu.serving.grpc_server import (  # noqa: E402
    GrpcRateLimitServer,
    _load_pb2,
    grpc_available,
    grpc_server_for_limiter,
)

if not grpc_available():  # pragma: no cover - env without protoc
    pytest.skip("protoc or grpcio unusable here", allow_module_level=True)

T0 = 1_700_000_000.0


@pytest.fixture()
def pb2():
    return _load_pb2()


@pytest.fixture()
def served():
    clock = ManualClock(T0)
    cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=3, window=60.0)
    lim = create_limiter(cfg, backend="exact", clock=clock)
    srv = grpc_server_for_limiter(lim)
    srv.start()
    channel = grpc.insecure_channel(f"127.0.0.1:{srv.port}")
    yield channel, lim, clock
    channel.close()
    srv.shutdown()
    lim.close()


def _stub(channel, pb2):
    """Hand-rolled method callables (no grpc_tools-generated stub)."""
    base = "/ratelimiter.v1.RateLimiter/"

    def method(name, req_cls, resp_cls):
        return channel.unary_unary(
            base + name, request_serializer=req_cls.SerializeToString,
            response_deserializer=resp_cls.FromString)

    class Stub:
        Allow = method("Allow", pb2.AllowRequest, pb2.AllowResponse)
        AllowN = method("AllowN", pb2.AllowNRequest, pb2.AllowResponse)
        AllowBatch = method("AllowBatch", pb2.AllowBatchRequest,
                            pb2.AllowBatchResponse)
        Reset = method("Reset", pb2.ResetRequest, pb2.ResetResponse)
        Health = method("Health", pb2.HealthRequest, pb2.HealthResponse)
        SetOverride = method("SetOverride", pb2.SetOverrideRequest,
                             pb2.OverrideResponse)
        GetOverride = method("GetOverride", pb2.GetOverrideRequest,
                             pb2.OverrideResponse)
        DeleteOverride = method("DeleteOverride", pb2.DeleteOverrideRequest,
                                pb2.DeleteOverrideResponse)
        SetTenant = method("SetTenant", pb2.SetTenantRequest,
                           pb2.TenantResponse)
        GetTenant = method("GetTenant", pb2.GetTenantRequest,
                           pb2.TenantResponse)
        DeleteTenant = method("DeleteTenant", pb2.DeleteTenantRequest,
                              pb2.DeleteTenantResponse)
        AssignTenant = method("AssignTenant", pb2.AssignTenantRequest,
                              pb2.AssignTenantResponse)
        UnassignTenant = method("UnassignTenant", pb2.UnassignTenantRequest,
                                pb2.UnassignTenantResponse)

    return Stub


class TestGrpcServer:
    def test_allow_deny_reset_roundtrip(self, served, pb2):
        channel, _, _ = served
        stub = _stub(channel, pb2)
        for i in range(3):
            resp = stub.Allow(pb2.AllowRequest(key="u1"))
            assert resp.allowed and resp.remaining == 2 - i
            assert resp.limit == 3
        resp = stub.Allow(pb2.AllowRequest(key="u1"))
        assert not resp.allowed and resp.retry_after > 0
        assert resp.reset_at > T0
        stub.Reset(pb2.ResetRequest(key="u1"))
        assert stub.Allow(pb2.AllowRequest(key="u1")).allowed

    def test_allow_n_all_or_nothing(self, served, pb2):
        channel, _, _ = served
        stub = _stub(channel, pb2)
        assert stub.AllowN(pb2.AllowNRequest(key="u2", n=3)).allowed
        resp = stub.AllowN(pb2.AllowNRequest(key="u2", n=2))
        assert not resp.allowed and resp.remaining == 0  # denial consumed 0

    def test_allow_batch_in_order_with_sequencing(self, served, pb2):
        channel, _, _ = served
        stub = _stub(channel, pb2)
        req = pb2.AllowBatchRequest(items=[
            pb2.AllowBatchRequest.Item(key="b1", n=2),
            pb2.AllowBatchRequest.Item(key="b2", n=1),
            pb2.AllowBatchRequest.Item(key="b1", n=1),
            pb2.AllowBatchRequest.Item(key="b1", n=1),   # 4th unit: denied
        ])
        out = stub.AllowBatch(req)
        assert [r.allowed for r in out.results] == [True, True, True, False]

    def test_health(self, served, pb2):
        channel, _, _ = served
        stub = _stub(channel, pb2)
        stub.Allow(pb2.AllowRequest(key="h"))
        h = stub.Health(pb2.HealthRequest())
        assert h.serving and h.uptime_seconds >= 0

    def test_error_mapping_invalid_argument(self, served, pb2):
        channel, _, _ = served
        stub = _stub(channel, pb2)
        with pytest.raises(grpc.RpcError) as ei:
            stub.Allow(pb2.AllowRequest(key=""))
        assert ei.value.code() == grpc.StatusCode.INVALID_ARGUMENT
        with pytest.raises(grpc.RpcError) as ei:
            stub.AllowN(pb2.AllowNRequest(key="k", n=0))
        assert ei.value.code() == grpc.StatusCode.INVALID_ARGUMENT

    def test_error_mapping_unavailable_and_fail_open(self, pb2):
        clock = ManualClock(T0)
        cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=3,
                     window=60.0, fail_open=False)
        lim = create_limiter(cfg, backend="exact", clock=clock)
        srv = grpc_server_for_limiter(lim)
        srv.start()
        channel = grpc.insecure_channel(f"127.0.0.1:{srv.port}")
        stub = _stub(channel, pb2)
        try:
            lim.inject_failure()
            with pytest.raises(grpc.RpcError) as ei:
                stub.Allow(pb2.AllowRequest(key="k"))
            assert ei.value.code() == grpc.StatusCode.UNAVAILABLE
            lim.heal()
            assert stub.Allow(pb2.AllowRequest(key="k")).allowed
        finally:
            channel.close()
            srv.shutdown()
            lim.close()

    def test_fail_open_flag_carried(self, pb2):
        clock = ManualClock(T0)
        cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=3,
                     window=60.0, fail_open=True)
        lim = create_limiter(cfg, backend="exact", clock=clock)
        srv = grpc_server_for_limiter(lim)
        srv.start()
        channel = grpc.insecure_channel(f"127.0.0.1:{srv.port}")
        stub = _stub(channel, pb2)
        try:
            lim.inject_failure()
            resp = stub.Allow(pb2.AllowRequest(key="k"))
            assert resp.allowed and resp.fail_open
        finally:
            channel.close()
            srv.shutdown()
            lim.close()

    def test_allow_batch_single_bulk_submission(self, pb2):
        """Satellite pin: an N-item AllowBatch reaches the decide layer as
        ONE bulk submission (O(1) dispatches, not N sequential
        submit-wait round-trips), and results come back in request
        order."""
        calls = {"many": 0, "one": 0}

        def decide_many(pairs):
            calls["many"] += 1
            # Distinguishable per-item results to pin ordering.
            return [Result(allowed=(i % 2 == 0), limit=100, remaining=i,
                           retry_after=0.0, reset_at=T0)
                    for i, _ in enumerate(pairs)]

        def decide(key, n):
            calls["one"] += 1
            raise AssertionError("scalar path must not serve AllowBatch")

        srv = GrpcRateLimitServer(decide, lambda k: None,
                                  decide_many=decide_many)
        srv.start()
        channel = grpc.insecure_channel(f"127.0.0.1:{srv.port}")
        stub = _stub(channel, pb2)
        try:
            n_items = 64
            req = pb2.AllowBatchRequest(items=[
                pb2.AllowBatchRequest.Item(key=f"k{i}", n=1)
                for i in range(n_items)])
            out = stub.AllowBatch(req)
            assert calls == {"many": 1, "one": 0}
            assert [r.remaining for r in out.results] == list(range(n_items))
            assert [r.allowed for r in out.results] == [
                i % 2 == 0 for i in range(n_items)]
        finally:
            channel.close()
            srv.shutdown()

    def test_override_rpcs(self, pb2):
        """Set/Get/DeleteOverride change live decisions over gRPC."""
        clock = ManualClock(T0)
        cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=3, window=60.0)
        lim = create_limiter(cfg, backend="exact", clock=clock)
        srv = grpc_server_for_limiter(lim)
        srv.start()
        channel = grpc.insecure_channel(f"127.0.0.1:{srv.port}")
        stub = _stub(channel, pb2)
        try:
            resp = stub.GetOverride(pb2.GetOverrideRequest(key="vip"))
            assert not resp.found
            resp = stub.SetOverride(pb2.SetOverrideRequest(key="vip",
                                                           limit=7))
            assert resp.found and resp.limit == 7
            allowed = sum(stub.Allow(pb2.AllowRequest(key="vip")).allowed
                          for _ in range(9))
            assert allowed == 7
            assert stub.Allow(pb2.AllowRequest(key="std")).limit == 3
            resp = stub.GetOverride(pb2.GetOverrideRequest(key="vip"))
            assert resp.found and resp.limit == 7
            assert stub.DeleteOverride(
                pb2.DeleteOverrideRequest(key="vip")).deleted
            assert not stub.DeleteOverride(
                pb2.DeleteOverrideRequest(key="vip")).deleted
            with pytest.raises(grpc.RpcError) as ei:
                stub.SetOverride(pb2.SetOverrideRequest(key="v", limit=-4))
            assert ei.value.code() == grpc.StatusCode.INVALID_ARGUMENT
        finally:
            channel.close()
            srv.shutdown()
            lim.close()

    def test_policy_mutations_journaled(self, pb2):
        """The gRPC door records the same control-plane journal events
        as the HTTP/binary doors (ADR-021): set-override /
        delete-override / reset, actor="grpc", hashed key tokens only."""
        import json

        from ratelimiter_tpu.observability import events

        clock = ManualClock(T0)
        cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=3,
                     window=60.0)
        lim = create_limiter(cfg, backend="exact", clock=clock)
        srv = grpc_server_for_limiter(lim)
        srv.start()
        channel = grpc.insecure_channel(f"127.0.0.1:{srv.port}")
        stub = _stub(channel, pb2)
        events.enable(capacity=64)
        try:
            stub.SetOverride(pb2.SetOverrideRequest(key="vip", limit=7))
            stub.DeleteOverride(pb2.DeleteOverrideRequest(key="vip"))
            stub.DeleteOverride(pb2.DeleteOverrideRequest(key="vip"))
            stub.Reset(pb2.ResetRequest(key="vip"))
            evs = events.get().tail(category="policy")["events"]
            assert [(e["action"], e["actor"]) for e in evs] == [
                ("set-override", "grpc"),
                ("delete-override", "grpc"),
                ("delete-override", "grpc"),
                ("reset", "grpc"),
            ]
            set_ev = evs[0]
            assert set_ev["payload"]["limit"] == 7
            assert set_ev["payload"]["window_scale"] == 1.0
            assert evs[1]["payload"]["deleted"] is True
            assert evs[2]["payload"]["deleted"] is False
            # Same hashed token at every mutation site; raw key absent.
            tokens = {e["payload"]["key_hash"] for e in evs}
            assert len(tokens) == 1
            assert "vip" not in json.dumps(evs)
        finally:
            events.disable()
            channel.close()
            srv.shutdown()
            lim.close()

    def test_tenant_crud_and_journal(self, pb2):
        """Tenant CRUD over gRPC: the registry mutations work and land
        in the control-plane journal with actor="grpc" — the same
        vocabulary as the HTTP twin's /v1/tenants (ADR-021), so an
        incident reconstruction never depends on WHICH surface the
        operator used."""
        import json

        from ratelimiter_tpu import HierarchySpec, SketchParams
        from ratelimiter_tpu.observability import events

        clock = ManualClock(T0)
        cfg = Config(
            algorithm=Algorithm.SLIDING_WINDOW, limit=50, window=60.0,
            sketch=SketchParams(depth=2, width=512, sub_windows=4),
            hierarchy=HierarchySpec(tenants=4))
        lim = create_limiter(cfg, backend="sketch", clock=clock)
        srv = GrpcRateLimitServer(
            lambda key, n: lim.allow_n(key, n), lim.reset,
            tenants=lim)
        srv.start()
        channel = grpc.insecure_channel(f"127.0.0.1:{srv.port}")
        stub = _stub(channel, pb2)
        events.enable(capacity=64)
        try:
            out = stub.SetTenant(pb2.SetTenantRequest(
                name="gold", limit=30, weight=3))
            assert out.found and out.limit == 30 and out.weight == 3
            assert out.floor == 3  # default: ceiling / 10
            got = stub.GetTenant(pb2.GetTenantRequest(name="gold"))
            assert got.found and got.tid == out.tid
            miss = stub.GetTenant(pb2.GetTenantRequest(name="nope"))
            assert not miss.found
            stub.AssignTenant(pb2.AssignTenantRequest(
                key="acct:1", tenant="gold"))
            assert lim.tenant_of("acct:1") == "gold"
            un = stub.UnassignTenant(pb2.UnassignTenantRequest(
                key="acct:1"))
            assert un.unassigned
            dl = stub.DeleteTenant(pb2.DeleteTenantRequest(name="gold"))
            assert dl.deleted
            assert not stub.DeleteTenant(
                pb2.DeleteTenantRequest(name="gold")).deleted
            # Unknown tenant on assign -> INVALID_ARGUMENT (core error
            # taxonomy, same as every other surface).
            with pytest.raises(grpc.RpcError) as ei:
                stub.AssignTenant(pb2.AssignTenantRequest(
                    key="k", tenant="nope"))
            assert ei.value.code() == grpc.StatusCode.INVALID_ARGUMENT

            evs = events.get().tail(category="tenant")["events"]
            assert [(e["action"], e["actor"]) for e in evs] == [
                ("set", "grpc"),
                ("assign", "grpc"),
                ("unassign", "grpc"),
                ("delete", "grpc"),
                ("delete", "grpc"),
            ]
            assert evs[0]["payload"] == {"name": "gold", "limit": 30,
                                         "weight": 3, "floor": 3}
            assert evs[3]["payload"]["deleted"] is True
            assert evs[4]["payload"]["deleted"] is False
            # Keys ride as hashed tokens only (OPERATIONS §6).
            assert "acct:1" not in json.dumps(evs)
            assert evs[1]["payload"]["key_hash"] == \
                evs[2]["payload"]["key_hash"]
        finally:
            events.disable()
            channel.close()
            srv.shutdown()
            lim.close()

    def test_tenantless_server_unimplemented(self, served, pb2):
        """Without a hierarchy surface the tenant RPCs are absent —
        UNIMPLEMENTED, exactly like any unregistered method."""
        channel, _lim, _clock = served
        stub = _stub(channel, pb2)
        with pytest.raises(grpc.RpcError) as ei:
            stub.SetTenant(pb2.SetTenantRequest(name="gold", limit=1))
        assert ei.value.code() == grpc.StatusCode.UNIMPLEMENTED

    def test_closed_limiter_failed_precondition(self, pb2):
        cfg = Config(algorithm=Algorithm.FIXED_WINDOW, limit=3, window=60.0)
        lim = create_limiter(cfg, backend="exact", clock=ManualClock(T0))
        srv = grpc_server_for_limiter(lim)
        srv.start()
        channel = grpc.insecure_channel(f"127.0.0.1:{srv.port}")
        stub = _stub(channel, pb2)
        try:
            lim.close()
            with pytest.raises(grpc.RpcError) as ei:
                stub.Allow(pb2.AllowRequest(key="k"))
            assert ei.value.code() == grpc.StatusCode.FAILED_PRECONDITION
        finally:
            channel.close()
            srv.shutdown()


class TestGrpcOnServerBinary:
    def test_grpc_alongside_binary_protocol(self):
        """--grpc-port on the real binary: gRPC and binary-protocol
        traffic share ONE limiter (quota consumed over gRPC is gone over
        the binary protocol too)."""
        import os
        import signal as sig
        import socket
        import subprocess
        import sys

        from ratelimiter_tpu.serving import Client

        pb2 = _load_pb2()
        env = dict(os.environ)
        env["PYTHONPATH"] = os.pathsep.join(
            [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
            + env.get("PYTHONPATH", "").split(os.pathsep))


        port, grpc_port = free_port(), free_port()
        proc = subprocess.Popen(
            [sys.executable, "-m", "ratelimiter_tpu.serving",
             "--backend", "exact", "--algorithm", "sliding_window",
             "--limit", "2", "--window", "60", "--port", str(port),
             "--grpc-port", str(grpc_port)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        try:
            for _ in range(10):
                line = proc.stdout.readline()
                if line.startswith("serving"):
                    break
            assert "grpc:" in line, line
            channel = grpc.insecure_channel(f"127.0.0.1:{grpc_port}")
            stub = _stub(channel, pb2)
            assert stub.Allow(pb2.AllowRequest(key="shared")).allowed
            with Client(port=port, timeout=10.0) as c:
                assert c.allow("shared").allowed       # 2 of 2 used
                assert not c.allow("shared").allowed
            assert not stub.Allow(pb2.AllowRequest(key="shared")).allowed
            channel.close()
            proc.send_signal(sig.SIGTERM)
            assert proc.wait(timeout=15) == 0
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait()

    def test_grpc_on_native_sharded_door(self):
        """--native --shards 2 --grpc-port: gRPC decisions route through
        the same FNV shard router as binary traffic, so one key has ONE
        quota across both surfaces (the ADVICE r4 composition fix,
        exercised end to end on the real binary)."""
        import os
        import signal as sig
        import subprocess
        import sys

        from ratelimiter_tpu.serving import Client
        from ratelimiter_tpu.serving.native_server import (
            native_server_available,
        )

        if not native_server_available():
            pytest.skip("needs g++ for the native server")
        pb2 = _load_pb2()
        env = dict(os.environ)
        env["PYTHONPATH"] = os.pathsep.join(
            [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
            + env.get("PYTHONPATH", "").split(os.pathsep))
        env["JAX_PLATFORMS"] = "cpu"

        port, grpc_port = free_port(), free_port()
        proc = subprocess.Popen(
            [sys.executable, "-m", "ratelimiter_tpu.serving",
             "--backend", "sketch", "--algorithm", "sliding_window",
             "--limit", "4", "--window", "60",
             "--sketch-depth", "3", "--sketch-width", "256",
             "--no-prewarm", "--native", "--shards", "2",
             "--port", str(port), "--grpc-port", str(grpc_port)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        try:
            for _ in range(20):
                line = proc.stdout.readline()
                if line.startswith("serving"):
                    break
            assert "grpc:" in line, line
            channel = grpc.insecure_channel(f"127.0.0.1:{grpc_port}")
            stub = _stub(channel, pb2)
            with Client(port=port, timeout=30.0) as c:
                # Keys spanning both shards; half the quota per surface.
                for k in ("mix0", "mix1", "mix2", "mix3"):
                    assert c.allow_n(k, 2).allowed
                    assert stub.AllowN(
                        pb2.AllowNRequest(key=k, n=2)).allowed
                    assert not c.allow(k).allowed          # binary sees 4/4
                    assert not stub.Allow(
                        pb2.AllowRequest(key=k)).allowed   # so does gRPC
                    stub.Reset(pb2.ResetRequest(key=k))    # routed reset
                    assert c.allow(k).allowed
            channel.close()
            proc.send_signal(sig.SIGTERM)
            assert proc.wait(timeout=15) == 0
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait()
