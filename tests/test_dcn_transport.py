"""DCN exchange over a real transport: T_DCN_PUSH frames between
servers (VERDICT r3 item 5 — two OS processes exchanging history via the
serving protocol, converging within the documented staleness envelope)."""

from __future__ import annotations

import asyncio
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from netutil import free_port

from ratelimiter_tpu import (
    Algorithm,
    Config,
    ManualClock,
    SketchParams,
    create_limiter,
)
from ratelimiter_tpu.serving import Client, RateLimitServer
from ratelimiter_tpu.serving import protocol as p

T0 = 1_700_000_000.0


class TestDcnFrames:
    def test_slabs_roundtrip(self):
        periods = np.array([5, 9], dtype=np.int64)
        slabs = np.arange(2 * 3 * 16, dtype=np.int32).reshape(2, 3, 16)
        frame = p.encode_dcn_slabs(7, periods, slabs, 1_000_000)
        length, type_, rid = p.parse_header(frame[:p.HEADER_SIZE])
        assert type_ == p.T_DCN_PUSH and rid == 7
        kind, got_p, got_s = p.parse_dcn(frame[p.HEADER_SIZE:], 3, 16,
                                         1_000_000)
        assert kind == p.DCN_KIND_SLABS
        np.testing.assert_array_equal(got_p, periods)
        np.testing.assert_array_equal(got_s, slabs)

    def test_debt_roundtrip(self):
        delta = np.arange(3 * 16, dtype=np.int64).reshape(3, 16)
        frame = p.encode_dcn_debt(9, delta)
        kind, got, _ = p.parse_dcn(frame[p.HEADER_SIZE:], 3, 16, 0)
        assert kind == p.DCN_KIND_DEBT
        np.testing.assert_array_equal(got, delta)

    def test_geometry_mismatch_rejected(self):
        delta = np.zeros((3, 16), dtype=np.int64)
        frame = p.encode_dcn_debt(1, delta)
        with pytest.raises(p.ProtocolError, match="geometry"):
            p.parse_dcn(frame[p.HEADER_SIZE:], 4, 16, 0)

    def test_subwindow_mismatch_rejected(self):
        """Periods are denominated in sub_us units: a peer mid-window-
        migration (different sub_us) must be refused, not renumbered."""
        from ratelimiter_tpu import InvalidConfigError

        periods = np.array([5], dtype=np.int64)
        slabs = np.zeros((1, 3, 16), dtype=np.int32)
        frame = p.encode_dcn_slabs(1, periods, slabs, 1_000_000)
        with pytest.raises(InvalidConfigError, match="sub-window"):
            p.parse_dcn(frame[p.HEADER_SIZE:], 3, 16, 500_000)

    def test_dcn_frames_may_exceed_request_cap(self):
        # A d=4 w=65536 debt delta is 2 MiB > MAX_FRAME; the DCN type has
        # its own bound — but ONLY for servers that opted into DCN.
        delta = np.zeros((4, 65536), dtype=np.int64)
        frame = p.encode_dcn_debt(1, delta)
        length, type_, _ = p.parse_header(frame[:p.HEADER_SIZE],
                                          allow_dcn=True)
        assert length > p.MAX_FRAME and type_ == p.T_DCN_PUSH
        with pytest.raises(p.ProtocolError):
            p.parse_header(frame[:p.HEADER_SIZE])  # plain deployments


def _server_on_thread(limiter, dcn=True):
    """A live asyncio server on a background loop; returns (srv, loop)."""
    loop = asyncio.new_event_loop()
    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()
    srv = RateLimitServer(limiter, "127.0.0.1", 0, dcn=dcn)
    asyncio.run_coroutine_threadsafe(srv.start(), loop).result(10)
    return srv, loop, t


def _stop(srv, loop, t):
    asyncio.run_coroutine_threadsafe(srv.shutdown(), loop).result(10)
    loop.call_soon_threadsafe(loop.stop)
    t.join(timeout=10)
    loop.close()


class TestPushOverTcp:
    """Real protocol frames over TCP between two servers (one OS process,
    two event loops — the wire path is identical to cross-process; the
    subprocess test below covers process isolation)."""

    def _pod(self, algo, **sketch_kw):
        clock = ManualClock(T0)
        cfg = Config(algorithm=algo, limit=10, window=6.0,
                     sketch=SketchParams(depth=3, width=256, sub_windows=6,
                                         **sketch_kw))
        return create_limiter(cfg, backend="sketch", clock=clock), clock

    def test_windowed_slabs_push(self):
        from ratelimiter_tpu.serving.dcn_peer import DcnPusher

        a, ca = self._pod(Algorithm.TPU_SKETCH)
        b, cb = self._pod(Algorithm.TPU_SKETCH)
        srv, loop, t = _server_on_thread(b)
        try:
            assert a.allow_n("k", 10).allowed      # drain on A
            ca.advance(1.0)
            cb.advance(1.0)
            a.allow("warm")                        # complete A's sub-window
            b.allow("warm")                        # roll B to the same period
            pusher = DcnPusher(a, [("127.0.0.1", srv.port)])
            assert pusher.sync_once() == 1
            assert not b.allow("k").allowed        # A's history visible on B
            # Watermark: nothing new -> nothing pushed.
            assert pusher.sync_once() == 0
            pusher.stop()
        finally:
            _stop(srv, loop, t)
        a.close()

    def test_bucket_debt_push(self):
        from ratelimiter_tpu.serving.dcn_peer import DcnPusher

        a, _ca = self._pod(Algorithm.TOKEN_BUCKET)
        b, _cb = self._pod(Algorithm.TOKEN_BUCKET)
        srv, loop, t = _server_on_thread(b)
        try:
            assert a.allow_n("k", 10).allowed
            pusher = DcnPusher(a, [("127.0.0.1", srv.port)])
            assert pusher.sync_once() == 1
            assert not b.allow("k").allowed
            assert pusher.sync_once() == 0         # acc zeroed at export
            pusher.stop()
        finally:
            _stop(srv, loop, t)
        a.close()

    def test_dcn_frames_rejected_when_not_enabled(self):
        """A plain server (dcn=False, the default) refuses T_DCN_PUSH:
        small frames get a typed error, oversized headers drop the
        connection before buffering (memory-DoS bound)."""
        import struct

        a, _ = self._pod(Algorithm.TOKEN_BUCKET)
        b, _ = self._pod(Algorithm.TOKEN_BUCKET)
        srv, loop, t = _server_on_thread(b, dcn=False)
        try:
            a.allow_n("k", 5)
            from ratelimiter_tpu.parallel.dcn import export_debt
            from ratelimiter_tpu.serving.dcn_peer import _PeerConn

            delta = export_debt(a)
            peer = _PeerConn("127.0.0.1", srv.port)
            with pytest.raises(Exception, match="not enabled"):
                peer.push(p.encode_dcn_debt(1, delta), 1)
            peer.close()
            # Oversized header claiming T_DCN_PUSH: connection dropped,
            # nothing buffered.
            with socket.create_connection(("127.0.0.1", srv.port)) as sk:
                sk.sendall(struct.pack("<IBQ", 48 << 20, p.T_DCN_PUSH, 2))
                sk.settimeout(5)
                assert sk.recv(16) == b""          # server closed it
            # And the key is still fresh on B (nothing merged).
            assert b.allow("k").allowed
        finally:
            _stop(srv, loop, t)
        a.close()

    def test_no_echo_of_foreign_slabs(self):
        """Bidirectional pushers must not re-export merged foreign data
        (the contamination double-count): after A->B then B->A, A's view
        of the key equals the true global count, not double."""
        from ratelimiter_tpu.serving.dcn_peer import DcnPusher

        a, ca = self._pod(Algorithm.TPU_SKETCH)
        b, cb = self._pod(Algorithm.TPU_SKETCH)
        srv_a, loop_a, ta = _server_on_thread(a)
        srv_b, loop_b, tb = _server_on_thread(b)
        try:
            a.allow_n("k", 4)                      # 4 of 10 on A
            ca.advance(1.0)
            cb.advance(1.0)
            a.allow("warm")
            b.allow("warm")
            push_a = DcnPusher(a, [("127.0.0.1", srv_b.port)])
            push_b = DcnPusher(b, [("127.0.0.1", srv_a.port)])
            assert push_a.sync_once() == 1         # A's slab lands on B
            assert push_b.sync_once() == 1         # B exports its "warm"
            # B's export must NOT have echoed A's 4 back: A still sees
            # exactly 4 consumed, so 6 remain.
            assert a.allow_n("k", 6).allowed
            assert not a.allow("k").allowed
            push_a.stop()
            push_b.stop()
        finally:
            _stop(srv_a, loop_a, ta)
            _stop(srv_b, loop_b, tb)
        a.close()
        b.close()

    def test_debt_delta_restored_on_total_push_failure(self):
        """A partitioned pusher re-accumulates the delta instead of
        dropping an interval of traffic per cycle."""
        from ratelimiter_tpu.serving.dcn_peer import DcnPusher

        a, _ = self._pod(Algorithm.TOKEN_BUCKET)
        b, _ = self._pod(Algorithm.TOKEN_BUCKET)
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        dead_port = s.getsockname()[1]
        s.close()
        pusher = DcnPusher(a, [("127.0.0.1", dead_port)])
        a.allow_n("k", 10)
        assert pusher.sync_once() == 0             # partition: restored
        # Point at a live peer: the SAME traffic ships on the next cycle.
        srv, loop, t = _server_on_thread(b)
        try:
            pusher.peers[0].port = srv.port
            assert pusher.sync_once() == 1
            assert not b.allow("k").allowed
            pusher.stop()
        finally:
            _stop(srv, loop, t)
        a.close()

    def test_slab_pushes_chunk_under_frame_cap(self):
        """Many pending periods split across frames (one ring's worth of
        large slabs would exceed MAX_DCN_FRAME in a single frame)."""
        from ratelimiter_tpu.serving.dcn_peer import DcnPusher

        a, ca = self._pod(Algorithm.TPU_SKETCH)
        b, cb = self._pod(Algorithm.TPU_SKETCH)
        srv, loop, t = _server_on_thread(b)
        try:
            pusher = DcnPusher(a, [("127.0.0.1", srv.port)])
            pusher._payload_budget = pusher._slab_bytes  # force 1 slab/frame
            for i in range(4):                     # 4 completed periods
                a.allow_n(f"k{i}", 10)
                ca.advance(1.0)
                cb.advance(1.0)
            a.allow("warm")
            b.allow("warm")
            assert pusher.sync_once() == 1
            assert pusher.pushes_ok >= 4           # one frame per period
            for i in range(4):
                assert not b.allow(f"k{i}").allowed
            pusher.stop()
        finally:
            _stop(srv, loop, t)
        a.close()

    def test_oversized_geometry_rejected_at_construction(self):
        from ratelimiter_tpu.serving.dcn_peer import DcnPusher

        cfg = Config(algorithm=Algorithm.TPU_SKETCH, limit=10, window=6.0,
                     sketch=SketchParams(depth=16, width=1 << 21,
                                         sub_windows=6))
        lim = create_limiter(cfg, backend="sketch", clock=ManualClock(T0))
        with pytest.raises(ValueError, match="too large"):
            DcnPusher(lim, [("127.0.0.1", 1)])
        lim.close()

    def test_push_failure_counted_not_fatal(self):
        from ratelimiter_tpu.serving.dcn_peer import DcnPusher

        a, _ = self._pod(Algorithm.TOKEN_BUCKET)
        a.allow_n("k", 3)
        # Nobody listening on this port.
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        dead_port = s.getsockname()[1]
        s.close()
        pusher = DcnPusher(a, [("127.0.0.1", dead_port)])
        assert pusher.sync_once() == 0
        assert pusher.pushes_failed == 1
        pusher.stop()
        a.close()


class TestDcnAuth:
    """T_DCN_PUSH HMAC envelope (ADVICE r4: an open serving port accepting
    pushes is a targeted false-deny lever; the secret closes it)."""

    def _pod(self, **kw):
        clock = ManualClock(T0)
        cfg = Config(algorithm=Algorithm.TOKEN_BUCKET, limit=10, window=6.0,
                     sketch=SketchParams(depth=3, width=256, sub_windows=6))
        return create_limiter(cfg, backend="sketch", clock=clock)

    def test_matching_secret_accepted(self):
        from ratelimiter_tpu.serving.dcn_peer import DcnPusher

        a, b = self._pod(), self._pod()
        srv, loop, t = _server_on_thread(b)
        srv.dcn_secret = "s3cret"
        try:
            a.allow_n("k", 10)
            pusher = DcnPusher(a, [("127.0.0.1", srv.port)], secret="s3cret")
            assert pusher.sync_once() == 1
            assert not b.allow("k").allowed
            pusher.stop()
        finally:
            _stop(srv, loop, t)
        a.close()

    def test_unauthenticated_push_rejected(self):
        from ratelimiter_tpu.serving.dcn_peer import DcnPusher

        a, b = self._pod(), self._pod()
        srv, loop, t = _server_on_thread(b)
        srv.dcn_secret = "s3cret"
        try:
            a.allow_n("k", 10)
            pusher = DcnPusher(a, [("127.0.0.1", srv.port)])  # no secret
            assert pusher.sync_once() == 0
            assert pusher.pushes_failed == 1
            assert b.allow("k").allowed            # nothing merged
            pusher.stop()
        finally:
            _stop(srv, loop, t)
        a.close()

    def test_wrong_secret_rejected(self):
        from ratelimiter_tpu.serving.dcn_peer import DcnPusher

        a, b = self._pod(), self._pod()
        srv, loop, t = _server_on_thread(b)
        srv.dcn_secret = "s3cret"
        try:
            a.allow_n("k", 10)
            pusher = DcnPusher(a, [("127.0.0.1", srv.port)], secret="wrong")
            assert pusher.sync_once() == 0
            assert b.allow("k").allowed
            pusher.stop()
        finally:
            _stop(srv, loop, t)
        a.close()

    def test_tagged_push_to_open_server_accepted(self):
        """An open (no-secret) receiver strips and ignores the tag, so a
        fleet can roll the secret out one pod at a time."""
        from ratelimiter_tpu.serving.dcn_peer import DcnPusher

        a, b = self._pod(), self._pod()
        srv, loop, t = _server_on_thread(b)      # no secret on receiver
        try:
            a.allow_n("k", 10)
            pusher = DcnPusher(a, [("127.0.0.1", srv.port)], secret="s3cret")
            assert pusher.sync_once() == 1
            assert not b.allow("k").allowed
            pusher.stop()
        finally:
            _stop(srv, loop, t)
        a.close()


class TestDcnReplay:
    """Replay protection for authenticated pushes (ADR-007): the RLA2
    envelope carries a per-sender monotonic sequence INSIDE the HMAC;
    receivers reject stale/duplicate values — a replayed push is a
    counter-mass injection lever (targeted false denies)."""

    def _pod(self):
        clock = ManualClock(T0)
        cfg = Config(algorithm=Algorithm.TOKEN_BUCKET, limit=10, window=6.0,
                     sketch=SketchParams(depth=3, width=256, sub_windows=6))
        return create_limiter(cfg, backend="sketch", clock=clock)

    def _push_frame(self, port, frame, req_id):
        from ratelimiter_tpu.serving.dcn_peer import _PeerConn

        peer = _PeerConn("127.0.0.1", port)
        try:
            peer.push(frame, req_id)
        finally:
            peer.close()

    def test_replayed_frame_rejected(self):
        from ratelimiter_tpu.core.errors import InvalidConfigError
        from ratelimiter_tpu.parallel import dcn

        a, b = self._pod(), self._pod()
        srv, loop, t = _server_on_thread(b)
        srv.dcn_secret = "s3cret"
        try:
            a.allow_n("k", 10)
            delta = dcn.export_debt(a)
            seq = int(time.time() * 1e6)
            frame = p.encode_dcn_debt(1, delta, secret="s3cret",
                                      sender=7777, seq=seq)
            self._push_frame(srv.port, frame, 1)       # first copy lands
            with pytest.raises(InvalidConfigError, match="replayed"):
                self._push_frame(srv.port, frame, 1)   # byte-identical replay
            assert srv._dcn_guard.rejected == 1
        finally:
            _stop(srv, loop, t)
        a.close()

    def test_out_of_order_sequence_rejected(self):
        from ratelimiter_tpu.core.errors import InvalidConfigError
        from ratelimiter_tpu.parallel import dcn

        a, b = self._pod(), self._pod()
        srv, loop, t = _server_on_thread(b)
        srv.dcn_secret = "s3cret"
        try:
            a.allow_n("k", 5)
            delta = dcn.export_debt(a)
            seq = int(time.time() * 1e6)
            newer = p.encode_dcn_debt(1, delta, secret="s3cret",
                                      sender=42, seq=seq)
            older = p.encode_dcn_debt(2, delta, secret="s3cret",
                                      sender=42, seq=seq - 10)
            self._push_frame(srv.port, newer, 1)
            with pytest.raises(InvalidConfigError, match="replayed"):
                self._push_frame(srv.port, older, 2)
        finally:
            _stop(srv, loop, t)
        a.close()

    def test_stale_first_contact_rejected(self):
        """An unknown sender whose sequence is older than the freshness
        window (a captured stream from a dead incarnation) is refused —
        the documented residual is bounded to that window."""
        from ratelimiter_tpu.core.errors import InvalidConfigError
        from ratelimiter_tpu.parallel import dcn

        a, b = self._pod(), self._pod()
        srv, loop, t = _server_on_thread(b)
        srv.dcn_secret = "s3cret"
        try:
            a.allow_n("k", 5)
            delta = dcn.export_debt(a)
            stale_seq = int((time.time() - 3600.0) * 1e6)
            frame = p.encode_dcn_debt(1, delta, secret="s3cret",
                                      sender=99, seq=stale_seq)
            with pytest.raises(InvalidConfigError, match="stale"):
                self._push_frame(srv.port, frame, 1)
        finally:
            _stop(srv, loop, t)
        a.close()

    def test_legacy_unsequenced_envelope_rejected_by_secret_server(self):
        """RLA1 (HMAC but no sequence) replays forever, so a receiver
        that requires auth refuses it outright."""
        from ratelimiter_tpu.core.errors import InvalidConfigError
        from ratelimiter_tpu.parallel import dcn

        a, b = self._pod(), self._pod()
        srv, loop, t = _server_on_thread(b)
        srv.dcn_secret = "s3cret"
        try:
            a.allow_n("k", 5)
            delta = dcn.export_debt(a)
            legacy = p.encode_dcn_debt(1, delta, secret="s3cret")  # no seq
            with pytest.raises(InvalidConfigError, match="RLA1"):
                self._push_frame(srv.port, legacy, 1)
        finally:
            _stop(srv, loop, t)
        a.close()

    def test_long_running_sender_fresh_to_new_guard(self):
        """The pusher's sequence must TRACK wall-clock micros, not just
        increment: a receiver whose guard state is new (restart, late
        join, eviction) applies the first-contact freshness floor, and a
        sender that had merely counted up from its start time would look
        permanently stale after max_age_s of uptime."""
        from ratelimiter_tpu.serving.dcn_peer import DcnPusher

        a = self._pod()
        pusher = DcnPusher(a, [], secret="s3cret")
        for _ in range(50):                      # long-running incarnation
            pusher._next_seq()
        guard = p.DcnReplayGuard(max_age_s=300.0)
        guard.check(pusher._sender, pusher._next_seq())   # must not raise
        assert guard.rejected == 0
        # And still strictly increasing (replay of the previous frame is
        # caught even when two frames share a microsecond).
        s1, s2 = pusher._next_seq(), pusher._next_seq()
        assert s2 > s1
        a.close()

    def test_pusher_cycles_pass_the_guard(self):
        """A real DcnPusher's consecutive cycles carry strictly
        increasing sequences, so the guard never trips on the happy
        path — including multi-frame (chunked) cycles."""
        from ratelimiter_tpu.serving.dcn_peer import DcnPusher

        a, b = self._pod(), self._pod()
        srv, loop, t = _server_on_thread(b)
        srv.dcn_secret = "s3cret"
        try:
            pusher = DcnPusher(a, [("127.0.0.1", srv.port)],
                               secret="s3cret")
            a.allow_n("k", 4)
            assert pusher.sync_once() == 1
            a.allow_n("k2", 3)
            assert pusher.sync_once() == 1
            assert pusher.pushes_failed == 0
            assert srv._dcn_guard.rejected == 0
            pusher.stop()
        finally:
            _stop(srv, loop, t)
        a.close()


class TestNativeDcn:
    """The native (C++) front door receives T_DCN_PUSH via its dcn
    callback — a multi-pod deployment needs only --native servers
    (VERDICT r4 item 5)."""

    def _pod(self, algo=Algorithm.TPU_SKETCH):
        clock = ManualClock(T0)
        cfg = Config(algorithm=algo, limit=10, window=6.0,
                     sketch=SketchParams(depth=3, width=256, sub_windows=6))
        return create_limiter(cfg, backend="sketch", clock=clock), clock

    @pytest.fixture(autouse=True)
    def _need_native(self):
        from ratelimiter_tpu.serving.native_server import (
            native_server_available,
        )

        if not native_server_available():
            pytest.skip("needs g++ for the native server")

    def test_windowed_slabs_push_to_native_door(self):
        from ratelimiter_tpu.serving.dcn_peer import DcnPusher
        from ratelimiter_tpu.serving.native_server import (
            NativeRateLimitServer,
        )

        a, ca = self._pod()
        b, cb = self._pod()
        srv = NativeRateLimitServer(b, "127.0.0.1", 0, dcn=True)
        srv.start()
        try:
            assert a.allow_n("k", 10).allowed
            ca.advance(1.0)
            cb.advance(1.0)
            a.allow("warm")
            b.allow("warm")
            pusher = DcnPusher(a, [("127.0.0.1", srv.port)])
            assert pusher.sync_once() == 1
            assert not b.allow("k").allowed
            pusher.stop()
        finally:
            srv.shutdown()
        a.close()
        b.close()

    def test_debt_push_to_native_door_with_secret(self):
        from ratelimiter_tpu.serving.dcn_peer import DcnPusher
        from ratelimiter_tpu.serving.native_server import (
            NativeRateLimitServer,
        )

        a, _ = self._pod(Algorithm.TOKEN_BUCKET)
        b, _ = self._pod(Algorithm.TOKEN_BUCKET)
        srv = NativeRateLimitServer(b, "127.0.0.1", 0, dcn=True,
                                    dcn_secret="s3cret")
        srv.start()
        try:
            a.allow_n("k", 10)
            bad = DcnPusher(a, [("127.0.0.1", srv.port)])  # untagged
            assert bad.sync_once() == 0
            bad.stop()
            # Delta was restored on total failure; the tagged pusher
            # ships the SAME traffic.
            good = DcnPusher(a, [("127.0.0.1", srv.port)], secret="s3cret")
            assert good.sync_once() == 1
            assert not b.allow("k").allowed
            good.stop()
        finally:
            srv.shutdown()
        a.close()
        b.close()

    def test_native_door_without_dcn_refuses_pushes(self):
        from ratelimiter_tpu.serving.dcn_peer import _PeerConn
        from ratelimiter_tpu.serving.native_server import (
            NativeRateLimitServer,
        )
        from ratelimiter_tpu.parallel.dcn import export_debt

        a, _ = self._pod(Algorithm.TOKEN_BUCKET)
        b, _ = self._pod(Algorithm.TOKEN_BUCKET)
        srv = NativeRateLimitServer(b, "127.0.0.1", 0)   # dcn off
        srv.start()
        try:
            a.allow_n("k", 5)
            delta = export_debt(a)
            peer = _PeerConn("127.0.0.1", srv.port)
            with pytest.raises(Exception, match="not enabled"):
                peer.push(p.encode_dcn_debt(1, delta), 1)
            peer.close()
            assert b.allow("k").allowed
        finally:
            srv.shutdown()
        a.close()
        b.close()

    def test_large_frame_exceeding_request_cap_accepted(self):
        """A production-geometry push (> the 4 MiB plain read-buffer
        bound, here an 8 MiB debt delta) must survive the native door's
        IO loop — the backpressure cap is type-aware only on DCN-enabled
        servers (code-review r5 finding: the old flat 4*MAX_FRAME guard
        killed the connection mid-frame)."""
        from ratelimiter_tpu.serving.dcn_peer import DcnPusher
        from ratelimiter_tpu.serving.native_server import (
            NativeRateLimitServer,
        )

        def big_pod():
            clock = ManualClock(T0)
            cfg = Config(algorithm=Algorithm.TOKEN_BUCKET, limit=10,
                         window=6.0,
                         sketch=SketchParams(depth=4, width=1 << 18,
                                             sub_windows=6))
            return create_limiter(cfg, backend="sketch", clock=clock)

        a, b = big_pod(), big_pod()
        srv = NativeRateLimitServer(b, "127.0.0.1", 0, dcn=True)
        srv.start()
        try:
            a.allow_n("k", 10)
            pusher = DcnPusher(a, [("127.0.0.1", srv.port)])
            assert pusher.sync_once() == 1
            assert not b.allow("k").allowed
            pusher.stop()
        finally:
            srv.shutdown()
        a.close()
        b.close()

    def test_push_merges_into_every_shard(self):
        """Foreign mass must be visible no matter which shard owns the
        key (ADVICE r4 medium: shard-0-only export/merge loses
        (N-1)/N of traffic)."""
        from ratelimiter_tpu.serving.dcn_peer import DcnPusher
        from ratelimiter_tpu.serving.native_server import (
            NativeRateLimitServer,
        )

        a, _ = self._pod(Algorithm.TOKEN_BUCKET)
        b, _ = self._pod(Algorithm.TOKEN_BUCKET)
        srv = NativeRateLimitServer(b, "127.0.0.1", 0, shards=4, dcn=True)
        srv.start()
        try:
            keys = [f"user:{i}" for i in range(8)]
            shards_hit = {srv.shard_of(k) for k in keys}
            assert len(shards_hit) > 1             # keys span shards
            for k in keys:
                a.allow_n(k, 10)
            pusher = DcnPusher(a, [("127.0.0.1", srv.port)])
            assert pusher.sync_once() == 1
            with Client(port=srv.port) as c:
                for k in keys:                     # every shard denies
                    assert not c.allow(k).allowed
                assert c.allow("fresh").allowed
            pusher.stop()
        finally:
            srv.shutdown()
        a.close()
        b.close()


@pytest.mark.slow
class TestTwoProcesses:
    def test_cross_process_bucket_convergence(self):
        """Two OS processes running the real server binary converge: a key
        drained on pod A is denied on pod B within one push interval."""
        env = dict(os.environ)
        env["PYTHONPATH"] = os.pathsep.join(
            [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
            + env.get("PYTHONPATH", "").split(os.pathsep))
        # Force CPU in the subprocesses: an inherited accelerator
        # platform (e.g. the tunnel TPU) can't be shared by two server
        # processes and is beside the point here.
        env["JAX_PLATFORMS"] = "cpu"


        port_a, port_b = free_port(), free_port()
        common = [sys.executable, "-m", "ratelimiter_tpu.serving",
                  "--backend", "sketch", "--algorithm", "token_bucket",
                  "--limit", "10", "--window", "60",
                  "--sketch-depth", "3", "--sketch-width", "256",
                  "--no-prewarm", "--dcn-interval", "0.2"]
        pa = subprocess.Popen(
            common + ["--port", str(port_a),
                      "--dcn-peer", f"127.0.0.1:{port_b}"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        pb = subprocess.Popen(
            common + ["--port", str(port_b),
                      "--dcn-peer", f"127.0.0.1:{port_a}"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        try:
            assert "serving" in pa.stdout.readline()
            assert "serving" in pb.stdout.readline()
            with Client(port=port_a, timeout=60.0) as ca:
                assert ca.allow_n("k", 10).allowed   # drain on A
            # Poll with a bounded probe budget instead of one fixed
            # sleep (jit-compile noise under machine load made a 3 s
            # sleep flaky): <= 8 B-local probes can never exhaust the
            # limit of 10 by themselves, so a denial PROVES A's debt
            # landed.
            with Client(port=port_b, timeout=60.0) as cb:
                res = None
                for _ in range(8):
                    time.sleep(1.0)
                    res = cb.allow("k")
                    if not res.allowed:
                        break
                assert res is not None and not res.allowed
                assert res.retry_after > 0
                # Fresh keys still fine on B.
                assert cb.allow("other").allowed
            for proc in (pa, pb):
                proc.send_signal(signal.SIGTERM)
                assert proc.wait(timeout=20) == 0
        finally:
            for proc in (pa, pb):
                if proc.poll() is None:
                    proc.kill()
                    proc.wait()

    def test_cross_process_windowed_slab_convergence_native(self):
        """The windowed slab path (watermarks, foreign-record
        subtraction, chunking) between two real server binaries — both
        running the NATIVE front door, pod A with 2 dispatch shards, so
        the whole multi-pod surface (per-shard pushers, C++ T_DCN_PUSH
        receive, HMAC auth) is exercised end to end (VERDICT r4 items
        5+6)."""
        from ratelimiter_tpu.serving.native_server import (
            native_server_available,
        )

        if not native_server_available():
            pytest.skip("needs g++ for the native server")
        env = dict(os.environ)
        env["PYTHONPATH"] = os.pathsep.join(
            [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
            + env.get("PYTHONPATH", "").split(os.pathsep))
        env["JAX_PLATFORMS"] = "cpu"
        env["RATELIMITER_TPU_DCN_SECRET"] = "two-proc-secret"


        port_a, port_b = free_port(), free_port()
        common = [sys.executable, "-m", "ratelimiter_tpu.serving",
                  "--backend", "sketch", "--algorithm", "sliding_window",
                  "--limit", "10", "--window", "30",
                  "--sub-windows", "30",
                  "--sketch-depth", "3", "--sketch-width", "256",
                  "--no-prewarm", "--native", "--dcn-interval", "0.2"]
        pa = subprocess.Popen(
            common + ["--port", str(port_a), "--shards", "2",
                      "--dcn-peer", f"127.0.0.1:{port_b}"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        pb = subprocess.Popen(
            common + ["--port", str(port_b),
                      "--dcn-peer", f"127.0.0.1:{port_a}"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        try:
            assert "serving" in pa.stdout.readline()
            assert "serving" in pb.stdout.readline()
            keys = [f"user:{i}" for i in range(4)]
            with Client(port=port_a, timeout=60.0) as ca:
                for k in keys:
                    assert ca.allow_n(k, 10).allowed   # drain on A
            # Slabs only ship once their sub-window (1 s) completes, and
            # completion is driven by later dispatches: keep warm traffic
            # flowing on both pods while the exchange happens. Probe each
            # key at most 8 times: 8 B-local admissions < limit 10, so a
            # denial on B PROVES A's 10/10 drain landed (B alone could
            # never deny within the probe budget).
            converged = False
            with Client(port=port_a, timeout=60.0) as ca, \
                    Client(port=port_b, timeout=60.0) as cb:
                for _ in range(8):
                    ca.allow("warm-a")
                    cb.allow("warm-b")
                    time.sleep(1.0)
                    if all(not cb.allow(k).allowed for k in keys):
                        converged = True
                        break
                assert converged, "A's slabs never became visible on B"
                # Fresh keys unaffected.
                assert cb.allow("fresh").allowed
            for proc in (pa, pb):
                proc.send_signal(signal.SIGTERM)
                assert proc.wait(timeout=20) == 0
        finally:
            for proc in (pa, pb):
                if proc.poll() is None:
                    proc.kill()
                    proc.wait()
