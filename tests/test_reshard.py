"""Elastic re-bucketing (ADR-018): split/merge per-slice state onto a
new slice count.

The pinned contracts:

* **never over-admit**: a mesh restored onto ANY other slice count
  (split, merge, prime/coprime) never allows a request the
  same-geometry restore denies — conservative-union merges only raise
  estimates;
* **overrides exact**: per-key override tables re-route exactly by
  hash across every geometry change;
* **round trip**: ``N -> k*N -> N`` is bit-identical (splits copy
  verbatim; the merge of identical copies short-circuits), and
  ``tools/rebucket.py`` round-trips a plain PR 2 durability snapshot;
* the heavy-hitter side table folds back into CMS columns on a true
  merge (counts survive, direction still deny-ward);
* the token-bucket debt slab merges with exact decay normalization.
"""

from __future__ import annotations

import os
import subprocess
import sys

import numpy as np
import pytest

from ratelimiter_tpu import Algorithm, Config, SketchParams
from ratelimiter_tpu.checkpoint import save_state
from ratelimiter_tpu.core.clock import ManualClock
from ratelimiter_tpu.core.errors import CheckpointError
from ratelimiter_tpu.parallel import reshard

jax = pytest.importorskip("jax")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mesh(cfg, clock, n):
    from ratelimiter_tpu.parallel.limiter import SlicedMeshLimiter

    return SlicedMeshLimiter(cfg, clock, n_devices=n)


def _cfg(limit=20, hh_slots=0, algorithm=Algorithm.SLIDING_WINDOW):
    return Config(algorithm=algorithm, limit=limit, window=600.0,
                  sketch=SketchParams(depth=2, width=1024, sub_windows=6,
                                      hh_slots=hh_slots))


def _snapshot(lim, cfg, tmp_path, name="snap.npz"):
    kind, arrays, extra = lim.capture_state()
    path = str(tmp_path / name)
    save_state(path, kind, cfg, arrays, extra)
    return path


class TestContributors:
    def test_gcd_rule(self):
        # Clean split: one contributor (j % old_n).
        assert reshard.contributors(5, 4, 8) == [1]
        # Clean merge: the folded old slices.
        assert reshard.contributors(1, 8, 4) == [1, 5]
        # Coprime: every old slice can contribute.
        assert reshard.contributors(2, 4, 3) == [0, 1, 2, 3]
        # Same count: identity.
        assert reshard.contributors(3, 4, 4) == [3]


class TestReshardOracle:
    """N -> M restore never over-admits vs the same-geometry restore,
    and overrides survive exactly — both directions, prime M included
    (the ISSUE-11 acceptance oracle)."""

    @pytest.fixture(scope="class")
    def source(self, tmp_path_factory):
        tmp_path = tmp_path_factory.mktemp("reshard-src")
        clock = ManualClock(1000.0)
        src = _mesh(_cfg(), clock, 4)
        cfg = src.config
        rng = np.random.default_rng(7)
        keys = [f"user:{i}" for i in range(60)]
        # Uneven traffic: hot keys near/over the limit so the oracle
        # run has real denies to preserve.
        for _ in range(8):
            ks = ([keys[j] for j in rng.integers(0, 60, size=48)]
                  + keys[:6] * 2)
            src.allow_batch(ks)
            clock.advance(30.0)
        src.set_override("user:3", 5)
        src.set_override("user:7", 200)
        path = _snapshot(src, cfg, tmp_path)
        src.close()
        return cfg, clock, keys, path

    # 8 = clean split (verbatim copies), 3 = prime merge (every old
    # slice contributes — the all-contributors CRT shape; the clean
    # 2-merge is a strict subset of its logic).
    @pytest.mark.parametrize("m", [8, 3])
    def test_never_over_admits_and_overrides_exact(self, source, m):
        cfg, clock, keys, path = source
        oracle = _mesh(cfg, ManualClock(clock.now()), 4)
        oracle.restore(path)
        dst = _mesh(cfg, ManualClock(clock.now()), m)
        dst.restore(path)
        try:
            assert dst.get_override("user:3").limit == 5
            assert dst.get_override("user:7").limit == 200
            assert dst.override_count() == oracle.override_count()
            ro = oracle.allow_batch(keys)
            rd = dst.allow_batch(keys)
            over = rd.allowed & ~ro.allowed
            assert not over.any(), (
                f"resharded 4->{m} mesh over-admits {int(over.sum())} "
                f"key(s) vs the same-geometry source")
            # The oracle traffic must actually contain denies, or the
            # assertion above is vacuous.
            assert not ro.allowed.all()
        finally:
            oracle.close()
            dst.close()

    def test_split_then_merge_round_trip_bit_identical(self, source,
                                                       tmp_path):
        cfg, clock, _, path = source
        mid = _mesh(cfg, ManualClock(clock.now()), 8)
        mid.restore(path)
        p8 = _snapshot(mid, cfg, tmp_path, "snap8.npz")
        mid.close()
        back = _mesh(cfg, ManualClock(clock.now()), 4)
        back.restore(p8)
        p4 = _snapshot(back, cfg, tmp_path, "snap4.npz")
        back.close()
        with np.load(path, allow_pickle=False) as a, \
                np.load(p4, allow_pickle=False) as b:
            names = [k for k in a.files if not k.startswith("__")]
            assert set(names) == {k for k in b.files
                                  if not k.startswith("__")}
            for k in names:
                np.testing.assert_array_equal(a[k], b[k], err_msg=k)

    def test_restore_slice_refusal_names_rebucket_path(self, source):
        cfg, clock, _, path = source
        dst = _mesh(cfg, ManualClock(clock.now()), 3)
        try:
            with pytest.raises(CheckpointError) as ei:
                dst.restore_slice(path, 0)
            msg = str(ei.value)
            assert "rebucket" in msg and "restore()" in msg
        finally:
            dst.close()


class TestHeavyHitterFold:
    def test_merge_folds_hh_counts_never_over_admits(self, tmp_path):
        clock = ManualClock(1000.0)
        src = _mesh(_cfg(hh_slots=16), clock, 4)
        cfg = src.config
        # Hammer one key so it promotes into the side table, then keep
        # hammering: its exact count lives in hh cells, not the CMS.
        hot = "tenant:hot"
        for _ in range(6):
            src.allow_batch([hot] * 4)
            clock.advance(20.0)
        path = _snapshot(src, cfg, tmp_path)
        src.close()
        oracle = _mesh(cfg, ManualClock(clock.now()), 4)
        oracle.restore(path)
        merged = _mesh(cfg, ManualClock(clock.now()), 2)
        merged.restore(path)
        try:
            ro = oracle.allow_n(hot, 1)
            rm = merged.allow_n(hot, 1)
            # The fold keeps the promoted key's mass: if the source
            # denies, the merged mesh must deny too.
            assert not ro.allowed
            assert not rm.allowed
        finally:
            oracle.close()
            merged.close()


class TestTokenBucketReshard:
    def test_debt_merge_never_over_admits(self, tmp_path):
        clock = ManualClock(1000.0)
        src = _mesh(_cfg(limit=10, algorithm=Algorithm.TOKEN_BUCKET),
                    clock, 4)
        cfg = src.config
        ids = np.arange(48, dtype=np.uint64)
        rng = np.random.default_rng(3)
        for _ in range(4):
            src.allow_ids(ids[rng.integers(0, 48, size=96)]
                          .astype(np.uint64))
            clock.advance(0.5)
        path = _snapshot(src, cfg, tmp_path)
        src.close()
        for m in (3,):  # prime merge — the all-contributors shape
            oracle = _mesh(cfg, ManualClock(clock.now()), 4)
            oracle.restore(path)
            dst = _mesh(cfg, ManualClock(clock.now()), m)
            dst.restore(path)
            try:
                ro = oracle.allow_ids(ids)
                rd = dst.allow_ids(ids)
                over = rd.allowed & ~ro.allowed
                assert not over.any(), f"4->{m} bucket over-admits"
                assert not ro.allowed.all()
            finally:
                oracle.close()
                dst.close()

    def test_decay_normalization_is_exact_mirror(self):
        from ratelimiter_tpu.ops import bucket_kernels

        cfg = _mesh(_cfg(limit=10, algorithm=Algorithm.TOKEN_BUCKET),
                    ManualClock(0.0), 1).config
        _, num, den, _, _, _ = bucket_kernels._params(cfg)
        import jax.numpy as jnp

        for elapsed, rem in [(0, 0), (123456, 17), (10**9, den - 1),
                             (10**13, 0)]:
            host = reshard._decay_exact(elapsed, rem, num, den)
            dev, _ = bucket_kernels._decay(
                {"last": jnp.asarray(0, jnp.int64),
                 "rem": jnp.asarray(rem, jnp.int64)},
                jnp.asarray(elapsed, jnp.int64),
                rate_num=num, rate_den=den)
            assert host == int(dev), (elapsed, rem)


class TestMergeStates:
    def test_identical_states_short_circuit_verbatim(self):
        clock = ManualClock(1000.0)
        from ratelimiter_tpu.algorithms.sketch import SketchLimiter

        lim = SketchLimiter(_cfg(), clock)
        lim.allow_batch([f"k{i}" for i in range(32)])
        _, arrays, extra = lim.capture_state()
        merged, _ = reshard.merge_states(
            [dict(arrays), dict(arrays), dict(arrays)],
            [dict(extra)] * 3)
        for k in arrays:
            np.testing.assert_array_equal(np.asarray(arrays[k]),
                                          merged[k], err_msg=k)

    def test_merge_into_limiter_carries_counters_and_overrides(self):
        clock = ManualClock(1000.0)
        from ratelimiter_tpu.algorithms.sketch import SketchLimiter

        src = SketchLimiter(_cfg(), clock)
        cfg = src.config
        for _ in range(20):
            src.allow_n("hot", 1)
        src.set_override("vip", 3)
        _, arrays, extra = src.capture_state()
        dst = SketchLimiter(cfg, clock)
        for _ in range(4):
            dst.allow_n("other", 1)
        reshard.merge_into_limiter(dst, arrays, extra)
        assert not dst.allow_n("hot", 1).allowed
        assert dst.get_override("vip").limit == 3
        # The destination's own traffic survives the fold too.
        r = dst.allow_n("other", 1)
        assert r.allowed and r.remaining <= cfg.limit - 5


class TestRebucketTool:
    def test_cli_round_trips_a_plain_pr2_snapshot(self, tmp_path):
        """tools/rebucket.py round-trips the PR 2 durability format:
        plain -> 3-slice mesh -> plain, bit-identical, and both
        intermediate forms restore into live limiters."""
        from ratelimiter_tpu.algorithms.sketch import SketchLimiter
        from ratelimiter_tpu.parallel.limiter import SlicedMeshLimiter

        clock = ManualClock(1000.0)
        lim = SketchLimiter(_cfg(), clock)
        cfg = lim.config
        lim.allow_batch([f"k{i}" for i in range(40)])
        lim.set_override("vip", 9)
        plain = _snapshot(lim, cfg, tmp_path, "plain.npz")
        mesh3 = str(tmp_path / "mesh3.npz")
        back = str(tmp_path / "back.npz")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        # One leg through the real CLI (argv contract); the return leg
        # calls the same entry in-process (a second interpreter boot
        # would buy nothing but tier-1 seconds).
        subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "rebucket.py"),
             plain, mesh3, "--slices", "3"], check=True, env=env)
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            import rebucket as rebucket_cli

            assert rebucket_cli.main([mesh3, back, "--slices", "1"]) == 0
        finally:
            sys.path.remove(os.path.join(REPO, "tools"))
        with np.load(plain, allow_pickle=False) as a, \
                np.load(back, allow_pickle=False) as b:
            for k in [k for k in a.files if not k.startswith("__")]:
                np.testing.assert_array_equal(a[k], b[k], err_msg=k)
        m = SlicedMeshLimiter(cfg, ManualClock(clock.now()), n_devices=3)
        m.restore(mesh3)
        assert m.get_override("vip").limit == 9
        m.close()
        p = SketchLimiter(cfg, ManualClock(clock.now()))
        p.restore(back)
        assert p.get_override("vip").limit == 9

    def test_cli_rejects_bad_slices(self, tmp_path):
        rc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "rebucket.py"),
             "in.npz", "out.npz", "--slices", "0"],
            capture_output=True).returncode
        assert rc != 0
