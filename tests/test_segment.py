"""Properties of the in-batch admission op (ops/segment.py).

The correctness core of the batched design (SURVEY.md §7.4 hard part #1):
exactness for uniform-n segments, never-over-admit for adversarial mixed-n,
and agreement with a sequential greedy reference.
"""

import numpy as np
import pytest

import tests.conftest  # noqa: F401  (forces CPU platform before jax import)
import jax

jax.config.update("jax_enable_x64", True)
import jax.numpy as jnp

from ratelimiter_tpu.ops.segment import admit


def greedy_reference(sid, n, avail_by_slot):
    """Sequential greedy conditional consume — the semantics of k serialized
    Lua calls (SURVEY.md §4.2.4)."""
    level = dict(avail_by_slot)
    allowed = []
    for s, k in zip(sid, n):
        if k <= level[s]:
            level[s] -= k
            allowed.append(True)
        else:
            allowed.append(False)
    return allowed


def run_admit(sid, n, avail_by_slot, iters=4):
    sid = np.asarray(sid, dtype=np.int32)
    n = np.asarray(n, dtype=np.int64)
    avail = np.asarray([avail_by_slot[s] for s in sid], dtype=np.int64)
    allowed, seen, consumed = admit(
        jnp.asarray(sid), jnp.asarray(n), jnp.asarray(avail), iters)
    return np.asarray(allowed), np.asarray(seen), np.asarray(consumed)


def test_single_segment_unit_requests():
    allowed, seen, consumed = run_admit([0] * 10, [1] * 10, {0: 6})
    assert list(allowed) == [True] * 6 + [False] * 4
    assert consumed.sum() == 6


def test_multiple_segments_independent():
    sid = [2, 0, 2, 1, 0, 2]
    n = [1, 1, 1, 1, 1, 1]
    allowed, _, _ = run_admit(sid, n, {0: 1, 1: 0, 2: 2})
    assert list(allowed) == [True, True, True, False, False, False]


def test_uniform_n_exact():
    # avail 10, n=3 each -> first 3 requests fit (9 <= 10), 4th denied
    allowed, _, consumed = run_admit([5] * 5, [3] * 5, {5: 10})
    assert list(allowed) == [True, True, True, False, False]
    assert consumed.sum() == 9


def test_mixed_n_greedy_convergence():
    # R=10, n=[6,6,4]: greedy allows 1st and 3rd (fixpoint needs 2 iters).
    allowed, _, _ = run_admit([0, 0, 0], [6, 6, 4], {0: 10})
    assert list(allowed) == [True, False, True]


def test_adversarial_never_over_admits():
    # R=10, n=[11,6,6]: the fixpoint's even iterates over-admit ([F,T,T]);
    # the safety intersection must land on a feasible mask.
    allowed, _, consumed = run_admit([0, 0, 0], [11, 6, 6], {0: 10}, iters=1)
    assert consumed.sum() <= 10
    allowed, _, consumed = run_admit([0, 0, 0], [11, 6, 6], {0: 10}, iters=4)
    assert list(allowed) == [False, True, False]  # greedy


def test_seen_reports_pre_request_level():
    allowed, seen, _ = run_admit([0, 0, 0], [4, 4, 4], {0: 10})
    assert list(allowed) == [True, True, False]
    assert list(seen) == [10, 6, 2]


def test_padding_noop():
    # n=0 padding entries consume nothing and do not disturb real requests.
    allowed, _, consumed = run_admit([0, 7, 0, 7], [2, 0, 2, 0], {0: 3, 7: 0})
    assert list(allowed)[0] and not list(allowed)[2]
    assert consumed.sum() == 2


@pytest.mark.parametrize("seed", range(8))
def test_randomized_against_greedy_uniform_n(seed):
    """For uniform n per slot the op must equal sequential greedy exactly."""
    rng = np.random.default_rng(seed)
    B = 257
    sid = rng.integers(0, 13, B)
    per_slot_n = {s: int(rng.integers(1, 5)) for s in range(13)}
    n = np.array([per_slot_n[s] for s in sid])
    avail = {s: int(rng.integers(0, 40)) for s in range(13)}
    allowed, _, consumed = run_admit(sid, n, avail)
    assert list(allowed) == greedy_reference(sid, n, avail)


@pytest.mark.parametrize("seed", range(8))
def test_randomized_mixed_n_safe_and_usually_greedy(seed):
    """Mixed n: never over-admit; with default iters, matches greedy on
    random (non-adversarial) traffic."""
    rng = np.random.default_rng(100 + seed)
    B = 129
    sid = rng.integers(0, 7, B)
    n = rng.integers(1, 6, B)
    avail = {s: int(rng.integers(0, 60)) for s in range(7)}
    allowed, _, consumed = run_admit(sid, n, avail, iters=6)
    # safety: per-slot consumption within avail
    for s in range(7):
        assert consumed[np.asarray(sid) == s].sum() <= avail[s]
    assert list(allowed) == greedy_reference(sid, n, avail)
