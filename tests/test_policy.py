"""Policy engine (ratelimiter_tpu/policy/): the device-resident override
table, its ops-level binary search, checkpoint/restore survival, the
config-fingerprint gate, the occupancy gauge, and the serving wire frames.

Backend-contract behavior (mixed batches, per-key limits/windows) lives in
tests/contract.py and runs per backend; this file covers the subsystem's
own pieces."""

from __future__ import annotations

import asyncio
import os
import subprocess
import sys

import numpy as np
import pytest

from ratelimiter_tpu import (
    Algorithm,
    CheckpointError,
    Config,
    InvalidConfigError,
    ManualClock,
    create_limiter,
)
from ratelimiter_tpu.core.config import PolicySpec
from ratelimiter_tpu.ops import policy_kernels as pk
from ratelimiter_tpu.policy import PolicyTable

T0 = 1_700_000_000.0
BACKENDS = ("exact", "dense", "sketch")


def make(backend, algo=Algorithm.SLIDING_WINDOW, limit=4, window=60.0, **kw):
    clock = ManualClock(T0)
    cfg = Config(algorithm=algo, limit=limit, window=window, **kw)
    return create_limiter(cfg, backend=backend, clock=clock), clock


# ---------------------------------------------------------------- ops level

class TestLookupKernel:
    def _table(self, n, capacity, rng):
        keys = np.sort(rng.choice(2**62, size=n, replace=False)
                       .astype(np.int64))
        padded = np.full(capacity, pk.PAD_KEY, dtype=np.int64)
        padded[:n] = keys
        return keys, padded

    @pytest.mark.parametrize("capacity", [8, 64, 1024])
    def test_device_matches_host(self, capacity):
        import jax.numpy as jnp

        rng = np.random.default_rng(7)
        n = capacity // 2
        keys, padded = self._table(n, capacity, rng)
        hits = rng.choice(keys, size=50)
        misses = rng.choice(2**62, size=50).astype(np.int64)
        queries = np.concatenate([hits, misses])
        d_idx, d_found = pk.lookup_i64(jnp.asarray(padded),
                                       jnp.asarray(queries))
        h_idx, h_found = pk.lookup_host(padded, queries)
        np.testing.assert_array_equal(np.asarray(d_found), h_found)
        # Where found, both must point at the matching row.
        np.testing.assert_array_equal(
            padded[np.asarray(d_idx)][np.asarray(d_found)],
            queries[np.asarray(d_found)])
        np.testing.assert_array_equal(padded[h_idx][h_found],
                                      queries[h_found])
        # All planted keys are found; random non-members are not (they
        # were drawn from a disjoint range with prob ~1).
        assert bool(np.all(np.asarray(d_found)[:50]))

    @pytest.mark.parametrize("capacity", [8, 64])
    def test_full_table_every_row_reachable(self, capacity):
        """Regression: the offset descent must reach index capacity-1 —
        a FULL table's max-key override was silently invisible to the
        kernels before the bounds-masked step-P probe."""
        import jax.numpy as jnp

        rng = np.random.default_rng(11)
        keys = np.sort(rng.choice(2**62, size=capacity, replace=False)
                       .astype(np.int64))
        idx, found = pk.lookup_i64(jnp.asarray(keys), jnp.asarray(keys))
        assert bool(np.all(np.asarray(found)))
        np.testing.assert_array_equal(np.asarray(idx),
                                      np.arange(capacity, dtype=np.int32))

    def test_full_limiter_table_max_key_decides(self):
        """End-to-end form of the same regression: fill the table to
        capacity and check the entry with the LARGEST search key still
        changes decisions."""
        clock = ManualClock(T0)
        cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=2,
                     window=60.0, policy=PolicySpec(capacity=8))
        lim = create_limiter(cfg, backend="dense", clock=clock)
        for i in range(8):
            lim.set_override(f"k{i}", 5)
        arrs = lim._policy_table.host_arrays()
        max_key = [k for k, _ in lim._policy_table.items()
                   if lim._policy_key(k) == int(arrs["key"][7])][0]
        out = lim.allow_batch([max_key] * 7)
        assert out.allow_count == 5, max_key
        lim.close()

    def test_empty_table_misses_everything(self):
        import jax.numpy as jnp

        empty = pk.empty_arrays(16, {"limit": 5})
        _, found = pk.lookup_i64(jnp.asarray(empty["key"]),
                                 jnp.asarray(np.arange(100, dtype=np.int64)))
        assert not bool(np.any(np.asarray(found)))

    def test_pack_halves_device_matches_host(self):
        import jax.numpy as jnp

        rng = np.random.default_rng(3)
        h1 = rng.integers(0, 2**32, size=64, dtype=np.uint32)
        h2 = rng.integers(0, 2**32, size=64, dtype=np.uint32)
        dev = np.asarray(pk.pack_halves(jnp.asarray(h1), jnp.asarray(h2)))
        np.testing.assert_array_equal(dev, pk.pack_halves_host(h1, h2))


# ------------------------------------------------------------- table level

class TestPolicyTable:
    def _table(self, capacity=8, limit=4, window=60.0, **kw):
        cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=limit,
                     window=window, policy=PolicySpec(capacity=capacity))
        return PolicyTable(cfg, key_fn=lambda k: hash(k) & (2**62 - 1), **kw)

    def test_capacity_enforced(self):
        t = self._table(capacity=8)
        for i in range(8):
            t.set(f"k{i}", 10)
        with pytest.raises(InvalidConfigError, match="full"):
            t.set("overflow", 10)
        # Updating an existing entry is not a new slot.
        t.set("k0", 11)
        assert t.get("k0").limit == 11

    def test_spec_validation(self):
        with pytest.raises(InvalidConfigError):
            PolicySpec(capacity=12).validate()
        with pytest.raises(InvalidConfigError):
            PolicySpec(capacity=4).validate()
        PolicySpec(capacity=512).validate()

    def test_window_scaling_gate(self):
        t = self._table(window_scaling=False)
        with pytest.raises(InvalidConfigError, match="window"):
            t.set("k", 5, window_scale=0.5)
        t.set("k", 5)  # scale 1 is fine

    def test_effective_window_bounds(self):
        t = self._table(window=60.0)
        with pytest.raises(InvalidConfigError, match="window"):
            t.set("k", 5, window_scale=1e-9)

    def test_host_arrays_sorted_and_padded(self):
        t = self._table(capacity=8, limit=4)
        t.set("a", 7)
        t.set("b", 9)
        arrs = t.host_arrays()
        assert arrs["key"].shape == (8,)
        assert list(arrs["key"]) == sorted(arrs["key"])
        assert np.sum(arrs["key"] != pk.PAD_KEY) == 2
        # Padding rows carry defaults.
        assert arrs["limit"][-1] == 4

    def test_rebase_moves_defaults_only(self):
        t = self._table(limit=4)
        t.set("vip", 10)
        t.rebase(6, 60.0)
        arrs = t.host_arrays()
        assert arrs["limit"][-1] == 6            # default column moved
        assert t.get("vip").limit == 10          # entry pinned


# ----------------------------------------------------- limiter integration

class TestCheckpointRoundTrip:
    @pytest.mark.parametrize("backend", BACKENDS)
    def test_overrides_survive_restore(self, backend, tmp_path):
        lim, clock = make(backend)
        lim.set_override("vip", 9)
        lim.set_override("cheap", 2)
        lim.allow_batch(["vip"] * 5)
        path = str(tmp_path / "snap.npz")
        lim.save(path)
        lim2, _ = make(backend)
        lim2.restore(path)
        assert lim2.get_override("vip").limit == 9
        assert lim2.get_override("cheap").limit == 2
        assert lim2.override_count() == 2
        # Both the override AND the consumed quota restored: 4 of 9 left.
        assert lim2.allow_batch(["vip"] * 9).allow_count == 4
        lim.close()
        lim2.close()

    @pytest.mark.parametrize("backend", BACKENDS)
    def test_policy_spec_mismatch_rejected(self, backend, tmp_path):
        """PolicySpec is part of the config fingerprint: a snapshot taken
        under a different override-table geometry must refuse to load."""
        lim, _ = make(backend)
        path = str(tmp_path / "snap.npz")
        lim.save(path)
        clock = ManualClock(T0)
        cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=4,
                     window=60.0, policy=PolicySpec(capacity=64))
        lim2 = create_limiter(cfg, backend=backend, clock=clock)
        with pytest.raises(CheckpointError, match="fingerprint"):
            lim2.restore(path)
        lim.close()
        lim2.close()

    def test_pre_policy_checkpoint_restores_empty_table(self, tmp_path):
        """Snapshots written before any override existed restore with an
        empty table (the policy_* columns are present but zero-length)."""
        lim, _ = make("exact")
        path = str(tmp_path / "snap.npz")
        lim.save(path)
        lim2, _ = make("exact")
        lim2.set_override("vip", 9)
        lim2.restore(path)
        assert lim2.override_count() == 0
        lim.close()
        lim2.close()


class TestOccupancyGauge:
    def test_gauge_tracks_mutations(self):
        from ratelimiter_tpu.observability import metrics as m

        lim, _ = make("exact")
        lim.set_override("a", 5)
        lim.set_override("b", 6)
        g = m.DEFAULT.get("rate_limiter_policy_overrides")
        assert g is not None and g.value() == 2.0
        lim.delete_override("a")
        assert g.value() == 1.0
        lim.close()

    def test_occupancy_in_metrics_text(self):
        from ratelimiter_tpu.observability import metrics as m

        lim, _ = make("exact")
        lim.set_override("a", 5)
        assert "rate_limiter_policy_overrides" in m.DEFAULT.render()
        lim.close()


class TestUpdateInteractions:
    def test_update_limit_moves_default_tier_only(self):
        lim, _ = make("exact", limit=4)
        lim.set_override("vip", 10)
        lim.update_limit(6)
        assert lim.allow_batch(["std"] * 8).allow_count == 6
        assert lim.allow_batch(["vip"] * 12).allow_count == 10
        lim.close()

    def test_update_window_blocked_with_scaled_overrides(self):
        lim, _ = make("exact", window=60.0)
        lim.set_override("fast", window_scale=0.5)
        with pytest.raises(InvalidConfigError, match="window-scaled"):
            lim.update_window(30.0)
        lim.delete_override("fast")
        lim.update_window(30.0)  # fine once the scaled entry is gone
        lim.close()

    def test_update_window_revalidates_overrides(self):
        """A window change that would push an existing override past the
        exact-integer overflow gates is refused BEFORE any state moves."""
        lim, _ = make("dense", algo=Algorithm.TOKEN_BUCKET, limit=10,
                      window=60.0)
        lim.set_override("vip", 4_000_000)  # fine at 60s
        with pytest.raises(InvalidConfigError, match="vip"):
            lim.update_window(3.15e7)       # ~1 year: W*num overflows
        assert lim.config.window == 60.0    # nothing migrated
        lim.close()

    def test_dense_override_validated_against_gates(self):
        lim, _ = make("dense", limit=4, window=60.0)
        with pytest.raises(InvalidConfigError):
            lim.set_override("huge", 1 << 50)
        lim.close()

    def test_sketch_override_f32_gate(self):
        lim, _ = make("sketch", algo=Algorithm.TPU_SKETCH)
        with pytest.raises(InvalidConfigError, match="2\\*\\*24"):
            lim.set_override("huge", 1 << 24)
        lim.close()


# ------------------------------------------------------------- wire frames

class TestWireProtocol:
    def test_policy_frames_roundtrip_encode_parse(self):
        from ratelimiter_tpu.serving import protocol as p

        frame = p.encode_policy_set(7, "vip", 9, 0.5)
        length, type_, rid = p.parse_header(frame[:p.HEADER_SIZE])
        assert type_ == p.T_POLICY_SET and rid == 7
        key, limit, scale = p.parse_policy_set(frame[p.HEADER_SIZE:])
        assert (key, limit, scale) == ("vip", 9, 0.5)
        # limit=None -> "keep default" flag
        frame = p.encode_policy_set(8, "w", None, 2.0)
        _, limit, scale = p.parse_policy_set(frame[p.HEADER_SIZE:])
        assert limit is None and scale == 2.0
        body = p.encode_policy_r(9, True, 9, 0.5)[p.HEADER_SIZE:]
        assert p.parse_policy_r(body) == (True, 9, 0.5)

    def test_server_policy_rpcs(self):
        """SET/GET/DEL over the asyncio server change live decisions."""
        from ratelimiter_tpu.serving import Client
        from ratelimiter_tpu.serving.server import RateLimitServer

        async def run():
            lim, _ = make("exact", limit=3)
            srv = RateLimitServer(lim, port=0)
            await srv.start()

            def client_ops():
                c = Client(port=srv.port)
                assert c.set_override("vip", 7) == (7, 1.0)
                assert c.get_override("vip") == (7, 1.0)
                assert c.get_override("other") is None
                allowed = sum(c.allow("vip").allowed for _ in range(9))
                assert allowed == 7
                assert c.allow("std").limit == 3
                assert c.delete_override("vip") is True
                assert c.delete_override("vip") is False
                with pytest.raises(InvalidConfigError):
                    c.set_override("bad", -1)
                c.close()

            await asyncio.get_running_loop().run_in_executor(None, client_ops)
            await srv.shutdown()
            lim.close()

        asyncio.run(run())


# --------------------------------------------------------------- x64 hygiene

class TestX64Hygiene:
    def test_import_leaves_x64_untouched(self):
        """Satellite: importing the library (and its kernel modules) must
        not flip the process-global jax_enable_x64 — that global changes
        dtype semantics for unrelated user JAX code."""
        code = (
            "import jax\n"
            "before = bool(jax.config.jax_enable_x64)\n"
            "import ratelimiter_tpu\n"
            "import ratelimiter_tpu.ops.dense_kernels\n"
            "import ratelimiter_tpu.ops.sketch_kernels\n"
            "import ratelimiter_tpu.ops.bucket_kernels\n"
            "import ratelimiter_tpu.ops.policy_kernels\n"
            "after = bool(jax.config.jax_enable_x64)\n"
            "assert before == after == False, (before, after)\n"
            "print('untouched')\n"
        )
        out = subprocess.run(
            [sys.executable, "-c", code],
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stdout + out.stderr
        assert "untouched" in out.stdout

    def test_device_limiter_requires_x64(self):
        """Construction (not some deep dispatch) fails loudly without the
        flag, naming the fix."""
        code = (
            "import jax\n"
            "from ratelimiter_tpu import Algorithm, Config, create_limiter\n"
            "cfg = Config(algorithm=Algorithm.TPU_SKETCH, limit=5,"
            " window=60.0)\n"
            "try:\n"
            "    create_limiter(cfg, backend='sketch')\n"
            "except RuntimeError as e:\n"
            "    assert 'jax_enable_x64' in str(e), e\n"
            "    print('raised')\n"
            "else:\n"
            "    raise SystemExit('no error raised')\n"
        )
        out = subprocess.run(
            [sys.executable, "-c", code],
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stdout + out.stderr
        assert "raised" in out.stdout

    def test_exact_backend_works_without_x64(self):
        code = (
            "from ratelimiter_tpu import Algorithm, Config, create_limiter\n"
            "cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=5,"
            " window=60.0)\n"
            "lim = create_limiter(cfg, backend='exact')\n"
            "assert lim.allow('k').allowed\n"
            "print('exact ok')\n"
        )
        out = subprocess.run(
            [sys.executable, "-c", code],
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stdout + out.stderr
