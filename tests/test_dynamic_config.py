"""Dynamic limit updates: state survives, new limit governs (the
reference's 'dynamic configuration' roadmap item, realized)."""

import jax
import pytest

from ratelimiter_tpu import (
    Algorithm,
    Config,
    InvalidConfigError,
    ManualClock,
    SketchParams,
    create_limiter,
)

T0 = 1_700_000_000.0

BACKEND_ALGOS = [
    ("exact", Algorithm.FIXED_WINDOW),
    ("exact", Algorithm.SLIDING_WINDOW),
    ("exact", Algorithm.TOKEN_BUCKET),
    ("dense", Algorithm.SLIDING_WINDOW),
    ("dense", Algorithm.TOKEN_BUCKET),
    ("sketch", Algorithm.TPU_SKETCH),
    ("sketch", Algorithm.FIXED_WINDOW),
    ("sketch", Algorithm.TOKEN_BUCKET),
]


@pytest.mark.parametrize("backend,algo", BACKEND_ALGOS, ids=str)
def test_raise_limit_keeps_consumption(backend, algo):
    clock = ManualClock(T0)
    lim = create_limiter(Config(algorithm=algo, limit=5, window=60.0),
                         backend=backend, clock=clock)
    assert lim.allow_n("k", 5).allowed
    assert not lim.allow("k").allowed
    lim.update_limit(8)
    # Consumption stands: 3 more, not 8.
    assert lim.allow_n("k", 3).allowed
    assert not lim.allow("k").allowed
    assert lim.allow("k2").allowed  # other keys see the new limit too
    lim.close()


@pytest.mark.parametrize("backend,algo", BACKEND_ALGOS, ids=str)
def test_lower_limit_denies_immediately(backend, algo):
    clock = ManualClock(T0)
    lim = create_limiter(Config(algorithm=algo, limit=10, window=60.0),
                         backend=backend, clock=clock)
    assert lim.allow_n("k", 4).allowed
    lim.update_limit(4)
    assert not lim.allow("k").allowed       # 4 of 4 used
    assert lim.allow_n("fresh", 4).allowed  # new keys get the new limit
    assert not lim.allow("fresh").allowed
    lim.close()


@pytest.mark.parametrize("backend", ["exact", "dense", "sketch"])
def test_token_bucket_rate_and_capacity_change(backend):
    # Consumption-stands contract: after spending 10 of 10, raising the
    # limit to 20 leaves 10 immediately spendable (consumed 10 of 20),
    # and the refill rate doubles (limit/window) from now on.
    clock = ManualClock(T0)
    lim = create_limiter(
        Config(algorithm=Algorithm.TOKEN_BUCKET, limit=10, window=10.0),
        backend=backend, clock=clock)
    assert lim.allow_n("k", 10).allowed
    assert not lim.allow("k").allowed
    lim.update_limit(20)  # rate 1/s -> 2/s; capacity 20
    assert lim.allow_n("k", 10).allowed     # the raised headroom
    assert not lim.allow("k").allowed
    clock.advance(1.0)
    assert lim.allow_n("k", 2).allowed      # 2 tokens in 1 s at the new rate
    assert not lim.allow("k").allowed
    lim.close()


def test_token_bucket_lower_below_consumption_recovers_identically():
    """Lowering a TB limit BELOW already-spent consumption: every backend
    must clamp to the new capacity (debt form == token form) so recovery
    takes new_cap/new_rate seconds everywhere, not old-debt/new_rate."""
    results = {}
    for backend in ("exact", "dense", "sketch"):
        clock = ManualClock(T0)
        lim = create_limiter(
            Config(algorithm=Algorithm.TOKEN_BUCKET, limit=10, window=10.0),
            backend=backend, clock=clock)
        assert lim.allow_n("k", 10).allowed     # spend the full bucket
        lim.update_limit(2)                     # rate 1/s -> 0.2/s; cap 2
        trace = []
        for _ in range(12):
            clock.advance(1.0)
            trace.append(lim.allow("k").allowed)
        results[backend] = trace
        lim.close()
    assert results["exact"] == results["dense"] == results["sketch"]
    # cap 2, rate 0.2/s from a clamped-empty bucket: first token at 5 s.
    assert results["exact"][:5] == [False] * 4 + [True]


def test_result_limit_field_reflects_update():
    lim = create_limiter(
        Config(algorithm=Algorithm.SLIDING_WINDOW, limit=5, window=60.0),
        backend="exact", clock=ManualClock(T0))
    assert lim.allow("k").limit == 5
    lim.update_limit(7)
    assert lim.allow("k").limit == 7
    lim.close()


def test_invalid_limit_rejected_state_intact():
    lim = create_limiter(
        Config(algorithm=Algorithm.SLIDING_WINDOW, limit=3, window=60.0),
        backend="sketch", clock=ManualClock(T0))
    assert lim.allow_n("k", 3).allowed
    with pytest.raises(InvalidConfigError):
        lim.update_limit(0)
    with pytest.raises(InvalidConfigError):
        lim.update_limit(1 << 24)  # sketch gate
    assert lim.config.limit == 3
    assert not lim.allow("k").allowed  # state untouched by failed updates
    lim.close()


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_mesh_update_limit():
    from ratelimiter_tpu.parallel import MeshSketchLimiter, MeshTokenBucketLimiter, make_mesh

    mesh = make_mesh(n_devices=8)
    cfg = Config(algorithm=Algorithm.TPU_SKETCH, limit=10, window=60.0,
                 sketch=SketchParams(depth=2, width=256, sub_windows=6))
    lim = MeshSketchLimiter(cfg, ManualClock(T0), mesh=mesh, merge="gather")
    out = lim.allow_batch(["hot"] * 32)
    assert out.allow_count == 10
    lim.update_limit(20)
    out = lim.allow_batch(["hot"] * 32)
    assert out.allow_count == 10  # 10 more under the raised limit
    lim.close()

    cfg_tb = Config(algorithm=Algorithm.TOKEN_BUCKET, limit=10, window=10.0,
                    sketch=SketchParams(depth=2, width=256))
    lim = MeshTokenBucketLimiter(cfg_tb, ManualClock(T0), mesh=mesh)
    assert lim.allow_batch(["k"] * 16).allow_count == 10
    lim.update_limit(16)
    assert lim.allow_batch(["k"] * 16).allow_count == 6
    lim.close()


def test_checkpoint_fingerprint_tracks_updated_limit(tmp_path):
    # A snapshot taken after update_limit restores only into a limiter
    # configured with the NEW limit.
    path = str(tmp_path / "snap.npz")
    cfg5 = Config(algorithm=Algorithm.TPU_SKETCH, limit=5, window=60.0)
    lim = create_limiter(cfg5, backend="sketch", clock=ManualClock(T0))
    lim.update_limit(9)
    lim.allow_n("k", 9)
    lim.save(path)
    lim.close()

    from ratelimiter_tpu import CheckpointError

    wrong = create_limiter(cfg5, backend="sketch", clock=ManualClock(T0))
    with pytest.raises(CheckpointError):
        wrong.restore(path)
    wrong.close()
    cfg9 = Config(algorithm=Algorithm.TPU_SKETCH, limit=9, window=60.0)
    right = create_limiter(cfg9, backend="sketch", clock=ManualClock(T0))
    right.restore(path)
    assert not right.allow("k").allowed
    right.close()
