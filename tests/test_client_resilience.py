"""Client resilience + deadline propagation (ADR-015).

Covers the PR 8 client contract: separate connect vs per-call read
timeouts (the old single ``timeout`` knob silently bounded both), typed
mid-stream timeouts that name the pending request and NEVER let the
next call read the stale frame as its own result, bounded full-jitter
retries with automatic reconnect, per-call deadlines that bound the
retry loop AND ride the wire, and the protocol's deadline extension
itself (composition with the trace extension, shedding at both doors).
"""

import asyncio
import os
import socket
import sys
import threading
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ratelimiter_tpu.core.errors import (
    DeadlineExceededError,
    RequestTimeoutError,
)
from ratelimiter_tpu.core.types import Result
from ratelimiter_tpu.serving import protocol as p
from ratelimiter_tpu.serving.client import AsyncClient, Client, _jitter_delay

T0 = 1_700_000_000.0


def _result_frame(req_id: int, allowed=True) -> bytes:
    return p.encode_result(req_id, Result(
        allowed=allowed, limit=10, remaining=5, retry_after=0.0,
        reset_at=T0, fail_open=False))


class _ScriptedServer:
    """Minimal frame server driven by a per-request handler — the
    misbehavior harness (slow responses, dropped connections) the real
    doors would never exhibit on purpose."""

    def __init__(self, handler):
        self.handler = handler
        self.sock = socket.socket()
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(8)
        self.port = self.sock.getsockname()[1]
        self.connections = 0
        self._stop = False
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while not self._stop:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            self.connections += 1
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        buf = b""
        try:
            while True:
                while len(buf) < p.HEADER_SIZE:
                    chunk = conn.recv(65536)
                    if not chunk:
                        return
                    buf += chunk
                length, type_, rid = p.parse_header(buf[:p.HEADER_SIZE],
                                                    allow_dcn=True)
                while len(buf) < 4 + length:
                    chunk = conn.recv(65536)
                    if not chunk:
                        return
                    buf += chunk
                body = buf[p.HEADER_SIZE:4 + length]
                buf = buf[4 + length:]
                out = self.handler(type_, rid, body, conn)
                if out is not None:
                    conn.sendall(out)
        except OSError:
            pass
        finally:
            conn.close()

    def close(self):
        self._stop = True
        try:
            self.sock.close()
        except OSError:
            pass


# ------------------------------------------------- satellite 2: timeouts


class TestSeparateTimeouts:
    def test_connect_timeout_is_not_the_read_timeout(self):
        srv = _ScriptedServer(lambda t, rid, b, c: _result_frame(rid))
        try:
            c = Client(port=srv.port, connect_timeout=5.0,
                       call_timeout=0.75, retries=0)
            assert c._sock.gettimeout() == pytest.approx(0.75)
            assert c.allow("k").allowed
            c.close()
        finally:
            srv.close()

    def test_midstream_timeout_is_typed_and_names_the_request(self):
        answered = []

        def handler(type_, rid, body, conn):
            if not answered:
                answered.append(rid)
                return None  # swallow the first request forever
            return _result_frame(rid)

        srv = _ScriptedServer(handler)
        try:
            c = Client(port=srv.port, call_timeout=0.3, retries=0)
            with pytest.raises(RequestTimeoutError) as ei:
                c.allow("k")
            assert ei.value.request_id == 1
            assert ei.value.request_type == p.T_ALLOW_N
            assert c.desynced
            c.close()
        finally:
            srv.close()

    def test_next_call_after_timeout_never_returns_wrong_frames_result(self):
        """The pre-PR-8 failure mode: request 1 times out, its response
        arrives late, request 2 reads it as its own. The client must
        reconnect (or resync) instead."""
        lock = threading.Lock()
        state = {"first": None}

        def handler(type_, rid, body, conn):
            with lock:
                if state["first"] is None:
                    state["first"] = (rid, conn)

                    def late():
                        time.sleep(0.6)
                        try:
                            # The STALE answer: allowed=False so reading
                            # it as request 2's result is detectable.
                            conn.sendall(_result_frame(rid, allowed=False))
                        except OSError:
                            pass

                    threading.Thread(target=late, daemon=True).start()
                    return None
            return _result_frame(rid, allowed=True)

        srv = _ScriptedServer(handler)
        try:
            c = Client(port=srv.port, call_timeout=0.25, retries=0)
            with pytest.raises(RequestTimeoutError):
                c.allow("k")
            # Second call: must come back with ITS OWN (allowed=True)
            # result, never the stale allowed=False frame.
            res = c.allow("k2")
            assert res.allowed is True
            assert srv.connections == 2, "client must have reconnected"
            c.close()
        finally:
            srv.close()


# ---------------------------------------------------- retries + backoff


class TestRetries:
    def test_connection_error_retries_with_reconnect(self):
        calls = []

        def handler(type_, rid, body, conn):
            calls.append(rid)
            if len(calls) == 1:
                conn.close()  # first request: connection dies mid-call
                return None
            return _result_frame(rid)

        srv = _ScriptedServer(handler)
        try:
            c = Client(port=srv.port, retries=2, backoff=0.01,
                       call_timeout=5.0)
            assert c.allow("k").allowed
            assert srv.connections >= 2
            c.close()
        finally:
            srv.close()

    def test_retries_exhaust_to_the_underlying_error(self):
        srv = _ScriptedServer(lambda t, rid, b, conn: conn.close())
        try:
            c = Client(port=srv.port, retries=1, backoff=0.01,
                       call_timeout=5.0)
            with pytest.raises((ConnectionError, OSError)):
                c.allow("k")
            c.close()
        finally:
            srv.close()

    def test_midstream_timeout_is_never_auto_retried(self):
        seen = []
        srv = _ScriptedServer(
            lambda t, rid, b, conn: seen.append(rid))  # answer nothing
        try:
            c = Client(port=srv.port, call_timeout=0.2, retries=5)
            with pytest.raises(RequestTimeoutError):
                c.allow("k")
            time.sleep(0.1)
            # Exactly ONE send: a retried decision could double-spend
            # quota server-side.
            assert len(seen) == 1
            c.close()
        finally:
            srv.close()

    def test_full_jitter_backoff_is_bounded(self):
        for attempt in range(8):
            for _ in range(50):
                d = _jitter_delay(attempt, 0.05, 2.0)
                assert 0.0 <= d <= min(2.0, 0.05 * 2 ** attempt)


# ------------------------------------------------------------ deadlines


class TestClientDeadlines:
    def test_deadline_bounds_the_whole_call(self):
        srv = _ScriptedServer(lambda t, rid, b, conn: None)  # black hole
        try:
            c = Client(port=srv.port, call_timeout=30.0, retries=0)
            t0 = time.perf_counter()
            with pytest.raises((RequestTimeoutError,
                                DeadlineExceededError)):
                c.allow("k", deadline=0.4)
            assert time.perf_counter() - t0 < 2.0
            c.close()
        finally:
            srv.close()

    def test_deadline_rides_the_wire(self):
        got = {}

        def handler(type_, rid, body, conn):
            base, tid, budget, rest = p.split_request(type_, body)
            got.update(type=base, trace=tid, budget=budget)
            return _result_frame(rid)

        srv = _ScriptedServer(handler)
        try:
            c = Client(port=srv.port, retries=0)
            c.allow("k", deadline=1.5, trace_id=42)
            assert got["type"] == p.T_ALLOW_N
            assert got["trace"] == 42
            assert 0.0 < got["budget"] <= 1.5
            c.close()
        finally:
            srv.close()

    def test_expired_deadline_fails_before_send(self):
        srv = _ScriptedServer(lambda t, rid, b, c_: _result_frame(rid))
        try:
            c = Client(port=srv.port, retries=0)
            with pytest.raises(DeadlineExceededError):
                c.allow("k", deadline=-0.1)
            c.close()
        finally:
            srv.close()


class TestAsyncClientResilience:
    def test_reconnect_after_connection_loss(self):
        calls = []

        def handler(type_, rid, body, conn):
            calls.append(rid)
            if len(calls) == 1:
                conn.close()
                return None
            return _result_frame(rid)

        srv = _ScriptedServer(handler)

        async def main():
            c = await AsyncClient.connect(port=srv.port, retries=2,
                                          backoff=0.01)
            res = await c.allow("k")
            assert res.allowed
            await c.close()

        try:
            asyncio.run(main())
            assert srv.connections >= 2
        finally:
            srv.close()

    def test_deadline_bounds_wait_and_rides_wire(self):
        got = {}

        def handler(type_, rid, body, conn):
            base, tid, budget, rest = p.split_request(type_, body)
            got["budget"] = budget
            return None  # never answer

        srv = _ScriptedServer(handler)

        async def main():
            c = await AsyncClient.connect(port=srv.port, retries=0)
            t0 = time.perf_counter()
            with pytest.raises(DeadlineExceededError):
                await c.allow("k", deadline=0.3)
            assert time.perf_counter() - t0 < 2.0
            await c.close()

        try:
            asyncio.run(main())
            assert 0.0 < got["budget"] <= 0.3
        finally:
            srv.close()


# ------------------------------------------------- protocol extension


class TestDeadlineExtension:
    def test_roundtrip_and_composition_with_trace(self):
        frame = p.encode_allow_n(7, "key", 3)
        stamped = p.with_trace(p.with_deadline(frame, 2.5), 99)
        length, type_, rid = p.parse_header(stamped[:p.HEADER_SIZE])
        assert rid == 7
        assert type_ & p.TRACE_FLAG and type_ & p.DEADLINE_FLAG
        base, tid, budget, body = p.split_request(
            type_, stamped[p.HEADER_SIZE:])
        assert base == p.T_ALLOW_N
        assert tid == 99
        assert budget == pytest.approx(2.5)
        assert p.parse_allow_n(body) == ("key", 3)

    def test_deadline_alone(self):
        frame = p.with_deadline(p.encode_allow_n(1, "k", 1), 0.25)
        length, type_, rid = p.parse_header(frame[:p.HEADER_SIZE])
        base, tid, budget, body = p.split_request(
            type_, frame[p.HEADER_SIZE:])
        assert (base, tid) == (p.T_ALLOW_N, 0)
        assert budget == pytest.approx(0.25)

    def test_unflagged_frames_report_no_deadline(self):
        frame = p.encode_allow_n(1, "k", 1)
        _, type_, _ = p.parse_header(frame[:p.HEADER_SIZE])
        base, tid, budget, body = p.split_request(
            type_, frame[p.HEADER_SIZE:])
        assert budget is None

    def test_responses_cannot_carry_extensions(self):
        res = _result_frame(1)
        with pytest.raises(p.ProtocolError):
            p.with_deadline(res, 1.0)
        with pytest.raises(p.ProtocolError):
            p.with_trace(res, 1)

    def test_deadline_must_precede_trace(self):
        frame = p.with_trace(p.encode_allow_n(1, "k", 1), 5)
        with pytest.raises(p.ProtocolError):
            p.with_deadline(frame, 1.0)

    def test_error_code_maps_to_typed_exception(self):
        assert p.code_for(DeadlineExceededError("x")) == p.E_DEADLINE
        exc = p.exception_for(p.E_DEADLINE, "expired")
        assert isinstance(exc, DeadlineExceededError)
