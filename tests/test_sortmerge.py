"""Sort-merge table access vs direct indexing: equivalence on CPU.

The sort-merge branch of ops/sortmerge.py only activates on TPU
(_use_sortmerge returns False elsewhere), so without these tests the code
path the headline throughput number rests on would be executed by zero
tests (round-1 ADVICE item 5 / round-2 VERDICT weak #2). Here the strategy
switch is monkeypatched both ways and the two implementations are asserted
bit-equal on the same inputs, including the adversarial shapes: empty
columns, every-request-on-one-column, boundary columns 0 and w-1, B far
smaller and far larger than w, and random fuzz.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax.numpy as jnp

from ratelimiter_tpu.ops import sortmerge


@pytest.fixture
def force_sortmerge(monkeypatch):
    monkeypatch.setattr(sortmerge, "_use_sortmerge", lambda B, w: True)


@pytest.fixture
def force_direct(monkeypatch):
    monkeypatch.setattr(sortmerge, "_use_sortmerge", lambda B, w: False)


def _cases():
    rng = np.random.default_rng(7)
    cases = []
    # (w, cols) — the column patterns that stress the mix/unmix sorts.
    for w in (16, 64, 128):
        cases.append((w, np.zeros(8, np.int32)))                    # all col 0
        cases.append((w, np.full(8, w - 1, np.int32)))              # all col w-1
        cases.append((w, np.array([0, w - 1] * 8, np.int32)))       # boundary mix
        cases.append((w, rng.integers(0, w, size=4).astype(np.int32)))   # B << w
        cases.append((w, rng.integers(0, w, size=4 * w).astype(np.int32)))  # B >> w
        cases.append((w, np.arange(min(8, w), dtype=np.int32)))     # distinct
        # duplicates of a few columns, many columns empty
        cases.append((w, np.repeat(rng.integers(0, w, size=3), 5).astype(np.int32)))
    return cases


@pytest.mark.parametrize("w,cols", _cases())
def test_row_gather_matches_direct(w, cols, monkeypatch):
    rng = np.random.default_rng(int(w) + len(cols))
    rows = [jnp.asarray(rng.integers(0, 1000, size=w).astype(np.int32)),
            jnp.asarray(rng.integers(0, 1 << 20, size=w).astype(np.int32))]
    col = jnp.asarray(cols)

    monkeypatch.setattr(sortmerge, "_use_sortmerge", lambda B, w_: False)
    direct = sortmerge.row_gather(rows, col)
    monkeypatch.setattr(sortmerge, "_use_sortmerge", lambda B, w_: True)
    merged = sortmerge.row_gather(rows, col)

    for d, m in zip(direct, merged):
        np.testing.assert_array_equal(np.asarray(d), np.asarray(m))


@pytest.mark.parametrize("w,cols", _cases())
def test_row_histogram_matches_direct(w, cols, monkeypatch):
    rng = np.random.default_rng(2 * int(w) + len(cols))
    add = jnp.asarray(rng.integers(0, 50, size=len(cols)).astype(np.int32))
    col = jnp.asarray(cols)

    monkeypatch.setattr(sortmerge, "_use_sortmerge", lambda B, w_: False)
    direct = sortmerge.row_histogram(col, add, w)
    monkeypatch.setattr(sortmerge, "_use_sortmerge", lambda B, w_: True)
    merged = sortmerge.row_histogram(col, add, w)

    np.testing.assert_array_equal(np.asarray(direct), np.asarray(merged))
    # Also against a NumPy oracle: empty columns must be exactly zero.
    oracle = np.bincount(cols, weights=np.asarray(add), minlength=w).astype(np.int32)
    np.testing.assert_array_equal(np.asarray(merged), oracle)


@pytest.mark.parametrize("w,cols", _cases())
def test_row_histogram_max_matches_direct(w, cols, monkeypatch):
    rng = np.random.default_rng(3 * int(w) + len(cols))
    # Non-negative f32 with deliberate ties (the doc contract).
    val = jnp.asarray(rng.integers(0, 8, size=len(cols)).astype(np.float32))
    col = jnp.asarray(cols)

    monkeypatch.setattr(sortmerge, "_use_sortmerge", lambda B, w_: False)
    direct = sortmerge.row_histogram_max(col, val, w)
    monkeypatch.setattr(sortmerge, "_use_sortmerge", lambda B, w_: True)
    merged = sortmerge.row_histogram_max(col, val, w)

    np.testing.assert_array_equal(np.asarray(direct), np.asarray(merged))
    oracle = np.zeros(w, np.float32)
    np.maximum.at(oracle, cols, np.asarray(val))
    np.testing.assert_array_equal(np.asarray(merged), oracle)


def test_row_gather_under_jit(force_sortmerge):
    """The sort-merge path must trace cleanly under jit (the way the sketch
    kernels actually consume it)."""
    import jax

    w, B = 64, 32
    rng = np.random.default_rng(0)
    row = jnp.asarray(rng.integers(0, 100, size=w).astype(np.int32))
    col = jnp.asarray(rng.integers(0, w, size=B).astype(np.int32))

    @jax.jit
    def f(r, c):
        (out,) = sortmerge.row_gather((r,), c)
        return out

    np.testing.assert_array_equal(np.asarray(f(row, col)),
                                  np.asarray(row)[np.asarray(col)])


def test_full_sketch_step_with_forced_sortmerge(force_sortmerge):
    """End-to-end guard: a SketchLimiter decision sequence produces identical
    admissions with the sort-merge path forced on — catching any wrong unmix
    key that would silently corrupt counts only on TPU."""
    from ratelimiter_tpu.algorithms.sketch import SketchLimiter
    from ratelimiter_tpu.core.clock import ManualClock
    from ratelimiter_tpu.core.config import Config, SketchParams
    from ratelimiter_tpu.core.types import Algorithm
    from ratelimiter_tpu.ops import sketch_kernels

    # build_steps memoizes per-config; use a geometry unique to this test so
    # the cached kernel was traced with the forced strategy.
    cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=5, window=6.0,
                 key_prefix="sm",
                 sketch=SketchParams(depth=3, width=32, sub_windows=6,
                                     conservative_update=True))
    lim = SketchLimiter(cfg, ManualClock(1_000_000.0))
    out = lim.allow_batch(["a"] * 8 + ["b"] * 3)
    assert int(out.allowed[:8].sum()) == 5        # greedy within batch
    assert bool(out.allowed[8:].all())            # b under limit
    lim.clock.advance(1.0)
    again = lim.allow_batch(["a", "b"])
    assert not bool(again.allowed[0])             # a exhausted
    lim.close()
