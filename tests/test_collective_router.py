"""Collective mesh router parity suite (ADR-024).

``MeshSpec.router="collective"`` makes a mixed frame ONE shard_map'd
SPMD dispatch: owners computed on device (same ``h64 % n`` rule as the
host router), rows binned and routed with ``jax.lax.all_to_all``, the
existing fused decision kernels run on owned rows, results all_to_all'd
back to source order. The load-bearing invariant mirrors ADR-013's:
changing the ROUTING must never change the DECISIONS — pinned here
bit-for-bit against the host-routed sliced oracle for mixed and affine
frames, across sub-window rollovers, under policy overrides and the
hierarchy cascade, on the token-bucket backend, and through the raw-id
wire lane. The overflow fallback (capacity-1 bins via bin_headroom < 1)
must re-dispatch through the host router with no admission mass lost or
duplicated, and ``--quarantine`` must be refused loudly (a collective
dispatch is one mesh-wide execution — per-slice failure domains cannot
contain it). CI runs this file in the explicit 8-virtual-device mesh
lane with zero skips allowed (ci.yml); ``make test-collective`` runs it
locally.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax

from ratelimiter_tpu import (
    Algorithm,
    Config,
    ManualClock,
    SketchParams,
    create_limiter,
)
from ratelimiter_tpu.core.config import HierarchySpec, MeshSpec
from ratelimiter_tpu.core.errors import InvalidConfigError
from ratelimiter_tpu.ops.route_kernels import bin_capacity
from ratelimiter_tpu.parallel.collective import CollectiveMeshLimiter
from ratelimiter_tpu.parallel.limiter import SlicedMeshLimiter

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 4,
    reason="collective router tests need >= 4 devices "
           "(XLA_FLAGS=--xla_force_host_platform_device_count=8)")

T0 = 1_700_000_000.0


def _cfg(router: str, *, algo=Algorithm.SLIDING_WINDOW, limit=10,
         devices=8, headroom=2.0, hier=None, **kw) -> Config:
    return Config(
        algorithm=algo, limit=limit, window=60.0,
        sketch=SketchParams(depth=2, width=1 << 10, sub_windows=6),
        mesh=MeshSpec(devices=devices, router=router,
                      bin_headroom=headroom),
        hierarchy=hier or HierarchySpec(),
        **kw)


def _pair(router_cfg_kw=None, **kw):
    """(host-routed oracle, collective) on identical configs/clocks."""
    ckw = dict(kw)
    ckw.update(router_cfg_kw or {})
    host = create_limiter(_cfg("host", **kw), backend="mesh",
                          clock=ManualClock(T0))
    coll = create_limiter(_cfg("collective", **ckw), backend="mesh",
                          clock=ManualClock(T0))
    assert isinstance(coll, CollectiveMeshLimiter)
    assert isinstance(host, SlicedMeshLimiter)
    assert not isinstance(host, CollectiveMeshLimiter)
    return host, coll


def _assert_equal(rh, rc, *, i=None):
    for col in ("allowed", "remaining", "retry_after", "reset_at"):
        np.testing.assert_array_equal(
            getattr(rh, col), getattr(rc, col),
            err_msg=f"{col} diverged (frame {i})")
    if rh.limits is None:
        assert rc.limits is None
    else:
        np.testing.assert_array_equal(rh.limits, rc.limits)


# ------------------------------------------------------------ parity


class TestDecisionParity:
    def test_mixed_frames_bit_identical(self):
        """Random mixed frames (every frame spans many owners, weighted
        costs, duplicate keys) — the collective all_to_all path must be
        bit-identical to the host-routed oracle, with zero overflow
        fallbacks at the default headroom."""
        host, coll = _pair()
        rng = np.random.default_rng(0)
        try:
            for i in range(12):
                b = int(rng.integers(1, 400))
                h = rng.integers(0, 1 << 64, size=b, dtype=np.uint64)
                ns = rng.integers(1, 4, size=b).astype(np.int64)
                now = T0 + i * 0.5
                _assert_equal(host.allow_hashed(h, ns, now=now),
                              coll.allow_hashed(h, ns, now=now), i=i)
            assert coll.fallbacks == 0
            assert coll.router_stats() == {"mode": "collective",
                                           "fallbacks": 0}
        finally:
            host.close()
            coll.close()

    def test_affine_frames_bit_identical(self):
        """Single-owner frames (the consistent-hash-LB shape): the host
        router passes them through unsplit; the collective router still
        runs the full all_to_all step — decisions must agree anyway."""
        host, coll = _pair()
        try:
            all_ids = np.arange(1, 1 << 12, dtype=np.uint64)
            h = all_ids[host.owner_of_hash(all_ids) == 3][:64]
            assert len(h) == 64
            for i in range(4):
                now = T0 + i * 1.0
                _assert_equal(host.allow_hashed(h, now=now),
                              coll.allow_hashed(h, now=now), i=i)
            assert coll.fallbacks == 0
        finally:
            host.close()
            coll.close()

    def test_in_batch_same_key_sequencing(self):
        """A frame holding one key limit+5 times: exactly ``limit``
        admits, in FRAME ORDER — the bit-identity linchpin (the return
        route's stable compaction preserves global frame order)."""
        host, coll = _pair()
        try:
            h = np.full(15, 0xDEAD_BEEF_F00D, dtype=np.uint64)
            rh = host.allow_hashed(h, now=T0)
            rc = coll.allow_hashed(h, now=T0)
            _assert_equal(rh, rc)
            assert rc.allowed.tolist() == [True] * 10 + [False] * 5
        finally:
            host.close()
            coll.close()

    def test_rollover_parity(self):
        """Frames straddling sub-window rollovers (window 60s / 6
        sub-windows = 10s each) and a full-window expiry: the device-side
        period sync must match the host router's."""
        host, coll = _pair()
        rng = np.random.default_rng(1)
        try:
            # 15s steps cross a 10s sub-window boundary every frame;
            # the last step jumps past the full window.
            for i, dt in enumerate([0.0, 15.0, 30.0, 45.0, 61.0, 125.0]):
                b = int(rng.integers(32, 200))
                h = rng.integers(0, 1 << 64, size=b, dtype=np.uint64)
                now = T0 + dt
                _assert_equal(host.allow_hashed(h, now=now),
                              coll.allow_hashed(h, now=now), i=i)
        finally:
            host.close()
            coll.close()

    def test_token_bucket_parity(self):
        host, coll = _pair(algo=Algorithm.TOKEN_BUCKET)
        rng = np.random.default_rng(2)
        try:
            for i in range(8):
                b = int(rng.integers(1, 300))
                h = rng.integers(0, 1 << 64, size=b, dtype=np.uint64)
                now = T0 + i * 0.5
                _assert_equal(host.allow_hashed(h, now=now),
                              coll.allow_hashed(h, now=now), i=i)
        finally:
            host.close()
            coll.close()

    def test_policy_override_parity(self):
        """Per-key overrides ride the mesh-replicated policy table; the
        overridden keys' decisions AND the limits column must match."""
        host, coll = _pair()
        rng = np.random.default_rng(3)
        try:
            keys = ["vip-a", "vip-b", "cheap", "fast"]
            for key, lim in zip(keys, (2, 50, 1, 25)):
                for m in (host, coll):
                    m.set_override(key, lim)
            special = np.asarray(host._hash(keys), dtype=np.uint64)
            assert np.array_equal(special, coll._hash(keys))
            for i in range(6):
                b = int(rng.integers(64, 256))
                h = rng.integers(0, 1 << 64, size=b, dtype=np.uint64)
                h[: len(special)] = special  # overridden keys up front
                now = T0 + i * 0.5
                rh = host.allow_hashed(h, now=now)
                rc = coll.allow_hashed(h, now=now)
                _assert_equal(rh, rc, i=i)
                assert rh.limits is not None
        finally:
            host.close()
            coll.close()

    def test_hierarchy_cascade_parity(self):
        hier = HierarchySpec(tenants=4, global_limit=300)
        host, coll = _pair(hier=hier)
        rng = np.random.default_rng(4)
        try:
            for i in range(6):
                b = int(rng.integers(64, 400))
                h = rng.integers(0, 1 << 64, size=b, dtype=np.uint64)
                now = T0 + i * 0.5
                _assert_equal(host.allow_hashed(h, now=now),
                              coll.allow_hashed(h, now=now), i=i)
        finally:
            host.close()
            coll.close()

    def test_wire_lane_parity(self):
        """Raw-id premix lane with device packing requested: decisions
        and the packed wire buffers must match the host router's
        scatter-rebuilt packing."""
        host, coll = _pair()
        rng = np.random.default_rng(5)
        try:
            ids = rng.integers(0, 1 << 62, size=128, dtype=np.uint64)
            rh = host.resolve(host.launch_ids(ids, now=T0, wire=True))
            rc = coll.resolve(coll.launch_ids(ids, now=T0, wire=True))
            _assert_equal(rh, rc)
            assert rc.wire_packed is not None
            assert rh.wire_packed is not None
            pb_h, words_h, bh = rh.wire_packed
            pb_c, words_c, bc = rc.wire_packed
            assert bh == bc
            np.testing.assert_array_equal(np.asarray(pb_h),
                                          np.asarray(pb_c))
            np.testing.assert_array_equal(np.asarray(words_h),
                                          np.asarray(words_c))
        finally:
            host.close()
            coll.close()


# -------------------------------------------------- overflow fallback


class TestOverflowFallback:
    def test_capacity_one_bins_fall_back_bit_identically(self):
        """bin_headroom < 1 forces capacity-1 bins, so any frame with
        two same-owner rows on one source shard overflows. The frame
        must fall back to the host router with decisions STILL
        bit-identical — admission applied exactly once (the device step
        leaves state untouched on overflow; the fallback re-dispatches
        the original arrays)."""
        host, coll = _pair(router_cfg_kw={"headroom": 0.001})
        rng = np.random.default_rng(6)
        try:
            for i in range(6):
                b = int(rng.integers(64, 300))
                h = rng.integers(0, 1 << 64, size=b, dtype=np.uint64)
                ns = rng.integers(1, 4, size=b).astype(np.int64)
                now = T0 + i * 0.5
                _assert_equal(host.allow_hashed(h, ns, now=now),
                              coll.allow_hashed(h, ns, now=now), i=i)
            assert coll.fallbacks > 0
            assert coll.router_stats()["fallbacks"] == coll.fallbacks
        finally:
            host.close()
            coll.close()

    def test_no_lost_or_duplicated_admission_mass(self):
        """Exactly-once through the fallback, pinned on totals: a hot
        key driven to its limit through overflowing frames admits
        exactly ``limit`` units — a double-apply would admit fewer on
        later frames, a dropped frame more."""
        _, coll = _pair(router_cfg_kw={"headroom": 0.001})
        try:
            hot = np.full(4, 0xF00D, dtype=np.uint64)
            admitted = 0
            for i in range(4):
                admitted += int(coll.allow_hashed(
                    hot, now=T0 + i * 0.01).allowed.sum())
            assert admitted == 10  # limit, exactly once
            assert coll.fallbacks > 0
        finally:
            coll.close()

    def test_bin_capacity_bounds(self):
        # headroom multiplier with the binomial-tail floor...
        assert bin_capacity(1024, 8, 2.0) == 256
        # ...the tail bound dominating a thin multiplier at mid sizes
        # (mean 4, 2x-mean = 8 measured overflowing ~20% of frames)...
        assert bin_capacity(32, 8, 2.0) > 8
        # ...the flat floor on small shards, clamped to the shard...
        assert bin_capacity(8, 8, 2.0) == 8
        assert bin_capacity(4, 8, 8.0) == 4   # never above L
        # ...and headroom < 1 skipping every floor (the fallback lever).
        assert bin_capacity(64, 8, 0.001) == 1


# ---------------------------------------------- snapshot during flight


class TestSnapshotDuringInflight:
    def test_capture_quiesces_inflight_collective_dispatches(self, tmp_path):
        """save() with collective tickets un-resolved must reflect every
        LAUNCHED dispatch (quiescence by data dependence — the routed
        step commits its write-back at launch): restoring reproduces the
        post-launch counters exactly, matching the ADR-013 scatter-gather
        contract."""
        cfg = _cfg("collective", devices=4)
        coll = create_limiter(cfg, backend="mesh", clock=ManualClock(T0))
        try:
            hot = np.full(4, 0xF00D, dtype=np.uint64)
            t1 = coll.launch_ids(np.concatenate([hot, hot]), now=T0)
            t2 = coll.launch_ids(hot, now=T0)
            path = str(tmp_path / "mid.npz")
            coll.save(path)  # both windows still un-resolved
            assert coll.resolve(t1).allowed.tolist() == [True] * 8
            assert coll.resolve(t2).allowed.tolist() == [True, True,
                                                         False, False]
            restored = create_limiter(cfg, backend="mesh",
                                      clock=ManualClock(T0))
            try:
                restored.restore(path)
                # 12 units offered pre-snapshot, limit 10: nothing left.
                assert restored.allow_ids(
                    hot, now=T0).allowed.tolist() == [False] * 4
            finally:
                restored.close()
        finally:
            coll.close()

    def test_restore_round_trip_parity(self, tmp_path):
        """Snapshot taken by the collective mesh restores into a fresh
        collective mesh with decisions matching the host-routed oracle
        restored from ITS own snapshot of the same history."""
        host, coll = _pair()
        rng = np.random.default_rng(7)
        h = rng.integers(0, 1 << 64, size=200, dtype=np.uint64)
        try:
            host.allow_hashed(h, now=T0)
            coll.allow_hashed(h, now=T0)
            ph = str(tmp_path / "host.npz")
            pc = str(tmp_path / "coll.npz")
            host.save(ph)
            coll.save(pc)
            host2 = create_limiter(_cfg("host"), backend="mesh",
                                   clock=ManualClock(T0))
            coll2 = create_limiter(_cfg("collective"), backend="mesh",
                                   clock=ManualClock(T0))
            try:
                host2.restore(ph)
                coll2.restore(pc)
                _assert_equal(host2.allow_hashed(h, now=T0 + 1.0),
                              coll2.allow_hashed(h, now=T0 + 1.0))
            finally:
                host2.close()
                coll2.close()
        finally:
            host.close()
            coll.close()


# ----------------------------------------------------- config refusal


class TestQuarantineRefusal:
    def test_config_refuses_collective_plus_quarantine(self):
        with pytest.raises(InvalidConfigError, match="blast radius"):
            create_limiter(
                Config(algorithm=Algorithm.SLIDING_WINDOW, limit=10,
                       window=60.0,
                       sketch=SketchParams(depth=2, width=1 << 10),
                       mesh=MeshSpec(devices=4, router="collective",
                                     quarantine=True)),
                backend="mesh", clock=ManualClock(T0))

    def test_config_refuses_unknown_router(self):
        with pytest.raises(InvalidConfigError, match="router"):
            create_limiter(
                Config(algorithm=Algorithm.SLIDING_WINDOW, limit=10,
                       window=60.0,
                       sketch=SketchParams(depth=2, width=1 << 10),
                       mesh=MeshSpec(devices=4, router="p2p")),
                backend="mesh", clock=ManualClock(T0))

    def test_cli_refuses_collective_plus_quarantine(self):
        """The serving binary's loud SystemExit — refused at argument
        validation, before any device work."""
        import os
        import subprocess
        import sys

        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        out = subprocess.run(
            [sys.executable, "-m", "ratelimiter_tpu.serving",
             "--backend", "mesh", "--router", "collective",
             "--quarantine", "--port", "1"],
            capture_output=True, text=True, timeout=120, env=env)
        assert out.returncode != 0
        assert "blast radius" in (out.stderr + out.stdout)
