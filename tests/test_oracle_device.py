"""The on-device exact oracle (evaluation/oracle_device.py) must itself be
correct — it referees the headline accuracy metric. Its semantics: exact
per-key sliding window at sub-window resolution, identical time
discretization to the sketch, zero collision error."""

import numpy as np

import jax.numpy as jnp

from ratelimiter_tpu import Algorithm, Config, SketchParams
from ratelimiter_tpu.evaluation.oracle_device import (
    build_eval_chunk,
    build_oracle_rollover,
    init_oracle_state,
    oracle_geometry,
)
from ratelimiter_tpu.ops import sketch_kernels

T0 = 1_700_000_000 * 1_000_000


def _cfg(limit=5, window=6.0):
    return Config(algorithm=Algorithm.SLIDING_WINDOW, limit=limit, window=window,
                  max_batch_admission_iters=1,
                  sketch=SketchParams(depth=2, width=64, sub_windows=6))


def _oracle_step(cfg, n_keys):
    from functools import partial
    import jax

    return jax.jit(partial(sketch_kernels._sketch_step,
                           **oracle_geometry(cfg, n_keys)))


def _decide(step, st, ids, now_us, n_keys):
    h1 = jnp.asarray(np.asarray(ids, dtype=np.uint32))
    h2 = jnp.zeros(len(ids), jnp.uint32)
    n = jnp.ones(len(ids), jnp.int32)
    st, (allowed, _, _) = step(st, h1, h2, n, jnp.int64(now_us))
    return st, np.asarray(allowed)


def test_oracle_exact_per_key_admission():
    cfg = _cfg(limit=5)
    n_keys = 16
    step = _oracle_step(cfg, n_keys)
    roll = build_oracle_rollover(cfg, n_keys)
    st = roll(init_oracle_state(cfg, n_keys), jnp.int64(T0 // 1_000_000))
    # 8 requests each for keys 0 and 1 in one batch: exactly 5 admitted each,
    # the first 5 in batch order.
    ids = [0, 1] * 8
    st, allowed = _decide(step, st, ids, T0, n_keys)
    assert allowed.sum() == 10
    assert allowed[:10].all() and not allowed[10:].any()
    # Next batch: fully denied (no collision cross-talk for other keys).
    st, allowed = _decide(step, st, [0, 1, 2], T0 + 1000, n_keys)
    assert list(allowed) == [False, False, True]


def test_oracle_window_expiry():
    cfg = _cfg(limit=3, window=6.0)
    n_keys = 8
    step = _oracle_step(cfg, n_keys)
    roll = build_oracle_rollover(cfg, n_keys)
    sub_us = sketch_kernels.sketch_geometry(cfg)[1]
    st = roll(init_oracle_state(cfg, n_keys), jnp.int64(T0 // sub_us))
    st, allowed = _decide(step, st, [3, 3, 3, 3], T0, n_keys)
    assert allowed.sum() == 3
    # Two full windows later (host drives rollover, as the limiter does).
    t2 = T0 + 12_000_000
    st = roll(st, jnp.int64(t2 // sub_us))
    st, allowed = _decide(step, st, [3, 3, 3, 3], t2, n_keys)
    assert allowed.sum() == 3


def test_eval_chunk_counts_disagreements():
    """With sketch width == oracle width and identity-free hashing the
    sketch may err; the eval chunk's stats must tally exactly the
    disagreement masks. Force heavy sketch collisions (width 16) so false
    denies are certain, and check bookkeeping consistency."""
    cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=2, window=6.0,
                 max_batch_admission_iters=1,
                 sketch=SketchParams(depth=1, width=16, sub_windows=6))
    n_keys = 256
    B = 512
    chunk = build_eval_chunk(cfg, B, n_keys, 1.1)
    roll_sk = sketch_kernels.build_steps(cfg)[2]
    roll_or = build_oracle_rollover(cfg, n_keys)
    sub_us = sketch_kernels.sketch_geometry(cfg)[1]
    states = {"sk": roll_sk(sketch_kernels.init_state(cfg), jnp.int64(T0 // sub_us)),
              "or": roll_or(init_oracle_state(cfg, n_keys), jnp.int64(T0 // sub_us))}
    # Chunk 1 writes the state; collision errors surface in chunk 2 (cell
    # estimates are read pre-batch, so a single batch from empty state shows
    # no cross-key error).
    states, _ = chunk(states, jnp.uint64(0), jnp.int64(T0))
    states, stats = chunk(states, jnp.uint64(512), jnp.int64(T0 + 1000))
    fd, fa, sk_deny, or_deny = [int(np.asarray(s)) for s in stats]
    # Bookkeeping identities: disagreements bounded by deny counts.
    assert 0 <= fd <= sk_deny
    assert 0 <= fa <= or_deny
    # 16 cells shared by ~150 distinct Zipf keys at limit 2: fresh tail keys
    # read hot cells >= limit and must be falsely denied.
    assert fd > 0
    # Sketch never over-admits: anything the sketch allowed while the
    # oracle denied would be a real false allow; with depth 1 vanilla CU
    # disabled... it must stay 0 here (collisions only ADD counts).
    assert fa == 0
