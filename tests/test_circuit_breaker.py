"""Circuit breaker decorator: closed -> open -> half-open -> closed, with
virtual time (reference docs/ADR/002:170-197's planned state machine)."""

import pytest

from ratelimiter_tpu import (
    Algorithm,
    Config,
    ManualClock,
    StorageUnavailableError,
    create_limiter,
)
from ratelimiter_tpu.observability import CircuitBreakerDecorator, Registry


class _CountingLimiter:
    """Wraps a limiter counting backend touches (to prove the open state
    short-circuits)."""

    def __init__(self, inner):
        self._inner = inner
        self.calls = 0

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def allow_n(self, key, n, *, now=None):
        self.calls += 1
        return self._inner.allow_n(key, n, now=now)

    def allow_batch(self, keys, ns=None, *, now=None):
        self.calls += 1
        return self._inner.allow_batch(keys, ns, now=now)


def make(fail_open: bool):
    clock = ManualClock(1_700_000_000.0)
    cfg = Config(algorithm=Algorithm.TPU_SKETCH, limit=100, window=60.0,
                 fail_open=fail_open)
    inner = create_limiter(cfg, backend="sketch", clock=clock)
    counting = _CountingLimiter(inner)
    cb = CircuitBreakerDecorator(counting, failure_threshold=3, cooldown=5.0,
                                 registry=Registry())
    return cb, counting, inner, clock


class TestCircuitBreaker:
    def test_trips_after_threshold_and_short_circuits(self):
        cb, counting, inner, clock = make(fail_open=True)
        assert cb.allow("k").allowed and cb.state == "closed"
        inner.inject_failure()
        for _ in range(3):  # consecutive fail-open allowances trip it
            res = cb.allow("k")
            assert res.allowed and res.fail_open
        assert cb.state == "open"
        before = counting.calls
        for _ in range(10):  # open: backend untouched
            res = cb.allow("k")
            assert res.allowed and res.fail_open
        assert counting.calls == before
        cb.close()

    def test_half_open_probe_recovers(self):
        cb, counting, inner, clock = make(fail_open=True)
        inner.inject_failure()
        for _ in range(3):
            cb.allow("k")
        assert cb.state == "open"
        inner.heal()
        clock.advance(5.1)          # past the cooldown -> half-open probe
        res = cb.allow("k")
        assert res.allowed and not res.fail_open
        assert cb.state == "closed"
        # Fully back to normal: backend reached again.
        before = counting.calls
        cb.allow("k2")
        assert counting.calls == before + 1
        cb.close()

    def test_half_open_failure_reopens(self):
        cb, counting, inner, clock = make(fail_open=True)
        inner.inject_failure()
        for _ in range(3):
            cb.allow("k")
        clock.advance(5.1)
        res = cb.allow("k")          # probe fails (still injected)
        assert res.fail_open
        assert cb.state == "open"
        before = counting.calls
        cb.allow("k")                # short-circuited again
        assert counting.calls == before
        cb.close()

    def test_fail_closed_raises_without_backend(self):
        cb, counting, inner, clock = make(fail_open=False)
        inner.inject_failure()
        for _ in range(3):
            with pytest.raises(StorageUnavailableError):
                cb.allow("k")
        assert cb.state == "open"
        before = counting.calls
        with pytest.raises(StorageUnavailableError, match="circuit"):
            cb.allow("k")
        assert counting.calls == before
        cb.close()

    def test_batch_path_counts_and_short_circuits(self):
        cb, counting, inner, clock = make(fail_open=True)
        inner.inject_failure()
        for _ in range(3):
            out = cb.allow_batch(["a", "b"])
            assert out.fail_open
        assert cb.state == "open"
        out = cb.allow_batch(["a", "b", "c"])
        assert out.fail_open and len(out) == 3
        cb.close()

    def test_success_resets_consecutive_count(self):
        cb, counting, inner, clock = make(fail_open=True)
        inner.inject_failure()
        cb.allow("k")
        cb.allow("k")
        inner.heal()
        assert not cb.allow("k").fail_open   # success: streak broken
        inner.inject_failure()
        cb.allow("k")
        cb.allow("k")
        assert cb.state == "closed"          # 2 < threshold again
        cb.allow("k")
        assert cb.state == "open"
        cb.close()

    def test_half_open_probe_not_wedged_by_validation_error(self):
        # A probe that dies on a *non-storage* error (bad key, bad N) must
        # release the probe slot: the error says nothing about backend
        # health, and holding the slot would short-circuit every later call
        # until process restart.
        cb, counting, inner, clock = make(fail_open=True)
        inner.inject_failure()
        for _ in range(3):
            cb.allow("k")
        assert cb.state == "open"
        inner.heal()
        clock.advance(5.1)           # half-open; next call is the probe
        with pytest.raises(Exception):
            cb.allow_n("k", 0)       # InvalidNError from inner validation
        assert cb.state == "half-open"
        # The slot is free: a well-formed probe reaches the backend and
        # closes the breaker instead of being short-circuited forever.
        res = cb.allow("k")
        assert res.allowed and not res.fail_open
        assert cb.state == "closed"
        cb.close()

    def test_composes_with_contract_surface(self):
        # Breaker is transparent when the backend is healthy.
        cb, counting, inner, clock = make(fail_open=True)
        cfg_lim = 100
        allowed = sum(cb.allow("hot").allowed for _ in range(120))
        assert allowed == cfg_lim
        cb.reset("hot")
        assert cb.allow("hot").allowed
        cb.close()
